#!/usr/bin/env python
"""BASELINE config #5: a real 3-process cluster serving YCSB-E range scans
and TPC-H Q1-shaped coprocessor pushdown over TCP.

Reuses the multiprocess deployment shape proven by
tests/test_multiprocess_cluster.py (reference: test_raftstore ServerCluster,
src/server.rs:601): one PD service + three `tikv_tpu.server.standalone`
store PROCESSES over durable engine dirs (native LSM + raft log engine).
The lineitem-shaped table loads through MVCC transactions, splits into three
regions whose leaders spread across the stores, then:

  * YCSB-E — fixed-length range scans (kv_scan, 50 rows) at uniform-random
    starts against every region leader; metric = scanned rows/sec.
  * Q1 pushdown — the Q1 selection + group-by (sums/counts — the mergeable
    shape TiDB pushes down) runs per region leader through the REAL
    coprocessor service path; partials merge client-side and are verified
    against a numpy oracle over the generated arrays; metric = rows/sec
    through the executors.

Importable: ``run(...)`` returns the metrics dict (bench.py embeds it in the
driver detail JSON); ``python bench_cluster.py`` prints one JSON line.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

import numpy as np

TABLE_ID = 101
FIRST_REGION_ID = 1


def _spawn_store(store_id: int, pd_addr, data_dir: str,
                 accelerator: bool = False, device_platform: str = "cpu"):
    env = dict(os.environ)
    if accelerator and device_platform not in ("cpu", "cpu_fallback", "", None):
        # BASELINE config 5's "TPU copr plugin" role: this store owns the
        # accelerator — let the platform default (the tunnel device) stand.
        # Only reached when the caller has already observed a READY backend
        # this run; a hung tunnel init would otherwise eat the whole budget.
        env.pop("JAX_PLATFORMS", None)
    else:
        env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = _HERE
    # EVERY store enables the device serving path since the wire-path PR:
    # generic leader serving rides the region column cache + scheduler
    # coalescing on whatever backend the store has (JAX-on-CPU for the
    # non-accelerator stores) — the 28k rows/s wall was per-request Python
    # MVCC serving, not the wire itself (docs/wire_path.md)
    argv = [sys.executable, "-m", "tikv_tpu.server.standalone",
            "--store-id", str(store_id), "--pd", f"{pd_addr[0]}:{pd_addr[1]}",
            "--dir", data_dir, "--expect-stores", "3", "--enable-device"]
    return subprocess.Popen(
        argv, env=env, cwd=_HERE,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
    )


def _wait_ready(proc, timeout=120.0):
    # readline() blocks with no deadline of its own: a silent hung startup
    # must still fail the bench (not freeze the driver) — the watchdog kills
    # the process, which EOFs the pipe and breaks the loop.  The error names
    # the wedge (vs a fast crash) and how long the store stalled, so a
    # BENCH_rN tail alone distinguishes "device init hung at startup" from
    # "store crashed": rc=-9 with elapsed≈timeout is the watchdog's kill.
    timeout = float(os.environ.get("BENCH_CLUSTER_READY_TIMEOUT", str(timeout)))
    t0 = time.monotonic()
    watchdog = threading.Timer(timeout, lambda: os.kill(proc.pid, signal.SIGKILL))
    watchdog.daemon = True
    watchdog.start()
    try:
        while True:
            line = proc.stdout.readline()
            if not line:
                elapsed = time.monotonic() - t0
                rc = proc.poll()
                kind = ("wedged at startup (watchdog kill)"
                        if rc == -signal.SIGKILL and elapsed >= timeout - 1.0
                        else "exited before READY")
                raise RuntimeError(
                    f"store process {kind}: rc={rc} after {elapsed:.1f}s "
                    f"(timeout {timeout:.0f}s) argv={proc.args}")
            if line.startswith(b"READY"):
                return
    finally:
        watchdog.cancel()


DEVICE_STORE = 1  # the store that owns the accelerator (config 5's TPU plugin)


class _Cluster:
    def __init__(self, tmp: str, device_platform: str = "cpu"):
        from tikv_tpu.pd.client import MockPd
        from tikv_tpu.pd.service import PdService
        from tikv_tpu.server.server import Client, Server

        self.Client = Client
        self.pd = MockPd()
        self.pd_server = Server(PdService(self.pd))
        self.pd_server.start()
        self.procs = [
            _spawn_store(
                sid, self.pd_server.addr, os.path.join(tmp, f"s{sid}"),
                accelerator=sid == DEVICE_STORE, device_platform=device_platform,
            )
            for sid in (1, 2, 3)
        ]
        for p in self.procs:
            # a real accelerator init (tunnel) can take minutes on top of the
            # normal bootstrap; the CPU path stays on the short clock
            _wait_ready(p, timeout=300.0 if device_platform not in ("cpu", "", None) else 120.0)
        self._clients: dict[int, object] = {}
        # region -> leader store, refreshed from NotLeader response hints
        # (the client-go region-cache role): a hint re-routes the NEXT call
        # immediately instead of re-polling pd.leaders on a sleep loop
        self._route: dict[int, int] = {}

    def client_for_store(self, sid: int):
        c = self._clients.get(sid)
        if c is None:
            addr = self.pd.get_store_addr(sid)
            c = self._clients[sid] = self.Client(addr[0], addr[1])
        return c

    def leader_client(self, region_id: int, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            # the route cache (NotLeader hints) answers before PD's
            # heartbeat-lagged leader view
            sid = self._route.get(region_id) or self.pd.leaders.get(region_id)
            if sid is not None:
                return self.client_for_store(sid), sid
            time.sleep(0.1)
        raise RuntimeError(f"no leader reported for region {region_id}")

    def call_leader(self, region_id: int, method: str, req: dict, timeout=60.0):
        """Leader-following call with NotLeader/epoch retry.  A NotLeader
        response carrying a leader hint updates the route cache and re-routes
        IMMEDIATELY — no sleep, no pd.leaders re-poll."""
        deadline = time.monotonic() + timeout
        last = None
        hot_hops = 0
        while time.monotonic() < deadline:
            try:
                c, sid = self.leader_client(region_id)
                r = c.call(method, dict(req, context={"region_id": region_id}),
                           timeout=20.0)
            except (ConnectionError, TimeoutError, OSError, RuntimeError) as e:
                last = e
                self._route.pop(region_id, None)
                hot_hops = 0
                time.sleep(0.2)
                continue
            if isinstance(r, dict) and (r.get("error") or r.get("errors")):
                last = r
                hint = ((r.get("error") or {}).get("not_leader") or {}).get("leader_store")
                if hint and hint != sid:
                    self._route[region_id] = hint
                    # ONE sleepless re-route per backoff window: mid-election
                    # two stores can hint at each other, and an unbounded hot
                    # loop would hammer both until the deadline
                    if hot_hops < 1:
                        hot_hops += 1
                        continue
                else:
                    self._route.pop(region_id, None)
                hot_hops = 0
                time.sleep(0.2)
                continue
            self._route[region_id] = sid
            return r
        raise RuntimeError(f"{method} on region {region_id} never succeeded: {last!r}")

    def shutdown(self):
        for c in self._clients.values():
            try:
                c.close()
            except OSError:
                pass
        for p in self.procs:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except OSError:
                pass
            p.wait()
        self.pd_server.stop()


def _lineitem_cols():
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType

    return [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),          # quantity
        ColumnInfo(3, FieldType.decimal_type(2)),  # extendedprice
        ColumnInfo(4, FieldType.decimal_type(2)),  # discount
        ColumnInfo(5, FieldType.int64()),          # shipdate
        ColumnInfo(6, FieldType.varchar()),        # returnflag
        ColumnInfo(7, FieldType.varchar()),        # linestatus
    ]


def run(rows: int = 60_000, scan_seconds: float = 8.0, scan_len: int = 50,
        device_platform: str = "cpu") -> dict:
    from tikv_tpu.copr.dag import Aggregation, DagRequest, SelectResponse, Selection, TableScan
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag_wire import dag_to_wire
    from tikv_tpu.copr.rpn import call as rpn_call, col, const_int
    from tikv_tpu.copr.table import encode_row, record_key, record_range
    from tikv_tpu.storage.txn_types import Key

    tmp = tempfile.mkdtemp(prefix="bench-cluster-")
    out: dict = {"rows": rows}
    cluster = _Cluster(tmp, device_platform=device_platform)
    try:
        # ---- load the table through MVCC transactions --------------------
        rng = np.random.default_rng(11)
        qty = rng.integers(1, 51, rows)
        price = rng.integers(90000, 10500000, rows)
        disc = rng.integers(0, 11, rows)
        ship = rng.integers(8400, 10600, rows)
        rf = rng.integers(0, 3, rows)
        ls = rng.integers(0, 2, rows)
        flags, stats = (b"A", b"N", b"R"), (b"F", b"O")
        cols = _lineitem_cols()
        non_handle = cols[1:]
        t0 = time.perf_counter()
        batch = int(os.environ.get("BENCH_CLUSTER_TXN_BATCH", "500"))
        loaded = 0
        for s in range(0, rows, batch):
            e = min(s + batch, rows)
            muts = []
            for i in range(s, e):
                rk = record_key(TABLE_ID, i)
                val = encode_row(non_handle, [
                    int(qty[i]), int(price[i]), int(disc[i]), int(ship[i]),
                    flags[rf[i]], stats[ls[i]],
                ])
                muts.append({"op": "put", "key": rk, "value": val})
            # a batch can straddle a region boundary after the split: group
            # by region, one txn per group (the leader rejects foreign keys)
            by_region: dict[int, list] = {}
            for m in muts:
                by_region.setdefault(_region_for(cluster, m["key"]), []).append(m)
            for region_id, group in by_region.items():
                ts = cluster.pd.get_tso()
                cluster.call_leader(region_id, "kv_prewrite", {
                    "mutations": group, "primary_lock": group[0]["key"],
                    "start_version": ts,
                })
                cluster.call_leader(region_id, "kv_commit", {
                    "keys": [m["key"] for m in group], "start_version": ts,
                    "commit_version": cluster.pd.get_tso(),
                })
            loaded = e
            # split into three regions once enough data exists, so the rest
            # of the load and both workloads spread across all stores
            if loaded == batch * 2:
                _split_and_spread(cluster, rows)
        out["load_s"] = round(time.perf_counter() - t0, 1)
        out["load_rows_per_s"] = round(rows / (time.perf_counter() - t0), 1)

        regions = sorted(
            rid for rid, r in cluster.pd.regions.items()
            if _overlaps_table(r)
        )
        leaders = {rid: cluster.pd.leaders.get(rid) for rid in regions}
        out["regions"] = len(regions)
        out["leader_stores"] = sorted(set(leaders.values()))

        # ---- YCSB-E: fixed-length range scans ----------------------------
        # YCSB drives with concurrent clients when the host has cores for
        # them (BENCH_CLUSTER_YCSB_CLIENTS); on this 1-core builder the
        # servers already saturate the core, so the default stays 1 —
        # extra clients would only measure context-switch overhead
        read_ts = cluster.pd.get_tso()
        n_clients = max(1, int(os.environ.get(
            "BENCH_CLUSTER_YCSB_CLIENTS",
            "1" if (os.cpu_count() or 1) < 4 else "4")))
        starts = rng.integers(0, max(rows - scan_len, 1), 100_000)
        stop_at = time.monotonic() + scan_seconds
        totals = []

        def ycsb_worker(wid: int):
            conns: dict[int, object] = {}
            scans = 0
            got_rows = 0
            lats: list[float] = []
            i = wid
            try:
                while time.monotonic() < stop_at:
                    h = int(starts[i % len(starts)])
                    i += n_clients
                    rk = record_key(TABLE_ID, h)
                    region_id = _region_for(cluster, rk)
                    sid = cluster.pd.leaders.get(region_id)
                    if sid is None:
                        time.sleep(0.05)
                        continue
                    try:
                        c = conns.get(sid)
                        if c is None:
                            addr = cluster.pd.get_store_addr(sid)
                            c = conns[sid] = cluster.Client(addr[0], addr[1])
                        t_req = time.monotonic()
                        r = c.call("kv_scan", {
                            "start_key": rk, "limit": scan_len, "version": read_ts,
                            "context": {"region_id": region_id},
                        }, timeout=20.0)
                    except (ConnectionError, TimeoutError, OSError, RuntimeError):
                        # transient (leader transfer, slow scan): drop the
                        # connection and keep driving — work already counted
                        # must survive, like the old call_leader retry loop
                        bad = conns.pop(sid, None)
                        if bad is not None:
                            try:
                                bad.close()
                            except OSError:
                                pass
                        time.sleep(0.1)
                        continue
                    if isinstance(r, dict) and not r.get("error"):
                        scans += 1
                        got_rows += len(r.get("pairs", ()))
                        lats.append(time.monotonic() - t_req)
            finally:
                # counts gathered before any failure still aggregate
                totals.append((scans, got_rows, lats))
                for c in conns.values():
                    try:
                        c.close()
                    except OSError:
                        pass

        workers = [threading.Thread(target=ycsb_worker, args=(w,))
                   for w in range(n_clients)]
        for t in workers:
            t.start()
        for t in workers:
            t.join()
        scans = sum(s for s, _r, _l in totals)
        scanned_rows = sum(r for _s, r, _l in totals)
        all_lats = [l for _s, _r, ls in totals for l in ls]
        out["ycsb_e_clients"] = n_clients
        out["ycsb_e_scans_per_s"] = round(scans / scan_seconds, 1)
        out["ycsb_e_rows_per_s"] = round(scanned_rows / scan_seconds, 1)
        if all_lats:
            # the BASELINE metric pairs rows/sec with request latency tails
            p50, p99 = np.percentile(all_lats, [50, 99])
            out["ycsb_e_p50_ms"] = round(float(p50) * 1e3, 2)
            out["ycsb_e_p99_ms"] = round(float(p99) * 1e3, 2)

        # ---- Q1 pushdown: mergeable sums/counts per region ---------------
        def q1_dag():
            aggs = [
                AggDescriptor("sum", col(1)),                        # sum(qty)
                AggDescriptor("sum", col(2)),                        # sum(price)
                AggDescriptor("sum", col(3)),                        # sum(disc)
                AggDescriptor("count", None),
            ]
            return DagRequest(executors=[
                TableScan(TABLE_ID, cols),
                Selection([rpn_call("le", col(4), const_int(10500))]),
                Aggregation([col(5), col(6)], aggs),
            ])

        wire_dag = dag_to_wire(q1_dag())
        results: dict[int, bytes] = {}
        errs: list = []

        def push(rid):
            try:
                r = cluster.call_leader(rid, "coprocessor", {
                    "dag": wire_dag, "ranges": [list(record_range(TABLE_ID))],
                    "start_ts": read_ts,
                })
                results[rid] = r["data"]
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=push, args=(rid,)) for rid in regions]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        q1_t = time.perf_counter() - t0
        if errs:
            raise errs[0]
        # client-side partial merge + oracle check (row layout: aggregates
        # first, then the group-by keys — dag.py Aggregation encoding)
        merged: dict[tuple, list] = {}
        for rid, blob in results.items():
            for row in SelectResponse.decode(blob).iter_rows():
                key = (row[4], row[5])
                acc = merged.setdefault(key, [0, 0])
                acc[0] += int(row[0])   # sum(qty)
                acc[1] += int(row[3])   # count
        mask = ship <= 10500
        want_count = int(mask.sum())
        got_count = sum(v[1] for v in merged.values())
        if got_count != want_count:
            raise AssertionError(f"Q1 merge mismatch: {got_count} != {want_count}")
        want_qty = int(qty[mask].sum())
        got_qty = sum(v[0] for v in merged.values())
        if got_qty != want_qty:
            raise AssertionError(f"Q1 sum(qty) mismatch: {got_qty} != {want_qty}")
        out["q1_pushdown_rows_per_s"] = round(rows / q1_t, 1)
        out["q1_groups"] = len(merged)

        # ---- generic wire serving, sustained (docs/wire_path.md) ----------
        # THE previously-frozen number: plain unary coprocessor RPCs to the
        # region LEADERS over TCP — no client-side device routing, no
        # cache_version hints.  Server-side the stores now serve these off
        # the region column cache through the read scheduler's continuous
        # lanes (identical requests from concurrent connections share one
        # execution slot), so sustained throughput measures the whole
        # decode -> coalesce -> execute -> encode wire path warm.
        wire_secs = float(os.environ.get("BENCH_CLUSTER_WIRE_SECONDS", "6"))
        clients_per_region = int(os.environ.get(
            "BENCH_CLUSTER_WIRE_CLIENTS_PER_REGION", "2"))
        wire_req = {"dag": wire_dag,
                    "ranges": [list(record_range(TABLE_ID))],
                    "start_ts": read_ts}

        def q1_unary(conn_cache: dict, sid: int, rid: int, timeout=30.0):
            c = conn_cache.get(sid)
            if c is None:
                addr = cluster.pd.get_store_addr(sid)
                c = conn_cache[sid] = cluster.Client(addr[0], addr[1])
            return c.call("coprocessor",
                          dict(wire_req, context={"region_id": rid}),
                          timeout=timeout)

        # A loaded store can transiently refuse through the read ladder —
        # forward breaker half-open after one slow hop, follower watermark
        # briefly behind the region's apply index.  A real client retries
        # those classes (docs/stale_reads.md, util/retry.py); the bench
        # workers do the same, BOUNDED, so a genuine routing regression
        # still fails loud instead of being masked.
        _TRANSIENT_REFUSALS = ("not_leader", "data_not_ready",
                               "server_is_busy")

        def q1_unary_retry(conn_cache: dict, sid: int, rid: int,
                           timeout=30.0, attempts=8):
            last = None
            for i in range(attempts):
                r = q1_unary(conn_cache, sid, rid, timeout=timeout)
                err = r.get("error")
                if not err:
                    return r
                if not any(k in err for k in _TRANSIENT_REFUSALS):
                    raise RuntimeError(str(err))
                last = err
                time.sleep(0.05 * (i + 1))
            raise RuntimeError(
                f"transient refusal persisted after {attempts} attempts "
                f"(store {sid}, region {rid}): {last}")

        # warmup: one request per region builds the leader's region image
        # and compiles the plan, so the timed window measures serving (the
        # leader-following helper also refreshes the route cache)
        for rid in regions:
            cluster.call_leader(rid, "coprocessor", wire_req, timeout=120.0)
            leaders[rid] = cluster._route.get(rid, leaders[rid])
        wire_counts: dict[int, int] = {rid: 0 for rid in regions}
        wire_count_mu = threading.Lock()
        wire_samples: dict[int, bytes] = {}
        wire_errs: list = []
        wire_stop = time.monotonic() + wire_secs

        def wire_worker(rid: int):
            conns: dict[int, object] = {}
            served = 0  # thread-local: 2 workers share each rid slot, and
            # a racy `wire_counts[rid] += 1` would undercount the very
            # number the wire acceptance floor is judged on
            try:
                while time.monotonic() < wire_stop:
                    r = q1_unary_retry(conns, leaders[rid], rid)
                    prev = wire_samples.setdefault(rid, r["data"])
                    if prev != r["data"]:
                        raise AssertionError(
                            f"region {rid}: coalesced response bytes drifted")
                    served += 1
            except Exception as exc:  # noqa: BLE001
                wire_errs.append(exc)
            finally:
                with wire_count_mu:
                    wire_counts[rid] += served
                for c in conns.values():
                    try:
                        c.close()
                    except OSError:
                        pass

        t0 = time.perf_counter()
        wts = [threading.Thread(target=wire_worker, args=(rid,))
               for rid in regions for _ in range(clients_per_region)]
        for t in wts:
            t.start()
        for t in wts:
            t.join()
        wire_dt = time.perf_counter() - t0
        if wire_errs:
            raise wire_errs[0]
        # byte-identity: the warm wire responses merge to the same groups
        # the per-request leader round produced
        merged_wire: dict[tuple, list] = {}
        for rid, blob in wire_samples.items():
            for row in SelectResponse.decode(blob).iter_rows():
                key = (row[4], row[5])
                acc = merged_wire.setdefault(key, [0, 0])
                acc[0] += int(row[0])
                acc[1] += int(row[3])
        if merged_wire != merged:
            raise AssertionError("sustained wire serving merge differs from oracle")
        total_reqs = sum(wire_counts.values())
        # each request processes one region's share of the table, so the
        # sustained row rate is (whole-table rows) x (mean rounds per region)
        out["q1_wire_requests"] = total_reqs
        out["q1_wire_clients"] = clients_per_region * len(regions)
        out["q1_wire_rows_per_s"] = round(
            rows * (total_reqs / max(len(regions), 1)) / wire_dt, 1)

        # ---- TypeChunk wire serving (docs/wire_path.md) -------------------
        # The same sustained Q1 workload with the per-request chunk opt-in:
        # responses come back as column slabs (encode_type + data_parts),
        # decoded against the sent plan and merged to the same oracle groups
        from tikv_tpu.copr.dag import (
            ENC_TYPE_CHUNK,
            decode_wire_response,
            response_data,
        )

        chunk_dag = q1_dag()
        chunk_dag.encode_type = ENC_TYPE_CHUNK
        wire_dag_chunk = dag_to_wire(chunk_dag)
        chunk_req = dict(wire_req, dag=wire_dag_chunk)

        def q1_chunk_retry(conn_cache, sid, rid, timeout=30.0, attempts=8):
            last = None
            for i in range(attempts):
                c = conn_cache.get(sid)
                if c is None:
                    addr = cluster.pd.get_store_addr(sid)
                    c = conn_cache[sid] = cluster.Client(addr[0], addr[1])
                r = c.call("coprocessor",
                           dict(chunk_req, context={"region_id": rid}),
                           timeout=timeout)
                err = r.get("error")
                if not err:
                    return r
                if not any(k in err for k in _TRANSIENT_REFUSALS):
                    raise RuntimeError(str(err))
                last = err
                time.sleep(0.05 * (i + 1))
            raise RuntimeError(
                f"transient refusal persisted after {attempts} attempts "
                f"(store {sid}, region {rid}): {last}")

        chunk_counts: dict[int, int] = {rid: 0 for rid in regions}
        chunk_count_mu = threading.Lock()
        chunk_samples: dict[int, dict] = {}
        chunk_errs: list = []
        chunk_secs = float(os.environ.get("BENCH_CLUSTER_WIRE_SECONDS", "6"))
        # warmup one chunk request per region (negotiation + encoder path)
        warm_chunk: dict[int, object] = {}
        for rid in regions:
            q1_chunk_retry(warm_chunk, leaders[rid], rid, timeout=120.0)
        for c in warm_chunk.values():
            try:
                c.close()
            except OSError:
                pass
        chunk_stop = time.monotonic() + chunk_secs

        def chunk_worker(rid: int):
            conns: dict[int, object] = {}
            served = 0
            try:
                while time.monotonic() < chunk_stop:
                    r = q1_chunk_retry(conns, leaders[rid], rid)
                    if not r.get("encode_type"):
                        raise AssertionError(
                            f"region {rid}: chunk opt-in answered datum")
                    prev = chunk_samples.setdefault(rid, r)
                    if response_data(prev) != response_data(r):
                        raise AssertionError(
                            f"region {rid}: chunk response bytes drifted")
                    served += 1
            except Exception as exc:  # noqa: BLE001
                chunk_errs.append(exc)
            finally:
                with chunk_count_mu:
                    chunk_counts[rid] += served
                for c in conns.values():
                    try:
                        c.close()
                    except OSError:
                        pass

        t0 = time.perf_counter()
        cts = [threading.Thread(target=chunk_worker, args=(rid,))
               for rid in regions for _ in range(clients_per_region)]
        for t in cts:
            t.start()
        for t in cts:
            t.join()
        chunk_dt = time.perf_counter() - t0
        if chunk_errs:
            raise chunk_errs[0]
        merged_chunk: dict[tuple, list] = {}
        for rid, resp in chunk_samples.items():
            for row in decode_wire_response(resp, chunk_dag).iter_rows():
                key = (row[4], row[5])
                acc = merged_chunk.setdefault(key, [0, 0])
                acc[0] += int(row[0])
                acc[1] += int(row[3])
        if merged_chunk != merged:
            raise AssertionError("TypeChunk wire serving merge differs from oracle")
        chunk_total = sum(chunk_counts.values())
        out["q1_wire_chunk_requests"] = chunk_total
        out["q1_wire_chunk_rows_per_s"] = round(
            rows * (chunk_total / max(len(regions), 1)) / chunk_dt, 1)

        # ---- Q1 via the device store -------------------------------------
        # One accelerator per deployment: every region's device-eligible DAG
        # routes to the store that owns it, using follower replica reads
        # (raftkv.py ReadIndex barrier) for regions whose leader is
        # elsewhere — so a single chip serves the whole keyspace while
        # leaders stay spread for writes.  One coprocessor_batch RPC carries
        # all region sub-requests.
        dev_client = cluster.client_for_store(DEVICE_STORE)

        def device_round():
            # cache_version: the table is static after load, so the read_ts
            # doubles as the data version — repeated rounds then ride the
            # endpoint's block cache + zone layout instead of re-scanning
            # MVCC per request (the reference's cop-cache keys on region
            # apply version the same way, cache.rs:10)
            reqs = [
                {"dag": wire_dag, "ranges": [list(record_range(TABLE_ID))],
                 "start_ts": read_ts,
                 "context": {"region_id": rid, "replica_read": True,
                             "cache_version": read_ts}}
                for rid in regions
            ]
            t0 = time.perf_counter()
            r = dev_client.call("coprocessor_batch", {"requests": reqs},
                                timeout=180.0)
            return r, time.perf_counter() - t0

        def check(r):
            for sub in r["responses"]:
                if sub.get("error"):
                    raise RuntimeError(f"device-store coprocessor error: {sub['error']}")
            return r

        r0, cold_dt = device_round()  # compile + block-cache fill
        check(r0)
        out["q1_device_cold_rows_per_s"] = round(rows / cold_dt, 1)
        # one untimed warm round: the zone layout builds lazily on the first
        # cache-hit query, and that one-time cost belongs to warmup
        check(device_round()[0])
        ts = []
        for _ in range(3):
            r, dt = device_round()
            check(r)  # a failed round must fail the metric, not speed it up
            ts.append(dt)
        merged_dev: dict[tuple, list] = {}
        for sub in r["responses"]:
            for row in SelectResponse.decode(sub["data"]).iter_rows():
                key = (row[4], row[5])
                acc = merged_dev.setdefault(key, [0, 0])
                acc[0] += int(row[0])
                acc[1] += int(row[3])
        if merged_dev != merged:
            raise AssertionError("device-store Q1 merge differs from leader-path merge")
        out["q1_device_rows_per_s"] = round(rows / float(np.median(ts)), 1)
        out["q1_device_round_ms"] = [round(x * 1e3, 1) for x in ts]
        out["q1_device_from_device"] = all(
            bool(sub.get("from_device")) for sub in r["responses"]
        )
        out["q1_device_platform"] = device_platform

        # ---- device-owner routing (docs/wire_path.md) ---------------------
        # Each region's Q1 goes to the WRONG store — one that neither leads
        # nor warms the region.  The receiving store's dispatch tier
        # forwards one hop to the advertised device owner (whose warm image
        # serves it) instead of bouncing NotLeader or serving a cold CPU
        # fallback.  Placement rides the PD heartbeat, so first wait until
        # every store's owner map covers the bench regions.
        own_deadline = time.monotonic() + 15.0
        probe = cluster.client_for_store(2)
        while time.monotonic() < own_deadline:
            owners = probe.call("debug_device_owners", {}).get("owners", {})
            if all(rid in owners for rid in regions):
                break
            time.sleep(0.3)
        else:
            raise RuntimeError(
                f"device-owner placement never advertised: {owners}")
        out["device_owners"] = {int(k): v for k, v in owners.items()}
        store_ids = (1, 2, 3)

        def _wrong(rid):
            # prefer a store that neither leads the region, nor owns its
            # image, nor is the accelerator store (whose cache holds every
            # region after the device phase): that store MUST forward
            avoid = {leaders[rid], owners.get(rid), DEVICE_STORE}
            for s in store_ids:
                if s not in avoid:
                    return s
            return next(s for s in store_ids
                        if s != leaders[rid] and s != owners.get(rid))

        wrong_store = {rid: _wrong(rid) for rid in regions}
        own_secs = float(os.environ.get("BENCH_CLUSTER_OWNER_SECONDS", "4"))
        own_counts: dict[int, int] = {rid: 0 for rid in regions}
        own_samples: dict[int, bytes] = {}
        own_errs: list = []
        # warmup one forwarded request per region (route + breaker state)
        warm_conns2: dict[int, object] = {}
        for rid in regions:
            q1_unary_retry(warm_conns2, wrong_store[rid], rid, timeout=120.0)
        for c in warm_conns2.values():
            try:
                c.close()
            except OSError:
                pass
        own_stop = time.monotonic() + own_secs

        def owner_worker(rid: int):
            conns: dict[int, object] = {}
            try:
                while time.monotonic() < own_stop:
                    r = q1_unary_retry(conns, wrong_store[rid], rid)
                    prev = own_samples.setdefault(rid, r["data"])
                    if prev != r["data"]:
                        raise AssertionError(
                            f"region {rid}: owner-routed bytes drifted")
                    own_counts[rid] += 1
            except Exception as exc:  # noqa: BLE001
                own_errs.append(exc)
            finally:
                for c in conns.values():
                    try:
                        c.close()
                    except OSError:
                        pass

        t0 = time.perf_counter()
        ots = [threading.Thread(target=owner_worker, args=(rid,))
               for rid in regions]
        for t in ots:
            t.start()
        for t in ots:
            t.join()
        own_dt = time.perf_counter() - t0
        if own_errs:
            raise own_errs[0]
        merged_own: dict[tuple, list] = {}
        for rid, blob in own_samples.items():
            for row in SelectResponse.decode(blob).iter_rows():
                key = (row[4], row[5])
                acc = merged_own.setdefault(key, [0, 0])
                acc[0] += int(row[0])
                acc[1] += int(row[3])
        if merged_own != merged:
            raise AssertionError("owner-routed serving merge differs from oracle")
        own_total = sum(own_counts.values())
        out["q1_owner_routed_requests"] = own_total
        out["q1_owner_routed_rows_per_s"] = round(
            rows * (own_total / max(len(regions), 1)) / own_dt, 1)

        # ---- per-stage wire histogram summary (tikv_wire_stage_seconds) ---
        stages_total: dict[str, dict] = {}
        for sid in store_ids:
            c = cluster.client_for_store(sid)
            st = c.call("debug_wire_stages", {}).get("stages", {})
            for stage, v in st.items():
                agg = stages_total.setdefault(stage, {"count": 0, "seconds": 0.0})
                agg["count"] += v.get("count", 0)
                agg["seconds"] += v.get("seconds", 0.0)
        out["wire_stages"] = {
            s: {"count": v["count"], "seconds": round(v["seconds"], 4),
                "mean_us": round(1e6 * v["seconds"] / max(v["count"], 1), 1)}
            for s, v in sorted(stages_total.items())
        }
        out["ok"] = True
        return out
    finally:
        cluster.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)


def _region_for(cluster, raw_key: bytes):
    from tikv_tpu.storage.txn_types import Key
    from tikv_tpu.util import keys as keymod

    enc = keymod.data_key(Key.from_raw(raw_key).encoded)
    best = None
    for rid, region in cluster.pd.regions.items():
        start = keymod.data_key(region.start_key) if region.start_key else b""
        end = keymod.data_key(region.end_key) if region.end_key else None
        if enc >= start and (end is None or enc < end):
            best = rid
    return best if best is not None else FIRST_REGION_ID


def _overlaps_table(region) -> bool:
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.txn_types import Key

    lo_raw, hi_raw = record_range(TABLE_ID)
    # region boundaries live in ENCODED (memcomparable) key space
    lo = Key.from_raw(lo_raw).encoded
    hi = Key.from_raw(hi_raw).encoded
    start = region.start_key or b""
    end = region.end_key or None
    return (end is None or end > lo) and start < hi


def _split_and_spread(cluster, rows: int) -> None:
    """Split the table range into 3 regions and move leaders apart."""
    from tikv_tpu.copr.table import record_key
    from tikv_tpu.storage.txn_types import Key

    for frac in (1 / 3, 2 / 3):
        split_raw = record_key(TABLE_ID, int(rows * frac))
        region_id = _region_for(cluster, split_raw)
        # the service memcomparable-encodes user keys itself (kv.rs
        # split_region Key::from_raw) — pass the RAW record key
        cluster.call_leader(region_id, "kv_split_region", {"split_key": split_raw})
    # leader spread: one region leader per store via PD operators
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        regions = sorted(
            rid for rid, r in cluster.pd.regions.items() if _overlaps_table(r))
        leaders = {rid: cluster.pd.leaders.get(rid) for rid in regions}
        if len(regions) >= 3 and None not in leaders.values():
            break
        time.sleep(0.2)
    want = dict(zip(regions, (1, 2, 3)))
    for rid, sid in want.items():
        if cluster.pd.leaders.get(rid) != sid:
            region = cluster.pd.regions.get(rid)
            peer = region.peer_on_store(sid) if region is not None else None
            if peer is not None:
                cluster.pd.add_operator(
                    rid, {"type": "transfer_leader", "peer_id": peer.peer_id,
                          "store_id": sid})
    time.sleep(1.5)  # let heartbeats deliver the operators


def main() -> None:
    rows = int(os.environ.get("BENCH_CLUSTER_ROWS", "60000"))
    secs = float(os.environ.get("BENCH_CLUSTER_SCAN_SECONDS", "8"))
    out = run(rows, secs,
              device_platform=os.environ.get("BENCH_CLUSTER_DEVICE", "cpu"))
    print(json.dumps({
        "metric": "cluster3_q1_pushdown_rows_per_sec",
        "value": out["q1_pushdown_rows_per_s"],
        "unit": "rows/sec",
        "vs_baseline": 0.0,
        **out,
    }))


if __name__ == "__main__":
    main()
