"""Scalar-function catalog tranche (reference: tidb_query_expr impl_math.rs /
impl_op.rs / impl_string.rs / impl_compare.rs / impl_misc.rs): CPU oracle
checks, and device agreement for the xp-generic (numeric) kernels."""

import numpy as np
import pytest

from tikv_tpu.copr.datatypes import EvalType
from tikv_tpu.copr.kernels import KERNELS
from tikv_tpu.copr.rpn import call, col, compile_expr, const_bytes, const_int, const_real, eval_rpn


def _run(expr, columns=None, n=1, schema=()):
    rpn = compile_expr(expr, list(schema))
    return eval_rpn(rpn, columns or {}, n, xp=np)


def test_math_tranche():
    d, _ = _run(call("log2", const_real(8.0)))
    assert d[0] == 3.0
    d, _ = _run(call("log10", const_real(1000.0)))
    assert d[0] == 3.0
    d, _ = _run(call("atan2", const_real(1.0), const_real(1.0)))
    assert abs(d[0] - 0.7853981633974483) < 1e-12
    d, nl = _run(call("cot", const_real(0.0)))
    assert nl[0]  # cot(0) -> NULL (division by zero)
    d, _ = _run(call("radians", const_real(180.0)))
    assert abs(d[0] - 3.141592653589793) < 1e-12
    d, _ = _run(call("degrees", const_real(3.141592653589793)))
    assert abs(d[0] - 180.0) < 1e-9
    d, _ = _run(call("sign", const_real(-2.5)))
    assert d[0] == -1
    # MySQL ROUND: half away from zero, also for negatives
    d, _ = _run(call("round_real", const_real(2.5)))
    assert d[0] == 3.0
    d, _ = _run(call("round_real", const_real(-2.5)))
    assert d[0] == -3.0
    d, _ = _run(call("round_real_frac", const_real(3.14159), const_int(2)))
    assert d[0] == 3.14
    d, _ = _run(call("truncate_real_frac", const_real(3.199), const_int(2)))
    assert abs(d[0] - 3.19) < 1e-12


def test_bit_ops():
    d, _ = _run(call("bit_and", const_int(0b1100), const_int(0b1010)))
    assert d[0] == 0b1000
    d, _ = _run(call("bit_or", const_int(0b1100), const_int(0b1010)))
    assert d[0] == 0b1110
    d, _ = _run(call("bit_xor", const_int(0b1100), const_int(0b1010)))
    assert d[0] == 0b0110
    d, _ = _run(call("bit_neg", const_int(0)))
    assert d[0] == -1  # ~0 = u64 max bit pattern
    d, _ = _run(call("left_shift", const_int(1), const_int(10)))
    assert d[0] == 1024
    d, _ = _run(call("left_shift", const_int(1), const_int(64)))
    assert d[0] == 0  # MySQL: shift >= 64 -> 0
    d, _ = _run(call("right_shift", const_int(-1), const_int(60)))
    assert d[0] == 15  # logical shift on the u64 pattern


def test_greatest_least():
    d, _ = _run(call("greatest", const_int(3), const_int(9), const_int(5)))
    assert d[0] == 9
    d, _ = _run(call("least", const_real(3.5), const_real(-1.0)))
    assert d[0] == -1.0
    d, nl = _run(call("greatest", const_int(3), const_int(None)))
    assert nl[0]  # NULL if any operand NULL


def test_string_tranche():
    d, _ = _run(call("lpad", const_bytes(b"5"), const_int(3), const_bytes(b"0")))
    assert d[0] == b"005"
    d, _ = _run(call("rpad", const_bytes(b"ab"), const_int(5), const_bytes(b"xy")))
    assert d[0] == b"abxyx"
    d, nl = _run(call("lpad", const_bytes(b"a"), const_int(5), const_bytes(b"")))
    assert nl[0]  # empty pad, n > len -> NULL
    d, _ = _run(call("repeat", const_bytes(b"ab"), const_int(3)))
    assert d[0] == b"ababab"
    d, _ = _run(call("space", const_int(4)))
    assert d[0] == b"    "
    d, _ = _run(call("strcmp", const_bytes(b"a"), const_bytes(b"b")))
    assert d[0] == -1
    d, _ = _run(call("instr", const_bytes(b"foobar"), const_bytes(b"bar")))
    assert d[0] == 4
    d, _ = _run(call("char_length", const_bytes("héllo".encode())))
    assert d[0] == 6  # binary-collation semantics: byte length (reference)
    d, _ = _run(call("char_length_utf8", const_bytes("héllo".encode())))
    assert d[0] == 5  # character count
    d, _ = _run(call("crc32", const_bytes(b"MySQL")))
    assert d[0] == 3259397556  # known MySQL doc value
    d, _ = _run(call("find_in_set", const_bytes(b"b"), const_bytes(b"a,b,c")))
    assert d[0] == 2
    d, _ = _run(call("substring_index", const_bytes(b"www.mysql.com"), const_bytes(b"."), const_int(2)))
    assert d[0] == b"www.mysql"
    d, _ = _run(call("substring_index", const_bytes(b"www.mysql.com"), const_bytes(b"."), const_int(-2)))
    assert d[0] == b"mysql.com"
    d, _ = _run(call("elt", const_int(2), const_bytes(b"x"), const_bytes(b"y")))
    assert d[0] == b"y"
    d, nl = _run(call("elt", const_int(5), const_bytes(b"x"), const_bytes(b"y")))
    assert nl[0]
    d, _ = _run(call("field", const_bytes(b"b"), const_bytes(b"a"), const_bytes(b"b")))
    assert d[0] == 2
    d, _ = _run(call("oct_int", const_int(12)))
    assert d[0] == b"14"
    d, _ = _run(call("bin_int", const_int(12)))
    assert d[0] == b"1100"
    d, _ = _run(call("unhex", const_bytes(b"4D7953514C")))
    assert d[0] == b"MySQL"
    d, nl = _run(call("unhex", const_bytes(b"zz")))
    assert nl[0]  # invalid hex -> NULL
    d, _ = _run(call("to_base64", const_bytes(b"abc")))
    assert d[0] == b"YWJj"
    d, _ = _run(call("from_base64", const_bytes(b"YWJj")))
    assert d[0] == b"abc"
    d, _ = _run(call("md5", const_bytes(b"testing")))
    assert d[0] == b"ae2b1fca515949e5d54fb22b8ed95575"
    d, _ = _run(call("sha1", const_bytes(b"abc")))
    assert d[0] == b"a9993e364706816aba3e25717850c26c9cd0d89d"
    d, _ = _run(call("sha2", const_bytes(b"abc"), const_int(256)))
    assert d[0] == b"ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
    d, nl = _run(call("sha2", const_bytes(b"abc"), const_int(123)))
    assert nl[0]  # invalid length -> NULL


def test_inet():
    d, _ = _run(call("inet_aton", const_bytes(b"10.0.5.9")))
    assert d[0] == 167773449
    d, _ = _run(call("inet_aton", const_bytes(b"127.1")))  # MySQL short form
    assert d[0] == (127 << 24) | 1
    d, nl = _run(call("inet_aton", const_bytes(b"not.an.ip")))
    assert nl[0]
    d, _ = _run(call("inet_ntoa", const_int(167773449)))
    assert d[0] == b"10.0.5.9"
    d, nl = _run(call("inet_ntoa", const_int(2**40)))
    assert nl[0]


def test_numeric_tranche_device_agrees_with_cpu():
    """The xp-generic kernels must produce identical results under jax.numpy
    (CPU backend) — the one-kernel-table invariant."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    vals = np.array([-3.7, -0.5, 0.0, 0.5, 2.5, 9.99], dtype=np.float64)
    ints = np.array([-8, -1, 0, 1, 7, 63], dtype=np.int64)
    fcols = (vals, np.zeros(6, dtype=bool))
    icols = (ints, np.zeros(6, dtype=bool))
    for op, args in [
        ("round_real", [fcols]),
        ("sign", [fcols]),
        ("radians", [fcols]),
        ("degrees", [fcols]),
        ("bit_neg", [icols]),
        ("left_shift", [icols, (np.full(6, 3, dtype=np.int64), np.zeros(6, dtype=bool))]),
        ("greatest", [icols, (np.full(6, 2, dtype=np.int64), np.zeros(6, dtype=bool))]),
    ]:
        _, _, fn = KERNELS[op]
        dc, nc = fn(np, *args)
        jargs = [(jnp.asarray(d), jnp.asarray(nl)) for d, nl in args]
        dj, nj = fn(jnp, *jargs)
        np.testing.assert_array_equal(np.asarray(dc), np.asarray(dj), err_msg=op)
        np.testing.assert_array_equal(np.asarray(nc), np.asarray(nj), err_msg=op)


def test_catalog_review_fixes():
    # FIELD never NULL; NULL candidates skipped
    d, nl = _run(call("field", const_bytes(None), const_bytes(b"a")))
    assert d[0] == 0 and not nl[0]
    d, nl = _run(call("field", const_bytes(b"b"), const_bytes(b"a"), const_bytes(None), const_bytes(b"b")))
    assert d[0] == 3 and not nl[0]
    # ELT: unselected NULL candidate doesn't null the row
    d, nl = _run(call("elt", const_int(1), const_bytes(b"x"), const_bytes(None)))
    assert d[0] == b"x" and not nl[0]
    d, nl = _run(call("elt", const_int(2), const_bytes(b"x"), const_bytes(None)))
    assert nl[0]
    # pads/space/repeat refuse blob-width bombs with NULL, no allocation
    d, nl = _run(call("space", const_int(10**12)))
    assert nl[0]
    d, nl = _run(call("lpad", const_bytes(b"a"), const_int(10**9), const_bytes(b" ")))
    assert nl[0]
    d, nl = _run(call("repeat", const_bytes(b"ab"), const_int(10**9)))
    assert nl[0]
    # from_base64 reference semantics
    d, nl = _run(call("from_base64", const_bytes(b"abc")))
    assert d[0] == b"" and not nl[0]  # bad length -> empty
    d, _ = _run(call("from_base64", const_bytes(b"YWJj\n")))
    assert d[0] == b"abc"  # whitespace stripped
    d, nl = _run(call("from_base64", const_bytes(b"Y!Jj")))
    assert nl[0]  # invalid chars -> NULL
    # inet_aton strictness
    d, nl = _run(call("inet_aton", const_bytes(b"+1.2.3.4")))
    assert nl[0]
    d, nl = _run(call("inet_aton", const_bytes(b"1..2")))
    assert d[0] == 16777218 and not nl[0]
    d, nl = _run(call("inet_aton", const_bytes(b"1.2.3.")))
    assert nl[0]
    # n-ary decimal alignment: greatest over mixed fracs compares VALUES
    from tikv_tpu.copr.rpn import const_decimal

    d, _ = _run(call("greatest", const_decimal(150, 2), const_decimal(21, 1), const_decimal(33, 2)))
    assert d[0] == 210  # 2.1 at frac 2


def test_catalog_review_fixes_round2():
    # domain NaN -> NULL
    d, nl = _run(call("log2", const_real(-1.0)))
    assert nl[0]
    d, nl = _run(call("asin", const_real(2.0)))
    assert nl[0]
    d, nl = _run(call("asin", const_real(0.5)))
    assert not nl[0]
    # f64::round edge: 0.49999999999999994 rounds DOWN (floor(x+0.5) lies)
    d, _ = _run(call("round_real", const_real(0.49999999999999994)))
    assert d[0] == 0.0
    d, _ = _run(call("round_real", const_real(-2.5)))
    assert d[0] == -3.0
    # reference divides by 10^-d: ROUND(0.35, 1) = 0.30000000000000004
    d, _ = _run(call("round_real_frac", const_real(0.35), const_int(1)))
    assert d[0] == 0.30000000000000004
    # empty list
    d, _ = _run(call("find_in_set", const_bytes(b""), const_bytes(b"")))
    assert d[0] == 0
    # form feed stripped in from_base64
    d, _ = _run(call("from_base64", const_bytes(b"YWJj\x0c")))
    assert d[0] == b"abc"


def test_catalog_review_fixes_round3():
    # LOG2(0)/LOG10(0): NULL, not -inf (f64_to_real is_finite gate)
    d, nl = _run(call("log2", const_real(0.0)))
    assert nl[0]
    d, nl = _run(call("log10", const_real(0.0)))
    assert nl[0]
    # reference TRUNCATE multiplies by 10^d (asymmetric with ROUND's divide)
    d, _ = _run(call("truncate_real_frac", const_real(0.35), const_int(1)))
    assert d[0] == 0.2999999999999999889 or abs(d[0] - 0.3) < 1e-15
    import numpy as _n

    assert _run(call("truncate_real_frac", const_real(0.35), const_int(1)))[0][0] == _n.trunc(0.35 * 10) / 10
    # overflow passes the value through unchanged
    d, nl = _run(call("truncate_real_frac", const_real(1e300), const_int(10)))
    assert d[0] == 1e300 and not nl[0]


def test_truncate_underflow_returns_zero():
    # reference: scaled value underflowing to 0 yields 0.0, overflow passes x
    d, _ = _run(call("truncate_real_frac", const_real(5.0), const_int(-400)))
    assert d[0] == 0.0
    d, _ = _run(call("truncate_real_frac", const_real(1e-200), const_int(-200)))
    assert d[0] == 0.0


def test_date_time_formatting_family():
    from tikv_tpu.copr.mysql_time import pack_datetime

    dt = pack_datetime(2026, 7, 29, 14, 5, 9, 123456)
    dtc = lambda: __import__("tikv_tpu.copr.rpn", fromlist=["Constant"]).Constant(
        dt, __import__("tikv_tpu.copr.datatypes", fromlist=["EvalType"]).EvalType.DATETIME
    )
    d, _ = _run(call("date_format", dtc(), const_bytes(b"%Y-%m-%d %H:%i:%s.%f")))
    assert d[0] == b"2026-07-29 14:05:09.123456"
    d, _ = _run(call("date_format", dtc(), const_bytes(b"%W %M %e, %y at %l:%i %p")))
    assert d[0] == b"Wednesday July 29, 26 at 2:05 PM"
    d, _ = _run(call("date_format", dtc(), const_bytes(b"%j day, %r, 100%%")))
    assert d[0] == b"210 day, 02:05:09 PM, 100%"
    d, _ = _run(call("month_name", dtc()))
    assert d[0] == b"July"
    d, _ = _run(call("day_name", dtc()))
    assert d[0] == b"Wednesday"
    d, _ = _run(call("day_of_week", dtc()))
    assert d[0] == 4  # Wednesday, 1=Sunday
    d, _ = _run(call("week_day", dtc()))
    assert d[0] == 2  # 0=Monday
    d, _ = _run(call("day_of_year", dtc()))
    assert d[0] == 210
    d, _ = _run(call("quarter", dtc()))
    assert d[0] == 3
    # TO_DAYS('2026-07-29') per MySQL; FROM_DAYS round-trips
    d, _ = _run(call("to_days", dtc()))
    todays = int(d[0])
    import datetime

    assert todays == datetime.date(2026, 7, 29).toordinal() + 365
    d, _ = _run(call("from_days", const_int(todays)))
    from tikv_tpu.copr.mysql_time import unpack_datetime

    assert unpack_datetime(int(d[0]))[:3] == (2026, 7, 29)
    d, _ = _run(call("last_day", dtc()))
    assert unpack_datetime(int(d[0]))[:3] == (2026, 7, 31)
    # datediff
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET

    other = Constant(pack_datetime(2026, 7, 1), ET.DATETIME)
    d, _ = _run(call("date_diff", dtc(), other))
    assert d[0] == 28


def test_str_to_date():
    from tikv_tpu.copr.mysql_time import unpack_datetime

    d, nl = _run(call("str_to_date", const_bytes(b"29/07/2026 14:05"), const_bytes(b"%d/%m/%Y %H:%i")))
    assert not nl[0] and unpack_datetime(int(d[0]))[:5] == (2026, 7, 29, 14, 5)
    d, nl = _run(call("str_to_date", const_bytes(b"Jul 29 2026"), const_bytes(b"%b %d %Y")))
    assert unpack_datetime(int(d[0]))[:3] == (2026, 7, 29)
    d, nl = _run(call("str_to_date", const_bytes(b"not-a-date"), const_bytes(b"%Y-%m-%d")))
    assert nl[0]
    d, nl = _run(call("str_to_date", const_bytes(b"2026-13-45"), const_bytes(b"%Y-%m-%d")))
    assert nl[0]  # out-of-range components -> NULL


def test_regexp_family():
    d, _ = _run(call("regexp", const_bytes(b"hello world"), const_bytes(b"wor.d")))
    assert d[0] == 1
    d, _ = _run(call("regexp", const_bytes(b"hello"), const_bytes(b"^x")))
    assert d[0] == 0
    d, _ = _run(call("regexp_like_ci", const_bytes(b"HELLO"), const_bytes(b"hel+o")))
    assert d[0] == 1
    d, nl = _run(call("regexp", const_bytes(b"x"), const_bytes(b"[unclosed")))
    assert nl[0]  # invalid pattern -> NULL (loud would also be fine; stable choice)
    d, _ = _run(call("regexp_substr", const_bytes(b"abc123def"), const_bytes(b"[0-9]+")))
    assert d[0] == b"123"
    d, nl = _run(call("regexp_substr", const_bytes(b"abc"), const_bytes(b"[0-9]+")))
    assert nl[0]  # no match -> NULL
    d, _ = _run(call("regexp_instr", const_bytes(b"abc123"), const_bytes(b"[0-9]")))
    assert d[0] == 4
    d, _ = _run(call("regexp_replace", const_bytes(b"a1b2"), const_bytes(b"[0-9]"), const_bytes(b"_")))
    assert d[0] == b"a_b_"


def test_date_review_fixes():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime

    zero = Constant(0, ET.DATETIME)
    d, nl = _run(call("day_name", zero))
    assert nl[0]  # zero date -> NULL, not a crash
    dt = Constant(pack_datetime(2026, 7, 29), ET.DATETIME)
    d, _ = _run(call("date_format", dt, const_bytes(b"%x-%v")))
    assert d[0] == b"2026-31"  # ISO year-week
    d, _ = _run(call("date_format", dt, const_bytes(b"%X week %V")))
    assert b"week" in d[0] and not d[0].startswith(b"X")


def test_date_review_fixes_round2():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime

    # impossible calendar dates -> NULL
    d, nl = _run(call("str_to_date", const_bytes(b"2026-02-31"), const_bytes(b"%Y-%m-%d")))
    assert nl[0]
    # %U on a Sunday-starting year: 2023-01-01 is week 01, Dec 31 week 53
    jan1 = Constant(pack_datetime(2023, 1, 1), ET.DATETIME)
    d, _ = _run(call("date_format", jan1, const_bytes(b"%U")))
    assert d[0] == b"01"
    dec31 = Constant(pack_datetime(2023, 12, 31), ET.DATETIME)
    d, _ = _run(call("date_format", dec31, const_bytes(b"%U")))
    assert d[0] == b"53"


def test_interval_and_unix_timestamp():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime, unpack_datetime

    dt = lambda *a: Constant(pack_datetime(*a), ET.DATETIME)
    d, _ = _run(call("date_add", dt(2026, 1, 31), const_int(1), const_bytes(b"MONTH")))
    assert unpack_datetime(int(d[0]))[:3] == (2026, 2, 28)  # day clamped
    d, _ = _run(call("date_add", dt(2024, 1, 31), const_int(1), const_bytes(b"MONTH")))
    assert unpack_datetime(int(d[0]))[:3] == (2024, 2, 29)  # leap year
    d, _ = _run(call("date_add", dt(2026, 7, 29, 23, 30), const_int(45), const_bytes(b"MINUTE")))
    assert unpack_datetime(int(d[0]))[:5] == (2026, 7, 30, 0, 15)  # day rollover
    d, _ = _run(call("date_sub", dt(2026, 1, 1), const_int(1), const_bytes(b"DAY")))
    assert unpack_datetime(int(d[0]))[:3] == (2025, 12, 31)
    d, _ = _run(call("date_add", dt(2026, 3, 15), const_int(-2), const_bytes(b"QUARTER")))
    assert unpack_datetime(int(d[0]))[:3] == (2025, 9, 15)
    d, nl = _run(call("date_add", dt(9999, 12, 31), const_int(1), const_bytes(b"DAY")))
    assert nl[0]  # out of range -> NULL
    # unknown unit -> loud error at eval
    with pytest.raises(ValueError, match="unknown interval unit"):
        _run(call("date_add", dt(2026, 1, 1), const_int(1), const_bytes(b"FORTNIGHT")))
    # unix timestamp round trip (UTC session tz)
    d, _ = _run(call("unix_timestamp", dt(2026, 7, 29, 12, 0, 0)))
    import datetime
    expect = int((datetime.datetime(2026, 7, 29, 12) - datetime.datetime(1970, 1, 1)).total_seconds())
    assert d[0] == expect
    d, _ = _run(call("from_unixtime", const_int(expect)))
    assert unpack_datetime(int(d[0]))[:4] == (2026, 7, 29, 12)
    d, _ = _run(call("unix_timestamp", dt(1960, 1, 1)))
    assert d[0] == 0  # pre-epoch -> 0 (MySQL)
    d, nl = _run(call("from_unixtime", const_int(-5)))
    assert nl[0]


def test_interval_boundary_fixes():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime, unpack_datetime

    dt = lambda *a: Constant(pack_datetime(*a), ET.DATETIME)
    # December 9999 month arithmetic must not construct year 10000
    d, nl = _run(call("date_add", dt(9999, 11, 15), const_int(1), const_bytes(b"MONTH")))
    assert not nl[0] and unpack_datetime(int(d[0]))[:3] == (9999, 12, 15)
    # underflow below year 1 -> NULL, not a crash
    d, nl = _run(call("date_add", dt(1, 1, 15), const_int(-1), const_bytes(b"MONTH")))
    assert nl[0]
    # huge second offsets -> NULL, not OverflowError mid-dict
    d, nl = _run(call("date_add", dt(2026, 1, 1), const_int(2_000_000_000_000), const_bytes(b"SECOND")))
    assert nl[0]
    # TIMESTAMP cap second with microseconds still converts
    d, _ = _run(call("unix_timestamp", dt(2038, 1, 19, 3, 14, 7, 1)))
    assert d[0] == 2147483647


def test_regexp_replace_backrefs():
    # $N group references (MySQL/ICU syntax)
    d, _ = _run(call("regexp_replace", const_bytes(b"John Smith"),
                     const_bytes(rb"(\w+) (\w+)"), const_bytes(b"$2, $1")))
    assert d[0] == b"Smith, John"
    # \$ escapes a literal dollar; backslash escapes pass through literally
    d, _ = _run(call("regexp_replace", const_bytes(b"price 42"),
                     const_bytes(rb"(\d+)"), const_bytes(rb"\$$1.00")))
    assert d[0] == b"price $42.00"
    # backslash consumes the next char (ICU rule): backslash-t -> literal t,
    # double backslash -> one literal backslash (never a python \g escape)
    d, _ = _run(call("regexp_replace", const_bytes(b"ab"),
                     const_bytes(b"a"), const_bytes(rb"c:\temp")))
    assert d[0] == b"c:tempb"
    d, _ = _run(call("regexp_replace", const_bytes(b"ab"),
                     const_bytes(b"a"), const_bytes(b"c:\\\\temp")))
    assert d[0] == b"c:\\tempb"
    # invalid group -> NULL (pattern has 1 group, $2 invalid)
    d, nl = _run(call("regexp_replace", const_bytes(b"x"),
                      const_bytes(b"(x)"), const_bytes(b"$2")))
    assert nl[0]


def test_regexp_replace_multidigit_groups():
    pat = b"(" + b")(".join(b"abcdefghijkl"[i:i+1] for i in range(12)) + b")"
    # 12 groups: $12 must reference group 12, not group 1 + literal '2'
    d, _ = _run(call("regexp_replace", const_bytes(b"abcdefghijkl"), const_bytes(pat), const_bytes(b"$12$1")))
    assert d[0] == b"la"


def test_regexp_group_number_bounding():
    # "$12" with one group: ICU takes the longest VALID group -> group 1 + "2"
    d, nl = _run(call("regexp_replace", const_bytes(b"ab"), const_bytes(b"(a)"), const_bytes(b"$12")))
    assert not nl[0] and d[0] == b"a2b"
    # single-digit invalid group still errors to NULL
    d, nl = _run(call("regexp_replace", const_bytes(b"x"), const_bytes(b"(x)"), const_bytes(b"$9")))
    assert nl[0]


# -- round-2 catalog extension (kernels_ext.py) ------------------------------

def test_cast_family_ext():
    from tikv_tpu.copr.mysql_time import format_datetime, pack_datetime

    d, _ = _run(call("cast_string_int", const_bytes(b"  42abc")))
    assert d[0] == 42
    d, _ = _run(call("cast_string_real", const_bytes(b"3.5x")))
    assert d[0] == 3.5
    d, _ = _run(call("cast_string_real", const_bytes(b"junk")))
    assert d[0] == 0.0
    d, _ = _run(call("cast_int_string", const_int(-7)))
    assert d[0] == b"-7"
    p = pack_datetime(2026, 7, 29, 10, 30, 5)
    d, _ = _run(call("cast_datetime_string", const_int(p)))
    assert d[0] == b"2026-07-29 10:30:05"
    d, _ = _run(call("cast_datetime_int", const_int(p)))
    assert d[0] == 20260729103005
    d, _ = _run(call("cast_int_datetime", const_int(20260729103005)))
    assert format_datetime(int(d[0])) == "2026-07-29 10:30:05"
    d, nl = _run(call("cast_int_datetime", const_int(20261399000000)))
    assert nl[0]  # month 13 -> NULL
    d, _ = _run(call("cast_int_duration", const_int(-12_30_45)))
    assert d[0] == -(12 * 3600 + 30 * 60 + 45) * 10**9
    d, _ = _run(call("cast_duration_int", const_int((1 * 3600 + 2 * 60 + 3) * 10**9)))
    assert d[0] == 10203


def test_control_ext():
    d, nl = _run(call("null_eq", const_int(None), const_int(None)))
    assert d[0] == 1 and not nl[0]
    d, nl = _run(call("null_eq", const_int(None), const_int(5)))
    assert d[0] == 0 and not nl[0]
    d, nl = _run(call("nullif", const_int(3), const_int(3)))
    assert nl[0]
    d, nl = _run(call("nullif", const_int(3), const_int(4)))
    assert d[0] == 3 and not nl[0]
    d, _ = _run(call("interval_int", const_int(23), const_int(1), const_int(10), const_int(30)))
    assert d[0] == 2
    d, _ = _run(call("interval_int", const_int(None), const_int(1)))
    assert d[0] == -1


def test_math_ext():
    d, _ = _run(call("log_base", const_real(2.0), const_real(8.0)))
    assert d[0] == 3.0
    d, nl = _run(call("log_base", const_real(1.0), const_real(8.0)))
    assert nl[0]
    d, _ = _run(call("conv", const_bytes(b"ff"), const_int(16), const_int(10)))
    assert d[0] == b"255"
    d, _ = _run(call("conv", const_bytes(b"255"), const_int(10), const_int(2)))
    assert d[0] == b"11111111"
    d, _ = _run(call("bit_count", const_int(0b1011)))
    assert d[0] == 3
    d, _ = _run(call("round_int_frac", const_int(12345), const_int(-2)))
    assert d[0] == 12300
    d, _ = _run(call("round_int_frac", const_int(12355), const_int(-2)))
    assert d[0] == 12400
    d, _ = _run(call("truncate_int_frac", const_int(12399), const_int(-2)))
    assert d[0] == 12300


def test_string_ext():
    d, _ = _run(call("insert_str", const_bytes(b"Quadratic"), const_int(3), const_int(4), const_bytes(b"What")))
    assert d[0] == b"QuWhattic"
    d, _ = _run(call("ord", const_bytes(b"2")))
    assert d[0] == 50
    d, _ = _run(call("quote", const_bytes(b"Don't!")))
    assert d[0] == b"'Don\\'t!'"
    d, _ = _run(call("soundex", const_bytes(b"Robert")))
    assert d[0] == b"R163"
    d, _ = _run(call("make_set", const_int(0b101), const_bytes(b"a"), const_bytes(b"b"), const_bytes(b"c")))
    assert d[0] == b"a,c"
    d, _ = _run(call("export_set3", const_int(5), const_bytes(b"Y"), const_bytes(b"N")))
    assert d[0].startswith(b"Y,N,Y,N")
    d, _ = _run(call("char_fn", const_int(77), const_int(121)))
    assert d[0] == b"My"
    d, _ = _run(call("format", const_real(1234567.891), const_int(2)))
    assert d[0] == b"1,234,567.89"
    d, _ = _run(call("locate3", const_bytes(b"o"), const_bytes(b"foobarbar"), const_int(3)))
    assert d[0] == 3
    d, _ = _run(call("mid", const_bytes(b"abcdef"), const_int(-3), const_int(2)))
    assert d[0] == b"de"
    d, _ = _run(call("concat_ws", const_bytes(b","), const_bytes(b"a"), const_bytes(None), const_bytes(b"b")))
    assert d[0] == b"a,b"
    d, _ = _run(call("trim2", const_bytes(b"xxbarxx"), const_bytes(b"x")))
    assert d[0] == b"bar"
    d, _ = _run(call("trim_leading", const_bytes(b"xxbarxx"), const_bytes(b"x")))
    assert d[0] == b"barxx"
    d, _ = _run(call("left_utf8", const_bytes("héllo".encode()), const_int(2)))
    assert d[0] == "hé".encode()
    d, _ = _run(call("substr_utf8_2", const_bytes("héllo".encode()), const_int(-2)))
    assert d[0] == b"lo"
    d, _ = _run(call("position", const_bytes(b"bar"), const_bytes(b"foobar")))
    assert d[0] == 4


def test_compress_ext():
    import zlib

    src = b"hello hello hello"
    d, _ = _run(call("compress", const_bytes(src)))
    comp = d[0]
    assert int.from_bytes(comp[:4], "little") == len(src)
    d, _ = _run(call("uncompress", const_bytes(comp)))
    assert d[0] == src
    d, _ = _run(call("uncompressed_length", const_bytes(comp)))
    assert d[0] == len(src)
    d, nl = _run(call("uncompress", const_bytes(b"\x05\x00\x00\x00junk")))
    assert nl[0]


def test_time_ext():
    from tikv_tpu.copr.mysql_time import (
        NANOS_PER_SEC,
        format_datetime,
        pack_datetime,
    )

    d, _ = _run(call("makedate", const_int(2026), const_int(32)))
    assert format_datetime(int(d[0])).startswith("2026-02-01")
    d, _ = _run(call("maketime", const_int(2), const_int(30), const_int(15)))
    assert d[0] == (2 * 3600 + 30 * 60 + 15) * NANOS_PER_SEC
    d, _ = _run(call("period_add", const_int(202607), const_int(7)))
    assert d[0] == 202702
    d, _ = _run(call("period_diff", const_int(202702), const_int(202607)))
    assert d[0] == 7
    d, _ = _run(call("time_to_sec", const_int(90 * NANOS_PER_SEC)))
    assert d[0] == 90
    d, _ = _run(call("sec_to_time", const_int(90)))
    assert d[0] == 90 * NANOS_PER_SEC
    p = pack_datetime(2026, 7, 29, 12, 0, 0)
    d, _ = _run(call("convert_tz", const_int(p), const_bytes(b"+00:00"), const_bytes(b"+05:30")))
    assert format_datetime(int(d[0])) == "2026-07-29 17:30:00"
    d, nl = _run(call("convert_tz", const_int(p), const_bytes(b"Mars/Olympus"), const_bytes(b"+00:00")))
    assert nl[0]
    d, _ = _run(call("time_format", const_int((26 * 3600 + 5 * 60 + 9) * NANOS_PER_SEC), const_bytes(b"%H:%i:%s")))
    assert d[0] == b"26:05:09"
    d, _ = _run(call("week_of_year", const_int(pack_datetime(2026, 1, 8))))
    assert d[0] == 2
    d, _ = _run(call("extract_datetime", const_bytes(b"MONTH"), const_int(p)))
    assert d[0] == 7
    d, _ = _run(call("timestamp_add", const_bytes(b"DAY"), const_int(3), const_int(p)))
    assert format_datetime(int(d[0])).startswith("2026-08-01")
    d, _ = _run(call("add_datetime_duration", const_int(p), const_int(3600 * NANOS_PER_SEC)))
    assert format_datetime(int(d[0])) == "2026-07-29 13:00:00"
    d, _ = _run(call("get_format", const_bytes(b"DATE"), const_bytes(b"ISO")))
    assert d[0] == b"%Y-%m-%d"


def test_json_ext():
    from tikv_tpu.copr.json_value import json_encode, json_parse_text

    def j(text):
        return const_bytes(json_encode(json_parse_text(text)))

    d, _ = _run(call("json_merge_patch", j('{"a":1,"b":2}'), j('{"b":null,"c":3}')))
    from tikv_tpu.copr.json_value import json_decode

    assert json_decode(bytes(d[0])) == {"a": 1, "c": 3}
    d, _ = _run(call("json_storage_size", j('{"a":1}')))
    assert d[0] > 0
    d, _ = _run(call("json_member_of", j("2"), j("[1,2,3]")))
    assert d[0] == 1
    d, _ = _run(call("json_overlaps", j("[1,9]"), j("[9,10]")))
    assert d[0] == 1
    d, _ = _run(call("json_overlaps", j("[1,2]"), j("[3]")))
    assert d[0] == 0
    d, _ = _run(call("json_search", j('["abc","ab"]'), const_bytes(b"one"), const_bytes(b"ab%")))
    assert json_decode(bytes(d[0])) == "$[0]"
    d, _ = _run(call("json_contains_path", j('{"a":{"b":1}}'), const_bytes(b"one"), const_bytes(b"$.a.b")))
    assert d[0] == 1
    d, _ = _run(call("json_array_append", j("[1,2]"), const_bytes(b"$"), j("3")))
    assert json_decode(bytes(d[0])) == [1, 2, 3]
    d, _ = _run(call("json_array_insert", j("[1,3]"), const_bytes(b"$[1]"), j("2")))
    assert json_decode(bytes(d[0])) == [1, 2, 3]
    d, _ = _run(call("json_pretty", j("[1,2]")))
    assert b"\n" in d[0]


def test_misc_ext():
    d, _ = _run(call("is_ipv4", const_bytes(b"10.0.0.1")))
    assert d[0] == 1
    d, _ = _run(call("is_ipv6", const_bytes(b"::1")))
    assert d[0] == 1
    d, _ = _run(call("inet6_aton", const_bytes(b"::1")))
    assert d[0] == b"\x00" * 15 + b"\x01"
    d, _ = _run(call("inet6_ntoa", const_bytes(b"\x00" * 15 + b"\x01")))
    assert d[0] == b"::1"
    d, _ = _run(call("is_ipv4_mapped", const_bytes(b"\x00" * 10 + b"\xff\xff" + b"\x7f\x00\x00\x01")))
    assert d[0] == 1
    d, _ = _run(call("is_uuid", const_bytes(b"6ccd780c-baba-1026-9564-5b8c656024db")))
    assert d[0] == 1
    d, _ = _run(call("uuid_to_bin", const_bytes(b"6ccd780c-baba-1026-9564-5b8c656024db")))
    assert len(d[0]) == 16
    d, _ = _run(call("bin_to_uuid", const_bytes(bytes(range(16)))))
    assert d[0] == b"00010203-0405-0607-0809-0a0b0c0d0e0f"
    d, _ = _run(call("password", const_bytes(b"mypass")))
    assert d[0].startswith(b"*") and len(d[0]) == 41
    d, _ = _run(call("greatest_string", const_bytes(b"b"), const_bytes(b"a"), const_bytes(b"c")))
    assert d[0] == b"c"
    d, _ = _run(call("least_real", const_real(2.5), const_real(1.5)))
    assert d[0] == 1.5
    d, nl = _run(call("is_not_null", const_int(None)))
    assert d[0] == 0 and not nl[0]


def test_catalog_size_and_coverage():
    """The round-2 bar: >= 250 kernels, and the generated coverage doc maps
    every reference sig to a kernel or an explicit declined reason."""
    assert len(KERNELS) >= 250, len(KERNELS)
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "catalog_coverage.py")],
        capture_output=True, cwd=repo, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr
    if "unavailable" not in r.stdout:
        assert "missing=0" in r.stdout, r.stdout


def test_ext_edge_cases_from_review():
    """Regressions: int64-max string casts parse exactly (no float round
    trip), numeric date literals honor the 2-digit-year rule, to_seconds
    matches the to_days day-count convention, YEARWEEK uses mode 0,
    LOCATE(pos<1)=0, and JSON predicates yield NULL on NULL operands."""
    from tikv_tpu.copr.mysql_time import format_datetime, pack_datetime

    d, _ = _run(call("cast_string_int", const_bytes(b"9223372036854775807")))
    assert d[0] == 9223372036854775807
    d, _ = _run(call("cast_string_int", const_bytes(b"99999999999999999999")))
    assert d[0] == 9223372036854775807  # clamped, not crashed
    d, _ = _run(call("cast_int_datetime", const_int(700101)))
    assert format_datetime(int(d[0])).startswith("1970-01-01")
    d, _ = _run(call("cast_int_datetime", const_int(690101)))
    assert format_datetime(int(d[0])).startswith("2069-01-01")
    d, _ = _run(call("to_seconds", const_int(pack_datetime(1970, 1, 1))))
    assert d[0] == 719528 * 86400  # to_days('1970-01-01') * 86400
    d, _ = _run(call("year_week", const_int(pack_datetime(2026, 1, 1))))
    assert d[0] == 202552  # week-0 rolls back to the previous year
    d, _ = _run(call("locate3", const_bytes(b"o"), const_bytes(b"foo"), const_int(0)))
    assert d[0] == 0
    d, nl = _run(call("json_member_of", const_bytes(None), const_bytes(None)))
    assert nl[0]  # NULL operand -> NULL, not a crash


def test_string_time_arithmetic():
    """ADDTIME/SUBTIME string arms (impl_time.rs Add*AndString family)."""
    from tikv_tpu.copr.mysql_time import format_datetime, parse_datetime, parse_duration

    dt = parse_datetime("2024-03-01 10:00:00")
    # datetime + 'HH:MM:SS' string
    d, nl = _run(call("add_datetime_and_string", const_int(dt), const_bytes(b"01:30:00")))
    assert not nl[0] and format_datetime(int(d[0])) == "2024-03-01 11:30:00"
    d, _ = _run(call("sub_datetime_and_string", const_int(dt), const_bytes(b"11:00:00")))
    assert format_datetime(int(d[0])) == "2024-02-29 23:00:00"  # leap day
    # datetime + datetime-string is NULL (MySQL)
    _, nl = _run(call("add_datetime_and_string", const_int(dt), const_bytes(b"2024-01-01 00:00:00")))
    assert nl[0]
    # duration + string
    d, _ = _run(call("add_duration_and_string", const_int(parse_duration("01:00:00")), const_bytes(b"00:30:15")))
    assert int(d[0]) == parse_duration("01:30:15")
    # string + duration → string
    d, _ = _run(call("add_string_and_duration", const_bytes(b"01:00:00"), const_int(parse_duration("02:15:00"))))
    assert bytes(d[0]) == b"03:15:00"
    d, _ = _run(call("sub_string_and_duration", const_bytes(b"2024-03-01 10:00:00"), const_int(parse_duration("10:00:01"))))
    assert bytes(d[0]) == b"2024-02-29 23:59:59"
    # garbage strings are NULL, not errors
    _, nl = _run(call("add_string_and_duration", const_bytes(b"nope"), const_int(0)))
    assert nl[0]
    # the statically-NULL arm
    _, nl = _run(call("add_time_string_null", const_int(1), const_bytes(b"x")))
    assert nl[0]


def test_string_time_numeric_and_date_arms():
    from tikv_tpu.copr.mysql_time import parse_datetime, parse_duration

    # bare numeric time is RIGHT-aligned HHMMSS: '123' = 00:01:23 (MySQL)
    d, _ = _run(call("add_string_and_duration", const_bytes(b"123"), const_int(parse_duration("01:00:00"))))
    assert bytes(d[0]) == b"01:01:23"
    _, nl = _run(call("add_string_and_duration", const_bytes(b"178"), const_int(0)))
    assert nl[0]  # 00:01:78 is not a valid time
    # add_date_and_string: packed date + duration string → formatted string
    dt = parse_datetime("2024-03-01 00:00:00")
    d, nl = _run(call("add_date_and_string", const_int(dt), const_bytes(b"26:00:00")))
    assert not nl[0] and bytes(d[0]) == b"2024-03-02 02:00:00"
    # datetime-string second operand → NULL
    _, nl = _run(call("add_date_and_string", const_int(dt), const_bytes(b"2024-01-01 00:00:00")))
    assert nl[0]
