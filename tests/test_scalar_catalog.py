"""Scalar-function catalog tranche (reference: tidb_query_expr impl_math.rs /
impl_op.rs / impl_string.rs / impl_compare.rs / impl_misc.rs): CPU oracle
checks, and device agreement for the xp-generic (numeric) kernels."""

import numpy as np
import pytest

from tikv_tpu.copr.datatypes import EvalType
from tikv_tpu.copr.kernels import KERNELS
from tikv_tpu.copr.rpn import call, col, compile_expr, const_bytes, const_int, const_real, eval_rpn


def _run(expr, columns=None, n=1, schema=()):
    rpn = compile_expr(expr, list(schema))
    return eval_rpn(rpn, columns or {}, n, xp=np)


def test_math_tranche():
    d, _ = _run(call("log2", const_real(8.0)))
    assert d[0] == 3.0
    d, _ = _run(call("log10", const_real(1000.0)))
    assert d[0] == 3.0
    d, _ = _run(call("atan2", const_real(1.0), const_real(1.0)))
    assert abs(d[0] - 0.7853981633974483) < 1e-12
    d, nl = _run(call("cot", const_real(0.0)))
    assert nl[0]  # cot(0) -> NULL (division by zero)
    d, _ = _run(call("radians", const_real(180.0)))
    assert abs(d[0] - 3.141592653589793) < 1e-12
    d, _ = _run(call("degrees", const_real(3.141592653589793)))
    assert abs(d[0] - 180.0) < 1e-9
    d, _ = _run(call("sign", const_real(-2.5)))
    assert d[0] == -1
    # MySQL ROUND: half away from zero, also for negatives
    d, _ = _run(call("round_real", const_real(2.5)))
    assert d[0] == 3.0
    d, _ = _run(call("round_real", const_real(-2.5)))
    assert d[0] == -3.0
    d, _ = _run(call("round_real_frac", const_real(3.14159), const_int(2)))
    assert d[0] == 3.14
    d, _ = _run(call("truncate_real_frac", const_real(3.199), const_int(2)))
    assert abs(d[0] - 3.19) < 1e-12


def test_bit_ops():
    d, _ = _run(call("bit_and", const_int(0b1100), const_int(0b1010)))
    assert d[0] == 0b1000
    d, _ = _run(call("bit_or", const_int(0b1100), const_int(0b1010)))
    assert d[0] == 0b1110
    d, _ = _run(call("bit_xor", const_int(0b1100), const_int(0b1010)))
    assert d[0] == 0b0110
    d, _ = _run(call("bit_neg", const_int(0)))
    assert d[0] == -1  # ~0 = u64 max bit pattern
    d, _ = _run(call("left_shift", const_int(1), const_int(10)))
    assert d[0] == 1024
    d, _ = _run(call("left_shift", const_int(1), const_int(64)))
    assert d[0] == 0  # MySQL: shift >= 64 -> 0
    d, _ = _run(call("right_shift", const_int(-1), const_int(60)))
    assert d[0] == 15  # logical shift on the u64 pattern


def test_greatest_least():
    d, _ = _run(call("greatest", const_int(3), const_int(9), const_int(5)))
    assert d[0] == 9
    d, _ = _run(call("least", const_real(3.5), const_real(-1.0)))
    assert d[0] == -1.0
    d, nl = _run(call("greatest", const_int(3), const_int(None)))
    assert nl[0]  # NULL if any operand NULL


def test_string_tranche():
    d, _ = _run(call("lpad", const_bytes(b"5"), const_int(3), const_bytes(b"0")))
    assert d[0] == b"005"
    d, _ = _run(call("rpad", const_bytes(b"ab"), const_int(5), const_bytes(b"xy")))
    assert d[0] == b"abxyx"
    d, nl = _run(call("lpad", const_bytes(b"a"), const_int(5), const_bytes(b"")))
    assert nl[0]  # empty pad, n > len -> NULL
    d, _ = _run(call("repeat", const_bytes(b"ab"), const_int(3)))
    assert d[0] == b"ababab"
    d, _ = _run(call("space", const_int(4)))
    assert d[0] == b"    "
    d, _ = _run(call("strcmp", const_bytes(b"a"), const_bytes(b"b")))
    assert d[0] == -1
    d, _ = _run(call("instr", const_bytes(b"foobar"), const_bytes(b"bar")))
    assert d[0] == 4
    d, _ = _run(call("char_length", const_bytes("héllo".encode())))
    assert d[0] == 6  # binary-collation semantics: byte length (reference)
    d, _ = _run(call("char_length_utf8", const_bytes("héllo".encode())))
    assert d[0] == 5  # character count
    d, _ = _run(call("crc32", const_bytes(b"MySQL")))
    assert d[0] == 3259397556  # known MySQL doc value
    d, _ = _run(call("find_in_set", const_bytes(b"b"), const_bytes(b"a,b,c")))
    assert d[0] == 2
    d, _ = _run(call("substring_index", const_bytes(b"www.mysql.com"), const_bytes(b"."), const_int(2)))
    assert d[0] == b"www.mysql"
    d, _ = _run(call("substring_index", const_bytes(b"www.mysql.com"), const_bytes(b"."), const_int(-2)))
    assert d[0] == b"mysql.com"
    d, _ = _run(call("elt", const_int(2), const_bytes(b"x"), const_bytes(b"y")))
    assert d[0] == b"y"
    d, nl = _run(call("elt", const_int(5), const_bytes(b"x"), const_bytes(b"y")))
    assert nl[0]
    d, _ = _run(call("field", const_bytes(b"b"), const_bytes(b"a"), const_bytes(b"b")))
    assert d[0] == 2
    d, _ = _run(call("oct_int", const_int(12)))
    assert d[0] == b"14"
    d, _ = _run(call("bin_int", const_int(12)))
    assert d[0] == b"1100"
    d, _ = _run(call("unhex", const_bytes(b"4D7953514C")))
    assert d[0] == b"MySQL"
    d, nl = _run(call("unhex", const_bytes(b"zz")))
    assert nl[0]  # invalid hex -> NULL
    d, _ = _run(call("to_base64", const_bytes(b"abc")))
    assert d[0] == b"YWJj"
    d, _ = _run(call("from_base64", const_bytes(b"YWJj")))
    assert d[0] == b"abc"
    d, _ = _run(call("md5", const_bytes(b"testing")))
    assert d[0] == b"ae2b1fca515949e5d54fb22b8ed95575"
    d, _ = _run(call("sha1", const_bytes(b"abc")))
    assert d[0] == b"a9993e364706816aba3e25717850c26c9cd0d89d"
    d, _ = _run(call("sha2", const_bytes(b"abc"), const_int(256)))
    assert d[0] == b"ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
    d, nl = _run(call("sha2", const_bytes(b"abc"), const_int(123)))
    assert nl[0]  # invalid length -> NULL


def test_inet():
    d, _ = _run(call("inet_aton", const_bytes(b"10.0.5.9")))
    assert d[0] == 167773449
    d, _ = _run(call("inet_aton", const_bytes(b"127.1")))  # MySQL short form
    assert d[0] == (127 << 24) | 1
    d, nl = _run(call("inet_aton", const_bytes(b"not.an.ip")))
    assert nl[0]
    d, _ = _run(call("inet_ntoa", const_int(167773449)))
    assert d[0] == b"10.0.5.9"
    d, nl = _run(call("inet_ntoa", const_int(2**40)))
    assert nl[0]


def test_numeric_tranche_device_agrees_with_cpu():
    """The xp-generic kernels must produce identical results under jax.numpy
    (CPU backend) — the one-kernel-table invariant."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    vals = np.array([-3.7, -0.5, 0.0, 0.5, 2.5, 9.99], dtype=np.float64)
    ints = np.array([-8, -1, 0, 1, 7, 63], dtype=np.int64)
    fcols = (vals, np.zeros(6, dtype=bool))
    icols = (ints, np.zeros(6, dtype=bool))
    for op, args in [
        ("round_real", [fcols]),
        ("sign", [fcols]),
        ("radians", [fcols]),
        ("degrees", [fcols]),
        ("bit_neg", [icols]),
        ("left_shift", [icols, (np.full(6, 3, dtype=np.int64), np.zeros(6, dtype=bool))]),
        ("greatest", [icols, (np.full(6, 2, dtype=np.int64), np.zeros(6, dtype=bool))]),
    ]:
        _, _, fn = KERNELS[op]
        dc, nc = fn(np, *args)
        jargs = [(jnp.asarray(d), jnp.asarray(nl)) for d, nl in args]
        dj, nj = fn(jnp, *jargs)
        np.testing.assert_array_equal(np.asarray(dc), np.asarray(dj), err_msg=op)
        np.testing.assert_array_equal(np.asarray(nc), np.asarray(nj), err_msg=op)


def test_catalog_review_fixes():
    # FIELD never NULL; NULL candidates skipped
    d, nl = _run(call("field", const_bytes(None), const_bytes(b"a")))
    assert d[0] == 0 and not nl[0]
    d, nl = _run(call("field", const_bytes(b"b"), const_bytes(b"a"), const_bytes(None), const_bytes(b"b")))
    assert d[0] == 3 and not nl[0]
    # ELT: unselected NULL candidate doesn't null the row
    d, nl = _run(call("elt", const_int(1), const_bytes(b"x"), const_bytes(None)))
    assert d[0] == b"x" and not nl[0]
    d, nl = _run(call("elt", const_int(2), const_bytes(b"x"), const_bytes(None)))
    assert nl[0]
    # pads/space/repeat refuse blob-width bombs with NULL, no allocation
    d, nl = _run(call("space", const_int(10**12)))
    assert nl[0]
    d, nl = _run(call("lpad", const_bytes(b"a"), const_int(10**9), const_bytes(b" ")))
    assert nl[0]
    d, nl = _run(call("repeat", const_bytes(b"ab"), const_int(10**9)))
    assert nl[0]
    # from_base64 reference semantics
    d, nl = _run(call("from_base64", const_bytes(b"abc")))
    assert d[0] == b"" and not nl[0]  # bad length -> empty
    d, _ = _run(call("from_base64", const_bytes(b"YWJj\n")))
    assert d[0] == b"abc"  # whitespace stripped
    d, nl = _run(call("from_base64", const_bytes(b"Y!Jj")))
    assert nl[0]  # invalid chars -> NULL
    # inet_aton strictness
    d, nl = _run(call("inet_aton", const_bytes(b"+1.2.3.4")))
    assert nl[0]
    d, nl = _run(call("inet_aton", const_bytes(b"1..2")))
    assert d[0] == 16777218 and not nl[0]
    d, nl = _run(call("inet_aton", const_bytes(b"1.2.3.")))
    assert nl[0]
    # n-ary decimal alignment: greatest over mixed fracs compares VALUES
    from tikv_tpu.copr.rpn import const_decimal

    d, _ = _run(call("greatest", const_decimal(150, 2), const_decimal(21, 1), const_decimal(33, 2)))
    assert d[0] == 210  # 2.1 at frac 2


def test_catalog_review_fixes_round2():
    # domain NaN -> NULL
    d, nl = _run(call("log2", const_real(-1.0)))
    assert nl[0]
    d, nl = _run(call("asin", const_real(2.0)))
    assert nl[0]
    d, nl = _run(call("asin", const_real(0.5)))
    assert not nl[0]
    # f64::round edge: 0.49999999999999994 rounds DOWN (floor(x+0.5) lies)
    d, _ = _run(call("round_real", const_real(0.49999999999999994)))
    assert d[0] == 0.0
    d, _ = _run(call("round_real", const_real(-2.5)))
    assert d[0] == -3.0
    # reference divides by 10^-d: ROUND(0.35, 1) = 0.30000000000000004
    d, _ = _run(call("round_real_frac", const_real(0.35), const_int(1)))
    assert d[0] == 0.30000000000000004
    # empty list
    d, _ = _run(call("find_in_set", const_bytes(b""), const_bytes(b"")))
    assert d[0] == 0
    # form feed stripped in from_base64
    d, _ = _run(call("from_base64", const_bytes(b"YWJj\x0c")))
    assert d[0] == b"abc"


def test_catalog_review_fixes_round3():
    # LOG2(0)/LOG10(0): NULL, not -inf (f64_to_real is_finite gate)
    d, nl = _run(call("log2", const_real(0.0)))
    assert nl[0]
    d, nl = _run(call("log10", const_real(0.0)))
    assert nl[0]
    # reference TRUNCATE multiplies by 10^d (asymmetric with ROUND's divide)
    d, _ = _run(call("truncate_real_frac", const_real(0.35), const_int(1)))
    assert d[0] == 0.2999999999999999889 or abs(d[0] - 0.3) < 1e-15
    import numpy as _n

    assert _run(call("truncate_real_frac", const_real(0.35), const_int(1)))[0][0] == _n.trunc(0.35 * 10) / 10
    # overflow passes the value through unchanged
    d, nl = _run(call("truncate_real_frac", const_real(1e300), const_int(10)))
    assert d[0] == 1e300 and not nl[0]


def test_truncate_underflow_returns_zero():
    # reference: scaled value underflowing to 0 yields 0.0, overflow passes x
    d, _ = _run(call("truncate_real_frac", const_real(5.0), const_int(-400)))
    assert d[0] == 0.0
    d, _ = _run(call("truncate_real_frac", const_real(1e-200), const_int(-200)))
    assert d[0] == 0.0


def test_date_time_formatting_family():
    from tikv_tpu.copr.mysql_time import pack_datetime

    dt = pack_datetime(2026, 7, 29, 14, 5, 9, 123456)
    dtc = lambda: __import__("tikv_tpu.copr.rpn", fromlist=["Constant"]).Constant(
        dt, __import__("tikv_tpu.copr.datatypes", fromlist=["EvalType"]).EvalType.DATETIME
    )
    d, _ = _run(call("date_format", dtc(), const_bytes(b"%Y-%m-%d %H:%i:%s.%f")))
    assert d[0] == b"2026-07-29 14:05:09.123456"
    d, _ = _run(call("date_format", dtc(), const_bytes(b"%W %M %e, %y at %l:%i %p")))
    assert d[0] == b"Wednesday July 29, 26 at 2:05 PM"
    d, _ = _run(call("date_format", dtc(), const_bytes(b"%j day, %r, 100%%")))
    assert d[0] == b"210 day, 02:05:09 PM, 100%"
    d, _ = _run(call("month_name", dtc()))
    assert d[0] == b"July"
    d, _ = _run(call("day_name", dtc()))
    assert d[0] == b"Wednesday"
    d, _ = _run(call("day_of_week", dtc()))
    assert d[0] == 4  # Wednesday, 1=Sunday
    d, _ = _run(call("week_day", dtc()))
    assert d[0] == 2  # 0=Monday
    d, _ = _run(call("day_of_year", dtc()))
    assert d[0] == 210
    d, _ = _run(call("quarter", dtc()))
    assert d[0] == 3
    # TO_DAYS('2026-07-29') per MySQL; FROM_DAYS round-trips
    d, _ = _run(call("to_days", dtc()))
    todays = int(d[0])
    import datetime

    assert todays == datetime.date(2026, 7, 29).toordinal() + 365
    d, _ = _run(call("from_days", const_int(todays)))
    from tikv_tpu.copr.mysql_time import unpack_datetime

    assert unpack_datetime(int(d[0]))[:3] == (2026, 7, 29)
    d, _ = _run(call("last_day", dtc()))
    assert unpack_datetime(int(d[0]))[:3] == (2026, 7, 31)
    # datediff
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET

    other = Constant(pack_datetime(2026, 7, 1), ET.DATETIME)
    d, _ = _run(call("date_diff", dtc(), other))
    assert d[0] == 28


def test_str_to_date():
    from tikv_tpu.copr.mysql_time import unpack_datetime

    d, nl = _run(call("str_to_date", const_bytes(b"29/07/2026 14:05"), const_bytes(b"%d/%m/%Y %H:%i")))
    assert not nl[0] and unpack_datetime(int(d[0]))[:5] == (2026, 7, 29, 14, 5)
    d, nl = _run(call("str_to_date", const_bytes(b"Jul 29 2026"), const_bytes(b"%b %d %Y")))
    assert unpack_datetime(int(d[0]))[:3] == (2026, 7, 29)
    d, nl = _run(call("str_to_date", const_bytes(b"not-a-date"), const_bytes(b"%Y-%m-%d")))
    assert nl[0]
    d, nl = _run(call("str_to_date", const_bytes(b"2026-13-45"), const_bytes(b"%Y-%m-%d")))
    assert nl[0]  # out-of-range components -> NULL


def test_regexp_family():
    d, _ = _run(call("regexp", const_bytes(b"hello world"), const_bytes(b"wor.d")))
    assert d[0] == 1
    d, _ = _run(call("regexp", const_bytes(b"hello"), const_bytes(b"^x")))
    assert d[0] == 0
    d, _ = _run(call("regexp_like_ci", const_bytes(b"HELLO"), const_bytes(b"hel+o")))
    assert d[0] == 1
    d, nl = _run(call("regexp", const_bytes(b"x"), const_bytes(b"[unclosed")))
    assert nl[0]  # invalid pattern -> NULL (loud would also be fine; stable choice)
    d, _ = _run(call("regexp_substr", const_bytes(b"abc123def"), const_bytes(b"[0-9]+")))
    assert d[0] == b"123"
    d, nl = _run(call("regexp_substr", const_bytes(b"abc"), const_bytes(b"[0-9]+")))
    assert nl[0]  # no match -> NULL
    d, _ = _run(call("regexp_instr", const_bytes(b"abc123"), const_bytes(b"[0-9]")))
    assert d[0] == 4
    d, _ = _run(call("regexp_replace", const_bytes(b"a1b2"), const_bytes(b"[0-9]"), const_bytes(b"_")))
    assert d[0] == b"a_b_"


def test_date_review_fixes():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime

    zero = Constant(0, ET.DATETIME)
    d, nl = _run(call("day_name", zero))
    assert nl[0]  # zero date -> NULL, not a crash
    dt = Constant(pack_datetime(2026, 7, 29), ET.DATETIME)
    d, _ = _run(call("date_format", dt, const_bytes(b"%x-%v")))
    assert d[0] == b"2026-31"  # ISO year-week
    d, _ = _run(call("date_format", dt, const_bytes(b"%X week %V")))
    assert b"week" in d[0] and not d[0].startswith(b"X")


def test_date_review_fixes_round2():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime

    # impossible calendar dates -> NULL
    d, nl = _run(call("str_to_date", const_bytes(b"2026-02-31"), const_bytes(b"%Y-%m-%d")))
    assert nl[0]
    # %U on a Sunday-starting year: 2023-01-01 is week 01, Dec 31 week 53
    jan1 = Constant(pack_datetime(2023, 1, 1), ET.DATETIME)
    d, _ = _run(call("date_format", jan1, const_bytes(b"%U")))
    assert d[0] == b"01"
    dec31 = Constant(pack_datetime(2023, 12, 31), ET.DATETIME)
    d, _ = _run(call("date_format", dec31, const_bytes(b"%U")))
    assert d[0] == b"53"


def test_interval_and_unix_timestamp():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime, unpack_datetime

    dt = lambda *a: Constant(pack_datetime(*a), ET.DATETIME)
    d, _ = _run(call("date_add", dt(2026, 1, 31), const_int(1), const_bytes(b"MONTH")))
    assert unpack_datetime(int(d[0]))[:3] == (2026, 2, 28)  # day clamped
    d, _ = _run(call("date_add", dt(2024, 1, 31), const_int(1), const_bytes(b"MONTH")))
    assert unpack_datetime(int(d[0]))[:3] == (2024, 2, 29)  # leap year
    d, _ = _run(call("date_add", dt(2026, 7, 29, 23, 30), const_int(45), const_bytes(b"MINUTE")))
    assert unpack_datetime(int(d[0]))[:5] == (2026, 7, 30, 0, 15)  # day rollover
    d, _ = _run(call("date_sub", dt(2026, 1, 1), const_int(1), const_bytes(b"DAY")))
    assert unpack_datetime(int(d[0]))[:3] == (2025, 12, 31)
    d, _ = _run(call("date_add", dt(2026, 3, 15), const_int(-2), const_bytes(b"QUARTER")))
    assert unpack_datetime(int(d[0]))[:3] == (2025, 9, 15)
    d, nl = _run(call("date_add", dt(9999, 12, 31), const_int(1), const_bytes(b"DAY")))
    assert nl[0]  # out of range -> NULL
    # unknown unit -> loud error at eval
    with pytest.raises(ValueError, match="unknown interval unit"):
        _run(call("date_add", dt(2026, 1, 1), const_int(1), const_bytes(b"FORTNIGHT")))
    # unix timestamp round trip (UTC session tz)
    d, _ = _run(call("unix_timestamp", dt(2026, 7, 29, 12, 0, 0)))
    import datetime
    expect = int((datetime.datetime(2026, 7, 29, 12) - datetime.datetime(1970, 1, 1)).total_seconds())
    assert d[0] == expect
    d, _ = _run(call("from_unixtime", const_int(expect)))
    assert unpack_datetime(int(d[0]))[:4] == (2026, 7, 29, 12)
    d, _ = _run(call("unix_timestamp", dt(1960, 1, 1)))
    assert d[0] == 0  # pre-epoch -> 0 (MySQL)
    d, nl = _run(call("from_unixtime", const_int(-5)))
    assert nl[0]


def test_interval_boundary_fixes():
    from tikv_tpu.copr.rpn import Constant
    from tikv_tpu.copr.datatypes import EvalType as ET
    from tikv_tpu.copr.mysql_time import pack_datetime, unpack_datetime

    dt = lambda *a: Constant(pack_datetime(*a), ET.DATETIME)
    # December 9999 month arithmetic must not construct year 10000
    d, nl = _run(call("date_add", dt(9999, 11, 15), const_int(1), const_bytes(b"MONTH")))
    assert not nl[0] and unpack_datetime(int(d[0]))[:3] == (9999, 12, 15)
    # underflow below year 1 -> NULL, not a crash
    d, nl = _run(call("date_add", dt(1, 1, 15), const_int(-1), const_bytes(b"MONTH")))
    assert nl[0]
    # huge second offsets -> NULL, not OverflowError mid-dict
    d, nl = _run(call("date_add", dt(2026, 1, 1), const_int(2_000_000_000_000), const_bytes(b"SECOND")))
    assert nl[0]
    # TIMESTAMP cap second with microseconds still converts
    d, _ = _run(call("unix_timestamp", dt(2038, 1, 19, 3, 14, 7, 1)))
    assert d[0] == 2147483647


def test_regexp_replace_backrefs():
    # $N group references (MySQL/ICU syntax)
    d, _ = _run(call("regexp_replace", const_bytes(b"John Smith"),
                     const_bytes(rb"(\w+) (\w+)"), const_bytes(b"$2, $1")))
    assert d[0] == b"Smith, John"
    # \$ escapes a literal dollar; backslash escapes pass through literally
    d, _ = _run(call("regexp_replace", const_bytes(b"price 42"),
                     const_bytes(rb"(\d+)"), const_bytes(rb"\$$1.00")))
    assert d[0] == b"price $42.00"
    # backslash consumes the next char (ICU rule): backslash-t -> literal t,
    # double backslash -> one literal backslash (never a python \g escape)
    d, _ = _run(call("regexp_replace", const_bytes(b"ab"),
                     const_bytes(b"a"), const_bytes(rb"c:\temp")))
    assert d[0] == b"c:tempb"
    d, _ = _run(call("regexp_replace", const_bytes(b"ab"),
                     const_bytes(b"a"), const_bytes(b"c:\\\\temp")))
    assert d[0] == b"c:\\tempb"
    # invalid group -> NULL (pattern has 1 group, $2 invalid)
    d, nl = _run(call("regexp_replace", const_bytes(b"x"),
                      const_bytes(b"(x)"), const_bytes(b"$2")))
    assert nl[0]


def test_regexp_replace_multidigit_groups():
    pat = b"(" + b")(".join(b"abcdefghijkl"[i:i+1] for i in range(12)) + b")"
    # 12 groups: $12 must reference group 12, not group 1 + literal '2'
    d, _ = _run(call("regexp_replace", const_bytes(b"abcdefghijkl"), const_bytes(pat), const_bytes(b"$12$1")))
    assert d[0] == b"la"


def test_regexp_group_number_bounding():
    # "$12" with one group: ICU takes the longest VALID group -> group 1 + "2"
    d, nl = _run(call("regexp_replace", const_bytes(b"ab"), const_bytes(b"(a)"), const_bytes(b"$12")))
    assert not nl[0] and d[0] == b"a2b"
    # single-digit invalid group still errors to NULL
    d, nl = _run(call("regexp_replace", const_bytes(b"x"), const_bytes(b"(x)"), const_bytes(b"$9")))
    assert nl[0]
