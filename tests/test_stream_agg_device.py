"""Streamed (sorted-input) aggregation rides the device path: for sorted
input, the device hash path's first-active-row group ordering IS the stream
order, so responses are byte-identical to BatchStreamAggregationExecutor
(VERDICT weak #6: Q1-sorted plans must not be CPU-only)."""

from __future__ import annotations

import pytest

from copr_fixtures import TABLE_ID, numeric_table_kvs
from tikv_tpu.copr import jax_eval
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, TableScan
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.table import record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import WriteBatch
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType


def _engine(n=2500):
    cols, kvs, _ = numeric_table_kvs(n, seed=3)
    eng = BTreeEngine()
    wb = WriteBatch()
    for rk, val in kvs:
        wb.put_cf("write", Key.from_raw(rk).append_ts(11).encoded,
                  Write(WriteType.PUT, 10, short_value=val).to_bytes())
    eng.write(wb)
    return cols, eng


@pytest.mark.parametrize("group_expr", ["pk", "mod"])
def test_streamed_agg_rides_device_byte_identical(group_expr):
    cols, eng = _engine()
    group = col(0) if group_expr == "pk" else call("mod", col(0), const_int(7))
    dag = lambda: DagRequest(executors=[
        TableScan(TABLE_ID, cols),
        Aggregation([group],
                    [AggDescriptor("count", None), AggDescriptor("sum", col(2)),
                     AggDescriptor("avg", col(1))],
                    streamed=True),
    ])
    ep_dev = Endpoint(LocalEngine(eng), enable_device=True)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    req = lambda: CoprRequest(103, dag(), [record_range(TABLE_ID)], 100, context={})
    r_dev = ep_dev.handle_request(req())
    r_cpu = ep_cpu.handle_request(req())
    if group_expr == "pk":
        # scan order sorts by the group key: device may merge, output equals
        # the stream executor's byte-for-byte
        assert jax_eval.supports(dag())
        assert r_dev.from_device, ep_dev.last_device_error
    else:
        # NOT sorted by group key: per-run stream semantics are not the
        # device hash output, so the gate must route this to the CPU
        assert not jax_eval.supports(dag())
        assert not r_dev.from_device
    assert r_dev.data == r_cpu.data
    assert len(r_dev.data) > 50
