"""Overload control plane (tikv_tpu/copr/overload.py; docs/robustness.md
"Overload control plane").

The acceptance contract (ISSUE 15):

* per-tenant token buckets gate admission at the scheduler and the service
  read entries; over-quota work defers a bounded wait then sheds as
  ``ServerBusyError`` whose ``retry_after_s`` is the bucket's ACTUAL refill
  deficit;
* client-declared ``priority`` is clamped to a configured ceiling (global
  and per-tenant) — never trusted — with demotions counted;
* the adaptive controller tightens/relaxes effective rates and the queue
  cap from queue depth, lane wait, and observatory p99-vs-floor evidence;
* the region column cache partitions its byte budget per tenant and
  degrades an over-budget tenant down the ladder (evict its coldest →
  demote its pins → CPU-fallback its device paths) without touching other
  tenants' warm sets;
* THE scenario: a hot tenant floods a 3-store socket cluster at >=10x its
  quota while a well-behaved tenant suffers ZERO failed reads and keeps a
  bounded p99 — and with overload OFF the same seed demonstrably starves
  it (both directions asserted).
"""

import itertools
import json
import threading
import time
import urllib.request

import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID
from fixtures import put_committed

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, TableScan
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.overload import (
    AdaptiveController,
    OverloadConfig,
    OverloadControl,
    QuotaLimiter,
    TenantQuota,
)
from tikv_tpu.copr.region_cache import RegionColumnCache
from tikv_tpu.copr.scheduler import SchedulerConfig, _clamped_lane
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.util import failpoint
from tikv_tpu.util.chaos import Nemesis
from tikv_tpu.util.metrics import REGISTRY
from tikv_tpu.util.retry import ServerBusyError

NON_HANDLE = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]
HOT_TABLES = (50, 51, 52)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.teardown()
    yield
    failpoint.teardown()


def _engine(tables=(TABLE_ID,), n=64):
    eng = BTreeEngine()
    for tid in tables:
        for i in range(n):
            put_committed(eng, record_key(tid, i),
                          encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]),
                          90, 100)
    return eng


def _agg_dag(tid=TABLE_ID):
    return DagRequest(executors=[
        TableScan(tid, PRODUCT_COLUMNS),
        Aggregation([], [AggDescriptor("count", None)]),
    ])


def _scan_dag(tid=TABLE_ID):
    return DagRequest(executors=[TableScan(tid, PRODUCT_COLUMNS)],
                      output_offsets=[0, 1, 2, 3])


def _req(tid=TABLE_ID, ts=200, tenant=None, priority=None, region=1,
         dag=None):
    ctx = {"region_id": region, "region_epoch": (1, 1), "apply_index": 7}
    if tenant is not None:
        ctx["tenant"] = tenant
    if priority is not None:
        ctx["priority"] = priority
    return CoprRequest(103, dag or _agg_dag(tid), [record_range(tid)], ts,
                       context=ctx)


def _control(clock, slept=None, region_cache=None, **cfg_kw):
    cfg_kw.setdefault("adaptive", False)
    cfg = OverloadConfig(**cfg_kw)
    return OverloadControl(cfg, region_cache=region_cache, clock=clock,
                           sleep=(slept.append if slept is not None
                                  else (lambda s: None)))


# ---------------------------------------------------------------------------
# token buckets + admission semantics
# ---------------------------------------------------------------------------

def test_bucket_burst_refill_and_runtime_retune():
    clk = [0.0]
    cfg = OverloadConfig(default_quota=TenantQuota(requests_per_s=4.0,
                                                   burst_s=2.0))
    lim = QuotaLimiter(cfg, clock=lambda: clk[0])
    # burst capacity = 4/s * 2s = 8 tokens, all admitted back to back
    for _ in range(8):
        assert lim.probe("t") == 0.0
    # empty: next request's deficit is exactly one token's refill time
    assert lim.probe("t") == pytest.approx(0.25)
    clk[0] += 0.5  # two tokens refill
    assert lim.probe("t") == 0.0
    assert lim.probe("t") == 0.0
    assert lim.probe("t") == pytest.approx(0.25)
    # runtime retune: rates apply on the NEXT probe, no bucket surgery
    lim.set_quota("t", TenantQuota(requests_per_s=100.0))
    clk[0] += 0.01  # 1 token at the new rate
    assert lim.probe("t") == 0.0


def test_admit_defers_within_wait_budget_then_serves():
    clk = [0.0]
    slept = []

    def sleeping(s):
        slept.append(s)
        clk[0] += s  # the defer wait IS the refill time

    cfg = OverloadConfig(default_quota=TenantQuota(requests_per_s=10.0,
                                                   burst_s=0.1),
                         max_wait_s=0.2, adaptive=False)
    ov = OverloadControl(cfg, clock=lambda: clk[0], sleep=sleeping)
    assert ov.admit({"tenant": "a"}) == "a"  # the burst token
    # bucket empty, deficit 0.1s <= max_wait 0.2s: deferred, then admitted
    assert ov.admit({"tenant": "a"}) == "a"
    assert slept == [pytest.approx(0.1)]
    snap = ov.snapshot()["tenants"]["a"]
    assert snap["admitted"] == 1 and snap["deferred"] == 1


def test_shed_retry_after_is_the_refill_deficit():
    clk = [0.0]
    ov = _control(lambda: clk[0],
                  default_quota=TenantQuota(requests_per_s=2.0, burst_s=0.5),
                  max_wait_s=0.02)
    assert ov.admit({"tenant": "a"}) == "a"
    with pytest.raises(ServerBusyError) as ei:
        ov.admit({"tenant": "a"})
    # one token at 2/s = 0.5s — proportional, not a constant
    assert ei.value.retry_after_s == pytest.approx(0.5)
    assert ov.snapshot()["tenants"]["a"]["shed"] == 1
    # a retried request with the SAME context dict is re-gated (the
    # idempotence marker stamps only on success)
    ctx = {"tenant": "a"}
    with pytest.raises(ServerBusyError):
        ov.admit(ctx)
    clk[0] += 1.0
    assert ov.admit(ctx) == "a"
    assert ctx.get("_overload_admitted") is True
    # and the marker makes a NESTED layer charge nothing further
    level = ov.limiter.snapshot()["a"]["request_tokens"]
    assert ov.admit(ctx) == "a"
    assert ov.limiter.snapshot()["a"]["request_tokens"] == level


def test_read_bytes_post_charge_gates_next_admission():
    clk = [0.0]
    ov = _control(lambda: clk[0],
                  default_quota=TenantQuota(requests_per_s=0.0,
                                            read_bytes_per_s=100.0,
                                            burst_s=1.0),
                  max_wait_s=0.01)
    ctx = {"tenant": "b"}
    assert ov.admit(dict(ctx)) == "b"
    ov.note_bytes(ctx, 600)  # 100-token capacity, 600 charged: 500 in debt
    with pytest.raises(ServerBusyError) as ei:
        ov.admit(dict(ctx))
    assert ei.value.retry_after_s == pytest.approx(5.0)  # 500 B / 100 B/s
    clk[0] += 5.0
    assert ov.admit(dict(ctx)) == "b"


def test_disabled_control_is_a_noop():
    ov = _control(time.monotonic, enabled=False,
                  default_quota=TenantQuota(requests_per_s=0.001))
    for _ in range(50):
        assert ov.admit({"tenant": "x"}) == "x"
    assert ov.snapshot()["enabled"] is False


# ---------------------------------------------------------------------------
# priority clamping (satellite: _lane_of must not trust the client)
# ---------------------------------------------------------------------------

def test_lane_clamped_to_global_and_tenant_ceilings():
    demote = REGISTRY.counter("tikv_overload_demote_total")
    req = _req(tenant="t1", priority="high")
    # overload DISABLED: the SchedulerConfig ceiling still clamps
    d0 = demote.get(tenant="t1", lane="normal")
    assert _clamped_lane(req, SchedulerConfig(max_priority="normal"),
                         None) == "normal"
    assert demote.get(tenant="t1", lane="normal") == d0 + 1
    # default config ("high") keeps historical behavior: no clamp
    assert _clamped_lane(req, SchedulerConfig(), None) == "high"
    # per-tenant ceiling beats the global one when LOWER priority
    ov = _control(time.monotonic, max_priority="normal",
                  tenants={"t1": TenantQuota(max_priority="low")})
    d1 = demote.get(tenant="t1", lane="low")
    assert _clamped_lane(req, SchedulerConfig(), ov) == "low"
    assert demote.get(tenant="t1", lane="low") == d1 + 1
    # asking for a LOWER lane than the ceiling is always allowed
    low_req = _req(tenant="t2", priority="low")
    assert _clamped_lane(low_req, SchedulerConfig(max_priority="normal"),
                         ov) == "low"


# ---------------------------------------------------------------------------
# adaptive controller
# ---------------------------------------------------------------------------

def test_controller_tightens_on_queue_pressure_and_relaxes(monkeypatch):
    from tikv_tpu.copr import observatory as obs

    monkeypatch.setattr(obs.OBSERVATORY, "enabled", False)
    clk = [0.0]
    cfg = OverloadConfig(window_s=1.0, min_scale=0.25)
    ctrl = AdaptiveController(cfg, clock=lambda: clk[0])
    for _ in range(4):
        ctrl.note_queue(90, 100)
    clk[0] += 1.1
    ctrl.note_queue(90, 100)  # window elapsed: tick on pressure
    assert ctrl.scale == pytest.approx(0.5)
    assert ctrl.actions["tighten"] == 1
    clk[0] += 1.1
    ctrl.note_queue(95, 100)
    assert ctrl.scale == pytest.approx(0.25)  # floored at min_scale
    assert ctrl.queue_cap(100) == 25 and ctrl.pressure
    # evidence clears: relax climbs back to 1.0
    for _ in range(6):
        clk[0] += 1.1
        ctrl.note_queue(0, 100)
    assert ctrl.scale == 1.0 and not ctrl.pressure
    assert ctrl.actions["relax"] >= 2
    assert ctrl.queue_cap(100) == 100


def test_controller_p99_vs_floor_evidence(monkeypatch):
    from tikv_tpu.copr import observatory as obs

    fresh = obs.Observatory(window_s=100.0, enabled=True)
    monkeypatch.setattr(obs, "OBSERVATORY", fresh)
    clk = [0.0]
    ctrl = AdaptiveController(OverloadConfig(window_s=1.0, p99_ratio=3.0),
                              clock=lambda: clk[0])
    for _ in range(16):
        fresh.record_serve("sigA", "unary", 0.0002, rows=10)
    clk[0] += 1.1
    ctrl.note_queue(0, 100)  # first tick LEARNS the floor
    assert not ctrl.pressure
    # tail latency explodes while the queue stays empty: the observatory
    # p99-vs-floor evidence alone must tighten
    for _ in range(200):
        fresh.record_serve("sigA", "unary", 0.1, rows=10)
    clk[0] += 1.1
    ctrl.note_queue(0, 100)
    assert ctrl.pressure and ctrl.actions["tighten"] >= 1
    assert ctrl.last_evidence["p99_pressure"] is True
    assert ctrl.last_evidence["p99_detail"]["sig"] == "sigA"


def test_adaptive_pressure_busy_rejects_below_static_cap(monkeypatch):
    """Evidence-based shedding replaces the static boolean: with
    busy_reject=False but the controller under pressure, queue-full
    admission sheds typed at the SCALED cap."""
    from tikv_tpu.copr import observatory as obs

    monkeypatch.setattr(obs.OBSERVATORY, "enabled", False)
    ep = Endpoint(LocalEngine(_engine()), enable_device=True)
    ov = _control(time.monotonic, adaptive=True)
    ep.overload = ov
    ov.controller.scale = 0.001  # forced pressure: effective cap = 1
    ep.scheduler.cfg = SchedulerConfig(max_queue=64, busy_reject=False)
    ep.scheduler.start()
    try:
        failpoint.cfg("sched_dispatch", "pause")  # wedge the dispatcher
        results = []

        def submit(ts):
            try:
                results.append(ep.scheduler.execute(_req(ts=ts), timeout=30))
            except ServerBusyError as e:
                results.append(e)

        # two submitters: the dispatcher pops (and parks on) the first;
        # the second OCCUPIES the scaled cap-1 queue
        threads = [threading.Thread(target=submit, args=(300 + i,))
                   for i in range(2)]
        for t in threads:
            t.start()
            time.sleep(0.3)
        with pytest.raises(ServerBusyError) as ei:
            ep.scheduler.execute(_req(ts=310))
        assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        failpoint.remove("sched_dispatch")
        for t in threads:
            t.join(timeout=10)
        assert len(results) == 2 and not any(
            isinstance(r, ServerBusyError) for r in results)
    finally:
        failpoint.teardown()
        ep.scheduler.stop()


# ---------------------------------------------------------------------------
# scheduler + endpoint integration
# ---------------------------------------------------------------------------

def test_scheduler_execute_sheds_over_quota_typed_and_counted():
    ep = Endpoint(LocalEngine(_engine()), enable_device=True)
    ep.overload = _control(
        time.monotonic, max_wait_s=0.0,
        tenants={"hot": TenantQuota(requests_per_s=0.5, burst_s=2.0)})
    shed = REGISTRY.counter("tikv_coprocessor_sched_shed_total")
    s0 = shed.get(reason="tenant_quota")
    # works with the scheduler STOPPED too: admission precedes the bypass
    assert ep.scheduler.execute(_req(tenant="hot")).data
    with pytest.raises(ServerBusyError) as ei:
        ep.scheduler.execute(_req(tenant="hot"))
    assert ei.value.retry_after_s == pytest.approx(2.0, rel=0.1)
    assert shed.get(reason="tenant_quota") == s0 + 1
    # an unlimited sibling is untouched
    assert ep.scheduler.execute(_req(tenant="victim")).data


def test_run_batch_over_quota_rider_fails_only_its_slot():
    ep = Endpoint(LocalEngine(_engine()), enable_device=True)
    ep.overload = _control(
        time.monotonic,
        tenants={"hot": TenantQuota(requests_per_s=0.5, burst_s=2.0)})
    want = ep.handle_request(_req(tenant="victim")).data
    reqs = [_req(tenant="victim"), _req(tenant="hot"),
            _req(tenant="hot"), _req(tenant="victim")]
    results, errors = ep.handle_batch_errors(reqs)
    assert errors[0] is None and results[0].data == want
    assert errors[3] is None and results[3].data == want
    assert errors[1] is None and results[1].data == want  # hot's one token
    assert isinstance(errors[2], ServerBusyError) and results[2] is None
    assert errors[2].retry_after_s > 0


def test_service_read_entries_gate_with_wire_busy_shape():
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    eng = _engine()
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    ep.overload = _control(
        time.monotonic, max_wait_s=0.0,
        tenants={"hot": TenantQuota(requests_per_s=0.5, burst_s=1.0)})
    svc = KvService(Storage(engine=LocalEngine(eng)), ep)
    assert svc.overload is ep.overload  # picked off the endpoint

    def copr_req(tenant):
        return {"dag": _agg_dag(), "ranges": [list(record_range(TABLE_ID))],
                "start_ts": 200,
                "context": {"region_id": 1, "region_epoch": (1, 1),
                            "apply_index": 7, "tenant": tenant}}

    assert "error" not in svc.coprocessor(copr_req("hot"))
    r = svc.coprocessor(copr_req("hot"))
    busy = r["error"]["server_is_busy"]
    assert busy["retry_after_ms"] >= 1  # non-zero hint on the wire
    # kv reads gate through the same buckets
    r = svc.kv_get({"key": b"k", "version": 10,
                    "context": {"tenant": "hot"}})
    assert "server_is_busy" in r["error"]
    # the victim tenant is untouched
    assert "error" not in svc.coprocessor(copr_req("victim"))


def test_service_charges_response_bytes_against_byte_quota():
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    eng = _engine()
    ep = Endpoint(LocalEngine(eng), enable_device=False)
    ep.overload = _control(
        time.monotonic, max_wait_s=0.0,
        tenants={"scanner": TenantQuota(read_bytes_per_s=50.0, burst_s=1.0)})
    svc = KvService(Storage(engine=LocalEngine(eng)), ep)

    def req():  # fresh context per wire request (like real decoded frames)
        return {"dag": _scan_dag(), "ranges": [list(record_range(TABLE_ID))],
                "start_ts": 200,
                "context": {"region_id": 1, "region_epoch": (1, 1),
                            "apply_index": 7, "tenant": "scanner"}}

    r = svc.coprocessor(req())
    assert "error" not in r and len(r["data"]) > 50
    # the 64-row scan blew the 50 B/s budget: the NEXT admission sheds
    # with a deficit proportional to the debt
    r2 = svc.coprocessor(req())
    assert r2["error"]["server_is_busy"]["retry_after_ms"] > 1000


def test_tenant_blocked_requests_never_join_device_batches():
    ep = Endpoint(LocalEngine(_engine()), enable_device=True)
    ep.overload = _control(time.monotonic, region_cache=ep.region_cache)
    assert ep.scheduler._batchable(_req(tenant="hot"))
    ep.region_cache._device_blocked["hot"] = time.monotonic() + 60
    assert not ep.scheduler._batchable(_req(tenant="hot"))
    assert ep.scheduler._batchable(_req(tenant="victim"))


# ---------------------------------------------------------------------------
# per-tenant HBM partitions + the memory-pressure ladder
# ---------------------------------------------------------------------------

def _warm(ep, tid, tenant, ts=200):
    return ep.handle_request(_req(tid, ts=ts, tenant=tenant))


def test_hbm_partition_evicts_only_the_over_budget_tenant():
    ep = Endpoint(LocalEngine(_engine(tables=(TABLE_ID,) + HOT_TABLES)),
                  enable_device=True)
    rc = ep.region_cache
    evict = REGISTRY.counter("tikv_overload_hbm_evict_total")
    hot_ev0 = evict.get(tenant="hot", step="evict")
    vic_ev0 = evict.get(tenant="victim", step="evict")
    _warm(ep, TABLE_ID, "victim")
    img_bytes = max(i.nbytes for i in rc._images.values())
    # hot may hold ~1.5 images; victim gets the remainder pool
    rc.set_tenant_budgets({"hot": int(img_bytes * 1.5)})
    for tid in HOT_TABLES:
        _warm(ep, tid, "hot")
    tenants = [i.tenant for i in rc._images.values()]
    assert tenants.count("victim") == 1, "victim's warm image must survive"
    assert 1 <= tenants.count("hot") <= 2
    assert evict.get(tenant="hot", step="evict") > hot_ev0
    assert evict.get(tenant="victim", step="evict") == vic_ev0
    occ = rc.tenant_occupancy()
    assert occ["hot"]["bytes"] <= occ["hot"]["budget"]
    # only the DEFAULT tenant owns the remainder pool; other unlisted
    # tenants ride the global budget alone
    assert occ["victim"]["budget"] is None
    assert rc.tenant_budget("default") == rc.byte_budget - int(img_bytes * 1.5)


def test_ladder_demotes_pins_then_blocks_device_with_cooldown():
    ep = Endpoint(LocalEngine(_engine(tables=(TABLE_ID, 50))),
                  enable_device=True)
    rc = ep.region_cache
    ep.overload = _control(time.monotonic, region_cache=rc)
    evict = REGISTRY.counter("tikv_overload_hbm_evict_total")
    block = REGISTRY.counter("tikv_overload_device_block_total")
    d0 = evict.get(tenant="hot", step="demote")
    c0 = evict.get(tenant="hot", step="cpu_block")
    b0 = block.get(tenant="hot")
    _warm(ep, TABLE_ID, "victim")
    _warm(ep, 50, "hot")  # image built, pins placed on first device serve
    hot_img = next(i for i in rc._images.values() if i.tenant == "hot")
    # a partition SMALLER than the single image: rung 1 has nothing to
    # evict (the image is the tenant's only one), rung 2 demotes its pins,
    # rung 3 blocks its device serving for the cooldown
    rc.set_tenant_budgets({"hot": max(hot_img.nbytes // 2, 1)})
    assert evict.get(tenant="hot", step="demote") == d0 + 1
    assert evict.get(tenant="hot", step="cpu_block") == c0 + 1
    assert block.get(tenant="hot") == b0 + 1
    assert hot_img.block_cache.device_nbytes() == 0, "pins demoted to host"
    assert not rc.device_allowed("hot")
    assert rc.device_allowed("victim")
    # endpoint serving honors the block: CPU fallback, counted per cause
    fb = REGISTRY.counter("tikv_coprocessor_path_fallback_total")
    f0 = fb.get(path="unary", cause="tenant_pressure")
    r = _warm(ep, 50, "hot", ts=210)
    assert not r.from_device
    assert fb.get(path="unary", cause="tenant_pressure") == f0 + 1
    assert _warm(ep, TABLE_ID, "victim", ts=210).from_device
    # the cooldown lifts the block by itself
    rc._clock = lambda: time.monotonic() + rc.device_block_cooldown_s + 1
    assert rc.device_allowed("hot")


def test_memory_squeeze_fault_and_heal_restores_budgets():
    ep = Endpoint(LocalEngine(_engine(tables=(TABLE_ID,) + HOT_TABLES)),
                  enable_device=True)
    rc = ep.region_cache
    for tid in (TABLE_ID,) + HOT_TABLES:
        _warm(ep, tid, "default")
    n_before = len(rc._images)
    assert n_before >= 4
    budget = rc.byte_budget
    total = rc.total_bytes()
    nem = Nemesis(None, seed=3)
    try:
        nem.memory_squeeze(rc, fraction=(total * 0.5) / budget)
        assert len(rc._images) < n_before, "squeeze must evict"
        assert rc.total_bytes() <= rc.byte_budget
        assert nem.stats["squeezed"] == 1
        nem.heal()
        assert rc.byte_budget == budget
    finally:
        nem.close()


# ---------------------------------------------------------------------------
# hot-tenant flood on an in-memory endpoint (the check.sh smoke scenario)
# ---------------------------------------------------------------------------

def test_hot_tenant_flood_in_memory_victim_serve_continuity():
    """Seeded load nemesis floods one tenant through the continuous
    scheduler lanes at many times its quota; the victim tenant's serves
    never fail and the hot tenant's overage is shed typed."""
    ep = Endpoint(LocalEngine(_engine(tables=(TABLE_ID, 50))),
                  enable_device=True)
    ep.overload = _control(
        time.monotonic, max_wait_s=0.002,
        tenants={"hot": TenantQuota(requests_per_s=20.0, burst_s=0.5,
                                    max_priority="low")})
    ep.scheduler.start()
    nem = Nemesis(None, seed=11)
    admission = REGISTRY.counter("tikv_overload_admission_total")
    shed0 = admission.get(tenant="hot", outcome="shed", where="sched")
    ts = itertools.count(300)

    def hot_submit(i, tenant):
        r = ep.scheduler.execute(_req(50, ts=next(ts), tenant=tenant,
                                      priority="high"))
        assert r.data

    try:
        want = ep.scheduler.execute(_req(ts=next(ts), tenant="victim")).data
        nem.hot_tenant(hot_submit, qps=400.0, threads=3)
        deadline = time.monotonic() + 3.0
        served = 0
        while time.monotonic() < deadline and served < 60:
            r = ep.scheduler.execute(_req(ts=next(ts), tenant="victim"))
            assert r.data == want, "victim bytes must stay correct"
            served += 1
        assert served >= 60, "victim serve continuity broken under flood"
        assert admission.get(tenant="hot", outcome="shed",
                             where="sched") > shed0, \
            "the hot tenant's overage must be shed"
        assert nem.stats["hot_tenant_requests"] + \
            nem.stats["hot_tenant_errors"] > 0
    finally:
        nem.heal()
        nem.close()
        ep.scheduler.stop()


def test_wire_client_cannot_spoof_the_admission_marker():
    """Review regression: `_overload_admitted` is an in-process nesting
    contract, NOT a client claim — a wire request arriving with it
    pre-stamped is stripped at the service boundary and still gated."""
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    eng = _engine()
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    ep.overload = _control(
        time.monotonic, max_wait_s=0.0,
        tenants={"hot": TenantQuota(requests_per_s=0.5, burst_s=1.0)})
    svc = KvService(Storage(engine=LocalEngine(eng)), ep)

    def spoofed():
        return {"dag": _agg_dag(), "ranges": [list(record_range(TABLE_ID))],
                "start_ts": 200,
                "context": {"region_id": 1, "region_epoch": (1, 1),
                            "apply_index": 7, "tenant": "hot",
                            "_overload_admitted": True}}

    assert "error" not in svc.coprocessor(spoofed())  # the one burst token
    r = svc.coprocessor(spoofed())
    assert "server_is_busy" in r["error"], \
        "a self-stamped marker must not bypass quota admission"
    # kv entries strip it too
    r = svc.kv_get({"key": b"k", "version": 10,
                    "context": {"tenant": "hot", "_overload_admitted": True}})
    assert "server_is_busy" in r["error"]
    # batch subs strip it per slot
    r = svc.coprocessor_batch({"requests": [spoofed(), spoofed()]})
    assert all("server_is_busy" in s["error"] for s in r["responses"])


def test_contextless_request_charges_exactly_one_token():
    """Review regression: a request WITHOUT a context dict must charge one
    token total — the service materializes a context so its admission
    stamp reaches the scheduler's nested gate."""
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    eng = _engine()
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    ep.overload = _control(
        lambda: 0.0,  # frozen clock: no refill masks a double charge
        default_quota=TenantQuota(requests_per_s=100.0, burst_s=1.0))
    ep.scheduler.start()
    svc = KvService(Storage(engine=LocalEngine(eng)), ep)
    try:
        r = svc.coprocessor({"dag": _agg_dag(),
                             "ranges": [list(record_range(TABLE_ID))],
                             "start_ts": 200})
        assert "error" not in r
        snap = ep.overload.limiter.snapshot()["default"]
        assert snap["admitted"] == 1
        assert snap["request_tokens"] == pytest.approx(99.0, abs=0.5), \
            "a context-less request must not be double-charged"
    finally:
        ep.scheduler.stop()


def test_stacked_memory_squeezes_heal_to_the_original_budget():
    """Review regression: two squeezes of one cache snapshot in order;
    heal must restore the TRUE original budget, not the half-squeezed
    intermediate."""
    rc = RegionColumnCache(byte_budget=1 << 20)
    nem = Nemesis(None, seed=9)
    try:
        nem.memory_squeeze(rc, fraction=0.5)
        nem.memory_squeeze(rc, fraction=0.5)
        assert rc.byte_budget == (1 << 20) // 4
        nem.heal()
        assert rc.byte_budget == 1 << 20
    finally:
        nem.close()


# ---------------------------------------------------------------------------
# ops surfaces: RPC + HTTP + ctl + online config
# ---------------------------------------------------------------------------

def test_debug_overload_rpc_http_and_ctl_surfaces(capsys):
    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService
    from tikv_tpu.server.status_server import StatusServer
    from tikv_tpu.storage.storage import Storage

    eng = _engine()
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    ep.overload = _control(
        time.monotonic, region_cache=ep.region_cache,
        tenants={"hot": TenantQuota(requests_per_s=5.0)})
    ep.overload.admit({"tenant": "hot"})
    svc = KvService(Storage(engine=LocalEngine(eng)), ep)
    out = svc.debug_overload({})
    assert out["enabled"] and "hot" in out["tenants"]
    assert out["tenants"]["hot"]["admitted"] == 1
    assert "hbm" in out and "controller" in out

    srv = Server(svc)
    srv.start()
    status = StatusServer(overload=lambda: svc.debug_overload({}))
    status.start()
    try:
        c = Client(*srv.addr)
        r = c.call("debug_overload", {})
        assert r["enabled"] and r["tenants"]["hot"]["requests_per_s"] == 5.0
        c.close()
        url = f"http://{status.addr[0]}:{status.addr[1]}/debug/overload"
        body = json.loads(urllib.request.urlopen(url).read())
        assert body["enabled"] and "hot" in body["tenants"]
        import ctl as ctl_mod

        rc = ctl_mod.main(["--addr", f"{srv.addr[0]}:{srv.addr[1]}",
                           "overload"])
        assert rc == 0
        assert '"enabled": true' in capsys.readouterr().out
    finally:
        status.stop()
        srv.stop()


def test_config_controller_reconfigures_overload_online():
    from tikv_tpu.util.config import ConfigController, TikvConfig

    ov = _control(time.monotonic, enabled=False)
    ctl = ConfigController(TikvConfig())
    ctl.register("overload", ov.reconfigure)
    diff = ctl.update({"overload.enabled": True,
                       "overload.requests_per_s": 7.0,
                       "overload.max_priority": "normal"})
    assert diff["overload"]["enabled"] is True
    assert ov.cfg.enabled is True
    assert ov.cfg.default_quota.requests_per_s == 7.0
    assert ov.cfg.max_priority == "normal"
    with pytest.raises(ValueError):
        ctl.update({"overload.max_priority": "urgent"})
    with pytest.raises(ValueError):
        ctl.update({"overload.min_scale": 0.0})
    assert ov.cfg.max_priority == "normal"  # bad updates change nothing


# ---------------------------------------------------------------------------
# THE acceptance scenario: 3-store socket cluster, both directions
# ---------------------------------------------------------------------------

def _seed_table(kv, region_id, tid, n=32):
    from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    wb = WriteBatch()
    for i in range(n):
        k = Key.from_raw(record_key(tid, i))
        w = Write(WriteType.PUT, 90,
                  short_value=encode_row(NON_HANDLE,
                                         [b"pear", i % 7, 100 + i]))
        wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
    kv.write({"region_id": region_id}, wb)


def test_hot_tenant_socket_cluster_fairness_both_directions():
    """ISSUE 15 acceptance: on a 3-store socket cluster, a hot tenant
    floods at >=10x its quota mid-traffic.  Overload OFF: the well-behaved
    tenant demonstrably starves (typed busy failures).  Overload ON (the
    same seed): ZERO victim failures, victim p99 bounded by its unloaded
    baseline, the hot tenant's declared priority clamped, and its HBM
    partition pressure never evicts the victim's warm image."""
    from tikv_tpu.copr.dag_wire import dag_to_wire
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.server.cluster import FIRST_REGION_ID, ServerCluster
    from tikv_tpu.server.server import Client

    ov_cfg = OverloadConfig(
        enabled=False,  # direction 1 runs with overload OFF
        tenants={"hot": TenantQuota(requests_per_s=8.0, burst_s=0.5,
                                    max_priority="low")},
        max_priority="normal",
        max_wait_s=0.005,
        adaptive=False,  # static quotas: the deterministic half
    )
    sched_cfg = SchedulerConfig(max_queue=4, busy_reject=True, max_batch=4,
                                max_wait_s=0.002, high_max_wait_s=0.001,
                                low_max_wait_s=0.004)
    c = ServerCluster(
        3, pd=MockPd(), full_service=True,
        copr_kwargs={"enable_device": True, "sched_config": sched_cfg},
        overload_config=ov_cfg, sched_continuous=True)
    c.run()
    nem = Nemesis(c, seed=1515)
    clients: list = []
    cl_mu = threading.Lock()
    tls = threading.local()
    ts_counter = itertools.count(1000)

    def client_for_thread(addr):
        cl = getattr(tls, "cl", None)
        if cl is None:
            cl = tls.cl = Client(*addr)
            with cl_mu:
                clients.append(cl)
        return cl

    def wire_req(tid, tenant, priority):
        return {"dag": dag_to_wire(_agg_dag(tid)),
                "ranges": [list(record_range(tid))],
                "start_ts": next(ts_counter),
                "context": {"region_id": FIRST_REGION_ID, "tenant": tenant,
                            "priority": priority}}

    try:
        leader = c.wait_leader(FIRST_REGION_ID)
        sid = leader.store.store_id
        node = c.nodes[sid]
        kv = node.raftkv
        for tid in (TABLE_ID,) + HOT_TABLES:
            _seed_table(kv, FIRST_REGION_ID, tid)
        addr = c.addrs[sid]
        vclient = Client(*addr)
        clients.append(vclient)

        def victim_call():
            return vclient.call(
                "coprocessor", wire_req(TABLE_ID, "victim", "normal"),
                timeout=30.0)

        # warmup: compile every plan shape, build every table's image
        expected = victim_call()
        assert "error" not in expected, expected
        expected = expected["data"]
        for tid in HOT_TABLES:
            r = vclient.call("coprocessor", wire_req(tid, "hot", "normal"),
                             timeout=60.0)
            assert "error" not in r, r
        rc = node.service.copr.region_cache
        hot_img = max(i.nbytes for i in rc._images.values()
                      if i.tenant == "hot")
        evict = REGISTRY.counter("tikv_overload_hbm_evict_total")
        hot_ev0 = evict.get(tenant="hot", step="evict")
        vic_ev0 = evict.get(tenant="victim", step="evict")
        rc.set_tenant_budgets({"hot": int(hot_img * 1.5)})

        # pace the dispatcher so the bounded queue is the contended
        # resource (deterministic saturation, not wall-clock racing): with
        # ~60ms rounds of <=4 items the drain rate (~66/s) sits far below
        # the flood's submission rate and far above the victim's
        failpoint.cfg("sched_dispatch", "sleep(60)")

        # unloaded baseline: victim latency with the pacer, no flood
        base = []
        for _ in range(15):
            t0 = time.perf_counter()
            r = victim_call()
            base.append(time.perf_counter() - t0)
            assert "error" not in r and r["data"] == expected
        baseline_p99 = sorted(base)[-1]

        def hot_submit(i, tenant):
            cl = client_for_thread(addr)
            r = cl.call("coprocessor",
                        wire_req(HOT_TABLES[i % len(HOT_TABLES)], tenant,
                                 "high"),
                        timeout=30.0)
            if isinstance(r, dict) and r.get("error"):
                raise RuntimeError(str(r["error"]))

        # ---- direction 1: overload OFF — the flood starves the victim ----
        nem.hot_tenant(hot_submit, qps=800.0, threads=24)
        time.sleep(0.8)  # let the queue saturate
        off_failures = 0
        off_lat = []
        for _ in range(25):
            t0 = time.perf_counter()
            r = victim_call()
            off_lat.append(time.perf_counter() - t0)
            if isinstance(r, dict) and r.get("error"):
                off_failures += 1
            else:
                assert r["data"] == expected
        nem.heal()
        p99_off = sorted(off_lat)[-1]
        # starvation is typed busy failures (the queue the flood owns) or
        # a blown tail — either way the victim demonstrably suffers
        assert off_failures > 0 or p99_off > 3 * baseline_p99 + 0.05, (
            f"flood must starve the victim with overload OFF: failures="
            f"{off_failures} p99_off={p99_off:.3f}s baseline="
            f"{baseline_p99:.3f}s nem={nem.stats}")

        # ---- direction 2: overload ON, same seeded flood ----
        ov_cfg.enabled = True  # runtime flip, shared across the cluster
        admission = REGISTRY.counter("tikv_overload_admission_total")
        demote = REGISTRY.counter("tikv_overload_demote_total")
        shed0 = sum(admission.get(tenant="hot", outcome="shed", where=w)
                    for w in ("copr", "sched", "batch", "kv", "stream"))
        dem0 = demote.get(tenant="hot", lane="low")
        nem.hot_tenant(hot_submit, qps=800.0, threads=24)
        time.sleep(0.8)
        on_failures = 0
        on_lat = []
        for _ in range(25):
            t0 = time.perf_counter()
            r = victim_call()
            on_lat.append(time.perf_counter() - t0)
            if isinstance(r, dict) and r.get("error"):
                on_failures += 1
            else:
                assert r["data"] == expected
        nem.heal()
        assert on_failures == 0, \
            "with overload control ON the victim must suffer ZERO failures"
        p99_on = sorted(on_lat)[-1]
        assert p99_on <= 3 * baseline_p99 + 0.05, \
            f"victim p99 {p99_on:.3f}s vs baseline {baseline_p99:.3f}s"
        shed1 = sum(admission.get(tenant="hot", outcome="shed", where=w)
                    for w in ("copr", "sched", "batch", "kv", "stream"))
        assert shed1 > shed0, "the hot tenant's overage must be shed"
        assert demote.get(tenant="hot", lane="low") > dem0, \
            "hot's self-declared high priority must be clamped"
        # HBM partition isolation: hot's pressure evicted only hot images
        assert evict.get(tenant="hot", step="evict") > hot_ev0
        assert evict.get(tenant="victim", step="evict") == vic_ev0
        assert any(i.tenant == "victim" for i in rc._images.values()), \
            "the victim's warm image must survive the hot tenant's churn"
    finally:
        failpoint.teardown()
        nem.heal()
        nem.close()
        for cl in clients:
            try:
                cl.close()
            except OSError:
                pass
        c.shutdown()
