"""Worker framework, unified read pool, and profiling surface.

Mirrors tikv_util/src/worker tests (schedule-before-start, stop drains) and
the yatp multilevel behavior the unified read pool exists for (heavy groups
demote, light traffic keeps low latency).
"""

import threading
import time
import urllib.request

from tikv_tpu.server.status_server import StatusServer
from tikv_tpu.util.worker import (
    Runnable,
    TaskPriority,
    UnifiedReadPool,
    Worker,
)


class _Collect(Runnable):
    def __init__(self):
        self.seen = []
        self.ticks = 0
        self.shut = False

    def run(self, task):
        self.seen.append(task)

    def on_timeout(self):
        self.ticks += 1

    def shutdown(self):
        self.shut = True


def test_worker_schedules_and_drains_on_stop():
    r = _Collect()
    w = Worker("test-worker")
    assert w.schedule("before-start")  # buffered
    w.start(r)
    for i in range(10):
        w.schedule(i)
    w.stop()
    assert r.seen[0] == "before-start"
    assert r.seen[1:] == list(range(10))
    assert r.shut
    assert w.handled == 11


def test_worker_rejects_after_stop():
    w = Worker("t2")
    w.start(_Collect())
    w.stop()
    assert not w.schedule("late")


def test_worker_timer_ticks():
    r = _Collect()
    w = Worker("t3", timer_interval=0.05)
    w.start(r)
    time.sleep(0.3)
    w.stop()
    assert r.ticks >= 2


def test_worker_survives_task_exception():
    class Boom(Runnable):
        def __init__(self):
            self.ok = 0

        def run(self, task):
            if task == "boom":
                raise RuntimeError("x")
            self.ok += 1

    r = Boom()
    w = Worker("t4")
    w.start(r)
    w.schedule("boom")
    w.schedule("fine")
    w.stop()
    assert r.ok == 1


# ---------------------------------------------------------------- read pool

def test_read_pool_basic_result_and_error():
    pool = UnifiedReadPool(workers=2)
    try:
        assert pool.submit(lambda a, b: a + b, 2, 3).result(5) == 5
        fut = pool.submit(lambda: 1 / 0)
        try:
            fut.result(5)
            raise AssertionError("expected ZeroDivisionError")
        except ZeroDivisionError:
            pass
    finally:
        pool.stop()


def test_read_pool_demotes_heavy_groups():
    pool = UnifiedReadPool(workers=1)
    try:
        # burn >100ms of accounted time in one group
        for _ in range(3):
            pool.submit(time.sleep, 0.06, group="heavy").result(5)
        assert pool.level_of("heavy") == 2
        assert pool.level_of("light") == 0
        # a new task from the heavy group enqueues at L2, light at L0
        ev = threading.Event()
        pool.submit(ev.wait, 0.2, group="heavy")
        depths_before = pool.queue_depths()
        ev.set()
        assert depths_before[0] == 0
    finally:
        pool.stop()


def test_read_pool_high_priority_pins_l0():
    pool = UnifiedReadPool(workers=1)
    try:
        for _ in range(3):
            pool.submit(time.sleep, 0.06, group="vip").result(5)
        assert pool.level_of("vip") == 2
        # HIGH priority ignores the group's level
        block = threading.Event()
        release = threading.Event()

        def gate():
            block.set()
            release.wait(5)

        pool.submit(gate)  # occupy the single worker
        block.wait(5)
        pool.submit(lambda: "hi", group="vip", priority=TaskPriority.HIGH)
        assert pool.queue_depths()[0] == 1  # sits in L0, not L2
        release.set()
    finally:
        pool.stop()


def test_read_pool_starvation_freedom():
    pool = UnifiedReadPool(workers=1)
    try:
        for _ in range(3):
            pool.submit(time.sleep, 0.06, group="bg").result(5)
        # L2 work still completes while L0 is busy
        results = [pool.submit(lambda i=i: i, group="bg") for i in range(5)]
        for _ in range(20):
            pool.submit(lambda: None).result(5)
        assert [f.result(5) for f in results] == list(range(5))
    finally:
        pool.stop()


# ----------------------------------------------------------------- profiler

def test_pprof_endpoints():
    srv = StatusServer()
    srv.start()
    host, port = srv.addr
    # a busy sibling thread the sampler must capture
    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(1000))

    t = threading.Thread(target=spin, name="spinner", daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
            f"http://{host}:{port}/debug/pprof/profile?seconds=0.3"
        ) as r:
            body = r.read()
        assert body.startswith(b"cpu profile:")
        # cross-thread work appears (the whole point of the sampler)
        assert b"spin" in body

        with urllib.request.urlopen(f"http://{host}:{port}/debug/pprof/heap?top=5") as r:
            heap = r.read()
        assert heap.startswith(b"heap profile:")
    finally:
        stop.set()
        t.join()
        srv.stop()


def test_pprof_raw_is_collapsed_stacks():
    from tikv_tpu.server.profiler import Profiler

    stop = threading.Event()

    def spin():
        while not stop.is_set():
            sum(range(1000))

    t = threading.Thread(target=spin, daemon=True)
    t.start()
    try:
        raw = Profiler().cpu_profile(seconds=0.2, raw=True).decode()
    finally:
        stop.set()
        t.join()
    lines = [ln for ln in raw.splitlines() if ln]
    assert lines, "no samples collected"
    for ln in lines:
        stack, _, count = ln.rpartition(" ")
        assert stack and count.isdigit()
        assert ";" in stack or ":" in stack  # frame;frame format


def test_heap_profile_concurrent_requests():
    from tikv_tpu.server.profiler import Profiler

    p = Profiler()
    results = []
    errors = []

    def grab():
        try:
            results.append(p.heap_profile(top=5))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=grab) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(results) == 4


def test_worker_ticks_under_continuous_load():
    """The periodic tick must fire even when the queue never drains."""
    r = _Collect()
    w = Worker("busy", timer_interval=0.05)
    w.start(r)
    stop = threading.Event()

    def feed():
        while not stop.is_set():
            w.schedule("x")
            time.sleep(0.002)

    t = threading.Thread(target=feed, daemon=True)
    t.start()
    time.sleep(0.4)
    stop.set()
    t.join()
    w.stop()
    assert r.ticks >= 3


def test_group_eviction_keeps_active_groups():
    pool = UnifiedReadPool(workers=1)
    try:
        for _ in range(3):
            pool.submit(time.sleep, 0.06, group="hot").result(5)
        assert pool.level_of("hot") == 2
        # flood with one-shot groups to cross the 4096 bound
        for i in range(4200):
            pool.submit(lambda: None, group=f"g{i}").result(5)
        # the recently-active heavy group survived eviction
        assert pool.level_of("hot") == 2
    finally:
        pool.stop()


def test_malformed_context_does_not_kill_connection():
    from tikv_tpu.server.server import Client, Server

    class Svc:
        def dispatch(self, method, request):
            return {"m": method}

    srv = Server(Svc())
    srv.start()
    try:
        cli = Client(*srv.addr)
        # truthy non-dict context on a read method
        assert cli.call("kv_get", {"context": [1], "key": b"k"})["m"] == "kv_get"
        # connection still alive afterwards
        assert cli.call("kv_get", {"key": b"k"})["m"] == "kv_get"
        cli.close()
    finally:
        srv.stop()


def test_pprof_bad_params_return_400():
    srv = StatusServer()
    srv.start()
    host, port = srv.addr
    try:
        import urllib.error

        for path in ("/debug/pprof/profile?seconds=abc", "/debug/pprof/heap?top=x"):
            try:
                urllib.request.urlopen(f"http://{host}:{port}{path}")
                raise AssertionError("expected HTTP 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
    finally:
        srv.stop()


def test_read_pool_lazy_creation():
    from tikv_tpu.server.server import Server

    class Svc:
        def dispatch(self, method, request):
            return {}

    srv = Server(Svc())
    assert srv._read_pool is None  # no read dispatched yet, no threads
    srv.stop()
