"""Joint-consensus membership changes (raft thesis 4.3; raft-rs ConfChangeV2;
reference: tests/integrations/raftstore/test_joint_consensus.rs).

Core rule under test: while in the joint config C_old,new every decision —
commit, election, lease, read quorum — needs a majority of BOTH configs."""

import random

import pytest

from tikv_tpu.raft.core import Message, MsgType, RaftNode, Role, Snapshot
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

from test_raft_core import Net


class JNet(Net):
    """Net over an explicit (node_ids, initial_voters) membership."""

    def __init__(self, ids, voters, seed=0):
        self.nodes = {
            i: RaftNode(i, list(voters), rng=random.Random(seed * 100 + i)) for i in ids
        }
        self.cut = set()
        self.applied = {i: [] for i in self.nodes}
        self.persisted = {i: [] for i in self.nodes}
        self.reads = {i: [] for i in self.nodes}


def _enter_joint(net, leader, changes):
    idx = leader.propose_conf_change(("enter_joint", tuple(changes)))
    assert idx is not None
    net.drain()
    return idx


def test_joint_commit_needs_both_majorities():
    """C_old={1,2,3} -> C_new={1,4,5}: with the old majority unreachable,
    entries must NOT commit even though the new config has a majority."""
    net = JNet([1, 2, 3, 4, 5], [1, 2, 3])
    leader = net.elect(1)
    _enter_joint(net, leader, [("add", 4), ("add", 5), ("remove", 2), ("remove", 3)])
    assert leader.outgoing == {1, 2, 3} and leader.voters == {1, 4, 5}
    for p in (2, 3):
        net.partition(1, p)
        net.partition(4, p)
        net.partition(5, p)
    commit_before = leader.commit
    leader.propose(b"joint-data")
    net.drain()
    assert leader.commit == commit_before  # new-majority acks alone are not enough
    net.heal()
    net.tick_all(3)  # heartbeat round retransmits to the lagging old majority
    assert leader.commit > commit_before
    assert b"joint-data" in net.applied[4] and b"joint-data" in net.applied[2]
    # leave: C_new alone rules; old-only peers drop out of the config
    leader.propose_conf_change(("leave_joint", ()))
    net.drain()
    assert leader.outgoing is None
    for p in (2, 3):
        net.partition(1, p)
        net.partition(4, p)
        net.partition(5, p)
    leader.propose(b"after-leave")
    net.drain()
    assert b"after-leave" in net.applied[5]


def test_joint_election_needs_both_majorities():
    """A candidate in the joint config cannot win with one config's votes."""
    net = JNet([1, 2, 3, 4, 5], [1, 2, 3])
    leader = net.elect(1)
    _enter_joint(net, leader, [("add", 4), ("add", 5), ("remove", 2), ("remove", 3)])
    net.drain()
    # depose and cut node 1 off from the NEW peers only
    for p in (4, 5):
        net.partition(1, p)
    net.nodes[1].campaign()
    net.drain()
    assert net.nodes[1].role != Role.LEADER  # old majority {1,2,3} granted, new did not
    net.heal()
    net.nodes[1].campaign()
    net.drain()
    assert net.nodes[1].role == Role.LEADER


def test_joint_proposal_ordering_guards():
    net = JNet([1, 2, 3], [1, 2, 3])
    leader = net.elect(1)
    assert leader.propose_conf_change(("leave_joint", ())) is None  # not joint
    _enter_joint(net, leader, [("remove", 3)])
    assert leader.propose_conf_change(("enter_joint", (("add", 4),))) is None  # already joint
    assert leader.propose_conf_change(("leave_joint", ())) is not None


def test_snapshot_carries_joint_config():
    net = JNet([1, 2, 3], [1, 2, 3])
    leader = net.elect(1)
    _enter_joint(net, leader, [("remove", 3), ("add", 4)])
    snap = Snapshot(
        index=leader.applied, term=leader.term, data=b"",
        voters=tuple(leader.voters), learners=(), outgoing=tuple(leader.outgoing),
    )
    fresh = RaftNode(4, [])
    fresh.step(Message(MsgType.SNAPSHOT, 1, 4, leader.term, snapshot=snap))
    assert fresh.voters == {1, 2, 4}
    assert fresh.outgoing == {1, 2, 3}


# ---------------------------------------------------------------------------
# cluster level (store + region metadata + auto-leave)


@pytest.fixture
def cluster():
    c = Cluster(5)
    c.bootstrap_subset([1, 2, 3])
    c.elect_leader(FIRST_REGION_ID, 1)
    return c


def test_replace_peer_atomically(cluster):
    """add+remove in ONE change: no intermediate 2-voter or 4-voter config
    window (the availability hole single-step changes have)."""
    cluster.must_put(b"jk", b"jv")
    leader = cluster.leader_peer(FIRST_REGION_ID)
    victim = next(p.peer_id for p in leader.region.peers if p.store_id == 3)
    conf_ver_before = leader.region.epoch.conf_ver
    (new_pid,) = cluster.joint_conf_change(
        FIRST_REGION_ID, [("add", 4), ("remove", victim)]
    )
    leader = cluster.leader_peer(FIRST_REGION_ID)
    assert leader.node.outgoing is None
    assert {p.store_id for p in leader.region.peers} == {1, 2, 4}
    assert new_pid in leader.node.voters and victim not in leader.node.voters
    # enter + leave each bump conf_ver
    assert leader.region.epoch.conf_ver >= conf_ver_before + 2
    cluster.tick(5)
    assert cluster.get_on_store(4, b"jk") == b"jv"  # snapshot-seeded
    assert FIRST_REGION_ID not in cluster.stores[3].peers  # destroyed
    cluster.must_put(b"jk2", b"jv2")
    cluster.tick(3)
    assert cluster.get_on_store(4, b"jk2") == b"jv2"


def test_joint_demote_with_replacement(cluster):
    """Demote a voter to learner while adding its replacement — the
    reference's safe way to shrink without a no-quorum window."""
    leader = cluster.leader_peer(FIRST_REGION_ID)
    demoted = next(p.peer_id for p in leader.region.peers if p.store_id == 2)
    (new_pid,) = cluster.joint_conf_change(
        FIRST_REGION_ID, [("add", 5), ("demote", demoted)]
    )
    leader = cluster.leader_peer(FIRST_REGION_ID)
    assert demoted in leader.node.learners and demoted not in leader.node.voters
    assert new_pid in leader.node.voters
    role = next(p.role for p in leader.region.peers if p.peer_id == demoted)
    assert role == "learner"
    cluster.must_put(b"dk", b"dv")
    cluster.tick(3)
    assert cluster.get_on_store(2, b"dk") == b"dv"  # learners still replicate


def test_joint_config_survives_crash_recovery(cluster):
    """A store restarted mid-joint must come back with the joint config —
    region roles alone cannot reconstruct C_old ∩ C_new."""
    from tikv_tpu.raft.store import Store
    from tikv_tpu.storage.engine import CF_RAFT
    from tikv_tpu.util import keys

    cluster.must_put(b"ck", b"cv")
    old_store = cluster.stores[2]
    peer = old_store.peers[FIRST_REGION_ID]
    # freeze the peer mid-joint and persist, as if it crashed between
    # enter_joint and leave_joint
    peer.node.outgoing = set(peer.node.voters)
    peer.node.voters = (peer.node.voters - {peer.peer_id}) | {999}
    peer.node.learners = {peer.peer_id}
    old_store.engine.put_cf(
        CF_RAFT, keys.raft_state_key(FIRST_REGION_ID), peer._encode_raft_state()
    )
    new_store = Store(2, cluster.transport, engine=old_store.engine)
    assert new_store.recover() == 1
    node = new_store.peers[FIRST_REGION_ID].node
    assert node.outgoing == peer.node.outgoing
    assert node.voters == peer.node.voters
    assert node.learners == {peer.peer_id}


def test_new_leader_reproposes_leave_joint():
    """If the leader dies between enter_joint applying and leave_joint
    committing, the next leader must finish the transition on its own."""
    net = JNet([1, 2, 3, 4], [1, 2, 3])
    leader = net.elect(1)
    _enter_joint(net, leader, [("add", 4), ("remove", 3)])
    net.tick_all(3)  # heartbeat rounds bring the new peer up to date
    assert all(net.nodes[i].outgoing == {1, 2, 3} for i in (1, 2, 3, 4))
    # old leader crashes before proposing leave (core has no auto-leave —
    # that's the store's job — so the joint config is still active here)
    for p in (2, 3, 4):
        net.partition(1, p)
    net.nodes[2].campaign()
    net.drain()
    assert net.nodes[2].role == Role.LEADER
    net.tick_all(3)
    assert net.nodes[2].outgoing is None  # re-proposed leave committed
    assert net.nodes[4].outgoing is None
    assert net.nodes[2].voters == {1, 2, 4}


def test_no_overlapping_conf_changes():
    """has_pending_conf: a second membership change is rejected until the
    first one's entry is applied; simple ops are rejected mid-joint."""
    net = JNet([1, 2, 3], [1, 2, 3])
    leader = net.elect(1)
    idx = leader.propose_conf_change(("enter_joint", (("remove", 3),)))
    assert idx is not None
    # not yet applied: everything else bounces
    assert leader.propose_conf_change(("add", 9)) is None
    assert leader.propose_conf_change(("enter_joint", (("add", 9),))) is None
    net.drain()  # enter_joint applies; joint active
    assert leader.propose_conf_change(("add", 9)) is None  # simple op mid-joint
    assert leader.propose_conf_change(("leave_joint", ())) is not None
    net.drain()
    assert leader.outgoing is None
    assert leader.propose_conf_change(("add", 9)) is not None  # back to normal


def test_conf_state_persisted_at_apply_time(cluster):
    """Recovery right after a conf change applies must see the POST-change
    membership — the raft-state blob written earlier in the same ready
    carries the pre-change config and must have been rewritten."""
    from tikv_tpu.raft.store import Store

    new_pid = cluster.add_peer(FIRST_REGION_ID, 4)
    cluster.tick(3)
    for sid in (1, 2):
        old_store = cluster.stores[sid]
        new_store = Store(sid, cluster.transport, engine=old_store.engine)
        assert new_store.recover() == 1
        node = new_store.peers[FIRST_REGION_ID].node
        assert new_pid in node.voters, f"store {sid} recovered stale ConfState"
        assert node.outgoing is None


def test_bogus_joint_op_rejected(cluster):
    with pytest.raises(ValueError, match="frobnicate"):
        cluster.joint_conf_change(FIRST_REGION_ID, [("frobnicate", 2)])


def test_leader_crash_mid_joint_completes_at_cluster_level(cluster):
    """Peer placement rides in the conf entry, so a NEW leader (which never
    saw the proposal) can still reach the added peer and finish the joint
    transition after the old leader dies."""
    cluster.must_put(b"a", b"1")
    lead = cluster.leader_peer(FIRST_REGION_ID)
    victim = next(p.peer_id for p in lead.region.peers if p.store_id == 3)
    wire = (("add", cluster.alloc_id(), 4), ("remove", victim, 0))
    cmd = {
        "epoch": (lead.region.epoch.conf_ver, lead.region.epoch.version),
        "ops": [],
        "admin": ("conf_change_v2", wire),
    }
    lead.propose_cmd(cmd, lambda r: None)
    cluster.process()
    cluster.stop_node(1)  # dies before driving leave_joint
    cluster.tick(30)
    nl = cluster.leader_peer(FIRST_REGION_ID)
    assert nl is not None, "no leader elected after crash mid-joint"
    assert nl.node.outgoing is None, "stuck in joint config"
    cluster.must_put(b"b", b"2")
    cluster.tick(3)
    assert cluster.get_on_store(4, b"b") == b"2"


def test_no_conf_replay_after_recovery(cluster):
    """ConfState + apply index persist in one batch at conf-change apply, so
    recovery can never replay the entry against post-change membership (which
    would double-bump conf_ver and corrupt outgoing to C_new)."""
    from tikv_tpu.raft.store import Store

    victim = next(
        p.peer_id
        for p in cluster.leader_peer(FIRST_REGION_ID).region.peers
        if p.store_id == 3
    )
    cluster.joint_conf_change(FIRST_REGION_ID, [("add", 4), ("remove", victim)])
    for sid in (1, 2, 4):
        pre = cluster.stores[sid].peers[FIRST_REGION_ID]
        ns = Store(sid, cluster.transport, engine=cluster.stores[sid].engine)
        assert ns.recover() == 1
        p = ns.peers[FIRST_REGION_ID]
        assert p.node.outgoing is None
        assert p.node.voters == pre.node.voters
        assert p.region.epoch.conf_ver == pre.region.epoch.conf_ver, "conf entry replayed"
        assert p.node.applied >= 1
