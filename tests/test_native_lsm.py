"""LSM structure of the native engine: sorted runs, bloom/block index,
merge compaction, tombstone masking, merged reads, perf context
(engine_rocks/rocksdb role: WAL + memtable flush + SSTs + compaction).
"""

from __future__ import annotations

import os

import pytest

from tikv_tpu.native.engine import NativeEngine, native_available
from tikv_tpu.storage.engine import CF_DEFAULT, CF_WRITE, WriteBatch

pytestmark = pytest.mark.skipif(not native_available(), reason="no native engine")


def put(e, key, val, cf=CF_DEFAULT):
    wb = WriteBatch()
    wb.put_cf(cf, key, val)
    e.write(wb)


def delete(e, key, cf=CF_DEFAULT):
    wb = WriteBatch()
    wb.delete_cf(cf, key)
    e.write(wb)


def test_reads_merge_memtable_and_runs(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    for i in range(100):
        put(e, b"a%03d" % i, b"gen1-%d" % i)
    e.flush()
    assert e.run_count("default") == 1
    # overwrite a subset post-flush: memtable must mask the run
    for i in range(0, 100, 10):
        put(e, b"a%03d" % i, b"gen2-%d" % i)
    for i in range(100):
        want = b"gen2-%d" % i if i % 10 == 0 else b"gen1-%d" % i
        assert e.get_cf(CF_DEFAULT, b"a%03d" % i) == want
    # scan sees the merged view in order
    got = list(e.scan_cf(CF_DEFAULT, b"", None))
    assert [k for k, _ in got] == [b"a%03d" % i for i in range(100)]
    e.close()


def test_tombstone_in_newer_run_masks_older_run(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    put(e, b"k1", b"v1")
    put(e, b"k2", b"v2")
    e.flush()
    delete(e, b"k1")
    e.flush()
    assert e.run_count("default") == 2
    assert e.get_cf(CF_DEFAULT, b"k1") is None
    assert e.get_cf(CF_DEFAULT, b"k2") == b"v2"
    assert [k for k, _ in e.scan_cf(CF_DEFAULT, b"", None)] == [b"k2"]
    # survives recovery
    e.close()
    e2 = NativeEngine(path=str(tmp_path / "db"))
    assert e2.get_cf(CF_DEFAULT, b"k1") is None
    assert e2.get_cf(CF_DEFAULT, b"k2") == b"v2"
    e2.close()


def test_merge_folds_runs_and_drops_bottom_tombstones(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    for gen in range(4):
        for i in range(50):
            put(e, b"m%03d" % i, b"g%d-%d" % (gen, i))
        e.flush()
    delete(e, b"m007")
    e.flush()
    assert e.run_count("default") == 5
    assert e.merge_runs("default") == 1
    assert e.run_count("default") == 1
    assert e.get_cf(CF_DEFAULT, b"m007") is None
    assert e.get_cf(CF_DEFAULT, b"m008") == b"g3-8"
    # the merged run dropped the tombstone at the bottom level: the key is
    # physically gone after recovery too
    e.close()
    e2 = NativeEngine(path=d)
    assert e2.run_count("default") == 1
    assert e2.get_cf(CF_DEFAULT, b"m007") is None
    assert [k for k, _ in e2.scan_cf(CF_DEFAULT, b"", None)] == [
        b"m%03d" % i for i in range(50) if i != 7]
    e2.close()


def test_snapshot_pins_versions_across_flush(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    put(e, b"s1", b"old")
    snap = e.snapshot()
    put(e, b"s1", b"new")
    e.flush()
    assert snap.get_cf(CF_DEFAULT, b"s1") == b"old"
    assert e.get_cf(CF_DEFAULT, b"s1") == b"new"
    e.close()


def test_reverse_scan_and_seek_for_prev_across_runs(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    for i in range(0, 100, 2):   # evens in a run
        put(e, b"r%03d" % i, b"run-%d" % i)
    e.flush()
    for i in range(1, 100, 2):   # odds in the memtable
        put(e, b"r%03d" % i, b"mem-%d" % i)
    got = [k for k, _ in e.scan_cf(CF_DEFAULT, b"", None, reverse=True)]
    assert got == [b"r%03d" % i for i in reversed(range(100))]
    got = [k for k, _ in e.scan_cf(CF_DEFAULT, b"r010", b"r020", reverse=True)]
    assert got == [b"r%03d" % i for i in range(19, 9, -1)]
    # seek_for_prev via the snapshot cursor surface
    snap = e.snapshot()
    cur = snap.cursor_cf(CF_DEFAULT)
    assert cur.seek_for_prev(b"r015")
    assert (cur.key(), cur.value()) == (b"r015", b"mem-15")
    assert cur.seek_for_prev(b"r015\xff")
    assert (cur.key(), cur.value()) == (b"r015", b"mem-15")
    assert cur.seek(b"r014")
    assert (cur.key(), cur.value()) == (b"r014", b"run-14")
    snap.release()
    e.close()


def test_deep_version_scan_limit_with_runs(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    for i in range(20):
        put(e, b"w%02d" % i, b"x", cf=CF_WRITE)
    e.flush()
    got = list(e.scan_cf(CF_WRITE, b"", None, limit=5))
    assert [k for k, _ in got] == [b"w%02d" % i for i in range(5)]
    e.close()


def test_perf_context_counts_bloom_and_blocks(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    for i in range(500):
        put(e, b"p%04d" % i, b"v" * 50)
    e.flush()
    base = e.perf_context()
    # present key: bloom passes, a block is read
    assert e.get_cf(CF_DEFAULT, b"p0100") == b"v" * 50
    mid = e.perf_context()
    assert mid["gets"] == base["gets"] + 1
    assert mid["blocks_read"] > base["blocks_read"]
    # absent keys: overwhelmingly skipped by the bloom filter
    for i in range(200):
        assert e.get_cf(CF_DEFAULT, b"zz%04d" % i) is None
    end = e.perf_context()
    assert end["bloom_skips"] - mid["bloom_skips"] > 150
    assert end["flushes"] >= 1
    e.close()


def test_mem_limit_keeps_memtable_flat(tmp_path):
    """The 10M-key-load shape scaled to CI: with a memtable cap, a load many
    times that size keeps resident memtable bytes bounded by flushing."""
    e = NativeEngine(path=str(tmp_path / "db"), mem_limit=256 * 1024, sync=False)
    peak = 0
    for i in range(4000):
        put(e, b"L%06d" % i, b"v" * 100)
        peak = max(peak, e.mem_bytes())
    assert peak < 2 * 256 * 1024 + 64 * 1024, f"memtable peaked at {peak}"
    assert e.run_count("default") >= 2
    assert e.perf_context()["flushes"] >= 2
    # everything still readable through the merged view
    assert e.get_cf(CF_DEFAULT, b"L000000") == b"v" * 100
    assert e.get_cf(CF_DEFAULT, b"L003999") == b"v" * 100
    # and after folding into one run
    e.merge_runs("default")
    assert e.run_count("default") == 1
    assert e.get_cf(CF_DEFAULT, b"L002000") == b"v" * 100
    e.close()


def test_partial_flush_discarded_at_recovery(tmp_path):
    """A run file without a completion marker above it is a crashed flush:
    recovery must ignore it and recover from the WAL instead."""
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    put(e, b"c1", b"v1")
    e.flush()
    put(e, b"c2", b"v2")
    e.close()
    # forge a partial flush: a run claiming seq far ahead, but no marker
    runs = [f for f in os.listdir(d) if f.startswith("run0-")]
    assert len(runs) == 1
    src = os.path.join(d, runs[0])
    forged = os.path.join(d, "run0-%016x" % (10**9))
    with open(src, "rb") as f:
        data = bytearray(f.read())
    with open(forged, "wb") as f:
        f.write(data)
    e2 = NativeEngine(path=d)
    assert not os.path.exists(forged)  # discarded
    assert e2.get_cf(CF_DEFAULT, b"c1") == b"v1"
    assert e2.get_cf(CF_DEFAULT, b"c2") == b"v2"
    e2.close()


def test_merge_leftover_inputs_cleaned_at_recovery(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    put(e, b"x1", b"v1")
    e.flush()
    put(e, b"x2", b"v2")
    e.flush()
    assert e.run_count("default") == 2
    files_before = {f for f in os.listdir(d) if f.startswith("run0-")}
    e.merge_runs("default")
    e.close()
    # simulate crash-before-unlink: restore one input file alongside the
    # merged output (merge keeps the newest input's name)
    e2 = NativeEngine(path=d)
    assert e2.run_count("default") == 1
    assert e2.get_cf(CF_DEFAULT, b"x1") == b"v1"
    assert e2.get_cf(CF_DEFAULT, b"x2") == b"v2"
    e2.close()
    assert len(files_before) == 2


def test_compaction_keeps_tombstones_that_mask_runs(tmp_path):
    # memtable GC must not resurrect: a tombstone whose value lives in a
    # sorted run survives compact() and dies only at a bottom-level merge
    e = NativeEngine(path=str(tmp_path / "db"))
    put(e, b"k1", b"v1")
    e.flush()
    delete(e, b"k1")
    e.compact()
    assert e.get_cf(CF_DEFAULT, b"k1") is None
    # the masking still holds across flush + recovery
    e.flush()
    e.close()
    e2 = NativeEngine(path=str(tmp_path / "db"))
    assert e2.get_cf(CF_DEFAULT, b"k1") is None
    # bottom-level merge may now drop both versions for good
    e2.merge_runs("default")
    assert e2.get_cf(CF_DEFAULT, b"k1") is None
    e2.close()


def test_delete_range_covers_flushed_runs(tmp_path):
    e = NativeEngine(path=str(tmp_path / "db"))
    for i in range(20):
        put(e, b"r%02d" % i, b"v%02d" % i)
    e.flush()  # all twenty live only in a run now
    put(e, b"r25", b"vmem")  # and one memtable resident
    wb = WriteBatch()
    wb.delete_range_cf(CF_DEFAULT, b"r00", b"r10")
    e.write(wb)
    for i in range(20):
        want = None if i < 10 else b"v%02d" % i
        assert e.get_cf(CF_DEFAULT, b"r%02d" % i) == want, i
    assert e.get_cf(CF_DEFAULT, b"r25") == b"vmem"
    assert [k for k, _ in e.scan_cf(CF_DEFAULT, b"r00", b"r20")] == [
        b"r%02d" % i for i in range(10, 20)
    ]
    # durable: the range tombstones replay from the WAL
    e.close()
    e2 = NativeEngine(path=str(tmp_path / "db"))
    assert e2.get_cf(CF_DEFAULT, b"r05") is None
    assert e2.get_cf(CF_DEFAULT, b"r15") == b"v15"
    e2.close()


def test_damaged_trusted_run_refuses_open(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    for i in range(50):
        put(e, b"k%03d" % i, b"v" * 100)
    e.flush()
    e.close()
    run = next(f for f in os.listdir(d) if f.startswith("run0-"))
    with open(os.path.join(d, run), "r+b") as f:
        f.seek(-8, os.SEEK_END)
        f.write(b"\xff" * 4)  # wreck the index/bloom crc: structural damage
    # the WAL covering this run is gone: opening would silently lose
    # acked writes, so the engine must refuse (like a torn WAL segment)
    with pytest.raises(RuntimeError):
        NativeEngine(path=d)


def test_range_tombstone_is_o1_and_masks_runs(tmp_path):
    # delete_range is a real range tombstone (rocksdb DeleteRange role):
    # O(1) on the write path, masking memtable + flushed keys at read time
    e = NativeEngine(path=str(tmp_path / "db"))
    for i in range(30):
        put(e, b"t%02d" % i, b"v%02d" % i)
    e.flush()
    wb = WriteBatch()
    wb.delete_range_cf(CF_DEFAULT, b"t00", b"t10")
    e.write(wb)
    assert e.mem_bytes() < 1024  # no per-key expansion into the memtable
    assert e.get_cf(CF_DEFAULT, b"t05") is None
    assert e.get_cf(CF_DEFAULT, b"t15") == b"v15"
    # re-put after the range delete: newer version wins
    put(e, b"t03", b"resurrected")
    assert e.get_cf(CF_DEFAULT, b"t03") == b"resurrected"
    got = [k for k, _ in e.scan_cf(CF_DEFAULT, b"t00", b"t99")]
    assert got == [b"t03"] + [b"t%02d" % i for i in range(10, 30)]
    # reverse scan applies the same masking
    rev = [k for k, _ in e.scan_cf(CF_DEFAULT, b"t00", b"t99", reverse=True)]
    assert rev == list(reversed(got))
    e.close()


def test_range_tombstone_survives_flush_merge_recovery(tmp_path):
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    for i in range(20):
        put(e, b"k%02d" % i, b"old%02d" % i)
    e.flush()
    snap = e.snapshot()  # pins the pre-delete state
    wb = WriteBatch()
    wb.delete_range_cf(CF_DEFAULT, b"k00", b"k10")
    e.write(wb)
    # snapshot still sees everything; live view does not
    assert snap.get_cf(CF_DEFAULT, b"k05") == b"old05"
    assert e.get_cf(CF_DEFAULT, b"k05") is None
    e.flush()  # tombstone rides into a run
    assert e.get_cf(CF_DEFAULT, b"k05") is None
    assert snap.get_cf(CF_DEFAULT, b"k05") == b"old05"
    snap.release()
    # with the snapshot gone, a merge folds the range delete for good
    e.merge_runs("default")
    assert e.run_count("default") == 1
    assert e.get_cf(CF_DEFAULT, b"k05") is None
    assert e.get_cf(CF_DEFAULT, b"k15") == b"old15"
    e.close()
    e2 = NativeEngine(path=d)
    assert e2.get_cf(CF_DEFAULT, b"k05") is None
    assert e2.get_cf(CF_DEFAULT, b"k15") == b"old15"
    e2.close()


def test_flush_with_empty_memtable_keeps_marker_chain(tmp_path):
    # a flush that produces no runs (all records since the last flush were
    # no-ops) must still advance the completion marker before truncating the
    # WAL — deleting mark-N without a successor would make recovery distrust
    # and unlink every run
    d = str(tmp_path / "db")
    e = NativeEngine(path=d)
    for i in range(10):
        put(e, b"k%d" % i, b"v%d" % i)
    e.flush()
    e.write(WriteBatch())  # advances seq, leaves the memtable empty
    e.flush()
    assert any(f.startswith("mark-") for f in os.listdir(d))
    e.close()
    e2 = NativeEngine(path=d)
    for i in range(10):
        assert e2.get_cf(CF_DEFAULT, b"k%d" % i) == b"v%d" % i
    e2.close()


def test_seek_for_prev_below_range_start(tmp_path):
    # target below the cursor's lower bound must return not-found, not a key
    # outside the range (and must not walk off the front of the memtable)
    e = NativeEngine(path=str(tmp_path / "db"))
    put(e, b"b", b"1")
    put(e, b"k1", b"2")
    put(e, b"m", b"3")
    e.flush()
    put(e, b"c", b"4")  # memtable resident below the bound
    snap = e.snapshot()
    cur = snap.cursor_cf(CF_DEFAULT, lower=b"k", upper=b"z")
    assert not cur.seek_for_prev(b"a")
    assert cur.seek_for_prev(b"k5")
    assert cur.key() == b"k1"
    snap.release()
    e.close()


def test_in_memory_engine_reclaims_range_deletes_on_compact():
    # with no runs the memtable is the whole store: compact() applies and
    # drops range tombstones no snapshot can see below, reclaiming memory
    e = NativeEngine()  # in-memory
    for i in range(1000):
        put(e, b"g%04d" % i, b"v" * 100)
    high = e.mem_bytes()
    wb = WriteBatch()
    wb.delete_range_cf(CF_DEFAULT, b"g0000", b"g0900")
    e.write(wb)
    assert e.get_cf(CF_DEFAULT, b"g0500") is None
    e.compact()
    assert e.mem_bytes() < high // 5, e.mem_bytes()
    assert e.get_cf(CF_DEFAULT, b"g0500") is None
    assert e.get_cf(CF_DEFAULT, b"g0950") == b"v" * 100
    e.close()


def test_io_classification_and_throttle(tmp_path):
    """Engine IO is tagged per type (file_system role): foreground writes,
    flushes, and compaction each account their bytes, and an attached rate
    limiter sees the requests."""
    from tikv_tpu.util.io_limiter import IoRateLimiter, IoType

    lim = IoRateLimiter(bytes_per_sec=0)  # unlimited, but counts requests
    seen = []
    orig = lim.request

    def spy(nbytes, io_type=None, timeout=5.0):
        seen.append((io_type, nbytes))
        return orig(nbytes, io_type, timeout)

    lim.request = spy
    e = NativeEngine(path=str(tmp_path / "db"), sync=False, io_limiter=lim)
    for i in range(100):
        put(e, b"io%03d" % i, b"v" * 50)
    e.flush()
    for i in range(100, 200):
        put(e, b"io%03d" % i, b"v" * 50)
    e.flush()
    e.merge_runs("default")
    stats = e.io_stats()
    assert stats.get("foreground_write", 0) > 0
    assert stats.get("flush", 0) > 0
    assert stats.get("compaction", 0) > 0
    types = {t for t, _ in seen}
    assert {IoType.FOREGROUND_WRITE, IoType.FLUSH, IoType.COMPACTION} <= types
    e.close()


def test_cold_scan_does_not_block_writers(tmp_path):
    """A cold range scan's run-block IO must not hold the engine lock: a put
    issued mid-scan completes in a fraction of the scan's runtime.  Before
    the MergeIter split (init under the lock, block IO after release) the
    writer waited out the entire scan (engine.cc eng_scan)."""
    import threading
    import time

    e = NativeEngine(path=str(tmp_path / "db"), sync=False)
    val = b"v" * 384
    n_keys = 120_000
    wb = WriteBatch()
    for i in range(n_keys):
        wb.put_cf(CF_DEFAULT, b"k%07d" % i, val)
        if i % 10_000 == 9_999:
            e.write(wb)
            wb = WriteBatch()
            e.flush()  # many cold runs: the scan merges across real block IO
    snap = e.snapshot()
    started = threading.Event()
    scan_s = [0.0]

    def scanner():
        t0 = time.perf_counter()
        started.set()
        n, _ = snap.scan_raw(CF_DEFAULT, b"", None)
        scan_s[0] = time.perf_counter() - t0
        assert n == n_keys

    t = threading.Thread(target=scanner)
    t.start()
    started.wait()
    time.sleep(0.02)  # scanner is inside eng_scan (ctypes released the GIL)
    t0 = time.perf_counter()
    put(e, b"probe-mid-scan", b"x")
    put_s = time.perf_counter() - t0
    t.join()
    snap.release()
    assert e.get_cf(CF_DEFAULT, b"probe-mid-scan") == b"x"
    e.close()
    # enough runtime that a lock-held scan would provably stall the put
    if scan_s[0] <= 0.03:
        pytest.skip(f"scan too fast to measure contention: {scan_s[0]:.3f}s")
    assert put_s < max(0.01, scan_s[0] / 2), (
        f"writer stalled {put_s:.3f}s behind a {scan_s[0]:.3f}s scan"
    )


def test_chunked_scan_crosses_memtable_cap(tmp_path):
    """Scans/seeks re-init in bounded chunks once the memtable walk passes
    the native cap (65536 entries per locked walk, 1024 for seeks); results
    must be seamless across chunk boundaries, including runs of tombstones
    wider than a seek chunk and reverse iteration."""
    e = NativeEngine(path=str(tmp_path / "db"), sync=False)
    n = 100_000
    wb = WriteBatch()
    for i in range(n):
        wb.put_cf(CF_DEFAULT, b"c%06d" % i, b"v%d" % i)
    e.write(wb)  # all resident in the memtable: forces chunked walks
    # tombstone belt wider than the 1024-entry seek chunk
    wb = WriteBatch()
    for i in range(10_000, 12_500):
        wb.delete_cf(CF_DEFAULT, b"c%06d" % i)
    e.write(wb)
    snap = e.snapshot()
    n_live = n - 2_500
    got = list(snap.scan_cf(CF_DEFAULT, b"", None))
    assert len(got) == n_live
    assert got[0][0] == b"c000000" and got[-1][0] == b"c%06d" % (n - 1)
    assert got[9_999][0] == b"c009999" and got[10_000][0] == b"c012500"
    rev = list(snap.scan_cf(CF_DEFAULT, b"", None, reverse=True))
    assert [k for k, _ in rev] == [k for k, _ in got][::-1]
    # limited scan stops exactly at the limit across a chunk edge
    lim = list(snap.scan_cf(CF_DEFAULT, b"c009000", None, limit=3_000))
    assert len(lim) == 3_000 and lim[-1][0] == b"c014499"
    # seek across the tombstone belt (forward) and back over it (for_prev)
    cur = snap.cursor_cf(CF_DEFAULT)
    assert cur.seek(b"c010000")
    assert cur.key() == b"c012500"
    assert cur.seek_for_prev(b"c012499")
    assert cur.key() == b"c009999"
    snap.release()
    e.close()


def test_reads_do_not_serialize_behind_wal_sync(tmp_path):
    """The commit path's WAL append + fdatasync runs under the writer lock
    only (engine.cc write_mu): point reads and scans must keep flowing while
    a large batch is in its IO phase, instead of queueing behind the
    engine's unique lock as before."""
    import threading
    import time

    from tikv_tpu.native.engine import NativeEngine, native_available

    if not native_available():
        pytest.skip("native engine unavailable")
    eng = NativeEngine(path=str(tmp_path / "db"), sync=True)
    for i in range(200):
        eng.put_cf("default", b"seed-%04d" % i, b"v" * 100)
    snap_done = threading.Event()
    write_done = threading.Event()
    reads_during = [0]

    def reader():
        snap_done.set()
        while not write_done.is_set():
            assert eng.get_cf("default", b"seed-0100") is not None
            n = 0
            for _k, _v in eng.snapshot().scan_cf("default", b"seed-", b"seed-\xff"):
                n += 1
                if n >= 50:
                    break
            reads_during[0] += 1

    t = threading.Thread(target=reader)
    t.start()
    snap_done.wait()
    # a fat batch: its WAL write+fsync dominates its in-memory apply
    wb_val = b"x" * (1 << 20)
    t0 = time.perf_counter()
    for i in range(60):
        eng.put_cf("default", b"big-%02d" % i, wb_val)
    wt = time.perf_counter() - t0
    write_done.set()
    t.join()
    eng.close()
    # with the old single-lock commit path the reader managed ~0-2 rounds
    # while 60MB of synced batches went through; off-lock WAL IO gives it
    # hundreds.  10 is a conservative floor that still proves overlap.
    assert reads_during[0] >= 10, (reads_during[0], wt)
