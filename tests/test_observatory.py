"""Performance observatory (copr/observatory.py, docs/observatory.md):
bounded per-sig path cost profiles, the device compile ledger, exemplar
trace resolution, HBM watermarks, and the obs_diff floor gate.

Run under TIKV_TPU_SANITIZE=1 by scripts/check.sh — the report hot path
must share no lock with serving."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from copr_fixtures import TABLE_ID as PRODUCT_TABLE  # noqa: F401 (path setup)
from tikv_tpu.copr import observatory as obs
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util import trace
from tikv_tpu.util.failpoint import cfg
from tikv_tpu.util.metrics import REGISTRY, Histogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TABLE_ID = 91

COLS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),
    ColumnInfo(3, FieldType.int64()),
]


def _engine(n: int, seed: int = 0) -> BTreeEngine:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 50, n)
    b = rng.integers(0, 100000, n)
    eng = BTreeEngine()
    items = []
    for i in range(n):
        rk = record_key(TABLE_ID, i)
        val = encode_row(COLS[1:], [int(a[i]), int(b[i])])
        items.append((Key.from_raw(rk).append_ts(20).encoded,
                      Write(WriteType.PUT, 10, short_value=val).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    return eng


def _sum_dag(cut: int = 40) -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("lt", col(1), const_int(cut))]),
        Aggregation([], [AggDescriptor("sum", col(2)),
                         AggDescriptor("count", None)]),
    ])


def _region_req(region: int, rows_per: int, dag: DagRequest,
                apply_index: int = 7) -> CoprRequest:
    lo = record_key(TABLE_ID, region * rows_per)
    hi = record_key(TABLE_ID, (region + 1) * rows_per)
    return CoprRequest(103, dag, [(lo, hi)], 100, context={
        "region_id": region + 1, "region_epoch": (1, 1),
        "apply_index": apply_index,
    })


ROWS_PER = 400
N_REGIONS = 4


@pytest.fixture(autouse=True)
def _fresh_observatory():
    obs.OBSERVATORY.reset()
    yield
    obs.OBSERVATORY.reset()


@pytest.fixture
def sampled_traces():
    old = trace.sample_rate()
    trace.set_sample_rate(1.0)
    yield
    trace.set_sample_rate(old)


# ---------------------------------------------------------------------------
# Histogram.percentile (satellite: bucket-interpolated accessor)
# ---------------------------------------------------------------------------

def test_histogram_percentile_interpolates_within_bucket():
    h = Histogram("t_pct", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    # rank 2 of 4 lands in the (1, 2] bucket (cum 1 before, 2 inside)
    p50 = h.percentile(0.5)
    assert 1.0 <= p50 <= 2.0
    # 100th percentile of in-range data interpolates to the last bucket hit
    assert h.percentile(1.0) == pytest.approx(4.0)


def test_histogram_percentile_edge_buckets():
    h = Histogram("t_pct_edge", buckets=(1.0, 2.0))
    h.observe(0.25)  # first bucket: lower bound is 0
    assert 0.0 <= h.percentile(0.5) <= 1.0
    h2 = Histogram("t_pct_inf", buckets=(1.0, 2.0))
    h2.observe(100.0)  # overflow bucket clamps to the last finite bound
    assert h2.percentile(0.99) == pytest.approx(2.0)


def test_histogram_percentile_empty_and_labels():
    h = Histogram("t_pct_empty", buckets=(1.0,))
    assert h.percentile(0.5) == 0.0
    h.observe(0.5, lane="a")
    assert h.percentile(0.5, lane="b") == 0.0
    assert 0.0 < h.percentile(0.5, lane="a") <= 1.0


# ---------------------------------------------------------------------------
# recorder bounds + windows
# ---------------------------------------------------------------------------

def test_bounded_memory_under_sig_churn():
    o = obs.Observatory(window_s=60.0, max_sigs=8, enabled=True)
    for i in range(100):
        o.record_serve(f"sig{i:03d}", "unary", 0.001, rows=10)
    snap = o.snapshot()
    assert snap["live_sigs"] <= 8
    assert snap["evicted_sigs"] == 100 - snap["live_sigs"]
    # the survivors are the most recently used
    assert "sig099" in snap["sigs"] and "sig000" not in snap["sigs"]


def test_window_roll_drops_old_observations():
    o = obs.Observatory(window_s=0.03, max_sigs=8, enabled=True)
    o.record_serve("s", "unary", 1.0, rows=1)  # old, slow
    time.sleep(0.04)
    for _ in range(obs.N_WINDOWS):
        o.record_serve("s", "unary", 0.001, rows=1)
        time.sleep(0.04)
    v = o.snapshot()["sigs"]["s"]["paths"]["unary|plain"]
    # the 1s outlier rolled out of the retained windows; lifetime totals keep it
    assert v["count"] == obs.N_WINDOWS
    assert v["total_count"] == obs.N_WINDOWS + 1
    assert v["p99_ms"] < 100.0
    assert v["time_spent_s"] > 1.0  # lifetime time spent still counts the outlier


def test_profile_percentiles_and_axes():
    o = obs.Observatory(window_s=60.0, enabled=True)
    for ms in (1, 1, 1, 1, 1, 1, 1, 1, 1, 50):
        o.record_serve("s", "xregion", ms / 1000.0, rows=100, occupancy=4,
                       queue_wait_s=0.002, padding_waste=0.25)
    v = o.snapshot()["sigs"]["s"]["paths"]["xregion|plain"]
    assert v["count"] == 10
    assert v["p50_ms"] < 5.0 < v["p99_ms"]
    assert v["mean_occupancy"] == pytest.approx(4.0)
    assert v["padding_waste"] == pytest.approx(0.25)
    assert v["queue_wait_ms_mean"] == pytest.approx(2.0, rel=0.01)
    assert v["rows_per_s"] > 0


def test_declines_recorded_per_sig_and_cause():
    o = obs.Observatory(window_s=60.0, enabled=True)
    o.record_serve("s", "xregion", 0.001, rows=1)
    o.record_decline("s", "xregion", "padding")
    o.record_decline("s", "xregion", "padding")
    o.record_decline("s", "xregion", "no_cache")
    v = o.snapshot()["sigs"]["s"]["paths"]["xregion|plain"]
    assert v["declines"] == {"padding": 2, "no_cache": 1}


def test_kill_switch_disables_recording():
    o = obs.Observatory(enabled=False)
    o.record_serve("s", "unary", 0.001, rows=1)
    o.record_compile("site", "unary", 0.1, sig="s")
    snap = o.snapshot()
    assert snap["enabled"] is False
    assert not snap["sigs"] and not snap["compiles"]["events"]


# ---------------------------------------------------------------------------
# compile ledger (jit boundary)
# ---------------------------------------------------------------------------

def test_compile_ledger_first_call_vs_cached(monkeypatch):
    import jax
    import jax.numpy as jnp

    o = obs.Observatory(window_s=60.0, enabled=True)
    monkeypatch.setattr(obs, "OBSERVATORY", o)

    fn = obs.timed_jit(jax.jit(lambda x: x * 2 + 1), "test.site", "unary",
                       "sigX")
    fn(jnp.ones(8))
    fn(jnp.ones(8))  # cached executable: no new event
    events = o.snapshot()["compiles"]["events"]
    assert len(events) == 1
    ev = events[0]
    assert ev["site"] == "test.site" and ev["path"] == "unary"
    assert ev["sig"] == "sigX" and ev["wall_s"] > 0
    assert ev["cache_size"] == 1
    fn(jnp.ones(16))  # new shape: recompile, second event
    events = o.snapshot()["compiles"]["events"]
    assert len(events) == 2 and events[1]["cache_size"] == 2
    agg = o.snapshot()["compiles"]["by_sig_path"]["sigX|unary"]
    assert agg["count"] == 2
    sizes = o.snapshot()["compiles"]["executable_cache_sizes"]
    assert sizes["test.site"] == 2


def test_compile_ledger_xla_cost_analysis(monkeypatch):
    import jax
    import jax.numpy as jnp

    o = obs.Observatory(window_s=60.0, enabled=True)
    o.xla_analysis = True
    monkeypatch.setattr(obs, "OBSERVATORY", o)
    fn = obs.timed_jit(jax.jit(lambda x: x @ x), "test.mm", "unary", "sigY")
    fn(jnp.ones((8, 8)))
    ev = o.snapshot()["compiles"]["events"][0]
    # the CPU backend exposes cost_analysis: flops/bytes land in the ledger
    assert ev.get("flops", 0) > 0
    assert ev.get("bytes_accessed", 0) > 0


# ---------------------------------------------------------------------------
# HBM watermarks
# ---------------------------------------------------------------------------

def test_hbm_watermark_movement(monkeypatch):
    from tikv_tpu.copr.cache import ColumnBlockCache

    o = obs.Observatory(window_s=60.0, enabled=True)
    monkeypatch.setattr(obs, "OBSERVATORY", o)
    cache = ColumnBlockCache()
    cache.add([None], 16)
    blk = cache.blocks[0]
    arr = np.zeros(1024, dtype=np.int64)  # 8192 bytes
    cache.device_arrays(blk, ("blockenc", 1), lambda b: (arr,))
    cache.device_arrays(blk, ("zone_layout", 2), lambda b: (arr, arr))
    snap = o.snapshot()["hbm"]
    assert snap["unary"]["bytes"] == arr.nbytes
    assert snap["zone"]["bytes"] == 2 * arr.nbytes
    # a repeat hit pins nothing new
    cache.device_arrays(blk, ("blockenc", 1), lambda b: (arr,))
    assert o.snapshot()["hbm"]["unary"]["bytes"] == arr.nbytes
    cache.drop_device()
    snap = o.snapshot()["hbm"]
    assert snap["unary"]["bytes"] == 0 and snap["zone"]["bytes"] == 0
    # the high-water mark survives the unpin
    assert snap["unary"]["watermark_bytes"] == arr.nbytes
    assert snap["zone"]["watermark_bytes"] == 2 * arr.nbytes


def test_clear_blocks_unpins_with_accounting(monkeypatch):
    """Discarding blocks must release their pinned bytes from the HBM
    gauges — a raw blocks.clear() (the old repack/failure-cleanup shape)
    would strand them at the watermark forever."""
    from tikv_tpu.copr.cache import ColumnBlockCache

    o = obs.Observatory(window_s=60.0, enabled=True)
    monkeypatch.setattr(obs, "OBSERVATORY", o)
    cache = ColumnBlockCache()
    cache.add([None], 16)
    arr = np.zeros(128, dtype=np.int64)
    cache.device_arrays(cache.blocks[0], ("blockenc", 1), lambda b: (arr,))
    assert o.snapshot()["hbm"]["unary"]["bytes"] == arr.nbytes
    cache.clear_blocks()
    snap = o.snapshot()["hbm"]
    assert snap["unary"]["bytes"] == 0
    assert snap["unary"]["watermark_bytes"] == arr.nbytes
    assert not cache.blocks


# ---------------------------------------------------------------------------
# THE acceptance: one sig on >=3 paths, exemplars resolve, compiles ledgered
# ---------------------------------------------------------------------------

def test_same_sig_three_paths_profiles_exemplars_compiles(sampled_traces):
    eng = _engine(ROWS_PER * N_REGIONS, seed=5)
    dev = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    cpu = Endpoint(LocalEngine(eng), enable_device=False)
    dag = _sum_dag()
    sig_id, _desc = obs.dag_sig(dag)

    # path 1: unary warm serving (zone may take it — also a distinct path)
    for _ in range(3):
        with trace.start_trace("client.unary"):
            dev.handle_request(_region_req(0, ROWS_PER, dag))
    # path 2: the scheduler's cross-region batch (same sig, 4 regions)
    with trace.start_trace("client.batch"):
        resps = dev.handle_batch(
            [_region_req(r, ROWS_PER, dag) for r in range(N_REGIONS)])
    # path 3: the CPU pipeline (device disabled endpoint, same plan)
    with trace.start_trace("client.cpu"):
        cpu_resp = cpu.handle_request(_region_req(1, ROWS_PER, dag))
    assert resps[1].data == cpu_resp.data  # byte identity across paths

    via_rpc = {"sigs": obs.OBSERVATORY.snapshot(sig=sig_id)["sigs"]}
    entry = via_rpc["sigs"][sig_id]
    paths = {pk.split("|")[0] for pk in entry["paths"]}
    assert {"xregion", "cpu"} <= paths and len(paths) >= 3, paths
    for pk, v in entry["paths"].items():
        assert v["count"] >= 1
        assert v["time_spent_s"] > 0
        # every per-path profile carries >=1 exemplar that RESOLVES to a
        # live trace (docs/tracing.md)
        assert v["exemplar_traces"], f"no exemplar on {pk}"
        assert any(trace.TRACER.get(t) is not None
                   for t in v["exemplar_traces"]), pk
    # measured costs differ across paths (cpu vs device batch)
    lats = {pk.split("|")[0]: v["mean_ms"] for pk, v in entry["paths"].items()}
    assert len(set(lats.values())) > 1
    # every compile that occurred is in the ledger with its sig and path
    events = obs.OBSERVATORY.snapshot()["compiles"]["events"]
    assert events, "no compile events recorded"
    for ev in events:
        assert ev["site"] and ev["path"] and "sig" in ev and ev["wall_s"] > 0
    assert any(ev["sig"] == sig_id for ev in events)
    # rows flowed: warm serves attribute the image's rows
    assert any(v["rows"] > 0 for v in entry["paths"].values())


def test_slow_log_carries_path_and_plan_sig():
    eng = _engine(ROWS_PER)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    ep.slow_log.threshold_s = 0.0
    dag = _sum_dag()
    sig_id, _ = obs.dag_sig(dag)
    ep.handle_request(_region_req(0, ROWS_PER, dag))
    entry = ep.slow_log.tail(1)[0]
    assert entry["plan_sig"] == sig_id
    assert entry["path"] in ("unary", "zone", "mesh", "cpu")


def test_txn_slow_log_carries_path_and_sig():
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Mutation

    storage = Storage()
    storage.scheduler.slow_log.threshold_s = 0.0
    pd = MockPd()
    ts = pd.get_tso()
    storage.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(b"ok"), b"v")], b"ok", ts), None)
    storage.sched_txn_command(Commit([Key.from_raw(b"ok")], ts, pd.get_tso()),
                              None)
    entries = storage.scheduler.slow_log.tail(10)
    assert entries
    for e in entries:
        assert e["path"] in ("txn", "txn_group")
        assert e["plan_sig"].startswith("txn:")


# ---------------------------------------------------------------------------
# floor gate: clean pass, seeded regression fails (failpoint-slowed path)
# ---------------------------------------------------------------------------

def _serve_n(ep, dag, n):
    for _ in range(n):
        ep.handle_request(_region_req(0, ROWS_PER, dag))


def test_floor_diff_pass_and_seeded_regression(tmp_path):
    eng = _engine(ROWS_PER)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    dag = _sum_dag()
    _serve_n(ep, dag, 2)  # warm: compile + fill outside the floor window
    obs.OBSERVATORY.reset()
    _serve_n(ep, dag, 4)
    floor_path = str(tmp_path / "floor.json")
    floor = obs.OBSERVATORY.write_floor(floor_path, min_count=3)
    assert floor["sigs"], "floor captured no profiles"

    # clean run: same serving speed passes the gate
    obs.OBSERVATORY.reset()
    _serve_n(ep, dag, 4)
    clean = obs.OBSERVATORY.snapshot()
    verdict = obs.floor_diff(floor, clean, ratio=2.0, min_count=3)
    assert verdict["ok"], verdict
    assert verdict["checked"] >= 1

    # seeded regression: a failpoint-slowed serve path drops rows/s >2x
    obs.OBSERVATORY.reset()
    cfg("coprocessor_serve", "sleep(60)")
    try:
        _serve_n(ep, dag, 4)
    finally:
        cfg("coprocessor_serve", "off")
    slow = obs.OBSERVATORY.snapshot()
    verdict = obs.floor_diff(floor, slow, ratio=2.0, min_count=3)
    assert not verdict["ok"], verdict
    assert verdict["regressions"]
    reg_paths = {r["path"] for r in verdict["regressions"]}
    assert any(pk in reg_paths for pk in floor["sigs"][next(iter(floor["sigs"]))])

    # the script-level gate (scripts/obs_diff.py) agrees on both verdicts
    clean_path = str(tmp_path / "clean.json")
    slow_path = str(tmp_path / "slow.json")
    json.dump(clean, open(clean_path, "w"))
    json.dump(slow, open(slow_path, "w"))
    script = os.path.join(REPO, "scripts", "obs_diff.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ok = subprocess.run([sys.executable, script, "--floor", floor_path,
                         "--current", clean_path], capture_output=True,
                        text=True, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = subprocess.run([sys.executable, script, "--floor", floor_path,
                          "--current", slow_path], capture_output=True,
                         text=True, env=env)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "REGRESSION" in bad.stderr
    # --write-floor normalizes a snapshot into the floor shape
    wf = subprocess.run([sys.executable, script, "--floor",
                         str(tmp_path / "f2.json"), "--current", clean_path,
                         "--write-floor"], capture_output=True, text=True,
                        env=env)
    assert wf.returncode == 0, wf.stdout + wf.stderr
    assert json.load(open(tmp_path / "f2.json"))["sigs"]


# ---------------------------------------------------------------------------
# surfaces: RPC + HTTP
# ---------------------------------------------------------------------------

def test_debug_observatory_rpc_and_http(capsys):
    import urllib.request

    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService
    from tikv_tpu.server.status_server import StatusServer
    from tikv_tpu.storage.storage import Storage

    eng = _engine(ROWS_PER)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    dag = _sum_dag()
    sig_id, _ = obs.dag_sig(dag)
    _serve_n(ep, dag, 2)

    svc = KvService(Storage(), ep)
    srv = Server(svc)
    srv.start()
    c = Client(*srv.addr)
    try:
        snap = c.call("debug_observatory", {})
        assert sig_id in snap["sigs"]
        top = c.call("debug_observatory", {"top": True, "limit": 5})
        assert top["top"] and top["top"][0]["sig"]
        one = c.call("debug_observatory", {"sig": sig_id})
        assert list(one["sigs"]) == [sig_id]
        fl = c.call("debug_observatory", {"floor": True, "min_count": 1})
        assert sig_id in fl["sigs"]
        # the ctl surface renders all three actions off the same RPC
        sys.path.insert(0, REPO)
        try:
            import ctl
        finally:
            sys.path.pop(0)
        addr = f"{srv.addr[0]}:{srv.addr[1]}"
        assert ctl.main(["--addr", addr, "observatory", "top"]) == 0
        out = capsys.readouterr().out
        assert "SIG" in out and sig_id in out
        assert ctl.main(["--addr", addr, "observatory", "sig", sig_id]) == 0
        out = capsys.readouterr().out
        assert sig_id in out and "p95" in out
        assert ctl.main(["--addr", addr, "observatory", "compiles"]) == 0
        out = capsys.readouterr().out
        assert "compile events" in out
    finally:
        c.close()
        srv.stop()

    ss = StatusServer()
    ss.start()
    try:
        host, port = ss.addr
        base = f"http://{host}:{port}"
        body = urllib.request.urlopen(f"{base}/debug/observatory").read()
        assert b"SIG" in body and sig_id.encode() in body
        js = json.loads(urllib.request.urlopen(
            f"{base}/debug/observatory?format=json").read())
        assert sig_id in js["sigs"]
        one = urllib.request.urlopen(
            f"{base}/debug/observatory?sig={sig_id}").read()
        assert sig_id.encode() in one
    finally:
        ss.stop()


def test_metrics_series_move():
    eng = _engine(ROWS_PER)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    _serve_n(ep, _sum_dag(), 2)
    text = REGISTRY.render()
    for series in ("tikv_observatory_serve_total",
                   "tikv_observatory_serve_seconds",
                   "tikv_observatory_compile_total",
                   "tikv_observatory_pinned_hbm_bytes"):
        assert series in text, series


# ---------------------------------------------------------------------------
# concurrency: report hot path is lock-clean under the sanitizer
# ---------------------------------------------------------------------------

def test_concurrent_record_and_snapshot_clean():
    o = obs.Observatory(window_s=0.05, max_sigs=16, enabled=True)
    stop = threading.Event()
    errs = []

    def writer(k):
        i = 0
        while not stop.is_set():
            try:
                o.record_serve(f"sig{(k + i) % 24}", "unary", 0.001, rows=5,
                               trace_id=f"t{i}")
                o.record_decline(f"sig{(k + i) % 24}", "xregion", "padding")
                o.record_compile(f"site{k}", "unary", 0.01, sig=f"sig{k}",
                                 cache_size=i)
                o.note_pin("blockenc", 64)
                o.note_pin("blockenc", -64)
            except Exception as e:  # noqa: BLE001
                errs.append(e)
                return
            i += 1

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    t_end = time.monotonic() + 0.5
    while time.monotonic() < t_end:
        snap = o.snapshot()
        assert snap["live_sigs"] <= 16
        o.top(5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errs
    snap = o.snapshot()
    assert snap["live_sigs"] + snap["evicted_sigs"] > 0
