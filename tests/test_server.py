"""Server layer tests: node bootstrap with PD, KV service over TCP,
batch multiplexing, coprocessor over the wire (reference:
tests/integrations/server + kv service tests)."""

import threading

import pytest

from tikv_tpu.copr.dag import BatchExecutorsRunner, DagRequest, SelectResponse, TableScan
from tikv_tpu.copr.dag_wire import dag_from_wire, dag_to_wire
from tikv_tpu.copr.endpoint import Endpoint
from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.raft.raftkv import RaftKv
from tikv_tpu.server import wire
from tikv_tpu.server.node import Node
from tikv_tpu.server.server import Client, Server
from tikv_tpu.server.service import KvService
from tikv_tpu.storage.storage import Storage


def test_wire_roundtrip():
    vals = [
        None, True, False, 0, -1, 2**62, -(2**62), 1.5, b"bytes", "str",
        [1, [2, [3]]], {"k": b"v", 1: None}, (1, 2), {"nested": {"a": [b"x"]}},
    ]
    for v in vals:
        assert wire.loads(wire.dumps(v)) == v
    with pytest.raises(ValueError):
        wire.loads(wire.dumps([1]) + b"x")


def test_dag_wire_roundtrip():
    import sys, os

    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_kvs
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation, Selection, TopN
    from tikv_tpu.copr.executors import FixtureScanSource
    from tikv_tpu.copr.rpn import call, col, const_int

    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Selection([call("gt", col(2), const_int(5))]),
            Aggregation([col(1)], [AggDescriptor("count", None), AggDescriptor("sum", col(2))]),
            TopN([(col(0), True)], 3),
        ]
    )
    d2 = dag_from_wire(wire.loads(wire.dumps(dag_to_wire(dag))))
    r1 = BatchExecutorsRunner(dag, FixtureScanSource(product_kvs())).handle_request()
    r2 = BatchExecutorsRunner(d2, FixtureScanSource(product_kvs())).handle_request()
    assert r1.encode() == r2.encode()


@pytest.fixture
def single_node():
    """One-node 'cluster' with running background loops + TCP server."""
    pd = MockPd()
    from tikv_tpu.raft.store import ChannelTransport

    transport = ChannelTransport()
    node = Node(pd, transport)
    transport.register(node.store)
    region = node.try_bootstrap_cluster([node.store_id])
    node.create_region_peers()
    peer = node.store.peers[FIRST_REGION_ID]
    peer.node.campaign()
    node.pump()
    assert peer.node.is_leader()
    node.start()
    kv = RaftKv(node.store)  # background loops pump; default pump yields
    storage = Storage(engine=kv)
    copr = Endpoint(kv, enable_device=False)
    from tikv_tpu.server.debug import Debugger

    service = KvService(storage, copr, debugger=Debugger(node.store.engine))
    server = Server(service)
    server.start()
    yield node, server, pd
    server.stop()
    node.stop()


def test_kv_service_over_tcp(single_node):
    node, server, pd = single_node
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    ts1 = pd.get_tso()
    r = client.call(
        "kv_prewrite",
        {
            "mutations": [{"op": "put", "key": b"k", "value": b"v"}],
            "primary_lock": b"k",
            "start_version": ts1,
            "context": ctx,
        },
    )
    assert "error" not in r and "errors" not in r, r
    ts2 = pd.get_tso()
    r = client.call("kv_commit", {"keys": [b"k"], "start_version": ts1, "commit_version": ts2, "context": ctx})
    assert "error" not in r
    r = client.call("kv_get", {"key": b"k", "version": pd.get_tso(), "context": ctx})
    assert r["value"] == b"v"
    # raw API
    client.call("raw_put", {"key": b"rk", "value": b"rv", "context": ctx})
    assert client.call("raw_get", {"key": b"rk", "context": ctx})["value"] == b"rv"
    r = client.call("raw_compare_and_swap", {"key": b"rk", "previous_value": b"rv", "value": b"r2", "context": ctx})
    assert r["succeed"]
    client.close()


def test_locked_key_error_over_wire(single_node):
    node, server, pd = single_node
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    ts1 = pd.get_tso()
    client.call(
        "kv_prewrite",
        {"mutations": [{"op": "put", "key": b"L", "value": b"v"}], "primary_lock": b"L",
         "start_version": ts1, "context": ctx},
    )
    r = client.call("kv_get", {"key": b"L", "version": pd.get_tso(), "context": ctx})
    assert "locked" in r["error"]
    assert r["error"]["locked"]["lock_ts"] == ts1
    # resolve by rollback, then visible as absent
    client.call("kv_batch_rollback", {"keys": [b"L"], "start_version": ts1, "context": ctx})
    r = client.call("kv_get", {"key": b"L", "version": pd.get_tso(), "context": ctx})
    assert r.get("not_found")
    client.close()


def test_batch_multiplexing(single_node):
    node, server, pd = single_node
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    results = {}

    def put(i):
        ts1 = pd.get_tso()
        r1 = client.call(
            "kv_prewrite",
            {"mutations": [{"op": "put", "key": b"mk%d" % i, "value": b"v%d" % i}],
             "primary_lock": b"mk%d" % i, "start_version": ts1, "context": ctx},
        )
        r2 = client.call(
            "kv_commit",
            {"keys": [b"mk%d" % i], "start_version": ts1, "commit_version": pd.get_tso(), "context": ctx},
        )
        results[i] = (r1, r2)

    threads = [threading.Thread(target=put, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(8):
        r1, r2 = results[i]
        assert "error" not in r1 and "errors" not in r1
        assert "error" not in r2
    r = client.call("kv_scan", {"start_key": b"mk", "version": pd.get_tso(), "limit": 20, "context": ctx})
    assert len(r["pairs"]) == 8
    client.close()


def test_coprocessor_over_wire(single_node):
    import sys, os

    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_kvs
    from tikv_tpu.copr.table import record_range

    node, server, pd = single_node
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    for rk, val in product_kvs():
        ts1 = pd.get_tso()
        client.call(
            "kv_prewrite",
            {"mutations": [{"op": "put", "key": rk, "value": val}], "primary_lock": rk,
             "start_version": ts1, "context": ctx},
        )
        client.call("kv_commit", {"keys": [rk], "start_version": ts1, "commit_version": pd.get_tso(), "context": ctx})
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r = client.call(
        "coprocessor",
        {"dag": dag_to_wire(dag), "ranges": [list(record_range(TABLE_ID))],
         "start_ts": pd.get_tso(), "context": ctx},
    )
    assert "error" not in r, r
    resp = SelectResponse(chunks=[])  # decode via iter_rows on raw bytes
    # reconstruct response object from bytes for assertion
    from tikv_tpu.util import codec as c

    data = r["data"]
    nchunks, off = c.decode_var_u64(data, 0)
    chunks = []
    for _ in range(nchunks):
        ln, off = c.decode_var_u64(data, off)
        chunks.append(data[off : off + ln])
        off += ln
    resp = SelectResponse(chunks=chunks)
    assert len(resp.iter_rows()) == 6
    client.close()


def test_pd_tso_and_region_routing():
    pd = MockPd()
    a, b, c = pd.get_tso(), pd.get_tso(), pd.get_tso()
    assert a < b < c
    cluster = Cluster(3, pd=pd)
    cluster.run()
    cluster.must_put(b"k", b"v")
    new_id = cluster.split_region(FIRST_REGION_ID, b"m")
    r = pd.get_region_by_key(b"a")
    assert r is not None and r.id == FIRST_REGION_ID
    r = pd.get_region_by_key(b"z")
    assert r is not None and r.id == new_id


def test_node_auto_split_by_size():
    """PD-worker style auto split when a region exceeds the key threshold."""
    pd = MockPd()
    from tikv_tpu.raft.store import ChannelTransport

    transport = ChannelTransport()
    node = Node(pd, transport, split_threshold_keys=10)
    transport.register(node.store)
    node.try_bootstrap_cluster([node.store_id])
    node.create_region_peers()
    peer = node.store.peers[FIRST_REGION_ID]
    peer.node.campaign()
    node.pump()
    kv = RaftKv(node.store, pump=node.pump)
    storage = Storage(engine=kv)
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    ctx = {"region_id": FIRST_REGION_ID}
    for i in range(30):
        k = b"key%03d" % i
        ts = pd.get_tso()
        storage.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), b"v")], k, ts), ctx)
        storage.sched_txn_command(Commit([Key.from_raw(k)], ts, pd.get_tso()), ctx)
    # trigger the split check directly (the pd_loop does this periodically)
    node._maybe_split(peer)
    node.pump()
    assert len(node.store.peers) == 2
    regions = sorted(p.region.id for p in node.store.peers.values())
    # both regions known to PD after the split report
    for rid in regions:
        assert pd.get_region_by_id(rid) is not None


def test_endpoint_block_cache_serving():
    """Repeated identical requests with a data version hit the block cache."""
    import sys, os

    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_engine
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.rpn import col
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.kv import LocalEngine

    eng = LocalEngine(product_engine())
    ep = Endpoint(eng, enable_device=True)
    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor("count", None), AggDescriptor("sum", col(2))]),
        ]
    )
    req = lambda: CoprRequest(
        103, DagRequest(executors=dag.executors), [record_range(TABLE_ID)], 200,
        context={"region_id": 1, "cache_version": 7},
    )
    r1 = ep.handle_request(req())
    r2 = ep.handle_request(req())
    r3 = ep.handle_request(req())
    assert r1.from_device and not r1.from_cache
    assert r2.from_cache and r3.from_cache
    assert r1.data == r2.data == r3.data
    # a new data version is a cold start again
    r4 = ep.handle_request(
        CoprRequest(103, DagRequest(executors=dag.executors), [record_range(TABLE_ID)], 200,
                    context={"region_id": 1, "cache_version": 8})
    )
    assert not r4.from_cache and r4.data == r1.data
    # CPU fallback agrees byte-for-byte
    ep_cpu = Endpoint(eng, enable_device=False)
    r5 = ep_cpu.handle_request(req())
    assert not r5.from_device and r5.data == r1.data


def test_debug_service_over_wire(single_node):
    """tikv-ctl's debug commands ride the same RPC surface (debug.rs gRPC)."""
    node, server, pd = single_node
    client = Client(*server.addr)
    r = client.call("debug_region_info", {"region_id": FIRST_REGION_ID})
    assert r["info"]["region"]["id"] == FIRST_REGION_ID
    r = client.call("debug_region_properties", {"region_id": FIRST_REGION_ID})
    assert "mvcc" in r["props"]
    r = client.call("debug_bad_regions", {})
    assert r["bad"] == []
    r = client.call("debug_all_regions", {})
    assert FIRST_REGION_ID in r["regions"]
    r = client.call("debug_region_info", {"region_id": 777})
    assert "error" in r
    client.close()


def test_cdc_over_wire(single_node):
    """ChangeData service over real sockets: register -> incremental scan,
    live events with old values, resolved watermarks, pull-resume by seq,
    deregister (reference: cdc/src/service.rs EventFeed adapted to the
    request/response transport)."""
    from tikv_tpu.sidecar.cdc import CdcService

    node, server, pd = single_node
    server.service.cdc = CdcService(node.store)
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}

    def txn(key, value, op="put"):
        ts = pd.get_tso()
        mut = {"op": op, "key": key}
        if value is not None:
            mut["value"] = value
        client.call("kv_prewrite", {"mutations": [mut], "primary_lock": key,
                                    "start_version": ts, "context": ctx})
        client.call("kv_commit", {"keys": [key], "start_version": ts,
                                  "commit_version": pd.get_tso(), "context": ctx})

    txn(b"pre", b"existing")  # before registration: surfaces via scan
    r = client.call("cdc_register", {"region_id": FIRST_REGION_ID,
                                     "checkpoint_ts": pd.get_tso()})
    assert "error" not in r and r["scanned"] >= 1
    sub = r["sub_id"]
    txn(b"live1", b"v1")
    txn(b"live1", b"v2")  # update: old value captured
    txn(b"live1", None, op="delete")
    import time

    deadline = time.time() + 5
    evs = []
    last = 0
    while time.time() < deadline and len(evs) < 4:
        r = client.call("cdc_events", {"sub_id": sub, "after_seq": last})
        assert "error" not in r, r
        evs += [e for e in r["events"] if e["type"] != "resolved"]
        last = max(last, r.get("last_seq", last))
        time.sleep(0.05)
    # the feed delivers the incremental-scan snapshot first, then deltas —
    # the reference's EventFeed ordering
    assert [(e["type"], e["key"]) for e in evs] == [
        ("put", b"pre"),
        ("put", b"live1"), ("put", b"live1"), ("delete", b"live1")
    ]
    assert evs[0]["value"] == b"existing"
    assert evs[1]["old_value"] == b""
    assert evs[2]["old_value"] == b"v1"
    # resolved watermark interleaves
    server.service.cdc.resolved(sub, 999999)
    r = client.call("cdc_events", {"sub_id": sub, "after_seq": last})
    assert any(e["type"] == "resolved" and e["ts"] == 999999 for e in r["events"])
    # pull-resume: acked events are gone
    r2 = client.call("cdc_events", {"sub_id": sub, "after_seq": r["last_seq"]})
    assert r2["events"] == []
    # unknown sub errors cleanly; deregister works
    assert "error" in client.call("cdc_events", {"sub_id": 777})
    client.call("cdc_deregister", {"sub_id": sub})
    assert "error" in client.call("cdc_events", {"sub_id": sub})
    client.close()


def test_flashback_over_wire(single_node):
    node, server, pd = single_node
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}

    def txn(key, value):
        ts = pd.get_tso()
        client.call("kv_prewrite", {"mutations": [{"op": "put", "key": key, "value": value}],
                                    "primary_lock": key, "start_version": ts, "context": ctx})
        client.call("kv_commit", {"keys": [key], "start_version": ts,
                                  "commit_version": pd.get_tso(), "context": ctx})

    txn(b"fb", b"good")
    point = pd.get_tso()
    txn(b"fb", b"bad")
    r = client.call("kv_flashback_to_version", {
        "version": point, "start_ts": pd.get_tso(), "commit_ts": pd.get_tso(), "context": ctx,
    })
    assert r.get("flashback_keys") == 1
    r = client.call("kv_get", {"key": b"fb", "version": pd.get_tso(), "context": ctx})
    assert r["value"] == b"good"
    client.close()


def test_split_readindex_checkleader_over_wire(single_node):
    """Appendix-A surface: split_region, read_index, check_leader handlers."""
    node, server, pd = single_node
    server.service.pd = pd
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    for k in (b"a", b"m", b"z"):
        client.call("raw_put", {"key": k, "value": b"v", "context": ctx})
    r = client.call("kv_read_index", {"context": ctx})
    assert "error" not in r and r["read_index"] > 0
    r = client.call("kv_check_leader", {"regions": [FIRST_REGION_ID, 999]})
    assert r["regions"] == [FIRST_REGION_ID]
    # raw-mode split: boundaries in raw key space
    r = client.call("kv_split_region", {"split_key": b"m", "is_raw_kv": True, "context": ctx})
    assert "error" not in r, r
    new_id = r["new_region_id"]
    import time

    deadline = time.time() + 5
    while time.time() < deadline and new_id not in node.store.peers:
        time.sleep(0.02)
    assert new_id in node.store.peers
    # probe: split at a key now outside the left region
    r2 = client.call("kv_split_region", {"split_key": b"a", "is_raw_kv": True,
                                         "context": {"region_id": new_id}})
    assert "error" in r2  # 'a' not in the right-hand region
    client.close()


def test_txn_split_region_encodes_boundary(single_node):
    """Txn-mode splits memcomparable-encode the boundary, so user keys on
    either side keep routing to the correct region."""
    from tikv_tpu.storage.txn_types import Key as TKey

    node, server, pd = single_node
    server.service.pd = pd
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}

    def txn(key, value):
        ts = pd.get_tso()
        client.call("kv_prewrite", {"mutations": [{"op": "put", "key": key, "value": value}],
                                    "primary_lock": key, "start_version": ts, "context": ctx})
        client.call("kv_commit", {"keys": [key], "start_version": ts,
                                  "commit_version": pd.get_tso(), "context": ctx})

    txn(b"l", b"1")
    txn(b"m", b"2")
    r = client.call("kv_split_region", {"split_key": b"m", "context": ctx})
    assert "error" not in r, r
    new_id = r["new_region_id"]
    import time

    deadline = time.time() + 5
    while time.time() < deadline and new_id not in node.store.peers:
        time.sleep(0.02)
    left = node.store.peers[FIRST_REGION_ID].region
    right = node.store.peers[new_id].region
    # the encoded user key b"m" is the boundary: b"l" routes left, b"m" right
    assert left.contains(TKey.from_raw(b"l").encoded)
    assert right.contains(TKey.from_raw(b"m").encoded)
    # the new region elects a leader under the background loops
    deadline = time.time() + 8
    r = {}
    while time.time() < deadline:
        r = client.call("kv_get", {"key": b"m", "version": pd.get_tso(),
                                   "context": {"region_id": new_id}})
        if r.get("value") == b"2":
            break
        time.sleep(0.1)
    assert r.get("value") == b"2", r
    client.close()


def test_import_sst_over_wire(single_node, tmp_path):
    """ImportSST service: backup -> external storage -> download + ingest
    through the raft propose path, with key-prefix rewrite."""
    from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage, SstImporter
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage as St
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    node, server, pd = single_node
    ext = LocalStorage(str(tmp_path))
    server.service.importer = SstImporter(ext)
    # source cluster: commit keys and back them up
    src_eng = BTreeEngine()
    src = St(engine=LocalEngine(src_eng))
    for i in range(4):
        k = b"old/k%d" % i
        src.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), b"v%d" % i)], k, 10 + i))
        src.sched_txn_command(Commit([Key.from_raw(k)], 10 + i, 20 + i))
    BackupEndpoint(ext).backup_range(src_eng.snapshot(), "dump.bak", backup_ts=100)

    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    # rewrite applies at DOWNLOAD time, like the reference's download API
    r = client.call("import_download", {"name": "dump.bak",
                                        "rewrite_old": b"old/", "rewrite_new": b"new/"})
    assert r.get("kvs") == 4, r
    rts = pd.get_tso()
    r = client.call("import_ingest", {"name": "dump.bak", "restore_ts": rts, "context": ctx})
    assert r.get("kvs") == 4, r
    for i in range(4):
        g = client.call("kv_get", {"key": b"new/k%d" % i, "version": pd.get_tso(), "context": ctx})
        assert g["value"] == b"v%d" % i
    # probe: missing file errors cleanly
    r = client.call("import_download", {"name": "nope.bak"})
    assert "error" in r
    client.close()


def test_cdc_long_poll(single_node):
    """cdc_events with timeout_ms blocks until an event arrives (long-poll)
    instead of returning empty immediately."""
    import threading
    import time

    from tikv_tpu.sidecar.cdc import CdcService

    node, server, pd = single_node
    server.service.cdc = CdcService(node.store)
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    sub = client.call("cdc_register", {"region_id": FIRST_REGION_ID,
                                       "checkpoint_ts": pd.get_tso()})["sub_id"]
    # empty feed + no timeout: immediate return
    t0 = time.time()
    r = client.call("cdc_events", {"sub_id": sub})
    assert r["events"] == [] and time.time() - t0 < 0.5
    # long-poll: a write during the wait unblocks the pull
    got: list = []

    def puller():
        c2 = Client(*server.addr)
        got.append(c2.call("cdc_events", {"sub_id": sub, "timeout_ms": 5000}))
        c2.close()

    t = threading.Thread(target=puller)
    t.start()
    time.sleep(0.2)
    ts = pd.get_tso()
    client.call("kv_prewrite", {"mutations": [{"op": "put", "key": b"lp", "value": b"x"}],
                                "primary_lock": b"lp", "start_version": ts, "context": ctx})
    client.call("kv_commit", {"keys": [b"lp"], "start_version": ts,
                              "commit_version": pd.get_tso(), "context": ctx})
    t.join(timeout=6)
    assert got and any(e["type"] == "put" for e in got[0]["events"])
    client.close()


def test_import_ingest_retry_uses_staged_bytes(single_node, tmp_path):
    """A failed ingest retried must consume the SAME rewritten staged bytes,
    and supplying the rewrite on both calls must not double-apply."""
    from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage, SstImporter
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage as St
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    node, server, pd = single_node
    ext = LocalStorage(str(tmp_path))
    imp = SstImporter(ext)
    server.service.importer = imp
    src_eng = BTreeEngine()
    src = St(engine=LocalEngine(src_eng))
    src.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"a-key"), b"v")], b"a-key", 10))
    src.sched_txn_command(Commit([Key.from_raw(b"a-key")], 10, 11))
    BackupEndpoint(ext).backup_range(src_eng.snapshot(), "r.bak", backup_ts=100)
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    client.call("import_download", {"name": "r.bak", "rewrite_old": b"a-", "rewrite_new": b"ab-"})
    # first ingest fails (bad region) -> staged bytes retained
    r = client.call("import_ingest", {"name": "r.bak", "restore_ts": pd.get_tso(),
                                      "context": {"region_id": 777}})
    assert "error" in r
    # retry WITH the rewrite repeated: staged bytes win, no double-apply
    r = client.call("import_ingest", {"name": "r.bak", "restore_ts": pd.get_tso(), "context": ctx,
                                      "rewrite_old": b"a-", "rewrite_new": b"ab-"})
    assert r.get("kvs") == 1, r
    g = client.call("kv_get", {"key": b"ab-key", "version": pd.get_tso(), "context": ctx})
    assert g["value"] == b"v"
    g = client.call("kv_get", {"key": b"abb-key", "version": pd.get_tso(), "context": ctx})
    assert g.get("value") is None  # double-applied prefix never exists
    client.close()


def test_import_ingest_after_staged_eviction_reapplies_rewrite(single_node, tmp_path):
    """If staged (rewritten) bytes were evicted before ingest, the fallback
    source re-read must re-apply the rewrite registered at download time —
    never silently ingest un-rewritten keys."""
    from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage, SstImporter
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage as St
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    node, server, pd = single_node
    ext = LocalStorage(str(tmp_path))
    imp = SstImporter(ext)
    server.service.importer = imp
    src_eng = BTreeEngine()
    src = St(engine=LocalEngine(src_eng))
    src.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"a-key"), b"v")], b"a-key", 10))
    src.sched_txn_command(Commit([Key.from_raw(b"a-key")], 10, 11))
    BackupEndpoint(ext).backup_range(src_eng.snapshot(), "ev.bak", backup_ts=100)
    client = Client(*server.addr)
    ctx = {"region_id": FIRST_REGION_ID}
    client.call("import_download", {"name": "ev.bak", "rewrite_old": b"a-", "rewrite_new": b"ab-"})
    # simulate eviction of the staged bytes (keeps the rewrite record)
    with imp._mu:
        imp._staged.pop("ev.bak")
    r = client.call("import_ingest", {"name": "ev.bak", "restore_ts": pd.get_tso(),
                                      "context": ctx})
    assert r.get("kvs") == 1, r
    g = client.call("kv_get", {"key": b"ab-key", "version": pd.get_tso(), "context": ctx})
    assert g["value"] == b"v"  # rewrite applied despite eviction
    g = client.call("kv_get", {"key": b"a-key", "version": pd.get_tso(), "context": ctx})
    assert g.get("value") is None  # un-rewritten key never ingested
    client.close()
