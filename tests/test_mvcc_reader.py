"""MVCC read-path tests (reference: mvcc/reader/{point_getter,scanner} tests)."""

import pytest

from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.mvcc import (
    BackwardScanner,
    ForwardScanner,
    IsolationLevel,
    KeyIsLockedError,
    MvccReader,
    PointGetter,
)
from tikv_tpu.storage.txn_types import Key, LockType

from fixtures import delete_committed, lock_key, put_committed, put_committed_large, rollback


@pytest.fixture
def engine():
    e = BTreeEngine()
    # k1: v1@(5,10), v2@(15,20), deleted@(25,30)
    put_committed(e, b"k1", b"v1", 5, 10)
    put_committed(e, b"k1", b"v2", 15, 20)
    delete_committed(e, b"k1", 25, 30)
    # k2: large value in CF_DEFAULT
    put_committed_large(e, b"k2", b"big" * 200, 6, 12)
    # k3: only a rollback
    rollback(e, b"k3", 8)
    # k4: committed then rolled-back attempt on top
    put_committed(e, b"k4", b"v4", 5, 9)
    rollback(e, b"k4", 14)
    return e


def get(e, key, ts, **kw):
    return PointGetter(e.snapshot(), ts, **kw).get(Key.from_raw(key))


def test_point_get_versions(engine):
    assert get(engine, b"k1", 9) is None
    assert get(engine, b"k1", 10) == b"v1"
    assert get(engine, b"k1", 19) == b"v1"
    assert get(engine, b"k1", 20) == b"v2"
    assert get(engine, b"k1", 29) == b"v2"
    assert get(engine, b"k1", 30) is None
    assert get(engine, b"k1", 100) is None


def test_point_get_large_value(engine):
    assert get(engine, b"k2", 12) == b"big" * 200
    assert get(engine, b"k2", 11) is None


def test_point_get_skips_rollback(engine):
    assert get(engine, b"k3", 100) is None
    assert get(engine, b"k4", 100) == b"v4"  # rollback@14 skipped to PUT@9


def test_point_get_missing_key(engine):
    assert get(engine, b"nope", 100) is None


def test_locked_key_blocks_si_read(engine):
    lock_key(engine, b"k1", b"k1", start_ts=40)
    with pytest.raises(KeyIsLockedError):
        get(engine, b"k1", 50)
    # read below lock ts passes
    assert get(engine, b"k1", 25) == b"v2"
    # bypassing the lock passes
    assert get(engine, b"k1", 50, bypass_locks=frozenset([40])) is None
    # RC ignores locks
    assert get(engine, b"k1", 50, isolation=IsolationLevel.RC) is None


def test_lock_and_pessimistic_locks_do_not_block(engine):
    lock_key(engine, b"k1", b"k1", start_ts=40, lock_type=LockType.LOCK)
    assert get(engine, b"k1", 50) is None
    lock_key(engine, b"k4", b"k4", start_ts=40, lock_type=LockType.PESSIMISTIC)
    assert get(engine, b"k4", 50) == b"v4"


def scan_fwd(e, ts, start=b"", end=None, **kw):
    s = None if start == b"" else Key.from_raw(start)
    en = Key.from_raw(end) if end is not None else None
    return list(ForwardScanner(e.snapshot(), ts, s, en, **kw))


def scan_bwd(e, ts, start=b"", end=None, **kw):
    s = None if start == b"" else Key.from_raw(start)
    en = Key.from_raw(end) if end is not None else None
    return list(BackwardScanner(e.snapshot(), ts, s, en, **kw))


def test_forward_scan(engine):
    assert scan_fwd(engine, 100) == [(b"k2", b"big" * 200), (b"k4", b"v4")]
    assert scan_fwd(engine, 25) == [(b"k1", b"v2"), (b"k2", b"big" * 200), (b"k4", b"v4")]
    assert scan_fwd(engine, 10) == [(b"k1", b"v1"), (b"k4", b"v4")]
    assert scan_fwd(engine, 5) == []


def test_forward_scan_range(engine):
    assert scan_fwd(engine, 25, start=b"k2") == [(b"k2", b"big" * 200), (b"k4", b"v4")]
    assert scan_fwd(engine, 25, end=b"k2") == [(b"k1", b"v2")]
    assert scan_fwd(engine, 25, start=b"k1", end=b"k2") == [(b"k1", b"v2")]


def test_forward_scan_key_only(engine):
    assert scan_fwd(engine, 25, key_only=True) == [(b"k1", b""), (b"k2", b""), (b"k4", b"")]


def test_forward_scan_lock_check(engine):
    lock_key(engine, b"k2", b"k2", start_ts=40)
    with pytest.raises(KeyIsLockedError):
        scan_fwd(engine, 50)
    assert scan_fwd(engine, 50, isolation=IsolationLevel.RC) == [(b"k2", b"big" * 200), (b"k4", b"v4")]
    # range not covering the locked key is unaffected
    assert scan_fwd(engine, 50, start=b"k3") == [(b"k4", b"v4")]


def test_backward_scan(engine):
    assert scan_bwd(engine, 100) == [(b"k4", b"v4"), (b"k2", b"big" * 200)]
    assert scan_bwd(engine, 25) == [(b"k4", b"v4"), (b"k2", b"big" * 200), (b"k1", b"v2")]
    assert scan_bwd(engine, 25, end=b"k2") == [(b"k1", b"v2")]
    assert scan_bwd(engine, 25, start=b"k2") == [(b"k4", b"v4"), (b"k2", b"big" * 200)]


def test_mvcc_reader_helpers(engine):
    r = MvccReader(engine.snapshot())
    k1 = Key.from_raw(b"k1")
    # seek_write finds newest <= ts
    commit_ts, w = r.seek_write(k1, 25)
    assert commit_ts == 20 and w.start_ts == 15
    assert r.seek_write(k1, 9) is None
    # txn commit record search
    recs = r.get_txn_commit_record(k1, 15)
    assert [(c, w.write_type.name) for c, w in recs] == [(20, "PUT")]
    lock_key(engine, b"k9", b"k9", start_ts=77)
    r2 = MvccReader(engine.snapshot())
    assert r2.load_lock(Key.from_raw(b"k9")).ts == 77
    locks = r2.scan_locks(None, None)
    assert [k.to_raw() for k, _ in locks] == [b"k9"]
    assert r2.stats.lock.get == 1


def test_statistics_tracked(engine):
    from tikv_tpu.storage.mvcc import Statistics

    stats = Statistics()
    PointGetter(engine.snapshot(), 100, statistics=stats).get(Key.from_raw(b"k1"))
    assert stats.write.seek >= 1
    assert stats.total_ops() > 0


def test_scan_blocked_by_lock_on_writeless_key(engine):
    """A prewritten brand-new key (lock, no write record) must block scans."""
    lock_key(engine, b"k15", b"k15", start_ts=40)  # no CF_WRITE entry for k15
    with pytest.raises(KeyIsLockedError):
        scan_fwd(engine, 50)
    with pytest.raises(KeyIsLockedError):
        scan_bwd(engine, 50)
    # below the lock ts, or bypassing it, the scan proceeds
    assert scan_fwd(engine, 25) == [(b"k1", b"v2"), (b"k2", b"big" * 200), (b"k4", b"v4")]
    assert len(scan_fwd(engine, 50, bypass_locks=frozenset([40]))) == 2
