"""Unified coprocessor read scheduler (copr/scheduler.py): cross-region
continuous batching, mixed-eligibility handle_batch, admission control,
and the fused-batch metrics contract.

Every batched response must be byte-identical to the per-request CPU
pipeline — the scheduler only ever removes dispatches, never changes bytes.
"""

import threading

import numpy as np
import pytest

from tikv_tpu.copr import jax_eval
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, Limit, Selection, TableScan
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.scheduler import SchedulerConfig, plan_signature
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util.metrics import REGISTRY

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID as PRODUCT_TABLE, product_engine
from tikv_tpu.copr.table import record_range

TABLE_ID = 77

COLS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),
    ColumnInfo(3, FieldType.varchar()),
    ColumnInfo(4, FieldType.decimal_type(2)),
]


def _engine(n: int, seed: int = 0) -> BTreeEngine:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 100, n)
    price = rng.integers(100, 100000, n)
    names = (b"x", b"y", b"z")
    eng = BTreeEngine()
    items = []
    for i in range(n):
        rk = record_key(TABLE_ID, i)
        val = encode_row(COLS[1:], [int(a[i]), names[i % 3], int(price[i])])
        items.append((Key.from_raw(rk).append_ts(20).encoded,
                      Write(WriteType.PUT, 10, short_value=val).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    return eng


def _sum_dag(cut: int) -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("lt", col(1), const_int(cut))]),
        Aggregation([], [AggDescriptor("sum", col(3)),
                         AggDescriptor("count", None)]),
    ])


def _group_dag() -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Aggregation([col(2)], [AggDescriptor("sum", col(1)),
                               AggDescriptor("count", None)]),
    ])


def _scan_dag() -> DagRequest:
    return DagRequest(executors=[TableScan(TABLE_ID, COLS), Limit(10)])


def _region_req(region: int, rows_per: int, dag: DagRequest,
                priority: str | None = None, apply_index: int = 7) -> CoprRequest:
    lo = record_key(TABLE_ID, region * rows_per)
    hi = record_key(TABLE_ID, (region + 1) * rows_per)
    ctx = {"region_id": region + 1, "region_epoch": (1, 1),
           "apply_index": apply_index}
    if priority is not None:
        ctx["priority"] = priority
    return CoprRequest(103, dag, [(lo, hi)], 100, context=ctx)


ROWS_PER = 600
N_REGIONS = 4


@pytest.fixture(scope="module")
def engines():
    eng = _engine(ROWS_PER * N_REGIONS, seed=5)
    dev = Endpoint(LocalEngine(eng), enable_device=True, block_rows=1024)
    cpu = Endpoint(LocalEngine(eng), enable_device=False)
    return dev, cpu


def test_plan_signature_groups_same_plans():
    assert plan_signature(_sum_dag(50)) == plan_signature(_sum_dag(50))
    assert plan_signature(_sum_dag(50)) != plan_signature(_sum_dag(51))
    assert plan_signature(_sum_dag(50)) != plan_signature(_group_dag())


def test_plan_signature_normalizes_wire_sigs():
    """A tipb ScalarFuncSig spelling and its kernel name key identically
    (sig_map is the single source of truth for the fold)."""
    a = DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("LtInt", col(1), const_int(9))]),
        Aggregation([], [AggDescriptor("count", None)]),
    ])
    b = DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("lt", col(1), const_int(9))]),
        Aggregation([], [AggDescriptor("count", None)]),
    ])
    assert plan_signature(a) == plan_signature(b)


def test_xregion_batch_byte_identical(engines):
    """Same plan across regions collapses into one cross-region program;
    responses match the CPU pipeline byte for byte (group order included)."""
    dev, cpu = engines
    dags = [lambda: _sum_dag(50), lambda: _sum_dag(80), _group_dag]
    reqs = [_region_req(r, ROWS_PER, d()) for d in dags for r in range(N_REGIONS)]
    # warm (fills region images + compiles)
    dev.handle_batch([_region_req(r, ROWS_PER, d())
                      for d in dags for r in range(N_REGIONS)])
    before = REGISTRY.counter("tikv_coprocessor_sched_batches_total", "").get(
        kind="xregion")
    got = dev.handle_batch(reqs)
    after = REGISTRY.counter("tikv_coprocessor_sched_batches_total", "").get(
        kind="xregion")
    assert after >= before + 3  # one cross-region batch per signature
    assert all(r.from_device for r in got)
    for req, resp in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(103, req.dag, req.ranges, req.start_ts, dict(req.context)))
        assert resp.data == want.data
    # scheduler metadata rides the response
    assert any(r.metrics.get("sched_batch") == "xregion" for r in got)
    occ = [r.metrics.get("batch_occupancy") for r in got
           if r.metrics.get("sched_batch") == "xregion"]
    assert occ and all(o >= 2 for o in occ)


def test_xregion_dedupes_identical_requests(engines):
    """Identical hot requests from many clients share one execution slot."""
    dev, cpu = engines
    reqs = [_region_req(r, ROWS_PER, _sum_dag(42))
            for r in range(N_REGIONS) for _ in range(3)]
    got = dev.handle_batch(reqs)
    want = {r: cpu.handle_request(_region_req(r, ROWS_PER, _sum_dag(42))).data
            for r in range(N_REGIONS)}
    for req, resp in zip(reqs, got):
        assert resp.data == want[req.context["region_id"] - 1]
    # 12 requests, but the batch occupancy counts the 12 (shared slots serve
    # every rider), all from one device dispatch
    assert all(r.from_device for r in got)


def test_mixed_eligibility_batch(engines):
    """Ineligible requests (non-agg DAG, checksum) ride the same batch and
    answer per-request; order is preserved; eligible ones still fuse."""
    dev, cpu = engines
    reqs = [
        _region_req(0, ROWS_PER, _sum_dag(50)),
        _region_req(1, ROWS_PER, _scan_dag()),       # no aggregation
        _region_req(1, ROWS_PER, _sum_dag(50)),
        CoprRequest(105, None, [record_range(TABLE_ID)], 100, context={}),
        _region_req(2, ROWS_PER, _sum_dag(50)),
    ]
    got = dev.handle_batch(reqs)
    assert len(got) == len(reqs)
    for req, resp in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(req.tp, req.dag, req.ranges, req.start_ts,
                        dict(req.context or {})))
        assert resp.data == want.data
    assert got[0].from_device and got[2].from_device and got[4].from_device
    assert not got[3].from_device


def test_priority_lane_stamped(engines):
    dev, _cpu = engines
    reqs = [_region_req(r, ROWS_PER, _sum_dag(50), priority="high")
            for r in range(N_REGIONS)]
    got = dev.handle_batch(reqs)
    lanes = {r.metrics.get("sched_lane") for r in got}
    assert lanes == {"high"}


def test_cold_cache_first_fill_then_fused():
    """cache_version-keyed block cache, cold: the first request fills the
    shared cache per-request, the rest fuse — every response byte-identical
    to the CPU pipeline (the pre-scheduler _try_fused_batch contract)."""
    eng = LocalEngine(product_engine())
    dev = Endpoint(eng, enable_device=True)
    cpu = Endpoint(eng, enable_device=False)

    def agg_dag(fn, target):
        return DagRequest(executors=[
            TableScan(PRODUCT_TABLE, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor(fn, col(target))]),
        ])

    dags = [agg_dag("count", 0), agg_dag("sum", 0), agg_dag("max", 0),
            agg_dag("min", 2)]
    ctx = {"region_id": 1, "cache_version": 3}
    reqs = [CoprRequest(103, d, [record_range(PRODUCT_TABLE)], 200, dict(ctx))
            for d in dags]
    resps = dev.handle_batch(reqs)
    assert all(r.from_device for r in resps)
    kinds = [r.metrics.get("sched_batch") for r in resps]
    assert kinds[0] == "fill" and all(k == "fused" for k in kinds[1:]), kinds
    for d, got in zip(dags, resps):
        want = cpu.handle_request(
            CoprRequest(103, d, [record_range(PRODUCT_TABLE)], 200, dict(ctx)))
        assert got.data == want.data


def test_fused_latency_one_observation_per_request():
    """The duration histogram gets ONE observation per fused request (not a
    single mean observation), so count-weighted percentiles stay honest
    against the unary path."""
    eng = LocalEngine(product_engine())
    dev = Endpoint(eng, enable_device=True)

    def agg_dag(fn):
        return DagRequest(executors=[
            TableScan(PRODUCT_TABLE, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor(fn, col(0))]),
        ])

    ctx = {"region_id": 1, "cache_version": 9}
    reqs = [CoprRequest(103, agg_dag(fn), [record_range(PRODUCT_TABLE)], 200,
                        dict(ctx)) for fn in ("count", "sum", "max")]
    dev.handle_batch(reqs)  # cold: fill + fuse
    h = REGISTRY.histogram("tikv_coprocessor_request_duration_seconds", "")
    key = (("tp", "103"),)
    before = h._n.get(key, 0)
    resps = dev.handle_batch(reqs)  # warm: all three fuse
    assert all(r.from_device for r in resps)
    assert h._n.get(key, 0) >= before + len(reqs)


def test_device_failure_mid_batch_falls_back(engines, monkeypatch):
    """A device failure during the cross-region program sheds every slot to
    the per-request path — responses stay correct and nothing is lost."""
    dev, cpu = engines
    reqs = [_region_req(r, ROWS_PER, _sum_dag(60)) for r in range(N_REGIONS)]
    dev.handle_batch([_region_req(r, ROWS_PER, _sum_dag(60))
                      for r in range(N_REGIONS)])  # warm images

    def boom(*a, **k):
        raise RuntimeError("device lost mid-batch")

    monkeypatch.setattr(jax_eval, "launch_xregion_cached", boom)
    fallbacks = dev.device_fallbacks
    got = dev.handle_batch(reqs)
    assert dev.device_fallbacks > fallbacks
    for req, resp in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(103, req.dag, req.ranges, req.start_ts, dict(req.context)))
        assert resp.data == want.data
    monkeypatch.undo()
    # the region images survived the failure: next batch is fused again
    got2 = dev.handle_batch(reqs)
    assert all(r.from_device for r in got2)
    assert any(r.metrics.get("sched_batch") == "xregion" for r in got2)


def test_cold_fill_failure_leaves_no_partial_cache(monkeypatch):
    """A device failure during the cold fill must not leave a partially
    filled block cache behind (it would double-append and serve wrong data
    forever)."""
    eng = LocalEngine(product_engine())
    dev = Endpoint(eng, enable_device=True)
    cpu = Endpoint(eng, enable_device=False)

    def agg_dag(fn):
        return DagRequest(executors=[
            TableScan(PRODUCT_TABLE, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor(fn, col(0))]),
        ])

    ctx = {"region_id": 1, "cache_version": 77}
    reqs = [CoprRequest(103, agg_dag(fn), [record_range(PRODUCT_TABLE)], 200,
                        dict(ctx)) for fn in ("count", "sum")]

    calls = {"n": 0}
    orig = jax_eval.JaxDagEvaluator.run

    def failing_run(self, source, cache=None):
        calls["n"] += 1
        if cache is not None and not cache.filled:
            # crash mid-fill, after blocks were appended
            for cols, n_valid in self._blocks(source):
                break
            raise RuntimeError("device died during fill")
        return orig(self, source, cache=cache)

    monkeypatch.setattr(jax_eval.JaxDagEvaluator, "run", failing_run)
    got = dev.handle_batch(reqs)
    monkeypatch.undo()
    for req, resp in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(103, req.dag, req.ranges, req.start_ts, dict(req.context)))
        assert resp.data == want.data
    cache = dev._block_cache_for(reqs[0])
    assert cache.filled or not cache.blocks, "partially-filled cache left behind"


def test_padding_budget_sheds_block_count_outlier():
    """One region with 8x the blocks of its peers sheds to the per-request
    path instead of padding every peer up to its geometry."""
    eng = _engine(ROWS_PER * 8, seed=9)
    # tiny blocks so region 0's wider range spans many blocks
    dev = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256,
                   sched_config=SchedulerConfig(padding_budget=0.5))
    cpu = Endpoint(LocalEngine(eng), enable_device=False)
    big = CoprRequest(103, _sum_dag(70),
                      [(record_key(TABLE_ID, 0), record_key(TABLE_ID, 5 * ROWS_PER))],
                      100, context={"region_id": 1, "region_epoch": (1, 1),
                                    "apply_index": 7})
    smalls = [CoprRequest(
        103, _sum_dag(70),
        [(record_key(TABLE_ID, (5 + i) * ROWS_PER),
          record_key(TABLE_ID, (6 + i) * ROWS_PER))],
        100, context={"region_id": 10 + i, "region_epoch": (1, 1),
                      "apply_index": 7}) for i in range(3)]
    reqs = [big] + smalls
    dev.handle_batch([CoprRequest(r.tp, r.dag, r.ranges, r.start_ts,
                                  dict(r.context)) for r in reqs])  # warm
    before = REGISTRY.counter("tikv_coprocessor_sched_shed_total", "").get(
        reason="padding")
    got = dev.handle_batch(reqs)
    after = REGISTRY.counter("tikv_coprocessor_sched_shed_total", "").get(
        reason="padding")
    assert after > before
    assert got[0].metrics.get("sched_batch", "").startswith("shed:padding")
    assert all(r.metrics.get("sched_batch") == "xregion" for r in got[1:])
    for req, resp in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(103, req.dag, req.ranges, req.start_ts, dict(req.context)))
        assert resp.data == want.data


def test_aliased_image_slots_keep_snapshot_isolation():
    """Two requests over the SAME region at different start_ts around a
    write: the region cache holds ONE mutable image per (region, ranges,
    schema), so resolving the later request delta-applies it in place.
    Only the last resolution may batch; the earlier one must shed and still
    return the bytes its snapshot demands."""
    rows = ROWS_PER * 2
    eng = _engine(rows, seed=13)
    dev = Endpoint(LocalEngine(eng), enable_device=True, block_rows=1024)
    cpu = Endpoint(LocalEngine(eng), enable_device=False)

    def rq(ts, apply_index):
        return CoprRequest(103, _sum_dag(95),
                           [(record_key(TABLE_ID, 0), record_key(TABLE_ID, rows))],
                           ts, context={"region_id": 1, "region_epoch": (1, 1),
                                        "apply_index": apply_index})

    dev.handle_request(rq(100, 7))  # build the image at ts 100
    # overwrite a row at commit ts 150
    val = encode_row(COLS[1:], [1, b"zz", 424242])
    eng.bulk_load(CF_WRITE, [(
        Key.from_raw(record_key(TABLE_ID, 3)).append_ts(150).encoded,
        Write(WriteType.PUT, 140, short_value=val).to_bytes())])
    before = REGISTRY.counter("tikv_coprocessor_sched_shed_total", "").get(
        reason="aliased_image")
    got = dev.handle_batch([rq(100, 7), rq(200, 8)])
    after = REGISTRY.counter("tikv_coprocessor_sched_shed_total", "").get(
        reason="aliased_image")
    assert after > before
    want_old = cpu.handle_request(rq(100, 7))
    want_new = cpu.handle_request(rq(200, 8))
    assert got[0].data == want_old.data, "ts-100 reader saw the ts-150 write"
    assert got[1].data == want_new.data
    assert want_old.data != want_new.data  # the write is actually visible at 200


def test_continuous_mode_coalesces_across_threads(engines):
    """start() turns on the continuous lanes: concurrent unary submissions
    coalesce into scheduler batches and every caller gets its own bytes."""
    dev, cpu = engines
    sched = dev.scheduler
    # slow lanes a little so the submissions actually meet in one batch
    sched.cfg.max_wait_s = 0.05
    sched.start()
    try:
        want = {r: cpu.handle_request(_region_req(r, ROWS_PER, _sum_dag(33))).data
                for r in range(N_REGIONS)}
        dev.handle_batch([_region_req(r, ROWS_PER, _sum_dag(33))
                          for r in range(N_REGIONS)])  # warm images/compile
        results: dict[int, bytes] = {}
        errors: list = []

        def client(r):
            try:
                resp = sched.execute(_region_req(r, ROWS_PER, _sum_dag(33)),
                                     timeout=30.0)
                results[r] = resp.data
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=client, args=(r,))
                   for r in range(N_REGIONS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
        assert not errors, errors
        assert results == want
    finally:
        sched.stop()
    assert not sched.running


def test_continuous_mode_isolates_per_request_errors(engines, monkeypatch):
    """One rider's failure (lock conflict, decode error) must not poison the
    other requests that coalesced into the same dispatcher batch."""
    dev, cpu = engines
    sched = dev.scheduler
    sched.cfg.max_wait_s = 0.05
    orig = type(dev).handle_request

    def failing(self, req):
        if (req.context or {}).get("region_id") == 99:
            raise RuntimeError("injected per-request failure")
        return orig(self, req)

    monkeypatch.setattr(type(dev), "handle_request", failing)
    dev.handle_batch([_region_req(r, ROWS_PER, _sum_dag(37))
                      for r in range(N_REGIONS)])  # warm
    sched.start()
    try:
        results: dict[int, bytes] = {}
        errs: dict[int, BaseException] = {}

        def client(r, req):
            try:
                results[r] = sched.execute(req, timeout=30.0).data
            except BaseException as e:  # noqa: BLE001
                errs[r] = e

        bad = CoprRequest(103, _sum_dag(37), [(record_key(TABLE_ID, 0),
                                               record_key(TABLE_ID, 10))],
                          100, context={"region_id": 99})  # no cache -> shed
        reqs = [(r, _region_req(r, ROWS_PER, _sum_dag(37)))
                for r in range(N_REGIONS)] + [(99, bad)]
        threads = [threading.Thread(target=client, args=a) for a in reqs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    finally:
        sched.stop()
    assert isinstance(errs.get(99), RuntimeError)
    for r in range(N_REGIONS):
        assert r not in errs, f"rider {r} poisoned by region 99's failure: {errs.get(r)}"
        assert results[r] == cpu.handle_request(
            _region_req(r, ROWS_PER, _sum_dag(37))).data


def test_concurrent_queue_full_and_busy_reject_exactly_once(engines):
    """ISSUE 15 satellite: concurrent execute() callers racing a FULL
    queue each get exactly ONE typed outcome — batched/direct serve with
    correct bytes, or a busy rejection — with no lost wakeups (every call
    returns) and no double-counted sheds (the busy counter moves once per
    observed rejection)."""
    from tikv_tpu.util import failpoint
    from tikv_tpu.util.retry import ServerBusyError

    dev, cpu = engines
    sched = dev.scheduler
    old_cfg = sched.cfg
    rq = lambda: _region_req(0, ROWS_PER, _sum_dag(21))
    want = cpu.handle_request(rq()).data
    dev.handle_request(rq())  # warm image + compile
    shed = REGISTRY.counter("tikv_coprocessor_sched_shed_total")
    coalesce = REGISTRY.counter("tikv_wire_coalesce_total")
    N_THREADS, N_CALLS = 8, 6

    def drive():
        outcomes: list[str] = []
        mu = threading.Lock()

        def worker():
            for _ in range(N_CALLS):
                try:
                    r = sched.execute(rq(), timeout=30.0)
                    out = "served" if r.data == want else "wrong"
                except ServerBusyError:
                    out = "busy"
                with mu:
                    outcomes.append(out)

        threads = [threading.Thread(target=worker) for _ in range(N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        return outcomes

    # --- busy_reject: rejections are typed, counted exactly once ---
    sched.cfg = SchedulerConfig(max_queue=2, busy_reject=True)
    failpoint.cfg("sched_dispatch", "sleep(10)")  # keep the queue racing
    sched.start()
    try:
        busy0 = shed.get(reason="busy_reject")
        cbusy0 = coalesce.get(outcome="busy_reject")
        outcomes = drive()
        assert len(outcomes) == N_THREADS * N_CALLS, "a caller lost its wakeup"
        assert "wrong" not in outcomes
        n_busy = outcomes.count("busy")
        assert n_busy > 0, "the race never hit the full queue"
        assert shed.get(reason="busy_reject") == busy0 + n_busy, \
            "each rejection must count exactly once"
        assert coalesce.get(outcome="busy_reject") == cbusy0 + n_busy
    finally:
        failpoint.remove("sched_dispatch")
        sched.stop()

    # --- queue_full (busy_reject off): every racing caller is SERVED ---
    sched.cfg = SchedulerConfig(max_queue=1)
    failpoint.cfg("sched_dispatch", "sleep(10)")
    sched.start()
    try:
        qf0 = shed.get(reason="queue_full")
        cqf0 = coalesce.get(outcome="queue_full")
        outcomes = drive()
        assert len(outcomes) == N_THREADS * N_CALLS
        assert set(outcomes) == {"served"}, \
            "queue_full without busy_reject serves on the caller's thread"
        n_qf = shed.get(reason="queue_full") - qf0
        assert n_qf > 0, "the race never hit the full queue"
        assert coalesce.get(outcome="queue_full") == cqf0 + n_qf, \
            "direct-path sheds must count once on each series"
    finally:
        failpoint.remove("sched_dispatch")
        sched.stop()
        sched.cfg = old_cfg


def test_scheduler_stop_drains_queue(engines):
    dev, _cpu = engines
    sched = dev.scheduler
    sched.start()
    sched.stop()
    assert not sched.running
    # stopped scheduler serves directly
    resp = sched.execute(_region_req(0, ROWS_PER, _sum_dag(21)))
    assert resp.data


def test_mesh_serves_warm_cache_no_bypass(engines):
    """The PR-2 cache→mesh bypass is GONE: with a real mesh, a warm cached
    aggregation request serves THROUGH the sharded launcher
    (``mesh_cache_hit`` counts it), byte-identical to the meshless path;
    a plan with no mesh merge rule (``first``) declines to the
    single-device warm path without touching the counter."""
    from tikv_tpu.parallel.mesh import make_mesh

    dev, cpu = engines
    mesh_ep = Endpoint(LocalEngine(dev.engine.kv), enable_device=True,
                       block_rows=1024, mesh=make_mesh(groups=2))
    req = lambda d: _region_req(0, ROWS_PER, d)
    mesh_ep.handle_request(req(_sum_dag(44)))  # warm image (miss)
    before = REGISTRY.counter("tikv_coprocessor_mesh_cache_hit_total", "").get()
    resp = mesh_ep.handle_request(req(_sum_dag(44)))
    after = REGISTRY.counter("tikv_coprocessor_mesh_cache_hit_total", "").get()
    assert resp.from_device and resp.from_cache
    assert after == before + 1
    assert resp.data == cpu.handle_request(req(_sum_dag(44))).data
    # no merge rule for `first` -> documented decline, single-device warm
    first_dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Aggregation([], [AggDescriptor("first", col(1)),
                         AggDescriptor("count", None)]),
    ])
    mesh_ep.handle_request(req(first_dag))  # warm its image
    b2 = REGISTRY.counter("tikv_coprocessor_mesh_cache_hit_total", "").get()
    r2 = mesh_ep.handle_request(req(first_dag))
    assert r2.from_device
    assert REGISTRY.counter("tikv_coprocessor_mesh_cache_hit_total", "").get() == b2
    assert r2.data == cpu.handle_request(req(first_dag)).data
