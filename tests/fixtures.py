"""Test fixtures: write MVCC-shaped data directly into an engine.

Stands in for the reference's must_prewrite/must_commit test helpers until the
txn layer exists; afterwards these remain the low-level way to construct
arbitrary (including pathological) CF states.
"""

from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE, WriteBatch
from tikv_tpu.storage.txn_types import (
    Key,
    Lock,
    LockType,
    Write,
    WriteType,
)

SHORT_VALUE_MAX_LEN = 255


def put_committed(
    engine: BTreeEngine,
    raw_key: bytes,
    value: bytes,
    start_ts: int,
    commit_ts: int,
) -> None:
    k = Key.from_raw(raw_key)
    wb = WriteBatch()
    if len(value) <= SHORT_VALUE_MAX_LEN:
        w = Write(WriteType.PUT, start_ts, short_value=value)
    else:
        w = Write(WriteType.PUT, start_ts)
        wb.put_cf(CF_DEFAULT, k.append_ts(start_ts).encoded, value)
    wb.put_cf(CF_WRITE, k.append_ts(commit_ts).encoded, w.to_bytes())
    engine.write(wb)


def put_committed_large(engine, raw_key, value, start_ts, commit_ts):
    """Force the value into CF_DEFAULT even if short."""
    k = Key.from_raw(raw_key)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, k.append_ts(start_ts).encoded, value)
    wb.put_cf(CF_WRITE, k.append_ts(commit_ts).encoded, Write(WriteType.PUT, start_ts).to_bytes())
    engine.write(wb)


def delete_committed(engine, raw_key, start_ts, commit_ts):
    k = Key.from_raw(raw_key)
    engine.put_cf(CF_WRITE, k.append_ts(commit_ts).encoded, Write(WriteType.DELETE, start_ts).to_bytes())


def rollback(engine, raw_key, start_ts, protected=False):
    k = Key.from_raw(raw_key)
    engine.put_cf(
        CF_WRITE, k.append_ts(start_ts).encoded, Write.new_rollback(start_ts, protected).to_bytes()
    )


def lock_key(
    engine,
    raw_key,
    primary: bytes,
    start_ts: int,
    lock_type: LockType = LockType.PUT,
    ttl: int = 0,
    **kwargs,
) -> Lock:
    k = Key.from_raw(raw_key)
    lock = Lock(lock_type, primary, start_ts, ttl, **kwargs)
    engine.put_cf(CF_LOCK, k.encoded, lock.to_bytes())
    return lock
