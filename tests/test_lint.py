"""Project linter (analysis/lint.py): each rule on synthetic modules, the
waiver machinery, and the zero-findings gate on the real tree."""

import textwrap
from pathlib import Path

import pytest

from tikv_tpu.analysis import lint


def _lint_src(tmp_path: Path, src: str, rel: str = "tikv_tpu/mod.py",
              drift: bool = False, metrics: dict | None = None):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    if metrics:
        mdir = tmp_path / "metrics"
        mdir.mkdir(exist_ok=True)
        for name, content in metrics.items():
            (mdir / name).write_text(content)
    return lint.run([str(p.parent)], root=tmp_path, drift=drift)


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# lock-blocking-call
# ---------------------------------------------------------------------------

def test_direct_blocking_under_lock(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading, time
        class C:
            def __init__(self):
                self._mu = threading.Lock()
            def bad(self):
                with self._mu:
                    time.sleep(1)
            def good(self):
                time.sleep(1)
                with self._mu:
                    pass
    """)
    assert _rules(active) == ["lock-blocking-call"]
    assert "time.sleep" in active[0].message


def test_transitive_blocking_through_self_call(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class C:
            def __init__(self, engine):
                self._mu = threading.Lock()
                self.engine = engine
            def _write_out(self):
                self.engine.write(None)
            def bad(self):
                with self._mu:
                    self._write_out()
    """)
    assert _rules(active) == ["lock-blocking-call"]
    assert "_write_out" in active[0].message and "engine.write" in active[0].message


def test_condition_wait_on_held_lock_is_fine_foreign_wait_is_not(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self._mu = threading.Lock()
            def ok(self):
                with self._cv:
                    self._cv.wait(1.0)
            def bad(self, ev):
                with self._mu:
                    ev.wait()
    """)
    assert len(active) == 1 and active[0].rule == "lock-blocking-call"
    assert "ev.wait" in active[0].message


def test_engine_round_trip_and_device_sync_under_lock(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class C:
            def __init__(self, engine):
                self._latch_mu = threading.Lock()
                self.engine = engine
            def bad(self, arr):
                with self._latch_mu:
                    snap = self.engine.snapshot(None)
                    arr.block_until_ready()
    """)
    assert _rules(active) == ["lock-blocking-call"] * 2


def test_waiver_inline_and_above_with_reason(tmp_path):
    active, waived = _lint_src(tmp_path, """
        import threading, time
        class C:
            def __init__(self):
                self._mu = threading.Lock()
            def a(self):
                with self._mu:
                    time.sleep(1)  # lint: allow(lock-blocking-call) -- why
            def b(self):
                with self._mu:
                    # lint: allow(lock-blocking-call) -- reason spanning
                    # a second comment line does not break the reach
                    time.sleep(1)
            def c(self):
                with self._mu:
                    time.sleep(1)  # lint: allow(jit-nocache) -- wrong rule
    """)
    assert len(waived) == 2
    assert _rules(active) == ["lock-blocking-call"]  # wrong-rule waiver


def test_inline_waiver_does_not_leak_to_next_line(tmp_path):
    """The trailing-comment form covers ONLY its own line: an unreviewed
    violation directly below must keep its own finding."""
    active, waived = _lint_src(tmp_path, """
        import threading, time
        class C:
            def __init__(self, engine):
                self._mu = threading.Lock()
                self.engine = engine
            def f(self, b):
                with self._mu:
                    time.sleep(1)  # lint: allow(lock-blocking-call) -- ok
                    self.engine.write(b)
    """)
    assert len(waived) == 1 and "time.sleep" in waived[0].message
    assert _rules(active) == ["lock-blocking-call"]
    assert "engine.write" in active[0].message


# ---------------------------------------------------------------------------
# jit rules
# ---------------------------------------------------------------------------

def test_jit_nocache_flagged_cached_not(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import jax
        def hot(f):
            return jax.jit(f)
        def warm(f, cache, key):
            fn = cache.get(key)
            if fn is None:
                fn = cache[key] = jax.jit(f)
            return fn
    """, rel="tikv_tpu/copr/dev.py")
    assert _rules(active) == ["jit-nocache"]
    assert "hot" in active[0].message


def test_jit_static_args_and_shape_branch(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import jax
        def build(statics):  # cache word here would mask nothing below
            def step(x):
                if x.shape[0] > 4:
                    return x
                return x + 1
            memo = jax.jit(step, static_argnums=statics)
            return memo
    """, rel="tikv_tpu/copr/dev2.py")
    rules = set(_rules(active))
    assert "jit-static-args" in rules
    assert "jit-shape-branch" in rules


def test_jit_host_sync_in_jitted_fn(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import jax
        def build_cache():
            def step(x):
                return x.item()
            return jax.jit(step)
    """, rel="tikv_tpu/copr/dev3.py")
    assert _rules(active) == ["jit-host-sync"]


# ---------------------------------------------------------------------------
# drift passes
# ---------------------------------------------------------------------------

def test_metric_drift_both_directions(tmp_path):
    active, _ = _lint_src(tmp_path, """
        from ..util.metrics import REGISTRY
        REGISTRY.counter("tikv_lint_used_total", "used")
        REGISTRY.counter("tikv_lint_dead_total", "never charted")
    """, drift=True, metrics={"dash.json": (
        '{"panels": [{"targets": [{"expr": '
        '"rate(tikv_lint_used_total[1m]) + rate(tikv_lint_ghost_total[1m])"'
        '}]}]}'
    )})
    by_rule = {f.rule: f for f in active}
    assert "metric-drift-dashboard" in by_rule
    assert "tikv_lint_ghost_total" in by_rule["metric-drift-dashboard"].message
    assert "metric-drift-code" in by_rule
    assert "tikv_lint_dead_total" in by_rule["metric-drift-code"].message
    assert len(active) == 2


def test_histogram_series_suffixes_resolve(tmp_path):
    active, _ = _lint_src(tmp_path, """
        from ..util.metrics import REGISTRY
        REGISTRY.histogram("tikv_lint_lat_seconds", "latency")
    """, drift=True, metrics={"dash.json": (
        '{"panels": [{"targets": [{"expr": '
        '"histogram_quantile(0.99, rate(tikv_lint_lat_seconds_bucket[1m]))"}]}]}'
    )})
    assert active == []


def test_failpoint_drift_both_directions(tmp_path):
    root = tmp_path
    src = root / "tikv_tpu" / "mod.py"
    src.parent.mkdir(parents=True)
    src.write_text(textwrap.dedent("""
        from .util.failpoint import fail_point
        def f():
            fail_point("site_tested")
            fail_point("site_untested")
    """))
    test = root / "tests" / "test_mod.py"
    test.parent.mkdir()
    test.write_text(textwrap.dedent("""
        from tikv_tpu.util.failpoint import cfg, fail_point
        def test_it():
            cfg("site_tested", "return")
            cfg("site_gone", "return")
            cfg("local_site", "pause")
            fail_point("local_site")
    """))
    active, _ = lint.run([str(src.parent), str(test.parent)], root=root, drift=True)
    by_rule = {}
    for f in active:
        by_rule.setdefault(f.rule, []).append(f)
    assert [f.message for f in by_rule["failpoint-drift-test"]] \
        and "site_gone" in by_rule["failpoint-drift-test"][0].message
    assert "site_untested" in by_rule["failpoint-drift-source"][0].message
    assert len(active) == 2  # local_site + site_tested are both fine


def test_raw_lock_direct_in_wired_module(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class W:
            def __init__(self):
                self._cv = threading.Condition()
    """, rel="tikv_tpu/util/worker.py")
    assert _rules(active) == ["raw-lock-direct"]


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def test_real_tree_lints_clean():
    """THE acceptance gate: the shipped tree has zero unwaived findings —
    exactly what `python scripts/lint.py tikv_tpu tests` enforces in CI."""
    root = Path(lint.__file__).resolve().parents[2]
    active, waived = lint.run(["tikv_tpu", "tests"], root=root)
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    # the waivers carry reasons (-- ...) — spot-check they exist at all
    assert waived, "expected in-line waivers in the tree"


def test_cli_exit_codes(tmp_path, capsys):
    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "lock-blocking-call" in out
