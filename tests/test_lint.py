"""Project linter (analysis/lint.py): each rule on synthetic modules, the
waiver machinery, and the zero-findings gate on the real tree."""

import textwrap
from pathlib import Path

import pytest

from tikv_tpu.analysis import lint


def _lint_src(tmp_path: Path, src: str, rel: str = "tikv_tpu/mod.py",
              drift: bool = False, metrics: dict | None = None):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    if metrics:
        mdir = tmp_path / "metrics"
        mdir.mkdir(exist_ok=True)
        for name, content in metrics.items():
            (mdir / name).write_text(content)
    return lint.run([str(p.parent)], root=tmp_path, drift=drift)


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# lock-blocking-call
# ---------------------------------------------------------------------------

def test_direct_blocking_under_lock(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading, time
        class C:
            def __init__(self):
                self._mu = threading.Lock()
            def bad(self):
                with self._mu:
                    time.sleep(1)
            def good(self):
                time.sleep(1)
                with self._mu:
                    pass
    """)
    assert _rules(active) == ["lock-blocking-call"]
    assert "time.sleep" in active[0].message


def test_transitive_blocking_through_self_call(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class C:
            def __init__(self, engine):
                self._mu = threading.Lock()
                self.engine = engine
            def _write_out(self):
                self.engine.write(None)
            def bad(self):
                with self._mu:
                    self._write_out()
    """)
    assert _rules(active) == ["lock-blocking-call"]
    assert "_write_out" in active[0].message and "engine.write" in active[0].message


def test_condition_wait_on_held_lock_is_fine_foreign_wait_is_not(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class C:
            def __init__(self):
                self._cv = threading.Condition()
                self._mu = threading.Lock()
            def ok(self):
                with self._cv:
                    self._cv.wait(1.0)
            def bad(self, ev):
                with self._mu:
                    ev.wait()
    """)
    assert len(active) == 1 and active[0].rule == "lock-blocking-call"
    assert "ev.wait" in active[0].message


def test_engine_round_trip_and_device_sync_under_lock(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class C:
            def __init__(self, engine):
                self._latch_mu = threading.Lock()
                self.engine = engine
            def bad(self, arr):
                with self._latch_mu:
                    snap = self.engine.snapshot(None)
                    arr.block_until_ready()
    """)
    assert _rules(active) == ["lock-blocking-call"] * 2


def test_waiver_inline_and_above_with_reason(tmp_path):
    active, waived = _lint_src(tmp_path, """
        import threading, time
        class C:
            def __init__(self):
                self._mu = threading.Lock()
            def a(self):
                with self._mu:
                    time.sleep(1)  # lint: allow(lock-blocking-call) -- why
            def b(self):
                with self._mu:
                    # lint: allow(lock-blocking-call) -- reason spanning
                    # a second comment line does not break the reach
                    time.sleep(1)
            def c(self):
                with self._mu:
                    time.sleep(1)  # lint: allow(jit-nocache) -- wrong rule
    """)
    assert len(waived) == 2
    assert _rules(active) == ["lock-blocking-call"]  # wrong-rule waiver


def test_inline_waiver_does_not_leak_to_next_line(tmp_path):
    """The trailing-comment form covers ONLY its own line: an unreviewed
    violation directly below must keep its own finding."""
    active, waived = _lint_src(tmp_path, """
        import threading, time
        class C:
            def __init__(self, engine):
                self._mu = threading.Lock()
                self.engine = engine
            def f(self, b):
                with self._mu:
                    time.sleep(1)  # lint: allow(lock-blocking-call) -- ok
                    self.engine.write(b)
    """)
    assert len(waived) == 1 and "time.sleep" in waived[0].message
    assert _rules(active) == ["lock-blocking-call"]
    assert "engine.write" in active[0].message


# ---------------------------------------------------------------------------
# jit rules
# ---------------------------------------------------------------------------

def test_jit_nocache_flagged_cached_not(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import jax
        def hot(f):
            return jax.jit(f)
        def warm(f, cache, key):
            fn = cache.get(key)
            if fn is None:
                fn = cache[key] = jax.jit(f)
            return fn
    """, rel="tikv_tpu/copr/dev.py")
    assert _rules(active) == ["jit-nocache"]
    assert "hot" in active[0].message


def test_jit_static_args_and_shape_branch(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import jax
        def build(statics):  # cache word here would mask nothing below
            def step(x):
                if x.shape[0] > 4:
                    return x
                return x + 1
            memo = jax.jit(step, static_argnums=statics)
            return memo
    """, rel="tikv_tpu/copr/dev2.py")
    rules = set(_rules(active))
    assert "jit-static-args" in rules
    assert "jit-shape-branch" in rules


def test_jit_host_sync_in_jitted_fn(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import jax
        def build_cache():
            def step(x):
                return x.item()
            return jax.jit(step)
    """, rel="tikv_tpu/copr/dev3.py")
    assert _rules(active) == ["jit-host-sync"]


# ---------------------------------------------------------------------------
# drift passes
# ---------------------------------------------------------------------------

def test_metric_drift_both_directions(tmp_path):
    active, _ = _lint_src(tmp_path, """
        from ..util.metrics import REGISTRY
        REGISTRY.counter("tikv_lint_used_total", "used")
        REGISTRY.counter("tikv_lint_dead_total", "never charted")
    """, drift=True, metrics={"dash.json": (
        '{"panels": [{"targets": [{"expr": '
        '"rate(tikv_lint_used_total[1m]) + rate(tikv_lint_ghost_total[1m])"'
        '}]}]}'
    )})
    by_rule = {f.rule: f for f in active}
    assert "metric-drift-dashboard" in by_rule
    assert "tikv_lint_ghost_total" in by_rule["metric-drift-dashboard"].message
    assert "metric-drift-code" in by_rule
    assert "tikv_lint_dead_total" in by_rule["metric-drift-code"].message
    assert len(active) == 2


def test_histogram_series_suffixes_resolve(tmp_path):
    active, _ = _lint_src(tmp_path, """
        from ..util.metrics import REGISTRY
        REGISTRY.histogram("tikv_lint_lat_seconds", "latency")
    """, drift=True, metrics={"dash.json": (
        '{"panels": [{"targets": [{"expr": '
        '"histogram_quantile(0.99, rate(tikv_lint_lat_seconds_bucket[1m]))"}]}]}'
    )})
    assert active == []


def test_failpoint_drift_both_directions(tmp_path):
    root = tmp_path
    src = root / "tikv_tpu" / "mod.py"
    src.parent.mkdir(parents=True)
    src.write_text(textwrap.dedent("""
        from .util.failpoint import fail_point
        def f():
            fail_point("site_tested")
            fail_point("site_untested")
    """))
    test = root / "tests" / "test_mod.py"
    test.parent.mkdir()
    test.write_text(textwrap.dedent("""
        from tikv_tpu.util.failpoint import cfg, fail_point
        def test_it():
            cfg("site_tested", "return")
            cfg("site_gone", "return")
            cfg("local_site", "pause")
            fail_point("local_site")
    """))
    active, _ = lint.run([str(src.parent), str(test.parent)], root=root, drift=True)
    by_rule = {}
    for f in active:
        by_rule.setdefault(f.rule, []).append(f)
    assert [f.message for f in by_rule["failpoint-drift-test"]] \
        and "site_gone" in by_rule["failpoint-drift-test"][0].message
    assert "site_untested" in by_rule["failpoint-drift-source"][0].message
    assert len(active) == 2  # local_site + site_tested are both fine


def test_raw_lock_direct_in_wired_module(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import threading
        class W:
            def __init__(self):
                self._cv = threading.Condition()
    """, rel="tikv_tpu/util/worker.py")
    assert _rules(active) == ["raw-lock-direct"]


# ---------------------------------------------------------------------------
# buffer-inplace-export
# ---------------------------------------------------------------------------

def test_inplace_after_export_flagged(tmp_path):
    active, _ = _lint_src(tmp_path, """
        from tikv_tpu.server.wire import dumps_parts
        def bad(arr):
            parts = dumps_parts(arr)
            arr[0:4] = 0
            return parts
        def also_bad(arr):
            parts = dumps_parts(arr)
            arr += 1
            return parts
        def good_fill_then_export(arr):
            arr[0] = 1
            return dumps_parts(arr)
    """)
    assert _rules(active) == ["buffer-inplace-export"] * 2
    assert "flowed to the zero-copy export" in active[0].message


def test_inplace_sort_and_copyto_flagged(tmp_path):
    active, _ = _lint_src(tmp_path, """
        import numpy as np
        from tikv_tpu.analysis import bufsan
        def bad(arr, other):
            bufsan.export("wire_part", arr)
            np.copyto(arr, other)
        def bad2(arr):
            bufsan.export("wire_part", arr)
            arr.sort()
    """)
    assert _rules(active) == ["buffer-inplace-export"] * 2


def test_inplace_transitive_through_local_call(tmp_path):
    """Taint follows a positional arg into a local function whose body
    exports that parameter."""
    active, _ = _lint_src(tmp_path, """
        from tikv_tpu.server.wire import dumps_parts
        def send(buf):
            return dumps_parts(buf)
        def bad(arr):
            p = send(arr)
            arr[3] = 9
            return p
        def good(arr):
            arr[3] = 9
            return send(arr)
    """)
    assert _rules(active) == ["buffer-inplace-export"]


def test_inplace_export_waivable(tmp_path):
    active, waived = _lint_src(tmp_path, """
        from tikv_tpu.server.wire import dumps_parts
        def deliberate(arr):
            parts = dumps_parts(arr)
            # lint: allow(buffer-inplace-export) -- strike test fixture
            arr[0] = 1
            return parts
    """)
    assert active == []
    assert _rules(waived) == ["buffer-inplace-export"]


# ---------------------------------------------------------------------------
# buffer-export-unregistered
# ---------------------------------------------------------------------------

def test_boundary_without_bufsan_flagged(tmp_path):
    active, _ = _lint_src(tmp_path, """
        def dumps_parts(obj):
            return [obj]
    """, rel="tikv_tpu/server/wire.py")
    assert _rules(active) == ["buffer-export-unregistered"]
    assert "dumps_parts" in active[0].message


def test_boundary_routed_through_bufsan_clean(tmp_path):
    """Transitive reach counts: the boundary may delegate registration to
    a same-module helper."""
    active, _ = _lint_src(tmp_path, """
        from tikv_tpu.analysis import bufsan as _bufsan
        def _register(o):
            _bufsan.export("wire_part", o)
        def dumps_parts(obj):
            _register(obj)
            return [obj]
    """, rel="tikv_tpu/server/wire.py")
    assert active == []


def test_boundary_rule_scoped_to_named_files(tmp_path):
    """A dumps_parts defined elsewhere is not an exposure boundary."""
    active, _ = _lint_src(tmp_path, """
        def dumps_parts(obj):
            return [obj]
    """, rel="tikv_tpu/other.py")
    assert active == []


# ---------------------------------------------------------------------------
# view-escape
# ---------------------------------------------------------------------------

def test_view_escape_flagged(tmp_path):
    active, _ = _lint_src(tmp_path, """
        class Cache:
            def get_block(self):
                return self._buf[2:10]
            def expose(self):
                return memoryview(self.raw)
    """)
    assert _rules(active) == ["view-escape"] * 2


def test_view_escape_copies_and_private_clean(tmp_path):
    active, _ = _lint_src(tmp_path, """
        from tikv_tpu.analysis import bufsan
        class Cache:
            def copied(self):
                return self._buf[2:10].copy()
            def frozen(self):
                return memoryview(self.raw).toreadonly()
            def _internal(self):
                return self._buf[2:10]
            def registered(self):
                bufsan.export("wire_part", self._buf)
                return self._buf[2:10]
            def not_a_buffer(self):
                return self.items[2:10]
    """)
    assert active == []


def test_view_escape_waivable(tmp_path):
    active, waived = _lint_src(tmp_path, """
        class Row:
            def cell(self):
                # lint: allow(view-escape) -- raw is bytes, slices copy
                return self.raw[2:10]
    """)
    assert active == []
    assert _rules(waived) == ["view-escape"]


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------

def test_real_tree_lints_clean():
    """THE acceptance gate: the shipped tree has zero unwaived findings —
    exactly what `python scripts/lint.py tikv_tpu tests` enforces in CI."""
    root = Path(lint.__file__).resolve().parents[2]
    active, waived = lint.run(["tikv_tpu", "tests"], root=root)
    assert active == [], "\n" + "\n".join(f.format() for f in active)
    # the waivers carry reasons (-- ...) — spot-check they exist at all
    assert waived, "expected in-line waivers in the tree"


def test_cli_exit_codes(tmp_path, capsys):
    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "lock-blocking-call" in out
