"""Lock-order race sanitizer (analysis/sanitizer.py): seeded inversions are
caught with both stacks, clean orderings stay silent, and the wired hot
paths (txn scheduler + latches, raft cluster) run hazard-free under it."""

import threading

import pytest

from tikv_tpu.analysis import sanitizer as S


@pytest.fixture(autouse=True)
def _clean():
    # snapshot/restore, NOT clear: under TIKV_TPU_SANITIZE=1 the session-wide
    # conftest gate is accumulating real edges across the whole run — these
    # tests must neither see that state nor erase it (a cleared half-edge
    # would blind the gate to an inversion straddling this file)
    saved = S.snapshot_state()
    S.clear_reports()
    yield
    S.restore_state(saved)


def _run_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(5)
    assert not t.is_alive()


# ---------------------------------------------------------------------------
# core detector
# ---------------------------------------------------------------------------

def test_seeded_inversion_reports_cycle_with_both_stacks():
    """A -> B in one thread, B -> A in another: the closing edge reports a
    potential deadlock WITHOUT any timing window (no thread ever parks)."""
    with S.force():
        a, b = S.make_lock("test.A"), S.make_lock("test.B")

    def forward():
        with a:
            with b:
                pass

    def inverted():
        with b:
            with a:
                pass

    _run_thread(forward)
    _run_thread(inverted)
    cycles = S.reports("lock-order-cycle")
    assert len(cycles) == 1
    rep = cycles[0]
    assert "test.A" in rep.message and "test.B" in rep.message
    assert "potential deadlock" in rep.message
    # both sides' stacks: the inverting thread's two acquisitions AND the
    # forward thread's recorded A-held -> B-acquired edge
    titles = [t for t, _ in rep.stacks]
    assert any("held at" in t for t in titles)
    assert any("acquired under" in t for t in titles)
    assert len(rep.stacks) >= 3
    frames = "\n".join(fr for _, fs in rep.stacks for fr in fs)
    assert "inverted" in frames and "forward" in frames


def test_clean_ordering_reports_nothing():
    with S.force():
        a, b = S.make_lock("test.C"), S.make_lock("test.D")

    def consistent():
        for _ in range(3):
            with a:
                with b:
                    pass

    _run_thread(consistent)
    _run_thread(consistent)
    assert S.reports() == []
    assert S.lock_graph() == {"test.C": {"test.D"}}


def test_three_lock_cycle_detected():
    """A->B, B->C, C->A: the cycle spans three edges, not a simple pair."""
    with S.force():
        a, b, c = (S.make_lock(k) for k in ("t3.A", "t3.B", "t3.C"))
    for outer, inner in ((a, b), (b, c), (c, a)):
        def nest(o=outer, i=inner):
            with o:
                with i:
                    pass
        _run_thread(nest)
    cycles = S.reports("lock-order-cycle")
    assert len(cycles) == 1
    assert all(k in cycles[0].message for k in ("t3.A", "t3.B", "t3.C"))


def test_rlock_reentrancy_is_not_an_ordering_event():
    with S.force():
        r = S.make_rlock("test.R")
    with r:
        with r:  # re-acquire: no self-edge, no report
            pass
    assert S.reports() == []
    assert S.held_locks() == []


def test_same_order_key_nesting_flagged():
    """Two INSTANCES sharing an order key nested inside each other have no
    defined order — lockdep's same-class rule."""
    with S.force():
        x = S.make_lock("test.same", label="x")
        y = S.make_lock("test.same", label="y")
    with x:
        with y:
            pass
    reps = S.reports("lock-order-same-key")
    assert len(reps) == 1 and "test.same" in reps[0].message


def test_condition_wait_parks_the_hold(monkeypatch):
    """cv.wait() releases the lock: a long wait is NOT a long hold, and the
    wake-up re-registers the hold for order tracking."""
    monkeypatch.setenv("TIKV_TPU_SANITIZE_HOLD_MS", "80")
    with S.force():
        cv = S.make_condition("test.cv")

    def waiter():
        with cv:
            cv.wait(0.25)  # longer than the hold threshold

    _run_thread(waiter)
    assert S.reports("long-hold") == []


def test_long_hold_reported(monkeypatch):
    monkeypatch.setenv("TIKV_TPU_SANITIZE_HOLD_MS", "40")
    import time

    with S.force():
        lk = S.make_lock("test.slow")
    with lk:
        # lint: allow(lock-blocking-call) -- the long hold IS the scenario
        time.sleep(0.08)
    reps = S.reports("long-hold")
    assert len(reps) == 1 and "test.slow" in reps[0].message


def test_note_blocking_under_lock(monkeypatch):
    with S.force():
        lk = S.make_lock("test.blk")
        with lk:
            S.note_blocking("raftkv.write")
        S.note_blocking("raftkv.write")  # nothing held: silent
    reps = S.reports("blocking-under-lock")
    assert len(reps) == 1
    assert "raftkv.write" in reps[0].message and "test.blk" in reps[0].message


def test_fatal_mode_raises(monkeypatch):
    monkeypatch.setenv("TIKV_TPU_SANITIZE_FATAL", "1")
    with S.force():
        a, b = S.make_lock("tf.A"), S.make_lock("tf.B")

    def forward():
        with a:
            with b:
                pass

    _run_thread(forward)
    with pytest.raises(RuntimeError, match="lock-order inversion"):
        with b:
            with a:
                pass
    # the failed acquire left nothing held
    assert S.held_locks() == []


def test_disabled_factories_return_plain_primitives():
    with S.force(False):
        lk = S.make_lock("plain")
        cv = S.make_condition("plain")
    assert type(lk) is type(threading.Lock())
    assert isinstance(cv, threading.Condition)


def test_condition_shares_tracked_lock():
    """make_condition(key, lock) must track through BOTH entry points —
    `with mu:` and `with cv:` are the same mutex."""
    with S.force():
        mu = S.make_lock("test.shared")
        cv = S.make_condition("test.shared", mu)
        other = S.make_lock("test.other")

    def via_cv():
        with cv:
            with other:
                pass

    def via_mu_inverted():
        with other:
            with mu:
                pass

    _run_thread(via_cv)
    _run_thread(via_mu_inverted)
    assert len(S.reports("lock-order-cycle")) == 1


# ---------------------------------------------------------------------------
# tier-1 hot paths under the sanitizer
# ---------------------------------------------------------------------------

def test_txn_scheduler_and_latches_clean_under_sanitizer():
    """The whole txn write path (latches -> sched pool -> group commit ->
    engine) exercised concurrently with order tracking live: zero hazards."""
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    with S.force():
        store = Storage()
        # the wrapped lock proves the wiring is live, not vestigial
        assert isinstance(store.scheduler.latches._mu, S._TrackedLock)

        def txn(i: int):
            k = f"k{i}".encode()
            store.sched_txn_command(
                Prewrite([Mutation.put(Key.from_raw(k), b"v")], k, 10 + i * 10)
            )
            store.sched_txn_command(Commit([Key.from_raw(k)], 10 + i * 10, 15 + i * 10))

        threads = [threading.Thread(target=txn, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        store.scheduler.stop()
    assert S.reports("lock-order-cycle") == []
    assert S.reports("blocking-under-lock") == []
    for i in range(8):
        assert store.get(f"k{i}".encode(), 200) == b"v"


def test_raft_cluster_clean_under_sanitizer():
    """A 3-store raft cluster (store locks, peer cb locks, transport, region
    cache invalidation hooks) drives writes end-to-end under the sanitizer."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    with S.force():
        c = Cluster(3)
        c.bootstrap_subset([1, 2, 3])
        c.elect_leader(FIRST_REGION_ID, 1)
        for i in range(5):
            c.must_put(f"s{i}".encode(), b"v")
        c.tick(3)
    assert S.reports("lock-order-cycle") == []
    for i in range(5):
        assert c.get_on_store(1, f"s{i}".encode()) == b"v"
