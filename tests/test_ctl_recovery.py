"""Operator disaster tooling: wreck a 3-node cluster (kill 2 of 3 stores)
and recover quorum via ctl's offline unsafe-recover, plus recover-mvcc,
tombstone, recreate-region, compact (cmd/tikv-ctl/src/main.rs:1513-1642)."""

from __future__ import annotations

import json

import pytest

import ctl
from tikv_tpu.native.engine import NativeEngine, native_available
from tikv_tpu.pd.client import MockPd
from tikv_tpu.server.cluster import FIRST_REGION_ID, ServerCluster, StoreNode
from tikv_tpu.server.debug import Debugger
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE, WriteBatch
from tikv_tpu.storage.txn_types import Key, Lock, LockType, Write, WriteType
from tikv_tpu.util import keys as keymod

pytestmark = pytest.mark.skipif(not native_available(), reason="no native engine")


def test_unsafe_recover_restores_quorum_via_ctl(tmp_path, capsys):
    """Two of three stores die for good; ctl unsafe-recover on the survivor's
    (stopped) engine dir strips the dead peers; the survivor reboots as a
    single-voter region and serves reads AND writes again."""
    dirs = {sid: str(tmp_path / f"store{sid}") for sid in (1, 2, 3)}
    engines = {sid: NativeEngine(path=dirs[sid], sync=False) for sid in (1, 2, 3)}
    c = ServerCluster(3, pd=MockPd(), engines=engines)
    c.run()
    for i in range(20):
        c.must_put(b"key%02d" % i, b"val%02d" % i)
    for sid in (1, 2, 3):
        c.wait_get_on_store(sid, b"key00", b"val00")
    # catastrophe: stores 2 and 3 die permanently; stop 1 for offline surgery
    c.stop_node(2)
    c.stop_node(3)
    c.stop_node(1)
    engines[1].close()

    rc = ctl.main(["--db", dirs[1], "unsafe-recover", "--stores", "2,3"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert FIRST_REGION_ID in out["modified_regions"]

    # reboot the survivor over its repaired engine dir
    eng1 = NativeEngine(path=dirs[1], sync=False)
    node = StoreNode(c, 1, engine=eng1)
    node.store.recover()
    c.nodes[1] = node
    node.start()
    peer = node.store.peers[FIRST_REGION_ID]
    assert [p.store_id for p in peer.region.peers] == [1]  # dead peers gone
    peer.node.campaign()
    c.wait_leader(FIRST_REGION_ID)
    # old data survived; new writes commit with the single-voter quorum
    assert c.must_get(b"key07") == b"val07"
    c.must_put(b"after-recovery", b"alive")
    assert c.must_get(b"after-recovery") == b"alive"
    c.shutdown()
    eng1.close()


def test_recover_mvcc_repairs_cross_cf_state(tmp_path, capsys):
    d = str(tmp_path / "db")
    eng = NativeEngine(path=d, sync=False)
    wb = WriteBatch()
    # healthy committed row
    k1 = Key.from_raw(b"good")
    wb.put_cf(CF_DEFAULT, keymod.data_key(k1.append_ts(10).encoded), b"v" * 300)
    wb.put_cf(CF_WRITE, keymod.data_key(k1.append_ts(11).encoded),
              Write(WriteType.PUT, 10).to_bytes())
    # orphan lock from a long-dead txn
    k2 = Key.from_raw(b"locked")
    wb.put_cf(CF_LOCK, keymod.data_key(k2.encoded),
              Lock(LockType.PUT, b"locked", 5, 3000).to_bytes())
    # dangling default: no write record references ts 7
    k3 = Key.from_raw(b"dangling")
    wb.put_cf(CF_DEFAULT, keymod.data_key(k3.append_ts(7).encoded), b"junk")
    eng.write(wb)
    eng.close()

    # without --safe-ts nothing counts as an orphan lock (destructive
    # filters default to removing nothing) and the locked txn's value is
    # protected by its lock reference
    rc = ctl.main(["--db", d, "recover-mvcc"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out == {"orphan_locks": 0, "dangling_defaults": 1, "applied": False}

    rc = ctl.main(["--db", d, "recover-mvcc", "--safe-ts", "50"])  # dry run
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out == {"orphan_locks": 1, "dangling_defaults": 1, "applied": False}

    rc = ctl.main(["--db", d, "recover-mvcc", "--apply", "--safe-ts", "50"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["applied"] is True

    eng = NativeEngine(path=d, sync=False)
    dbg = Debugger(eng)
    assert eng.get_cf(CF_LOCK, keymod.data_key(k2.encoded)) is None
    assert eng.get_cf(CF_DEFAULT, keymod.data_key(k3.append_ts(7).encoded)) is None
    # the healthy row is untouched
    assert eng.get_cf(CF_DEFAULT, keymod.data_key(k1.append_ts(10).encoded)) is not None
    eng.close()


def test_tombstone_and_recreate_region_via_ctl(tmp_path, capsys):
    d = str(tmp_path / "db")
    eng = NativeEngine(path=d, sync=False)
    Debugger(eng).recreate_region(77, b"a", b"z", store_id=1, peer_id=701)
    eng.flush()
    eng.close()

    rc = ctl.main(["--db", d, "tombstone", "--region", "77"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["tombstoned"] is True

    rc = ctl.main(["--db", d, "recreate-region", "--region", "77",
                   "--store", "1", "--peer", "702", "--start", "a", "--end", "z"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["recreated"] == 77

    eng = NativeEngine(path=d, sync=False)
    info = Debugger(eng).region_info(77)
    assert info["region"]["peers"] == [(702, 1)]
    eng.close()


def test_compact_via_ctl(tmp_path, capsys):
    d = str(tmp_path / "db")
    eng = NativeEngine(path=d, sync=False)
    for i in range(50):
        wb = WriteBatch()
        wb.put_cf(CF_DEFAULT, b"c%02d" % i, b"v" * 100)
        eng.write(wb)
        if i % 10 == 9:
            eng.flush()
    assert eng.run_count("default") >= 2
    eng.close()
    rc = ctl.main(["--db", d, "compact"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["supported"] and out["merged_runs"] >= 1
    eng = NativeEngine(path=d, sync=False)
    assert eng.run_count("default") == 1
    assert eng.get_cf(CF_DEFAULT, b"c42") == b"v" * 100
    eng.close()


def test_offline_backup_restore_via_ctl(tmp_path, capsys):
    """BR-style offline flow: back a stopped store's engine up through ctl,
    verify checksums, restore into a fresh engine (tikv-ctl + BR roles)."""
    d = str(tmp_path / "store1")
    engines = {1: NativeEngine(path=d, sync=False)}
    c = ServerCluster(1, pd=MockPd(), engines=engines)
    c.run()
    storage_keys = []
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Mutation

    from tikv_tpu.raft.raftkv import RaftKv

    st = Storage(engine=RaftKv(c.nodes[1].store))
    pd = c.pd
    for i in range(15):
        k = b"cb-%02d" % i
        ts = pd.get_tso()
        st.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), b"v%d" % i)], k, ts),
                             {"region_id": FIRST_REGION_ID})
        st.sched_txn_command(Commit([Key.from_raw(k)], ts, pd.get_tso()),
                             {"region_id": FIRST_REGION_ID})
        storage_keys.append(k)
    backup_ts = pd.get_tso()
    c.shutdown()
    engines[1].close()

    out_dir = str(tmp_path / "bk")
    rc = ctl.main(["--db", d, "backup", "--out", out_dir, "--backup-ts",
                   str(backup_ts)])
    assert rc == 0
    meta = json.loads(capsys.readouterr().out)
    assert meta["total_kvs"] == 15 and meta["regions"] >= 1

    rc = ctl.main(["backup-verify", "--out", out_dir])  # no --db: storage-only
    assert rc == 0
    v = json.loads(capsys.readouterr().out)
    assert v["total_kvs"] == 15

    # restore into a FRESH engine dir — and prove the dir BOOTS as a store
    d2 = str(tmp_path / "store-restored")
    NativeEngine(path=d2, sync=False).close()
    rc = ctl.main(["--db", d2, "restore", "--out", out_dir, "--restore-ts",
                   str(backup_ts + 10)])
    assert rc == 0
    r = json.loads(capsys.readouterr().out)
    assert r["kvs"] == 15
    e3 = NativeEngine(path=d2, sync=False)
    c2 = ServerCluster(1, pd=MockPd(), engines={1: e3})
    node = StoreNode(c2, 1, engine=e3)
    assert node.store.recover() == 1  # the restored region meta is found
    c2.nodes[1] = node
    node.start()
    node.store.peers[1].node.campaign()
    c2.wait_leader(1)
    from tikv_tpu.raft.raftkv import RaftKv as _RaftKv

    st2 = Storage(engine=_RaftKv(node.store))
    assert st2.get(b"cb-07", pd.get_tso(), {"region_id": 1}) == b"v7"
    assert st2.get(b"cb-14", pd.get_tso(), {"region_id": 1}) == b"v14"
    c2.shutdown()
    e3.close()
