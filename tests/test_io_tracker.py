"""IO rate limiter + request tracker/slow log."""

import threading
import time

from tikv_tpu.copr.tracker import SlowLog, Tracker
from tikv_tpu.util.io_limiter import IoRateLimiter, IoType, get_io_type, set_io_type


def test_io_limiter_unlimited_and_tagging():
    lim = IoRateLimiter(0)
    assert lim.request(10**9, IoType.COMPACTION) == 10**9
    set_io_type(IoType.GC)
    assert get_io_type() == IoType.GC
    lim.request(100)
    assert lim.stats[IoType.GC] == 100


def test_io_limiter_throttles_background_not_foreground():
    lim = IoRateLimiter(bytes_per_sec=10_000, refill_period=0.02)
    # foreground never blocks
    t0 = time.monotonic()
    for _ in range(20):
        lim.request(5_000, IoType.FOREGROUND_WRITE)
    assert time.monotonic() - t0 < 0.05
    # background must wait for refills: 5 requests of one epoch-budget each
    t0 = time.monotonic()
    for _ in range(5):
        lim.request(200, IoType.COMPACTION)
    elapsed = time.monotonic() - t0
    assert elapsed >= 0.02  # at least one refill wait
    assert lim.stats[IoType.COMPACTION] == 1000


def test_tracker_phases_and_slowlog():
    tr = Tracker("copr")
    time.sleep(0.01)
    tr.on_schedule()
    tr.on_snapshot_finished()
    time.sleep(0.01)
    m = tr.on_finish(scanned_keys=42, from_device=True)
    assert m.schedule_wait_s >= 0.009
    assert m.handle_s >= 0.009
    assert m.total_s >= m.schedule_wait_s + m.handle_s - 1e-6
    d = m.to_dict()
    assert d["scanned_keys"] == 42 and d["from_device"] is True

    slow = SlowLog(threshold_s=0.015)
    assert slow.observe(tr) is True
    fast = Tracker("fast")
    fast.on_schedule()
    fast.on_snapshot_finished()
    fast.on_finish()
    assert slow.observe(fast) is False
    assert len(slow.tail()) == 1 and slow.tail()[0]["tag"] == "copr"


def test_endpoint_carries_metrics():
    import sys, os

    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_engine
    from tikv_tpu.copr.dag import DagRequest, TableScan
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.copr.tracker import SlowLog
    from tikv_tpu.storage.kv import LocalEngine

    slow = SlowLog(threshold_s=0.0)  # record everything
    ep = Endpoint(LocalEngine(product_engine()), enable_device=False, slow_log=slow)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r = ep.handle_request(CoprRequest(103, dag, [record_range(TABLE_ID)], 200, context={"region_id": 1}))
    assert r.metrics["scanned_keys"] == 6
    assert r.metrics["total_ms"] >= r.metrics["handle_ms"]
    assert not r.metrics["from_device"]
    assert slow.tail()[0]["tag"].startswith("copr tp=103")


def test_slow_log_file_sink(tmp_path):
    """Slow requests append one JSON line each to the slow-log file (the
    reference's separate slow-log stream), in addition to the ring."""
    import json

    from tikv_tpu.copr.tracker import SlowLog, Tracker

    path = str(tmp_path / "slow.log")
    slow = SlowLog(threshold_s=0.0, path=path)
    t = Tracker("copr tp=103 region=7")
    t.on_schedule()
    t.on_snapshot_finished()
    t.on_finish(scanned_keys=5, from_device=False)
    assert slow.observe(t)
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert len(lines) == 1 and lines[0]["tag"] == "copr tp=103 region=7"
    assert "ts" in lines[0] and lines[0]["scanned_keys"] == 5
