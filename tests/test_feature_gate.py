"""FeatureGate: version-gated feature rollout (feature_gate.rs:14 parity)
and the online device knob (POST /config coprocessor.enable_device)."""

import json
import urllib.request

import numpy as np
import pytest

from tikv_tpu.pd.feature_gate import (
    BATCH_FUSION,
    DEVICE_COPROCESSOR,
    Feature,
    FeatureGate,
    MESH_SERVING,
    parse_version,
)


def test_gate_monotonic_and_thresholds():
    g = FeatureGate()
    assert not g.can_enable(DEVICE_COPROCESSOR)
    assert g.set_version("4.9.9")
    assert not g.can_enable(DEVICE_COPROCESSOR)
    assert g.set_version("5.0.0")
    assert g.can_enable(DEVICE_COPROCESSOR)
    assert not g.can_enable(MESH_SERVING)  # needs 5.1
    # stale heartbeat must not regress the gate (CAS-loop semantics)
    assert not g.set_version("4.0.0")
    assert g.can_enable(DEVICE_COPROCESSOR)
    assert g.set_version("5.1.2-beta+build")
    assert g.can_enable(MESH_SERVING) and g.can_enable(BATCH_FUSION)


def test_parse_version_rejects_garbage():
    for bad in ("5.1", "a.b.c", "5.1.70000", ""):
        with pytest.raises(ValueError):
            parse_version(bad)
    assert parse_version("v5.1.0") == parse_version("5.1.0")
    assert parse_version("5.1.1") > parse_version("5.1.0")
    assert Feature(5, 1, 0).ver == parse_version("5.1.0")


def _endpoint(gate):
    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine

    return Endpoint(LocalEngine(BTreeEngine()), enable_device=True,
                    feature_gate=gate)


def test_endpoint_respects_gate_and_online_toggle():
    g = FeatureGate("4.0.0")
    ep = _endpoint(g)
    assert not ep.device_enabled()  # gated off below 5.0
    g.set_version("5.0.0")
    assert ep.device_enabled()
    ep.set_enable_device(False)  # the online knob still wins
    assert not ep.device_enabled()
    ep.set_enable_device(True)
    assert ep.device_enabled()


def test_mockpd_cluster_version_monotonic():
    from tikv_tpu.pd.client import MockPd

    pd = MockPd()
    assert pd.get_cluster_version() == "5.1.0"
    pd.set_cluster_version("5.2.0")
    with pytest.raises(ValueError):
        pd.set_cluster_version("5.1.0")


def test_online_device_knob_over_http(tmp_path):
    """POST /config toggles device serving on a RUNNING store; the config
    readback reflects it (online_config surface)."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.pd.service import PdService, RemotePd
    from tikv_tpu.server.server import Server
    from tikv_tpu.server.standalone import StoreServer

    pd = MockPd()
    pd_server = Server(PdService(pd))
    pd_server.start()
    srv = None
    try:
        rpd = RemotePd(*pd_server.addr)
        srv = StoreServer(1, rpd, data_dir=None, enable_device=True)
        srv.start()
        srv.bootstrap_or_join(1)
        assert srv.copr.enable_device
        host, port = srv.status_server.addr
        req = urllib.request.Request(
            f"http://{host}:{port}/config",
            data=json.dumps({"coprocessor.enable_device": False}).encode(),
            method="POST", headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert "coprocessor" in resp, resp
        assert srv.copr.enable_device is False
        cfg = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/config", timeout=10).read())
        assert cfg["coprocessor"]["enable_device"] is False
        # feature gate synced from PD's cluster version at construction
        assert srv.feature_gate.can_enable(DEVICE_COPROCESSOR)
    finally:
        if srv is not None:
            srv.stop()
        pd_server.stop()
