"""The bench device-worker wedge watchdog (ISSUE 8 satellite).

BENCH_r05's failure shape: the worker heartbeated ``init_wait`` for the
full 900s init budget while the parent built CPU fixtures, then died as
``worker_killed`` / ``init_budget_exhausted`` with no cause and
``device_cache_built s:0.0``.  The fix moves wedge detection onto a
monitor thread that runs from spawn and kills the worker with a NAMED
cause at BENCH_INIT_STALL seconds — these tests drive the monitor's
verdict logic directly on a harness-free DeviceWorker instance (no real
subprocess, no jax backend)."""

import queue
import threading
import time

import bench


class _FakeProc:
    """Just enough of subprocess.Popen for the monitor + kill paths."""

    def __init__(self):
        self.pid = -1  # os.killpg(-1, ...) raises OSError -> .kill() path
        self.killed = threading.Event()

    def poll(self):
        return None  # "still running" — the wedge monitor's case

    def kill(self):
        self.killed.set()


def _bare_worker(stall_s: float, *, spawned_ago: float = 0.0,
                 silent_for: float = 0.0) -> bench.DeviceWorker:
    """A DeviceWorker with the spawn side effects (subprocess, reader and
    monitor threads) stripped: only the state the verdict logic reads."""
    w = bench.DeviceWorker.__new__(bench.DeviceWorker)
    now = time.time()
    w.timeline = []
    w.t0 = now
    w.proc = _FakeProc()
    w.platform = None
    w._q = queue.Queue()
    w._seq = 0
    w._stall_s = stall_s
    w._spawned_at = now - spawned_ago
    w._last_msg = now - silent_for
    w._ready_seen = False
    w._wedged = None
    w._wedge_mu = threading.Lock()
    return w


def _events(w):
    return [e["ev"] for e in w.timeline]


def test_monitor_declares_backend_init_stall():
    """Zero progress for BENCH_INIT_STALL of worker uptime -> the monitor
    kills the worker and records worker_wedged with the stall cause (one
    5s monitor cycle; the r05 shape burned 900s here).  The heartbeat is
    fresh, so the silence detector stays quiet and the verdict names the
    uptime budget."""
    w = _bare_worker(stall_s=20.0, spawned_ago=30.0)
    t0 = time.monotonic()
    w._monitor_loop()  # first cycle: age >= stall -> verdict, returns
    assert time.monotonic() - t0 < 30.0
    assert w._wedged == "backend_init_stall"
    assert w.proc.killed.is_set()
    ev = [e for e in w.timeline if e["ev"] == "worker_wedged"]
    assert len(ev) == 1 and ev[0]["cause"] == "backend_init_stall"


def test_monitor_declares_heartbeat_silence():
    """A worker whose heartbeat went quiet (backend init holding the GIL)
    wedges on SILENCE even though its uptime is under the stall budget."""
    w = _bare_worker(stall_s=20.0, spawned_ago=0.0, silent_for=30.0)
    w._monitor_loop()
    assert w._wedged == "heartbeat_silent"
    assert w.proc.killed.is_set()


def test_ready_worker_never_wedges():
    """The verdict is init-scoped: once ready has been seen, neither
    detector may kill the worker (a slow OP is the op timeout's job)."""
    w = _bare_worker(stall_s=1.0, spawned_ago=30.0, silent_for=30.0)
    w._ready_seen = True
    w._monitor_loop()
    assert w._wedged is None
    assert not w.proc.killed.is_set()
    w._ready_seen = False
    w._wedged = "backend_init_stall"  # already decided: at most one verdict
    w._declare_wedged("heartbeat_silent")
    assert w._wedged == "backend_init_stall"
    assert not w.proc.killed.is_set()


def test_wait_ready_returns_timeout_on_wedge_without_burning_budget():
    """wait_ready surfaces the monitor's verdict immediately — the 900s
    init budget is NOT burned, and the monitor-kill eof is not mistaken
    for a respawnable worker death."""
    w = _bare_worker(stall_s=1.0)
    w._wedged = "backend_init_stall"
    t0 = time.monotonic()
    assert w.wait_ready(900.0) == "timeout"
    assert time.monotonic() - t0 < 5.0
    assert "init_budget_exhausted" not in _events(w)

    w2 = _bare_worker(stall_s=1.0)
    w2._wedged = "heartbeat_silent"
    w2._q.put({"ev": "eof"})  # the kill EOFs the pipe
    assert w2.wait_ready(900.0) == "timeout"
    assert "worker_died_at_init" not in _events(w2)


def test_init_wait_heartbeats_coalesce_into_one_timeline_event():
    """BENCH_r05 logged one worker_init_wait event every 10s for 900s — 90
    near-identical lines drowning the JSON tail.  Repeats now fold into a
    SINGLE timeline entry carrying first_t/last_t/count, and the eventual
    ready/backend_probe verdicts are untouched."""
    w = _bare_worker(stall_s=900.0)
    for t in (10.0, 20.0, 30.0, 40.0):
        w._q.put({"ev": "init_wait", "t": t})
    w._q.put({"ev": "ready", "platform": "cpu", "t": 45.0})
    assert w.wait_ready(900.0) == "ready"
    waits = [e for e in w.timeline if e["ev"] == "worker_init_wait"]
    assert len(waits) == 1
    assert waits[0]["first_t"] == 10.0
    assert waits[0]["last_t"] == 40.0
    assert waits[0]["count"] == 4
    # the ready verdict still lands as its own event
    assert _events(w).count("ready") == 1


def test_init_wait_coalescing_keeps_stall_backstop():
    """Folding the heartbeat spam must not disable wait_ready's stale-
    heartbeat backstop: a beat whose worker clock passed the stall budget
    still earns the named wedge verdict."""
    w = _bare_worker(stall_s=2.0)
    w._q.put({"ev": "init_wait", "t": 1.0})
    w._q.put({"ev": "init_wait", "t": 5.0})
    assert w.wait_ready(900.0) == "timeout"
    assert w._wedged == "backend_init_stall"
    waits = [e for e in w.timeline if e["ev"] == "worker_init_wait"]
    assert len(waits) == 1 and waits[0]["count"] == 2


def test_wait_ready_backstop_wedges_on_stale_init_wait():
    """Even if the monitor thread never ran, an init_wait heartbeat whose
    own worker-side clock passed the stall budget triggers the verdict in
    wait_ready's drain loop."""
    w = _bare_worker(stall_s=2.0)
    w._q.put({"ev": "init_wait", "t": 5.0})
    assert w.wait_ready(900.0) == "timeout"
    assert w._wedged == "backend_init_stall"
    assert w.proc.killed.is_set()
