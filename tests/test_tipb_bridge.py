"""End-to-end tipb wire contract: protobuf DAG request in, protobuf
SelectResponse out (VERDICT r2 item 2's differential test).

A reference-format DAGRequest is built with the tipb message classes (whose
encodings are pinned byte-identical to the real protobuf runtime by
test_proto_wire.py), decoded through the bridge, executed by the internal
batch pipeline, and the response is re-encoded as tipb.SelectResponse in both
encode types, then decoded back and checked value-for-value.
"""

from __future__ import annotations

import pytest

from tikv_tpu.copr import datum as datum_mod
from tikv_tpu.copr.chunk_codec import (
    ChunkColumn,
    column_values,
    decode_chunk,
    decode_decimal_cell,
    encode_chunk,
    encode_decimal_cell,
)
from tikv_tpu.copr.dag import BatchExecutorsRunner
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType, FieldTypeTp
from tikv_tpu.copr.executors import FixtureScanSource
from tikv_tpu.copr.mydecimal import MyDecimal
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.copr.tipb_bridge import (
    decode_dag_request,
    dag_from_pb,
    decode_ref_datum,
    encode_select_response,
    expr_from_pb,
)
from tikv_tpu.proto import tipb_pb as tp
from tikv_tpu.util import codec

TABLE_ID = 77

COLS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),
    ColumnInfo(3, FieldType.decimal_type(2)),
    ColumnInfo(4, FieldType.varchar()),
]


def fixture_kvs(n=50):
    kvs = []
    for h in range(n):
        v = encode_row(COLS[1:], [h % 7, h * 100 + h % 3, f"s{h % 5}".encode()])
        kvs.append((record_key(TABLE_ID, h), v))
    return kvs


def pb_col(ci: ColumnInfo) -> tp.ColumnInfoPb:
    out = tp.ColumnInfoPb(column_id=ci.col_id, tp=int(ci.ftype.tp),
                          decimal=ci.ftype.decimal)
    if ci.is_pk_handle:
        out.pk_handle = True
    return out


def colref(i: int) -> tp.Expr:
    return tp.Expr(tp=tp.ExprType.ColumnRef, val=codec.encode_i64(i))


def int_const(v: int) -> tp.Expr:
    return tp.Expr(tp=tp.ExprType.Int64, val=codec.encode_i64(v))


def scalar(sig: str, *children) -> tp.Expr:
    return tp.Expr(tp=tp.ExprType.ScalarFunc, sig=tp.SCALAR_FUNC_SIG[sig],
                   children=list(children))


def wire_dag(executors, output_offsets) -> bytes:
    return tp.DAGRequest(
        start_ts_fallback=100,
        executors=executors,
        output_offsets=output_offsets,
        encode_type=tp.EncodeType.TypeDefault,
    ).encode()


def run_wire_request(data: bytes):
    dag, pb = decode_dag_request(data)
    resp = BatchExecutorsRunner(dag, FixtureScanSource(fixture_kvs())).handle_request()
    return dag, pb, resp


def decode_default_rows(select_resp_bytes: bytes, n_cols: int):
    """Parse reference-format SelectResponse (TypeDefault) into rows."""
    pb = tp.SelectResponse.decode(select_resp_bytes)
    rows = []
    for ch in pb.chunks:
        buf = ch.rows_data
        off = 0
        row = []
        while off < len(buf):
            d, off = decode_ref_datum(buf, off)
            row.append(d)
            if len(row) == n_cols:
                rows.append(row)
                row = []
        assert not row, "trailing partial row"
    return pb, rows


def test_scan_selection_wire_roundtrip():
    data = wire_dag(
        [
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan,
                          tbl_scan=tp.TableScanPb(table_id=TABLE_ID,
                                                  columns=[pb_col(c) for c in COLS])),
            tp.ExecutorPb(tp=tp.ExecType.TypeSelection, selection=tp.SelectionPb(
                conditions=[scalar("LtInt", colref(1), int_const(3))])),
        ],
        output_offsets=[0, 1, 3],
    )
    dag, pbreq, resp = run_wire_request(data)
    assert pbreq.start_ts_fallback == 100
    out = encode_select_response(resp)
    pb, rows = decode_default_rows(out, 3)
    assert pb.encode_type == tp.EncodeType.TypeDefault
    # col2 (= h % 7) < 3 filter over h in [0,50)
    expected = [h for h in range(50) if h % 7 < 3]
    assert [r[0].value for r in rows] == expected
    assert all(r[1].value == h % 7 for r, h in zip(rows, expected))
    assert [r[2].value for r in rows] == [f"s{h % 5}".encode() for h in expected]


def test_agg_decimal_reencoded_as_mysql_binary():
    data = wire_dag(
        [
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan,
                          tbl_scan=tp.TableScanPb(table_id=TABLE_ID,
                                                  columns=[pb_col(c) for c in COLS])),
            tp.ExecutorPb(tp=tp.ExecType.TypeAggregation, aggregation=tp.AggregationPb(
                group_by=[colref(1)],
                agg_func=[tp.Expr(tp=tp.ExprType.Sum, children=[colref(2)])])),
        ],
        output_offsets=[0, 1],
    )
    dag, _, resp = run_wire_request(data)
    out = encode_select_response(resp)
    _, rows = decode_default_rows(out, 2)
    # reference decimal datum: flag 6 + prec + frac + write_bin payload; our
    # decoder yields (scaled, frac) back — cross-check against plain python
    sums = {}
    for h in range(50):
        sums.setdefault(h % 7, 0)
        sums[h % 7] += h * 100 + h % 3
    got = {}
    for r in rows:
        scaled, frac = r[0].value
        assert frac == 2
        got[r[1].value] = scaled
    assert got == sums


def test_topn_limit_stream_agg_wire():
    data = wire_dag(
        [
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan,
                          tbl_scan=tp.TableScanPb(table_id=TABLE_ID,
                                                  columns=[pb_col(c) for c in COLS])),
            tp.ExecutorPb(tp=tp.ExecType.TypeTopN, top_n=tp.TopNPb(
                order_by=[tp.ByItem(expr=colref(0), desc=True)], limit=5)),
        ],
        output_offsets=[0],
    )
    _, _, resp = run_wire_request(data)
    _, rows = decode_default_rows(encode_select_response(resp), 1)
    assert [r[0].value for r in rows] == [49, 48, 47, 46, 45]

    data = wire_dag(
        [
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan,
                          tbl_scan=tp.TableScanPb(table_id=TABLE_ID,
                                                  columns=[pb_col(c) for c in COLS])),
            tp.ExecutorPb(tp=tp.ExecType.TypeLimit, limit=tp.LimitPb(limit=3)),
        ],
        output_offsets=[0],
    )
    _, _, resp = run_wire_request(data)
    _, rows = decode_default_rows(encode_select_response(resp), 1)
    assert [r[0].value for r in rows] == [0, 1, 2]

    # stream agg arrives as ExecType 6 and maps onto the streamed executor
    data = wire_dag(
        [
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan,
                          tbl_scan=tp.TableScanPb(table_id=TABLE_ID,
                                                  columns=[pb_col(c) for c in COLS])),
            tp.ExecutorPb(tp=tp.ExecType.TypeStreamAgg, aggregation=tp.AggregationPb(
                group_by=[colref(0)],
                agg_func=[tp.Expr(tp=tp.ExprType.Count, children=[int_const(1)])])),
        ],
        output_offsets=[0, 1],
    )
    dag, _, resp = run_wire_request(data)
    assert dag.executors[1].streamed
    _, rows = decode_default_rows(encode_select_response(resp), 2)
    assert len(rows) == 50 and all(r[0].value == 1 for r in rows)


def test_type_chunk_encoding():
    data = wire_dag(
        [
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan,
                          tbl_scan=tp.TableScanPb(table_id=TABLE_ID,
                                                  columns=[pb_col(c) for c in COLS])),
        ],
        output_offsets=[0, 1, 2, 3],
    )
    _, _, resp = run_wire_request(data)
    fts = [c.ftype for c in COLS]
    out = encode_select_response(resp, encode_type=tp.EncodeType.TypeChunk,
                                 field_types=fts)
    pb = tp.SelectResponse.decode(out)
    assert pb.encode_type == tp.EncodeType.TypeChunk
    cols = decode_chunk(pb.chunks[0].rows_data, fts)
    assert cols[0].rows == 50
    assert column_values(cols[0]) == list(range(50))
    assert column_values(cols[1]) == [h % 7 for h in range(50)]
    assert column_values(cols[2]) == [(h * 100 + h % 3, 2) for h in range(50)]
    assert column_values(cols[3]) == [f"s{h % 5}".encode() for h in range(50)]


# ---------------------------------------------------------------------------
# chunk codec units
# ---------------------------------------------------------------------------

def test_chunk_column_nulls_and_bitmap():
    ft = FieldType.int64()
    c = ChunkColumn(ft)
    vals = [1, None, -5, None, 2**62, 0, None]
    for v in vals:
        c.append(v)
    enc = c.encode()
    # layout: rows, null_cnt, bitmap present (null_cnt>0)
    import struct

    rows, nulls = struct.unpack_from("<II", enc, 0)
    assert (rows, nulls) == (7, 3)
    [out] = decode_chunk(enc, [ft])
    assert column_values(out) == vals


def test_chunk_no_nulls_omits_bitmap():
    ft = FieldType.int64()
    c = ChunkColumn(ft)
    for v in (1, 2, 3):
        c.append(v)
    assert len(c.encode()) == 8 + 3 * 8  # header + data, no bitmap
    [out] = decode_chunk(c.encode(), [ft])
    assert column_values(out) == [1, 2, 3]


def test_chunk_varlen_offsets():
    ft = FieldType.varchar()
    c = ChunkColumn(ft)
    vals = [b"", b"abc", None, b"x" * 100]
    for v in vals:
        c.append(v)
    [out] = decode_chunk(c.encode(), [ft])
    assert column_values(out) == vals


@pytest.mark.parametrize("unscaled,frac", [
    (0, 0), (0, 2), (1, 0), (-1, 0), (12345, 2), (-12345, 2),
    (10**17, 4), (-(10**17), 4), (999999999, 0), (1000000000, 0),
    (123456789012345678, 9), (5, 5),
])
def test_decimal_struct_roundtrip(unscaled, frac):
    cell = encode_decimal_cell(unscaled, frac)
    assert len(cell) == 40
    got = decode_decimal_cell(cell)
    assert got == (unscaled, frac)


def test_decimal_struct_layout_vector():
    # 1234567890123.45: int words [1234, 567890123], frac word 450000000
    import struct

    cell = encode_decimal_cell(123456789012345, 2)
    int_cnt, frac_cnt, rf, neg, *words = struct.unpack("<BBBB9I", cell)
    assert (int_cnt, frac_cnt, rf, neg) == (13, 2, 2, 0)
    assert words[:3] == [1234, 567890123, 450000000]
    assert all(w == 0 for w in words[3:])


def test_chunk_float32_width():
    ft = FieldType(tp=FieldTypeTp.FLOAT)
    c = ChunkColumn(ft)
    c.append(1.5)
    c.append(-2.25)
    assert len(c.encode()) == 8 + 2 * 4
    [out] = decode_chunk(c.encode(), [ft])
    assert column_values(out) == [1.5, -2.25]


def test_chunk_time_duration_fixed8():
    for tp_, v in ((FieldTypeTp.DATETIME, 2**40 + 5), (FieldTypeTp.DURATION, -3_600_000_000_000)):
        ft = FieldType(tp=tp_)
        c = ChunkColumn(ft)
        c.append(v)
        c.append(None)
        [out] = decode_chunk(c.encode(), [ft])
        assert column_values(out) == [v, None]
