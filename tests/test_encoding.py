"""Unit tests for copr/encoding.py — the compressed-resident column layer.

Round-trips (encode→decode byte-stable), late-materialize gathers, in-place
payload patches vs demotions, dictionary encoding with sorted codes, the
device-plan eligibility matrix with per-cause decline counters, and the
dict-code-space predicate rewrite rules."""

import numpy as np

from tikv_tpu.copr import encoding as E
from tikv_tpu.copr.cache import ColumnBlockCache
from tikv_tpu.copr.dag import DagRequest, Selection, TableScan
from tikv_tpu.copr.datatypes import Column, ColumnInfo, EvalType, FieldType
from tikv_tpu.copr.rpn import call, col, const_bytes
from tikv_tpu.util.metrics import REGISTRY

from copr_fixtures import TABLE_ID


def _col(values):
    return Column.from_values(EvalType.INT, values)


def _cache_with(cols_per_block, n_valids):
    cache = ColumnBlockCache()
    for cols, nv in zip(cols_per_block, n_valids):
        cache.add(cols, nv)
    cache.filled = True
    return cache


def _counter_val(name, **labels):
    c = REGISTRY._metrics.get(name)
    if c is None:
        return 0
    return c.get(**labels)


# -- encode / decode round trips --------------------------------------------

def test_bitpack_round_trip_and_nulls():
    vals = [100, 105, None, 227] * 200
    c = _col(vals)
    e = E._encode_one(c, len(vals))
    assert e is not None and e.kind == "bp"
    assert e.packed.dtype == np.int8 and e.ref == 100
    assert np.array_equal(e.data, c.data)  # null slots normalize to 0
    assert np.array_equal(e.nulls, c.nulls)
    assert e.encoded_nbytes() < (c.data.nbytes + c.nulls.nbytes) // 4


def test_rle_round_trip_with_null_runs():
    vals = [7] * 500 + [None] * 300 + [-2] * 200
    c = _col(vals)
    e = E._encode_one(c, len(vals))
    assert e is not None and e.kind == "rle"
    assert len(e.run_values) == 3
    assert np.array_equal(e.data, c.data)
    assert np.array_equal(e.nulls, c.nulls)


def test_take_late_materializes_only_selected_rows():
    c = _col(list(range(50, 150)) * 10)
    e = E._encode_one(c, 1000)
    assert e is not None and e.kind == "bp"
    idx = np.array([0, 7, 999])
    t = e.take(idx)
    assert list(t.data) == [int(c.data[i]) for i in idx]
    r = E._encode_one(_col([3] * 900 + [4] * 100), 1000)
    assert r.kind == "rle"
    t2 = r.take(np.array([0, 899, 900, 999]))
    assert list(t2.data) == [3, 3, 4, 4]


def test_wide_range_column_stays_plain():
    rng = np.random.default_rng(0)
    c = _col([int(x) for x in rng.integers(-(1 << 40), 1 << 40, 500)])
    assert E._encode_one(c, 500) is None


def test_real_columns_stay_plain():
    c = Column.from_values(EvalType.REAL, [1.5, 1.5, 1.5] * 100)
    assert E._encode_one(c, 300) is None


# -- in-place patch vs demote ------------------------------------------------

def test_bitpack_patch_in_range_and_demote_out_of_range():
    c = _col([10, 20, 30] * 100)
    e = E._encode_one(c, 300)
    assert e.try_patch(np.array([1]), np.array([25]), np.array([False]))
    assert int(e.data[1]) == 25
    # out of the int8 frame → encoding broken
    assert not e.try_patch(np.array([2]), np.array([1 << 40]), np.array([False]))


def test_rle_never_patches_in_place():
    e = E._encode_one(_col([5] * 1000), 1000)
    assert e.kind == "rle"
    assert not e.try_patch(np.array([0]), np.array([6]), np.array([False]))


def test_demote_column_counts_and_drops_pins():
    cache = _cache_with([[_col([1, 1, 1, 1])]], [4])
    E.encode_blocks(cache, None)
    assert isinstance(cache.blocks[0].cols[0], E.EncodedColumn)
    before = _counter_val("tikv_tpu_never", x="y")  # counter access shape
    v0 = cache.enc_version
    E.demote_column(cache, 0, "inplace_update")
    assert not isinstance(cache.blocks[0].cols[0], E.EncodedColumn)
    assert cache.enc_version > v0
    assert _counter_val("tikv_coprocessor_encoding_demote_total",
                        kind="rle", cause="inplace_update") >= 1
    assert before == 0


# -- fill-time stats pass ----------------------------------------------------

def test_encode_blocks_uniform_choice_and_dictionary():
    n = 60
    name = np.empty(n, dtype=object)
    name[:] = [[b"b", b"a", b"c"][i % 3] for i in range(n)]
    blocks = [
        [_col(list(range(1, n + 1))),                   # increasing → bp
         Column(EvalType.BYTES, name, np.zeros(n, bool)),
         _col([9] * n)],                                 # runs → rle
    ]
    cache = _cache_with(blocks, [n])
    changed = E.encode_blocks(cache, None)
    assert changed[0] == "bp" and changed[2] == "rle"
    assert changed[1] == "dict"
    dcol = cache.blocks[0].cols[1]
    assert dcol.is_dict_encoded
    # dictionary is SORTED → order-preserving codes (range rewrites)
    assert [bytes(v) for v in dcol.dictionary] == [b"a", b"b", b"c"]
    assert np.array_equal(dcol.data[:6], [1, 0, 2, 1, 0, 2])
    assert dcol.data.dtype == np.int8


def test_ensure_code_capacity_widens_lanes():
    codes = np.array([0, 1, 2], dtype=np.int8)
    d = np.empty(3, dtype=object)
    d[:] = [b"a", b"b", b"c"]
    cache = _cache_with(
        [[Column(EvalType.BYTES, codes, np.zeros(3, bool), 0, d)]], [3])
    assert not E.ensure_code_capacity(cache.blocks, 0, 100)   # fits
    assert E.ensure_code_capacity(cache.blocks, 0, 1 << 20)   # widens
    assert cache.blocks[0].cols[0].data.dtype.itemsize >= 4


# -- device plans / eligibility matrix --------------------------------------

def _encoded_cache(seed=0, n=256):
    rng = np.random.default_rng(seed)
    cache = _cache_with(
        [[_col([int(x) for x in rng.integers(0, 50, n)]), _col([3] * n)]], [n])
    E.encode_blocks(cache, None)
    return cache


def test_device_plan_descriptors_and_memo():
    cache = _encoded_cache()
    plan = E.device_plan(cache, [0, 1], [])
    assert plan is not None
    assert plan.sig[0][0] == "bp" and plan.sig[1][0] == "rle"
    assert E.device_plan(cache, [0, 1], []) is plan  # memoized
    E.demote_column(cache, 1, "inplace_update")
    plan2 = E.device_plan(cache, [0, 1], [])
    assert plan2 is not plan and plan2.sig[1] == ("plain",)


def test_batch_plan_mismatch_and_rle_declines_counted():
    a, b = _encoded_cache(1), _encoded_cache(2)
    # identical shapes/signatures → encoded
    assert E.batch_plan([a, b], [0, 1], [], "xregion") is not None
    # rle excluded on the sharded path → decode-ship, counted per-cause
    before = _counter_val("tikv_coprocessor_encoded_decline_total",
                          path="mesh_sharded", cause="rle_sharded")
    assert E.batch_plan([a, b], [0, 1], [], "mesh_sharded",
                        allow_rle=False) is None
    assert _counter_val("tikv_coprocessor_encoded_decline_total",
                        path="mesh_sharded", cause="rle_sharded") == before + 1
    # signature mismatch (one cache demoted) → decode-ship, counted
    E.demote_column(b, 0, "inplace_update")
    before = _counter_val("tikv_coprocessor_encoded_decline_total",
                          path="xregion", cause="enc_mismatch")
    assert E.batch_plan([a, b], [0, 1], [], "xregion") is None
    assert _counter_val("tikv_coprocessor_encoded_decline_total",
                        path="xregion", cause="enc_mismatch") == before + 1


def test_byte_accounting_encoded_vs_decoded():
    cache = _encoded_cache()
    assert cache.nbytes() < cache.nbytes_decoded() // 2


# -- dict-code-space rewrite -------------------------------------------------

def _dict_blocks(values, sorted_dict=True):
    values = list(values) * 30  # clear the cardinality gate
    data = np.empty(len(values), dtype=object)
    data[:] = values
    cache = _cache_with(
        [[_col(list(range(len(values)))),
          Column(EvalType.BYTES, data, np.zeros(len(values), bool))]],
        [len(values)])
    E.encode_blocks(cache, None)
    if not sorted_dict:
        # simulate a delta-grown (append-ordered) dictionary
        c = cache.blocks[0].cols[1]
        d = np.empty(len(c.dictionary) + 1, dtype=object)
        d[:-1] = c.dictionary
        d[-1] = b"a_late"
        c.dictionary = d
    return cache.blocks


def _sel_dag(cond):
    cols_info = [ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
                 ColumnInfo(2, FieldType.varchar())]
    return DagRequest(executors=[TableScan(TABLE_ID, cols_info),
                                 Selection([cond])])


def test_rewrite_probe_and_eq_rewrite():
    dag = _sel_dag(call("eq", col(1), const_bytes(b"bb")))
    assert E.dict_rewrite_probe(dag)
    blocks = _dict_blocks([b"aa", b"bb", b"cc", b"bb"])
    new_dag, rewritten = E.rewrite_dag_for_dict(dag, blocks)
    assert new_dag is not None and rewritten == {1}
    cond = new_dag.executors[1].conditions[0]
    assert cond.op == "eq" and cond.children[1].value == 1  # code of b"bb"
    assert cond.children[1].eval_type == EvalType.INT
    # absent constant maps to the impossible code -1
    dag2 = _sel_dag(call("eq", col(1), const_bytes(b"zz")))
    nd2, _ = E.rewrite_dag_for_dict(dag2, blocks)
    assert nd2.executors[1].conditions[0].children[1].value == -1


def test_rewrite_range_requires_sorted_dictionary():
    dag = _sel_dag(call("lt", col(1), const_bytes(b"bb")))
    nd, _ = E.rewrite_dag_for_dict(dag, _dict_blocks([b"aa", b"bb", b"cc"]))
    assert nd is not None
    nd2, cause = E.rewrite_dag_for_dict(
        dag, _dict_blocks([b"aa", b"bb", b"cc"], sorted_dict=False))
    assert nd2 is None and cause == "dict_unsorted"
    # equality stays rewritable on the unsorted dictionary
    nd3, _ = E.rewrite_dag_for_dict(
        _sel_dag(call("eq", col(1), const_bytes(b"a_late"))),
        _dict_blocks([b"aa", b"bb", b"cc"], sorted_dict=False))
    assert nd3 is not None


def test_rewrite_declines_outside_references():
    """A rewritten column's schema entry becomes INT, so a reference
    anywhere else (aggregate arg, group-by, another condition) would serve
    raw dictionary codes — the rewrite must decline those plans."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation

    blocks = _dict_blocks([b"aa", b"bb", b"cc"])
    cols_info = [ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
                 ColumnInfo(2, FieldType.varchar())]
    for extra in (Aggregation([], [AggDescriptor("max", col(1))]),
                  Aggregation([col(1)], [AggDescriptor("count", None)])):
        dag = DagRequest(executors=[
            TableScan(TABLE_ID, cols_info),
            Selection([call("ge", col(1), const_bytes(b"bb"))]),
            extra,
        ])
        nd, cause = E.rewrite_dag_for_dict(dag, blocks)
        assert nd is None and cause == "outside_reference", (cause, extra)
    # an unrewritable condition referencing the column blocks it too
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, cols_info),
        Selection([call("eq", col(1), const_bytes(b"bb")),
                   call("eq", col(1), col(1))]),
    ])
    nd, cause = E.rewrite_dag_for_dict(dag, blocks)
    assert nd is None and cause == "outside_reference"


def test_rewrite_probe_rejects_non_candidates():
    cols_info = [ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
                 ColumnInfo(2, FieldType.int64())]
    dag = DagRequest(executors=[TableScan(TABLE_ID, cols_info),
                                Selection([call("eq", col(1), col(1))])])
    assert not E.dict_rewrite_probe(dag)
