"""txn_types data model tests (reference: components/txn_types tests)."""

import pytest

from tikv_tpu.storage import txn_types as t
from tikv_tpu.storage.txn_types import Key, Lock, LockType, Mutation, Write, WriteType


def test_timestamp_compose():
    ts = t.compose_ts(423456789, 1024)
    assert t.ts_physical(ts) == 423456789
    assert t.ts_logical(ts) == 1024
    assert t.ts_next(ts) == ts + 1
    assert t.ts_prev(ts) == ts - 1


def test_key_roundtrip_and_ts():
    k = Key.from_raw(b"hello")
    assert k.to_raw() == b"hello"
    kt = k.append_ts(42)
    assert kt.decode_ts() == 42
    assert kt.truncate_ts() == k
    base, ts = kt.split_on_ts()
    assert base == k and ts == 42
    assert k.is_encoded_from(b"hello")
    assert not k.is_encoded_from(b"world")


def test_key_ts_ordering():
    """Newer timestamps must sort *before* older ones under the same key."""
    k = Key.from_raw(b"k")
    v1 = k.append_ts(100).encoded
    v2 = k.append_ts(200).encoded
    v3 = k.append_ts(300).encoded
    assert v3 < v2 < v1
    # and all versions of 'k' sort before any version of the next key
    assert v1 < Key.from_raw(b"k\x00").append_ts(2**63).encoded


@pytest.mark.parametrize(
    "w",
    [
        Write(WriteType.PUT, 100),
        Write(WriteType.PUT, 100, short_value=b"short"),
        Write(WriteType.DELETE, 5),
        Write(WriteType.LOCK, 2**60),
        Write(WriteType.ROLLBACK, 7),
        Write.new_rollback(7, protected=True),
        Write(WriteType.PUT, 100, short_value=b"", has_overlapped_rollback=True),
        Write(WriteType.PUT, 100, gc_fence=0),
        Write(WriteType.PUT, 100, short_value=b"v", has_overlapped_rollback=True, gc_fence=999),
    ],
)
def test_write_roundtrip(w):
    assert Write.from_bytes(w.to_bytes()) == w


def test_write_protected():
    assert Write.new_rollback(1, True).is_protected()
    assert not Write.new_rollback(1, False).is_protected()
    assert not Write(WriteType.PUT, 1, short_value=b"P").is_protected()


@pytest.mark.parametrize(
    "lock",
    [
        Lock(LockType.PUT, b"pk", 100),
        Lock(LockType.PUT, b"pk", 100, ttl=3000, short_value=b"sv"),
        Lock(LockType.DELETE, b"pk", 100, for_update_ts=120, txn_size=5),
        Lock(LockType.PESSIMISTIC, b"pk", 100, for_update_ts=120),
        Lock(LockType.LOCK, b"pk", 100, min_commit_ts=101),
        Lock(
            LockType.PUT, b"pk", 100, ttl=1, min_commit_ts=103,
            use_async_commit=True, secondaries=[b"s1", b"s2"], rollback_ts=[99, 98],
        ),
    ],
)
def test_lock_roundtrip(lock):
    assert Lock.from_bytes(lock.to_bytes()) == lock


def test_lock_visibility():
    lock = Lock(LockType.PUT, b"pk", ts=100, ttl=10)
    assert lock.is_visible_to(99)
    assert not lock.is_visible_to(100)
    assert not lock.is_visible_to(150)
    assert lock.is_visible_to(150, bypass_locks=frozenset([100]))
    # Lock/Pessimistic never block reads
    assert Lock(LockType.LOCK, b"pk", 100).is_visible_to(200)
    assert Lock(LockType.PESSIMISTIC, b"pk", 100).is_visible_to(200)
    # min_commit_ts pushed above the reader
    assert Lock(LockType.PUT, b"pk", 100, min_commit_ts=201).is_visible_to(200)


def test_mutation_helpers():
    k = Key.from_raw(b"k")
    assert Mutation.put(k, b"v").lock_type() == LockType.PUT
    assert Mutation.insert(k, b"v").lock_type() == LockType.PUT
    assert Mutation.delete(k).lock_type() == LockType.DELETE
    assert Mutation.lock(k).lock_type() == LockType.LOCK
    assert Mutation.insert(k, b"v").should_not_exists()
    assert Mutation.check_not_exists(k).should_not_exists()
    assert not Mutation.put(k, b"v").should_not_exists()
