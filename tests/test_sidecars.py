"""Sidecar subsystems: GC, lock manager/deadlock, resolved-ts, CDC, backup,
config system, metrics, status server."""

import json
import threading
import urllib.request

import pytest

from tikv_tpu.server.gc_worker import GcManager, GcWorker
from tikv_tpu.server.lock_manager import DeadlockDetector, DeadlockError, WaiterManager
from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage, SstImporter
from tikv_tpu.sidecar.cdc import CdcObserver
from tikv_tpu.sidecar.resolved_ts import ResolvedTsEndpoint, Resolver
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn.commands import Commit, Prewrite, Rollback
from tikv_tpu.storage.txn_types import Key, Mutation
from tikv_tpu.util.config import ConfigController, TikvConfig
from tikv_tpu.util.metrics import Registry


def put(store, key, value, start_ts, commit_ts):
    r = store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(key), value)], key, start_ts))
    assert "errors" not in r
    store.sched_txn_command(Commit([Key.from_raw(key)], start_ts, commit_ts))


# -- GC ---------------------------------------------------------------------

def test_gc_drops_old_versions_keeps_visible():
    store = Storage()
    for i, (s, c) in enumerate([(10, 11), (20, 21), (30, 31), (40, 41)]):
        put(store, b"k", b"v%d" % i, s, c)
    gc = GcWorker(store.engine)
    stats = gc.gc_range(None, None, safe_point=25)
    # versions below the base at safe point 25 (commit 21) are gone
    assert stats["versions_deleted"] >= 1
    assert store.get(b"k", 100) == b"v3"
    assert store.get(b"k", 25) == b"v1"  # base at safe point survives
    # reads below the dropped versions no longer see them
    assert store.get(b"k", 11) is None


def test_gc_removes_deleted_keys():
    store = Storage()
    put(store, b"d", b"v", 10, 11)
    store.sched_txn_command(Prewrite([Mutation.delete(Key.from_raw(b"d"))], b"d", 20))
    store.sched_txn_command(Commit([Key.from_raw(b"d")], 20, 21))
    gc = GcWorker(store.engine)
    gc.gc_range(None, None, safe_point=50)
    # the whole key history is physically gone
    assert list(store.engine.snapshot(None).scan_cf(CF_WRITE, b"", None)) == []


def test_gc_rollback_markers_and_manager():
    store = Storage()
    put(store, b"k", b"v", 10, 11)
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"r"), b"x")], b"r", 30))
    store.sched_txn_command(Rollback([Key.from_raw(b"r")], 30))
    gc = GcWorker(store.engine)

    class FakePd:
        def get_gc_safe_point(self):
            return 40

    mgr = GcManager(gc, FakePd(), interval=0.01)
    mgr.start()
    import time

    time.sleep(0.1)
    mgr.stop()
    assert mgr.last_safe_point == 40
    assert store.get(b"k", 100) == b"v"


def test_gc_physical_scan_lock_and_destroy_range():
    store = Storage()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"L1"), b"v")], b"L1", 15))
    gc = GcWorker(store.engine)
    locks = gc.physical_scan_lock(max_ts=100)
    assert [(k, l.ts) for k, l in locks] == [(b"L1", 15)]
    put(store, b"x1", b"v", 20, 21)
    gc.unsafe_destroy_range(b"L", b"z")
    assert gc.physical_scan_lock(100) == []
    assert store.get(b"x1", 100) is None


# -- lock manager / deadlock -------------------------------------------------

def test_deadlock_detection_cycle():
    det = DeadlockDetector()
    det.detect(1, 2)  # txn1 waits on txn2
    det.detect(2, 3)
    with pytest.raises(DeadlockError) as ei:
        det.detect(3, 1)  # closes 3→1→2→3
    assert set(ei.value.cycle) >= {1, 2, 3}
    # cleanup breaks the graph
    det.clean_up(1)
    det.detect(3, 1)


def test_waiter_manager_wake_on_release():
    wm = WaiterManager(default_timeout=5)
    results = []

    def waiter():
        results.append(wm.wait_for(start_ts=100, lock_ts=50, key=b"k"))

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.05)
    assert wm.wake_up(b"k", released_ts=50) == 1
    t.join(timeout=2)
    assert results == [True]


def test_waiter_timeout():
    wm = WaiterManager(default_timeout=0.05)
    assert wm.wait_for(1, 2, b"k") is False


# -- resolved ts -------------------------------------------------------------

def test_resolver_watermark():
    r = Resolver(1)
    assert r.resolve(100) == 100
    r.track_lock(120, b"a")
    r.track_lock(150, b"b")
    assert r.resolve(200) == 119  # min lock - 1
    r.untrack_lock(b"a")
    assert r.resolve(200) == 149
    r.untrack_lock(b"b")
    assert r.resolve(200) == 200
    # never regresses
    assert r.resolve(50) == 200


def test_resolver_retrack_same_key_newer_ts_drops_stale_heap_head():
    """track -> untrack -> re-track of ONE key at a newer ts: the old heap
    head is stale (locks_by_key moved on) and must not pin the watermark."""
    r = Resolver(1)
    r.track_lock(10, b"k")
    r.untrack_lock(b"k")
    r.track_lock(20, b"k")
    # the (10, k) heap head is stale: the live lock is 20, so the watermark
    # pins at 19, NOT 9
    assert r.resolve(100) == 19
    # re-track the SAME key even newer while the (20, k) entry still sits
    # in the heap — again only the live registration counts
    r.track_lock(40, b"k")
    assert r.resolve(100) == 39
    r.untrack_lock(b"k")
    assert r.resolve(100) == 100


def test_resolver_watermark_never_regresses_under_late_lock():
    """A lock tracked BELOW the published watermark (late replay, observer
    race) must not pull resolved_ts backwards — the max() keeps the
    guarantee reads at/below the watermark rely on."""
    r = Resolver(1)
    assert r.resolve(100) == 100
    r.track_lock(50, b"late")
    assert r.resolve(200) == 100  # candidate 49 loses to the floor
    r.untrack_lock(b"late")
    assert r.resolve(200) == 200


def test_min_resolved_ts_and_safe_ts_with_zero_regions():
    from tikv_tpu.pd.client import MockPd

    ep = ResolvedTsEndpoint(MockPd())
    assert ep.min_resolved_ts() == 0
    assert ep.safe_ts() == 0
    assert ep.progress_snapshot() == {}
    assert ep.progress_of(7) == (0, 0)


def test_safe_ts_minimum_over_progress_and_resolver_fallback():
    """safe_ts = min over known regions: disseminated pairs win where
    present, a region with no pair falls back to its local resolver."""
    from tikv_tpu.pd.client import MockPd

    ep = ResolvedTsEndpoint(MockPd())
    ep.resolver(1).resolve(30)          # local-only region: resolver floor
    with ep._mu:
        ep.read_progress[2] = (12, 4)   # disseminated pair
    assert ep.progress_snapshot() == {1: (30, 0), 2: (12, 4)}
    assert ep.safe_ts() == 12
    with ep._mu:
        ep.read_progress[2] = (45, 5)
    assert ep.safe_ts() == 30


def test_resolved_ts_over_cluster():
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    pd = MockPd()
    cluster = Cluster(3, pd=pd)
    cluster.run()
    ep = ResolvedTsEndpoint(pd)
    for s in cluster.stores.values():
        s.apply_observers.append(ep.observe_apply)
    leader = cluster.wait_leader(FIRST_REGION_ID)
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}
    ts1 = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", ts1), ctx)
    watermarks = ep.advance_all()
    # pending lock pins the watermark below ts1
    assert watermarks[FIRST_REGION_ID] == ts1 - 1
    store.sched_txn_command(Commit([Key.from_raw(b"k")], ts1, pd.get_tso()), ctx)
    w2 = ep.advance_all()[FIRST_REGION_ID]
    assert w2 > ts1


def test_resolved_ts_leadership_gate():
    """read_progress is published only under quorum-confirmed leadership:
    via a valid lease, or a CheckLeader-style (term, leader_id) quorum count
    — so hibernated groups (frozen clock, zeroed lease) keep advancing,
    while an isolated deposed leader never publishes (advance.rs)."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    pd = MockPd()
    cluster = Cluster(3, pd=pd)
    cluster.run()
    ep = ResolvedTsEndpoint(pd)
    for s in cluster.stores.values():
        ep.attach_store(s)
    ep.resolver(FIRST_REGION_ID)
    for st in cluster.stores.values():
        p = st.peers.get(FIRST_REGION_ID)
        if p is not None:
            p.node.hibernate_after = 3
    cluster.tick(40)
    leader = cluster.leader_peer(FIRST_REGION_ID)
    assert leader.node.hibernated and not leader.node.lease_valid()
    ep.advance_all()
    resolved, _ = ep.progress_of(FIRST_REGION_ID)
    assert resolved > 0  # hibernation must not freeze the watermark
    # a leader whose followers no longer recognize it must NOT publish
    before = resolved
    for st in cluster.stores.values():
        p = st.peers.get(FIRST_REGION_ID)
        if p is not None and p.node is not leader.node:
            p.node.term = leader.node.term + 5  # saw a newer election
            p.node.leader_id = None
    leader.node._lease_until = 0
    leader.node.hibernated = True  # frozen: no quorum self-check passes
    ep.advance_all()
    after, _ = ep.progress_of(FIRST_REGION_ID)
    assert after == before  # watermark must not move for a deposed leader


# -- CDC ---------------------------------------------------------------------

def test_cdc_captures_committed_changes():
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    pd = MockPd()
    cluster = Cluster(1, pd=pd)
    cluster.run()
    obs = CdcObserver()
    for s in cluster.stores.values():
        s.apply_observers.append(obs.observe_apply)
    obs.subscribe(FIRST_REGION_ID)
    leader = cluster.wait_leader(FIRST_REGION_ID)
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}

    ts1 = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"c1"), b"v1")], b"c1", ts1), ctx)
    c1 = pd.get_tso()
    store.sched_txn_command(Commit([Key.from_raw(b"c1")], ts1, c1), ctx)
    # update with old value
    ts2 = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"c1"), b"v2")], b"c1", ts2), ctx)
    store.sched_txn_command(Commit([Key.from_raw(b"c1")], ts2, pd.get_tso()), ctx)
    # delete
    ts3 = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.delete(Key.from_raw(b"c1"))], b"c1", ts3), ctx)
    store.sched_txn_command(Commit([Key.from_raw(b"c1")], ts3, pd.get_tso()), ctx)
    # rollback produces no event
    ts4 = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"c2"), b"x")], b"c2", ts4), ctx)
    store.sched_txn_command(Rollback([Key.from_raw(b"c2")], ts4), ctx)

    evs = obs.sink.events
    assert [(e.key, e.op, e.value) for e in evs] == [
        (b"c1", "put", b"v1"),
        (b"c1", "put", b"v2"),
        (b"c1", "delete", None),
    ]
    assert evs[0].old_value is None
    assert evs[1].old_value == b"v1"  # old value captured on update
    assert evs[1].commit_ts > evs[0].commit_ts


def test_cdc_incremental_scan():
    store = Storage()
    put(store, b"a", b"1", 10, 11)
    put(store, b"b", b"2", 20, 21)
    obs = CdcObserver()
    n = obs.incremental_scan(store.engine.snapshot(None), region_id=1, start_ts=15)
    assert n == 1  # only 'a' committed before ts 15
    assert obs.sink.events[0].key == b"a"


# -- backup / restore --------------------------------------------------------

def test_backup_restore_roundtrip(tmp_path):
    store = Storage()
    for i in range(10):
        put(store, b"bk%02d" % i, b"val%d" % i, 10 + i, 11 + i)
    # later write not part of the backup
    put(store, b"bk00", b"newer", 100, 101)
    storage = LocalStorage(str(tmp_path))
    ep = BackupEndpoint(storage)
    meta = ep.backup_range(store.engine.snapshot(None), "full.bak", backup_ts=50)
    assert meta["kvs"] == 10
    # restore into a fresh store
    store2 = Storage()
    imp = SstImporter(storage)
    r = imp.restore(store2.engine, "full.bak", restore_ts=200)
    assert r["kvs"] == 10
    assert store2.get(b"bk00", 300) == b"val0"  # backup_ts view, not 'newer'
    assert store2.get(b"bk09", 300) == b"val9"
    # rewrite rule
    store3 = Storage()
    imp.restore(store3.engine, "full.bak", restore_ts=200, rewrite=(b"bk", b"rk"))
    assert store3.get(b"rk05", 300) == b"val5"
    assert store3.get(b"bk05", 300) is None


# -- config ------------------------------------------------------------------

def test_config_toml_validate_and_unknown_keys():
    cfg = TikvConfig.from_toml("""
[raftstore]
election-tick = 20
heartbeat-tick = 4
[coprocessor]
enable-device = false
""")
    assert cfg.raftstore.election_tick == 20
    assert cfg.coprocessor.enable_device is False
    cfg.validate()
    with pytest.raises(ValueError, match="unknown config keys"):
        TikvConfig.from_toml("[raftstore]\nbogus-key = 1\n")
    with pytest.raises(ValueError, match="heartbeat_tick"):
        TikvConfig.from_toml("[raftstore]\nheartbeat-tick = 50\n").validate()


def test_online_reconfig_dispatch():
    ctl = ConfigController(TikvConfig())
    seen = {}
    ctl.register("coprocessor", lambda changed: seen.update(changed))
    diff = ctl.update({"coprocessor.enable_device": False})
    assert diff == {"coprocessor": {"enable_device": False}}
    assert seen == {"enable_device": False}
    assert ctl.config.coprocessor.enable_device is False
    # invalid updates change nothing
    with pytest.raises(ValueError):
        ctl.update({"raftstore.heartbeat_tick": 99})
    assert ctl.config.raftstore.heartbeat_tick == 2


# -- metrics + status server -------------------------------------------------

def test_metrics_and_status_server():
    from tikv_tpu.server.status_server import StatusServer

    reg = Registry()
    reg.counter("copr_requests_total", "requests").inc(3, path="device")
    reg.gauge("regions", "region count").set(5)
    reg.histogram("req_duration_seconds", "latency").observe(0.004)
    ctl = ConfigController(TikvConfig())
    srv = StatusServer(ctl, registry=reg)
    srv.start()
    host, port = srv.addr
    try:
        body = urllib.request.urlopen(f"http://{host}:{port}/metrics").read().decode()
        assert 'copr_requests_total{path="device"} 3' in body
        assert "regions 5" in body
        assert "req_duration_seconds_bucket" in body
        assert urllib.request.urlopen(f"http://{host}:{port}/status").read() == b"ok"
        cfg = json.loads(urllib.request.urlopen(f"http://{host}:{port}/config").read())
        assert cfg["raftstore"]["election_tick"] == 10
        # online reconfig over HTTP
        req = urllib.request.Request(
            f"http://{host}:{port}/config",
            data=json.dumps({"coprocessor.block_rows": 1024}).encode(),
            method="POST",
        )
        diff = json.loads(urllib.request.urlopen(req).read())
        assert diff == {"coprocessor": {"block_rows": 1024}}
        assert ctl.config.coprocessor.block_rows == 1024
        # invalid POST rejected
        req = urllib.request.Request(
            f"http://{host}:{port}/config",
            data=json.dumps({"coprocessor.block_rows": 1000}).encode(),  # not pow2
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
    finally:
        srv.stop()


# -- debugger + ctl ----------------------------------------------------------

def test_debugger_inspection():
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
    from tikv_tpu.server.debug import Debugger

    cluster = Cluster(3)
    cluster.run()
    leader = cluster.wait_leader(FIRST_REGION_ID)
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}
    put_ctx = lambda k, v, s, c: (
        store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), v)], k, s), ctx),
        store.sched_txn_command(Commit([Key.from_raw(k)], s, c), ctx),
    )
    put_ctx(b"dk", b"dv", 10, 20)
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"locked"), b"x")], b"locked", 30), ctx)

    dbg = Debugger(leader.store.engine)
    assert dbg.all_regions() == [FIRST_REGION_ID]
    info = dbg.region_info(FIRST_REGION_ID)
    assert info["region"]["id"] == FIRST_REGION_ID
    assert len(info["region"]["peers"]) == 3
    assert info["apply_state"]["applied_index"] > 0
    size = dbg.region_size(FIRST_REGION_ID)
    assert size["write"]["keys"] == 1 and size["lock"]["keys"] == 1
    mvcc = dbg.scan_mvcc()
    assert mvcc[0]["commit_ts"] == 20 and mvcc[0]["type"] == "PUT"
    locks = dbg.scan_locks()
    assert locks[0]["ts"] == 30
    log = dbg.raft_log(FIRST_REGION_ID, info["apply_state"]["applied_index"])
    assert log is not None and "cmd" in log
    assert dbg.bad_regions() == []


def test_ctl_cli_over_live_store():
    import io
    from contextlib import redirect_stdout

    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.raftkv import RaftKv
    from tikv_tpu.raft.store import ChannelTransport
    from tikv_tpu.server.node import Node
    from tikv_tpu.server.server import Server
    from tikv_tpu.server.service import KvService

    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import ctl

    pd = MockPd()
    transport = ChannelTransport()
    node = Node(pd, transport)
    transport.register(node.store)
    node.try_bootstrap_cluster([node.store_id])
    node.create_region_peers()
    peer = node.store.peers[1]
    peer.node.campaign()
    node.pump()
    node.start()
    service = KvService(Storage(engine=RaftKv(node.store)), None)
    server = Server(service)
    server.start()
    addr = f"{server.addr[0]}:{server.addr[1]}"
    try:
        assert ctl.main(["--addr", addr, "raw-put", "ck", "cv"]) == 0
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert ctl.main(["--addr", addr, "raw-get", "ck"]) == 0
        assert json.loads(buf.getvalue())["value"] == "cv"
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert ctl.main(["--addr", addr, "raw-scan"]) == 0
        assert len(json.loads(buf.getvalue())["kvs"]) == 1
    finally:
        server.stop()
        node.stop()


def test_ttl_checker_reclaims_expired():
    """ttl_checker.rs: expired raw entries are actively reclaimed, not just
    lazily filtered on read; live and no-TTL entries survive the sweep."""
    import time as _time

    from tikv_tpu.server.ttl_checker import TtlChecker
    from tikv_tpu.storage.engine import CF_DEFAULT
    from tikv_tpu.storage.storage import RAW_PREFIX, Storage

    store = Storage()
    now = _time.time()
    store.raw_put(b"live", b"v", ttl=10_000)
    store.raw_put(b"dead", b"v", ttl=1)
    store.raw_put(b"forever", b"v", ttl=0)
    checker = TtlChecker(store)
    # nothing expired yet
    assert checker.run_once(now=now) == 0
    # after expiry: lazy read already hides it, the sweep deletes it
    later = now + 5
    assert store.raw_get(b"dead", now=later) is None
    n = checker.run_once(now=later)
    assert n == 1 and checker.reclaimed == 1
    raw_keys = [k for k, _ in store.engine.snapshot(None).scan_cf(
        CF_DEFAULT, RAW_PREFIX, RAW_PREFIX[:-1] + bytes([RAW_PREFIX[-1] + 1]))]
    assert raw_keys == [RAW_PREFIX + b"forever", RAW_PREFIX + b"live"]
    assert store.raw_get(b"live", now=later) == b"v"
    assert store.raw_get(b"forever", now=later) == b"v"
    # background loop runs without incident
    checker.interval = 0.05
    checker.start()
    _time.sleep(0.15)
    checker.stop()


def test_ttl_checker_safety_rules():
    """V1 rule: refuses to sweep a store holding txn data; a key re-put
    after the scan snapshot survives the delete batch."""
    import time as _time

    from tikv_tpu.server.ttl_checker import TtlChecker
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    mixed = Storage()
    mixed.raw_put(b"rk", b"v", ttl=1)
    mixed.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"rx"), b"txn")], b"rx", 10))
    mixed.sched_txn_command(Commit([Key.from_raw(b"rx")], 10, 11))
    checker = TtlChecker(mixed)
    with pytest.raises(RuntimeError, match="raw-mode"):
        checker.run_once(now=_time.time() + 10)
    assert mixed.get(b"rx", 20) == b"txn"  # txn data untouched
    # errors recorded, loop survives
    checker.interval = 0.02
    checker.start()
    _time.sleep(0.08)
    checker.stop()
    assert checker.errors > 0 and "raw-mode" in checker.last_error
    # stop/start resumes (the event is cleared)
    checker.start()
    assert checker._thread.is_alive()
    checker.stop()


def test_region_driven_backup_with_checksums(tmp_path):
    """Reference-depth backup (endpoint.rs:434 + writer.rs): regions iterate
    via the RegionInfoAccessor, leader ranges scan through their own region
    snapshots, files split by size and carry mergeable crc64 checksums, and
    restore is backupmeta-driven."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
    from tikv_tpu.sidecar.backup import BackupEndpoint, LocalStorage, RegionInfoAccessor
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    pd = MockPd()
    c = Cluster(1, pd=pd)
    c.run()
    leader = c.wait_leader(FIRST_REGION_ID)
    storage = Storage(engine=c.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}

    def put(key, value, rid=None):
        ts = pd.get_tso()
        cx = {"region_id": rid or c.region_for_key(key)}
        storage_for = Storage(engine=c.raftkv(1))
        storage_for.sched_txn_command(
            Prewrite([Mutation.put(Key.from_raw(key), value)], key, ts), cx)
        storage_for.sched_txn_command(Commit([Key.from_raw(key)], ts, pd.get_tso()), cx)

    for i in range(40):
        put(b"bk-%03d" % i, b"val-%03d" % i)
    # split so the backup must walk MULTIPLE regions
    c.split_region(FIRST_REGION_ID, b"bk-020")
    backup_ts = pd.get_tso()
    for i in range(5):
        put(b"bk-9%02d" % i, b"after-backup")  # not part of the view

    store = c.stores[1]
    acc = RegionInfoAccessor(store)
    overlapping = acc.regions_in_range(b"bk-", b"bk-\xff")
    assert len(overlapping) == 2

    ep = BackupEndpoint(LocalStorage(str(tmp_path / "bk")))
    meta = ep.backup(store, "full", backup_ts, max_file_bytes=200)
    assert len(meta["regions"]) == 2
    assert meta["total_kvs"] == 40
    # size splitting produced multiple files per region
    assert sum(len(r["files"]) for r in meta["regions"]) > 2
    # checksums verify against the stored bytes
    v = ep.verify("full")
    assert v["total_kvs"] == 40 and v["crc64xor"] == meta["crc64xor"]
    # corrupting one file fails verification loudly
    storage_dir = tmp_path / "bk"
    victim = meta["regions"][0]["files"][0]["file"]
    raw = (storage_dir / victim).read_bytes()
    (storage_dir / victim).write_bytes(raw[:-3] + b"\x00\x00\x00")
    with pytest.raises(ValueError):
        ep.verify("full")
    (storage_dir / victim).write_bytes(raw)

    # meta-driven restore into a fresh store sees the backup_ts view
    store2 = Storage()
    r = ep.restore(store2.engine, "full", restore_ts=backup_ts + 10)
    assert r["kvs"] == 40
    assert store2.get(b"bk-000", pd.get_tso()) == b"val-000"
    assert store2.get(b"bk-900", pd.get_tso()) is None  # post-backup write


def test_ttl_checker_reclaims_expired_raw_entries():
    """ttl_checker.rs role: expired raw values physically disappear via the
    replicated delete path; live ones survive; reads were already filtered."""
    from tikv_tpu.server.ttl import TtlChecker
    from tikv_tpu.storage.storage import RAW_PREFIX
    from tikv_tpu.storage.engine import CF_DEFAULT

    store = Storage()
    now = 1_000_000.0
    import time as _time
    real_time = _time.time
    _time.time = lambda: now
    try:
        store.raw_put(b"ttl-a", b"va", ttl=10)
        store.raw_put(b"ttl-b", b"vb", ttl=10_000)
        store.raw_put(b"ttl-c", b"vc")  # no TTL
    finally:
        _time.time = real_time
    later = now + 100
    # reads filter, but the bytes are still resident pre-sweep
    assert store.raw_get(b"ttl-a", now=later) is None
    snap = store.engine.snapshot(None)
    resident = [k for k, _ in snap.scan_cf(CF_DEFAULT, RAW_PREFIX, b"s")]
    assert len(resident) == 3
    checker = TtlChecker(store)
    removed = checker.sweep(now=later)
    assert removed == 1
    snap = store.engine.snapshot(None)
    resident = [k for k, _ in snap.scan_cf(CF_DEFAULT, RAW_PREFIX, b"s")]
    assert len(resident) == 2
    assert store.raw_get(b"ttl-b", now=later) == b"vb"
    assert store.raw_get(b"ttl-c", now=later) == b"vc"
    assert checker.sweep(now=later) == 0  # idempotent


def test_ttl_sweep_never_destroys_fresh_writes():
    """The sweep's delete re-checks expiry under the raw latches: a value
    re-written (live) after the scan snapshot must survive the delete that
    was queued for its expired predecessor."""
    store = Storage()
    now = 2_000_000.0
    import time as _time
    real_time = _time.time
    _time.time = lambda: now
    try:
        store.raw_put(b"race-k", b"old", ttl=5)
    finally:
        _time.time = real_time
    later = now + 100
    # the sweep scanned and queued b"race-k"... then a client writes fresh:
    _time.time = lambda: later
    try:
        store.raw_put(b"race-k", b"fresh")  # no TTL
    finally:
        _time.time = real_time
    from tikv_tpu.server.ttl import TtlChecker  # noqa: F401 (path parity)

    removed = store.raw_delete_if_expired([b"race-k"], now=later)
    assert removed == 0
    assert store.raw_get(b"race-k", now=later + 1) == b"fresh"


def test_check_leader_single_replica_self_vote():
    """RPC-mode leadership confirmation with NO peer stores (single-replica
    region, or all other replicas colocated): the self-vote alone is a
    majority of one voter and the region must confirm — an empty fan-out
    used to return nothing and stall read_progress forever."""
    from types import SimpleNamespace

    from tikv_tpu.sidecar.resolved_ts import ResolvedTsEndpoint

    ep = ResolvedTsEndpoint(pd=None, store_id=1,
                            check_leader_send=lambda sid, payload: None)
    region = SimpleNamespace(peers=[SimpleNamespace(store_id=1, role="voter")])
    peer = SimpleNamespace(region=region, node=SimpleNamespace(term=3, id=11))
    confirmed = ep._check_leader_round({42: peer}, {42: peer})
    assert confirmed == {42}

    # two-replica region with the peer store unreachable: 1 of 2 votes is
    # NOT a majority — must stay unconfirmed (the fix only tallies, it must
    # not loosen the quorum rule)
    region2 = SimpleNamespace(peers=[
        SimpleNamespace(store_id=1, role="voter"),
        SimpleNamespace(store_id=2, role="voter"),
    ])
    peer2 = SimpleNamespace(region=region2, node=SimpleNamespace(term=3, id=11))
    ep2 = ResolvedTsEndpoint(pd=None, store_id=1,
                             check_leader_send=lambda sid, payload: None)
    confirmed2 = ep2._check_leader_round({42: peer2}, {42: peer2})
    assert confirmed2 == set()
