"""Collations (reference: tidb_query_datatype/src/codec/collation): sort-key
equivalence, PAD SPACE, case folding, and kernel/group-by integration."""

import numpy as np
import pytest

from tikv_tpu.copr.collation import get_collator
from tikv_tpu.copr.rpn import call, col, compile_expr, const_bytes, eval_rpn
from tikv_tpu.copr.datatypes import EvalType


def test_binary_collator_is_identity():
    c = get_collator("binary")
    assert c.sort_key(b"Abc ") == b"Abc "  # NO PAD: trailing space significant
    assert c.compare(b"a", b"B") > 0


def test_utf8mb4_bin_pad_space():
    c = get_collator("utf8mb4_bin")
    assert c.eq("abc".encode(), "abc   ".encode())  # PAD SPACE
    assert not c.eq(b"abc", b"Abc")  # case-sensitive
    assert c.compare("a".encode(), "b".encode()) < 0
    # codepoint order beyond ASCII
    assert c.compare("é".encode(), "z".encode()) > 0


def test_general_ci_semantics():
    c = get_collator("utf8mb4_general_ci")
    assert c.eq(b"HELLO", b"hello")
    assert c.eq(b"Hello  ", b"hello")  # PAD SPACE too
    assert c.eq("Ä".encode(), "ä".encode())
    assert not c.eq(b"a", b"b")
    # sort keys order case-insensitively: 'apple' < 'Banana' < 'cherry'
    keys = sorted([b"cherry", b"Banana", b"apple"], key=c.sort_key)
    assert keys == [b"apple", b"Banana", b"cherry"]
    # supplementary plane collapses, BMP compares by uppercased codepoint
    assert c.compare("😀".encode(), "😁".encode()) == 0


def test_collator_lookup_by_tidb_id():
    assert get_collator(-45).name == "utf8mb4_general_ci"
    assert get_collator(63).name == "binary"
    with pytest.raises(ValueError):
        get_collator("latin1_swedish_ci")
    with pytest.raises(ValueError):
        get_collator(999)


def _run(expr, columns, n):
    rpn = compile_expr(expr, [(EvalType.BYTES, 0)])
    return eval_rpn(rpn, columns, n, xp=np)


def test_collation_kernels():
    vals = np.array([b"Widget", b"WIDGET  ", b"gadget", b"widgeta"], dtype=object)
    cols = {0: (vals, np.zeros(4, dtype=bool))}
    d, _ = _run(call("eq_utf8mb4_general_ci", col(0), const_bytes(b"widget")), cols, 4)
    assert list(d) == [1, 1, 0, 0]
    d, _ = _run(call("eq_utf8mb4_bin", col(0), const_bytes(b"WIDGET")), cols, 4)
    assert list(d) == [0, 1, 0, 0]  # pad space, case-sensitive
    d, _ = _run(call("like_ci", col(0), const_bytes(b"widget%")), cols, 4)
    assert list(d) == [1, 1, 0, 1]
    # sort_key feeds ordinary byte comparisons
    d, _ = _run(
        call(
            "eq",
            call("sort_key_utf8mb4_general_ci", col(0)),
            call("sort_key_utf8mb4_general_ci", const_bytes(b"WiDgEt   ")),
        ),
        cols,
        4,
    )
    assert list(d) == [1, 1, 0, 0]


def test_ci_group_by_via_sort_key():
    """GROUP BY a CI column: group on sort_key(col), output first(col) —
    the executor composition the collation framework is designed for."""
    from tikv_tpu.copr.groupby import GroupDict
    from tikv_tpu.copr.collation import get_collator

    c = get_collator("utf8mb4_general_ci")
    vals = [b"Apple", b"APPLE", b"pear", b"apple  ", b"Pear"]
    keys = np.array([c.sort_key(v) for v in vals], dtype=object)
    gd = GroupDict()
    gids = gd.assign([(keys, np.zeros(len(vals), dtype=bool))])
    assert len(gd) == 2
    assert list(gids) == [0, 0, 1, 0, 1]
    # first-occurrence ordering preserves the original first spellings
    first = {}
    for v, g in zip(vals, gids):
        first.setdefault(int(g), v)
    assert first == {0: b"Apple", 1: b"pear"}


def test_like_ci_folds_unicode():
    vals = np.array(["Äpfel".encode(), "äpfel".encode(), b"apfel"], dtype=object)
    cols = {0: (vals, np.zeros(3, dtype=bool))}
    d, _ = _run(call("like_ci", col(0), const_bytes("ä%".encode())), cols, 3)
    assert list(d) == [1, 1, 0]


# --------------------------------------------------------- utf8mb4_unicode_ci

def test_unicode_ci_case_insensitive():
    c = get_collator("utf8mb4_unicode_ci")
    assert c.eq("Hello".encode(), "hELLO".encode())
    assert c.compare("abc".encode(), "ABD".encode()) < 0


def test_unicode_ci_accent_insensitive():
    c = get_collator("utf8mb4_unicode_ci")
    assert c.eq("café".encode(), "cafe".encode())
    assert c.eq("Ére".encode(), "ere".encode())
    # general_ci does NOT fold accents the same way (é keeps its codepoint)
    g = get_collator("utf8mb4_general_ci")
    assert not g.eq("café".encode(), "cafe".encode())


def test_unicode_ci_expansions():
    c = get_collator("utf8mb4_unicode_ci")
    assert c.eq("straße".encode(), "STRASSE".encode())  # ß → ss
    assert c.eq("ﬁne".encode(), "fine".encode())  # ﬁ ligature → fi


def test_unicode_ci_supplementary_collapses():
    c = get_collator("utf8mb4_unicode_ci")
    assert c.eq("😀".encode(), "😂".encode())  # both weigh 0xFFFD


def test_unicode_ci_pad_space():
    c = get_collator("utf8mb4_unicode_ci")
    assert c.eq(b"abc  ", b"ABC")


def test_unicode_ci_by_tidb_id():
    assert get_collator(224).name == "utf8mb4_unicode_ci"
    assert get_collator(-224).name == "utf8mb4_unicode_ci"


def test_unicode_ci_sort_key_orders():
    c = get_collator("utf8mb4_unicode_ci")
    words = [w.encode() for w in ["Zebra", "åpple", "Apple", "banana", "ÉCLAIR"]]
    got = sorted(words, key=c.sort_key)
    # primary weights: apple==åpple group first (stable), then banana, eclair, zebra
    folded = [w.decode().lower() for w in got]
    assert folded[-1] == "zebra"
    assert set(folded[:2]) == {"åpple", "apple"}
