"""Test config: force JAX onto a virtual 8-device CPU mesh.

Setting env vars alone is not reliable (pytest plugins may import jax before
this conftest), so the platform is also forced through jax.config.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Sanitize-enabled smoke pass (docs/static_analysis.md): running ANY test
# selection with TIKV_TPU_SANITIZE=1 arms the lock-order sanitizer for every
# wired subsystem and fails the session if a cycle was observed anywhere.
if os.environ.get("TIKV_TPU_SANITIZE") == "1":
    import pytest  # noqa: E402

    @pytest.fixture(scope="session", autouse=True)
    def _sanitizer_session_gate():
        yield
        from tikv_tpu.analysis import sanitizer

        cycles = sanitizer.reports("lock-order-cycle")
        assert not cycles, (
            "lock-order inversions observed during the run:\n\n"
            + "\n\n".join(r.format() for r in cycles)
        )
        from tikv_tpu.analysis import bufsan

        violations = bufsan.reports()
        assert not violations, (
            "buffer mutations while exposed observed during the run:\n\n"
            + "\n\n".join(r.format() for r in violations)
        )
