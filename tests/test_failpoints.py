"""Failpoint fault-injection cases (reference: tests/failpoints/cases/,
fail_point! sites like coprocessor_parse_request, scheduler paths)."""

import threading

import pytest

from tikv_tpu.util import failpoint
from tikv_tpu.util.failpoint import FailpointError, cfg, fail_point, teardown
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn.commands import Commit, Prewrite
from tikv_tpu.storage.txn_types import Key, Mutation


@pytest.fixture(autouse=True)
def _clean():
    teardown()
    yield
    teardown()


def test_failpoint_actions():
    fail_point("nope")  # unconfigured: no-op
    cfg("p1", "return")
    with pytest.raises(FailpointError):
        fail_point("p1")
    cfg("p1", "off")
    fail_point("p1")
    cfg("p2", "2*return")
    for _ in range(2):
        with pytest.raises(FailpointError):
            fail_point("p2")
    fail_point("p2")  # count exhausted
    cfg("p3", "panic")
    with pytest.raises(RuntimeError, match="panic"):
        fail_point("p3")
    assert failpoint.list_active() == {"p3": "panic"}


def test_scheduler_failpoint_blocks_write_atomically():
    """A fault before the engine write must leave no partial state."""
    store = Storage()
    cfg("scheduler_before_write", "return")
    with pytest.raises(FailpointError):
        store.sched_txn_command(
            Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10)
        )
    teardown()
    # nothing was written — and the latch was released (no deadlock)
    assert store.scan_lock(None, None, 100) == []
    r = store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10))
    assert "errors" not in r
    store.sched_txn_command(Commit([Key.from_raw(b"k")], 10, 20))
    assert store.get(b"k", 30) == b"v"


def test_scheduler_snapshot_failpoint_fails_command_cleanly():
    """A fault at snapshot acquisition (scheduler_async_snapshot — before
    any process_write runs) must fail the command, release its latches, and
    leave the scheduler serviceable."""
    store = Storage()
    cfg("scheduler_async_snapshot", "return")
    with pytest.raises(FailpointError):
        store.sched_txn_command(
            Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10)
        )
    teardown()
    # the latch was released: the same key prewrites and commits fine
    r = store.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10)
    )
    assert "errors" not in r
    store.sched_txn_command(Commit([Key.from_raw(b"k")], 10, 20))
    assert store.get(b"k", 30) == b"v"


def test_pause_failpoint_creates_race_window():
    """pause holds a thread mid-command; writes resume when released."""
    store = Storage()
    cfg("scheduler_before_write", "pause")
    done = threading.Event()

    def writer():
        store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"p"), b"v")], b"p", 10))
        done.set()

    t = threading.Thread(target=writer)
    t.start()
    assert not done.wait(0.1)  # held at the failpoint
    failpoint.remove("scheduler_before_write")
    assert done.wait(2)
    t.join()


def test_pause_wakes_on_notify_not_poll():
    """A paused thread parks on the condition and wakes on the cfg/teardown
    notify — release latency is notification-bound, not 10ms-poll-bound.

    One release under a 10ms poll still wakes within ~10ms, so a single
    sample cannot tell the implementations apart; 20 park/release cycles
    can: polling costs ~5ms expected latency per cycle (~100ms total, up
    to 200ms), notify wakes each cycle in well under a millisecond.  The
    60ms budget below fails the polling implementation with huge margin
    while leaving notify-wake ~10x headroom for scheduler noise."""
    import time

    total = 0.0
    for i in range(20):
        name = f"wake{i}"
        cfg(name, "pause")
        entered = threading.Event()
        woke_at = []

        def parked():
            entered.set()
            fail_point(name)
            woke_at.append(time.monotonic())

        t = threading.Thread(target=parked)
        t.start()
        assert entered.wait(2)
        time.sleep(0.005)  # let the thread actually park inside the wait
        released_at = time.monotonic()
        failpoint.remove(name)
        t.join(2)
        assert not t.is_alive()
        assert woke_at
        total += woke_at[0] - released_at
    assert total < 0.06, f"pause release latency poll-bound: {total:.3f}s/20"


def test_list_active_shows_remaining_counts():
    """Counted actions render their REMAINING budget so a test mid-schedule
    can see how far the injection has progressed."""
    cfg("cnt", "3*return")
    assert failpoint.list_active() == {"cnt": "3*return"}
    with pytest.raises(FailpointError):
        fail_point("cnt")
    assert failpoint.list_active() == {"cnt": "2*return"}
    with pytest.raises(FailpointError):
        fail_point("cnt")
    with pytest.raises(FailpointError):
        fail_point("cnt")
    assert failpoint.list_active() == {}  # budget exhausted: point removed


def test_coprocessor_failpoint_over_endpoint():
    import sys, os

    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_engine
    from tikv_tpu.copr.dag import DagRequest, TableScan
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.kv import LocalEngine

    ep = Endpoint(LocalEngine(product_engine()), enable_device=False)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    req = lambda: CoprRequest(103, DagRequest(executors=dag.executors), [record_range(TABLE_ID)], 200, context={})
    cfg("coprocessor_parse_request", "1*return")
    with pytest.raises(FailpointError):
        ep.handle_request(req())
    r = ep.handle_request(req())  # next request fine
    assert len(r.data) > 0


def test_snapshot_generation_failpoint_in_cluster():
    """A failed snapshot generation is retried on later ticks (the catch-up
    path survives transient snapshot faults)."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    c = Cluster(4)
    region = c.bootstrap_subset([1, 2, 3])
    c.elect_leader(region.id, 1)
    c.must_put(b"k", b"v")
    cfg("region_gen_snapshot", "2*panic")
    try:
        c.add_peer(region.id, 4)
        for _ in range(10):
            try:
                c.tick(1)
            except RuntimeError:
                pass  # snapshot generation faulted this round
        teardown()
        c.tick(5)
        assert c.get_on_store(4, b"k") == b"v"
    finally:
        teardown()


def test_counted_pause_actually_pauses():
    """'1*pause' must hold arriving threads; the window ends on reconfigure,
    counts never decrement it."""
    cfg("cp", "1*pause")
    released = threading.Event()

    def waiter():
        fail_point("cp")
        released.set()

    t = threading.Thread(target=waiter)
    t.start()
    assert not released.wait(0.15)  # actually held
    failpoint.remove("cp")
    assert released.wait(2)
    t.join()


def test_apply_failpoint_does_not_lose_committed_entries():
    """A fault between commit and apply must re-deliver the entry, not drop
    it: ready() pre-advances applied, so handle_ready rewinds on failure."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    c = Cluster(3)
    c.bootstrap_subset([1, 2, 3])
    c.elect_leader(FIRST_REGION_ID, 1)
    c.must_put(b"a", b"1")
    cfg("apply_before_exec", "3*return")  # one fault per store
    faults = 0
    for _ in range(30):
        try:
            c.tick(1)
        except FailpointError:
            faults += 1
        try:
            c.must_put(b"b", b"2")
            break
        except FailpointError:
            faults += 1
    teardown()
    c.tick(5)
    assert faults > 0  # the failpoint did fire
    for sid in (1, 2, 3):
        assert c.get_on_store(sid, b"a") == b"1"
        assert c.get_on_store(sid, b"b") == b"2"
