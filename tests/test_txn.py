"""Percolator transaction tests (reference: src/storage/txn tests,
components/test_storage SyncTestStorage harness)."""

import pytest

from tikv_tpu.storage.mvcc.reader import KeyIsLockedError, WriteConflictError
from tikv_tpu.storage.mvcc.txn import AlreadyExistsError, TxnStatusKind
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn.commands import (
    AcquirePessimisticLock,
    CheckSecondaryLocks,
    CheckTxnStatus,
    Cleanup,
    Commit,
    PessimisticRollback,
    Prewrite,
    ResolveLock,
    Rollback,
    TxnHeartBeat,
)
from tikv_tpu.storage.txn_types import Key, Mutation, compose_ts


@pytest.fixture
def store():
    return Storage()


def put(store, key, value, start_ts, commit_ts):
    r = store.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(key), value)], key, start_ts)
    )
    assert "errors" not in r, r
    store.sched_txn_command(Commit([Key.from_raw(key)], start_ts, commit_ts))


def test_prewrite_commit_get(store):
    put(store, b"k", b"v1", 10, 20)
    assert store.get(b"k", 25) == b"v1"
    assert store.get(b"k", 15) is None
    put(store, b"k", b"v2", 30, 40)
    assert store.get(b"k", 45) == b"v2"
    assert store.get(b"k", 39) == b"v1"


def test_prewrite_blocks_reads_until_commit(store):
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10))
    with pytest.raises(KeyIsLockedError):
        store.get(b"k", 50)
    store.sched_txn_command(Commit([Key.from_raw(b"k")], 10, 20))
    assert store.get(b"k", 50) == b"v"


def test_write_conflict(store):
    put(store, b"k", b"v1", 10, 20)
    r = store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"k"), b"x")], b"k", 15))
    assert isinstance(r["errors"][0], WriteConflictError)


def test_rollback_then_retry_prewrite_fails(store):
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10))
    store.sched_txn_command(Rollback([Key.from_raw(b"k")], 10))
    assert store.get(b"k", 50) is None
    # late prewrite at the same ts must fail against the rollback record
    r = store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10))
    assert r.get("errors"), "prewrite after rollback must fail"


def test_insert_checks_not_exists(store):
    put(store, b"k", b"v", 10, 20)
    r = store.sched_txn_command(Prewrite([Mutation.insert(Key.from_raw(b"k"), b"x")], b"k", 30))
    assert isinstance(r["errors"][0], AlreadyExistsError)
    # after a delete, insert succeeds
    store.sched_txn_command(Prewrite([Mutation.delete(Key.from_raw(b"k"))], b"k", 40))
    store.sched_txn_command(Commit([Key.from_raw(b"k")], 40, 45))
    r = store.sched_txn_command(Prewrite([Mutation.insert(Key.from_raw(b"k"), b"x")], b"k", 50))
    assert "errors" not in r


def test_delete(store):
    put(store, b"k", b"v", 10, 20)
    store.sched_txn_command(Prewrite([Mutation.delete(Key.from_raw(b"k"))], b"k", 30))
    store.sched_txn_command(Commit([Key.from_raw(b"k")], 30, 35))
    assert store.get(b"k", 50) is None
    assert store.get(b"k", 25) == b"v"


def test_batch_and_scan(store):
    for i, ts in [(1, 10), (2, 30), (3, 50)]:
        put(store, b"k%d" % i, b"v%d" % i, ts, ts + 5)
    got = store.batch_get([b"k1", b"k2", b"k3", b"nope"], 100)
    assert got == [(b"k1", b"v1"), (b"k2", b"v2"), (b"k3", b"v3")]
    assert store.scan(b"", None, None, 40) == [(b"k1", b"v1"), (b"k2", b"v2")]
    assert store.scan(b"", None, 2, 100) == [(b"k1", b"v1"), (b"k2", b"v2")]
    assert store.scan(b"", None, 1, 100, reverse=True) == [(b"k3", b"v3")]


def test_batch_ops_single_pass_and_counted(store, monkeypatch):
    """batch_get takes ONE snapshot and ONE PointGetter for the whole key
    set (no per-key re-entry), and every batched call observes its size in
    tikv_storage_batch_size{op}."""
    from tikv_tpu.storage import storage as storage_mod
    from tikv_tpu.util.metrics import REGISTRY

    for i, ts in [(1, 10), (2, 30), (3, 50)]:
        put(store, b"b%d" % i, b"v%d" % i, ts, ts + 5)
    made = []
    real = storage_mod.PointGetter

    class CountingGetter(real):
        def __init__(self, *a, **kw):
            made.append(1)
            super().__init__(*a, **kw)

    monkeypatch.setattr(storage_mod, "PointGetter", CountingGetter)
    h = REGISTRY.histogram("tikv_storage_batch_size", "")
    before = h.count(op="batch_get")
    got = store.batch_get([b"b1", b"b2", b"b3", b"nope"], 100)
    assert got == [(b"b1", b"v1"), (b"b2", b"v2"), (b"b3", b"v3")]
    assert len(made) == 1, "batch_get must build exactly one PointGetter"
    assert h.count(op="batch_get") == before + 1
    # raw batches count too, one observation per call
    b_put = h.count(op="raw_batch_put")
    b_get = h.count(op="raw_batch_get")
    b_del = h.count(op="raw_batch_delete")
    store.raw_batch_put([(b"ra", b"1"), (b"rb", b"2")])
    store.raw_batch_get([b"ra", b"rb"])
    store.raw_batch_delete([b"ra", b"rb"])
    assert h.count(op="raw_batch_put") == b_put + 1
    assert h.count(op="raw_batch_get") == b_get + 1
    assert h.count(op="raw_batch_delete") == b_del + 1


def test_pessimistic_flow(store):
    put(store, b"k", b"v0", 5, 6)
    k = Key.from_raw(b"k")
    r = store.sched_txn_command(
        AcquirePessimisticLock([(k, False)], b"k", 10, 11, return_values=True)
    )
    assert r["values"] == [b"v0"]
    # another txn cannot lock
    with pytest.raises(KeyIsLockedError):
        store.sched_txn_command(AcquirePessimisticLock([(k, False)], b"k", 12, 13))
    # reads are NOT blocked by pessimistic locks
    assert store.get(b"k", 100) == b"v0"
    # pessimistic prewrite + commit
    r = store.sched_txn_command(
        Prewrite(
            [Mutation.put(k, b"v1")], b"k", 10,
            is_pessimistic=True, pessimistic_flags=[True], for_update_ts=11,
        )
    )
    assert "errors" not in r
    store.sched_txn_command(Commit([k], 10, 20))
    assert store.get(b"k", 30) == b"v1"


def test_pessimistic_write_conflict(store):
    put(store, b"k", b"v1", 10, 20)
    k = Key.from_raw(b"k")
    with pytest.raises(WriteConflictError):
        store.sched_txn_command(AcquirePessimisticLock([(k, False)], b"k", 5, 15))


def test_pessimistic_rollback(store):
    k = Key.from_raw(b"k")
    store.sched_txn_command(AcquirePessimisticLock([(k, False)], b"k", 10, 11))
    store.sched_txn_command(PessimisticRollback([k], 10, 11))
    # lock is gone — another txn can take it
    store.sched_txn_command(AcquirePessimisticLock([(k, False)], b"k", 12, 13))


def test_check_txn_status_and_heartbeat(store):
    k = Key.from_raw(b"pk")
    ts10 = compose_ts(1000, 0)
    store.sched_txn_command(
        Prewrite([Mutation.put(k, b"v")], b"pk", ts10, lock_ttl=100)
    )
    r = store.sched_txn_command(TxnHeartBeat(k, ts10, 500))
    assert r["lock_ttl"] == 500
    # within TTL: still locked (caller below min_commit window)
    r = store.sched_txn_command(
        CheckTxnStatus(k, ts10, 0, compose_ts(1100, 0))
    )
    assert r["status"].kind in (TxnStatusKind.LOCKED, TxnStatusKind.MIN_COMMIT_PUSHED)
    # TTL expired: rolled back
    r = store.sched_txn_command(
        CheckTxnStatus(k, ts10, 0, compose_ts(9000, 0))
    )
    assert r["status"].kind == TxnStatusKind.TTL_EXPIRED
    assert store.get(b"pk", compose_ts(9999, 0)) is None


def test_check_txn_status_async_commit_never_rolled_back(store):
    """An expired async-commit primary must NOT be rolled back or pushed:
    the txn may already be committed through its secondaries
    (check_txn_status.rs:26 returns uncommitted for use_async_commit)."""
    k = Key.from_raw(b"pk")
    ts10 = compose_ts(1000, 0)
    store.sched_txn_command(
        Prewrite(
            [Mutation.put(k, b"v")], b"pk", ts10, lock_ttl=100,
            use_async_commit=True, secondaries=[],
        )
    )
    # far past TTL: still LOCKED, not TTL_EXPIRED
    r = store.sched_txn_command(CheckTxnStatus(k, ts10, 0, compose_ts(9000, 0)))
    assert r["status"].kind == TxnStatusKind.LOCKED
    # min_commit_ts must not be pushed either
    caller = compose_ts(9500, 0)
    r = store.sched_txn_command(CheckTxnStatus(k, ts10, caller, compose_ts(9500, 1)))
    assert r["status"].kind == TxnStatusKind.LOCKED
    # commit still possible — the lock survived
    store.sched_txn_command(Commit([k], ts10, compose_ts(9600, 0)))
    assert store.get(b"pk", compose_ts(9999, 0)) == b"v"
    # force_sync_commit overrides the guard (client knows commit never happened)
    ts2 = compose_ts(20000, 0)
    store.sched_txn_command(
        Prewrite([Mutation.put(k, b"w")], b"pk", ts2, lock_ttl=100,
                 use_async_commit=True, secondaries=[])
    )
    r = store.sched_txn_command(
        CheckTxnStatus(k, ts2, 0, compose_ts(99000, 0), force_sync_commit=True)
    )
    assert r["status"].kind == TxnStatusKind.TTL_EXPIRED


def test_check_txn_status_committed(store):
    put(store, b"pk", b"v", 10, 20)
    r = store.sched_txn_command(CheckTxnStatus(Key.from_raw(b"pk"), 10, 0, 100))
    assert r["status"].kind == TxnStatusKind.COMMITTED
    assert r["status"].commit_ts == 20


def test_cleanup_and_resolve(store):
    # secondary locks of a dead txn get resolved by its primary's fate
    ka, kb = Key.from_raw(b"a"), Key.from_raw(b"b")
    store.sched_txn_command(Prewrite([Mutation.put(ka, b"va"), Mutation.put(kb, b"vb")], b"a", 10))
    # primary commits at 15 → resolve commits secondaries
    store.sched_txn_command(Commit([ka], 10, 15))
    store.sched_txn_command(ResolveLock(10, 15))
    assert store.get(b"a", 20) == b"va"
    assert store.get(b"b", 20) == b"vb"
    # a dead txn's lock: cleanup rolls it back
    store.sched_txn_command(Prewrite([Mutation.put(ka, b"x")], b"a", 30))
    store.sched_txn_command(Cleanup(ka, 30, 0))
    assert store.get(b"a", 50) == b"va"


def test_resolve_rollback_path(store):
    ka, kb = Key.from_raw(b"a"), Key.from_raw(b"b")
    store.sched_txn_command(Prewrite([Mutation.put(ka, b"va"), Mutation.put(kb, b"vb")], b"a", 10))
    store.sched_txn_command(ResolveLock(10, 0))  # roll back everything
    assert store.get(b"a", 50) is None
    assert store.get(b"b", 50) is None
    assert store.scan_lock(None, None, 100) == []


def test_check_secondary_locks(store):
    ka, kb = Key.from_raw(b"a"), Key.from_raw(b"b")
    store.sched_txn_command(Prewrite([Mutation.put(ka, b"va"), Mutation.put(kb, b"vb")], b"a", 10, use_async_commit=True, secondaries=[b"b"]))
    r = store.sched_txn_command(CheckSecondaryLocks([kb], 10))
    assert len(r["locks"]) == 1 and r["commit_ts"] == 0
    # a key that was never locked -> whole txn must roll back
    kc = Key.from_raw(b"c")
    r = store.sched_txn_command(CheckSecondaryLocks([kc], 10))
    assert r["locks"] == [] and r["commit_ts"] == 0


def test_scan_lock(store):
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"x"), b"1")], b"x", 11))
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"y"), b"2")], b"y", 22))
    locks = store.scan_lock(None, None, 100)
    assert [(k.to_raw(), l.ts) for k, l in locks] == [(b"x", 11), (b"y", 22)]
    locks = store.scan_lock(None, None, 15)
    assert [(k.to_raw(), l.ts) for k, l in locks] == [(b"x", 11)]


def test_raw_kv(store):
    store.raw_put(b"rk", b"rv")
    assert store.raw_get(b"rk") == b"rv"
    store.raw_batch_put([(b"a", b"1"), (b"b", b"2")])
    assert store.raw_batch_get([b"a", b"b", b"zz"]) == [(b"a", b"1"), (b"b", b"2")]
    assert store.raw_scan(b"", None) == [(b"a", b"1"), (b"b", b"2"), (b"rk", b"rv")]
    assert store.raw_scan(b"", None, reverse=True, limit=1) == [(b"rk", b"rv")]
    store.raw_delete(b"a")
    assert store.raw_get(b"a") is None
    store.raw_delete_range(b"b", b"c")
    assert store.raw_get(b"b") is None
    # raw and txn keyspaces are disjoint
    put(store, b"rk", b"txn-v", 10, 20)
    assert store.raw_get(b"rk") == b"rv"
    assert store.get(b"rk", 50) == b"txn-v"


def test_raw_ttl(store):
    store.raw_put(b"t", b"v", ttl=100)
    assert store.raw_get(b"t") == b"v"
    assert 0 < store.raw_get_key_ttl(b"t") <= 100
    import time as _t
    future = _t.time() + 1000
    assert store.raw_get(b"t", now=future) is None
    store.raw_put(b"t2", b"v2")  # no ttl
    assert store.raw_get_key_ttl(b"t2") == 0
    assert store.raw_get(b"t2", now=future) == b"v2"


def test_raw_cas(store):
    ok, prev = store.raw_compare_and_swap(b"c", None, b"v1")
    assert ok and prev is None
    ok, prev = store.raw_compare_and_swap(b"c", None, b"v2")
    assert not ok and prev == b"v1"
    ok, prev = store.raw_compare_and_swap(b"c", b"v1", b"v2")
    assert ok
    assert store.raw_get(b"c") == b"v2"


def test_concurrent_transfer_consistency(store):
    """Bank-transfer style concurrency: latches + MVCC keep totals constant."""
    import threading

    put(store, b"acc1", b"100", 1, 2)
    put(store, b"acc2", b"100", 1, 2)
    errs = []

    def transfer(start_ts, frm, to, amt):
        try:
            v1 = int(store.get(frm, start_ts))
            v2 = int(store.get(to, start_ts))
            muts = [
                Mutation.put(Key.from_raw(frm), str(v1 - amt).encode()),
                Mutation.put(Key.from_raw(to), str(v2 + amt).encode()),
            ]
            r = store.sched_txn_command(Prewrite(muts, frm, start_ts))
            if r.get("errors"):
                return
            store.sched_txn_command(
                Commit([Key.from_raw(frm), Key.from_raw(to)], start_ts, start_ts + 5)
            )
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=transfer, args=(10 + i * 20, b"acc1", b"acc2", 10))
        for i in range(5
        )
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = int(store.get(b"acc1", 10**6) or 0) + int(store.get(b"acc2", 10**6) or 0)
    assert total == 200


def test_flashback_to_version():
    """FlashbackToVersion: append-only restore of a range to an earlier
    version — history intact, locks cleared, later reads see the old state
    (commands/flashback_to_version.rs)."""
    from tikv_tpu.storage.txn.commands import FlashbackToVersion

    store = Storage()

    def txn(key, value, ts, cts, op="put"):
        mut = Mutation.put(Key.from_raw(key), value) if op == "put" else Mutation.delete(Key.from_raw(key))
        store.sched_txn_command(Prewrite([mut], key, ts))
        store.sched_txn_command(Commit([Key.from_raw(key)], ts, cts))

    txn(b"a", b"old-a", 10, 11)
    txn(b"b", b"old-b", 12, 13)
    # mutations after the flashback point (version=20):
    txn(b"a", b"new-a", 30, 31)      # update
    txn(b"b", None, 32, 33, "delete")  # delete
    txn(b"c", b"new-c", 34, 35)      # created after version
    big = b"x" * 5000
    txn(b"d", big, 36, 37)           # long value created after version
    # a dangling lock in range
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"e"), b"locked")], b"e", 40))
    assert store.scan_lock(None, None, 100)

    r = store.sched_txn_command(FlashbackToVersion(version=20, start_ts=50, commit_ts=51))
    assert r["flashback_keys"] == 4  # a, b, c, d all diverged from v20

    # post-flashback reads = state at version 20
    assert store.get(b"a", 60) == b"old-a"
    assert store.get(b"b", 60) == b"old-b"
    assert store.get(b"c", 60) is None
    assert store.get(b"d", 60) is None
    assert store.scan_lock(None, None, 100) == []  # locks cleared
    # MVCC history below the flashback commit is intact
    assert store.get(b"a", 31) == b"new-a"
    assert store.get(b"b", 33) is None
    assert store.get(b"d", 38) == big
    # idempotent-ish: a second flashback to the same version changes nothing
    r2 = store.sched_txn_command(FlashbackToVersion(version=20, start_ts=70, commit_ts=71))
    assert r2["flashback_keys"] == 0


def test_flashback_range_bounds():
    from tikv_tpu.storage.txn.commands import FlashbackToVersion

    store = Storage()
    for i, k in enumerate([b"k1", b"k2", b"k3"]):
        ts = 10 + 2 * i
        store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), b"v1")], k, ts))
        store.sched_txn_command(Commit([Key.from_raw(k)], ts, ts + 1))
    for i, k in enumerate([b"k1", b"k2", b"k3"]):
        ts = 30 + 2 * i
        store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(k), b"v2")], k, ts))
        store.sched_txn_command(Commit([Key.from_raw(k)], ts, ts + 1))
    # flashback only [k2, k3)
    r = store.sched_txn_command(
        FlashbackToVersion(
            version=20, start_ts=50, commit_ts=51,
            start_key=Key.from_raw(b"k2"), end_key=Key.from_raw(b"k3"),
        )
    )
    assert r["flashback_keys"] == 1
    assert store.get(b"k1", 60) == b"v2"  # outside range: untouched
    assert store.get(b"k2", 60) == b"v1"  # flashed back
    assert store.get(b"k3", 60) == b"v2"


def test_flashback_review_fixes():
    """Dangling lock on a key WITH history must not abort the flashback; the
    superseded txn cannot commit afterwards; concurrent writers serialize."""
    from tikv_tpu.storage.txn.commands import FlashbackToVersion

    store = Storage()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"a"), b"v1")], b"a", 10))
    store.sched_txn_command(Commit([Key.from_raw(b"a")], 10, 11))
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"a"), b"v2")], b"a", 30))
    store.sched_txn_command(Commit([Key.from_raw(b"a")], 30, 31))
    # dangling lock ON a key that also has post-version writes, with a LONG
    # value (CF_DEFAULT orphan candidate)
    big = b"L" * 1000
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"a"), big)], b"a", 40))
    r = store.sched_txn_command(FlashbackToVersion(version=20, start_ts=50, commit_ts=51))
    assert "errors" not in r
    assert store.get(b"a", 60) == b"v1"
    # the superseded txn's commit must fail loudly (its lock was rolled
    # back with a protected marker)
    from tikv_tpu.storage.mvcc.txn import TxnLockNotFoundError

    with pytest.raises(TxnLockNotFoundError):
        store.sched_txn_command(Commit([Key.from_raw(b"a")], 40, 70))
    assert store.get(b"a", 80) == b"v1"  # v40's big value never lands


def test_flashback_rejects_racing_commit():
    """A write committed at/after the flashback's commit_ts fails the command
    loudly — the restore record would otherwise be silently shadowed."""
    from tikv_tpu.storage.mvcc.reader import WriteConflictError
    from tikv_tpu.storage.txn.commands import FlashbackToVersion

    store = Storage()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"r"), b"v1")], b"r", 10))
    store.sched_txn_command(Commit([Key.from_raw(b"r")], 10, 11))
    # a commit that lands AFTER the flashback's TSOs were fetched
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"r"), b"late")], b"r", 60))
    store.sched_txn_command(Commit([Key.from_raw(b"r")], 60, 61))
    with pytest.raises(WriteConflictError):
        store.sched_txn_command(FlashbackToVersion(version=20, start_ts=50, commit_ts=51))
