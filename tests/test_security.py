"""TLS wire security, log redaction, and stable error codes.

Mirrors the reference's security/log_wrappers/error_code unit strategy
(components/security/src/lib.rs tests, log_wrappers/src/lib.rs tests).
"""

import subprocess

import pytest

from tikv_tpu.server import wire
from tikv_tpu.server.security import SecurityConfig, SecurityError
from tikv_tpu.server.server import Client, Server
from tikv_tpu.util import error_code, logger
from tikv_tpu.util.config import TikvConfig


class _EchoService:
    def dispatch(self, method, request):
        if method == "boom":
            from tikv_tpu.raft.region import NotLeaderError

            raise NotLeaderError(1, 2)
        return {"echo": [method, request]}


def _gen_ca_and_cert(tmp, name, cn):
    """Self-signed CA + a CA-signed cert for ``cn`` via the openssl CLI."""
    ca_key, ca_pem = tmp / "ca.key", tmp / "ca.pem"
    if not ca_pem.exists():
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(ca_key), "-out", str(ca_pem), "-days", "1",
             "-subj", "/CN=tikv-tpu-test-ca"],
            check=True, capture_output=True,
        )
    key, csr, pem = tmp / f"{name}.key", tmp / f"{name}.csr", tmp / f"{name}.pem"
    subprocess.run(
        ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={cn}"],
        check=True, capture_output=True,
    )
    subprocess.run(
        ["openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_pem),
         "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(pem), "-days", "1"],
        check=True, capture_output=True,
    )
    return SecurityConfig(ca_path=str(ca_pem), cert_path=str(pem), key_path=str(key))


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tls")
    return {
        "server": _gen_ca_and_cert(tmp, "server", "tikv-server"),
        "client": _gen_ca_and_cert(tmp, "client", "tikv-client"),
        "dir": tmp,
    }


def test_partial_config_rejected():
    with pytest.raises(SecurityError):
        SecurityConfig(ca_path="/x").validate()
    with pytest.raises(SecurityError):
        SecurityConfig(cert_allowed_cn={"a"}).validate()
    SecurityConfig().validate()  # plaintext is fine
    assert not SecurityConfig().enabled


def test_mutual_tls_roundtrip(certs):
    srv = Server(_EchoService(), security=certs["server"])
    srv.start()
    try:
        cli = Client(*srv.addr, security=certs["client"])
        assert cli.call("ping", {"k": 1}) == {"echo": ["ping", {"k": 1}]}
        cli.close()
    finally:
        srv.stop()


def test_plaintext_client_rejected_by_tls_server(certs):
    srv = Server(_EchoService(), security=certs["server"])
    srv.start()
    try:
        cli = Client(*srv.addr)  # no TLS
        with pytest.raises((TimeoutError, ConnectionError)):
            cli.call("ping", {}, timeout=1.0)
        cli.close()
    finally:
        srv.stop()


def test_cert_allowed_cn_enforced(certs):
    sec = SecurityConfig(
        ca_path=certs["server"].ca_path,
        cert_path=certs["server"].cert_path,
        key_path=certs["server"].key_path,
        cert_allowed_cn={"some-other-cn"},
    )
    srv = Server(_EchoService(), security=sec)
    srv.start()
    try:
        # rejection may surface during the client handshake (EOF) or the call
        with pytest.raises((TimeoutError, OSError)):
            cli = Client(*srv.addr, security=certs["client"])
            try:
                cli.call("ping", {}, timeout=1.0)
            finally:
                cli.close()
    finally:
        srv.stop()
    # and the right CN passes
    sec_ok = SecurityConfig(
        ca_path=sec.ca_path, cert_path=sec.cert_path, key_path=sec.key_path,
        cert_allowed_cn={"tikv-client"},
    )
    srv = Server(_EchoService(), security=sec_ok)
    srv.start()
    try:
        cli = Client(*srv.addr, security=certs["client"])
        assert cli.call("ping", {})["echo"][0] == "ping"
        cli.close()
    finally:
        srv.stop()


def test_tikv_config_security_section(certs):
    cfg = TikvConfig()
    cfg.security.ca_path = certs["server"].ca_path
    with pytest.raises(SecurityError):
        cfg.validate()  # partial
    cfg.security.cert_path = certs["server"].cert_path
    cfg.security.key_path = certs["server"].key_path
    cfg.validate()
    assert cfg.security_config().enabled


# ------------------------------------------------------------- log redaction

def test_redact_modes():
    try:
        logger.set_redact_info_log(True)
        assert logger.key(b"secret") == "?"
        logger.set_redact_info_log("marker")
        assert logger.key(b"\x01ab") == "‹016162›"
        logger.set_redact_info_log(False)
        assert logger.key(b"\xff") == "FF"
        with pytest.raises(ValueError):
            logger.set_redact_info_log("nope")
    finally:
        logger.set_redact_info_log(False)


def test_structured_log_line_format():
    import io
    import logging as stdlog

    log = logger.get_logger("testmod")
    buf = io.StringIO()
    handler = stdlog.StreamHandler(buf)
    handler.setFormatter(logger._Formatter())
    stdlog.getLogger("tikv_tpu.testmod").addHandler(handler)
    logger.set_redact_info_log(True)
    try:
        log.info("something happened", region=7, key=logger.key(b"user-key"))
    finally:
        logger.set_redact_info_log(False)
        stdlog.getLogger("tikv_tpu.testmod").removeHandler(handler)
    out = buf.getvalue()
    assert "[INFO] [tikv_tpu.testmod] [something happened] [region=7] [key=?]" in out
    assert "user-key" not in out and "757365" not in out.lower()


# --------------------------------------------------------------- error codes

def test_error_codes_resolve():
    from tikv_tpu.raft.region import EpochError, NotLeaderError, Region
    from tikv_tpu.storage.mvcc.reader import KeyIsLockedError

    error_code.register_builtin()
    assert error_code.code_of(NotLeaderError(1, 2)) == "KV:Raftstore:NotLeader"
    assert error_code.code_of(EpochError(Region(id=1))) == "KV:Raftstore:EpochNotMatch"
    from tikv_tpu.storage.txn_types import Lock

    lk = KeyIsLockedError(b"k", Lock(lock_type="put", primary=b"k", ts=1, ttl=1))
    assert error_code.code_of(lk) == "KV:Storage:KeyIsLocked"
    assert error_code.code_of(RuntimeError("x")) == "KV:Unknown"


def test_error_code_instance_override():
    e = RuntimeError("x")
    e.error_code = "KV:Custom:Thing"
    assert error_code.code_of(e) == "KV:Custom:Thing"


def test_error_code_spec_artifact():
    spec = error_code.spec()
    assert "KV:Raftstore:NotLeader" in spec
    assert all(code.startswith("KV:") for code in spec)


def test_error_code_on_the_wire():
    srv = Server(_EchoService())
    srv.start()
    try:
        cli = Client(*srv.addr)
        resp = cli.call("boom", {})
        assert resp["error"]["code"] == "KV:Raftstore:NotLeader"
        cli.close()
    finally:
        srv.stop()


def test_apply_security_sets_redaction():
    cfg = TikvConfig()
    cfg.security.redact_info_log = "on"
    try:
        assert cfg.apply_security() is None  # plaintext, but redaction applied
        assert logger.redact_mode() == "on"
        assert logger.key(b"x") == "?"
    finally:
        logger.set_redact_info_log(False)


def test_v1_explicit_null_stays_null():
    """An explicitly stored NULL must not resurrect as the column default
    (matches row v2; only an *absent* column takes the default)."""
    import numpy as np

    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.table import RowBatchDecoder, encode_row

    info = ColumnInfo(2, FieldType.int64(), default_value=42)
    pk = ColumnInfo(1, FieldType.int64(), is_pk_handle=True)
    stored_null = encode_row([info], [None])
    absent = b""  # no columns stored at all
    cols = RowBatchDecoder([pk, info]).decode(np.array([1, 2]), [stored_null, absent])
    assert cols[1].to_values() == [None, 42]


def test_status_server_tls(certs):
    """status_server/mod.rs parity: the status listener rides the same TLS
    config as the KV server — mutual TLS, CN allow-list, and no plaintext."""
    import urllib.request

    from tikv_tpu.server.status_server import StatusServer

    cfg = certs["server"]
    cn_cfg = SecurityConfig(
        ca_path=cfg.ca_path, cert_path=cfg.cert_path, key_path=cfg.key_path,
        cert_allowed_cn={"tikv-client"},
    )
    srv = StatusServer(security=cn_cfg)
    srv.start()
    host, port = srv.addr
    try:
        ctx = certs["client"].client_context()
        ctx.check_hostname = False
        resp = urllib.request.urlopen(
            f"https://{host}:{port}/status", context=ctx, timeout=5)
        assert resp.read() == b"ok"
        # plaintext is rejected
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://{host}:{port}/status", timeout=5)
        # a CA-signed cert whose CN is not allow-listed is rejected
        rogue = _gen_ca_and_cert(certs["dir"], "rogue", "rogue-cn")
        rctx = rogue.client_context()
        rctx.check_hostname = False
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"https://{host}:{port}/status", context=rctx, timeout=5)
        # a silent client must not wedge the accept loop for others
        import socket as _socket

        quiet = _socket.create_connection((host, port), timeout=5)
        try:
            resp = urllib.request.urlopen(
                f"https://{host}:{port}/status", context=ctx, timeout=5)
            assert resp.read() == b"ok"
        finally:
            quiet.close()
    finally:
        srv.stop()
