"""MySQL JSON: binary codec, paths, scalar functions, DAG integration
(reference: tidb_query_datatype/src/codec/mysql/json + impl_json.rs)."""

import numpy as np
import pytest

from tikv_tpu.copr import json_value as jv
from tikv_tpu.copr.datatypes import Column, EvalType, FieldType, FieldTypeTp
from tikv_tpu.copr.kernels import KERNELS
from tikv_tpu.copr.rpn import call, col, compile_expr, const_bytes, const_json, eval_rpn


# -- binary codec -----------------------------------------------------------


@pytest.mark.parametrize(
    "v",
    [
        None, True, False, 0, 42, -7, 2**62, jv.JsonU64(2**63 + 5), 3.25, 1.0,
        "", "hello", "unié", [], [1, 2, 3], ["a", None, True, 2.5],
        {}, {"a": 1}, {"bb": [1, {"c": None}], "a": "x", "ccc": 2.5},
        [[1, [2, [3]]]], {"k": {"k": {"k": "deep"}}},
    ],
)
def test_json_binary_roundtrip(v):
    b = jv.json_encode(v)
    assert jv.json_decode(b) == v
    assert jv.json_binary_len(b + b"garbage", 0) == len(b)


def test_json_binary_layout_stable():
    # spot-check the wire layout (type codes from json/mod.rs)
    assert jv.json_encode(None) == b"\x04\x00"
    assert jv.json_encode(True) == b"\x04\x01"
    assert jv.json_encode(7) == b"\x09" + (7).to_bytes(8, "little")
    assert jv.json_encode("hi") == b"\x0c\x02hi"
    arr = jv.json_encode([1])
    assert arr[0] == 0x03 and int.from_bytes(arr[1:5], "little") == 1


def test_object_keys_sorted_mysql_style():
    # shorter keys first, then byte order — independent of insert order
    b1 = jv.json_encode({"bb": 1, "a": 2, "c": 3})
    b2 = jv.json_encode({"c": 3, "bb": 1, "a": 2})
    assert b1 == b2
    assert list(jv.json_decode(b1)) == ["a", "c", "bb"]


# -- paths ------------------------------------------------------------------


def test_path_extract():
    doc = {"a": {"b": [10, 20, {"c": 30}]}, "x": [1, 2]}
    assert jv.extract(doc, ["$.a.b[2].c"]) == 30
    assert jv.extract(doc, ["$.a.b[0]"]) == 10
    assert jv.extract(doc, ["$.x"]) == [1, 2]
    assert jv.extract(doc, ["$"]) == doc
    assert jv.extract(doc, ["$.missing"]) is jv._NO_MATCH
    # wildcard → array of matches
    assert jv.extract(doc, ["$.a.b[*]"]) == [10, 20, {"c": 30}]
    assert jv.extract({"p": {"q": 1}, "r": {"q": 2}}, ["$.*.q"]) == [1, 2]
    # ** finds at any depth
    assert sorted(jv.extract(doc, ["$**.c"])) == [30]
    # multiple paths → array
    assert jv.extract(doc, ["$.a.b[0]", "$.a.b[1]"]) == [10, 20]
    # scalar auto-wrap: $[0] of a scalar is the scalar
    assert jv.extract(5, ["$[0]"]) == 5
    # quoted member
    assert jv.extract({"odd key": 1}, ['$."odd key"']) == 1
    with pytest.raises(ValueError):
        jv.parse_path("a.b")
    with pytest.raises(ValueError):
        jv.parse_path("$**")


def test_modify_and_remove():
    doc = {"a": 1, "b": [1, 2]}
    assert jv.modify(doc, [("$.c", 3)], "set") == {"a": 1, "b": [1, 2], "c": 3}
    assert jv.modify(doc, [("$.a", 9)], "insert") == doc  # exists: no-op
    assert jv.modify(doc, [("$.a", 9)], "replace")["a"] == 9
    assert jv.modify(doc, [("$.c", 9)], "replace") == doc  # missing: no-op
    assert jv.modify(doc, [("$.b[5]", 9)], "set")["b"] == [1, 2, 9]  # append
    assert jv.remove(doc, ["$.b[0]"]) == {"a": 1, "b": [2]}
    assert jv.remove(doc, ["$.a"]) == {"b": [1, 2]}
    with pytest.raises(ValueError):
        jv.modify(doc, [("$.*", 1)], "set")


def test_merge_contains_type_depth():
    assert jv.merge([[1], [2, 3]]) == [1, 2, 3]
    assert jv.merge([{"a": 1}, {"b": 2}]) == {"a": 1, "b": 2}
    assert jv.merge([{"a": 1}, {"a": 2}]) == {"a": [1, 2]}
    assert jv.merge([1, "x"]) == [1, "x"]
    assert jv.contains([1, 2, [3, 4]], [3])
    assert jv.contains({"a": 1, "b": 2}, {"a": 1})
    assert not jv.contains({"a": 1}, {"a": 2})
    assert not jv.contains([1, 2], 3)
    assert jv.contains([1, 2], 2.0)  # numeric cross-type equality
    assert not jv.contains([1], True)  # but bool is not 1
    assert jv.json_type_name(jv.JsonU64(2**63)) == "UNSIGNED INTEGER"
    assert jv.depth({"a": [1, [2]]}) == 4
    assert jv.depth("x") == 1


def test_text_serialization():
    assert jv.json_to_text({"b": 1, "a": [1.5, None, "q\"uote"]}) == '{"a": [1.5, null, "q\\"uote"], "b": 1}'
    assert jv.json_to_text(1.0) == "1.0"  # doubles keep .0, MySQL-style


# -- kernels through RPN ----------------------------------------------------


def _run(expr, columns=None, n=1):
    schema = []
    rpn = compile_expr(expr, schema)
    return eval_rpn(rpn, columns or {}, n, xp=np)


def test_json_kernels_rpn():
    doc = const_json({"a": {"b": 2}, "list": [1, 2, 3]})
    d, nl = _run(call("json_extract", doc, const_bytes(b"$.a.b")))
    assert not nl[0] and jv.json_decode(d[0]) == 2
    d, nl = _run(call("json_unquote", call("json_extract", const_json({"s": "text"}), const_bytes(b"$.s"))))
    assert d[0] == b"text"
    d, _ = _run(call("json_type", doc))
    assert d[0] == b"OBJECT"
    d, _ = _run(call("json_length", doc, const_bytes(b"$.list")))
    assert d[0] == 3
    d, _ = _run(call("json_depth", doc))
    assert d[0] == 3
    d, _ = _run(call("json_valid", const_bytes(b'{"ok": 1}')))
    assert d[0] == 1
    d, _ = _run(call("json_valid", const_bytes(b"nope{")))
    assert d[0] == 0
    d, _ = _run(call("json_keys", doc))
    assert jv.json_decode(d[0]) == ["a", "list"]
    d, _ = _run(call("json_contains", doc, const_json({"a": {"b": 2}})))
    assert d[0] == 1
    d, _ = _run(call("json_set", doc, const_bytes(b"$.new"), const_json(5)))
    assert jv.json_decode(d[0])["new"] == 5
    d, _ = _run(call("json_remove", doc, const_bytes(b"$.list[0]")))
    assert jv.json_decode(d[0])["list"] == [2, 3]
    d, _ = _run(call("json_merge", const_json([1]), const_json([2])))
    assert jv.json_decode(d[0]) == [1, 2]
    d, _ = _run(call("json_array", const_json(1), const_json("x")))
    assert jv.json_decode(d[0]) == [1, "x"]
    d, _ = _run(call("json_object", const_bytes(b"k"), const_json(9)))
    assert jv.json_decode(d[0]) == {"k": 9}
    d, _ = _run(call("json_quote", const_bytes(b'say "hi"')))
    assert d[0] == b'"say \\"hi\\""'
    # missing path → SQL NULL
    d, nl = _run(call("json_extract", doc, const_bytes(b"$.nope")))
    assert nl[0]
    # casts
    d, _ = _run(call("cast_json_int", const_json(7.9)))
    assert d[0] == 8  # MySQL rounds half away from zero
    d, _ = _run(call("cast_json_int", const_json(-7.5)))
    assert d[0] == -8
    d, _ = _run(call("cast_json_real", doc.__class__(jv.json_encode("2.5"), EvalType.JSON)))
    assert d[0] == 2.5
    d, _ = _run(call("cast_string_json", const_bytes(b"[1, 2]")))
    assert jv.json_decode(d[0]) == [1, 2]
    d, _ = _run(call("cast_json_string", const_json({"a": 1})))
    assert d[0] == b'{"a": 1}'


# -- full DAG over a JSON column -------------------------------------------


def test_json_column_through_dag():
    """TableScan over a JSON column → selection on json_length → response:
    the full executor pipeline with JSON datums in the row codec."""
    from tikv_tpu.copr.dag import BatchExecutorsRunner, DagRequest, Selection, TableScan
    from tikv_tpu.copr.datatypes import ColumnInfo
    from tikv_tpu.copr.executors import FixtureScanSource
    from tikv_tpu.copr.rpn import const_int
    from tikv_tpu.copr.table import record_key, encode_row

    TABLE = 99
    cols = [
        ColumnInfo(col_id=1, ftype=FieldType.int64(), is_pk_handle=True),
        ColumnInfo(col_id=2, ftype=FieldType(FieldTypeTp.JSON)),
    ]
    docs = [
        {"name": "a", "tags": [1, 2]},
        {"name": "b", "tags": [3]},
        None,
        {"name": "d", "nested": {"deep": True}},
    ]
    items = []
    for h, doc in enumerate(docs):
        payload = None if doc is None else jv.json_encode(doc)
        items.append((record_key(TABLE, h + 1), encode_row([cols[1]], [payload])))
    dag = DagRequest(
        executors=[
            TableScan(TABLE, cols),
            Selection(
                [call("ge", call("json_length", col(1), const_bytes(b"$.tags")), const_int(1))]
            ),
        ]
    )
    resp = BatchExecutorsRunner(dag, FixtureScanSource(items)).handle_request()
    rows = resp.iter_rows()
    assert len(rows) == 2  # docs a and b have tags; NULL and no-tags filtered
    # the surviving JSON datums round-trip to the original documents
    for row, expect in zip(rows, docs[:2]):
        assert jv.json_decode(row[1]) == expect


def test_json_plan_falls_back_to_cpu():
    """supports() must reject JSON expressions so the endpoint routes them to
    the CPU pipeline rather than the device."""
    from tikv_tpu.copr import jax_eval
    from tikv_tpu.copr.dag import DagRequest, Selection, TableScan
    from tikv_tpu.copr.datatypes import ColumnInfo

    cols = [
        ColumnInfo(col_id=1, ftype=FieldType.int64(), is_pk_handle=True),
        ColumnInfo(col_id=2, ftype=FieldType(FieldTypeTp.JSON)),
    ]
    dag = DagRequest(
        executors=[
            TableScan(5, cols),
            Selection([call("json_valid", call("cast_json_string", col(1)))]),
        ]
    )
    assert not jax_eval.supports(dag)


def test_json_min_max_orders_by_value_not_payload():
    """MIN/MAX over JSON must use MySQL JSON ordering, not payload bytes
    (little-endian ints order bytewise wrong: 256 < 1)."""
    from tikv_tpu.copr.aggr import AggState

    st = AggState("min", EvalType.JSON, 0)
    st.grow(1)
    data = np.array([jv.json_encode(256), jv.json_encode(1), jv.json_encode(-5)], dtype=object)
    st.update(np.zeros(3, dtype=np.int64), data, np.zeros(3, dtype=bool))
    assert jv.json_decode(st.value[0]) == -5
    st2 = AggState("max", EvalType.JSON, 0)
    st2.grow(1)
    st2.update(np.zeros(3, dtype=np.int64), data, np.zeros(3, dtype=bool))
    assert jv.json_decode(st2.value[0]) == 256
    # precedence: booleans above arrays above strings above numbers
    vals = [True, [1], "z", 99]
    data = np.array([jv.json_encode(v) for v in vals], dtype=object)
    st3 = AggState("max", EvalType.JSON, 0)
    st3.grow(1)
    st3.update(np.zeros(4, dtype=np.int64), data, np.zeros(4, dtype=bool))
    assert jv.json_decode(st3.value[0]) is True


def test_json_pairwise_arity_and_bad_paths():
    with pytest.raises(ValueError, match="parameter count"):
        _run(call("json_object", const_bytes(b"k"), const_json(1), const_bytes(b"odd")))
    with pytest.raises(ValueError, match="parameter count"):
        _run(call("json_set", const_json({}), const_bytes(b"$.a"), const_json(1), const_bytes(b"$.b")))
    with pytest.raises(ValueError, match="invalid json path"):
        jv.parse_path('$."unterminated')
    with pytest.raises(ValueError, match="invalid json path"):
        jv.parse_path('$."trailing\\')


def test_bytes_min_max_within_single_batch():
    """Regression: has_value must update per row — min/max over BYTES with
    several rows of one group in ONE batch used to keep the LAST value."""
    from tikv_tpu.copr.aggr import AggState

    st = AggState("min", EvalType.BYTES, 0)
    st.grow(1)
    data = np.array([b"mm", b"zz", b"aa"], dtype=object)
    st.update(np.zeros(3, dtype=np.int64), data, np.zeros(3, dtype=bool))
    assert bytes(st.value[0]) == b"aa"
    st2 = AggState("max", EvalType.BYTES, 0)
    st2.grow(1)
    st2.update(np.zeros(3, dtype=np.int64), data, np.zeros(3, dtype=bool))
    assert bytes(st2.value[0]) == b"zz"


def test_review_fixes_round2():
    # saturating cast of u64 / huge values
    d, _ = _run(call("cast_json_int", const_json(jv.JsonU64(2**63 + 5))))
    assert d[0] == 2**63 - 1
    d, _ = _run(call("cast_json_int", const_json(1e30)))
    assert d[0] == 2**63 - 1
    d, _ = _run(call("cast_json_int", const_json(-1e30)))
    assert d[0] == -(2**63)
    # exact large-int ordering
    assert jv.json_cmp_values(2**63 - 1, 2**63 - 2) > 0
    assert jv.json_cmp_values(2**62, 2**62 + 1) < 0
    assert jv.json_cmp_values(1, 1.5) < 0  # mixed int/float still numeric
    # negative array index rejected
    with pytest.raises(ValueError, match="negative index"):
        jv.parse_path("$.b[-1]")
