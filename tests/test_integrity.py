"""Integrity plane: fingerprints, scrubber, shadow reads, quarantine/repair.

The contract under test is the ISSUE 9 acceptance list (docs/integrity.md):

* the image fingerprint fold over wt_delta / scan_delta applies equals a
  full recompute AND the engine oracle on every tested schedule;
* Checksum (tp=105) served off a warm image fingerprint is byte-identical
  to the CPU-oracle scan;
* with ``corrupt_image`` faults injected mid-traffic, every mismatch is
  detected by the scrubber or a shadow read, ZERO wrong bytes reach any
  client (the shadow path serves the CPU result), the image quarantines
  and rebuilds, and post-heal warm serves are byte-identical;
* split/merge/conf-change invalidation holds under a seeded Nemesis
  schedule — no stale-epoch image is ever served;
* the raft consistency check counts per result, rides the derived-plane
  scrub, and surfaces through the debug RPCs.
"""

import random

import numpy as np
import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID
from fixtures import put_committed

from tikv_tpu.copr import integrity
from tikv_tpu.copr.analyze import checksum_range, crc64
from tikv_tpu.copr.dag import DagRequest, Limit, TableScan
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.raft.cluster import Cluster, FIRST_REGION_ID
from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util import chaos
from tikv_tpu.util.metrics import REGISTRY
from tikv_tpu.util.chaos import Nemesis

NON_HANDLE = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]


def _engine(n=64, v2=False):
    from tikv_tpu.storage.btree_engine import BTreeEngine

    eng = BTreeEngine()
    enc = encode_row_v2 if v2 else encode_row
    for i in range(n):
        name = [b"apple", b"banana", b"cherry"][i % 3]
        put_committed(eng, record_key(TABLE_ID, i),
                      enc(NON_HANDLE, [name, i * 7 % 23, 100 + i]), 90, 100)
    return eng


def _scan_dag():
    return DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), Limit(1 << 20)])


def _req(dag, ts, apply_index, region_id=7, epoch=(1, 1), tp=103):
    return CoprRequest(
        tp, dag, [record_range(TABLE_ID)], ts,
        context={"region_id": region_id, "region_epoch": epoch,
                 "apply_index": apply_index},
    )


def _checksum_req(ts, apply_index, region_id=7):
    return _req(None, ts, apply_index, region_id=region_id, tp=105)


def _pair(eng, **kw):
    warm = Endpoint(LocalEngine(eng), enable_device=True, **kw)
    cold = Endpoint(LocalEngine(eng), enable_device=False,
                    enable_region_cache=False)
    return warm, cold


def _the_image(ep):
    cache = ep.region_cache
    (key,) = list(cache._images)
    return key, cache._images[key]


# ---------------------------------------------------------------------------
# fingerprint primitives
# ---------------------------------------------------------------------------

def test_crc64_batch_matches_scalar():
    rng = random.Random(0)
    rows = [bytes(rng.randrange(256) for _ in range(rng.randrange(0, 70)))
            for _ in range(257)]
    got = integrity.crc64_batch(rows)
    want = np.array([crc64(r) for r in rows], dtype=np.uint64)
    assert (got == want).all()
    assert integrity.crc64_batch([]).size == 0


def test_crc64_batch_bounded_on_skewed_lengths(monkeypatch):
    """A jumbo blob among small rows must take the scalar path (never a
    dense matrix padded to the blob's length), and the small-row matrix is
    sliced — both paths stay bit-identical to the scalar crc64."""
    rng = random.Random(1)
    rows = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 40)))
            for _ in range(64)]
    rows[7] = bytes(rng.randrange(256)
                    for _ in range(integrity._JUMBO_ROW + 500))
    rows[40] = b""
    # tiny slice budget: force multiple matrix chunks
    monkeypatch.setattr(integrity, "_MATRIX_BYTES", 256)
    got = integrity.crc64_batch(rows)
    want = np.array([crc64(r) for r in rows], dtype=np.uint64)
    assert (got == want).all()


def test_shadow_sampler_deterministic_cadence(monkeypatch):
    s = integrity.ShadowSampler(4)
    picks = [s.pick("unary") for _ in range(9)]
    assert picks == [False, False, False, True] * 2 + [False]
    assert integrity.ShadowSampler(0).pick("unary") is False
    monkeypatch.setenv("TIKV_TPU_SHADOW_SAMPLE", "2")
    s2 = integrity.ShadowSampler()
    assert [s2.pick("x") for _ in range(4)] == [False, True, False, True]


# ---------------------------------------------------------------------------
# fold == recompute == oracle across delta schedules
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
def test_fingerprint_fold_matches_recompute_and_oracle(v2):
    """Build → hit → in-place update delta → structural insert+delete delta:
    after every step the incremental fold equals the vectorized recompute
    of the row arrays AND the engine-oracle verification passes."""
    from fixtures import delete_committed

    eng = _engine(v2=v2)
    warm, cold = _pair(eng)
    enc = encode_row_v2 if v2 else encode_row

    def check(label):
        key, img = _the_image(warm)
        assert img.fp_valid, label
        assert img.fp_value == integrity.fold(img.row_fp), label
        assert img.fp_integrity == integrity.fold(
            integrity.mix_fp(img.row_fp, img.row_commit_ts)), label
        res = integrity.verify_image(
            warm.region_cache, key, warm.engine.snapshot(None))
        assert res["outcome"] == "ok", (label, res)

    warm.handle_request(_req(_scan_dag(), 200, 3))
    check("build")
    # in-place update path
    put_committed(eng, record_key(TABLE_ID, 7),
                  enc(NON_HANDLE, [b"apple", 1, 2]), 210, 220)
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "delta"
    check("in-place delta")
    # structural path: new dictionary value + insert + delete
    put_committed(eng, record_key(TABLE_ID, 5),
                  enc(NON_HANDLE, [b"durian", 999, 5]), 310, 320)
    put_committed(eng, record_key(TABLE_ID, 500),
                  enc(NON_HANDLE, [b"elder", 7, 1]), 310, 320)
    delete_committed(eng, record_key(TABLE_ID, 0), 310, 320)
    r = warm.handle_request(_req(_scan_dag(), 400, 5))
    assert r.metrics["region_cache"] == "delta"
    check("structural delta")
    # and the served bytes stayed byte-identical throughout
    assert r.data == cold.handle_request(_req(_scan_dag(), 400, 5)).data


def _seed_rows(kv, region_id, n=32):
    wb = WriteBatch()
    for i in range(n):
        k = Key.from_raw(record_key(TABLE_ID, i))
        w = Write(WriteType.PUT, 90,
                  short_value=encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]))
        wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
    kv.write({"region_id": region_id}, wb)


def _commit_rows(kv, region_id, rows, ts0):
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn.scheduler import Scheduler
    from tikv_tpu.storage.txn_types import Mutation

    sched = Scheduler(kv, pool_size=1, group_commit_max=16)
    ctx = {"region_id": region_id}
    try:
        for i, (handle, row) in enumerate(rows):
            rk = record_key(TABLE_ID, handle)
            t = sched.submit(Prewrite(
                [Mutation.put(Key.from_raw(rk), row)], rk, start_ts=ts0 + i), ctx)
            assert t.done.wait(30) and t.exc is None, t.exc
            t = sched.submit(Commit(
                [Key.from_raw(rk)], ts0 + i, ts0 + 500 + i), ctx)
            assert t.done.wait(30) and t.exc is None, t.exc
    finally:
        sched.stop()
    return ts0 + 500 + len(rows)


def _rreq(dag, ts, region_id, tp=103):
    return CoprRequest(tp, dag, [record_range(TABLE_ID)], ts,
                       context={"region_id": region_id})


def test_wt_delta_fold_equals_full_recompute():
    """The write-through fold (zero CF_WRITE scans) lands the exact
    fingerprint a from-scratch build computes, and the oracle agrees —
    through a real raft write path."""
    c = Cluster(1)
    c.run()
    kv = c.raftkv(1)
    rid = FIRST_REGION_ID
    _seed_rows(kv, rid)
    warm = Endpoint(kv, enable_device=True)
    warm.handle_request(_rreq(_scan_dag(), 200, rid))
    hi = _commit_rows(kv, rid, [
        (3, encode_row(NON_HANDLE, [b"banana", 3, 3])),
        (40, encode_row(NON_HANDLE, [b"cherry", 4, 4])),
    ], ts0=300)
    r = warm.handle_request(_rreq(_scan_dag(), hi + 10, rid))
    assert r.metrics["region_cache"] == "wt_delta"
    key, img = _the_image(warm)
    assert img.fp_valid
    assert img.fp_value == integrity.fold(img.row_fp)
    # full recompute: an independent endpoint builds the same view cold
    fresh = Endpoint(kv, enable_device=True)
    fresh.handle_request(_rreq(_scan_dag(), hi + 10, rid))
    _, img2 = _the_image(fresh)
    assert (img.fp_value, img.fp_integrity) == (img2.fp_value, img2.fp_integrity)
    # and the scrubber oracle (local protocol-free snapshot) agrees
    res = integrity.verify_image(warm.region_cache, key, kv.local_snapshot(rid))
    assert res["outcome"] == "ok", res


# ---------------------------------------------------------------------------
# Checksum (tp=105) off the warm fingerprint
# ---------------------------------------------------------------------------

def test_checksum_warm_serves_off_fingerprint_byte_identical():
    eng = _engine()
    warm, cold = _pair(eng)
    before = REGISTRY.counter("tikv_coprocessor_checksum_total").get(path="warm")
    cold_resp = cold.handle_request(_checksum_req(200, 3))
    # no image yet: the warm endpoint's first checksum scans cold too
    r0 = warm.handle_request(_checksum_req(200, 3))
    assert r0.data == cold_resp.data and not r0.from_cache
    warm.handle_request(_req(_scan_dag(), 200, 3))  # build the image
    r1 = warm.handle_request(_checksum_req(200, 3))
    assert r1.from_cache, "fresh image must answer the checksum warm"
    assert r1.data == cold_resp.data
    assert REGISTRY.counter(
        "tikv_coprocessor_checksum_total").get(path="warm") == before + 1
    # the checksum definition really is checksum_range's (crc64-xor)
    from tikv_tpu.storage.mvcc import ForwardScanner

    start, end = record_range(TABLE_ID)
    kvs = list(ForwardScanner(eng.snapshot(), 200,
                              Key.from_raw(start), Key.from_raw(end)))
    oracle = checksum_range(kvs)
    _, img = _the_image(warm)
    assert img.checksum_parts() == (
        oracle["checksum"], oracle["total_kvs"], oracle["total_bytes"])


def test_checksum_below_image_snapshot_ts_serves_cold():
    """A Checksum at a start_ts BELOW the image's snapshot must refuse the
    warm path (the image may hold rows committed above the reader's ts) —
    the same stale guard as the serving hit path."""
    eng = _engine()  # rows committed at cts=100
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))  # image at snapshot_ts=200
    r = warm.handle_request(_checksum_req(50, 3))
    assert not r.from_cache, "a ts=50 reader must never see the ts=200 image"
    assert r.data == cold.handle_request(_checksum_req(50, 3)).data


def test_checksum_stays_byte_identical_through_deltas():
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    put_committed(eng, record_key(TABLE_ID, 9),
                  encode_row(NON_HANDLE, [b"kiwi", 5, 5]), 210, 220)
    r = warm.handle_request(_req(_scan_dag(), 300, 4))  # fold the delta
    assert r.metrics["region_cache"] == "delta"
    rw = warm.handle_request(_checksum_req(300, 4))
    rc = cold.handle_request(_checksum_req(300, 4))
    assert rw.from_cache and rw.data == rc.data


# ---------------------------------------------------------------------------
# shadow reads: detect → serve oracle → quarantine → rebuild
# ---------------------------------------------------------------------------

def test_shadow_read_detects_corruption_and_serves_oracle():
    eng = _engine()
    warm, cold = _pair(eng, shadow_sample=1)
    oracle = cold.handle_request(_req(_scan_dag(), 200, 3)).data
    warm.handle_request(_req(_scan_dag(), 200, 3))
    r1 = warm.handle_request(_req(_scan_dag(), 200, 3))
    assert r1.from_device and r1.data == oracle
    assert warm.shadow.results.get(("unary", "ok"), 0) >= 1

    info = chaos.corrupt_image(warm.region_cache, random.Random(1), mode="block")
    assert info is not None and info["mode"] == "block"
    r2 = warm.handle_request(_req(_scan_dag(), 200, 3))
    # the CPU result served: zero wrong bytes despite the corrupted image
    assert r2.data == oracle and not r2.from_device
    assert warm.shadow.results.get(("unary", "mismatch")) == 1
    ledger = warm.region_cache.quarantine_ledger
    assert len(ledger) == 1 and ledger[0]["stage"] == "shadow_read"
    # quarantine dropped the image; the next serve rebuilds byte-identically
    r3 = warm.handle_request(_req(_scan_dag(), 200, 3))
    assert r3.metrics["region_cache"] == "miss" and r3.data == oracle
    r4 = warm.handle_request(_req(_scan_dag(), 200, 3))
    assert r4.metrics["region_cache"] == "hit" and r4.from_device
    assert r4.data == oracle


def test_shadow_read_mismatch_fatal_env_raises(monkeypatch):
    eng = _engine()
    warm, _cold = _pair(eng, shadow_sample=1)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    chaos.corrupt_image(warm.region_cache, random.Random(3), mode="block")
    monkeypatch.setenv("TIKV_TPU_INTEGRITY_FATAL", "1")
    with pytest.raises(integrity.IntegrityMismatch):
        warm.handle_request(_req(_scan_dag(), 200, 3))


def test_shadow_read_samples_batch_path():
    """The scheduler's cross-region batch path samples too, and a corrupt
    image batch slot serves the CPU oracle bytes."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation
    from tikv_tpu.copr.rpn import col

    def agg_dag():
        return DagRequest(executors=[
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor("sum", col(2)),
                             AggDescriptor("count", None)]),
        ])

    eng = _engine()
    warm, cold = _pair(eng, shadow_sample=1)

    def reqs():
        return [_req(agg_dag(), 200, 3, region_id=r) for r in (7, 8)]

    oracles = [cold.handle_request(r).data for r in reqs()]
    warm.handle_batch(reqs())  # cold fills
    r1 = warm.handle_batch(reqs())  # warm xregion batch, sampled
    assert [r.data for r in r1] == oracles
    assert warm.shadow.results.get(("batch", "ok"), 0) >= 1
    # corrupt until the strike lands on a column this plan aggregates (a
    # flip in an unread column legitimately leaves the response identical)
    rng = random.Random(5)
    while chaos.corrupt_image(warm.region_cache, rng, region_id=7,
                              mode="block")["column"] != 2:
        pass
    r2 = warm.handle_batch(reqs())
    assert [r.data for r in r2] == oracles, "corrupt slot must serve oracle bytes"
    assert warm.shadow.results.get(("batch", "mismatch"), 0) >= 1
    assert any(e["region_id"] == 7 for e in warm.region_cache.quarantine_ledger)


# ---------------------------------------------------------------------------
# scrubber
# ---------------------------------------------------------------------------

def test_scrubber_detects_corrupt_pending_fold():
    """A corrupted write-through pending delta folds into the image; the
    fingerprint tracks the corrupted CONTENT while the engine oracle holds
    the truth — the hash scrub catches it and the eager rebuild repairs."""
    from tikv_tpu.copr.region_cache import notify_region_write
    from tikv_tpu.storage.txn_types import append_ts

    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))

    # one committed batch: engine write + matching write-through notify
    row = encode_row(NON_HANDLE, [b"banana", 9, 9])
    put_committed(eng, record_key(TABLE_ID, 4), row, 210, 220)
    enc_user = Key.from_raw(record_key(TABLE_ID, 4)).encoded
    w = Write(WriteType.PUT, 210, short_value=row)
    notify_region_write(
        7, [("put", CF_WRITE, append_ts(enc_user, 220), w.to_bytes())], 4)
    _key, img = _the_image(warm)
    assert img.wt_pending is not None

    info = chaos.corrupt_image(warm.region_cache, random.Random(11),
                               mode="pending")
    assert info == {"mode": "pending", "region_id": 7, "handle": 4}
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "wt_delta", "corrupt value folded in"

    results = warm.scrubber.scrub_once()
    assert [x["outcome"] for x in results] == ["mismatch"]
    assert "content" in results[0]["failed"]
    assert warm.region_cache.quarantine_ledger[-1]["stage"] == "scrub"
    # eager rebuild: the image is back, verified, serving oracle bytes warm
    assert [x["outcome"] for x in warm.scrubber.scrub_once()] == ["ok"]
    r2 = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r2.metrics["region_cache"] == "hit"
    assert r2.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data


def test_scrubber_deep_detects_block_corruption_without_traffic():
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    before = REGISTRY.counter(
        "tikv_coprocessor_integrity_scrub_total").get(outcome="mismatch")
    chaos.corrupt_image(warm.region_cache, random.Random(2), mode="block")
    results = warm.scrubber.scrub_once()
    assert [x["outcome"] for x in results] == ["mismatch"]
    assert any(f.startswith(("column:", "nulls:", "handles", "commit_ts"))
               for f in results[0]["failed"])
    assert REGISTRY.counter(
        "tikv_coprocessor_integrity_scrub_total").get(outcome="mismatch") == before + 1
    # repaired eagerly — serving resumes byte-identical with zero cold cost
    r = warm.handle_request(_req(_scan_dag(), 200, 3))
    assert r.metrics["region_cache"] == "hit"
    assert r.data == cold.handle_request(_req(_scan_dag(), 200, 3)).data


def test_scrubber_worker_cadence_and_snapshot():
    eng = _engine()
    warm, _ = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    s = warm.scrubber
    s.start(0.02)
    try:
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and s.snapshot()["rounds"] == 0:
            time.sleep(0.01)
        snap = s.snapshot()
        assert snap["running"] and snap["rounds"] >= 1 and snap["ok"] >= 1
    finally:
        s.stop()
    assert not s.snapshot()["running"]


# ---------------------------------------------------------------------------
# raft consistency check: metrics + derived-plane ride-along
# ---------------------------------------------------------------------------

def _run_consistency_round(c, rid):
    import threading

    leader = c.wait_leader(rid)
    done = threading.Event()
    leader.schedule_consistency_check(lambda r: done.set())
    for _ in range(300):
        c.process()
        c.tick()
        if done.is_set() and all(
            rid in s.consistency_hashes for s in c.stores.values()
        ):
            break
    return leader


def test_consistency_check_counts_results_and_scrubs_images():
    c = Cluster(3)
    c.run()
    rid = FIRST_REGION_ID
    kv = c.raftkv(1)
    _seed_rows(kv, rid)
    warm = Endpoint(kv, enable_device=True)
    cold = Endpoint(kv, enable_device=False)
    oracle = cold.handle_request(_rreq(_scan_dag(), 200, rid)).data
    warm.handle_request(_rreq(_scan_dag(), 200, rid))

    compute0 = REGISTRY.counter("tikv_raft_consistency_check_total").get(result="compute")
    match0 = REGISTRY.counter("tikv_raft_consistency_check_total").get(result="match")
    _run_consistency_round(c, rid)
    cnt = REGISTRY.counter("tikv_raft_consistency_check_total")
    assert cnt.get(result="compute") >= compute0 + 3, "every replica computes"
    assert cnt.get(result="match") >= match0 + 3, "every replica verifies"
    assert cnt.get(result="mismatch") == 0
    # the clean warm image rode the round unquarantined
    assert warm.region_cache.quarantine_ledger == []

    # corrupt the raw fingerprint chain of the leader store's warm image:
    # the NEXT round's ride-along (hash-level — the apply thread never pays
    # a full decode) must quarantine it with zero read traffic
    _key, img = _the_image(warm)
    img.row_fp[0] ^= np.uint64(1)
    _run_consistency_round(c, rid)
    ledger = warm.region_cache.quarantine_ledger
    assert ledger and ledger[-1]["stage"] == "consistency_check"
    # serving recovers byte-identically (rebuild on next serve)
    r = warm.handle_request(_rreq(_scan_dag(), 200, rid))
    assert r.data == oracle


def test_verify_hash_cmd_codec_carries_image_fingerprints():
    """The verify_hash raft entry must round-trip the leader's image
    fingerprint payload through encode_cmd/decode_cmd — otherwise the
    replica cross-check is dead code on the real raft path — and still
    decode pre-integrity-plane entries that carry no payload."""
    from tikv_tpu.raft.store import decode_cmd, encode_cmd

    fps = {"a1b2c3d4e5f60718": {"apply_index": 42, "snapshot_ts": 200,
                                "max_commit_ts": 100,
                                "fingerprint": (1 << 64) - 3},
           "00ff00ff00ff00ff": {"apply_index": 7, "snapshot_ts": 90,
                                "max_commit_ts": 0, "fingerprint": 12345}}
    cmd = {"epoch": (1, 2), "ops": [], "admin": ("verify_hash", 9, 777, fps)}
    rt = decode_cmd(encode_cmd(cmd))
    assert rt["admin"] == ("verify_hash", 9, 777, fps)
    # empty payload round-trips too
    cmd2 = {"epoch": (1, 2), "ops": [], "admin": ("verify_hash", 9, 777, {})}
    assert decode_cmd(encode_cmd(cmd2))["admin"] == ("verify_hash", 9, 777, {})
    # a pre-integrity-plane entry (no count byte) still decodes
    from tikv_tpu.util import codec as ucodec

    legacy = bytearray()
    legacy += ucodec.encode_var_u64(1) + ucodec.encode_var_u64(2)
    legacy.append(6)
    legacy += ucodec.encode_var_u64(9) + ucodec.encode_var_u64(777)
    assert decode_cmd(bytes(legacy))["admin"] == ("verify_hash", 9, 777, {})


def test_scrubber_fatal_mode_recorded_not_swallowed(monkeypatch):
    """Fatal mode on the cadenced path: scrub_once finishes the round's
    bookkeeping then raises, and the worker wrapper records the error
    (the Worker itself swallows exceptions) and halts further rounds."""
    eng = _engine()
    warm, _ = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    chaos.corrupt_image(warm.region_cache, random.Random(2), mode="block")
    monkeypatch.setenv("TIKV_TPU_INTEGRITY_FATAL", "1")
    with pytest.raises(integrity.IntegrityMismatch):
        warm.scrubber.scrub_once()
    # the raise did NOT skip the round's bookkeeping
    snap = warm.scrubber.snapshot()
    assert snap["rounds"] == 1 and snap["mismatch"] == 1
    assert warm.region_cache.quarantine_ledger, "quarantine still recorded"
    # cadenced path: the wrapper records and halts instead of vanishing
    warm.handle_request(_req(_scan_dag(), 200, 3))  # rebuild an image
    chaos.corrupt_image(warm.region_cache, random.Random(3), mode="block")
    s = warm.scrubber
    s.start(0.01)
    try:
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and s.fatal_error is None:
            time.sleep(0.01)
        assert s.fatal_error is not None
        assert s.snapshot()["fatal_error"] == s.fatal_error
    finally:
        s.stop()


def test_replica_cross_check_quarantines_divergent_image():
    """verify_hash carries the leader's image fingerprints; a local image
    at the SAME apply index with a different fingerprint is quarantined."""
    eng = _engine()
    warm, _ = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3, region_id=731))
    key, img = _the_image(warm)
    kid = integrity.image_key_id(key)

    def rec(**over):
        base = {"apply_index": img.apply_index, "snapshot_ts": img.snapshot_ts,
                "max_commit_ts": img.max_commit_ts,
                "fingerprint": img.fp_integrity}
        base.update(over)
        return {kid: base}

    # leader agrees: nothing happens
    ok = integrity.cross_check_image_fps(731, None, rec())
    assert ok == [] and warm.region_cache.quarantine_ledger == []
    # different apply index: incomparable, skipped
    assert integrity.cross_check_image_fps(
        731, None, rec(apply_index=img.apply_index + 5,
                       fingerprint=img.fp_integrity ^ 1)) == []
    # same apply index but a version separates the two read points (the
    # leader's image saw a commit above OUR snapshot): healthy images built
    # at different stale-read timestamps must NOT false-quarantine
    assert integrity.cross_check_image_fps(
        731, None, rec(max_commit_ts=img.snapshot_ts + 50,
                       snapshot_ts=img.snapshot_ts + 100,
                       fingerprint=img.fp_integrity ^ 1)) == []
    assert warm.region_cache.quarantine_ledger == []
    # provably-identical row sets, different fingerprint: quarantined
    bad = integrity.cross_check_image_fps(
        731, None, rec(fingerprint=img.fp_integrity ^ 1))
    assert len(bad) == 1 and bad[0]["stage"] == "replica_divergence"
    assert key not in warm.region_cache._images


# ---------------------------------------------------------------------------
# debug surfaces
# ---------------------------------------------------------------------------

def test_debug_integrity_and_consistency_check_rpcs():
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    c = Cluster(1)
    c.run()
    rid = FIRST_REGION_ID
    kv = c.raftkv(1)
    _seed_rows(kv, rid)
    warm = Endpoint(kv, enable_device=True)
    warm.handle_request(_rreq(_scan_dag(), 200, rid))
    svc = KvService(Storage(engine=kv), warm, raft_router=c.stores[1])

    out = svc.debug_integrity({})
    assert out["enabled"] and len(out["fingerprints"]) == 1
    fp = out["fingerprints"][0]
    assert fp["region_id"] == rid and fp["fp_valid"]
    assert out["quarantine"] == []
    assert out["shadow"]["every"] >= 0 and out["scrubber"]["running"] is False

    trig = svc.debug_consistency_check({})
    assert trig["scheduled"] == [rid]
    for _ in range(200):
        c.process()
        c.tick()
        if rid in c.stores[1].consistency_hashes:
            break
    res = svc.debug_consistency({})
    assert rid in res["hashes"] and res["inconsistent"] == {}

    # quarantine shows up in the ledger view
    chaos.corrupt_image(warm.region_cache, random.Random(1), mode="block")
    warm.scrubber.scrub_once()
    out = svc.debug_integrity({})
    assert len(out["quarantine"]) == 1
    assert out["scrubber"]["mismatch"] == 1


# ---------------------------------------------------------------------------
# THE seeded corruption chaos scenario (tier-1 closure)
# ---------------------------------------------------------------------------

def test_seeded_corruption_chaos_detect_quarantine_repair():
    """corrupt_image faults injected mid-traffic under transport chaos:
    every corruption is detected by a shadow read or the scrubber, ZERO
    wrong bytes reach any client, quarantined images rebuild, and post-heal
    warm serving is byte-identical to the CPU oracle."""
    c = Cluster(3)
    c.run()
    rid = FIRST_REGION_ID
    kv = c.raftkv(1)
    _seed_rows(kv, rid)
    warm = Endpoint(kv, enable_device=True, shadow_sample=1)
    cold = Endpoint(kv, enable_device=False)
    nem = Nemesis(c, seed=909)
    injected = detected_before = 0
    try:
        nem.delay(1, 2, rate=0.3)
        nem.duplicate(rate=0.2)
        ts = 300
        for round_i in range(4):
            # writes land through raft under transport chaos
            ts = _commit_rows(kv, rid, [
                (3 + round_i, encode_row(NON_HANDLE, [b"banana", round_i, 1])),
                (40 + round_i, encode_row(NON_HANDLE, [b"cherry", round_i, 2])),
            ], ts0=ts + 100)
            r = warm.handle_request(_rreq(_scan_dag(), ts + 10, rid))
            assert r.data == cold.handle_request(_rreq(_scan_dag(), ts + 10, rid)).data
            # strike: corrupt the warm image (block and pending modes both
            # land across the seeded schedule), then read immediately — the
            # shadow path must serve the oracle bytes
            info = nem.corrupt_image(warm.region_cache, region_id=rid)
            if info is not None:
                injected += 1
                r = warm.handle_request(_rreq(_scan_dag(), ts + 20, rid))
                assert r.data == cold.handle_request(
                    _rreq(_scan_dag(), ts + 20, rid)).data, \
                    f"round {round_i}: wrong bytes reached a client"
            # scrub sweeps whatever traffic did not touch
            warm.scrubber.scrub_once()
        nem.heal()
        detected = (warm.shadow.results.get(("unary", "mismatch"), 0)
                    + warm.scrubber.snapshot()["mismatch"])
        assert injected >= 2, "the seeded schedule must actually strike"
        assert detected >= injected - detected_before, (
            f"every corruption must be detected: injected={injected} "
            f"detected={detected}")
        assert len(warm.region_cache.quarantine_ledger) >= injected
        # post-heal: warm serving resumes, verified and byte-identical
        ts = _commit_rows(kv, rid, [
            (90, encode_row(NON_HANDLE, [b"elder", 6, 6])),
        ], ts0=ts + 100)
        r = warm.handle_request(_rreq(_scan_dag(), ts + 10, rid))
        assert r.data == cold.handle_request(_rreq(_scan_dag(), ts + 10, rid)).data
        key, img = _the_image(warm)
        assert img.fp_valid and img.fp_value == integrity.fold(img.row_fp)
        res = integrity.verify_image(warm.region_cache, key, kv.local_snapshot(rid))
        assert res["outcome"] == "ok", res
    finally:
        nem.heal()
        nem.close()


# ---------------------------------------------------------------------------
# split/merge/conf-change invalidation under chaos (PR-1 hooks under faults)
# ---------------------------------------------------------------------------

def test_split_merge_conf_change_invalidation_under_chaos():
    """A seeded Nemesis schedule splits, conf-changes, and merges the
    region mid-traffic: no stale-epoch image is ever served — every warm
    response stays byte-identical to the CPU oracle, and the first serve
    after each epoch change rebuilds instead of hitting the dead image."""
    c = Cluster(3)
    c.run()
    rid = FIRST_REGION_ID
    kv = c.raftkv(1)
    _seed_rows(kv, rid)
    warm = Endpoint(kv, enable_device=True, shadow_sample=1)
    cold = Endpoint(kv, enable_device=False)
    nem = Nemesis(c, seed=1234)

    def serve_identical(region_id, ts):
        rw = warm.handle_request(_rreq(_scan_dag(), ts, region_id))
        rc = cold.handle_request(_rreq(_scan_dag(), ts, region_id))
        assert rw.data == rc.data, f"region {region_id} diverged at ts {ts}"
        return rw

    def no_stale_epoch_images():
        with warm.region_cache._mu:
            for key, img in warm.region_cache._images.items():
                peer = c.stores[1].peers.get(key[0])
                assert peer is not None, f"image of dead region {key[0]}"
                cur = (peer.region.epoch.conf_ver, peer.region.epoch.version)
                assert img.epoch == cur, (
                    f"stale-epoch image: region {key[0]} image epoch "
                    f"{img.epoch} != current {cur}")

    try:
        nem.delay(1, 2, rate=0.3)
        nem.reorder(window=3)
        inval0 = warm.region_cache.stats.invalidations
        assert serve_identical(rid, 200).metrics["region_cache"] == "miss"
        serve_identical(rid, 200)
        no_stale_epoch_images()

        # split mid-traffic: both children must serve their clamped halves
        right_id = c.split_region(rid, record_key(TABLE_ID, 16))
        # the new region's leader lands wherever the election fell — pull
        # it onto store 1, whose raftkv both endpoints serve through
        c.elect_leader(right_id, 1)
        r = serve_identical(rid, 300)
        assert r.metrics["region_cache"] == "miss", \
            "post-split serve must rebuild, never hit the pre-split image"
        serve_identical(right_id, 300)
        no_stale_epoch_images()

        # conf change mid-traffic (remove a follower, re-add it)
        leader = c.wait_leader(rid)
        victim_store = next(s for s in (2, 3)
                            if s != leader.region.peer_by_id(leader.peer_id).store_id)
        victim = leader.region.peer_on_store(victim_store)
        c.remove_peer(rid, victim.peer_id)
        serve_identical(rid, 400)
        c.add_peer(rid, victim_store)
        serve_identical(rid, 500)
        no_stale_epoch_images()

        # merge the halves back mid-traffic
        c.merge_regions(rid, right_id)
        r = serve_identical(rid, 600)
        assert r.metrics["region_cache"] == "miss", \
            "post-merge serve must rebuild over the widened range"
        serve_identical(rid, 600)
        no_stale_epoch_images()
        assert warm.region_cache.stats.invalidations > inval0, \
            "the epoch-change hooks must actually fire under this schedule"
        # the whole run was shadow-verified with zero mismatches
        assert warm.shadow.results.get(("unary", "mismatch"), 0) == 0
    finally:
        nem.heal()
        nem.close()
