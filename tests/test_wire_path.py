"""Wire-path tests (ISSUE 8, docs/wire_path.md).

Covers the four layers of the unfrozen cluster wire path:

* the server wire codec: property-based round-trips (deep/large values),
  ``dumps_parts`` zero-copy byte-identity, memoryview-based decode, and the
  gather frame writer;
* the vectorized datum/chunk encoders vs the per-row scalar encoders —
  across every datum type, null patterns, dictionary encodings, chunk
  framing splits, and BOTH row formats (rowv1/rowv2);
* socket-level coalesced serving: concurrent connections through the read
  scheduler's continuous lanes must produce byte-identical responses to
  serial per-request serving, with the stage histogram + coalesce counter
  populated;
* device-owner forwarding: the one-hop, loop-guarded, breaker-protected
  route to the store owning the warm region image.
"""

from __future__ import annotations

import random
import socket
import threading

import numpy as np
import pytest

from tikv_tpu.copr import datum as datum_mod, datum_vec
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.chunk_codec import ChunkColumn, decode_column
from tikv_tpu.copr.dag import (
    Aggregation,
    DagRequest,
    ResponseEncoder,
    Selection,
    TableScan,
)
from tikv_tpu.copr.dag_wire import dag_to_wire
from tikv_tpu.copr.datatypes import (
    Chunk,
    Column,
    ColumnInfo,
    EvalType,
    FieldType,
    enum_column,
    set_column,
)
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rpn import call as rpn_call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.server import wire
from tikv_tpu.server.read_plane import ReadPlane
from tikv_tpu.server.server import (
    Client,
    Server,
    read_frame,
    write_frame_parts,
)
from tikv_tpu.server.service import KvService
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.storage import Storage
from tikv_tpu.util import codec
from tikv_tpu.util.metrics import REGISTRY

from copr_fixtures import TABLE_ID
from fixtures import put_committed

# ---------------------------------------------------------------------------
# server wire codec
# ---------------------------------------------------------------------------


def _random_value(rng: random.Random, depth: int = 0):
    t = rng.randrange(9 if depth < 4 else 6)
    if t == 0:
        return None
    if t == 1:
        return rng.choice([True, False])
    if t == 2:
        return rng.randrange(-(2**63), 2**63)
    if t == 3:
        return rng.random() * 10**rng.randrange(-5, 6)
    if t == 4:
        n = rng.choice([0, 1, 7, 100, 5000])
        return bytes(rng.randrange(256) for _ in range(n))
    if t == 5:
        return "".join(chr(rng.randrange(32, 0x2FF)) for _ in range(rng.randrange(0, 40)))
    if t == 6:
        return [_random_value(rng, depth + 1) for _ in range(rng.randrange(0, 6))]
    if t == 7:
        return tuple(_random_value(rng, depth + 1) for _ in range(rng.randrange(0, 4)))
    return {
        _random_value(rng, 5): _random_value(rng, depth + 1)
        for _ in range(rng.randrange(0, 5))
    }


def _materialize(v):
    if isinstance(v, memoryview):
        return bytes(v)
    if isinstance(v, list):
        return [_materialize(x) for x in v]
    if isinstance(v, tuple):
        return tuple(_materialize(x) for x in v)
    if isinstance(v, dict):
        return {_materialize(k): _materialize(x) for k, x in v.items()}
    return v


def test_wire_roundtrip_property():
    """Property-based round-trip incl. deep nesting and large payloads:
    dumps == concat(dumps_parts), loads inverts, bytes_view decodes to the
    same values (views materialized), memoryview/bytearray inputs accepted."""
    rng = random.Random(1234)
    for i in range(200):
        v = _random_value(rng)
        b = wire.dumps(v)
        parts = wire.dumps_parts(v)
        assert b == b"".join(bytes(p) for p in parts), f"case {i}"
        assert wire.loads(b) == v, f"case {i}"
        assert wire.loads(memoryview(b)) == v, f"case {i}"
        assert wire.loads(bytearray(b)) == v, f"case {i}"
        assert _materialize(wire.loads(b, bytes_view=True)) == v, f"case {i}"


def test_wire_deep_and_trailing_guards():
    deep = None
    for _ in range(40):
        deep = [deep]
    with pytest.raises(ValueError):
        wire.dumps(deep)
    ok = 1
    for _ in range(32):
        ok = [ok]
    assert wire.loads(wire.dumps(ok)) == ok
    with pytest.raises(ValueError):
        wire.loads(wire.dumps(1) + b"\x00")


def test_wire_parts_large_payload_is_not_copied():
    big = bytes(range(256)) * 64  # 16 KiB >= PASSTHROUGH_MIN
    parts = wire.dumps_parts({"data": big, "n": 1})
    views = [p for p in parts if isinstance(p, memoryview)]
    assert views and any(v.obj is big for v in views), \
        "large payload must pass through as a view of the caller's buffer"
    small = b"x" * 16
    parts_small = wire.dumps_parts({"data": small})
    assert not any(isinstance(p, memoryview) and p.obj is small
                   for p in parts_small)


def test_wire_bytes_view_zero_copy_decode():
    big = b"z" * (wire.PASSTHROUGH_MIN + 1)
    frame = wire.dumps({"data": big, "k": b"small"})
    v = wire.loads(frame, bytes_view=True)
    assert isinstance(v["data"], memoryview) and bytes(v["data"]) == big
    assert isinstance(v["k"], bytes)  # small payloads stay plain bytes


def test_write_frame_parts_gather_matches_plain_frame():
    value = [7, "resp", {"data": bytes(range(256)) * 40, "ok": True}]
    a, b = socket.socketpair()
    try:
        write_frame_parts(a, wire.dumps_parts(value))
        got = read_frame(b)
    finally:
        a.close()
        b.close()
    assert got == wire.dumps(value)
    assert wire.loads(got) == value


# ---------------------------------------------------------------------------
# vectorized datum / chunk encoders
# ---------------------------------------------------------------------------


def _scalar_rows(cols, rows) -> bytes:
    out = bytearray()
    for r in rows:
        out += codec.encode_var_u64(len(cols))
        for c in cols:
            flag, value = c.datum_at(int(r))
            datum_mod.encode_datum(out, flag, value)
    return bytes(out)


def _mixed_columns(n: int, rng: np.random.Generator) -> list[Column]:
    mk = lambda p, f: [None if rng.random() < p else f() for _ in range(n)]
    cols = [
        Column.from_values(EvalType.INT,
                           mk(0.1, lambda: int(rng.integers(-(2**63), 2**63 - 1)))),
        Column.from_values(EvalType.REAL, mk(0.1, lambda: float(rng.normal() * 1e18))),
        Column.from_values(EvalType.DECIMAL,
                           mk(0.1, lambda: int(rng.integers(-(10**12), 10**12))), frac=4),
        Column.from_values(EvalType.BYTES,
                           mk(0.1, lambda: bytes(rng.integers(0, 256, rng.integers(0, 40)).astype(np.uint8)))),
        Column.from_values(EvalType.DURATION,
                           mk(0.1, lambda: int(rng.integers(-(10**15), 10**15)))),
        Column.from_values(EvalType.DATETIME,
                           mk(0.1, lambda: int(rng.integers(0, 2**63 - 1)))),
        enum_column([int(rng.integers(0, 4)) for _ in range(n)], (b"a", b"bb", b"ccc")),
        set_column([int(rng.integers(0, 8)) for _ in range(n)], (b"x", b"y", b"z")),
        Column(EvalType.BYTES, rng.integers(0, 3, n), np.zeros(n, bool),
               dictionary=np.array([b"alpha", b"beta", b"gamma"], dtype=object)),
        Column.from_values(EvalType.INT, [None] * n),
        Column.from_values(EvalType.INT,
                           ([0, -1, 1, -(2**63), 2**63 - 1] * (n // 5 + 1))[:n]),
    ]
    return cols


def test_vectorized_rows_byte_identical_all_types():
    rng = np.random.default_rng(7)
    n = 500
    cols = _mixed_columns(n, rng)
    rows = np.arange(n)
    buf, ends = datum_vec.encode_chunk_rows(cols, rows)
    want = _scalar_rows(cols, rows)
    assert buf == want
    assert int(ends[-1]) == len(want)
    # a logical-row selection (executor mask semantics)
    sel = np.sort(rng.choice(n, 117, replace=False))
    assert datum_vec.encode_chunk_rows(cols, sel)[0] == _scalar_rows(cols, sel)
    # empty selection
    b0, e0 = datum_vec.encode_chunk_rows(cols, np.empty(0, np.int64))
    assert b0 == b"" and len(e0) == 0


def test_varint_batch_identity():
    rng = np.random.default_rng(11)
    vals = np.concatenate([
        rng.integers(0, 2**63 - 1, 200, dtype=np.int64).view(np.uint64),
        np.array([0, 1, 127, 128, 2**32, 2**63, 2**64 - 1], np.uint64),
    ])
    data, lens = codec.encode_var_u64_batch(vals)
    want = b"".join(codec.encode_var_u64(int(v)) for v in vals)
    assert data.tobytes() == want
    assert [len(codec.encode_var_u64(int(v))) for v in vals] == lens.tolist()
    ivals = np.array([0, -1, 1, -(2**63), 2**63 - 1, -123456789], np.int64)
    idata, _ = codec.encode_var_i64_batch(ivals)
    assert idata.tobytes() == b"".join(codec.encode_var_i64(int(v)) for v in ivals)


@pytest.mark.parametrize("chunk_rows", [1, 7, 100, 1024])
def test_response_encoder_framing_identical(chunk_rows, monkeypatch):
    rng = np.random.default_rng(3)
    n = 300
    cols = _mixed_columns(n, rng)

    def run(vec: bool):
        monkeypatch.setattr(datum_vec, "VEC_MIN_ROWS", 1 if vec else 10**9)
        enc = ResponseEncoder(chunk_rows)
        for lo, hi in ((0, 33), (33, 34), (34, n)):
            enc.add_chunk(Chunk(cols, np.arange(lo, hi)), None)
        return enc.finish()

    assert run(True) == run(False)


def test_response_encoder_output_offsets(monkeypatch):
    rng = np.random.default_rng(5)
    cols = _mixed_columns(64, rng)
    chunk = Chunk(cols, np.arange(64))

    def run(vec: bool):
        monkeypatch.setattr(datum_vec, "VEC_MIN_ROWS", 1 if vec else 10**9)
        enc = ResponseEncoder(50)
        enc.add_chunk(chunk, [2, 0, 5])
        return enc.finish()

    assert run(True) == run(False)


def test_chunk_column_extend_identity():
    for ft, values in [
        (FieldType.int64(), [1, None, -5, 2**40, None] * 20),
        (FieldType.double(), [1.5, None, -2.25, 1e300] * 25),
    ]:
        a, b = ChunkColumn(ft), ChunkColumn(ft)
        for v in values:
            a.append(v)
        b.extend(values)
        assert a.encode() == b.encode()
        # decode round-trips through the vectorized offsets reader
        dec, consumed = decode_column(a.encode(), 0, ft)
        assert consumed == len(a.encode())
        assert dec.rows == len(values)


# ---------------------------------------------------------------------------
# rowv1 / rowv2 serving byte-identity
# ---------------------------------------------------------------------------

_WIDE_COLUMNS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.varchar()),
    ColumnInfo(3, FieldType.int64()),
    ColumnInfo(4, FieldType.decimal_type(2)),
]


def _wide_rows(n: int):
    rng = np.random.default_rng(9)
    rows = []
    for i in range(n):
        name = None if rng.random() < 0.1 else bytes(f"item-{i % 37}", "ascii")
        cnt = None if rng.random() < 0.1 else int(rng.integers(-1000, 1000))
        price = None if rng.random() < 0.1 else int(rng.integers(0, 10**6))
        rows.append((i, name, cnt, price))
    return rows


def _engine_for(rows, v2: bool):
    eng = BTreeEngine()
    non_handle = _WIDE_COLUMNS[1:]
    for rid, name, cnt, price in rows:
        vals = [name, cnt, price]
        raw = (encode_row_v2(non_handle, vals) if v2
               else encode_row(non_handle, vals))
        put_committed(eng, record_key(TABLE_ID, rid), raw, 90, 100)
    return eng


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
def test_scan_serving_vectorized_identity_both_row_formats(v2, monkeypatch):
    rows = _wide_rows(200)
    ep = Endpoint(LocalEngine(_engine_for(rows, v2)), enable_device=False)
    lo = record_key(TABLE_ID, 0)
    hi = record_key(TABLE_ID, len(rows) + 1)
    req = lambda: CoprRequest(103, DagRequest(executors=[
        TableScan(TABLE_ID, _WIDE_COLUMNS)]), [(lo, hi)], 150)
    monkeypatch.setattr(datum_vec, "VEC_MIN_ROWS", 10**9)
    scalar = ep.handle_request(req()).data
    monkeypatch.setattr(datum_vec, "VEC_MIN_ROWS", 1)
    vectorized = ep.handle_request(req()).data
    assert scalar == vectorized


def test_rowv1_and_rowv2_serve_identical_bytes():
    rows = _wide_rows(150)
    dag = lambda: DagRequest(executors=[TableScan(TABLE_ID, _WIDE_COLUMNS)])
    lo, hi = record_key(TABLE_ID, 0), record_key(TABLE_ID, len(rows) + 1)
    outs = []
    for v2 in (False, True):
        ep = Endpoint(LocalEngine(_engine_for(rows, v2)), enable_device=False)
        outs.append(ep.handle_request(
            CoprRequest(103, dag(), [(lo, hi)], 150)).data)
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# socket-level coalesced serving
# ---------------------------------------------------------------------------


def _numeric_engine(regions: int, rows_per: int):
    rng = np.random.default_rng(21)
    eng = BTreeEngine()
    non_handle = _WIDE_COLUMNS[1:]
    oracle = []
    for i in range(regions * rows_per):
        vals = [b"n%d" % (i % 13), int(rng.integers(0, 100)),
                int(rng.integers(0, 100000))]
        oracle.append(vals)
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(non_handle, vals), 90, 100)
    return eng


def _agg_dag(cut: int) -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, _WIDE_COLUMNS),
        Selection([rpn_call("lt", col(2), const_int(cut))]),
        Aggregation([], [AggDescriptor("sum", col(2)),
                         AggDescriptor("count", None)]),
    ])


def _wire_reqs(regions: int, rows_per: int, clients: int):
    out = []
    for cut in (50, 80):
        for r in range(regions):
            lo = record_key(TABLE_ID, r * rows_per)
            hi = record_key(TABLE_ID, (r + 1) * rows_per)
            for _ in range(clients):
                out.append({
                    "dag": dag_to_wire(_agg_dag(cut)),
                    "ranges": [[lo, hi]],
                    "start_ts": 150,
                    "context": {"region_id": r + 1, "region_epoch": (1, 1),
                                "apply_index": 7},
                })
    return out


def _serve_concurrent(addr, reqs, n_conns: int):
    conns = [Client(*addr) for _ in range(n_conns)]
    results: list = [None] * len(reqs)
    errs: list = []

    def worker(ci):
        try:
            for i in range(ci, len(reqs), n_conns):
                results[i] = conns[ci].call("coprocessor", reqs[i], timeout=120.0)
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    ts = [threading.Thread(target=worker, args=(ci,)) for ci in range(n_conns)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for c in conns:
        c.close()
    assert not errs, errs
    for r in results:
        assert isinstance(r, dict) and not r.get("error"), r
    return [r["data"] for r in results]


def test_socket_coalesced_matches_serial():
    """Multi-connection coalesced serving is byte-identical to per-request
    serving, requests ride dispatcher batches, and every wire stage lands in
    the histogram + the debug_wire_stages RPC."""
    regions, rows_per, clients = 4, 800, 2
    eng = _numeric_engine(regions, rows_per)
    reqs = _wire_reqs(regions, rows_per, clients)

    def run(continuous: bool):
        ep = Endpoint(LocalEngine(eng), enable_device=continuous,
                      block_rows=1 << 10)
        svc = KvService(Storage(engine=LocalEngine(eng)), ep)
        srv = Server(svc)
        srv.start()
        if continuous:
            ep.scheduler.start()
        try:
            _serve_concurrent(srv.addr, reqs, 4)  # warm + compile
            datas = _serve_concurrent(srv.addr, reqs, 4)
            stages = None
            if continuous:
                c = Client(*srv.addr)
                stages = c.call("debug_wire_stages", {})["stages"]
                c.close()
            return datas, stages
        finally:
            ep.scheduler.stop()
            srv.stop()

    coalesce = REGISTRY.counter("tikv_wire_coalesce_total", "")
    before = coalesce.get(outcome="batched")
    coal, stages = run(True)
    assert coalesce.get(outcome="batched") > before, \
        "no request was served out of a coalesced batch"
    serial, _ = run(False)
    assert coal == serial
    for stage in ("decode", "route", "execute", "encode"):
        assert stages.get(stage, {}).get("count", 0) > 0, (stage, stages)


# ---------------------------------------------------------------------------
# device-owner forwarding
# ---------------------------------------------------------------------------


def _owner_counter():
    return REGISTRY.counter("tikv_copr_owner_forward_total", "")


def test_forward_device_owner_one_hop_context():
    calls = []

    def send(store_id, method, req, timeout):
        calls.append((store_id, method, req))
        return {"data": b"OWNED", "from_device": True}

    rp = ReadPlane(send=send)
    rp.set_device_owners({7: 3})
    assert rp.device_owner_of(7) == 3
    before = _owner_counter().get(outcome="ok")
    r = rp.forward_device_owner(
        "coprocessor", {"ranges": [], "start_ts": 5,
                        "context": {"region_id": 7}}, 3)
    assert r == {"data": b"OWNED", "from_device": True}
    assert _owner_counter().get(outcome="ok") == before + 1
    sid, method, freq = calls[0]
    assert sid == 3 and method == "coprocessor"
    # the hop is loop-guarded and may serve on a non-leader owner
    assert freq["context"]["forwarded"] is True
    assert freq["context"]["stale_fallback"] is True


def test_forward_device_owner_remote_error_and_breaker():
    def send_err(store_id, method, req, timeout):
        return {"error": {"not_leader": {"region_id": 7}}}

    rp = ReadPlane(send=send_err)
    before = _owner_counter().get(outcome="remote_region_error")
    assert rp.forward_device_owner("coprocessor", {"context": {}}, 3) is None
    assert _owner_counter().get(outcome="remote_region_error") == before + 1

    def send_boom(store_id, method, req, timeout):
        raise ConnectionError("down")

    rp2 = ReadPlane(send=send_boom)
    assert rp2.forward_device_owner("coprocessor", {"context": {}}, 3) is None
    # consecutive failures trip the per-store breaker
    for _ in range(3):
        rp2.forward_device_owner("coprocessor", {"context": {}}, 3)
    b = _owner_counter().get(outcome="breaker_open")
    assert rp2.forward_device_owner("coprocessor", {"context": {}}, 3) is None
    assert _owner_counter().get(outcome="breaker_open") >= b


def test_owner_forward_service_gating():
    eng = _numeric_engine(1, 64)
    served = []

    def send(store_id, method, req, timeout):
        served.append(store_id)
        return {"data": b"REMOTE", "from_device": True}

    rp = ReadPlane(send=send)
    rp.store_id = 2
    rp.set_device_owners({1: 5})
    ep = Endpoint(LocalEngine(eng), enable_device=False)
    svc = KvService(Storage(engine=LocalEngine(eng)), ep, read_plane=rp)
    agg = dag_to_wire(_agg_dag(50))
    lo, hi = record_key(TABLE_ID, 0), record_key(TABLE_ID, 65)
    base = {"dag": agg, "ranges": [[lo, hi]], "start_ts": 150}

    # owner elsewhere + eligible plan -> forwarded
    r = svc.coprocessor(dict(base, context={"region_id": 1}))
    assert r == {"data": b"REMOTE", "from_device": True} and served == [5]

    # loop guard: a forwarded request NEVER re-forwards
    r = svc.coprocessor(dict(base, context={"region_id": 1, "forwarded": True}))
    assert r.get("data") != b"REMOTE" and served == [5]

    # owner is self -> local serving
    rp.set_device_owners({1: 2})
    svc.coprocessor(dict(base, context={"region_id": 1}))
    assert served == [5]

    # ineligible plan (pure scan) -> local serving
    rp.set_device_owners({1: 5})
    scan = dag_to_wire(DagRequest(executors=[TableScan(TABLE_ID, _WIDE_COLUMNS)]))
    svc.coprocessor({"dag": scan, "ranges": [[lo, hi]], "start_ts": 150,
                     "context": {"region_id": 1}})
    assert served == [5]

    # warm local device image -> local serving even with a remote owner
    ep2 = Endpoint(LocalEngine(eng), enable_device=True)
    svc2 = KvService(Storage(engine=LocalEngine(eng)), ep2, read_plane=rp)
    warm = dict(base, context={"region_id": 1, "region_epoch": (1, 1),
                               "apply_index": 7})
    svc2.coprocessor(warm)  # builds the local image
    if ep2.region_cache.has_warm_region(1):
        svc2.coprocessor(warm)
        assert served == [5]


def test_owner_forward_end_to_end_socket():
    """Store A (CPU-only) forwards a device-eligible DAG to warm owner B
    over a real socket; bytes match B's direct serving."""
    eng = _numeric_engine(1, 512)
    ep_b = Endpoint(LocalEngine(eng), enable_device=True, block_rows=1 << 10)
    svc_b = KvService(Storage(engine=LocalEngine(eng)), ep_b)
    srv_b = Server(svc_b)
    srv_b.start()
    try:
        req = {
            "dag": dag_to_wire(_agg_dag(60)),
            "ranges": [[record_key(TABLE_ID, 0), record_key(TABLE_ID, 513)]],
            "start_ts": 150,
            "context": {"region_id": 1, "region_epoch": (1, 1),
                        "apply_index": 7},
        }
        cb = Client(*srv_b.addr)
        direct = cb.call("coprocessor", req, timeout=120.0)
        cb.close()
        assert not direct.get("error")

        rp = ReadPlane(resolver=lambda sid: srv_b.addr if sid == 9 else None,
                       forward_timeout=120.0)
        rp.store_id = 2
        rp.set_device_owners({1: 9})
        ep_a = Endpoint(LocalEngine(eng), enable_device=False)
        svc_a = KvService(Storage(engine=LocalEngine(eng)), ep_a,
                          read_plane=rp)
        srv_a = Server(svc_a)
        srv_a.start()
        try:
            before = _owner_counter().get(outcome="ok")
            ca = Client(*srv_a.addr)
            via_a = ca.call("coprocessor", req, timeout=120.0)
            ca.close()
            assert not via_a.get("error")
            assert via_a["data"] == direct["data"]
            assert _owner_counter().get(outcome="ok") == before + 1
        finally:
            srv_a.stop()
            rp.close()
    finally:
        srv_b.stop()
