"""kvproto protobuf gateway over a live store: the reference's external wire
contract driven end-to-end (service/kv.rs surface, protobuf payloads).

A real StoreServer (raft store + storage + coprocessor) serves ``pb/<rpc>``
frames whose payloads are kvproto bytes; the client builds protobuf requests
and decodes protobuf responses, including a tipb DAGRequest/SelectResponse
coprocessor round-trip.
"""

from __future__ import annotations

import time

import pytest

from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.pd.service import MockPd, PdService, RemotePd
from tikv_tpu.proto import kvproto_pb as kp
from tikv_tpu.proto import tipb_pb as tp
from tikv_tpu.server.pb_gateway import PbClient
from tikv_tpu.server.server import Server
from tikv_tpu.server.standalone import StoreServer
from tikv_tpu.util import codec

FIRST_REGION_ID = 1


@pytest.fixture(scope="module")
def store():
    pd = MockPd()
    pds = Server(PdService(pd))
    pds.start()
    srv = StoreServer(1, RemotePd(*pds.addr))
    srv.start()
    srv.bootstrap_or_join(1)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        p = srv.store.peers.get(FIRST_REGION_ID)
        if p is not None and p.node.is_leader():
            break
        time.sleep(0.05)
    cli = PbClient(*srv.server.addr)
    yield srv, cli
    cli.close()
    srv.stop()
    pds.stop()


def _ts(store):
    return store.pd.get_tso()


def test_txn_cycle_over_protobuf(store):
    srv, cli = store
    start = _ts(srv)
    r = cli.call("kv_prewrite", kp.PrewriteRequest(
        mutations=[kp.Mutation(op=kp.Op.Put, key=b"pbk1", value=b"v1"),
                   kp.Mutation(op=kp.Op.Put, key=b"pbk2", value=b"v2")],
        primary_lock=b"pbk1", start_version=start,
    ))
    assert not r.errors, r
    commit = _ts(srv)
    r = cli.call("kv_commit", kp.CommitRequest(
        start_version=start, commit_version=commit, keys=[b"pbk1", b"pbk2"]))
    assert r.error is None and r.commit_version == commit
    read = _ts(srv)
    g = cli.call("kv_get", kp.GetRequest(key=b"pbk1", version=read))
    assert g.value == b"v1" and not g.not_found
    s = cli.call("kv_scan", kp.ScanRequest(start_key=b"pbk", version=read, limit=10))
    assert [(p.key, p.value) for p in s.pairs] == [(b"pbk1", b"v1"), (b"pbk2", b"v2")]
    bg = cli.call("kv_batch_get", kp.BatchGetRequest(keys=[b"pbk2", b"pbk1"], version=read))
    assert {(p.key, p.value) for p in bg.pairs} == {(b"pbk1", b"v1"), (b"pbk2", b"v2")}


def test_lock_error_surfaces_as_keyerror(store):
    srv, cli = store
    start = _ts(srv)
    r = cli.call("kv_prewrite", kp.PrewriteRequest(
        mutations=[kp.Mutation(op=kp.Op.Put, key=b"pblock", value=b"x")],
        primary_lock=b"pblock", start_version=start))
    assert not r.errors
    # a read at a later ts hits the lock: GetResponse.error.locked
    g = cli.call("kv_get", kp.GetRequest(key=b"pblock", version=_ts(srv)))
    assert g.error is not None and g.error.locked is not None
    assert g.error.locked.lock_version == start
    assert g.error.locked.primary_lock == b"pblock"
    # check_txn_status sees a live lock; then rollback and verify clean
    r = cli.call("kv_batch_rollback", kp.BatchRollbackRequest(
        start_version=start, keys=[b"pblock"]))
    assert r.error is None
    g = cli.call("kv_get", kp.GetRequest(key=b"pblock", version=_ts(srv)))
    assert g.error is None and g.not_found


def test_raw_ops_over_protobuf(store):
    srv, cli = store
    assert cli.call("raw_put", kp.RawPutRequest(key=b"rk1", value=b"rv1")).error == ""
    g = cli.call("raw_get", kp.RawGetRequest(key=b"rk1"))
    assert g.value == b"rv1"
    cli.call("raw_batch_put", kp.RawBatchPutRequest(
        pairs=[kp.KvPair(key=b"rk2", value=b"rv2"), kp.KvPair(key=b"rk3", value=b"rv3")]))
    sc = cli.call("raw_scan", kp.RawScanRequest(start_key=b"rk", limit=10))
    assert [(p.key, p.value) for p in sc.kvs] == [
        (b"rk1", b"rv1"), (b"rk2", b"rv2"), (b"rk3", b"rv3")]
    cas = cli.call("raw_compare_and_swap", kp.RawCasRequest(
        key=b"rk1", value=b"rv1b", previous_value=b"rv1"))
    assert cas.succeed
    cli.call("raw_delete", kp.RawDeleteRequest(key=b"rk1"))
    assert cli.call("raw_get", kp.RawGetRequest(key=b"rk1")).not_found


def test_coprocessor_dag_over_protobuf(store):
    srv, cli = store
    table_id = 55
    cols = [ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
            ColumnInfo(2, FieldType.int64())]
    # load rows through the txn path so the coprocessor sees committed MVCC data
    start = _ts(srv)
    muts = []
    for h in range(20):
        muts.append(kp.Mutation(op=kp.Op.Put, key=record_key(table_id, h),
                                value=encode_row(cols[1:], [h * 3])))
    r = cli.call("kv_prewrite", kp.PrewriteRequest(
        mutations=muts, primary_lock=muts[0].key, start_version=start))
    assert not r.errors
    commit = _ts(srv)
    assert cli.call("kv_commit", kp.CommitRequest(
        start_version=start, commit_version=commit,
        keys=[m.key for m in muts])).error is None

    dag = tp.DAGRequest(
        start_ts_fallback=_ts(srv),
        executors=[
            tp.ExecutorPb(tp=tp.ExecType.TypeTableScan, tbl_scan=tp.TableScanPb(
                table_id=table_id,
                columns=[tp.ColumnInfoPb(column_id=1, tp=8, pk_handle=True),
                         tp.ColumnInfoPb(column_id=2, tp=8)])),
            tp.ExecutorPb(tp=tp.ExecType.TypeSelection, selection=tp.SelectionPb(
                conditions=[tp.Expr(tp=tp.ExprType.ScalarFunc,
                                    sig=tp.SCALAR_FUNC_SIG["GtInt"],
                                    children=[
                                        tp.Expr(tp=tp.ExprType.ColumnRef,
                                                val=codec.encode_i64(1)),
                                        tp.Expr(tp=tp.ExprType.Int64,
                                                val=codec.encode_i64(39)),
                                    ])])),
        ],
        output_offsets=[0, 1],
    )
    lo, hi = record_range(table_id)
    resp = cli.call("coprocessor", kp.CoprRequestPb(
        tp=kp.REQ_DAG, data=dag.encode(),
        ranges=[kp.KeyRange(start=lo, end=hi)],
        start_ts=dag.start_ts_fallback,
        context=kp.Context(region_id=FIRST_REGION_ID),
    ))
    assert resp.other_error == "" and resp.region_error is None
    sel = tp.SelectResponse.decode(resp.data)
    from tikv_tpu.copr.tipb_bridge import decode_ref_datum

    rows = []
    for ch in sel.chunks:
        off = 0
        while off < len(ch.rows_data):
            h, off = decode_ref_datum(ch.rows_data, off)
            v, off = decode_ref_datum(ch.rows_data, off)
            rows.append((h.value, v.value))
    # col2 = 3h > 39  ⇒  h >= 14
    assert rows == [(h, h * 3) for h in range(14, 20)]


def test_mvcc_debug_over_protobuf(store):
    srv, cli = store
    r = cli.call("mvcc_get_by_key", kp.MvccGetByKeyRequest(key=b"pbk1"))
    assert r.error == "" and r.info is not None
    assert len(r.info.writes) >= 1


def test_coprocessor_type_chunk_over_wire(store):
    """encode_type=TypeChunk in the DAGRequest yields an Arrow-like chunk
    response when the plan's output schema is wire-derivable."""
    from tikv_tpu.copr.chunk_codec import column_values, decode_chunk
    from tikv_tpu.copr.datatypes import FieldType

    srv, cli = store
    dag = tp.DAGRequest(
        start_ts_fallback=_ts(srv),
        executors=[tp.ExecutorPb(tp=tp.ExecType.TypeTableScan, tbl_scan=tp.TableScanPb(
            table_id=55, columns=[tp.ColumnInfoPb(column_id=1, tp=8, pk_handle=True),
                                  tp.ColumnInfoPb(column_id=2, tp=8)]))],
        output_offsets=[0, 1],
        encode_type=tp.EncodeType.TypeChunk,
    )
    lo, hi = record_range(55)
    resp = cli.call("coprocessor", kp.CoprRequestPb(
        tp=kp.REQ_DAG, data=dag.encode(), ranges=[kp.KeyRange(start=lo, end=hi)],
        start_ts=dag.start_ts_fallback, context=kp.Context(region_id=FIRST_REGION_ID)))
    assert resp.other_error == ""
    sel = tp.SelectResponse.decode(resp.data)
    assert sel.encode_type == tp.EncodeType.TypeChunk
    fts = [FieldType.int64(), FieldType.int64()]
    handles, vals = [], []
    for ch in sel.chunks:
        cols = decode_chunk(ch.rows_data, fts)
        handles += column_values(cols[0])
        vals += column_values(cols[1])
    assert handles == list(range(20)) and vals == [h * 3 for h in range(20)]


def test_pb_priority_hint_parses(store):
    from tikv_tpu.server.pb_gateway import sched_hints

    req = kp.GetRequest(context=kp.Context(region_id=1, priority=kp.CommandPri.High,
                                           task_id=42), key=b"k", version=9)
    group, prio = sched_hints(req.encode())
    assert group == 42 and prio == "high"
    # a request with no context yields no hints, without raising
    assert sched_hints(kp.GetRequest(key=b"k").encode()) == (None, None)


def test_deadlock_service_over_pb(store):
    """deadlock.proto over the wire: Detect edges through the pb gateway,
    cycle answered as DeadlockResponse with entry + wait chain."""
    from tikv_tpu.proto import kvproto_pb as kp

    srv, cli = store
    det = kp.DeadlockRequest(
        tp=kp.DEADLOCK_DETECT, entry=kp.WaitForEntry(txn=910, wait_for_txn=920))
    resp = cli.call("deadlock_detect", det)
    assert resp.entry is None  # no cycle yet
    det2 = kp.DeadlockRequest(
        tp=kp.DEADLOCK_DETECT,
        entry=kp.WaitForEntry(txn=920, wait_for_txn=910, key_hash=7777))
    resp = cli.call("deadlock_detect", det2)
    # the response echoes the REQUEST entry (key_hash preserved)
    assert resp.entry is not None and resp.entry.txn == 920
    assert resp.entry.key_hash == 7777
    assert resp.deadlock_key_hash == 7777
    chain = [(e.txn, e.wait_for_txn) for e in resp.wait_chain]
    # a well-formed cycle: no self-edges, and it closes back on itself
    assert chain == [(910, 920), (920, 910)], chain
    # cleanup clears the waiter's edges
    cu = kp.DeadlockRequest(tp=kp.DEADLOCK_CLEAN_UP, entry=kp.WaitForEntry(txn=910))
    cli.call("deadlock_detect", cu)
    resp = cli.call("deadlock_detect", det2)
    assert resp.entry is None  # edge 910->920 gone: no cycle


def test_region_error_maps_data_not_ready_to_errorpb():
    """The stale-read refusal survives the kvproto surface: a
    ``data_not_ready`` dict becomes errorpb.DataIsNotReady with the
    resolved watermark as safe_ts, and round-trips the wire encoding."""
    from tikv_tpu.server.pb_gateway import _region_error

    re = _region_error({"data_not_ready": {
        "region_id": 3, "read_ts": 500, "resolved": 420}})
    assert re is not None and re.data_is_not_ready is not None
    assert re.data_is_not_ready.region_id == 3
    assert re.data_is_not_ready.safe_ts == 420
    back = kp.RegionError.decode(re.encode())
    assert back.data_is_not_ready.safe_ts == 420
    # the read plane's enriched refusal (safe_ts hint, resolved absent)
    re = _region_error({"data_not_ready": {"region_id": 3, "safe_ts": 7}})
    assert re.data_is_not_ready.safe_ts == 7
