"""Wide MySQL decimal + Enum/Set eval types.

Mirrors the reference's decimal unit-test strategy
(tidb_query_datatype/src/codec/mysql/decimal.rs test module): string
round-trips, rounding modes, arithmetic result scales, and binary-codec
memcomparability; plus eval_type.rs Enum/Set columns.
"""

import numpy as np
import pytest

from tikv_tpu.copr import datum
from tikv_tpu.copr.datatypes import (
    Column,
    EvalType,
    FieldType,
    FieldTypeTp,
    enum_column,
    enum_names,
    set_column,
    set_names,
)
from tikv_tpu.copr.mydecimal import (
    CEILING,
    HALF_EVEN,
    MAX_DIGITS,
    TRUNCATE,
    DecimalOverflow,
    MyDecimal,
)


# ---------------------------------------------------------------- parse/print

@pytest.mark.parametrize(
    "s,out",
    [
        ("0", "0"),
        ("-0", "0"),
        ("123.45", "123.45"),
        ("-123.45", "-123.45"),
        (".5", "0.5"),
        ("5.", "5"),
        ("+7", "7"),
        ("1e3", "1000"),
        ("1.5e2", "150"),
        ("1.5e-2", "0.015"),
        ("00012.3400", "12.3400"),
        ("99999999999999999999999999999999999999", "99999999999999999999999999999999999999"),
    ],
)
def test_parse_roundtrip(s, out):
    assert MyDecimal.from_str(s).to_string() == out


def test_parse_errors():
    with pytest.raises(ValueError):
        MyDecimal.from_str("")
    with pytest.raises(ValueError):
        MyDecimal.from_str("abc")
    with pytest.raises(DecimalOverflow):
        MyDecimal.from_str("1" + "0" * MAX_DIGITS)


def test_frac_beyond_30_rounds():
    d = MyDecimal.from_str("0." + "3" * 29 + "35")  # 31 frac digits, tail 35
    assert d.frac == 30
    assert d.to_string().endswith("4")  # rounded half away from zero


# ------------------------------------------------------------------- rounding

@pytest.mark.parametrize(
    "v,frac,mode,out",
    [
        ("2.345", 2, HALF_EVEN, "2.35"),
        ("-2.345", 2, HALF_EVEN, "-2.35"),
        ("2.344", 2, HALF_EVEN, "2.34"),
        ("2.349", 2, TRUNCATE, "2.34"),
        ("-2.349", 2, TRUNCATE, "-2.34"),
        ("2.341", 2, CEILING, "2.35"),
        ("-2.349", 2, CEILING, "-2.34"),
        ("15.1", 0, HALF_EVEN, "15"),
        ("15.5", 0, HALF_EVEN, "16"),
        ("-15.5", 0, HALF_EVEN, "-16"),
        ("153", -2, HALF_EVEN, "200"),
        ("5.45", 1, HALF_EVEN, "5.5"),
    ],
)
def test_round(v, frac, mode, out):
    assert MyDecimal.from_str(v).round(frac, mode).to_string() == out


def test_round_widens_scale():
    assert MyDecimal.from_str("1.5").round(3).to_string() == "1.500"


# ----------------------------------------------------------------- arithmetic

def test_add_sub_result_scale():
    a, b = MyDecimal.from_str("1.25"), MyDecimal.from_str("3.1")
    assert (a + b).to_string() == "4.35"
    assert (a - b).to_string() == "-1.85"
    assert (b - a).frac == 2  # max of operand fracs


def test_mul_scale_adds():
    a, b = MyDecimal.from_str("1.5"), MyDecimal.from_str("2.05")
    c = a * b
    assert c.to_string() == "3.075"
    assert c.frac == 3


def test_mul_scale_capped_at_30():
    a = MyDecimal.from_str("0." + "1" * 20)
    c = a * a
    assert c.frac == 30


def test_div_adds_four_frac_digits():
    a, b = MyDecimal.from_str("1"), MyDecimal.from_str("3")
    assert a.div(b).to_string() == "0.3333"
    assert MyDecimal.from_str("10.0").div(MyDecimal.from_str("4")).to_string() == "2.50000"


def test_div_by_zero_none():
    assert MyDecimal.from_str("1").div(MyDecimal.zero()) is None
    assert MyDecimal.from_str("1") % MyDecimal.zero() is None


def test_mod_sign_follows_dividend():
    assert (MyDecimal.from_str("7.5") % MyDecimal.from_str("2")).to_string() == "1.5"
    assert (MyDecimal.from_str("-7.5") % MyDecimal.from_str("2")).to_string() == "-1.5"


def test_shift():
    d = MyDecimal.from_str("12.34")
    assert d.shift(2).to_string() == "1234"
    assert d.shift(-1).to_string() == "1.234"
    assert d.shift(0) is d


def test_overflow_clamps():
    big = MyDecimal.from_str("9" * (MAX_DIGITS - 1))
    c = big + big
    assert c.int_digits() <= MAX_DIGITS


def test_compare_across_scales():
    assert MyDecimal.from_str("1.50") == MyDecimal.from_str("1.5")
    assert MyDecimal.from_str("1.49") < MyDecimal.from_str("1.5")
    assert MyDecimal.from_str("-2") < MyDecimal.from_str("-1.99")


def test_device_bridge():
    d = MyDecimal.from_str("123.45")
    assert d.to_i64_scaled() == (12345, 2)
    assert MyDecimal.from_i64_scaled(12345, 2) == d
    with pytest.raises(DecimalOverflow):
        MyDecimal.from_str("9" * 40).to_i64_scaled()


# -------------------------------------------------------------- binary codec

@pytest.mark.parametrize(
    "s,prec,frac",
    [
        ("0", 1, 0),
        ("1234567890.1234", 14, 4),
        ("-1234567890.1234", 14, 4),
        ("0.00012345000098765", 22, 20),
        ("-0.00012345000098765", 22, 20),
        ("12345", 5, 0),
        ("-12345", 5, 0),
        ("0.333", 5, 3),
        ("98765432109876543210.123456789", 29, 9),
    ],
)
def test_bin_roundtrip(s, prec, frac):
    d = MyDecimal.from_str(s)
    raw = d.encode_bin(prec, frac)
    assert len(raw) == MyDecimal.bin_size(prec, frac)
    back, used = MyDecimal.decode_bin(raw, prec, frac)
    assert used == len(raw)
    assert back == d


def test_bin_known_layout():
    # 1234567890.1234 @ (14,4): int part = 1 digit + 1 word, frac = 4 digits
    assert MyDecimal.bin_size(14, 4) == 1 + 4 + 2


def test_bin_memcomparable():
    vals = ["-999.99", "-1.5", "-0.01", "0", "0.01", "1.5", "2.49", "999.99"]
    encoded = [MyDecimal.from_str(v).encode_bin(10, 2) for v in vals]
    assert encoded == sorted(encoded)


def test_bin_rounds_to_target_frac():
    d = MyDecimal.from_str("1.999")
    back, _ = MyDecimal.decode_bin(d.encode_bin(10, 2), 10, 2)
    assert back.to_string() == "2.00"


def test_bin_overflow_clamps_to_max():
    d = MyDecimal.from_str("12345")
    back, _ = MyDecimal.decode_bin(d.encode_bin(3, 1), 3, 1)
    assert back.to_string() == "99.9"


# ------------------------------------------------------------------ enum/set

def test_enum_field_type():
    ft = FieldType.enum_type([b"red", b"green", b"blue"])
    assert ft.tp == FieldTypeTp.ENUM
    assert ft.eval_type == EvalType.ENUM
    assert ft.elems == (b"red", b"green", b"blue")


def test_enum_column_names_and_codes():
    elems = (b"red", b"green", b"blue")
    col = enum_column([1, 3, 0, 2, None], elems)
    assert col.eval_type == EvalType.ENUM
    assert col.data.dtype == np.int64
    names = enum_names(col)
    assert names.to_values() == [b"red", b"blue", b"", b"green", None]
    # logical values stay the dictionary codes (ORDER BY semantics)
    assert col.to_values() == [1, 3, 0, 2, None]


def test_enum_datum_is_uint_index():
    col = enum_column([2], (b"a", b"b"))
    flag, v = col.datum_at(0)
    assert (flag, v) == (datum.UINT_FLAG, 2)


def test_set_column_mask_and_names():
    elems = (b"a", b"b", b"c")
    col = set_column([0b101, 0b010, 0, None], elems)
    assert col.eval_type == EvalType.SET
    names = set_names(col)
    assert names.to_values() == [b"a,c", b"b", b"", None]


def test_set_limit_64():
    with pytest.raises(ValueError):
        FieldType.set_type([b"x%d" % k for k in range(65)])


def test_enum_rpn_int_context():
    """Enum codes flow through RPN comparisons as plain ints."""
    from tikv_tpu.copr import rpn

    col = enum_column([1, 2, 3, 2], (b"s", b"m", b"l"))
    expr = rpn.call("eq", rpn.col(0), rpn.const_int(2))
    compiled = rpn.compile_expr(expr, [(EvalType.ENUM, 0)])
    data, nulls = rpn.eval_rpn(compiled, {0: (col.data, col.nulls)}, 4)
    assert list(data) == [0, 1, 0, 1]


def test_mul_excess_scale_exact_truncation():
    a = MyDecimal.from_str("1." + "1" * 25)
    c = a * a
    assert c.frac == 30
    exact = (a.unscaled * a.unscaled) // 10 ** (50 - 30)
    assert c.unscaled == exact


def test_enum_concat_keeps_dictionary():
    elems = (b"a", b"b")
    c = Column.concat([enum_column([1], elems), enum_column([2], elems)])
    assert enum_names(c).to_values() == [b"a", b"b"]
    with pytest.raises(ValueError):
        Column.concat([enum_column([1], elems), enum_column([1], (b"x", b"y"))])


def test_set_bit63_representable():
    col = set_column([1 << 63], tuple(b"x%d" % k for k in range(64)))
    assert int(col.data[0]) == 1 << 63
    assert set_names(col).to_values() == [b"x63"]


def test_enum_names_out_of_range_is_invalid_empty():
    col = enum_column([5, -1], (b"a", b"b"))
    assert enum_names(col).to_values() == [b"", b""]


def test_enum_row_codec_roundtrip():
    from tikv_tpu.copr.table import RowBatchDecoder, encode_row
    from tikv_tpu.copr.datatypes import ColumnInfo
    import numpy as np

    infos = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.enum_type([b"red", b"blue"])),
        ColumnInfo(3, FieldType.set_type([b"r", b"w"])),
    ]
    rows = [encode_row(infos[1:], [2, 0b11]), encode_row(infos[1:], [None, 1 << 1])]
    cols = RowBatchDecoder(infos).decode(np.array([7, 8]), rows)
    assert cols[1].eval_type == EvalType.ENUM
    assert enum_names(cols[1]).to_values() == [b"blue", None]
    assert set_names(cols[2]).to_values() == [b"r,w", b"w"]


def test_bin_zero_int_part_prec_eq_frac():
    d = MyDecimal.from_str("0.50")
    back, _ = MyDecimal.decode_bin(d.encode_bin(2, 2), 2, 2)
    assert back.to_string() == "0.50"


def test_set_const_bit63_comparison():
    from tikv_tpu.copr import rpn

    col = set_column([0b11, (1 << 63) + 3], tuple(b"x%d" % k for k in range(64)))
    expr = rpn.call("eq", rpn.col(0), rpn.const_set((1 << 63) + 3))
    compiled = rpn.compile_expr(expr, [(EvalType.SET, 0)])
    data, _ = rpn.eval_rpn(compiled, {0: (col.data, col.nulls)}, 2)
    assert list(data) == [0, 1]


def test_groupby_enum_keeps_dictionary():
    from tikv_tpu.copr.executors import (
        BatchExecuteResult,
        BatchExecutor,
        BatchHashAggregationExecutor,
    )
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.datatypes import Chunk
    from tikv_tpu.copr import rpn

    elems = (b"red", b"green")
    chunk = Chunk.full([
        enum_column([1, 2, 1, 1], elems),
        Column.from_values(EvalType.INT, [10, 20, 30, 40]),
    ])

    class _Stub(BatchExecutor):
        def __init__(self):
            self._sent = False

        def schema(self):
            return [(EvalType.ENUM, 0), (EvalType.INT, 0)]

        def next_batch(self, scan_rows):
            if self._sent:
                return BatchExecuteResult(Chunk.full([]), True)
            self._sent = True
            return BatchExecuteResult(chunk, True)

    child = _Stub()
    agg = BatchHashAggregationExecutor(
        child, [rpn.col(0)], [AggDescriptor("sum", rpn.col(1))]
    )
    r = agg.next_batch(1024)
    key_col = r.chunk.columns[-1]
    assert key_col.eval_type == EvalType.ENUM
    got = dict(zip(enum_names(key_col).to_values(), r.chunk.columns[0].to_values()))
    assert got == {b"red": 80, b"green": 20}
