"""Socket tests for the Appendix-A RPCs added in round 2: MVCC debug reads,
raw_batch_scan, GC support (unsafe_destroy_range, physical_scan_lock + lock
observer trio), get_store_safe_ts, get_lock_wait_info, Backup and
Diagnostics services — each driven over the framed-TCP wire against the full
single-node assembly (kv.rs:229-1061, server.rs:887-993)."""

import threading
import time

import pytest

from tikv_tpu.pd.client import MockPd
from tikv_tpu.server.node import FIRST_REGION_ID
from tikv_tpu.server.server import Client
from tikv_tpu.server.standalone import StoreServer
from tikv_tpu.pd.service import PdService
from tikv_tpu.server.server import Server


@pytest.fixture(scope="module")
def node_client():
    pd = MockPd()
    pds = Server(PdService(pd))
    pds.start()
    from tikv_tpu.pd.service import RemotePd

    srv = StoreServer(1, RemotePd(*pds.addr))
    srv.start()
    srv.bootstrap_or_join(1)
    # wait for leadership
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        p = srv.store.peers.get(FIRST_REGION_ID)
        if p is not None and p.node.is_leader():
            break
        time.sleep(0.05)
    client = Client(*srv.server.addr)
    yield srv, client, pd
    client.close()
    srv.stop()
    pds.stop()


CTX = {"region_id": FIRST_REGION_ID}


def _put(client, pd, key, value):
    ts1 = pd.get_tso()
    r = client.call(
        "kv_prewrite",
        {
            "mutations": [{"op": "put", "key": key, "value": value}],
            "primary_lock": key,
            "start_version": ts1,
            "context": CTX,
        },
    )
    assert "error" not in r and not r.get("errors"), r
    r = client.call(
        "kv_commit",
        {"keys": [key], "start_version": ts1, "commit_version": pd.get_tso(), "context": CTX},
    )
    assert "error" not in r, r
    return ts1


def test_mvcc_get_by_key_and_start_ts(node_client):
    srv, client, pd = node_client
    ts1 = _put(client, pd, b"mk", b"mv1")
    _put(client, pd, b"mk", b"mv2")
    r = client.call("mvcc_get_by_key", {"key": b"mk", "context": CTX})
    assert "error" not in r, r
    assert r["info"]["lock"] is None
    assert len(r["info"]["writes"]) == 2
    assert r["info"]["writes"][0]["short_value"] == b"mv2"  # newest first
    r2 = client.call("mvcc_get_by_start_ts", {"start_ts": ts1, "context": CTX})
    assert r2["key"] == b"mk"
    assert any(w["start_ts"] == ts1 for w in r2["info"]["writes"])


def test_mvcc_get_by_start_ts_finds_live_lock(node_client):
    srv, client, pd = node_client
    ts = pd.get_tso()
    r = client.call(
        "kv_prewrite",
        {
            "mutations": [{"op": "put", "key": b"locked-k", "value": b"x"}],
            "primary_lock": b"locked-k",
            "start_version": ts,
            "context": CTX,
        },
    )
    assert "error" not in r and not r.get("errors"), r
    r = client.call("mvcc_get_by_start_ts", {"start_ts": ts, "context": CTX})
    assert r["key"] == b"locked-k"
    assert r["info"]["lock"] is not None and r["info"]["lock"]["start_ts"] == ts
    # cleanup: rollback so later tests see no lock
    client.call("kv_batch_rollback", {"keys": [b"locked-k"], "start_version": ts, "context": CTX})


def test_raw_batch_scan(node_client):
    srv, client, pd = node_client
    for i in range(6):
        client.call("raw_put", {"key": b"rb%d" % i, "value": b"v%d" % i, "context": CTX})
    r = client.call(
        "raw_batch_scan",
        {"ranges": [[b"rb0", b"rb2"], [b"rb4", b"rb9"]], "each_limit": 10, "context": CTX},
    )
    got = [k for k, _v in r["kvs"]]
    assert got == [b"rb0", b"rb1", b"rb4", b"rb5"]


def test_kv_gc_is_deliberate_stub(node_client):
    srv, client, pd = node_client
    r = client.call("kv_gc", {"context": CTX})
    assert "deprecated" in r["error"]["other"]


def test_lock_observer_trio_and_physical_scan(node_client):
    srv, client, pd = node_client
    max_ts = pd.get_tso() + (1000 << 18)
    assert client.call("register_lock_observer", {"max_ts": max_ts}) == {}
    ts = pd.get_tso()
    client.call(
        "kv_prewrite",
        {
            "mutations": [{"op": "put", "key": b"obs-k", "value": b"x"}],
            "primary_lock": b"obs-k",
            "start_version": ts,
            "context": CTX,
        },
    )
    r = client.call("check_lock_observer", {})
    assert r["is_clean"] is True
    assert any(l["key"] == b"obs-k" and l["lock_ts"] == ts for l in r["locks"]), r
    # physical scan sees it too (green GC fallback path)
    r = client.call("physical_scan_lock", {"max_ts": max_ts})
    assert any(l["key"] == b"obs-k" for l in r["locks"])
    assert client.call("remove_lock_observer", {}) == {}
    r = client.call("check_lock_observer", {})
    assert "error" in r  # no observer registered anymore
    client.call("kv_batch_rollback", {"keys": [b"obs-k"], "start_version": ts, "context": CTX})


def test_unsafe_destroy_range(node_client):
    srv, client, pd = node_client
    _put(client, pd, b"udr-a", b"1")
    _put(client, pd, b"udr-b", b"2")
    _put(client, pd, b"uds-keep", b"3")
    r = client.call("unsafe_destroy_range", {"start_key": b"udr-", "end_key": b"udr-\xff"})
    assert "error" not in r, r
    r = client.call("kv_get", {"key": b"udr-a", "version": pd.get_tso(), "context": CTX})
    assert r.get("value") is None
    r = client.call("kv_get", {"key": b"uds-keep", "version": pd.get_tso(), "context": CTX})
    assert r["value"] == b"3"


def test_get_store_safe_ts(node_client):
    srv, client, pd = node_client
    _put(client, pd, b"sts", b"v")
    srv.resolved_ts.advance_all()
    r = client.call("get_store_safe_ts", {})
    assert r["safe_ts"] > 0


def test_get_lock_wait_info(node_client):
    srv, client, pd = node_client
    r = client.call("get_lock_wait_info", {})
    assert r == {"entries": []}
    done = threading.Event()

    def waiter():
        try:
            srv.lock_manager.wait_for(900, 800, b"wk", timeout=2.0)
        finally:
            done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.monotonic() + 2
    entries = []
    while time.monotonic() < deadline and not entries:
        entries = client.call("get_lock_wait_info", {})["entries"]
        time.sleep(0.02)
    assert entries and entries[0]["txn"] == 900 and entries[0]["wait_for_txn"] == 800
    srv.lock_manager.wake_up(b"wk", 800)
    done.wait(3)


def test_pessimistic_lock_waits_for_release(node_client):
    """kv_pessimistic_lock with wait_timeout_ms parks on the waiter manager
    and retries after the blocker commits (waiter_manager.rs flow)."""
    srv, client, pd = node_client
    ts1 = pd.get_tso()
    r = client.call(
        "kv_prewrite",
        {
            "mutations": [{"op": "put", "key": b"pw-k", "value": b"v1"}],
            "primary_lock": b"pw-k",
            "start_version": ts1,
            "context": CTX,
        },
    )
    assert "error" not in r and not r.get("errors"), r
    results = {}

    def contender():
        c2 = Client(*srv.server.addr)
        ts2 = pd.get_tso()
        resp = c2.call(
            "kv_pessimistic_lock",
            {
                "keys": [b"pw-k"],
                "primary_lock": b"pw-k",
                "start_version": ts2,
                "for_update_ts": ts2,
                "wait_timeout_ms": 5000,
                "context": CTX,
            },
            timeout=15,
        )
        if "conflict" in (resp.get("error") or {}):
            # the blocker committed above our for_update_ts while we waited:
            # like TiDB, retry at a fresh for_update_ts (the wait part —
            # which this test measures — already succeeded)
            resp = c2.call(
                "kv_pessimistic_lock",
                {
                    "keys": [b"pw-k"],
                    "primary_lock": b"pw-k",
                    "start_version": ts2,
                    "for_update_ts": pd.get_tso(),
                    "wait_timeout_ms": 0,
                    "context": CTX,
                },
                timeout=15,
            )
        results["resp"] = resp
        results["ts2"] = ts2
        c2.close()

    t = threading.Thread(target=contender, daemon=True)
    t.start()
    # the contender is parked on the wait queue
    deadline = time.monotonic() + 3
    entries = []
    while time.monotonic() < deadline and not entries:
        entries = client.call("get_lock_wait_info", {})["entries"]
        time.sleep(0.02)
    assert entries and entries[0]["wait_for_txn"] == ts1, entries
    # blocker commits -> waiter wakes, retries, acquires
    client.call(
        "kv_commit",
        {"keys": [b"pw-k"], "start_version": ts1, "commit_version": pd.get_tso(), "context": CTX},
    )
    t.join(10)
    assert not t.is_alive()
    assert "error" not in results["resp"], results["resp"]
    # cleanup the pessimistic lock
    client.call(
        "kv_pessimistic_rollback",
        {"keys": [b"pw-k"], "start_version": results["ts2"], "for_update_ts": results["ts2"], "context": CTX},
    )


def test_backup_service_over_wire(node_client, tmp_path):
    srv, client, pd = node_client
    _put(client, pd, b"bk-1", b"bv1")
    _put(client, pd, b"bk-2", b"bv2")
    backup_ts = pd.get_tso()
    r = client.call(
        "backup",
        {
            "storage": f"local://{tmp_path}",
            "ranges": [[b"bk-", b"bk-\xff"]],
            "backup_ts": backup_ts,
            "name_prefix": "t1",
            "context": CTX,
        },
    )
    assert "error" not in r, r
    assert r["files"][0]["kvs"] == 2
    # the file is really in the external storage
    from tikv_tpu.sidecar.backup import LocalStorage

    st = LocalStorage(str(tmp_path))
    assert "t1-0000" in st.list()


def test_diagnostics_service(node_client, tmp_path):
    srv, client, pd = node_client
    log = tmp_path / "store.log"
    log.write_text(
        "2026-07-29 10:00:00 INFO start ok\n"
        "2026-07-29 10:00:01 WARN slow request region=1\n"
        "2026-07-29 10:00:02 ERROR disk failure on /dev/x\n"
    )
    srv.service.diagnostics.log_path = str(log)
    r = client.call("diagnostics_search_log", {"patterns": ["region=1"]})
    assert len(r["lines"]) == 1 and r["lines"][0]["level"] == "WARN"
    r = client.call("diagnostics_search_log", {"levels": ["ERROR"]})
    assert len(r["lines"]) == 1 and "disk failure" in r["lines"][0]["message"]
    info = client.call("diagnostics_server_info", {})
    assert info["cpu_count"] >= 1 and info["pid"] > 0 and "memory" in info


def test_standalone_builds_mesh_endpoint_on_multidevice(tmp_path):
    """Under the 8-virtual-device test mesh, the ASSEMBLED store serves the
    coprocessor through a (regions × groups) mesh (BASELINE config #5: the
    copr scale-out path is reachable from the real serving assembly)."""
    import jax

    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.pd.service import PdService
    from tikv_tpu.server.server import Server
    from tikv_tpu.server.standalone import StoreServer

    assert jax.device_count() == 8
    pds = Server(PdService(MockPd()))
    pds.start()
    from tikv_tpu.pd.service import RemotePd

    srv = StoreServer(1, RemotePd(*pds.addr), enable_device=True)
    try:
        mesh = srv.copr.mesh
        assert mesh is not None and mesh.size == 8
        assert dict(mesh.shape) == {"regions": 4, "groups": 2}
    finally:
        srv.stop()
        pds.stop()
