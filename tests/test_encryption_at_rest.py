"""Encryption at rest wired into the LIVE store (VERDICT r4 item 4).

Unit level: the native LSM engine and raft log engine encrypt every file
(runs, WAL, segments) with per-file sidecar metadata, recover across reopen,
rotate data keys on a running engine, and reject an unknown master key.
Staged import files seal under the same DataKeyManager.

Deployment level: three OS-process stores boot with --encryption-master-key,
survive kill -9 + recovery over encrypted dirs, rotate keys through the
debug RPC, and ctl backup/restore round-trips with the master key — while a
byte-scan proves no plaintext value ever touches disk.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tikv_tpu.storage.encryption import DataKeyManager, MasterKey
from tikv_tpu.storage.engine import CF_DEFAULT, WriteBatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SECRET = b"PLAINTEXTCANARY314159"


def _scan_plaintext(root: str, needle: bytes = SECRET) -> list:
    hits = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            with open(os.path.join(dirpath, fn), "rb") as f:
                if needle in f.read():
                    hits.append(os.path.join(dirpath, fn))
    return hits


def _native_or_skip():
    from tikv_tpu.native.engine import native_available

    if not native_available():
        pytest.skip("native engine unavailable")


def test_engine_files_encrypted_and_recover(tmp_path):
    _native_or_skip()
    from tikv_tpu.native.engine import NativeEngine

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    eng = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    wb = WriteBatch()
    for i in range(3000):
        wb.put_cf(CF_DEFAULT, b"k%06d" % i, SECRET + b"%d" % i)
    eng.write(wb)
    eng.checkpoint()  # flush → encrypted run
    wb2 = WriteBatch()
    wb2.put_cf(CF_DEFAULT, b"walonly", SECRET + b"w")
    eng.write(wb2)  # stays in the encrypted WAL
    eng.close()

    assert _scan_plaintext(str(tmp_path / "data")) == []
    assert any(f.endswith(".enc") for f in os.listdir(tmp_path / "data"))

    eng2 = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    snap = eng2.snapshot()
    assert snap.get_cf(CF_DEFAULT, b"k000042") == SECRET + b"42"
    assert snap.get_cf(CF_DEFAULT, b"walonly") == SECRET + b"w"
    assert sum(1 for _ in snap.scan_cf(CF_DEFAULT, b"k", b"l")) == 3000
    eng2.close()


def test_engine_key_rotation_live(tmp_path):
    _native_or_skip()
    from tikv_tpu.native.engine import NativeEngine

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    eng = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"old", SECRET + b"old")
    eng.write(wb)
    eng.checkpoint()
    new_id = eng.rotate_data_key()
    assert new_id == 2
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"new", SECRET + b"new")
    eng.write(wb)
    eng.checkpoint()
    eng.close()
    assert _scan_plaintext(str(tmp_path / "data")) == []
    # both generations readable after reopen (old files keep their key)
    eng2 = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    s = eng2.snapshot()
    assert s.get_cf(CF_DEFAULT, b"old") == SECRET + b"old"
    assert s.get_cf(CF_DEFAULT, b"new") == SECRET + b"new"
    eng2.close()


def test_engine_wrong_master_key_rejected(tmp_path):
    _native_or_skip()
    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    del km
    with pytest.raises(ValueError):
        DataKeyManager.open(
            MasterKey.mem(b"another-master-key-1"), str(tmp_path / "keys.dict")
        )


def test_raftlog_segments_encrypted(tmp_path):
    from tikv_tpu.native.raftlog import raftlog_available

    if not raftlog_available():
        pytest.skip("native raftlog unavailable")
    from tikv_tpu.native.raftlog import NativeRaftLog

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    rl = NativeRaftLog(str(tmp_path / "log"), segment_bytes=1 << 14, keys_mgr=km)
    for i in range(1, 400):
        rl.append(7, i, [SECRET + b"-%d" % i], state=b"HS")
    rl.close()
    assert _scan_plaintext(str(tmp_path / "log")) == []
    rl2 = NativeRaftLog(str(tmp_path / "log"), segment_bytes=1 << 14, keys_mgr=km)
    assert rl2.entries(7, 9, 11) == [(9, SECRET + b"-9"), (10, SECRET + b"-10")]
    kid = rl2.rotate_data_key()
    rl2.append(7, 400, [SECRET + b"-rot"])
    # purge triggers rewrite of surviving records into NEW (rotated) segments
    rl2.purge(7, 390)
    rl2.close()
    assert _scan_plaintext(str(tmp_path / "log")) == []
    rl3 = NativeRaftLog(str(tmp_path / "log"), segment_bytes=1 << 14, keys_mgr=km)
    assert rl3.entries(7, 400, 401) == [(400, SECRET + b"-rot")]
    assert rl3.entries(7, 395, 396) == [(395, SECRET + b"-395")]
    rl3.close()
    assert kid == 2


def test_plaintext_dir_migrates_to_encrypted(tmp_path):
    """A store that ran unencrypted opens with encryption on: old plaintext
    files (no sidecar) stay readable, new files encrypt."""
    _native_or_skip()
    from tikv_tpu.native.engine import NativeEngine

    eng = NativeEngine(str(tmp_path / "data"))
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"legacy", b"legacy-value")
    eng.write(wb)
    eng.checkpoint()
    eng.close()

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    eng2 = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    assert eng2.snapshot().get_cf(CF_DEFAULT, b"legacy") == b"legacy-value"
    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"fresh", SECRET)
    eng2.write(wb)
    eng2.checkpoint()
    eng2.close()
    assert _scan_plaintext(str(tmp_path / "data")) == []
    eng3 = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    s = eng3.snapshot()
    assert s.get_cf(CF_DEFAULT, b"legacy") == b"legacy-value"
    assert s.get_cf(CF_DEFAULT, b"fresh") == SECRET
    eng3.close()


def test_import_staging_sealed(tmp_path):
    from tikv_tpu.sidecar.backup import LocalStorage
    from tikv_tpu.sidecar.importer import SstImporter
    from tikv_tpu.util import codec

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    store = LocalStorage(str(tmp_path / "backup"))
    payload = bytearray(b"TPUBK1\n")
    payload += codec.encode_var_u64(5)
    payload += codec.encode_compact_bytes(b"rowkey")
    payload += codec.encode_compact_bytes(SECRET)
    store.write("f1", bytes(payload))
    imp = SstImporter(store, workdir=str(tmp_path / "staging"), keys_mgr=km)
    imp.download("f1")
    assert _scan_plaintext(str(tmp_path / "staging")) == []
    data, _rw = imp._staged_data("f1", None)
    assert SECRET in data  # unseals back to the plaintext staging content


# ---------------------------------------------------------------------------
# Deployment: 3 encrypted store processes + kill -9 + rotation + ctl round-trip
# ---------------------------------------------------------------------------


def _spawn_encrypted(store_id: int, pd_addr, data_dir: str, master_path: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [sys.executable, "-m", "tikv_tpu.server.standalone",
         "--store-id", str(store_id), "--pd", f"{pd_addr[0]}:{pd_addr[1]}",
         "--dir", data_dir, "--expect-stores", "3",
         "--encryption-master-key", master_path],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def test_encrypted_multiprocess_cluster(tmp_path):
    _native_or_skip()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_multiprocess_cluster import (
        FIRST_REGION_ID,
        _ClusterClient,
        _wait_ready,
    )

    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.pd.service import PdService
    from tikv_tpu.server.server import Server

    master_path = str(tmp_path / "master.key")
    with open(master_path, "wb") as f:
        f.write(os.urandom(32))
    pd = MockPd()
    pd_server = Server(PdService(pd))
    pd_server.start()
    procs, client = {}, None
    try:
        for sid in (1, 2, 3):
            procs[sid] = _spawn_encrypted(
                sid, pd_server.addr, str(tmp_path / f"store{sid}"), master_path)
        for sid in (1, 2, 3):
            _wait_ready(procs[sid])
        client = _ClusterClient(pd)
        client.put(b"alpha", SECRET + b"1")
        assert client.get(b"alpha") == SECRET + b"1"

        # rotate the data key on the leader through the RPC, keep writing
        leader_sid = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and leader_sid is None:
            leader_sid = pd.leader_of(FIRST_REGION_ID)
            time.sleep(0.1)
        lc = client._leader_client()
        r = lc.call("debug_rotate_data_key", {})
        assert r.get("key_id", 0) >= 2, r
        client.put(b"beta", SECRET + b"2")
        assert client.get(b"beta") == SECRET + b"2"

        # kill -9 the leader; survivors carry on; restart recovers the
        # encrypted dir (WAL + raft segments decrypt through keys.dict)
        procs[leader_sid].kill()
        procs[leader_sid].wait()
        client.put(b"gamma", SECRET + b"3")
        assert client.get(b"gamma") == SECRET + b"3"
        procs[leader_sid] = _spawn_encrypted(
            leader_sid, pd_server.addr, str(tmp_path / f"store{leader_sid}"),
            master_path)
        _wait_ready(procs[leader_sid])
        assert client.get(b"alpha") == SECRET + b"1"

        for sid in (1, 2, 3):
            procs[sid].send_signal(signal.SIGKILL)
            procs[sid].wait()

        # no store directory holds the canary in plaintext
        for sid in (1, 2, 3):
            assert _scan_plaintext(str(tmp_path / f"store{sid}")) == []

        # ctl offline backup → verify → restore, all under the master key
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        db = str(tmp_path / f"store{1}")
        out_dir = str(tmp_path / "backup")

        def ctl(*args):
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "ctl.py"), *args],
                env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
            assert r.returncode == 0, r.stdout + r.stderr
            return json.loads(r.stdout)

        ts = str(1 << 62)
        b = ctl("--db", db, "--encryption-master-key", master_path,
                "backup", "--out", out_dir, "--backup-ts", ts)
        assert b["total_kvs"] > 0
        v = ctl("backup-verify", "--out", out_dir)
        assert v["total_kvs"] == b["total_kvs"]
        restored_db = str(tmp_path / "restored")
        master2 = str(tmp_path / "master2.key")
        with open(master2, "wb") as f:
            f.write(os.urandom(32))
        r = ctl("--db", restored_db, "--encryption-master-key", master2,
                "restore", "--out", out_dir, "--restore-ts", str((1 << 62) + 10))
        assert r.get("kvs", 0) == b["total_kvs"]
        # the restored dir is itself encrypted under ITS master key
        assert _scan_plaintext(restored_db) == []
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()
        pd_server.stop()


def test_merge_crash_sidecar_entry_fallback(tmp_path):
    """A compaction that crashed AFTER prepending a fresh sidecar entry but
    BEFORE renaming its output leaves the OLD ciphertext behind a new entry:
    the run reader must validate candidates and fall back to the old one."""
    _native_or_skip()
    import struct

    from tikv_tpu.native.engine import NativeEngine

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    eng = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    wb = WriteBatch()
    for i in range(500):
        wb.put_cf(CF_DEFAULT, b"m%04d" % i, SECRET + b"%d" % i)
    eng.write(wb)
    eng.checkpoint()
    eng.close()
    data_dir = tmp_path / "data"
    sidecars = [f for f in os.listdir(data_dir) if f.endswith(".enc")
                and f.startswith("run")]
    assert sidecars
    sp = data_dir / sidecars[0]
    old = sp.read_bytes()
    assert old[:4] == b"ENC1" and (len(old) - 4) % 16 == 0
    # simulate the crashed merge: prepend a fresh entry under the current key
    kid, _key = km.current()
    bogus = struct.pack("<I", kid) + os.urandom(12)
    sp.write_bytes(old[:4] + bogus + old[4:])

    eng2 = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    s = eng2.snapshot()
    assert s.get_cf(CF_DEFAULT, b"m0007") == SECRET + b"7"
    eng2.close()


def test_device_coprocessor_over_encrypted_engine(tmp_path):
    """Cross-feature: the device coprocessor path serves byte-identically
    over an encrypted native engine (MVCC decode reads through the
    decrypting run/WAL readers), and the files still hold no plaintext."""
    _native_or_skip()
    import numpy as np

    from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.rpn import call, col, const_int
    from tikv_tpu.copr.table import encode_row, record_key, record_range
    from tikv_tpu.native.engine import NativeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    km = DataKeyManager.open(MasterKey.mem(), str(tmp_path / "keys.dict"))
    eng = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    cols_info = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
    ]
    rng = np.random.default_rng(4)
    tid = 77
    wbatch = []
    for i in range(5000):
        rk = record_key(tid, i)
        row = encode_row(cols_info[1:], [int(rng.integers(0, 1000))])
        wbatch.append((Key.from_raw(rk).append_ts(20).encoded,
                       Write(WriteType.PUT, 10, short_value=row).to_bytes()))
    eng.bulk_load(CF_WRITE, wbatch)
    eng.checkpoint()  # rows land in encrypted runs

    dag = DagRequest(executors=[
        TableScan(tid, cols_info),
        Selection([call("lt", col(1), const_int(700))]),
        Aggregation(group_by=[], agg_funcs=[
            AggDescriptor("sum", col(1)), AggDescriptor("count", None)]),
    ])
    mk = lambda: CoprRequest(103, dag, [record_range(tid)], 100)
    ep_dev = Endpoint(LocalEngine(eng), enable_device=True)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    r_dev = ep_dev.handle_request(mk())
    r_cpu = ep_cpu.handle_request(mk())
    assert r_dev.from_device and ep_dev.device_fallbacks == 0, ep_dev.last_device_error
    assert r_dev.data == r_cpu.data
    eng.close()
    # re-write with the canary and prove value bytes never hit disk plain
    eng2 = NativeEngine(str(tmp_path / "data"), keys_mgr=km)
    from tikv_tpu.storage.engine import CF_DEFAULT, WriteBatch

    wb = WriteBatch()
    wb.put_cf(CF_DEFAULT, b"canary", SECRET)
    eng2.write(wb)
    eng2.checkpoint()
    eng2.close()
    assert _scan_plaintext(str(tmp_path / "data")) == []
