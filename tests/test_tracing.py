"""End-to-end distributed tracing plane (ISSUE 11, docs/tracing.md).

* span-tree mechanics: nesting, explicit pool handoff, cross-thread finish,
  head sampling + tail promotion, the rate-0 no-op fast path;
* wire propagation: one trace from the client frame through forwarded hops,
  with decode/route/execute/encode stage spans accounting for >=90% of the
  root;
* THE acceptance scenario: a coprocessor request to the WRONG store
  (device-owner hop) yields ONE trace with wire, ladder, queue, and device
  spans across two stores;
* chaos: a seeded Nemesis leader isolation mid-traffic yields ONE trace
  whose spans cover >=2 stores (forward rung + retry joined, never a fresh
  trace per hop);
* fan-in: every coalesced rider links to the shared device-dispatch span;
* write path: slow-log parity with latch/propose/apply phases + trace ids,
  and the raft propose->apply span finished by the apply callback;
* log<->trace correlation through util.logger + diagnostics.search_log.
"""

import logging
import threading
import time

import pytest

from copr_fixtures import TABLE_ID as PRODUCT_TABLE  # noqa: F401 (path setup)
from tikv_tpu.copr.dag import (
    AggDescriptor,
    Aggregation,
    DagRequest,
    Selection,
    TableScan,
)
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rpn import call as rpn_call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.cluster import Cluster
from tikv_tpu.raft.raftkv import RaftKv
from tikv_tpu.server.read_plane import ReadPlane
from tikv_tpu.server.server import Client, Server
from tikv_tpu.server.service import KvService
from tikv_tpu.sidecar.resolved_ts import ResolvedTsEndpoint
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util import trace
from tikv_tpu.util.chaos import Nemesis

FIRST_REGION_ID = 1
TABLE_ID = 81

COLS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),
    ColumnInfo(3, FieldType.int64()),
]


@pytest.fixture(autouse=True)
def _tracer_isolation():
    old_rate = trace.sample_rate()
    old_slow = trace.slow_threshold()
    trace.TRACER.reset()
    trace.set_sample_rate(1.0)
    trace.set_slow_threshold(0.3)
    yield
    trace.set_sample_rate(old_rate)
    trace.set_slow_threshold(old_slow)
    trace.TRACER.reset()


def _engine(n: int) -> BTreeEngine:
    eng = BTreeEngine()
    items = []
    for i in range(n):
        rk = record_key(TABLE_ID, i)
        val = encode_row(COLS[1:], [i % 50, i])
        items.append((Key.from_raw(rk).append_ts(20).encoded,
                      Write(WriteType.PUT, 10, short_value=val).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    return eng


def _agg_dag(cut: int) -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([rpn_call("lt", col(1), const_int(cut))]),
        Aggregation([], [AggDescriptor("sum", col(2)),
                         AggDescriptor("count", None)]),
    ])


def _wait_for(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _spans_named(t: dict, name: str) -> list:
    return [s for s in t["spans"] if s["name"] == name]


# ---------------------------------------------------------------------------
# span-tree mechanics
# ---------------------------------------------------------------------------

def test_span_nesting_ids_and_ring_commit():
    with trace.start_trace("root", kind="test") as root:
        tid = root.rec.trace_id
        with trace.span("child") as c1:
            assert c1.parent_id == root.span_id
            with trace.span("grandchild") as c2:
                assert c2.parent_id == c1.span_id
    t = trace.TRACER.get(tid)
    assert t is not None and t["sampled"] and not t["promoted"]
    names = [s["name"] for s in t["spans"]]
    assert names.count("root") == 1
    assert set(names) == {"root", "child", "grandchild"}
    # parentage is reconstructible (the timeline renders a tree)
    text = trace.timeline(t)
    assert "root" in text and "    " in text


def test_explicit_handoff_and_cross_thread_finish():
    with trace.start_trace("root") as root:
        tid = root.rec.trace_id
        ctx = trace.current_context()
        assert ctx["trace_id"] == tid and ctx["sampled"]

        done = threading.Event()

        def worker():
            # pool-boundary handoff: attach, then nest
            with trace.attach(ctx):
                with trace.span("worker.step"):
                    pass
            done.set()

        th = threading.Thread(target=worker)
        th.start()
        done.wait(5)
        th.join(5)
        # cross-thread finish of a begin() handle (the raft-callback shape)
        h = trace.begin("late.handle")
        fin = threading.Thread(target=h.finish)
        fin.start()
        fin.join(5)
        # dispatcher-side remote span lands in this trace without touching
        # the worker's current stack
        trace.remote_span(ctx, "remote.step", start=0.0, end=0.001, k="v")
    t = trace.TRACER.get(tid)
    names = {s["name"] for s in t["spans"]}
    assert {"worker.step", "late.handle", "remote.step"} <= names
    ws = _spans_named(t, "worker.step")[0]
    assert ws["parent_id"] == ctx["span_id"]


def test_sampling_off_is_noop_and_costs_nothing():
    trace.set_sample_rate(0.0)
    assert not trace.enabled()
    sp = trace.start_trace("x")
    assert sp is trace.NOOP and not sp
    with trace.span("y") as s:
        assert s is trace.NOOP
    assert trace.current_trace_id() is None
    snap = trace.snapshot()
    assert snap["recent"] == [] and snap["slow"] == [] and snap["live"] == 0


def test_head_drop_and_tail_promotion():
    class _FixedRng:
        def random(self):
            return 0.99  # always above the rate: head says DROP

    trace.TRACER._rng = _FixedRng()
    trace.set_sample_rate(0.5)
    # fast trace: head-dropped, not slow -> vanishes
    with trace.start_trace("fast") as sp:
        tid_fast = sp.rec.trace_id
        assert not sp.rec.sampled
    assert trace.TRACER.get(tid_fast) is None
    # slow trace: head-dropped but crosses the threshold -> PROMOTED
    trace.set_slow_threshold(0.0)
    with trace.start_trace("slow") as sp:
        tid_slow = sp.rec.trace_id
        with trace.span("inner"):
            pass
    t = trace.TRACER.get(tid_slow)
    assert t is not None and t["promoted"] and t["slow"] and not t["sampled"]
    assert {"slow", "inner"} <= {s["name"] for s in t["spans"]}
    snap = trace.snapshot()
    assert any(x["trace_id"] == tid_slow for x in snap["slow"])
    assert not any(x["trace_id"] == tid_slow for x in snap["recent"])


def test_promoted_trace_keeps_cross_thread_spans():
    """Tail promotion exists to keep the phases where an UNSAMPLED slow
    request actually spent its time — attach/remote_span must record into
    head-dropped live traces (regression: they used to gate on sampled,
    leaving promoted traces without their worker-side spans)."""
    class _FixedRng:
        def random(self):
            return 0.99  # head says DROP

    trace.TRACER._rng = _FixedRng()
    trace.set_sample_rate(0.5)
    trace.set_slow_threshold(0.0)  # everything promotes
    with trace.start_trace("slow.write") as root:
        assert not root.rec.sampled
        tid = root.rec.trace_id
        ctx = trace.current_context()
        assert ctx["sampled"] is False

        def worker():
            with trace.attach(ctx):
                with trace.span("txn.process_write"):
                    pass

        th = threading.Thread(target=worker)
        th.start()
        th.join(5)
        trace.remote_span(ctx, "sched.batched", start=0.0, end=0.001)
    t = trace.TRACER.get(tid)
    assert t is not None and t["promoted"]
    names = {s["name"] for s in t["spans"]}
    assert {"txn.process_write", "sched.batched"} <= names, names


def test_span_cap_truncates_not_balloons():
    with trace.start_trace("root") as root:
        tid = root.rec.trace_id
        for _ in range(trace.MAX_SPANS + 40):
            with trace.span("s"):
                pass
    t = trace.TRACER.get(tid)
    assert len(t["spans"]) <= trace.MAX_SPANS
    assert t["truncated"] >= 40


# ---------------------------------------------------------------------------
# wire propagation over real sockets
# ---------------------------------------------------------------------------

def test_rpc_stage_spans_cover_root():
    storage = Storage()
    svc = KvService(storage, Endpoint(storage.engine))
    srv = Server(svc)
    srv.start()
    c = Client(*srv.addr)
    try:
        c.call("kv_get", {"key": b"x", "version": 10, "context": {}})
    finally:
        c.close()
        srv.stop()
    _wait_for(lambda: trace.snapshot()["recent"], msg="rpc trace commit")
    t = trace.snapshot()["recent"][-1]
    root = [s for s in t["spans"]
            if s["parent_id"] is None and s["name"] == "rpc.kv_get"]
    assert root, "rpc root span missing"
    kids = [s for s in t["spans"] if s["parent_id"] == root[0]["span_id"]]
    stages = {s["name"] for s in kids}
    assert {"wire.decode", "wire.route", "wire.execute",
            "wire.encode"} <= stages
    covered = sum(s["duration_ms"] for s in kids)
    total = root[0]["duration_ms"]
    # the stages tile the root; on a sub-millisecond request a scheduler
    # hiccup between two lock acquisitions can exceed 10% of the total, so
    # accept either the ratio or a small absolute gap
    assert covered >= 0.9 * total or total - covered <= 1.5, \
        f"stage spans cover only {covered:.3f} of {total:.3f}ms"


def test_acceptance_owner_forward_one_trace_wire_ladder_queue_device():
    """THE acceptance scenario: a device-eligible DAG sent to the WRONG
    store hops to the device owner; ONE trace carries wire, ladder, queue,
    and device spans across both stores, and the root's direct children
    account for >=90% of it."""
    eng = _engine(1200)
    # store 2: device owner, continuous scheduler (queue lanes)
    ep_b = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256)
    rp_b = ReadPlane()
    rp_b.store_id = 2
    svc_b = KvService(Storage(engine=LocalEngine(eng)), ep_b, read_plane=rp_b)
    srv_b = Server(svc_b)
    srv_b.start()
    ep_b.scheduler.start()
    # store 1: no device; PD named store 2 the warm owner of region 1
    rp_a = ReadPlane(resolver=lambda sid: srv_b.addr if sid == 2 else None)
    rp_a.store_id = 1
    rp_a.set_device_owners({FIRST_REGION_ID: 2})
    ep_a = Endpoint(LocalEngine(eng), enable_device=False)
    svc_a = KvService(Storage(engine=LocalEngine(eng)), ep_a, read_plane=rp_a)
    srv_a = Server(svc_a)
    srv_a.start()

    from tikv_tpu.copr.dag_wire import dag_to_wire

    lo, hi = record_key(TABLE_ID, 0), record_key(TABLE_ID, 1200)
    req = {"dag": dag_to_wire(_agg_dag(30)), "ranges": [[lo, hi]],
           "start_ts": 100,
           "context": {"region_id": FIRST_REGION_ID,
                       "region_epoch": (1, 1), "apply_index": 7}}
    c = Client(*srv_a.addr)
    try:
        r = c.call("coprocessor", req, timeout=120.0)
        assert not r.get("error") and r["from_device"], r
    finally:
        c.close()
        srv_a.stop()
        ep_b.scheduler.stop()
        srv_b.stop()
        rp_a.close()

    def traced():
        return [t for t in trace.snapshot(limit=50)["recent"]
                if _spans_named(t, "ladder.owner_forward")]

    _wait_for(lambda: traced(), msg="owner-forward trace commit")
    ts = traced()
    assert len(ts) == 1, "the hop must JOIN the trace, not mint a new one"
    t = ts[0]
    names = [s["name"] for s in t["spans"]]
    # wire spans from BOTH stores in the one trace
    assert names.count("rpc.coprocessor") == 2
    stores = {s["tags"].get("store") for s in t["spans"]
              if s["name"] == "rpc.coprocessor"}
    assert stores == {1, 2}, f"expected both stores' rpc spans, got {stores}"
    # ladder + queue + device spans
    fwd = _spans_named(t, "ladder.owner_forward")[0]
    assert fwd["tags"]["outcome"] == "ok" and fwd["tags"]["target_store"] == 2
    assert _spans_named(t, "sched.queue"), "queue-lane span missing"
    assert _spans_named(t, "device.run"), "device span missing"
    assert _spans_named(t, "copr.handle")[0]["tags"]["from_device"] is True
    # >=90% of the root accounted by its direct children
    root = [s for s in t["spans"] if s["parent_id"] is None
            and s["name"] == "rpc.coprocessor"]
    assert len(root) == 1
    kids = [s for s in t["spans"] if s["parent_id"] == root[0]["span_id"]]
    cov = sum(s["duration_ms"] for s in kids) / root[0]["duration_ms"]
    assert cov >= 0.9, f"child spans cover only {cov:.0%} of the root"


# ---------------------------------------------------------------------------
# chaos: trace propagation through a seeded leader isolation
# ---------------------------------------------------------------------------

def _commit_kv(pd, storage, ctx, key, value):
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Mutation

    ts = pd.get_tso()
    storage.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(key), value)], key, ts), ctx)
    cts = pd.get_tso()
    storage.sched_txn_command(Commit([Key.from_raw(key)], ts, cts), ctx)
    return cts


def test_chaos_leader_isolation_one_trace_spans_two_stores():
    """Seeded Nemesis isolates the leader mid-traffic: the client keeps ONE
    trace open across its retries — the pre-isolation forwarded read joins
    the leader's spans, the mid-isolation retry degrades to a follower
    stale serve — and every hop's spans land in that one trace (never a
    fresh trace per hop)."""
    pd = MockPd()
    c = Cluster(3, pd=pd)
    c.run()
    rts = ResolvedTsEndpoint(pd)
    for s in c.stores.values():
        rts.attach_store(s)
    leader = c.wait_leader(FIRST_REGION_ID)
    leader_sid = leader.store.store_id
    storage = Storage(engine=c.raftkv(leader_sid))
    _commit_kv(pd, storage, {"region_id": FIRST_REGION_ID}, b"rk", b"rv")
    w = rts.advance_all()[FIRST_REGION_ID]

    isolated: set = set()
    svcs: dict = {}

    def rpc_send(sid, method, req, timeout):
        # the injected wire: a partitioned store is unreachable, a healthy
        # one serves through the same trace-joining RPC shape server.py uses
        if sid in isolated:
            raise ConnectionError(f"store {sid} partitioned")
        return call_store(sid, method, req)

    def call_store(sid, method, req):
        root = trace.start_trace(f"rpc.{method}",
                                 ctx=(req.get("context") or None),
                                 method=method, store=sid)
        try:
            with root.active():
                return svcs[sid].dispatch(method, req)
        finally:
            root.finish()

    for sid, st in c.stores.items():
        plane = ReadPlane(store=st, resolved_ts=rts, send=rpc_send)
        kv = RaftKv(st, pump=c.process, resolved_ts=rts)
        svcs[sid] = KvService(Storage(engine=kv), raft_router=st,
                              resolved_ts=rts, read_plane=plane)

    fol = next(s for s in c.stores if s != leader_sid)
    nem = Nemesis(c, seed=20260804)
    client_root = trace.start_trace("client.read", store="client")
    tid = client_root.rec.trace_id
    try:
        with client_root.active():
            ctx = {"region_id": FIRST_REGION_ID, "stale_fallback": True}
            trace.inject(ctx)
            # pre-isolation: fresh read on the follower forwards one hop
            r = call_store(fol, "kv_get",
                           {"key": b"rk", "version": w, "context": dict(ctx)})
            assert r.get("error") is None and r["value"] == b"rv", r
            # mid-traffic leader isolation (seeded, deterministic)
            isolated.add(leader_sid)
            nem.isolate(leader_sid)
            for _ in range(5):
                c.tick()
            # the retry re-injects the SAME trace: forward fails, the
            # ladder degrades to a follower stale serve at the watermark
            r = call_store(fol, "kv_get",
                           {"key": b"rk", "version": w, "context": dict(ctx)})
            assert r.get("error") is None and r["value"] == b"rv", r
    finally:
        client_root.finish()
        isolated.clear()
        nem.heal()
        nem.close()

    t = trace.TRACER.get(tid)
    assert t is not None, "client trace never committed"
    # ONE trace, spans from >=2 stores
    stores = {s["tags"].get("store") for s in t["spans"]
              if "store" in s["tags"]} - {"client"}
    assert len(stores) >= 2, f"trace covers only stores {stores}"
    assert leader_sid in stores and fol in stores
    # forward rung (pre-isolation, served) + stale rung (mid-isolation)
    fwd = _spans_named(t, "ladder.forward")
    assert any(s["tags"].get("outcome") == "ok" for s in fwd)
    stale = _spans_named(t, "ladder.stale_serve")
    assert any(s["tags"].get("outcome") == "served" for s in stale)
    # never a fresh trace per hop: every rpc span of the exercise is HERE
    assert len(_spans_named(t, "rpc.kv_get")) >= 3  # 2 client calls + 1 hop
    others = [x for x in trace.snapshot(limit=50)["recent"]
              if x["trace_id"] != tid and _spans_named(x, "rpc.kv_get")]
    assert not others, "a hop minted its own trace instead of joining"


# ---------------------------------------------------------------------------
# fan-in: coalesced riders link to the shared dispatch span
# ---------------------------------------------------------------------------

def test_batch_fanin_links_every_rider():
    eng = _engine(2400)
    dev = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256)
    rows_per = 600

    def region_req(r):
        lo = record_key(TABLE_ID, r * rows_per)
        hi = record_key(TABLE_ID, (r + 1) * rows_per)
        return CoprRequest(103, _agg_dag(40), [(lo, hi)], 100,
                           context={"region_id": r + 1,
                                    "region_epoch": (1, 1), "apply_index": 7})

    # warm: fill the region images + compile outside the traced window
    dev.handle_batch([region_req(r) for r in range(4)])
    dev.scheduler.start()
    try:
        barrier = threading.Barrier(4)
        tids: list = [None] * 4
        errs: list = []

        def worker(i):
            try:
                root = trace.start_trace(f"client.{i}", store=f"client{i}")
                tids[i] = root.rec.trace_id
                with root.active():
                    barrier.wait(5)
                    dev.scheduler.execute(region_req(i))
                root.finish()
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for th in ts:
            th.start()
        for th in ts:
            th.join(30)
        assert not errs, errs
    finally:
        dev.scheduler.stop()

    recent = trace.snapshot(limit=50)["recent"]
    dispatches = [t for t in recent
                  if _spans_named(t, "sched.device_dispatch")]
    assert dispatches, "no shared device-dispatch trace recorded"
    # riders that were actually served out of a shared batch
    linked = 0
    for tid in tids:
        t = trace.TRACER.get(tid)
        assert t is not None
        queue = _spans_named(t, "sched.queue")
        assert queue, "rider lost its queue-lane span"
        if queue[0]["tags"].get("outcome") != "batched":
            continue  # underfull/direct riders carry no link — honest
        linked += 1
        ref = queue[0]["tags"]["batched_into"]
        batched = _spans_named(t, "sched.batched")
        assert batched and batched[0]["tags"]["batched_into"] == ref
        # the link resolves to a real dispatch trace naming this rider
        dtid, dsid = ref.split(":")
        dt = next((x for x in dispatches if x["trace_id"] == dtid), None)
        assert dt is not None, "batched_into names an unknown dispatch trace"
        dsp = _spans_named(dt, "sched.device_dispatch")[0]
        assert dsp["span_id"] == dsid
        assert tid in dsp["tags"]["participants"]
    assert linked >= 2, "expected at least one shared batch among 4 riders"
    # device spans nest under the dispatch trace (launch + pull)
    dt = next(x for x in dispatches
              if _spans_named(x, "sched.device_dispatch")[0]["tags"]
              .get("outcome") == "ok")
    assert _spans_named(dt, "device.launch") and _spans_named(dt, "device.pull")


# ---------------------------------------------------------------------------
# write path: slow-log parity + propose->apply span
# ---------------------------------------------------------------------------

def test_txn_slow_log_records_phases_and_trace_id():
    storage = Storage()
    storage.scheduler.slow_log.threshold_s = 0.0  # record every command
    with trace.start_trace("client.write") as root:
        tid = root.rec.trace_id
        _commit_kv(MockPd(), storage, None, b"wk", b"wv")
    entries = storage.scheduler.slow_log.tail(10)
    tags = [e["tag"] for e in entries]
    assert "txn Prewrite" in tags and "txn Commit" in tags
    for e in entries:
        assert e["trace_id"] == tid
        for k in ("latch_wait_ms", "process_ms", "propose_apply_ms",
                  "total_ms", "group_size", "status"):
            assert k in e, f"{k} missing from write slow-log entry"
        assert e["status"] == "done"
    # the worker-side spans landed in the submitting request's trace
    t = trace.TRACER.get(tid)
    names = {s["name"] for s in t["spans"]}
    assert {"txn.latch_wait", "txn.process_write"} <= names


def test_raft_propose_apply_span_finishes_via_callback():
    pd = MockPd()
    c = Cluster(1, pd=pd)
    c.run()
    try:
        leader = c.wait_leader(FIRST_REGION_ID)
        storage = Storage(engine=c.raftkv(leader.store.store_id))
        with trace.start_trace("client.write") as root:
            tid = root.rec.trace_id
            _commit_kv(pd, storage, {"region_id": FIRST_REGION_ID},
                       b"rk2", b"rv2")
    finally:
        pass  # in-memory Cluster needs no teardown (no threads of its own)
    t = trace.TRACER.get(tid)
    spans = _spans_named(t, "raft.propose_apply")
    assert spans, "propose->apply span missing from the write trace"
    for s in spans:
        assert s["duration_ms"] >= 0 and "error" not in s["tags"]
        assert s["tags"]["region"] == FIRST_REGION_ID


def test_copr_slow_log_gains_trace_ids():
    eng = _engine(600)
    ep = Endpoint(LocalEngine(eng), enable_device=False)
    ep.slow_log.threshold_s = 0.0
    lo, hi = record_key(TABLE_ID, 0), record_key(TABLE_ID, 600)
    with trace.start_trace("client.copr") as root:
        tid = root.rec.trace_id
        ep.handle_request(CoprRequest(103, _agg_dag(25), [(lo, hi)], 100,
                                      context={"region_id": 1}))
    entry = ep.slow_log.tail(1)[0]
    assert entry["trace_id"] == tid


# ---------------------------------------------------------------------------
# log<->trace correlation
# ---------------------------------------------------------------------------

def test_logger_attaches_trace_id_and_search_log_pivots(tmp_path):
    from tikv_tpu.server.diagnostics import Diagnostics
    from tikv_tpu.util.logger import _Formatter, get_logger

    log_path = tmp_path / "store.log"
    handler = logging.FileHandler(log_path)
    handler.setFormatter(_Formatter())
    pylog = logging.getLogger("tikv_tpu.tracetest")
    pylog.addHandler(handler)
    pylog.setLevel(logging.INFO)
    try:
        log = get_logger("tracetest")
        with trace.start_trace("client.op") as root:
            tid = root.rec.trace_id
            log.info("applying delta", region=7)
        log.info("outside any span", region=8)
    finally:
        handler.close()
        pylog.removeHandler(handler)
    text = log_path.read_text()
    assert f"[trace_id={tid}]" in text
    # exactly the in-span line carries the id; search_log pivots on it
    hits = Diagnostics(log_path=str(log_path)).search_log(patterns=[tid])
    assert len(hits) == 1 and "applying delta" in hits[0]["message"]
    assert "region=7" in hits[0]["message"]


# ---------------------------------------------------------------------------
# ops surfaces: RPC, HTTP, online config
# ---------------------------------------------------------------------------

def test_debug_traces_rpc_and_status_route_and_online_rate():
    import json
    import urllib.request

    from tikv_tpu.server.status_server import StatusServer
    from tikv_tpu.util.config import ConfigController, TikvConfig, TraceConfig

    storage = Storage()
    svc = KvService(storage, Endpoint(storage.engine))
    srv = Server(svc)
    srv.start()
    cl = Client(*srv.addr)
    try:
        cl.call("kv_get", {"key": b"k", "version": 5, "context": {}})
        _wait_for(lambda: trace.snapshot()["recent"], msg="trace commit")
        # RPC: list then show
        snap = cl.call("debug_traces", {"limit": 5})
        assert snap["sample_rate"] == 1.0 and snap["recent"]
        tid = snap["recent"][-1]["trace_id"]
        one = cl.call("debug_traces", {"trace_id": tid})
        assert one["trace"]["trace_id"] == tid
        assert "rpc.kv_get" in one["timeline"]
        missing = cl.call("debug_traces", {"trace_id": "nope"})
        assert missing.get("error")
    finally:
        cl.close()
        srv.stop()

    # HTTP: timeline text, JSON form, one-trace form + the online rate knob
    controller = ConfigController(TikvConfig(
        trace=TraceConfig(sample_rate=trace.sample_rate(),
                          slow_threshold_s=trace.slow_threshold())))
    controller.register(
        "trace",
        lambda changed: (
            trace.set_sample_rate(changed["sample_rate"])
            if "sample_rate" in changed else None,
            trace.set_slow_threshold(changed["slow_threshold_s"])
            if "slow_threshold_s" in changed else None,
        ),
    )
    ss = StatusServer(controller=controller)
    ss.start()
    base = f"http://{ss.addr[0]}:{ss.addr[1]}"
    try:
        text = urllib.request.urlopen(base + "/debug/traces").read().decode()
        assert "sample_rate=1.0" in text and "rpc.kv_get" in text
        j = json.loads(urllib.request.urlopen(
            base + "/debug/traces?format=json&limit=3").read())
        assert j["recent"] and j["sample_rate"] == 1.0
        one = urllib.request.urlopen(
            base + f"/debug/traces?trace_id={tid}").read().decode()
        assert "rpc.kv_get" in one
        # the ctl.py `trace set-sample-rate` path: POST /config trace.*
        req = urllib.request.Request(
            base + "/config",
            data=json.dumps({"trace.sample_rate": 0.25}).encode(),
            method="POST")
        diff = json.loads(urllib.request.urlopen(req).read())
        assert diff == {"trace": {"sample_rate": 0.25}}
        assert trace.sample_rate() == 0.25
        # validation rejects a bad rate and changes nothing
        req = urllib.request.Request(
            base + "/config",
            data=json.dumps({"trace.sample_rate": 7}).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req)
        assert trace.sample_rate() == 0.25
    finally:
        ss.stop()


def test_trace_metrics_series_move():
    from tikv_tpu.util.metrics import REGISTRY

    c = REGISTRY.counter("tikv_trace_total")
    before = c.get(outcome="sampled")
    with trace.start_trace("m"):
        pass
    assert c.get(outcome="sampled") == before + 1
    g = REGISTRY.gauge("tikv_trace_ring_traces")
    assert g.get(ring="recent") >= 1
