"""coprocessor_v2 plugins, encryption at rest, resource metering."""

import pytest

from tikv_tpu.copr.plugin import (
    CoprocessorPlugin,
    CoprV2Endpoint,
    PluginError,
    PluginRegistry,
    RawStorage,
)
from tikv_tpu.server.resource_metering import Reporter, ResourceTagFactory
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.encryption import (
    DataKeyManager,
    EncryptedEngine,
    MasterKey,
    seal,
    unseal,
)
from tikv_tpu.storage.storage import Storage


# -- plugins -----------------------------------------------------------------

class CounterPlugin(CoprocessorPlugin):
    NAME = "counter"
    VERSION = (1, 2, 0)

    def on_raw_coprocessor_request(self, ranges, request, storage: RawStorage) -> bytes:
        total = 0
        for start, end in ranges:
            total += len(storage.scan(start, end))
        return b"%d" % total


class IncrPlugin(CoprocessorPlugin):
    NAME = "incr"
    VERSION = (0, 1, 0)

    def on_raw_coprocessor_request(self, ranges, request, storage: RawStorage) -> bytes:
        cur = storage.get(request)
        n = int(cur or b"0") + 1
        storage.put(request, b"%d" % n)
        return b"%d" % n


def test_plugin_registry_and_dispatch():
    store = Storage()
    for i in range(5):
        store.raw_put(b"pk%d" % i, b"v")
    ep = CoprV2Endpoint(store)
    ep.registry.register(CounterPlugin())
    ep.registry.register(IncrPlugin())
    r = ep.handle_request({"copr_name": "counter", "ranges": [[b"pk", b"pk\xff"]], "data": b""})
    assert r == {"data": b"5"}
    # read-write plugin round trips through RawStorage
    assert ep.handle_request({"copr_name": "incr", "data": b"ctr"})["data"] == b"1"
    assert ep.handle_request({"copr_name": "incr", "data": b"ctr"})["data"] == b"2"
    assert store.raw_get(b"ctr") == b"2"


def test_plugin_version_requirements():
    reg = PluginRegistry()
    reg.register(CounterPlugin())
    assert reg.get("counter", "1").NAME == "counter"
    assert reg.get("counter", "1.2").NAME == "counter"
    with pytest.raises(PluginError):
        reg.get("counter", "2")
    with pytest.raises(PluginError):
        reg.get("counter", "1.3")
    with pytest.raises(PluginError):
        reg.get("nope")
    assert reg.list_plugins() == {"counter": (1, 2, 0)}


def test_plugin_dir_hot_reload(tmp_path):
    plug = tmp_path / "hello.py"
    plug.write_text(
        "from tikv_tpu.copr.plugin import CoprocessorPlugin\n"
        "class P(CoprocessorPlugin):\n"
        "    NAME = 'hello'\n"
        "    VERSION = (1, 0, 0)\n"
        "    def on_raw_coprocessor_request(self, ranges, request, storage):\n"
        "        return b'hi ' + request\n"
        "PLUGIN = P()\n"
    )
    reg = PluginRegistry(plugin_dir=str(tmp_path))
    ep = CoprV2Endpoint(Storage(), reg)
    r = ep.handle_request({"copr_name": "hello", "data": b"world"})
    assert r == {"data": b"hi world"}
    # hot reload on change
    import os, time

    plug.write_text(plug.read_text().replace(b"'hi '".decode(), "'HI '"))
    os.utime(plug, (time.time() + 5, time.time() + 5))
    r = ep.handle_request({"copr_name": "hello", "data": b"world"})
    assert r == {"data": b"HI world"}


def test_plugin_fault_contained():
    class Boom(CoprocessorPlugin):
        NAME = "boom"
        VERSION = (1, 0, 0)

        def on_raw_coprocessor_request(self, ranges, request, storage):
            raise RuntimeError("kaput")

    ep = CoprV2Endpoint(Storage())
    ep.registry.register(Boom())
    r = ep.handle_request({"copr_name": "boom"})
    assert "plugin error" in r["error"]["other"]


# -- encryption --------------------------------------------------------------

def test_seal_unseal_roundtrip_and_tamper():
    key = b"k" * 32
    for msg in [b"", b"x", b"hello world" * 100]:
        blob = seal(key, msg)
        assert unseal(key, blob) == msg
        # actually encrypted (skip tiny msgs: a 1-byte needle matches a
        # random nonce/tag byte with ~10% probability)
        assert len(msg) < 4 or msg not in blob
    blob = bytearray(seal(key, b"secret"))
    blob[20] ^= 1
    with pytest.raises(ValueError, match="mismatch"):
        unseal(key, bytes(blob))
    with pytest.raises(ValueError, match="mismatch"):
        unseal(b"wrong-key-wrong-key-wrong-key!!!", seal(key, b"secret"))


def test_data_key_rotation_and_dict_export():
    master = MasterKey.mem()
    mgr = DataKeyManager(master)
    id1, k1 = mgr.current()
    mgr.rotate()
    id2, k2 = mgr.current()
    assert id2 == id1 + 1 and k1 != k2
    sealed = mgr.export_dict()
    mgr2 = DataKeyManager.import_dict(master, sealed)
    assert mgr2.current() == (id2, k2)
    assert mgr2.by_id(id1) == k1
    with pytest.raises(ValueError):
        DataKeyManager.import_dict(MasterKey.mem(b"other-master-key-1234"), sealed)


def test_encrypted_engine_full_stack():
    """Values are ciphertext at rest; the whole txn stack works unchanged."""
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    inner = BTreeEngine()
    eng = EncryptedEngine(inner, DataKeyManager(MasterKey.mem()))
    store = Storage(engine=LocalEngine(eng))
    r = store.sched_txn_command(
        Prewrite([Mutation.put(Key.from_raw(b"secret-key"), b"secret-value")], b"secret-key", 10)
    )
    assert "errors" not in r
    store.sched_txn_command(Commit([Key.from_raw(b"secret-key")], 10, 20))
    assert store.get(b"secret-key", 30) == b"secret-value"
    # at rest: no plaintext value anywhere in the inner engine
    for cf in ("default", "lock", "write"):
        for k, v in inner.scan_cf(cf, b"", None):
            assert b"secret-value" not in v
    # key rotation: old data still readable, new data under the new key
    eng.keys.rotate()
    store.raw_put(b"r1", b"post-rotation")
    assert store.raw_get(b"r1") == b"post-rotation"
    assert store.get(b"secret-key", 30) == b"secret-value"


# -- resource metering -------------------------------------------------------

def test_resource_metering_attribution():
    tags = ResourceTagFactory()
    with tags.attach(b"group-a"):
        sum(i * i for i in range(200_000))
    with tags.attach(b"group-b"):
        pass
    with tags.attach(b"group-a"):
        pass
    snap = tags.snapshot()
    assert snap[b"group-a"]["ops"] == 2
    assert snap[b"group-b"]["ops"] == 1
    assert snap[b"group-a"]["cpu_secs"] > snap[b"group-b"]["cpu_secs"]
    rep = Reporter(tags, top_n=1, interval=999)
    report = rep.tick()
    assert list(report["top"]) == [b"group-a"]
    assert report["groups"] == 2
    # window reset: next tick is empty
    assert rep.tick()["groups"] == 0


def test_raw_coprocessor_and_metering_over_tcp():
    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService

    store = Storage()
    store.raw_put(b"x1", b"v")
    store.raw_put(b"x2", b"v")
    v2 = CoprV2Endpoint(store)
    v2.registry.register(CounterPlugin())
    tags = ResourceTagFactory()
    svc = KvService(store, None, copr_v2=v2, resource_tags=tags)
    server = Server(svc)
    server.start()
    try:
        c = Client(*server.addr)
        r = c.call("raw_coprocessor", {"copr_name": "counter", "ranges": [[b"x", b"y"]],
                                       "data": b"", "context": {"resource_group": b"analytics"}})
        assert r == {"data": b"2"}
        r = c.call("raw_coprocessor", {"copr_name": "missing", "context": {}})
        assert "no such plugin" in r["error"]["other"]
        snap = tags.snapshot()
        assert snap[b"analytics"]["ops"] == 1
        assert snap[b"default"]["ops"] == 1
        c.close()
    finally:
        server.stop()


def test_aes_gcm_is_the_active_cipher():
    """With the cryptography package present the sealed format must be real
    AES-256-GCM, not the fallback keystream."""
    from tikv_tpu.storage import encryption as enc

    assert enc.AESGCM is not None
    blob = seal(b"k" * 32, b"payload")
    assert blob[0] == enc._METHOD_AESGCM
    # independently decryptable with the library primitive
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    assert AESGCM(b"k" * 32).decrypt(blob[1:13], blob[13:], None) == b"payload"


def test_master_key_rotation_keeps_old_data_readable(tmp_path):
    """master_key/file.rs semantics: rotating the MASTER key re-seals only
    the key dictionary; values written under old data keys decrypt fine."""
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import WriteBatch

    dict_path = str(tmp_path / "file.dict")
    mgr = DataKeyManager.open(MasterKey.mem(), dict_path)
    eng = EncryptedEngine(BTreeEngine(), mgr)
    wb = WriteBatch()
    wb.put_cf("default", b"old-key", b"written-under-data-key-1")
    eng.write(wb)
    mgr.rotate()  # new data key for new writes
    wb = WriteBatch()
    wb.put_cf("default", b"new-key", b"written-under-data-key-2")
    eng.write(wb)
    new_master = MasterKey.mem(b"rotated-master-key-9999")
    mgr.rotate_master(new_master)
    # a fresh process opening with the NEW master reads everything
    mgr2 = DataKeyManager.open(new_master, dict_path)
    eng2 = EncryptedEngine(eng.inner, mgr2)
    assert eng2.get_cf("default", b"old-key") == b"written-under-data-key-1"
    assert eng2.get_cf("default", b"new-key") == b"written-under-data-key-2"
    # the OLD master no longer opens the dictionary
    with pytest.raises(ValueError):
        DataKeyManager.open(MasterKey.mem(), dict_path)


def test_dict_persistence_atomic_and_recoverable(tmp_path):
    dict_path = str(tmp_path / "file.dict")
    mgr = DataKeyManager.open(MasterKey.mem(), dict_path)
    ids = [mgr.rotate() for _ in range(3)]
    mgr2 = DataKeyManager.open(MasterKey.mem(), dict_path)
    assert mgr2.current_id == ids[-1]
    assert mgr2.keys == mgr.keys
    # values sealed before the reload decrypt after it
    blob = seal(mgr.current()[1], b"v")
    assert unseal(mgr2.by_id(mgr2.current_id), blob) == b"v"


def test_thread_cpu_recorder_samples_proc():
    """Per-thread CPU sampling from /proc/self/task (the reference's
    cpu/recorder/linux.rs): tagged work attributes to its tag; untagged
    background threads land under the empty tag; per-thread comm totals
    accumulate."""
    import threading

    from tikv_tpu.server.resource_metering import ThreadCpuRecorder

    tags = ResourceTagFactory()
    rec = ThreadCpuRecorder(tags, interval=0.2)
    rec.sample()  # baseline

    stop = threading.Event()

    def tagged_burn():
        with tags.attach(b"heavy-group"):
            while not stop.is_set():
                sum(i * i for i in range(2000))

    def untagged_burn():
        while not stop.is_set():
            sum(i * i for i in range(2000))

    t1 = threading.Thread(target=tagged_burn, name="burner-tagged")
    t2 = threading.Thread(target=untagged_burn, name="burner-bg")
    t1.start()
    t2.start()
    try:
        import time as _t

        deadline = _t.monotonic() + 10
        snap = {}
        while _t.monotonic() < deadline:
            _t.sleep(0.3)
            rec.sample()
            snap = rec.snapshot()
            if snap["by_tag"].get(b"heavy-group", 0) > 0 and \
                    snap["by_tag"].get(rec.UNTAGGED, 0) > 0:
                break
    finally:
        stop.set()
        t1.join()
        t2.join()
    assert snap["by_tag"].get(b"heavy-group", 0) > 0, snap
    assert snap["by_tag"].get(rec.UNTAGGED, 0) > 0, snap
    assert snap["by_thread"], snap
