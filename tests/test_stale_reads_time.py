"""Follower stale reads (resolved-ts gated) + MySQL time types."""

import pytest

from tikv_tpu.copr import mysql_time as mt
from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.raft.raftkv import RaftKv
from tikv_tpu.sidecar.resolved_ts import ResolvedTsEndpoint
from tikv_tpu.storage.mvcc import PointGetter
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn.commands import Commit, Prewrite
from tikv_tpu.storage.txn_types import Key, Mutation


def test_follower_stale_read():
    pd = MockPd()
    cluster = Cluster(3, pd=pd)
    cluster.run()
    rts = ResolvedTsEndpoint(pd)
    for s in cluster.stores.values():
        rts.attach_store(s)
    leader = cluster.wait_leader(FIRST_REGION_ID)
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}
    ts = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"sk"), b"sv")], b"sk", ts), ctx)
    store.sched_txn_command(Commit([Key.from_raw(b"sk")], ts, pd.get_tso()), ctx)
    watermark = rts.advance_all()[FIRST_REGION_ID]

    follower_sid = next(s for s in cluster.stores if s != leader.store.store_id)
    fkv = RaftKv(cluster.stores[follower_sid], pump=cluster.process, resolved_ts=rts)
    # read on the FOLLOWER at the watermark — no leader involved
    snap = fkv.snapshot({"region_id": FIRST_REGION_ID, "stale_read": True, "read_ts": watermark})
    assert PointGetter(snap, watermark).get(Key.from_raw(b"sk")) == b"sv"
    # above the watermark → DataNotReady (client must retry/fall back)
    with pytest.raises(RaftKv.DataNotReadyError):
        fkv.snapshot({"region_id": FIRST_REGION_ID, "stale_read": True, "read_ts": watermark + 10})
    # pending txn pins the watermark; stale read at old watermark still works
    ts2 = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"p"), b"x")], b"p", ts2), ctx)
    w2 = rts.advance_all()[FIRST_REGION_ID]
    assert w2 == ts2 - 1
    snap = fkv.snapshot({"region_id": FIRST_REGION_ID, "stale_read": True, "read_ts": w2})
    assert PointGetter(snap, w2).get(Key.from_raw(b"sk")) == b"sv"


def test_datetime_pack_order_and_roundtrip():
    a = mt.parse_datetime("2024-03-15 10:30:45.123456")
    b = mt.parse_datetime("2024-03-15 10:30:46")
    c = mt.parse_datetime("2025-01-01")
    assert a < b < c  # chronological == integer order
    assert mt.unpack_datetime(a) == (2024, 3, 15, 10, 30, 45, 123456)
    assert mt.format_datetime(a) == "2024-03-15 10:30:45.123456"
    assert mt.format_datetime(c) == "2025-01-01 00:00:00"
    with pytest.raises(ValueError):
        mt.parse_datetime("2024-13-01")


def test_duration_roundtrip():
    d = mt.parse_duration("-12:34:56.789000")
    assert d < 0
    assert mt.format_duration(d) == "-12:34:56.789000"
    assert mt.parse_duration("01:02:03") == mt.duration_nanos(1, 2, 3)
    assert mt.format_duration(mt.duration_nanos(100, 0, 0)) == "100:00:00"


def test_time_kernels_cpu_and_device_identical():
    """year/month/day kernels are pure int ops — device-eligible, and the
    device path matches the CPU path byte-for-byte."""
    import sys, os

    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import TABLE_ID
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation, BatchExecutorsRunner, DagRequest, Selection, TableScan
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType, FieldTypeTp
    from tikv_tpu.copr.executors import FixtureScanSource
    from tikv_tpu.copr.jax_eval import JaxDagEvaluator, supports
    from tikv_tpu.copr.rpn import call, col, const_int
    from tikv_tpu.copr.table import encode_row, record_key

    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType(FieldTypeTp.DATETIME)),
    ]
    kvs = []
    for i in range(200):
        packed = mt.pack_datetime(2020 + (i % 5), 1 + (i % 12), 1 + (i % 28), i % 24)
        kvs.append((record_key(TABLE_ID, i), encode_row(cols[1:], [packed])))
    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, cols),
            Selection([call("ge", call("year", col(1)), const_int(2022))]),
            Aggregation([], [AggDescriptor("count", None), AggDescriptor("max", call("month", col(1)))]),
        ]
    )
    assert supports(dag)
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
    dev = JaxDagEvaluator(DagRequest(executors=dag.executors), block_rows=64).run(FixtureScanSource(kvs))
    assert cpu.encode() == dev.encode()
    count, max_month = cpu.iter_rows()[0]
    expect = [i for i in range(200) if 2020 + (i % 5) >= 2022]
    assert count == len(expect)


def test_lagging_follower_refuses_stale_read():
    """RegionReadProgress: a follower that hasn't applied the watermark's
    paired index must refuse rather than serve missing data."""
    from tikv_tpu.raft.store import RegionPacketFilter
    from tikv_tpu.raft.core import MsgType

    pd = MockPd()
    cluster = Cluster(3, pd=pd)
    cluster.run()
    rts = ResolvedTsEndpoint(pd)
    for s in cluster.stores.values():
        rts.attach_store(s)
    leader = cluster.wait_leader(FIRST_REGION_ID)
    lagging = next(s for s in cluster.stores if s != leader.store.store_id)
    # cut replication to the lagging follower, then commit new data
    cluster.transport.filters.append(
        RegionPacketFilter(FIRST_REGION_ID, lagging, {MsgType.APPEND, MsgType.SNAPSHOT, MsgType.HEARTBEAT})
    )
    store = Storage(engine=cluster.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}
    ts = pd.get_tso()
    store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(b"lk"), b"lv")], b"lk", ts), ctx)
    store.sched_txn_command(Commit([Key.from_raw(b"lk")], ts, pd.get_tso()), ctx)
    w = rts.advance_all()[FIRST_REGION_ID]
    fkv = RaftKv(cluster.stores[lagging], pump=cluster.process, resolved_ts=rts)
    # the lagging follower must REFUSE (its applied < required index)
    with pytest.raises(RaftKv.DataNotReadyError):
        fkv.snapshot({"region_id": FIRST_REGION_ID, "stale_read": True, "read_ts": w})
    # heal; once caught up, the same read succeeds
    cluster.transport.filters.clear()
    cluster.tick(5)
    snap = fkv.snapshot({"region_id": FIRST_REGION_ID, "stale_read": True, "read_ts": w})
    assert PointGetter(snap, w).get(Key.from_raw(b"lk")) == b"lv"


def test_replica_read_linearizable_from_follower():
    """Replica read (read.rs replica-read): a FOLLOWER serves a snapshot
    after a ReadIndex round trip to the leader + apply catch-up — it must
    observe every write committed before the read was issued."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
    from tikv_tpu.raft.raftkv import RaftKv
    from tikv_tpu.raft.region import NotLeaderError
    from tikv_tpu.storage.engine import CF_DEFAULT

    c = Cluster(3)
    c.run()
    c.must_put(b"rr-1", b"v1")
    leader = c.wait_leader(FIRST_REGION_ID)
    follower_sid = next(
        sid for sid, s in c.stores.items() if sid != leader.store.store_id)
    kv = c.raftkv(follower_sid)
    # plain read on a follower refuses (leader-only)
    try:
        kv.snapshot({"region_id": FIRST_REGION_ID})
        raise AssertionError("follower served a non-replica read")
    except NotLeaderError:
        pass
    # replica read serves, and sees the committed write
    snap = kv.snapshot({"region_id": FIRST_REGION_ID, "replica_read": True})
    assert snap.get_cf(CF_DEFAULT, b"rr-1") == b"v1"
    # linearizability: a NEW write committed on the leader is visible to a
    # replica read issued afterwards
    c.must_put(b"rr-2", b"v2")
    snap = kv.snapshot({"region_id": FIRST_REGION_ID, "replica_read": True})
    assert snap.get_cf(CF_DEFAULT, b"rr-2") == b"v2"
