"""Operational artifacts: Grafana dashboards + alert rules must stay in
lockstep with the metric series the store actually emits (the reference
ships metrics/grafana/*.json + metrics/alertmanager/tikv.rules.yml; a
dashboard over nonexistent series is decoration, not observability)."""

import json
import os
import re

import yaml

from tikv_tpu.util.metrics import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# importing these modules registers their series in REGISTRY
import tikv_tpu.server.node  # noqa: F401,E402
import tikv_tpu.server.server  # noqa: F401,E402
import tikv_tpu.storage.txn.scheduler  # noqa: F401,E402

# series registered lazily at first use (counters created inside handlers)
LAZY_SERIES = {
    "tikv_bufsan_total",
    "tikv_coprocessor_request_total",
    "tikv_coprocessor_request_duration_seconds",
    "tikv_coprocessor_device_fallback_total",
    "tikv_coprocessor_cache_hit_total",
    "tikv_coprocessor_batch_total",
    "tikv_coprocessor_batch_queries_total",
    "tikv_coprocessor_sched_queue_depth",
    "tikv_coprocessor_sched_batch_occupancy",
    "tikv_coprocessor_sched_padding_waste",
    "tikv_coprocessor_sched_lane_wait_seconds",
    "tikv_coprocessor_sched_batches_total",
    "tikv_coprocessor_sched_shed_total",
    "tikv_coprocessor_sched_device_occupancy",
    "tikv_coprocessor_sharded_merge_seconds",
    "tikv_coprocessor_mesh_cache_hit_total",
    "tikv_coprocessor_path_fallback_total",
    "tikv_coprocessor_breaker_event_total",
    "tikv_coprocessor_breaker_state",
    "tikv_coprocessor_deadline_expired_total",
    "tikv_wire_stage_seconds",
    "tikv_wire_coalesce_total",
    "tikv_wire_chunk_total",
    "tikv_trace_total",
    "tikv_trace_ring_traces",
    "tikv_copr_owner_forward_total",
    "tikv_chaos_injected_total",
    "tikv_client_retry_total",
    "tikv_resolved_ts_safe_ts_lag",
    "tikv_read_forward_total",
    "tikv_read_stale_serve_total",
    "tikv_read_refuse_total",
    "tikv_coprocessor_follower_read_total",
    "tikv_coprocessor_region_cache_total",
    "tikv_coprocessor_region_cache_wt_lost_total",
    "tikv_coprocessor_integrity_mismatch_total",
    "tikv_coprocessor_integrity_quarantine_total",
    "tikv_coprocessor_integrity_scrub_total",
    "tikv_coprocessor_shadow_read_total",
    "tikv_coprocessor_checksum_total",
    "tikv_raft_consistency_check_total",
    "tikv_coprocessor_region_cache_device_bytes",
    "tikv_storage_batch_size",
    "tikv_coprocessor_region_cache_delta_rows_total",
    "tikv_coprocessor_region_cache_evict_total",
    "tikv_coprocessor_region_cache_invalidate_total",
    "tikv_coprocessor_region_cache_bytes",
    "tikv_coprocessor_region_cache_compression_ratio",
    "tikv_coprocessor_region_cache_device_pinned_bytes",
    "tikv_observatory_serve_total",
    "tikv_observatory_serve_seconds",
    "tikv_observatory_rows_total",
    "tikv_observatory_decline_total",
    "tikv_observatory_compile_total",
    "tikv_observatory_compile_seconds",
    "tikv_observatory_pinned_hbm_bytes",
    "tikv_observatory_pinned_hbm_watermark_bytes",
    "tikv_observatory_sigs",
    "tikv_observatory_evicted_sigs",
    "tikv_observatory_backend_probe_total",
    "tikv_coprocessor_encoding_total",
    "tikv_coprocessor_encoding_demote_total",
    "tikv_coprocessor_encoded_path_total",
    "tikv_coprocessor_encoded_decline_total",
    "tikv_coprocessor_encoded_rewrite_total",
    "tikv_coprocessor_zone_prune_total",
    "tikv_coprocessor_join_total",
    "tikv_coprocessor_cost_route_total",
    "tikv_coprocessor_cost_route_delta_ms_total",
    "tikv_coprocessor_geometry_tune_total",
    "tikv_overload_admission_total",
    "tikv_overload_demote_total",
    "tikv_overload_bucket_level",
    "tikv_overload_effective_scale",
    "tikv_overload_controller_total",
    "tikv_overload_hbm_bytes",
    "tikv_overload_hbm_evict_total",
    "tikv_overload_device_block_total",
    "tikv_gcworker_gc_tasks_total",
    "tikv_memory_usage_bytes",
    "tikv_raftstore_proposal_total",
    "tikv_raftstore_apply_duration_seconds",
    "tikv_raftstore_apply_batch_entries",
    "tikv_engine_wal_bytes",
    "tikv_engine_memtable_bytes",
    "tikv_engine_run_count",
    "tikv_engine_perf_events",
}

_METRIC_RE = re.compile(r"\btikv_[a-z0-9_]+")


def _known_series() -> set:
    known = set(REGISTRY._metrics) | set(LAZY_SERIES)
    # histograms expose _bucket/_sum/_count series
    for name in list(known):
        known.update({name + "_bucket", name + "_sum", name + "_count"})
    return known


def test_dashboard_panels_reference_real_series():
    """EVERY dashboard in metrics/grafana must only reference series the
    store actually emits (summary + raft + engine + coprocessor)."""
    gdir = os.path.join(REPO, "metrics", "grafana")
    dashes = sorted(f for f in os.listdir(gdir) if f.endswith(".json"))
    assert len(dashes) >= 4, "expected summary + raft + engine + copr dashboards"
    known = _known_series()
    for fn in dashes:
        dash = json.loads(open(os.path.join(gdir, fn)).read())
        exprs = [
            t["expr"]
            for p in dash["panels"]
            for t in p.get("targets", [])
            if "expr" in t
        ]
        assert len(exprs) >= 6, f"{fn} lost its panels"
        for expr in exprs:
            for name in _METRIC_RE.findall(expr):
                assert name in known, f"{fn} references unknown series {name}"


def test_alert_rules_reference_real_series():
    path = os.path.join(REPO, "metrics", "alertmanager", "tikv_tpu.rules.yml")
    doc = yaml.safe_load(open(path).read())
    rules = doc["groups"][0]["rules"]
    assert len(rules) >= 8
    known = _known_series()
    for rule in rules:
        assert rule["alert"] and rule["expr"] and rule["labels"]["level"]
        for name in _METRIC_RE.findall(rule["expr"]):
            assert name in known, f"alert {rule['alert']} references unknown {name}"


def test_served_metrics_include_dashboard_sources():
    """Drive a live server + endpoint and confirm /metrics exposes the
    headline series the dashboard's top row draws from."""
    from tikv_tpu.copr.endpoint import Endpoint
    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService
    from tikv_tpu.storage.storage import Storage

    storage = Storage()
    svc = KvService(storage, Endpoint(storage.engine))
    srv = Server(svc)
    srv.start()
    c = Client(*srv.addr)
    c.call("kv_get", {"key": b"x", "version": 10, "context": {}})
    c.close()
    srv.stop()
    text = REGISTRY.render()
    for series in ("tikv_grpc_msg_total", "tikv_grpc_msg_duration_seconds",
                   "tikv_raftstore_region_count", "tikv_scheduler_commands_total"):
        assert series in text, f"{series} missing from /metrics"
    assert 'method="kv_get"' in text
