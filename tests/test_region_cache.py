"""Region column cache: delta apply, invalidation, budget, fallbacks.

The contract under test is the ISSUE 1 acceptance list: byte-identical
DAGResponses across insert/update/delete deltas (vs a cold endpoint with the
cache off), invalidation on real region epoch changes (a raft split), LRU
eviction under a small byte budget, and the stale-``start_ts`` fallback.
"""

import numpy as np
import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID
from fixtures import delete_committed, lock_key, put_committed

from tikv_tpu.copr.dag import Aggregation, DagRequest, Limit, Selection, TableScan
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.region_cache import RegionColumnCache, notify_region_epoch_change
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine

NON_HANDLE = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]
N_ROWS = 64


def _engine(n=N_ROWS, v2=False, table_id=TABLE_ID):
    eng = BTreeEngine()
    enc = encode_row_v2 if v2 else encode_row
    for i in range(n):
        name = [b"apple", b"banana", b"cherry"][i % 3]
        val = enc(NON_HANDLE, [name, i * 7 % 23, 100 + i])
        put_committed(eng, record_key(table_id, i), val, 90, 100)
    return eng


def _scan_dag(table_id=TABLE_ID):
    return DagRequest(executors=[TableScan(table_id, PRODUCT_COLUMNS), Limit(1 << 20)])


def _sel_dag(table_id=TABLE_ID):
    return DagRequest(executors=[
        TableScan(table_id, PRODUCT_COLUMNS),
        Selection([call("gt", col(2), const_int(5))]),
    ])


def _agg_dag(table_id=TABLE_ID):
    aggs = [AggDescriptor("sum", col(2)), AggDescriptor("count", None)]
    return DagRequest(executors=[
        TableScan(table_id, PRODUCT_COLUMNS), Aggregation([col(1)], aggs),
    ])


def _req(dag, ts, apply_index, region_id=7, epoch=(1, 1), table_id=TABLE_ID):
    return CoprRequest(
        103, dag, [record_range(table_id)], ts,
        context={"region_id": region_id, "region_epoch": epoch,
                 "apply_index": apply_index},
    )


def _pair(eng, **kw):
    warm = Endpoint(LocalEngine(eng), enable_device=True, **kw)
    cold = Endpoint(LocalEngine(eng), enable_device=True, enable_region_cache=False)
    return warm, cold


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
@pytest.mark.parametrize("mk_dag", [_scan_dag, _sel_dag, _agg_dag],
                         ids=["scan", "selection", "aggregation"])
def test_delta_apply_byte_identical(v2, mk_dag):
    """Insert + update + delete between two apply_indexes must serve the
    exact cold-decode bytes through the incremental delta path."""
    eng = _engine(v2=v2)
    warm, cold = _pair(eng)

    r0 = warm.handle_request(_req(mk_dag(), 200, 3))
    assert r0.metrics["region_cache"] == "miss"
    assert r0.data == cold.handle_request(_req(mk_dag(), 200, 3)).data
    r1 = warm.handle_request(_req(mk_dag(), 200, 3))
    assert r1.metrics["region_cache"] == "hit"
    assert r1.data == r0.data

    enc = encode_row_v2 if v2 else encode_row
    # update 2 rows (one with a NEW dictionary value), insert 1, delete 1
    put_committed(eng, record_key(TABLE_ID, 5),
                  enc(NON_HANDLE, [b"durian", 999, 5]), 210, 220)
    put_committed(eng, record_key(TABLE_ID, 11),
                  enc(NON_HANDLE, [b"apple", 1000, 6]), 210, 220)
    put_committed(eng, record_key(TABLE_ID, 500),
                  enc(NON_HANDLE, [b"elderberry", 7, 1]), 210, 220)
    delete_committed(eng, record_key(TABLE_ID, 0), 210, 220)

    r2 = warm.handle_request(_req(mk_dag(), 300, 4))
    assert r2.metrics["region_cache"] == "delta"
    assert r2.metrics["region_cache_delta_rows"] == 4
    assert r2.data == cold.handle_request(_req(mk_dag(), 300, 4)).data
    # and the post-delta image keeps serving hits byte-identically
    r3 = warm.handle_request(_req(mk_dag(), 300, 4))
    assert r3.metrics["region_cache"] == "hit"
    assert r3.data == r2.data


def test_update_only_delta_scatters_into_pinned_arrays():
    """An update-only delta takes the in-place scatter path (device pins are
    patched, not dropped) and later requests stay byte-identical."""
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_agg_dag(), 200, 3))  # build + pin
    warm.handle_request(_req(_agg_dag(), 200, 3))  # warm agg pins stacked arrays
    for i in (2, 9, 30):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(NON_HANDLE, [b"banana", 4, 4]), 210, 220)
    r = warm.handle_request(_req(_agg_dag(), 300, 4))
    assert r.metrics["region_cache"] == "delta"
    assert r.data == cold.handle_request(_req(_agg_dag(), 300, 4)).data
    # host blocks and device pins agree on the next pure hit
    r2 = warm.handle_request(_req(_sel_dag(), 300, 4))
    assert r2.metrics["region_cache"] == "hit"
    assert r2.data == cold.handle_request(_req(_sel_dag(), 300, 4)).data


def test_stale_start_ts_falls_back():
    """A read below the image's snapshot ts must not serve from the image
    (it would see too-new data) — it reports 'stale' and answers through
    the per-request path, byte-identical to the cache-off endpoint."""
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    put_committed(eng, record_key(TABLE_ID, 1),
                  encode_row(NON_HANDLE, [b"apple", 1, 1]), 110, 120)
    r = warm.handle_request(_req(_scan_dag(), 150, 4))
    assert r.metrics["region_cache"] == "stale"
    assert r.data == cold.handle_request(_req(_scan_dag(), 150, 4)).data
    assert warm.region_cache.stats.stale == 1


def test_epoch_change_in_context_invalidates():
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3, epoch=(1, 1)))
    assert len(warm.region_cache) == 1
    # a split bumped the version: same region id, new epoch
    r = warm.handle_request(_req(_scan_dag(), 300, 4, epoch=(1, 2)))
    assert r.metrics["region_cache"] == "miss"
    assert warm.region_cache.stats.invalidations == 1
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4, epoch=(1, 2))).data


def test_raft_split_invalidates_cache():
    """A real region split through the raft apply path must invalidate the
    cached images of both sides via the store.py epoch-change hook."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    eng = _engine()
    warm, _cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3, region_id=FIRST_REGION_ID))
    assert len(warm.region_cache) == 1

    c = Cluster(3)
    c.run()
    c.must_put(b"a", b"1")
    c.must_put(b"z", b"2")
    c.split_region(FIRST_REGION_ID, b"m")
    assert len(warm.region_cache) == 0
    assert warm.region_cache.stats.invalidations >= 1


def test_notify_hook_is_region_scoped():
    eng = _engine()
    warm, _cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3, region_id=7))
    notify_region_epoch_change(8)  # some other region
    assert len(warm.region_cache) == 1
    notify_region_epoch_change(7, reason="merge")
    assert len(warm.region_cache) == 0


def test_lru_eviction_under_byte_budget():
    """Three regions under a budget that fits ~one image: LRU evicts, the
    endpoint keeps answering correctly, and nothing OOMs."""
    eng = _engine(n=128)
    # decoded residency: this test pins the LRU/budget mechanics — with
    # column encoding on (the default) all three images FIT the budget,
    # which is the capacity win tests/test_compressed_columns.py asserts
    small = RegionColumnCache(byte_budget=1 << 14, max_regions=8,
                              encode_columns=False)
    warm = Endpoint(LocalEngine(eng), enable_device=True, region_cache=small)
    cold = Endpoint(LocalEngine(eng), enable_device=True, enable_region_cache=False)
    for rid in (1, 2, 3):
        r = warm.handle_request(_req(_scan_dag(), 200, 3, region_id=rid))
        assert r.data == cold.handle_request(_req(_scan_dag(), 200, 3)).data
    assert small.stats.evictions >= 2
    assert small.total_bytes() <= (1 << 14) or len(small) == 1
    # the survivor still serves hits
    r = warm.handle_request(_req(_scan_dag(), 200, 3, region_id=3))
    assert r.metrics["region_cache"] == "hit"


def test_region_too_big_for_budget_degrades():
    eng = _engine(n=128)
    tiny = RegionColumnCache(byte_budget=64, max_regions=8)
    warm = Endpoint(LocalEngine(eng), enable_device=True, region_cache=tiny)
    cold = Endpoint(LocalEngine(eng), enable_device=True, enable_region_cache=False)
    r = warm.handle_request(_req(_scan_dag(), 200, 3))
    assert r.metrics["region_cache"] == "too_big"
    assert len(tiny) == 0  # never pinned
    assert r.data == cold.handle_request(_req(_scan_dag(), 200, 3)).data


def test_locked_range_still_blocks_cached_reads():
    """A pending lock below the read ts must surface through the cached path
    exactly like the scanners (the CPU fallback re-raises it)."""
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    lock_key(eng, record_key(TABLE_ID, 4), record_key(TABLE_ID, 4), 250)
    with pytest.raises(Exception, match="locked"):
        warm.handle_request(_req(_scan_dag(), 300, 4))
    with pytest.raises(Exception, match="locked"):
        cold.handle_request(_req(_scan_dag(), 300, 4))


def test_counters_and_tracker_exposure():
    from tikv_tpu.util.metrics import REGISTRY

    eng = _engine()
    warm, _cold = _pair(eng)
    before = REGISTRY.counter(
        "tikv_coprocessor_region_cache_total", "").get(outcome="hit")
    r0 = warm.handle_request(_req(_scan_dag(), 200, 3))
    r1 = warm.handle_request(_req(_scan_dag(), 200, 3))
    assert r0.metrics["region_cache"] == "miss"
    assert r1.metrics["region_cache"] == "hit"
    assert REGISTRY.counter(
        "tikv_coprocessor_region_cache_total", "").get(outcome="hit") == before + 1
    st = warm.region_cache.stats.to_dict()
    assert st["hits"] >= 1 and st["misses"] >= 1 and st["bytes_pinned"] > 0


def test_missing_context_is_off():
    eng = _engine()
    warm, cold = _pair(eng)
    req = CoprRequest(103, _scan_dag(), [record_range(TABLE_ID)], 200,
                      context={"region_id": 7})  # no epoch / apply_index
    r = warm.handle_request(req)
    assert "region_cache" not in r.metrics
    assert r.data == cold.handle_request(req).data
    assert len(warm.region_cache) == 0


def test_delta_update_with_large_value_resolves_exactly():
    """A changed key whose new value lives in CF_DEFAULT (no inline short
    value) must re-resolve through the exact path — regression for the
    encoded-key double-encoding that misclassified such updates as deletes."""
    from fixtures import put_committed_large

    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    # a real encoded row forced into CF_DEFAULT (no inline short value)
    row = encode_row(NON_HANDLE, [b"fig", 77, 88])
    put_committed_large(eng, record_key(TABLE_ID, 9), row, 210, 220)
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "delta"
    assert r.metrics["region_cache_delta_rows"] == 1
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data


def test_delta_rollback_pick_resolves_older_version():
    """A rollback record newer than the cached fingerprint must re-resolve
    to the surviving older version, not delete the row."""
    from fixtures import rollback

    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    rollback(eng, record_key(TABLE_ID, 9), 150)
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "delta"
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data
    # row 9 must still be present (update fingerprint, keep old value)
    r2 = warm.handle_request(_req(_sel_dag(), 300, 4))
    assert r2.data == cold.handle_request(_req(_sel_dag(), 300, 4)).data
