"""DR auto-sync replication mode (raftstore/src/store/replication_mode.rs +
PD's ReplicationStatus state machine): in ``sync`` state an entry commits
only when every label group holds it; losing a whole group drops the
cluster to ``async`` (majority commit) and its return passes through
``sync_recover`` back to ``sync``."""

import time

import pytest

from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.raft.region import NotLeaderError


@pytest.fixture
def dr_cluster():
    """3 stores: stores 1+2 = group 'east', store 3 = group 'west'."""
    c = Cluster(3)
    c.run()
    status = {"mode": "dr_auto_sync", "state": "sync",
              "labels": {1: "east", 2: "east", 3: "west"}}
    for s in c.stores.values():
        s.set_replication_mode(status)
    return c


def _commit_index(cluster, sid=None):
    leader = cluster.wait_leader(FIRST_REGION_ID)
    return leader.node.commit


def test_sync_state_requires_every_group(dr_cluster):
    c = dr_cluster
    c.must_put(b"k0", b"v0")  # all groups healthy: commits normally
    assert c.must_get(b"k0") == b"v0"
    leader = c.wait_leader(FIRST_REGION_ID)
    committed_before = leader.node.commit
    # the WHOLE west group (store 3) goes dark
    c.stop_node(3)
    kv = c.raftkv(leader.store.store_id)
    with pytest.raises((TimeoutError, NotLeaderError)):
        from tikv_tpu.storage.engine import WriteBatch

        wb = WriteBatch()
        wb.put_cf("default", b"k1", b"v1")
        kv.write({"region_id": FIRST_REGION_ID}, wb)
    # majority (east) held the entry but it must NOT have committed
    assert leader.node.commit == committed_before


def test_async_state_restores_majority_commit(dr_cluster):
    c = dr_cluster
    c.must_put(b"k0", b"v0")
    c.stop_node(3)
    # PD decides west is gone: state drops to async
    status = {"mode": "dr_auto_sync", "state": "async",
              "labels": {1: "east", 2: "east", 3: "west"}}
    for sid in (1, 2):
        c.stores[sid].set_replication_mode(status)
    c.must_put(b"k1", b"v1")  # 2/3 majority commits again
    assert c.must_get(b"k1") == b"v1"
    # west returns; sync restored — commits require west once more AND the
    # log it missed replicates over
    c.restart_node(3)
    sync = dict(status, state="sync")
    for s in c.stores.values():
        s.set_replication_mode(sync)
    c.must_put(b"k2", b"v2")
    c.tick(5)
    assert c.get_on_store(3, b"k1") == b"v1"
    assert c.get_on_store(3, b"k2") == b"v2"


def test_pd_replication_state_machine():
    pd = MockPd()
    pd.store_down_secs = 1.0
    pd.enable_dr_auto_sync({1: "east", 2: "east", 3: "west"})
    # fresh enablement settles through the recovery path once every group
    # has heartbeated (the machine never trusts a group it hasn't seen)
    deadline = time.monotonic() + 5
    st = {}
    while st.get("state") != "sync" and time.monotonic() < deadline:
        for sid in (1, 2, 3):
            st = pd.store_heartbeat(sid, {})
        time.sleep(0.2)
    assert st["state"] == "sync"
    # west stops beating: next east heartbeat observes the dead group
    time.sleep(1.2)
    st = pd.store_heartbeat(1, {})
    assert st["state"] == "async"
    # west returns: async -> sync_recover -> (grace) -> sync
    st = pd.store_heartbeat(3, {})
    assert st["state"] == "sync_recover"
    deadline = time.monotonic() + 5
    while st["state"] != "sync" and time.monotonic() < deadline:
        time.sleep(0.2)
        st = pd.store_heartbeat(1, {})
        pd.store_heartbeat(3, {})
    assert st["state"] == "sync"


def test_unlabeled_mode_unchanged():
    """Majority mode (the default) must behave exactly as before."""
    c = Cluster(3)
    c.run()
    for s in c.stores.values():
        s.set_replication_mode({"mode": "majority", "state": "sync", "labels": {}})
    c.must_put(b"m0", b"v")
    c.stop_node(3)
    c.must_put(b"m1", b"v")  # plain majority: 2/3 commits
    assert c.must_get(b"m1") == b"v"
