"""Compressed device-resident columns: end-to-end differential coverage.

The contract under test is the ISSUE 10 acceptance list: encoded-resident
region images must serve BYTE-IDENTICALLY to the CPU oracle on every path
(unary warm, fused same-region batch, cross-region vmapped), through
mid-stream delta folds and encoding-breaking updates, across dict/RLE/
bitpacked columns × rowv1/rowv2 × scan/selection/agg/topN — and an equal
byte budget must keep ≥2× more regions warm encoded than decoded, with the
integrity plane detecting encoded-payload corruption."""

import random

import numpy as np
import pytest

from copr_fixtures import TABLE_ID
from fixtures import delete_committed, put_committed

from tikv_tpu.copr import encoding as E
from tikv_tpu.copr import jax_eval
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import (
    Aggregation, DagRequest, Limit, Selection, TableScan, TopN,
)
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.region_cache import RegionColumnCache
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.rpn import call, col, const_bytes, const_int
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.util import chaos
from tikv_tpu.util.metrics import REGISTRY

# id (pk) | category (dict) | runlen (rle) | small (bitpack) | wide (plain)
COLUMNS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.varchar()),
    ColumnInfo(3, FieldType.int64()),
    ColumnInfo(4, FieldType.int64()),
    ColumnInfo(5, FieldType.int64()),
]
NON_HANDLE = COLUMNS[1:]
CATS = [b"alpha", b"beta", b"gamma", b"delta"]


def _row(i, rng):
    return [CATS[i % len(CATS)], i // 100, int(rng.integers(0, 120)),
            int(rng.integers(-(1 << 40), 1 << 40))]


def _engine(n=600, v2=False, seed=0, table_id=TABLE_ID):
    rng = np.random.default_rng(seed)
    eng = BTreeEngine()
    enc = encode_row_v2 if v2 else encode_row
    for i in range(n):
        put_committed(eng, record_key(table_id, i),
                      enc(NON_HANDLE, _row(i, rng)), 90, 100)
    return eng


def _req(dag, ts, ai, region_id=7, ranges=None):
    return CoprRequest(103, dag, ranges or [record_range(TABLE_ID)], ts,
                       context={"region_id": region_id,
                                "region_epoch": (1, 1), "apply_index": ai})


def _pair(eng, **kw):
    warm = Endpoint(LocalEngine(eng), enable_device=True, **kw)
    cold = Endpoint(LocalEngine(eng), enable_device=False,
                    enable_region_cache=False)
    return warm, cold


def _dags():
    return {
        "scan": DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                                      Limit(1 << 20)]),
        "selection": DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Selection([call("gt", col(3), const_int(40)),
                       call("le", col(2), const_int(4))]),
        ]),
        "agg": DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Aggregation([col(1)], [AggDescriptor("sum", col(3)),
                                   AggDescriptor("min", col(4)),
                                   AggDescriptor("count", None)]),
        ]),
        "topn": DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Selection([call("ge", col(3), const_int(10))]),
            TopN([(col(3), True), (col(0), False)], 25),
        ]),
    }


def _image(warm):
    [img] = warm.region_cache._images.values()
    return img


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
@pytest.mark.parametrize("name", ["scan", "selection", "agg", "topn"])
def test_encoded_serve_byte_identical_through_deltas(v2, name):
    """Every plan shape over an ENCODED-resident image answers the CPU
    oracle's bytes — warm, then again after a delta fold that includes an
    in-place bitpack patch, an encoding-BREAKING update (RLE column and an
    out-of-frame bitpack value), a new dictionary value, an insert and a
    delete (structural repack + re-encode)."""
    dag = _dags()[name]
    eng = _engine(v2=v2)
    warm, cold = _pair(eng)
    r0 = warm.handle_request(_req(dag, 200, 3))
    assert r0.metrics["region_cache"] == "miss"
    img = _image(warm)
    assert img.encodings, "stats pass encoded nothing"
    kinds = set(img.encodings.values())
    assert {"dict", "rle", "bp"} <= kinds
    assert r0.data == cold.handle_request(_req(dag, 200, 3)).data
    r1 = warm.handle_request(_req(dag, 200, 3))
    assert r1.metrics["region_cache"] == "hit" and r1.data == r0.data

    enc = encode_row_v2 if v2 else encode_row
    # in-place within-frame update (bitpack patch), RLE-breaking update,
    # out-of-frame value, new dictionary value
    put_committed(eng, record_key(TABLE_ID, 5),
                  enc(NON_HANDLE, [b"beta", 0, 119, 1]), 210, 220)
    put_committed(eng, record_key(TABLE_ID, 6),
                  enc(NON_HANDLE, [b"omega", 999999, 1 << 50, 2]), 210, 220)
    r2 = warm.handle_request(_req(dag, 300, 4))
    assert r2.metrics["region_cache"] in ("delta", "wt_delta")
    assert r2.data == cold.handle_request(_req(dag, 300, 4)).data

    # structural: insert + delete → repack → re-encode from fresh stats
    put_committed(eng, record_key(TABLE_ID, 900),
                  enc(NON_HANDLE, [b"alpha", 9, 50, 3]), 310, 320)
    delete_committed(eng, record_key(TABLE_ID, 0), 310, 320)
    r3 = warm.handle_request(_req(dag, 400, 5))
    assert r3.metrics["region_cache"] in ("delta", "wt_delta")
    assert r3.data == cold.handle_request(_req(dag, 400, 5)).data
    img = _image(warm)
    assert img.encodings, "repack lost the encodings"
    r4 = warm.handle_request(_req(dag, 400, 5))
    assert r4.metrics["region_cache"] == "hit" and r4.data == r3.data


def test_budget_accounts_encoded_bytes_and_doubles_capacity():
    """THE density claim: at one fixed byte budget, encoded residency keeps
    ≥2× the regions warm that decoded residency does."""
    eng = _engine(n=900)
    budget = None
    for encode in (False, True):
        rc = RegionColumnCache(byte_budget=1 << 62, max_regions=64,
                               encode_columns=encode)
        warm = Endpoint(LocalEngine(eng), enable_device=True, region_cache=rc)
        warm.handle_request(_req(_dags()["scan"], 200, 3, region_id=1))
        img = _image(warm)
        if not encode:
            budget = img.nbytes  # decoded size of ONE region
            decoded_bytes = img.nbytes
        else:
            encoded_bytes = img.nbytes
    assert encoded_bytes * 2 <= decoded_bytes, (encoded_bytes, decoded_bytes)

    resident = {}
    for encode in (False, True):
        rc = RegionColumnCache(byte_budget=budget * 3, max_regions=64,
                               encode_columns=encode)
        warm = Endpoint(LocalEngine(eng), enable_device=True, region_cache=rc)
        for rid in range(1, 13):
            warm.handle_request(_req(_dags()["scan"], 200, 3, region_id=rid))
        resident[encode] = len(rc)
    assert resident[True] >= 2 * resident[False], resident


def test_gauges_report_encoded_bytes_and_ratio():
    eng = _engine()
    pinned = {}
    for encode in (True, False):
        rc = RegionColumnCache(block_rows=1024, encode_columns=encode)
        warm = Endpoint(LocalEngine(eng), enable_device=True,
                        region_cache=rc, block_rows=1024)
        # selection (no zone layout — THAT pins its own clustered geometry)
        # so encoded and decoded runs pin the same per-block signature shape
        warm.handle_request(_req(_dags()["selection"], 200, 3))
        warm.handle_request(_req(_dags()["selection"], 200, 3))  # pins arrays
        img = _image(warm)
        assert REGISTRY._metrics[
            "tikv_coprocessor_region_cache_bytes"].get() == img.nbytes
        if encode:
            ratio = REGISTRY._metrics[
                "tikv_coprocessor_region_cache_compression_ratio"].get()
            assert ratio >= 2.0
        rc._gauge_bytes()
        pinned[encode] = REGISTRY._metrics[
            "tikv_coprocessor_region_cache_device_pinned_bytes"].get()
        assert pinned[encode] > 0
    # TRUE HBM bytes: the encoded pins (narrow lanes + runs) cost under
    # half the decoded pins for the SAME plan and block geometry
    assert pinned[True] * 2 <= pinned[False], pinned


def test_fused_and_xregion_paths_serve_encoded_images():
    """The same-region fused batch and the cross-region vmapped program both
    consume the encoded pins (descriptors ride the jit keys) and stay
    byte-identical to per-request serving."""
    eng = _engine()
    warm, cold = _pair(eng)
    agg = _dags()["agg"]
    lo, hi = record_range(TABLE_ID)
    mid = record_key(TABLE_ID, 300)
    ra, rb = [(lo, mid)], [(mid, hi)]
    warm.handle_request(_req(agg, 200, 3, region_id=1, ranges=ra))
    warm.handle_request(_req(agg, 200, 3, region_id=2, ranges=rb))
    caches = [img.block_cache
              for img in warm.region_cache._images.values()]
    assert len(caches) == 2
    ev = warm._evaluator_for(agg)
    before = REGISTRY.counter(
        "tikv_coprocessor_encoded_path_total", "").get(
        path="xregion", decision="encoded")
    outs = jax_eval.run_xregion_cached(ev, caches)
    assert REGISTRY.counter(
        "tikv_coprocessor_encoded_path_total", "").get(
        path="xregion", decision="encoded") == before + 1
    assert outs[0].encode() == cold.handle_request(
        _req(agg, 200, 3, ranges=ra)).data
    assert outs[1].encode() == cold.handle_request(
        _req(agg, 200, 3, ranges=rb)).data

    # fused same-region batch over the encoded image
    agg2 = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Aggregation([], [AggDescriptor("count", None),
                         AggDescriptor("max", col(3))]),
    ])
    ev2 = warm._evaluator_for(agg2)
    # rebuild a full-range image for the fused pair
    warm.handle_request(_req(agg, 200, 3, region_id=9))
    cache9 = next(img.block_cache
                  for k, img in warm.region_cache._images.items()
                  if k[0] == 9)
    fused = jax_eval.run_batch_cached([ev, ev2], cache9)
    assert fused[0].encode() == cold.handle_request(_req(agg, 200, 3)).data
    assert fused[1].encode() == cold.handle_request(_req(agg2, 200, 3)).data


def test_xregion_enc_mismatch_decode_ships_byte_identically():
    """Regions whose encodings diverged (one demoted) decode-ship the batch
    — counted, never silent — and bytes stay identical."""
    eng = _engine()
    warm, cold = _pair(eng)
    agg = _dags()["agg"]
    lo, hi = record_range(TABLE_ID)
    mid = record_key(TABLE_ID, 300)
    ra, rb = [(lo, mid)], [(mid, hi)]
    warm.handle_request(_req(agg, 200, 3, region_id=1, ranges=ra))
    warm.handle_request(_req(agg, 200, 3, region_id=2, ranges=rb))
    caches = [img.block_cache for img in warm.region_cache._images.values()]
    E.demote_column(caches[0], 3, "inplace_update")  # break a SHIPPED lane
    before = REGISTRY.counter(
        "tikv_coprocessor_encoded_decline_total", "").get(
        path="xregion", cause="enc_mismatch")
    ev = warm._evaluator_for(agg)
    outs = jax_eval.run_xregion_cached(ev, caches)
    assert REGISTRY.counter(
        "tikv_coprocessor_encoded_decline_total", "").get(
        path="xregion", cause="enc_mismatch") == before + 1
    assert outs[0].encode() == cold.handle_request(
        _req(agg, 200, 3, ranges=ra)).data
    assert outs[1].encode() == cold.handle_request(
        _req(agg, 200, 3, ranges=rb)).data


def test_dict_rewrite_serves_bytes_predicates_on_device():
    """equality / IN / range bytes predicates rewrite into the sorted
    dictionary's code space and serve warm on the device, byte-identical;
    a dictionary grown unsorted by a delta declines range ops (counted)."""
    eng = _engine()
    warm, cold = _pair(eng)
    conds = [
        call("eq", col(1), const_bytes(b"beta")),
        call("in", col(1), const_bytes(b"alpha"), const_bytes(b"nope")),
        call("lt", col(1), const_bytes(b"c")),
        call("ge", col(1), const_bytes(b"delta")),
    ]
    for cond in conds:
        dag = DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                                    Selection([cond])])
        warm.handle_request(_req(dag, 200, 3))
        r = warm.handle_request(_req(dag, 200, 3))
        assert r.from_device, cond.op
        assert r.data == cold.handle_request(_req(dag, 200, 3)).data

    # a delta introduces a NEW dictionary value (appended → unsorted):
    # range ops must now decline to the CPU path, still byte-identical
    put_committed(eng, record_key(TABLE_ID, 3),
                  enc_row := encode_row(NON_HANDLE, [b"aardvark", 0, 1, 1]),
                  210, 220)
    dag = DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                                Selection([call("lt", col(1),
                                                const_bytes(b"c"))])])
    warm.handle_request(_req(dag, 300, 4))  # folds the delta
    before = REGISTRY.counter(
        "tikv_coprocessor_encoded_rewrite_total", "").get(outcome="declined")
    r = warm.handle_request(_req(dag, 300, 4))
    assert not r.from_device
    assert r.data == cold.handle_request(_req(dag, 300, 4)).data
    assert REGISTRY.counter(
        "tikv_coprocessor_encoded_rewrite_total", "").get(
        outcome="declined") >= before + 1


def test_encoded_corruption_detected_by_shadow_and_scrub():
    """corrupt_image(mode="encoded") flips ENCODED payload bytes; a
    shadow-sampled serve detects it, serves the oracle bytes, and
    quarantines; the deep scrub detects the same flip independently."""
    eng = _engine()
    warm, cold = _pair(eng, shadow_sample=1)
    dag = _dags()["scan"]
    oracle = cold.handle_request(_req(dag, 200, 3)).data
    warm.handle_request(_req(dag, 200, 3))
    r1 = warm.handle_request(_req(dag, 200, 3))
    assert r1.from_device and r1.data == oracle

    info = chaos.corrupt_image(warm.region_cache, random.Random(5),
                               mode="encoded")
    assert info is not None and info["mode"] == "encoded"
    r2 = warm.handle_request(_req(dag, 200, 3))
    assert r2.data == oracle and not r2.from_device
    ledger = warm.region_cache.quarantine_ledger
    assert ledger and ledger[-1]["stage"] == "shadow_read"

    # independent detection: deep scrub on a freshly corrupted image
    warm2, _ = _pair(eng)
    warm2.handle_request(_req(dag, 200, 3))
    info = chaos.corrupt_image(warm2.region_cache, random.Random(6),
                               mode="encoded")
    assert info is not None
    res = warm2.scrubber.scrub_once()
    assert any(r.get("outcome") == "mismatch" for r in res), res
    assert warm2.region_cache.quarantine_ledger
    # quarantine → rebuild → byte-identical again
    r3 = warm2.handle_request(_req(dag, 200, 3))
    assert r3.data == oracle


def test_delta_folds_leave_no_decode_caches():
    """In-place delta folds must not leave full decode caches on encoded
    columns — the budget counts ENCODED bytes, so a cached decode would be
    unaccounted host memory on every written-to image."""
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_dags()["scan"], 200, 3))
    put_committed(eng, record_key(TABLE_ID, 7),
                  encode_row(NON_HANDLE, [b"beta", 0, 60, 2]), 210, 220)
    r = warm.handle_request(_req(_dags()["scan"], 300, 4))
    assert r.metrics["region_cache"] in ("delta", "wt_delta")
    assert r.data == cold.handle_request(_req(_dags()["scan"], 300, 4)).data
    img = _image(warm)
    cached = [
        (ci, c.kind) for b in img.block_cache.blocks
        for ci, c in enumerate(b.cols)
        if isinstance(c, E.EncodedColumn) and c._data is not None
    ]
    assert not cached, cached


def test_encode_columns_kill_switch_stays_decoded():
    eng = _engine()
    rc = RegionColumnCache(encode_columns=False)
    warm = Endpoint(LocalEngine(eng), enable_device=True, region_cache=rc)
    _, cold = _pair(eng)
    dag = _dags()["scan"]
    r = warm.handle_request(_req(dag, 200, 3))
    assert r.data == cold.handle_request(_req(dag, 200, 3)).data
    img = _image(warm)
    assert not img.encodings and not img.encode_enabled
    assert not any(isinstance(c, E.EncodedColumn)
                   for b in img.block_cache.blocks for c in b.cols)


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_compressed_differential_fuzz(seed):
    """Randomized plans over randomized encodable tables, rowv1 and rowv2:
    warm encoded serving == warm decoded serving == CPU oracle bytes,
    including a mid-stream delta fold between the two serve rounds."""
    rng = np.random.default_rng(seed)
    v2 = bool(rng.integers(0, 2))
    n = int(rng.integers(300, 800))
    eng = _engine(n=n, v2=v2, seed=seed)
    warm_enc = Endpoint(LocalEngine(eng), enable_device=True)
    warm_dec = Endpoint(LocalEngine(eng), enable_device=True,
                        encode_columns=False)
    cold = Endpoint(LocalEngine(eng), enable_device=False,
                    enable_region_cache=False)

    conj_pool = [
        lambda: call("gt", col(3), const_int(int(rng.integers(0, 120)))),
        lambda: call("le", col(2), const_int(int(rng.integers(0, n // 100 + 1)))),
        lambda: call("ne", col(0), const_int(int(rng.integers(0, n)))),
        lambda: call("eq", col(1), const_bytes(
            CATS[int(rng.integers(0, len(CATS)))])),
    ]
    agg_pool = [
        lambda: AggDescriptor("sum", col(3)),
        lambda: AggDescriptor("count", None),
        lambda: AggDescriptor("min", col(4)),
        lambda: AggDescriptor("max", col(2)),
        lambda: AggDescriptor("avg", col(3)),
    ]

    def plans():
        out = [DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                                     Limit(1 << 20)])]
        conds = [conj_pool[int(rng.integers(0, len(conj_pool)))]()
                 for _ in range(int(rng.integers(1, 3)))]
        out.append(DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                                         Selection(conds)]))
        group = [[], [col(1)], [col(2)]][int(rng.integers(0, 3))]
        aggs = [agg_pool[int(rng.integers(0, len(agg_pool)))]()
                for _ in range(int(rng.integers(1, 3)))]
        out.append(DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Aggregation(group_by=group, agg_funcs=aggs)]))
        out.append(DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            TopN([(col(3), bool(rng.integers(0, 2))), (col(0), False)],
                 int(rng.integers(1, 40)))]))
        return out

    def check(ts, ai):
        for dag in plans():
            oracle = cold.handle_request(_req(dag, ts, ai)).data
            for ep in (warm_enc, warm_dec):
                got = ep.handle_request(_req(dag, ts, ai))
                assert got.data == oracle, (
                    f"seed={seed} v2={v2} ts={ts} "
                    f"execs={[type(e).__name__ for e in dag.executors]}")

    check(200, 3)
    # mid-stream delta: updates (some encoding-breaking), insert, delete
    enc = encode_row_v2 if v2 else encode_row
    for _ in range(int(rng.integers(1, 6))):
        h = int(rng.integers(0, n))
        put_committed(eng, record_key(TABLE_ID, h),
                      enc(NON_HANDLE, [
                          CATS[int(rng.integers(0, len(CATS)))],
                          int(rng.integers(0, 1 << int(rng.choice([3, 50])))),
                          int(rng.integers(0, 200)),
                          int(rng.integers(-(1 << 40), 1 << 40))]),
                      210, 220)
    put_committed(eng, record_key(TABLE_ID, n + 50),
                  enc(NON_HANDLE, _row(n + 50, rng)), 210, 220)
    delete_committed(eng, record_key(TABLE_ID, 1), 210, 220)
    check(300, 4)
    check(300, 4)  # pure hits over the folded images
