"""Chaos nemesis + self-healing serving plane (util/chaos.py, util/retry.py,
copr/breaker.py, scheduler deadlines).

The acceptance contract (ISSUE 6 / docs/robustness.md):

* under seeded drop/delay/dup/reorder/partition/crash-restart schedules, NO
  acknowledged write is lost and the cluster converges after ``heal()``;
* warm (region-cache) reads stay byte-identical to the CPU oracle after
  heal — including when chaos forces the PR-4 write-through watermark gap
  repair;
* a deadline-expired request is shed, counted, and never dispatched to the
  device;
* the device-path circuit breaker trips to the CPU fallback on repeated
  injected faults and restores through a half-open probe, with
  trip/probe/restore metrics.

The fast seeded smoke runs in tier-1 (deterministic in-memory cluster);
full nemesis schedules over real sockets are marked ``slow``.
"""

import time

import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID
from fixtures import put_committed

from tikv_tpu.copr.breaker import BreakerConfig, DeviceCircuitBreaker
from tikv_tpu.copr.dag import Aggregation, DagRequest, Limit, TableScan
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.scheduler import SchedulerConfig
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util import failpoint, retry
from tikv_tpu.util.chaos import Nemesis
from tikv_tpu.util.metrics import REGISTRY
from tikv_tpu.util.retry import DeadlineExceeded, ServerBusyError

NON_HANDLE = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoint.teardown()
    yield
    failpoint.teardown()


# ---------------------------------------------------------------------------
# tier-1: fast seeded chaos smoke (deterministic in-memory cluster)
# ---------------------------------------------------------------------------

def test_chaos_smoke_seeded():
    """One compact scenario < 10s: message storm + partition + leader crash,
    every acknowledged write survives heal and all live stores converge."""
    c = Cluster(3)
    c.run()
    nem = Nemesis(c, seed=1234)
    acked = {}
    try:
        # phase 1: lossy, slow, duplicating, reordering network
        nem.drop(rate=0.25)
        nem.delay(1, 3, rate=0.4)
        nem.duplicate(rate=0.25)
        nem.reorder(window=3)
        for i in range(6):
            c.must_put(b"storm-%d" % i, b"v%d" % i)
            acked[b"storm-%d" % i] = b"v%d" % i
            c.tick()
        nem.heal()

        # phase 2: isolate the leader; the majority side keeps accepting
        leader_sid = c.wait_leader(FIRST_REGION_ID).store.store_id
        others = [s for s in c.stores if s != leader_sid]
        nem.partition({leader_sid}, others)
        for _ in range(30):
            c.tick()
        c.must_put(b"minority-cut", b"still-writable")
        acked[b"minority-cut"] = b"still-writable"
        nem.heal()

        # phase 3: crash the (possibly new) leader outright, write, restart
        leader_sid = c.wait_leader(FIRST_REGION_ID).store.store_id
        nem.crash(leader_sid)
        for _ in range(20):
            c.tick()
        c.must_put(b"post-crash", b"alive")
        acked[b"post-crash"] = b"alive"
        nem.heal()

        # convergence: every acknowledged write on every store
        for _ in range(80):
            c.tick()
        for k, v in acked.items():
            assert c.must_get(k) == v, k
            for sid in c.stores:
                assert c.get_on_store(sid, k) == v, (sid, k)
        assert nem.stats["dropped"] > 0 and nem.stats["delivered_late"] > 0
    finally:
        nem.heal()
        nem.close()


def test_chaos_replay_is_deterministic():
    """Same seed → identical injection decisions AND identical schedule
    composition; a different seed diverges."""
    def run(seed):
        c = Cluster(3)
        c.run()
        nem = Nemesis(c, seed=seed)
        nem.drop(rate=0.3)
        nem.delay(1, 2, rate=0.5)
        try:
            for i in range(8):
                c.must_put(b"d%d" % i, b"v")
                c.tick()
            return dict(nem.stats), nem.random_steps(6)
        finally:
            nem.heal()
            nem.close()

    a, b = run(99), run(99)
    assert a == b
    assert run(100)[1] != a[1]


def test_disk_stall_failpoint_wedges_then_heals():
    """disk_stall wedges the apply path through apply_before_exec; heal
    lifts it and the stalled write completes (nothing lost)."""
    import threading

    c = Cluster(1)
    c.run()
    nem = Nemesis(c, seed=0)
    try:
        nem.disk_stall()  # hard pause until heal
        done = threading.Event()

        def writer():
            c.must_put(b"stalled", b"w")
            done.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert not done.wait(0.3), "write completed through a stalled disk"
        nem.heal()
        # pump from THIS thread until the parked writer's proposal applies
        deadline = time.monotonic() + 10
        while not done.is_set() and time.monotonic() < deadline:
            c.tick()
            time.sleep(0.01)
        assert done.is_set()
        assert c.must_get(b"stalled") == b"w"
    finally:
        nem.heal()
        nem.close()


# ---------------------------------------------------------------------------
# warm reads vs CPU oracle under chaos (the PR-4 gap repair, under faults)
# ---------------------------------------------------------------------------

def _seed_rows(kv, region_id, n=32):
    wb = WriteBatch()
    for i in range(n):
        k = Key.from_raw(record_key(TABLE_ID, i))
        w = Write(WriteType.PUT, 90,
                  short_value=encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]))
        wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
    kv.write({"region_id": region_id}, wb)


def _commit_rows(kv, region_id, rows, ts0):
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn.scheduler import Scheduler
    from tikv_tpu.storage.txn_types import Mutation

    sched = Scheduler(kv, pool_size=1, group_commit_max=16)
    ctx = {"region_id": region_id}
    try:
        for i, (handle, row) in enumerate(rows):
            rk = record_key(TABLE_ID, handle)
            t = sched.submit(Prewrite(
                [Mutation.put(Key.from_raw(rk), row)], rk, start_ts=ts0 + i), ctx)
            assert t.done.wait(30) and t.exc is None, t.exc
            t = sched.submit(Commit(
                [Key.from_raw(rk)], ts0 + i, ts0 + 500 + i), ctx)
            assert t.done.wait(30) and t.exc is None, t.exc
    finally:
        sched.stop()
    return ts0 + 500 + len(rows)


def _scan_dag():
    return DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), Limit(1 << 20)])


def _rreq(dag, ts, region_id):
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts,
                       context={"region_id": region_id})


def test_warm_reads_byte_identical_after_chaos_heal():
    """Txn writes land through raft while the nemesis drops/delays/reorders
    replication — after heal, warm region-cache serving matches the CPU
    pipeline byte for byte, INCLUDING a chaos-forced write-through gap
    (apply_emit_write_delta fault → wt_lost → scan_delta repair)."""
    c = Cluster(3)
    c.run()
    kv = c.raftkv(1)
    rid = FIRST_REGION_ID
    _seed_rows(kv, rid)
    warm = Endpoint(kv, enable_device=True)
    cold = Endpoint(kv, enable_device=False)
    nem = Nemesis(c, seed=77)
    try:
        r0 = warm.handle_request(_rreq(_scan_dag(), 200, rid))
        assert r0.data == cold.handle_request(_rreq(_scan_dag(), 200, rid)).data

        # delay/dup/reorder only: these faults stall and scramble delivery
        # but still deliver eventually through the pump (the txn scheduler's
        # worker is the only thread driving raft here — drop-faults need
        # tick-driven retransmits, which phase 2 of the smoke test covers)
        nem.delay(1, 2, rate=0.4)
        nem.duplicate(rate=0.3)
        nem.reorder(window=3)
        hi = _commit_rows(kv, rid, [
            (3, encode_row(NON_HANDLE, [b"banana", 3, 3])),
            (40, encode_row(NON_HANDLE, [b"cherry", 4, 4])),
        ], ts0=300)
        # chaos also gaps the write-through chain mid-sequence: the next
        # notify is lost, forcing the watermark repair path under real faults
        failpoint.cfg("apply_emit_write_delta", "1*return")
        hi = _commit_rows(kv, rid, [
            (41, encode_row(NON_HANDLE, [b"durian", 5, 5])),
        ], ts0=2000)
        failpoint.remove("apply_emit_write_delta")
        nem.heal()

        r1 = warm.handle_request(_rreq(_scan_dag(), hi + 10, rid))
        assert warm.region_cache.stats.wt_lost >= 1, \
            "the injected emission gap must register as wt_lost"
        assert r1.data == cold.handle_request(_rreq(_scan_dag(), hi + 10, rid)).data
        # post-repair, write-through resumes and stays byte-identical
        hi2 = _commit_rows(kv, rid, [
            (42, encode_row(NON_HANDLE, [b"elder", 6, 6])),
        ], ts0=4000)
        r2 = warm.handle_request(_rreq(_scan_dag(), hi2 + 10, rid))
        assert r2.data == cold.handle_request(_rreq(_scan_dag(), hi2 + 10, rid)).data
    finally:
        nem.heal()
        nem.close()


# ---------------------------------------------------------------------------
# deadline propagation: expired work is shed, counted, never dispatched
# ---------------------------------------------------------------------------

COLS = PRODUCT_COLUMNS


def _local_endpoint(n=64):
    eng = BTreeEngine()
    for i in range(n):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]), 90, 100)
    return Endpoint(LocalEngine(eng), enable_device=True)


def _agg_req(ts=200, deadline=None, region=1):
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Aggregation([], [AggDescriptor("count", None)]),
    ])
    ctx = {"region_id": region, "region_epoch": (1, 1), "apply_index": 7}
    if deadline is not None:
        ctx["deadline"] = deadline
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts, context=ctx)


def test_deadline_expired_request_shed_counted_never_dispatched():
    ep = _local_endpoint()
    ep.handle_request(_agg_req())  # warm the plan + image
    expired = [_agg_req(deadline=time.monotonic() - 0.5) for _ in range(3)]
    shed_c = REGISTRY.counter("tikv_coprocessor_deadline_expired_total")
    batches = REGISTRY.counter("tikv_coprocessor_sched_batches_total")
    reqs_c = REGISTRY.counter("tikv_coprocessor_request_total")
    before = shed_c.get(at="dispatch")
    b_before = sum(batches._values.values())
    r_before = sum(reqs_c._values.values())
    with pytest.raises(DeadlineExceeded):
        ep.handle_batch(expired)
    assert shed_c.get(at="dispatch") == before + 3
    assert sum(batches._values.values()) == b_before, \
        "expired work must never form a device batch"
    assert sum(reqs_c._values.values()) == r_before, \
        "expired work must never be served at all"


def test_deadline_live_requests_still_serve_and_mixed_batches_isolate():
    """A live deadline serves normally; in a mixed batch only the expired
    member errors (per-slot isolation through the scheduler)."""
    ep = _local_endpoint()
    cpu = Endpoint(LocalEngine(ep.engine.kv), enable_device=False)
    want = cpu.handle_request(_agg_req()).data
    r = ep.handle_request(_agg_req(deadline=time.monotonic() + 30))
    assert r.data == want

    from tikv_tpu.copr.scheduler import _Item
    from tikv_tpu.util.retry import deadline_from_context

    reqs = [_agg_req(deadline=time.monotonic() + 30),
            _agg_req(deadline=time.monotonic() - 1),
            _agg_req()]
    items = [_Item(req=q, index=i, deadline=deadline_from_context(q.context))
             for i, q in enumerate(reqs)]
    results, errors = ep.scheduler._serve(items)
    assert results[0] is not None and results[0].data == want
    assert isinstance(errors[1], DeadlineExceeded) and results[1] is None
    assert results[2] is not None and results[2].data == want


def test_batch_with_expired_rider_keeps_sibling_responses():
    """One expired rider must not poison the batch: siblings keep their
    computed responses (no whole-batch per-slot re-serve), the expired slot
    reports DeadlineExceeded and is never dispatched."""
    ep = _local_endpoint()
    cpu = Endpoint(LocalEngine(ep.engine.kv), enable_device=False)
    want = cpu.handle_request(_agg_req()).data
    ep.handle_request(_agg_req())  # warm the plan + image
    reqs = [_agg_req(deadline=time.monotonic() + 30),
            _agg_req(deadline=time.monotonic() - 1),
            _agg_req()]
    reqs_c = REGISTRY.counter("tikv_coprocessor_request_total")
    r_before = sum(reqs_c._values.values())
    results, errors = ep.handle_batch_errors(reqs)
    assert errors[0] is None and results[0].data == want
    assert isinstance(errors[1], DeadlineExceeded) and results[1] is None
    assert errors[2] is None and results[2].data == want
    # each live rider was served exactly once; a poisoned batch (whole-batch
    # per-slot re-serve) would re-run them, and the expired slot must never
    # be served at all
    assert sum(reqs_c._values.values()) == r_before + 2


def test_scheduler_execute_sheds_expired_on_admission():
    ep = _local_endpoint()
    ep.scheduler.start()
    try:
        with pytest.raises(DeadlineExceeded):
            ep.scheduler.execute(_agg_req(deadline=time.monotonic() - 1))
        r = ep.scheduler.execute(_agg_req(deadline=time.monotonic() + 30))
        assert r.data  # live deadline still serves
    finally:
        ep.scheduler.stop()


def test_busy_reject_carries_retry_after_honored_by_policy():
    """Queue-full admission with busy_reject raises ServerIsBusy with a
    retry-after hint; the shared retry policy sleeps at least that long."""
    ep = _local_endpoint()
    ep.scheduler.cfg = SchedulerConfig(max_queue=0, busy_reject=True,
                                       busy_retry_after_s=0.2)
    ep.scheduler.start()
    try:
        shed = REGISTRY.counter("tikv_coprocessor_sched_shed_total")
        busy_before = shed.get(reason="busy_reject")
        direct_before = shed.get(reason="queue_full")
        with pytest.raises(ServerBusyError) as ei:
            ep.scheduler.execute(_agg_req())
        assert ei.value.retry_after_s == pytest.approx(0.2)
        # a rejection is neither served nor direct: its own shed reason,
        # NOT queue_full (which means "served on the caller's thread")
        assert shed.get(reason="busy_reject") == busy_before + 1
        assert shed.get(reason="queue_full") == direct_before

        slept = []
        attempts = [0]

        def submit():
            attempts[0] += 1
            if attempts[0] == 1:
                return ep.scheduler.execute(_agg_req())
            # capacity came back (queue un-capped) on the retry
            ep.scheduler.cfg = SchedulerConfig()
            return ep.scheduler.execute(_agg_req())

        r = retry.call(submit, site="test.busy", sleep=slept.append)
        assert r.data
        assert slept and slept[0] >= 0.2, "retry-after hint must be honored"
    finally:
        ep.scheduler.stop()


# ---------------------------------------------------------------------------
# device-path circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_trips_unary_to_cpu_and_restores_via_probe(monkeypatch):
    clk = [1000.0]
    breaker = DeviceCircuitBreaker(
        BreakerConfig(threshold=2, cooldown_s=5.0), clock=lambda: clk[0])
    eng = BTreeEngine()
    for i in range(32):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]), 90, 100)
    ep = Endpoint(LocalEngine(eng), enable_device=True, breaker=breaker)
    cpu = Endpoint(LocalEngine(eng), enable_device=False)
    want = cpu.handle_request(_agg_req()).data

    ev_c = REGISTRY.counter("tikv_coprocessor_breaker_event_total")
    fb_c = REGISTRY.counter("tikv_coprocessor_path_fallback_total")
    trips0 = ev_c.get(path="unary", event="trip")
    probes0 = ev_c.get(path="unary", event="probe")
    restores0 = ev_c.get(path="unary", event="restore")

    import tikv_tpu.copr.jax_eval as je

    real_run = je.JaxDagEvaluator.run

    def boom(self, *a, **k):
        raise RuntimeError("injected device fault")

    monkeypatch.setattr(je.JaxDagEvaluator, "run", boom)
    # two consecutive faults trip the path (each still served by CPU)
    for _ in range(2):
        r = ep.handle_request(_agg_req())
        assert not r.from_device and r.data == want
    assert breaker.state_of("unary") == "open"
    assert ev_c.get(path="unary", event="trip") == trips0 + 1

    # while open: CPU serves WITHOUT touching the (still broken) device
    open_before = fb_c.get(path="unary", cause="breaker_open")
    r = ep.handle_request(_agg_req())
    assert not r.from_device and r.data == want
    assert fb_c.get(path="unary", cause="breaker_open") == open_before + 1

    # device "repaired"; cooldown elapses; the half-open probe restores
    monkeypatch.setattr(je.JaxDagEvaluator, "run", real_run)
    clk[0] += 10.0
    r = ep.handle_request(_agg_req())
    assert r.from_device and r.data == want
    assert breaker.state_of("unary") == "closed"
    assert ev_c.get(path="unary", event="probe") == probes0 + 1
    assert ev_c.get(path="unary", event="restore") == restores0 + 1


def test_breaker_failed_probe_reopens_with_longer_cooldown(monkeypatch):
    clk = [0.0]
    b = DeviceCircuitBreaker(
        BreakerConfig(threshold=1, cooldown_s=2.0, cooldown_multiplier=2.0),
        clock=lambda: clk[0])
    b.record_failure("x")               # trip #1: cooldown 2s
    assert not b.allow("x")
    clk[0] = 2.5
    assert b.allow("x")                 # half-open probe admitted
    assert not b.allow("x")             # ...exactly one
    b.record_failure("x")               # probe fails: trip #2, cooldown 4s
    clk[0] = 5.0
    assert not b.allow("x"), "cooldown must have doubled"
    clk[0] = 7.0
    assert b.allow("x")
    b.record_success("x")
    assert b.state_of("x") == "closed"
    assert b.allow("x") and b.allow("x"), "closed path admits everyone"


def test_breaker_trips_xregion_batches_to_per_request(monkeypatch):
    """Repeated cross-region launch faults trip the xregion path: batches
    shed to per-request serving (bytes still correct), and the breaker
    holds the path open."""
    ep = _local_endpoint()
    ep.breaker = DeviceCircuitBreaker(BreakerConfig(threshold=2, cooldown_s=60.0))
    cpu = Endpoint(LocalEngine(ep.engine.kv), enable_device=False)
    # two regions, same plan → xregion batch shape
    def reqs():
        return [_agg_req(region=1), _agg_req(region=2)]

    want = [cpu.handle_request(q).data for q in reqs()]
    ep.handle_batch(reqs())  # warm the images so xregion actually launches

    import tikv_tpu.copr.jax_eval as je

    def boom(*a, **k):
        raise RuntimeError("injected xregion fault")

    monkeypatch.setattr(je, "launch_xregion_cached", boom)
    for _ in range(2):
        got = ep.handle_batch(reqs())
        assert [g.data for g in got] == want  # per-request fallback serves
    assert ep.breaker.state_of("xregion") == "open"
    shed_c = REGISTRY.counter("tikv_coprocessor_sched_shed_total")
    before = shed_c.get(reason="breaker_open")
    got = ep.handle_batch(reqs())
    assert [g.data for g in got] == want
    assert shed_c.get(reason="breaker_open") >= before + 1, \
        "an open breaker sheds the batch before launching"


def test_zone_real_arg_decline_counted_per_cause():
    """The VERDICT-weak-#6 case: a REAL aggregate argument declines the
    zone path — now visible as path_fallback{path=zone, cause=real_arg}."""
    from tikv_tpu.copr.rpn import col

    eng = BTreeEngine()
    for i in range(32):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]), 90, 100)
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    # an explicit REAL aggregate argument: sum(cast_int_real(count)) — the
    # device path takes it, the zone path must decline (float sum order)
    from tikv_tpu.copr.rpn import call as rcall

    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Aggregation([col(1)], [AggDescriptor("sum", rcall("cast_int_real", col(2)))]),
    ])
    req = CoprRequest(103, dag, [record_range(TABLE_ID)], 200,
                      context={"region_id": 1, "region_epoch": (1, 1), "apply_index": 7})
    c = REGISTRY.counter("tikv_coprocessor_path_fallback_total")
    before = c.get(path="zone", cause="real_arg")
    ep.handle_request(req)  # warm fill
    ep.handle_request(req)  # warm serve: zone probe runs and declines
    assert c.get(path="zone", cause="real_arg") >= before + 1


# ---------------------------------------------------------------------------
# full nemesis schedules (slow: real sockets, wall-clock pacing)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_full_random_schedule_over_sockets():
    """Seeded random nemesis schedule over the networked ServerCluster:
    acked writes survive every step and the cluster converges post-heal."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.server.cluster import ServerCluster

    c = ServerCluster(3, pd=MockPd())
    c.run()
    nem = Nemesis(c, seed=2024)
    acked = {}
    try:
        steps = nem.random_steps(6)
        for si, (op, kw) in enumerate(steps):
            fault = nem.apply_step(op, kw)
            for i in range(3):
                k = b"s%d-%d" % (si, i)
                try:
                    c.must_put(k, b"v", timeout=20.0)
                    acked[k] = b"v"
                except Exception:
                    pass  # unacked writes carry no guarantee
            time.sleep(0.2)
            if fault is not None:
                nem.remove(fault)
            # crash_restart steps toggle; make sure a crashed node returns
        nem.heal()
        time.sleep(0.5)
        for k, v in acked.items():
            assert c.must_get(k, timeout=20.0) == v, k
        for k, v in acked.items():
            for sid in c.nodes:
                c.wait_get_on_store(sid, k, v, timeout=20.0)
    finally:
        nem.heal()
        nem.close()
        c.shutdown()


@pytest.mark.slow
def test_asymmetric_partition_over_sockets():
    """The half-open link: leader's outbound cut while inbound flows — the
    majority side recovers leadership and no acked write is lost."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.server.cluster import ServerCluster

    c = ServerCluster(3, pd=MockPd())
    c.run()
    nem = Nemesis(c, seed=5)
    try:
        c.must_put(b"pre", b"1")
        sid = c.wait_leader(FIRST_REGION_ID).store.store_id
        others = [s for s in c.nodes if s != sid]
        nem.partition({sid}, others, symmetric=False)
        time.sleep(1.0)
        c.must_put(b"during", b"2", timeout=20.0)
        nem.heal()
        for s in c.nodes:
            c.wait_get_on_store(s, b"during", b"2", timeout=20.0)
        assert c.must_get(b"pre", timeout=20.0) == b"1"
    finally:
        nem.heal()
        nem.close()
        c.shutdown()
