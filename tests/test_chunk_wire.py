"""Columnar chunk wire format end-to-end (ISSUE 14, docs/wire_path.md
"Columnar chunk responses").

* differential byte-identity: TypeChunk responses decode to EXACTLY the
  datum-path rows (the CPU oracle) for every executor shape
  (scan/selection/agg/topN) × both row formats (rowv1/rowv2) × both
  residencies (encoded/decoded region images), including streamed frames
  and multi-region batched frames;
* negotiation: datum stays the default, unsupported field types decline to
  datum with a counted cause — never an error — and the service parse memo
  keys datum and chunk variants of one plan separately;
* zero-copy egress: each encoded column slab ≥ PASSTHROUGH_MIN rides the
  response frame as its OWN memoryview part through ``wire.dumps_parts``;
* scheduler: chunk and datum riders never share a response slot, and
  socket-coalesced chunk serving matches serial chunk serving and the
  oracle.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_engine
from fixtures import put_committed

from tikv_tpu.copr import chunk_codec
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import (
    ENC_TYPE_CHUNK,
    ENC_TYPE_DATUM,
    Aggregation,
    DagRequest,
    Selection,
    SelectResponse,
    TableScan,
    TopN,
    chunk_output_field_types,
    datum_twin,
    decode_wire_response,
    negotiate_encode_type,
    response_data,
)
from tikv_tpu.copr.dag_wire import dag_to_wire
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint, resolve_encode_type
from tikv_tpu.copr.region_cache import RegionColumnCache
from tikv_tpu.copr.rpn import call as rpn_call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.server import wire
from tikv_tpu.server.server import Client, Server
from tikv_tpu.server.service import KvService
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.storage import Storage
from tikv_tpu.util.metrics import REGISTRY

CHUNK_C = REGISTRY.counter("tikv_wire_chunk_total")

_COLUMNS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.varchar()),
    ColumnInfo(3, FieldType.int64()),
    ColumnInfo(4, FieldType.decimal_type(2)),
]


def _rows(n: int):
    rng = np.random.default_rng(5)
    out = []
    for i in range(n):
        name = None if rng.random() < 0.1 else b"item-%d" % (i % 7)
        cnt = None if rng.random() < 0.1 else int(rng.integers(-500, 500))
        price = None if rng.random() < 0.1 else int(rng.integers(0, 10**6))
        out.append((i, name, cnt, price))
    return out


def _engine(rows, v2: bool) -> BTreeEngine:
    eng = BTreeEngine()
    non_handle = _COLUMNS[1:]
    for rid, name, cnt, price in rows:
        raw = (encode_row_v2(non_handle, [name, cnt, price]) if v2
               else encode_row(non_handle, [name, cnt, price]))
        put_committed(eng, record_key(TABLE_ID, rid), raw, 90, 100)
    return eng


def _plans():
    return {
        "scan": [TableScan(TABLE_ID, _COLUMNS)],
        "selection": [TableScan(TABLE_ID, _COLUMNS),
                      Selection([rpn_call("lt", col(2), const_int(100))])],
        "agg": [TableScan(TABLE_ID, _COLUMNS),
                Aggregation([col(1)], [AggDescriptor("sum", col(2)),
                                       AggDescriptor("count", None)])],
        "topn": [TableScan(TABLE_ID, _COLUMNS), TopN([(col(2), True)], 9)],
    }


def _req(execs, enc, **ctx):
    return CoprRequest(
        103, DagRequest(executors=list(execs), encode_type=enc),
        [record_range(TABLE_ID)], 150,
        context={"region_id": 1, **ctx})


# ---------------------------------------------------------------------------
# differential byte-identity: executors × row formats × residency
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
@pytest.mark.parametrize("device", [False, True], ids=["cpu", "device"])
def test_chunk_rows_equal_datum_oracle_every_executor(v2, device):
    eng = LocalEngine(_engine(_rows(300), v2))
    ep = Endpoint(eng, enable_device=device)
    ep_oracle = Endpoint(eng, enable_device=False)
    for name, execs in _plans().items():
        rd = ep_oracle.handle_request(_req(execs, ENC_TYPE_DATUM))
        rc = ep.handle_request(_req(execs, ENC_TYPE_CHUNK))
        assert rc.encode_type == ENC_TYPE_CHUNK, name
        dag_c = DagRequest(executors=list(execs), encode_type=ENC_TYPE_CHUNK)
        rows_c = decode_wire_response(
            {"data_parts": rc.data_parts or [rc.data], "encode_type": 1},
            dag_c).iter_rows()
        rows_d = SelectResponse.decode(rd.data).iter_rows()
        assert rows_c == rows_d, name


def test_chunk_identity_encoded_and_decoded_residency():
    """Warm region images in BOTH residencies (compressed encoded columns
    and plain decoded) serve chunk responses identical to the datum oracle
    — the EncodedColumn.take late-materialization path included."""
    # low-cardinality name column → sorted dictionary; narrow cnt → bitpack
    eng = BTreeEngine()
    non_handle = _COLUMNS[1:]
    rng = np.random.default_rng(11)
    for i in range(400):
        vals = [b"n%d" % (i % 5), int(rng.integers(0, 50)),
                int(rng.integers(0, 1000))]
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(non_handle, vals), 90, 100)
    oracle_ep = Endpoint(LocalEngine(eng), enable_device=False)
    for encode_columns in (True, False):
        ep = Endpoint(LocalEngine(eng), enable_device=True,
                      region_cache=RegionColumnCache(
                          encode_columns=encode_columns))
        for name, execs in _plans().items():
            ctx = {"region_epoch": (1, 1), "apply_index": 7}
            ep.handle_request(_req(execs, ENC_TYPE_CHUNK, **ctx))  # fill
            rc = ep.handle_request(_req(execs, ENC_TYPE_CHUNK, **ctx))
            rd = oracle_ep.handle_request(_req(execs, ENC_TYPE_DATUM))
            dag_c = DagRequest(executors=list(execs),
                               encode_type=ENC_TYPE_CHUNK)
            rows_c = decode_wire_response(
                {"data_parts": rc.data_parts or [rc.data], "encode_type": 1},
                dag_c).iter_rows()
            assert rows_c == SelectResponse.decode(rd.data).iter_rows(), (
                name, encode_columns)
        if encode_columns:
            [img] = ep.region_cache._images.values()
            assert img.encodings, "fixture must actually encode columns"


def test_device_and_cpu_chunk_bytes_identical():
    """The chunk byte-identity contract mirrors the datum one: device and
    CPU pipelines emit the same chunk bytes for the same plan."""
    eng = LocalEngine(_engine(_rows(200), False))
    ep_dev = Endpoint(eng, enable_device=True)
    ep_cpu = Endpoint(eng, enable_device=False)
    for name, execs in _plans().items():
        a = ep_dev.handle_request(_req(execs, ENC_TYPE_CHUNK))
        b = ep_cpu.handle_request(_req(execs, ENC_TYPE_CHUNK))
        assert a.data == b.data, name


# ---------------------------------------------------------------------------
# negotiation: defaults, declines, memo
# ---------------------------------------------------------------------------


def test_datum_stays_default():
    ep = Endpoint(LocalEngine(product_engine()), enable_device=False)
    r = ep.handle_request(_req([TableScan(TABLE_ID, PRODUCT_COLUMNS)],
                               ENC_TYPE_DATUM))
    assert r.encode_type == ENC_TYPE_DATUM
    assert r.data_parts is not None  # frame parts exist either way
    # and the wire dict for a datum response has data, not parts
    svc = KvService(Storage(engine=LocalEngine(product_engine())),
                    Endpoint(LocalEngine(product_engine()),
                             enable_device=False))
    out = svc.coprocessor({"dag": dag_to_wire(DagRequest(
        executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])),
        "ranges": [list(record_range(TABLE_ID))], "start_ts": 150})
    assert "data" in out and "encode_type" not in out


def test_unsupported_field_type_declines_to_datum_with_cause():
    enum_cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.enum_type([b"a", b"b"])),
    ]
    dag = DagRequest(executors=[TableScan(TABLE_ID, enum_cols)],
                     encode_type=ENC_TYPE_CHUNK)
    eff, cause = negotiate_encode_type(dag)
    assert cause == "field_type"
    assert eff.encode_type == ENC_TYPE_DATUM
    assert eff.executors is dag.executors  # the twin shares the plan
    # request-level: downgrade in place + counted once
    before = CHUNK_C.get(outcome="decline", cause="field_type")
    req = CoprRequest(103, dag, [record_range(TABLE_ID)], 150,
                      context={"region_id": 1})
    resolve_encode_type(req)
    resolve_encode_type(req)  # idempotent: the marker stops double counting
    assert req.dag.encode_type == ENC_TYPE_DATUM
    assert req.context["chunk_declined"] == "field_type"
    assert CHUNK_C.get(outcome="decline", cause="field_type") == before + 1
    # and a declined request SERVES (datum bytes), never errors
    eng = BTreeEngine()
    put_committed(eng, record_key(TABLE_ID, 1),
                  encode_row(enum_cols[1:], [1]), 90, 100)
    ep = Endpoint(LocalEngine(eng), enable_device=False)
    r = ep.handle_request(CoprRequest(
        103, DagRequest(executors=[TableScan(TABLE_ID, enum_cols)],
                        encode_type=ENC_TYPE_CHUNK),
        [record_range(TABLE_ID)], 150, context={"region_id": 1}))
    assert r.encode_type == ENC_TYPE_DATUM
    assert SelectResponse.decode(r.data).iter_rows()


def test_wide_decimal_declines():
    cols = [ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
            ColumnInfo(2, FieldType.decimal_type(25))]
    dag = DagRequest(executors=[TableScan(TABLE_ID, cols)],
                     encode_type=ENC_TYPE_CHUNK)
    assert chunk_output_field_types(dag) is None
    _eff, cause = negotiate_encode_type(dag)
    assert cause == "field_type"


def test_empty_output_offsets_decline_instead_of_error():
    """output_offsets=[] (zero output columns) has no chunk representation
    (no column to carry the row count) — it must decline to datum, and the
    declined request must SERVE (review finding: _emit used to IndexError)."""
    dag = DagRequest(executors=[TableScan(TABLE_ID, _COLUMNS)],
                     output_offsets=[], encode_type=ENC_TYPE_CHUNK)
    assert chunk_output_field_types(dag) is None
    _eff, cause = negotiate_encode_type(dag)
    assert cause == "field_type"
    ep = Endpoint(LocalEngine(_engine(_rows(20), False)), enable_device=False)
    r = ep.handle_request(CoprRequest(
        103, DagRequest(executors=[TableScan(TABLE_ID, _COLUMNS)],
                        output_offsets=[], encode_type=ENC_TYPE_CHUNK),
        [record_range(TABLE_ID)], 150, context={"region_id": 1}))
    assert r.encode_type == ENC_TYPE_DATUM
    assert len(SelectResponse.decode(r.data).iter_rows()) == 20


def test_dict_rewrite_rung_declines_chunk_requests():
    """Review finding: the code-space rewrite rung flips a dict bytes
    column's declared type to LONGLONG, so a chunk response encoded off the
    REWRITTEN schema would ship raw dictionary codes the client cannot
    decode against the plan it sent.  Chunk-negotiated requests must skip
    the rung (counted decline) and still serve byte-correct chunk rows
    through the CPU pipeline — identical to the datum oracle's values."""
    from tikv_tpu.copr import encoding as _encoding

    eng = BTreeEngine()
    non_handle = _COLUMNS[1:]
    for i in range(200):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(non_handle, [b"n%d" % (i % 4), i % 50, i]),
                      90, 100)
    ep = Endpoint(LocalEngine(eng), enable_device=True,
                  region_cache=RegionColumnCache(encode_columns=True))
    execs = [TableScan(TABLE_ID, _COLUMNS),
             Selection([rpn_call("eq", col(1), _bytes_const(b"n2"))])]
    ctx = {"region_epoch": (1, 1), "apply_index": 7}
    # warm the image so the rewrite rung is reachable at all
    ep.handle_request(_req(execs, ENC_TYPE_DATUM, **ctx))
    decline_c = REGISTRY.counter("tikv_coprocessor_encoded_decline_total")
    before_decline = decline_c.get(path="rewrite", cause="chunk_encoding")
    rd = ep.handle_request(_req(execs, ENC_TYPE_DATUM, **ctx))
    rc = ep.handle_request(_req(execs, ENC_TYPE_CHUNK, **ctx))
    assert rc.encode_type == ENC_TYPE_CHUNK
    assert decline_c.get(path="rewrite", cause="chunk_encoding") \
        > before_decline
    dag_c = DagRequest(executors=list(execs), encode_type=ENC_TYPE_CHUNK)
    rows_c = decode_wire_response(
        {"data_parts": rc.data_parts or [rc.data], "encode_type": 1},
        dag_c).iter_rows()
    rows_d = SelectResponse.decode(rd.data).iter_rows()
    assert rows_c == rows_d
    assert rows_c and all(isinstance(r[1], bytes) for r in rows_c), \
        "the bytes column must decode as bytes, not dictionary codes"


def _bytes_const(v: bytes):
    from tikv_tpu.copr.datatypes import EvalType
    from tikv_tpu.copr.rpn import Constant

    return Constant(v, EvalType.BYTES)


def test_parse_memo_keys_datum_and_chunk_separately():
    svc = KvService(Storage(engine=LocalEngine(product_engine())),
                    Endpoint(LocalEngine(product_engine()),
                             enable_device=False))
    plain = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    chunky = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)],
                        encode_type=ENC_TYPE_CHUNK)
    a = svc._parse_dag_wire(dag_to_wire(plain))
    b = svc._parse_dag_wire(dag_to_wire(chunky))
    assert a is not b
    assert a.encode_type == ENC_TYPE_DATUM
    assert b.encode_type == ENC_TYPE_CHUNK
    # repeat parses hit their own memo entries
    assert svc._parse_dag_wire(dag_to_wire(plain)) is a
    assert svc._parse_dag_wire(dag_to_wire(chunky)) is b
    # the datum twin of the chunk plan serializes to the plain plan's bytes
    assert dag_to_wire(datum_twin(chunky)) == dag_to_wire(plain)


# ---------------------------------------------------------------------------
# zero-copy egress
# ---------------------------------------------------------------------------


def test_chunk_column_slab_is_own_frame_part():
    """A ≥PASSTHROUGH_MIN column slab must pass through ``dumps_parts`` as
    its own memoryview over the ENCODER'S buffer — the whole reason the
    response ships ``data_parts``."""
    rows = [(i, b"x" * 40, i, i * 100) for i in range(200)]
    ep = Endpoint(LocalEngine(_engine(rows, False)), enable_device=False)
    r = ep.handle_request(_req([TableScan(TABLE_ID, _COLUMNS)],
                               ENC_TYPE_CHUNK))
    big = [p for p in r.data_parts if len(p) >= wire.PASSTHROUGH_MIN]
    assert big, "fixture must produce at least one large column slab"
    resp_dict = {"data_parts": r.data_parts, "encode_type": 1}
    parts = wire.dumps_parts([7, resp_dict])
    views = [p for p in parts if isinstance(p, memoryview)]
    for slab in big:
        assert any(v.obj is slab for v in views), \
            "column slab was copied instead of passed through"
    # and the parts join back to the canonical encode() bytes
    joined = wire.loads(b"".join(bytes(p) for p in parts))
    assert response_data(joined[1]) == r.data


def test_bytes_view_payloads_are_read_only():
    """``loads(bytes_view=True)`` hands out memoryviews that alias the
    shared frame buffer: the read-only contract (docs/wire_path.md
    §zero-copy) is enforced, not advisory — writing through one raises."""
    payload = bytes(range(256)) * 16  # ≥ PASSTHROUGH_MIN
    frame = wire.dumps({"data": payload, "small": b"tiny"})
    out = wire.loads(bytearray(frame), bytes_view=True)
    mv = out["data"]
    assert isinstance(mv, memoryview) and mv.readonly
    with pytest.raises(TypeError):
        mv[0] = 0
    with pytest.raises(TypeError):
        mv[1:3] = b"xx"
    assert bytes(mv) == payload
    # below-threshold payloads keep the plain-bytes contract
    assert out["small"] == b"tiny"
    # default mode never hands out views at all
    assert isinstance(wire.loads(frame)["data"], bytes)


# ---------------------------------------------------------------------------
# streaming
# ---------------------------------------------------------------------------


def test_streaming_chunk_frames_match_unary_rows():
    eng = LocalEngine(_engine(_rows(300), False))
    ep = Endpoint(eng, enable_device=False)
    execs = [TableScan(TABLE_ID, _COLUMNS)]
    dag_c = DagRequest(executors=execs, encode_type=ENC_TYPE_CHUNK)
    frames = list(ep.handle_streaming_request(
        _req(execs, ENC_TYPE_CHUNK), rows_per_stream=64))
    assert len(frames) > 1, "stream must actually split into frames"
    rows = []
    fts = chunk_output_field_types(dag_c)
    for f in frames:
        assert f.encode_type == ENC_TYPE_CHUNK
        sr = SelectResponse.decode(
            b"".join(bytes(p) for p in f.data_parts), encode_type=1)
        rows.extend(sr.iter_rows(field_types=fts))
    unary = ep.handle_request(_req(execs, ENC_TYPE_DATUM))
    assert rows == SelectResponse.decode(unary.data).iter_rows()


def test_socket_stream_chunk_frames():
    eng = LocalEngine(_engine(_rows(256), False))
    svc = KvService(Storage(engine=eng), Endpoint(eng, enable_device=False))
    srv = Server(svc)
    srv.start()
    try:
        c = Client(*srv.addr)
        dag_c = DagRequest(executors=[TableScan(TABLE_ID, _COLUMNS)],
                           encode_type=ENC_TYPE_CHUNK)
        items = list(c.call_stream("coprocessor_stream", {
            "dag": dag_to_wire(dag_c),
            "ranges": [list(record_range(TABLE_ID))],
            "start_ts": 150, "rows_per_stream": 64,
        }))
        assert len(items) > 1
        rows = []
        for it in items:
            assert it.get("encode_type") == 1
            rows.extend(decode_wire_response(it, dag_c).iter_rows())
        c.close()
        ep = Endpoint(eng, enable_device=False)
        unary = ep.handle_request(_req([TableScan(TABLE_ID, _COLUMNS)],
                                       ENC_TYPE_DATUM))
        assert rows == SelectResponse.decode(unary.data).iter_rows()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# multi-region batched frames + scheduler
# ---------------------------------------------------------------------------


def _regioned_engine(regions: int, rows_per: int):
    eng = BTreeEngine()
    rng = np.random.default_rng(13)
    non_handle = _COLUMNS[1:]
    for i in range(regions * rows_per):
        vals = [b"n%d" % (i % 5), int(rng.integers(0, 100)),
                int(rng.integers(0, 100000))]
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(non_handle, vals), 90, 100)
    return eng


def _region_sub(dag_wire_dict, r: int, rows_per: int, **ctx):
    lo = record_key(TABLE_ID, r * rows_per)
    hi = record_key(TABLE_ID, (r + 1) * rows_per)
    return {"dag": dag_wire_dict, "ranges": [[lo, hi]], "start_ts": 150,
            "context": {"region_id": r + 1, "region_epoch": (1, 1),
                        "apply_index": 7, **ctx}}


def _batch_agg(enc):
    return DagRequest(executors=[
        TableScan(TABLE_ID, _COLUMNS),
        Selection([rpn_call("lt", col(2), const_int(60))]),
        Aggregation([], [AggDescriptor("sum", col(2)),
                         AggDescriptor("count", None)]),
    ], encode_type=enc)


def test_multi_region_batch_single_frame_chunk_payloads():
    """coprocessor_batch: all regions answered in ONE frame with per-region
    chunk payloads (the scheduler's vmapped cross-region batch behind it),
    and per-region error isolation — an expired rider reports typed while
    its siblings keep their chunk payloads."""
    regions, rows_per = 4, 200
    eng = LocalEngine(_regioned_engine(regions, rows_per))
    ep = Endpoint(eng, enable_device=True)
    svc = KvService(Storage(engine=eng), ep)
    srv = Server(svc)
    srv.start()
    try:
        c = Client(*srv.addr)
        wire_dag = dag_to_wire(_batch_agg(ENC_TYPE_CHUNK))
        subs = [_region_sub(wire_dag, r, rows_per) for r in range(regions)]
        c.call("coprocessor_batch", {"requests": subs}, timeout=60.0)  # warm
        batches = REGISTRY.counter("tikv_coprocessor_sched_batches_total")
        before = batches.get(kind="xregion")
        r = c.call("coprocessor_batch", {"requests": subs}, timeout=60.0)
        assert batches.get(kind="xregion") > before, \
            "warm same-sig regions must ride ONE vmapped batch"
        assert len(r["responses"]) == regions
        dag_c = _batch_agg(ENC_TYPE_CHUNK)
        oracle_ep = Endpoint(eng, enable_device=False)
        for i, sub in enumerate(r["responses"]):
            assert sub.get("encode_type") == 1, sub.keys()
            rows_c = decode_wire_response(sub, dag_c).iter_rows()
            od = oracle_ep.handle_request(CoprRequest(
                103, _batch_agg(ENC_TYPE_DATUM),
                [tuple(rng) for rng in subs[i]["ranges"]], 150,
                context=dict(subs[i]["context"])))
            assert rows_c == SelectResponse.decode(od.data).iter_rows(), i
        # per-region error isolation: one rider expired in queue
        dead = [_region_sub(wire_dag, r, rows_per) for r in range(regions)]
        dead[1]["context"]["timeout_ms"] = 0
        r = c.call("coprocessor_batch", {"requests": dead}, timeout=60.0)
        assert r["responses"][1].get("error", {}).get("deadline_exceeded") is not None
        for i in (0, 2, 3):
            assert r["responses"][i].get("encode_type") == 1
            assert decode_wire_response(r["responses"][i], dag_c).iter_rows()
        c.close()
    finally:
        srv.stop()


def test_scheduler_never_shares_slot_across_encodings():
    """Identical plan + region + start_ts in BOTH encodings through one
    run_batch: responses must come back in their own encodings (a shared
    slot would hand one encoding's bytes to the other's rider)."""
    regions, rows_per = 2, 150
    eng = LocalEngine(_regioned_engine(regions, rows_per))
    ep = Endpoint(eng, enable_device=True)
    reqs = []
    for enc in (ENC_TYPE_DATUM, ENC_TYPE_CHUNK):
        for r in range(regions):
            lo = record_key(TABLE_ID, r * rows_per)
            hi = record_key(TABLE_ID, (r + 1) * rows_per)
            reqs.append(CoprRequest(103, _batch_agg(enc), [(lo, hi)], 150,
                                    context={"region_id": r + 1,
                                             "region_epoch": (1, 1),
                                             "apply_index": 7}))
    ep.handle_batch(list(reqs))  # warm
    results = ep.handle_batch(list(reqs))
    dag_c = _batch_agg(ENC_TYPE_CHUNK)
    for i, r in enumerate(results):
        want_chunk = i >= regions
        assert (r.encode_type == ENC_TYPE_CHUNK) == want_chunk, i
    # pairwise value identity across encodings per region
    for r in range(regions):
        rows_d = SelectResponse.decode(results[r].data).iter_rows()
        rows_c = decode_wire_response(
            {"data_parts": results[regions + r].data_parts
             or [results[regions + r].data], "encode_type": 1},
            dag_c).iter_rows()
        assert rows_d == rows_c


def test_socket_coalesced_chunk_matches_serial_and_counts():
    """Concurrent chunk requests through the continuous lanes: responses
    byte-match serial chunk serving, and tikv_wire_chunk_total counts the
    served outcome."""
    regions, rows_per = 3, 150
    eng = LocalEngine(_regioned_engine(regions, rows_per))
    ep = Endpoint(eng, enable_device=True)
    svc = KvService(Storage(engine=eng), ep)
    srv = Server(svc)
    srv.start()
    ep.scheduler.start()
    try:
        wire_dag = dag_to_wire(_batch_agg(ENC_TYPE_CHUNK))
        reqs = [_region_sub(wire_dag, r, rows_per)
                for r in range(regions) for _ in range(3)]
        before = CHUNK_C.get(outcome="chunk", cause="")
        conns = [Client(*srv.addr) for _ in range(3)]
        results: list = [None] * len(reqs)
        errs: list = []

        def worker(ci):
            try:
                for i in range(ci, len(reqs), len(conns)):
                    results[i] = conns[ci].call("coprocessor", reqs[i],
                                                timeout=120.0)
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        ts = [threading.Thread(target=worker, args=(ci,))
              for ci in range(len(conns))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for c in conns:
            c.close()
        assert not errs, errs
        assert CHUNK_C.get(outcome="chunk", cause="") - before == len(reqs)
        ep.scheduler.stop()
        for i, r in enumerate(reqs):
            assert results[i].get("encode_type") == 1
            serial = svc.coprocessor(dict(r))
            assert response_data(results[i]) == response_data(serial), i
    finally:
        ep.scheduler.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# cluster client helper
# ---------------------------------------------------------------------------


def test_server_cluster_chunk_opt_in():
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.raftkv import RaftKv
    from tikv_tpu.server.cluster import ServerCluster
    from tikv_tpu.storage.engine import CF_WRITE, WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    c = ServerCluster(1, pd=MockPd(), full_service=True)
    try:
        c.run()
        leader = c.wait_leader(1)
        wb = WriteBatch()
        non_handle = [ci for ci in PRODUCT_COLUMNS if not ci.is_pk_handle]
        for i in range(24):
            k = Key.from_raw(record_key(TABLE_ID, i))
            w = Write(WriteType.PUT, 90,
                      short_value=encode_row(non_handle,
                                             [b"apple", i % 23, 100 + i]))
            wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
        RaftKv(leader.store).write({"region_id": 1}, wb)
        dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
        ranges = [record_range(TABLE_ID)]
        rows_d = c.coprocessor_rows(1, dag, ranges, 150,
                                    context={"region_id": 1})
        rows_c = c.coprocessor_rows(1, dag, ranges, 150, chunk=True,
                                    context={"region_id": 1})
        assert len(rows_d) == 24
        assert rows_d == rows_c
    finally:
        c.shutdown()
