"""resolved-ts advance over the wire (VERDICT r4 item 5).

Three OS-process stores with LEASES DISABLED (TIKV_TPU_DISABLE_LEASES=1):
watermark liveness then rests entirely on the check_leader RPC fan-out
(advance.rs:75,211 role) — the leader store confirms its claim against a
peer-store quorum and disseminates (resolved_ts, apply_index) pairs, which
is what lets a FOLLOWER store serve stale reads.

The scenario is the reference's core promise: hold a lock on the leader,
watch follower stale reads advance to lock_ts-1 (reads below succeed, reads
above refuse with DataNotReady), then commit and watch the watermark resume
past it.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIRST_REGION_ID = 1


def _spawn(store_id: int, pd_addr, data_dir: str, disable_leases: bool = True):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    if disable_leases:
        env["TIKV_TPU_DISABLE_LEASES"] = "1"
    env["TIKV_TPU_RESOLVED_TS_INTERVAL"] = "0.3"
    return subprocess.Popen(
        [sys.executable, "-m", "tikv_tpu.server.standalone",
         "--store-id", str(store_id), "--pd", f"{pd_addr[0]}:{pd_addr[1]}",
         "--dir", data_dir, "--expect-stores", "3"],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def test_follower_stale_reads_via_check_leader(tmp_path):
    _run_scenario(tmp_path, disable_leases=True)


def test_follower_stale_reads_with_leases_on(tmp_path):
    """Same scenario in the DEFAULT configuration: leases confirm
    leadership, but the watermark still reaches follower stores because the
    check_leader round also runs as the dissemination carrier."""
    _run_scenario(tmp_path, disable_leases=False)


def _run_scenario(tmp_path, disable_leases: bool):
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_multiprocess_cluster import _ClusterClient, _wait_ready

    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.pd.service import PdService
    from tikv_tpu.server.server import Client, Server

    pd = MockPd()
    pd_server = Server(PdService(pd))
    pd_server.start()
    procs, client, fol_client = {}, None, None
    try:
        for sid in (1, 2, 3):
            procs[sid] = _spawn(sid, pd_server.addr, str(tmp_path / f"s{sid}"),
                                disable_leases=disable_leases)
        for sid in (1, 2, 3):
            _wait_ready(procs[sid])
        client = _ClusterClient(pd)
        client.put(b"row1", b"v1")
        assert client.get(b"row1") == b"v1"

        leader_sid = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and leader_sid is None:
            leader_sid = pd.leader_of(FIRST_REGION_ID)
            time.sleep(0.1)
        follower_sid = next(s for s in (1, 2, 3) if s != leader_sid)
        addr = pd.get_store_addr(follower_sid)
        fol_client = Client(addr[0], addr[1])

        def stale_get(key: bytes, ts: int) -> dict:
            return fol_client.call("kv_get", {
                "key": key, "version": ts,
                "context": {"region_id": FIRST_REGION_ID,
                            "stale_read": True, "read_ts": ts},
            }, timeout=10.0)

        # watermark must reach a committed-read ts WITHOUT leases: only the
        # check_leader quorum + dissemination can get it to the follower
        ts0 = pd.get_tso()
        deadline = time.monotonic() + 20
        r = None
        while time.monotonic() < deadline:
            r = stale_get(b"row1", ts0)
            if not r.get("error"):
                break
            time.sleep(0.3)
        assert r is not None and not r.get("error"), f"stale read never unblocked: {r}"
        assert r["value"] == b"v1"

        # hold a lock (prewrite without commit) on the leader
        lock_ts = pd.get_tso()
        pr = client.call("kv_prewrite", {
            "mutations": [{"op": "put", "key": b"row2", "value": b"v2"}],
            "primary_lock": b"row2", "start_version": lock_ts,
        })
        assert not pr.get("errors") and not pr.get("error"), pr

        # the watermark advances to lock_ts-1 and PINS: reads below the lock
        # keep succeeding on the follower, reads above refuse (DataNotReady)
        below = lock_ts - 1
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = stale_get(b"row1", below)
            if not r.get("error"):
                break
            time.sleep(0.3)
        assert not r.get("error"), f"read below lock_ts never unblocked: {r}"
        above = pd.get_tso()
        r = stale_get(b"row1", above)
        assert r.get("error"), "read above a held lock must refuse (DataNotReady)"
        # ... and stays refused while the lock is held (the watermark is
        # pinned by min-lock-ts, not merely lagging)
        time.sleep(1.5)
        r = stale_get(b"row1", above)
        assert r.get("error"), "watermark advanced past a held lock"

        # commit: the watermark resumes past the old `above` ts
        cm = client.call("kv_commit", {
            "keys": [b"row2"], "start_version": lock_ts,
            "commit_version": pd.get_tso(),
        })
        assert not cm.get("error") and not cm.get("errors"), cm
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            r = stale_get(b"row1", above)
            if not r.get("error"):
                break
            time.sleep(0.3)
        assert not r.get("error"), f"stale read never resumed after commit: {r}"
        assert r["value"] == b"v1"
    finally:
        if client is not None:
            client.close()
        if fol_client is not None:
            fol_client.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()
        pd_server.stop()
