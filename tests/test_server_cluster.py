"""Raft over real sockets: the ServerCluster scenario suite.

Mirror of the reference's server-mode raftstore integration tests
(components/test_raftstore/src/server.rs:601 ServerCluster;
tests/integrations/raftstore/): every peer message and snapshot here crosses
the framed-TCP wire through RaftClient -> KvService.raft_* handlers — nothing
moves through in-process channels.
"""

import time

import pytest

from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.store import PartitionFilter
from tikv_tpu.server.cluster import FIRST_REGION_ID, ServerCluster


@pytest.fixture
def cluster3():
    c = ServerCluster(3, pd=MockPd())
    c.run()
    yield c
    c.shutdown()


def test_replication_over_sockets(cluster3):
    c = cluster3
    c.must_put(b"k1", b"v1")
    assert c.must_get(b"k1") == b"v1"
    # quorum-applied on every store's own engine
    for sid in (1, 2, 3):
        c.wait_get_on_store(sid, b"k1", b"v1")


def test_failover_after_leader_stop(cluster3):
    c = cluster3
    c.must_put(b"k1", b"v1")
    leader = c.wait_leader(FIRST_REGION_ID)
    dead = leader.store.store_id
    c.stop_node(dead)
    # a survivor campaigns once election timeouts fire; data stays readable
    # and writable with one of three stores gone
    c.must_put(b"k2", b"v2")
    assert c.must_get(b"k1") == b"v1"
    assert c.must_get(b"k2") == b"v2"
    new_leader = c.wait_leader(FIRST_REGION_ID)
    assert new_leader.store.store_id != dead


def test_restarted_node_catches_up(cluster3):
    c = cluster3
    c.must_put(b"a", b"1")
    c.stop_node(3)
    c.must_put(b"b", b"2")
    c.must_put(b"c", b"3")
    c.restart_node(3)
    c.wait_get_on_store(3, b"b", b"2")
    c.wait_get_on_store(3, b"c", b"3")


def test_partition_minority_leader_deposed(cluster3):
    c = cluster3
    c.must_put(b"k", b"v0")
    leader = c.wait_leader(FIRST_REGION_ID)
    minority = leader.store.store_id
    majority = [sid for sid in (1, 2, 3) if sid != minority]
    # cut the old leader off from the majority, both directions (filters are
    # outbound per node, so install on every side)
    part = PartitionFilter({minority}, set(majority))
    for sid in (1, 2, 3):
        c.nodes[sid].transport.filters.append(part)
    try:
        # majority side elects a fresh leader and accepts writes
        deadline = time.monotonic() + 10
        new_leader = None
        while time.monotonic() < deadline:
            peers = [
                c.nodes[sid].store.peers[FIRST_REGION_ID]
                for sid in majority
            ]
            winners = [p for p in peers if p.node.is_leader()]
            if winners:
                new_leader = winners[0]
                break
            time.sleep(0.05)
        assert new_leader is not None, "majority never elected a leader"
        assert new_leader.node.term > leader.node.term
    finally:
        for sid in (1, 2, 3):
            c.nodes[sid].transport.filters.remove(part)
    # healed: the deposed leader rejoins and sees post-partition writes
    c.must_put(b"k", b"v1")
    c.wait_get_on_store(minority, b"k", b"v1")


def test_snapshot_catch_up_over_wire(cluster3):
    c = cluster3
    c.must_put(b"seed", b"sv")
    c.stop_node(3)
    # write enough entries that log GC abandons the dead follower to a
    # snapshot (compaction threshold is 1024 entries; pd_loop requests GC)
    for i in range(1100):
        c.must_put(b"k%04d" % i, b"v%d" % i)
    leader = c.wait_leader(FIRST_REGION_ID)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if leader.node.log.offset > 1:
            break
        time.sleep(0.1)
    assert leader.node.log.offset > 1, "leader never compacted its log"
    c.restart_node(3)
    c.wait_get_on_store(3, b"k1099", b"v1099", timeout=30.0)
    p3 = c.nodes[3].store.peers[FIRST_REGION_ID]
    assert p3.node.log.snapshot_index > 0, "follower caught up without a snapshot?"
    c.wait_get_on_store(3, b"seed", b"sv")


def test_split_over_sockets(cluster3):
    c = cluster3
    c.must_put(b"a", b"1")
    c.must_put(b"m", b"2")
    new_id = c.split_region(FIRST_REGION_ID, b"h")
    assert c.region_for_key(b"a") == FIRST_REGION_ID
    assert c.region_for_key(b"m") == new_id
    c.must_put(b"b", b"3")
    c.must_put(b"z", b"4")
    assert c.must_get(b"b") == b"3"
    assert c.must_get(b"z") == b"4"


def test_conf_change_over_sockets():
    c = ServerCluster(3, pd=MockPd())
    c.start()
    c.bootstrap(store_ids=[1, 2])
    c.nodes[1].store.peers[FIRST_REGION_ID].node.campaign()
    c.wait_leader(FIRST_REGION_ID)
    try:
        c.must_put(b"k", b"v")
        # the new peer on store 3 is created by first contact over the wire
        # and seeded by a chunked snapshot stream
        pid = c.add_peer(FIRST_REGION_ID, 3)
        c.wait_get_on_store(3, b"k", b"v")
        c.must_put(b"k2", b"v2")
        c.wait_get_on_store(3, b"k2", b"v2")
        c.remove_peer(FIRST_REGION_ID, pid)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if FIRST_REGION_ID not in c.nodes[3].store.peers:
                break
            time.sleep(0.05)
        assert FIRST_REGION_ID not in c.nodes[3].store.peers
    finally:
        c.shutdown()
