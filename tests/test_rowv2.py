"""Row format v2 codec (codec/row/v2/row_slice.rs, compat_v1.rs parity)."""

import numpy as np
import pytest

from tikv_tpu.copr.datatypes import (
    ColumnInfo,
    EvalType,
    FieldType,
    enum_names,
    set_names,
)
from tikv_tpu.copr.mydecimal import MyDecimal
from tikv_tpu.copr.rowv2 import (
    CODEC_VERSION,
    RowSliceV2,
    decode_rows_v2,
    encode_row_v2,
    is_v2_row,
)
from tikv_tpu.copr.table import RowBatchDecoder


def _schema():
    return [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.double()),
        ColumnInfo(4, FieldType.varchar()),
        ColumnInfo(5, FieldType.decimal_type(2)),
        ColumnInfo(6, FieldType.enum_type([b"on", b"off"])),
    ]


def test_header_layout():
    raw = encode_row_v2(_schema()[1:], [7, 1.5, b"xy", 1234, 2])
    assert raw[0] == CODEC_VERSION
    assert raw[1] == 0  # small form
    sl = RowSliceV2(raw)
    assert sl.non_null_ids == [2, 3, 4, 5, 6]
    assert sl.null_ids == []
    assert sl.offsets == sorted(sl.offsets)


def test_roundtrip_with_nulls_and_defaults():
    schema = _schema()
    rows = [
        encode_row_v2(schema[1:], [7, 1.5, b"xy", 1234, 2]),
        encode_row_v2(schema[1:], [None, -2.25, b"", None, 1]),
        # column 4 and 6 absent entirely (schema evolution)
        encode_row_v2([schema[1], schema[2]], [-1, 0.0]),
    ]
    cols = decode_rows_v2(schema, rows)
    assert cols[1].to_values() == [7, None, -1]
    assert cols[2].to_values() == [1.5, -2.25, 0.0]
    assert cols[3].to_values() == [b"xy", b"", None]
    assert cols[4].to_values() == [1234, None, None]
    assert enum_names(cols[5]).to_values() == [b"off", b"on", None]


def test_fast_path_identical_layout():
    schema = _schema()[:3]
    rows = [encode_row_v2(schema[1:], [i * 1000, i * 0.5]) for i in range(100)]
    cols = decode_rows_v2(schema, rows)
    assert cols[1].to_values() == [i * 1000 for i in range(100)]
    assert cols[2].to_values() == [i * 0.5 for i in range(100)]


def test_signed_widths():
    schema = [ColumnInfo(2, FieldType.int64())]
    for v in (0, -1, 127, -128, 128, -32768, 1 << 30, -(1 << 40), (1 << 62)):
        raw = encode_row_v2(schema, [v])
        assert decode_rows_v2(schema, [raw])[0].to_values() == [v]


def test_minimal_width_encoding():
    schema = [ColumnInfo(2, FieldType.int64())]
    small = encode_row_v2(schema, [3])
    large = encode_row_v2(schema, [1 << 40])
    assert len(small) < len(large)
    sl = RowSliceV2(small)
    assert sl.get(2) == b"\x03"


def test_big_form_column_ids():
    schema = [ColumnInfo(300, FieldType.int64()), ColumnInfo(301, FieldType.varchar())]
    raw = encode_row_v2(schema, [5, b"wide"])
    assert raw[1] == 1  # big flag
    sl = RowSliceV2(raw)
    assert sl.non_null_ids == [300, 301]
    cols = decode_rows_v2(schema, [raw])
    assert cols[0].to_values() == [5]
    assert cols[1].to_values() == [b"wide"]


def test_decimal_cell_is_wide_format():
    info = ColumnInfo(2, FieldType.decimal_type(2))
    raw = encode_row_v2([info], [-12345])  # scaled: -123.45
    sl = RowSliceV2(raw)
    cell = sl.get(2)
    prec, frac = cell[0], cell[1]
    d, _ = MyDecimal.decode_bin(cell[2:], prec, frac)
    assert d.to_string() == "-123.45"
    cols = decode_rows_v2([info], [raw])
    assert cols[0].to_values() == [-12345]
    assert cols[0].frac == 2


def test_set_bit63_roundtrip():
    info = ColumnInfo(2, FieldType.set_type([b"x%d" % k for k in range(64)]))
    raw = encode_row_v2([info], [1 << 63])
    cols = decode_rows_v2([info], [raw])
    assert set_names(cols[0]).to_values() == [b"x63"]


def test_row_batch_decoder_dispatches_v2():
    schema = _schema()
    dec = RowBatchDecoder(schema)
    rows = [encode_row_v2(schema[1:], [i, 0.5, b"a", 100, 1]) for i in range(4)]
    cols = dec.decode(np.arange(4), rows)
    assert cols[0].to_values() == [0, 1, 2, 3]  # handle column
    assert cols[1].to_values() == [0, 1, 2, 3]
    assert enum_names(cols[5]).to_values() == [b"on"] * 4


def test_mixed_v1_v2_block():
    from tikv_tpu.copr.table import encode_row

    schema = _schema()
    dec = RowBatchDecoder(schema)
    v1 = encode_row(schema[1:], [10, 1.0, b"v1", 500, 1])
    v2 = encode_row_v2(schema[1:], [20, 2.0, b"v2", 600, 2])
    assert not is_v2_row(v1) and is_v2_row(v2)
    cols = dec.decode(np.array([1, 2, 3]), [v1, v2, v1])
    assert cols[1].to_values() == [10, 20, 10]
    assert cols[3].to_values() == [b"v1", b"v2", b"v1"]
    assert cols[4].to_values() == [500, 600, 500]
    assert enum_names(cols[5]).to_values() == [b"on", b"off", b"on"]


def test_value_section_over_64k_uses_big():
    info = [ColumnInfo(2, FieldType.varchar())]
    raw = encode_row_v2(info, [b"z" * 70000])
    assert raw[1] == 1
    cols = decode_rows_v2(info, [raw])
    assert cols[0].to_values() == [b"z" * 70000]


def test_wide_decimal_cell_roundtrip_via_wide_api():
    from tikv_tpu.copr.rowv2 import decode_cell_wide

    info = ColumnInfo(2, FieldType.decimal_type(2))
    info.ftype.flen = 30
    wide = MyDecimal.from_str("12345678901234567890.12")
    raw = encode_row_v2([info], [wide])
    cell = RowSliceV2(raw).get(2)
    assert decode_cell_wide(cell) == wide
    # the columnar bridge rejects it with a descriptive error
    with pytest.raises(ValueError, match="columnar"):
        decode_rows_v2([info], [raw])


def test_encode_bin_clamps_when_widening_overflows():
    from tikv_tpu.copr.mydecimal import MAX_DIGITS

    d = MyDecimal.from_str("9" * 80)
    raw = d.encode_bin(65, 2)  # widening to frac=2 would need 82 digits
    back, _ = MyDecimal.decode_bin(raw, 65, 2)
    assert back.to_string() == "9" * 63 + "." + "99"


# ---------------------------------------------------------------------------
# Grouped mixed-layout batch decode
# ---------------------------------------------------------------------------


def _col_values(cols, schema):
    out = []
    n = len(cols[0])
    for r in range(n):
        row = []
        for ci, info in enumerate(schema):
            c = cols[ci]
            if c.nulls[r]:
                row.append(None)
            elif c.is_dict_encoded:
                row.append(c.dictionary[c.data[r]])
            else:
                row.append(c.data[r])
        out.append(row)
    return out


def test_grouped_decode_mixed_layouts_matches_per_row():
    """Rows with different layouts (NULL patterns, value widths, varchar
    lengths) must decode identically to the per-row walk, in row order."""
    schema = _schema()
    rows = [
        [7, 1.5, b"xy", 1234, 2],
        [1 << 40, 2.5, b"longer-string", 5678, 1],  # wider int, longer bytes
        [None, 3.5, b"xy", 91, 2],                  # NULL int
        [7, 1.5, b"xy", 1234, 2],                   # same layout as row 0
        [3, None, None, None, 1],                   # mostly NULL
        [1 << 40, 2.5, b"longer-string", 5678, 1],  # same layout as row 1
    ]
    encoded = [encode_row_v2(schema[1:], r) for r in rows]
    cols = decode_rows_v2(schema, encoded)
    per_row = [decode_rows_v2(schema, [e]) for e in encoded]
    for r, cols1 in enumerate(per_row):
        got = _col_values(cols, schema)[r]
        want = _col_values(cols1, schema)[0]
        assert got[1:] == want[1:], f"row {r}"


def test_grouped_decode_layout_explosion_falls_back():
    """One distinct layout per row (> _MAX_LAYOUT_GROUPS) must still decode
    correctly through the slow path."""
    schema = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.varchar()),
        ColumnInfo(3, FieldType.int64()),
    ]
    rows = [[b"x" * (i + 1), i] for i in range(40)]
    encoded = [encode_row_v2(schema[1:], r) for r in rows]
    cols = decode_rows_v2(schema, encoded)
    vals = _col_values(cols, schema)
    for i in range(40):
        assert vals[i][1] == b"x" * (i + 1)
        assert vals[i][2] == i
