"""True server-streaming coprocessor responses over the wire
(src/coprocessor/endpoint.rs:508-584, kv.rs coprocessor_stream:574):
frames ride the TCP connection one at a time with the request's id, the
server holds O(one frame) of memory, and a slow client back-pressures the
executor instead of ballooning a server-side buffer."""

import threading
import time

import pytest

from tikv_tpu.copr.dag import DagRequest, TableScan
from tikv_tpu.copr.dag_wire import dag_to_wire
from tikv_tpu.copr.endpoint import Endpoint
from tikv_tpu.copr.table import record_range
from tikv_tpu.server.server import Client, Server
from tikv_tpu.server.service import KvService
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.storage import Storage

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_engine


@pytest.fixture
def served():
    eng = LocalEngine(product_engine())
    ep = Endpoint(eng, enable_device=False)
    svc = KvService(Storage(engine=eng), ep)
    srv = Server(svc)
    srv.start()
    client = Client(*srv.addr)
    yield client, svc, ep
    client.close()
    srv.stop()


def _stream_req(rows_per_stream=2):
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    return {
        "dag": dag_to_wire(dag),
        "ranges": [list(record_range(TABLE_ID))],
        "start_ts": 200,
        "rows_per_stream": rows_per_stream,
    }


def test_streamed_frames_match_inprocess(served):
    """Wire frames are byte-identical to the endpoint's in-process streaming
    output, and more than one frame actually crosses the wire."""
    client, svc, ep = served
    frames = [f["data"] for f in client.call_stream("coprocessor_stream", _stream_req())]
    assert len(frames) > 1, "scan must split into multiple frames"
    from tikv_tpu.copr.dag_wire import dag_from_wire
    from tikv_tpu.copr.endpoint import CoprRequest

    req = _stream_req()
    creq = CoprRequest(103, dag_from_wire(req["dag"]),
                       [tuple(r) for r in req["ranges"]], req["start_ts"])
    want = [r.data for r in ep.handle_streaming_request(creq, 2)]
    assert frames == want


def _big_bytes_engine(n_rows=8_000, payload=1_000):
    """~8MB of row data committed at ts=100, split into enough frames that a
    stalled consumer is clearly distinguishable from a drained stream."""
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.table import encode_row, record_key
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.engine import CF_WRITE
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.varchar()),
    ]
    from tikv_tpu.storage.engine import CF_DEFAULT

    blob = b"x" * payload
    writes, values = [], []
    wrec = Write(WriteType.PUT, 90).to_bytes()
    for i in range(n_rows):
        k = Key.from_raw(record_key(TABLE_ID, i))
        values.append((k.append_ts(90).encoded, encode_row(cols[1:], [blob])))
        writes.append((k.append_ts(100).encoded, wrec))
    eng = BTreeEngine()
    eng.bulk_load(CF_DEFAULT, values)
    eng.bulk_load(CF_WRITE, writes)
    return LocalEngine(eng), cols, n_rows


def test_backpressure_bounds_server_memory():
    """A stalled consumer must stall PRODUCTION at the credit window
    (server.py STREAM_WINDOW), proving both sides hold O(window) frames —
    the frames=[...] regression this guards against buffered the whole
    response before the first byte left."""
    from tikv_tpu.server.server import STREAM_WINDOW

    eng, cols, n_rows = _big_bytes_engine()
    ep = Endpoint(eng, enable_device=False)
    svc = KvService(Storage(engine=eng), ep)
    srv = Server(svc)
    srv.start()
    client = Client(*srv.addr)
    produced = []
    orig = ep.handle_streaming_request

    def tracking(req, rows_per_stream=1024):
        for r in orig(req, rows_per_stream):
            produced.append(len(r.data))
            yield r

    ep.handle_streaming_request = tracking
    try:
        dag = DagRequest(executors=[TableScan(TABLE_ID, cols)])
        it = client.call_stream("coprocessor_stream", {
            "dag": dag_to_wire(dag),
            "ranges": [list(record_range(TABLE_ID))],
            "start_ts": 200,
            "rows_per_stream": 256,
        }, timeout=120)
        total_frames = (n_rows + 255) // 256
        assert total_frames > 3 * STREAM_WINDOW  # stall must be observable
        # consume NOTHING: production must stall at the credit window
        deadline = time.monotonic() + 30
        stalled_at = None
        while time.monotonic() < deadline:
            time.sleep(0.4)
            cur = len(produced)
            time.sleep(0.4)
            if len(produced) == cur and cur > 0:
                stalled_at = cur
                break
        assert stalled_at is not None, "production never stalled"
        assert stalled_at <= STREAM_WINDOW + 1, (
            f"server produced {stalled_at}/{total_frames} frames with no "
            f"consumer — credit flow control is not bounding the stream"
        )
        # now drain: everything arrives and production resumes to completion
        frames = list(it)
        assert len(frames) == total_frames
        assert len(produced) == total_frames
    finally:
        ep.handle_streaming_request = orig
        client.close()
        srv.stop()


def test_unary_calls_interleave_with_open_stream(served):
    """A long stream must not monopolize the connection: frames take the
    send lock one at a time, so a unary response can slot in between."""
    client, svc, _ep = served
    it = client.call_stream("coprocessor_stream", _stream_req(rows_per_stream=1))
    next(it)  # stream is open with frames still pending
    r = client.call("kv_get", {"key": b"nonexistent", "version": 200,
                               "context": {}}, timeout=10)
    assert isinstance(r, dict)
    assert list(it)  # stream still completes


def test_validation_error_surfaces(served):
    client, _svc, _ep = served
    with pytest.raises(RuntimeError, match="dag required"):
        list(client.call_stream("coprocessor_stream",
                                {"ranges": [], "start_ts": 1}))


def test_mid_stream_error_carried_on_final_frame(served):
    client, svc, ep = served
    orig = ep.handle_streaming_request

    def exploding(req, rows_per_stream=1024):
        it = orig(req, rows_per_stream)
        yield next(it)
        raise RuntimeError("boom mid-stream")

    ep.handle_streaming_request = exploding
    try:
        it = client.call_stream("coprocessor_stream", _stream_req(rows_per_stream=1))
        assert next(it)["data"]
        with pytest.raises(RuntimeError, match="boom mid-stream"):
            list(it)
    finally:
        ep.handle_streaming_request = orig


def test_coprocessor_batch_fuses_on_device():
    """batch_coprocessor serving shape: K eligible aggregation DAGs over the
    same cached region view answer from ONE fused device program, byte-
    identical to per-request CPU answers."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation
    from tikv_tpu.copr.dag_wire import dag_to_wire
    from tikv_tpu.copr.endpoint import CoprRequest
    from tikv_tpu.copr.rpn import col

    eng = LocalEngine(product_engine())
    ep_dev = Endpoint(eng, enable_device=True)
    ep_cpu = Endpoint(eng, enable_device=False)

    def agg_dag(fn, target):
        return DagRequest(executors=[
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor(fn, col(target))]),
        ])

    dags = [agg_dag("count", 0), agg_dag("sum", 0), agg_dag("max", 0),
            agg_dag("min", 0)]
    ctx = {"region_id": 1, "cache_version": 7}
    reqs = [CoprRequest(103, d, [record_range(TABLE_ID)], 200, dict(ctx))
            for d in dags]
    resps = ep_dev.handle_batch(reqs)
    assert all(r.from_device for r in resps), [r.from_device for r in resps]
    for d, got in zip(dags, resps):
        want = ep_cpu.handle_request(
            CoprRequest(103, d, [record_range(TABLE_ID)], 200, dict(ctx)))
        assert got.data == want.data
    from tikv_tpu.util.metrics import REGISTRY

    assert REGISTRY.counter("tikv_coprocessor_batch_total", "").get() >= 1
    assert REGISTRY.counter("tikv_coprocessor_batch_queries_total", "").get() >= 4


def test_coprocessor_batch_over_wire(served):
    """The RPC surface: one coprocessor_batch call, ordered responses."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.dag import Aggregation
    from tikv_tpu.copr.rpn import col

    client, svc, _ep = served

    def sub(fn):
        dag = DagRequest(executors=[
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation([], [AggDescriptor(fn, col(0))]),
        ])
        return {"dag": dag_to_wire(dag), "ranges": [list(record_range(TABLE_ID))],
                "start_ts": 200, "context": {}}

    r = client.call("coprocessor_batch", {"requests": [sub("count"), sub("sum")]})
    assert "error" not in r, r
    assert len(r["responses"]) == 2
    for s, got in zip([sub("count"), sub("sum")], r["responses"]):
        want = client.call("coprocessor", {k: v for k, v in s.items()})
        assert got["data"] == want["data"]
