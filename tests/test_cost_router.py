"""Cost-based path router + geometry auto-tuner (copr/costmodel.py,
docs/cost_router.md): measured routing with bounded exploration, strict
static fallback, the kill switch's byte-and-metric identity, the hill-climb
tuner's convergence and automatic revert, and the operator surfaces.

Run under TIKV_TPU_SANITIZE=1 by scripts/check.sh — routing sits on the
serving hot path and must share no lock with the observatory or metrics."""

import json
import os
import sys

import numpy as np
import pytest

from copr_fixtures import TABLE_ID as PRODUCT_TABLE  # noqa: F401 (path setup)
from tikv_tpu.copr import observatory as obs
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.costmodel import (
    CostRouter, Decision, GeometryTuner, RouterConfig, TunerConfig,
)
from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.encoding import candidate_paths
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.overload import AdaptiveController, OverloadConfig
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util.config import ConfigController, TikvConfig
from tikv_tpu.util.metrics import REGISTRY

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TABLE_ID = 93

COLS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),
    ColumnInfo(3, FieldType.int64()),
]


def _engine(n: int, seed: int = 0) -> BTreeEngine:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 50, n)
    b = rng.integers(0, 100000, n)
    eng = BTreeEngine()
    items = []
    for i in range(n):
        rk = record_key(TABLE_ID, i)
        val = encode_row(COLS[1:], [int(a[i]), int(b[i])])
        items.append((Key.from_raw(rk).append_ts(20).encoded,
                      Write(WriteType.PUT, 10, short_value=val).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    return eng


def _sum_dag(cut: int = 40) -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("lt", col(1), const_int(cut))]),
        Aggregation([], [AggDescriptor("sum", col(2)),
                         AggDescriptor("count", None)]),
    ])


def _req(rows: int, dag: DagRequest) -> CoprRequest:
    lo = record_key(TABLE_ID, 0)
    hi = record_key(TABLE_ID, rows)
    return CoprRequest(103, dag, [(lo, hi)], 100, context={
        "region_id": 1, "region_epoch": (1, 1), "apply_index": 7,
    })


@pytest.fixture(autouse=True)
def _fresh_observatory():
    obs.OBSERVATORY.reset()
    yield
    obs.OBSERVATORY.reset()


def _seed_profiles(sig: str, table: dict[str, float], n: int = 8,
                   rows: int = 400) -> None:
    """Warm per-path profiles directly: ``table`` maps path -> latency_s."""
    for _ in range(n):
        for path, lat in table.items():
            obs.OBSERVATORY.record_serve(sig, path, lat, rows=rows)


# ---------------------------------------------------------------------------
# RouterConfig / candidate set
# ---------------------------------------------------------------------------

def test_router_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(epsilon=0.9)
    with pytest.raises(ValueError):
        RouterConfig(cold_probe_rate=-0.1)
    with pytest.raises(ValueError):
        RouterConfig(min_count=0)
    with pytest.raises(ValueError):
        RouterConfig(compile_amortize_floor=0)
    with pytest.raises(ValueError):
        TunerConfig(revert_ratio=1.5)
    with pytest.raises(ValueError):
        TunerConfig(min_serves=0)


def test_candidate_paths_static_ladder_order():
    agg = _sum_dag()
    assert candidate_paths(agg, device_ok=True, mesh_ok=False) == \
        ["zone", "unary", "cpu"]
    assert candidate_paths(agg, device_ok=True, mesh_ok=True) == \
        ["mesh", "zone", "unary", "cpu"]
    scan = DagRequest(executors=[TableScan(TABLE_ID, COLS)])
    assert candidate_paths(scan, device_ok=True, mesh_ok=False) == \
        ["unary", "cpu"]
    # ineligible for the device: CPU is the only rung
    assert candidate_paths(agg, device_ok=False, mesh_ok=True) == ["cpu"]


# ---------------------------------------------------------------------------
# route(): static fallback, kill switch, measured, explore/cold bounds
# ---------------------------------------------------------------------------

def test_cold_profiles_fall_back_to_static_head():
    r = CostRouter(config=RouterConfig(seed=1))
    d = r.route("sigX", ["zone", "unary", "cpu"])
    assert (d.path, d.reason) == ("zone", "static_fallback")
    assert d.delta_ms is None


def test_kill_switch_is_static_and_counted():
    c = REGISTRY.counter("tikv_coprocessor_cost_route_total", "")
    before = c.get(path="zone", reason="kill_switch")
    r = CostRouter(enabled=False)
    # even with a warm table showing cpu cheapest, the kill switch must
    # return the static head and never consult costs
    _seed_profiles("sigK", {"cpu": 0.001, "zone": 0.5})
    for _ in range(10):
        d = r.route("sigK", ["zone", "unary", "cpu"])
        assert (d.path, d.reason) == ("zone", "kill_switch")
    assert c.get(path="zone", reason="kill_switch") == before + 10


def test_kill_switch_env(monkeypatch):
    monkeypatch.setenv("TIKV_TPU_COST_ROUTER", "0")
    assert CostRouter().enabled is False
    monkeypatch.setenv("TIKV_TPU_COST_ROUTER", "1")
    assert CostRouter().enabled is True


def test_measured_picks_cheapest_and_reports_delta():
    r = CostRouter(config=RouterConfig(seed=5, epsilon=0.0,
                                       cold_probe_rate=0.0))
    costs = {"zone": {"count": 10, "cost_ms": 8.0},
             "unary": {"count": 10, "cost_ms": 2.0},
             "cpu": {"count": 10, "cost_ms": 30.0}}
    for _ in range(20):
        d = r.route("sigM", ["zone", "unary", "cpu"], costs=costs)
        assert (d.path, d.reason) == ("unary", "measured")
        assert d.delta_ms == 0.0


def test_explore_share_bounded_and_recovers_after_profile_improves():
    eps = 0.1
    r = CostRouter(config=RouterConfig(seed=7, epsilon=eps,
                                       cold_probe_rate=0.0))
    slow = {"fast": {"count": 50, "cost_ms": 1.0},
            "slow": {"count": 50, "cost_ms": 10.0}}
    n = 4000
    picks = [r.route("sigE", ["slow", "fast"], costs=slow).path
             for _ in range(n)]
    share = picks.count("slow") / n
    # the worse path keeps a BOUNDED probe share: epsilon, not zero and
    # not runaway (3-sigma slack around the configured rate)
    assert 0.05 < share < 0.15
    # the profile improves (the slow path got faster than the incumbent):
    # measured routing must recover its share immediately
    fast_now = {"fast": {"count": 50, "cost_ms": 1.0},
                "slow": {"count": 50, "cost_ms": 0.2}}
    picks = [r.route("sigE", ["slow", "fast"], costs=fast_now).path
             for _ in range(1000)]
    assert picks.count("slow") / 1000 > 0.85


def test_cold_paths_probed_at_budgeted_rate_round_robin():
    rate = 0.04
    r = CostRouter(config=RouterConfig(seed=13, epsilon=0.0,
                                       cold_probe_rate=rate))
    costs = {"unary": {"count": 50, "cost_ms": 1.0}}
    n = 6000
    picks = [r.route("sigC", ["zone", "unary", "cpu", "fused"], costs=costs)
             for _ in range(n)]
    cold = [d for d in picks if d.reason == "cold"]
    share = len(cold) / n
    assert 0.02 < share < 0.08
    # budget rotates across ALL cold candidates, not just the first
    probed = {d.path for d in cold}
    assert probed == {"zone", "cpu", "fused"}


def test_route_requires_candidates():
    with pytest.raises(ValueError):
        CostRouter().route("s", [])


def test_decision_snapshot_ring_bounded():
    r = CostRouter(config=RouterConfig(seed=2))
    for i in range(200):
        r.route(f"s{i % 3}", ["unary", "cpu"])
    snap = r.snapshot()
    assert len(snap["recent"]) <= 64
    assert snap["decisions_by_reason"]["static_fallback"] == 200


# ---------------------------------------------------------------------------
# endpoint integration: measured routing, byte identity, kill-switch identity
# ---------------------------------------------------------------------------

def _router_ep(eng, **router_kw):
    cfg = dict(seed=3, epsilon=0.0, cold_probe_rate=0.0, min_count=3)
    cfg.update(router_kw)
    return Endpoint(LocalEngine(eng), enable_device=True, block_rows=512,
                    cost_router=CostRouter(config=RouterConfig(**cfg)))


def test_router_routes_around_expensive_device_path():
    eng = _engine(400)
    ep = _router_ep(eng)
    dag = _sum_dag()
    sig, _ = obs.dag_sig(dag)
    fb = REGISTRY.counter("tikv_coprocessor_path_fallback_total", "")
    before = fb.get(path="unary", cause="cost_route")
    # measured profiles say the device path is 100x the CPU pipeline
    _seed_profiles(sig, {"unary": 0.5, "cpu": 0.005})
    resp = ep.handle_request(_req(400, dag))
    assert resp.from_device is False
    assert fb.get(path="unary", cause="cost_route") == before + 1
    # flip the evidence: the device path is cheap again -> device serve
    obs.OBSERVATORY.reset()
    _seed_profiles(sig, {"unary": 0.001, "cpu": 0.5})
    resp = ep.handle_request(_req(400, dag))
    assert resp.from_device is True


def test_byte_identity_on_every_routed_path():
    eng = _engine(400)
    # maximum legal exploration: every candidate path gets chosen
    ep = _router_ep(eng, epsilon=0.5, cold_probe_rate=0.5, min_count=1)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    dag = _sum_dag()
    oracle = ep_cpu.handle_request(_req(400, dag)).data
    served_paths = set()
    for _ in range(40):
        resp = ep.handle_request(_req(400, dag))
        assert resp.data == oracle
        served_paths.add("device" if resp.from_device else "cpu")
    # the explore/cold churn really did exercise more than one serving path
    assert served_paths == {"device", "cpu"}
    reasons = ep.cost_router.snapshot()["decisions_by_reason"]
    assert reasons["cold"] > 0 or reasons["explore"] > 0


def test_kill_switch_byte_and_metric_identical_to_static_rules():
    eng = _engine(400)
    dag = _sum_dag()
    # static baseline: router enabled but min_count so high nothing ever
    # warms — by construction every decision is the static-ladder head
    ep_static = _router_ep(eng, min_count=10**6)
    ep_kill = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512,
                       cost_router=CostRouter(enabled=False))
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    c = REGISTRY.counter("tikv_coprocessor_cost_route_total", "")
    delta = REGISTRY.counter("tikv_coprocessor_cost_route_delta_ms_total", "")
    kill0 = c.get(path="zone", reason="kill_switch")
    delta0 = delta.get()
    oracle = ep_cpu.handle_request(_req(400, dag)).data
    for _ in range(6):
        a = ep_static.handle_request(_req(400, dag))
        b = ep_kill.handle_request(_req(400, dag))
        assert a.data == b.data == oracle
        assert a.from_device == b.from_device
    # the kill switch took the same serving path, emitted ONLY
    # reason="kill_switch" decisions, and never accrued chosen-vs-best delta
    assert c.get(path="zone", reason="kill_switch") == kill0 + 6
    assert delta.get() == delta0
    sig, _ = obs.dag_sig(dag)
    routes = obs.OBSERVATORY.snapshot(sig=sig)["sigs"][sig]["routes"]
    assert routes.get("zone|kill_switch") == 6
    assert routes.get("zone|static_fallback") == 6


# ---------------------------------------------------------------------------
# chosen-vs-best deltas feed the AdaptiveController (route waste != saturation)
# ---------------------------------------------------------------------------

def test_route_waste_vetoes_relax_but_never_tightens():
    clk = [0.0]
    ctrl = AdaptiveController(OverloadConfig(window_s=1.0),
                              clock=lambda: clk[0])
    ctrl.scale = 0.5
    # persistent routing waste: chosen 5ms over a 1ms best, many samples
    for _ in range(12):
        ctrl.note_route_delta(5.0, 1.0)
    clk[0] += 2.0
    ctrl.note_queue(0, 100)  # idle queues would otherwise relax
    assert ctrl.last_evidence["route_pressure"] is True
    assert ctrl.last_evidence["route_samples"] == 12
    assert ctrl.scale == 0.5  # relax vetoed, NOT tightened
    # waste clears -> the relax branch resumes
    clk[0] += 2.0
    ctrl.note_queue(0, 100)
    assert ctrl.last_evidence["route_pressure"] is False
    assert ctrl.scale > 0.5


def test_endpoint_forwards_route_deltas_to_overload():
    from tikv_tpu.copr.overload import OverloadControl

    eng = _engine(400)
    ep = _router_ep(eng)
    ep.overload = OverloadControl(OverloadConfig(enabled=True, adaptive=True),
                                  region_cache=ep.region_cache)
    dag = _sum_dag()
    sig, _ = obs.dag_sig(dag)
    _seed_profiles(sig, {"unary": 0.001, "cpu": 0.5})
    ep.handle_request(_req(400, dag))
    # a measured decision carries delta 0 vs best — the controller saw it
    assert ep.overload.controller._route[2] >= 1


# ---------------------------------------------------------------------------
# geometry auto-tuner: hill-climb, one change in flight, revert on regression
# ---------------------------------------------------------------------------

class _FakeObs:
    """Deterministic throughput source: rate is a pure function of the
    registered knob's current value, rows/busy_s advance per drive()."""

    def __init__(self):
        self.serves = 0
        self.rows = 0
        self.busy = 0.0

    def totals(self):
        return {"serves": self.serves, "rows": self.rows,
                "busy_s": self.busy}

    def drive(self, serves: int, busy_per_serve: float, rows: int = 1024):
        self.serves += serves
        self.rows += serves * rows
        self.busy += serves * busy_per_serve


def test_tuner_walks_bad_block_rows_down_within_bounds():
    fake = _FakeObs()
    tuner = GeometryTuner(observatory=fake,
                          config=TunerConfig(min_serves=8, warmup_ticks=0))
    knob = {"block_rows": 1 << 18}
    lo, hi = 1 << 10, 1 << 18
    tuner.register("coprocessor.block_rows",
                   lambda: knob["block_rows"],
                   lambda v: knob.__setitem__("block_rows", int(v)),
                   lo, hi, integer=True)
    for _ in range(40):
        # padded-tile cost model: busy scales with block_rows, so every
        # halving improves the measured rate and is kept
        fake.drive(16, busy_per_serve=knob["block_rows"] / 1e6)
        tuner.tick()
    snap = tuner.snapshot()
    assert lo <= knob["block_rows"] <= 1 << 12  # converged to the floor
    assert snap["counts"]["keep"] >= 6
    assert snap["counts"]["reject"] == 0
    # every proposal stayed inside the validated bounds
    for ev in snap["history"]:
        if "new" in ev:
            assert lo <= ev["new"] <= hi


def test_tuner_tunes_bad_max_wait_back():
    fake = _FakeObs()
    tuner = GeometryTuner(observatory=fake,
                          config=TunerConfig(min_serves=8, warmup_ticks=0))
    knob = {"max_wait_s": 0.05}  # pathologically long linger
    tuner.register("coprocessor.max_wait_s",
                   lambda: knob["max_wait_s"],
                   lambda v: knob.__setitem__("max_wait_s", float(v)),
                   0.0005, 0.05)
    for _ in range(40):
        fake.drive(16, busy_per_serve=knob["max_wait_s"])
        tuner.tick()
    assert 0.0005 <= knob["max_wait_s"] <= 0.004


def test_tuner_reverts_on_floor_regression_and_flips_direction():
    c = REGISTRY.counter("tikv_coprocessor_geometry_tune_total", "")
    before = c.get(knob="coprocessor.block_rows", action="revert")
    fake = _FakeObs()
    tuner = GeometryTuner(observatory=fake,
                          config=TunerConfig(min_serves=8, warmup_ticks=0,
                                             revert_ratio=0.7))
    knob = {"block_rows": 1 << 14}
    tuner.register("coprocessor.block_rows",
                   lambda: knob["block_rows"],
                   lambda v: knob.__setitem__("block_rows", int(v)),
                   1 << 10, 1 << 18, integer=True)
    # seeded regression: the smaller geometry is 10x SLOWER (per-dispatch
    # overhead dominates) — the tuner must put the old value back
    fake.drive(16, busy_per_serve=0.001)
    tuner.tick()           # baseline window
    fake.drive(16, busy_per_serve=0.001)
    assert tuner.tick()["action"] == "propose"
    assert knob["block_rows"] == 1 << 13
    fake.drive(16, busy_per_serve=0.010)
    ev = tuner.tick()
    assert ev["action"] == "revert"
    assert knob["block_rows"] == 1 << 14  # old value restored
    assert c.get(knob="coprocessor.block_rows", action="revert") == before + 1
    # direction flipped: the next proposal climbs instead (the judging
    # tick re-anchored the baseline window, so one drive suffices)
    fake.drive(16, busy_per_serve=0.001)
    ev = tuner.tick()
    assert ev["action"] == "propose" and ev["new"] == 1 << 15


def test_tuner_warmup_discards_post_change_transient():
    fake = _FakeObs()
    tuner = GeometryTuner(observatory=fake,
                          config=TunerConfig(min_serves=8, warmup_ticks=1))
    knob = {"block_rows": 1 << 14}
    tuner.register("coprocessor.block_rows",
                   lambda: knob["block_rows"],
                   lambda v: knob.__setitem__("block_rows", int(v)),
                   1 << 10, 1 << 18, integer=True)
    fake.drive(16, busy_per_serve=0.001)
    tuner.tick()
    fake.drive(16, busy_per_serve=0.001)
    assert tuner.tick()["action"] == "propose"
    # the rebuild/recompile transient: 20x the steady rate, discarded
    fake.drive(16, busy_per_serve=0.020)
    assert tuner.tick() is None  # warmup tick re-anchors, no judgment
    fake.drive(16, busy_per_serve=0.0005)
    assert tuner.tick()["action"] == "keep"
    assert knob["block_rows"] == 1 << 13


def test_tuner_reject_via_validated_config_path():
    ctl = ConfigController(TikvConfig())
    ctl.update({"coprocessor.block_rows": 256})
    fake = _FakeObs()
    tuner = GeometryTuner(observatory=fake,
                          config=TunerConfig(min_serves=8, warmup_ticks=0))
    # bounds WIDER than the config's own validation: the proposal to 128
    # must be rejected by TikvConfig.validate, counted, and change nothing
    tuner.register("coprocessor.block_rows",
                   lambda: ctl.config.coprocessor.block_rows,
                   lambda v: ctl.update({"coprocessor.block_rows": int(v)}),
                   64, 1 << 18, integer=True)
    fake.drive(16, busy_per_serve=0.001)
    tuner.tick()
    fake.drive(16, busy_per_serve=0.001)
    ev = tuner.tick()
    assert ev["action"] == "reject"
    assert ctl.config.coprocessor.block_rows == 256
    assert tuner.snapshot()["counts"]["reject"] == 1


def test_tuner_disabled_is_inert():
    fake = _FakeObs()
    tuner = GeometryTuner(observatory=fake, enabled=False)
    knob = {"v": 8}
    tuner.register("k", lambda: knob["v"],
                   lambda v: knob.__setitem__("v", v), 1, 64)
    fake.drive(100, busy_per_serve=0.001)
    assert tuner.tick() is None
    assert knob["v"] == 8


# ---------------------------------------------------------------------------
# runtime-tunable scheduler geometry + config bounds (POST /config)
# ---------------------------------------------------------------------------

def test_config_validates_geometry_bounds():
    ctl = ConfigController(TikvConfig())
    with pytest.raises(ValueError):
        ctl.update({"coprocessor.block_rows": 64})       # below 2^8
    with pytest.raises(ValueError):
        ctl.update({"coprocessor.block_rows": 1 << 21})  # above 2^20
    with pytest.raises(ValueError):
        ctl.update({"coprocessor.block_rows": 3000})     # not a power of two
    with pytest.raises(ValueError):
        ctl.update({"coprocessor.max_wait_s": 0.0})
    with pytest.raises(ValueError):
        ctl.update({"coprocessor.low_max_wait_s": 2.0})
    # a rejected update changes NOTHING
    assert ctl.config.coprocessor.block_rows == 1 << 16
    diff = ctl.update({"coprocessor.block_rows": 4096,
                       "coprocessor.max_wait_s": 0.01})
    assert diff["coprocessor"] == {"block_rows": 4096, "max_wait_s": 0.01}


def test_scheduler_reconfigure_lane_waits():
    eng = _engine(64)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    ep.scheduler.reconfigure({"max_wait_s": 0.01,
                              "high_max_wait_s": 0.002,
                              "low_max_wait_s": 0.08})
    assert ep.scheduler.cfg.max_wait_s == 0.01
    assert ep.scheduler.cfg.high_max_wait_s == 0.002
    assert ep.scheduler.cfg.low_max_wait_s == 0.08


def test_endpoint_set_block_rows_invalidates_geometry():
    eng = _engine(400)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=512)
    dag = _sum_dag()
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    oracle = ep_cpu.handle_request(_req(400, dag)).data
    assert ep.handle_request(_req(400, dag)).data == oracle
    ep.set_block_rows(1024)
    assert ep.block_rows == 1024
    assert ep.region_cache.block_rows == 1024
    # the warm image was invalidated; the rebuilt geometry serves the
    # same bytes
    assert ep.handle_request(_req(400, dag)).data == oracle
    # no-op change keeps evaluator caches intact
    evs = ep._evaluators
    ep.set_block_rows(1024)
    assert ep._evaluators is evs


# ---------------------------------------------------------------------------
# observability: /debug/cost_router, RPC, ctl, observatory declines
# ---------------------------------------------------------------------------

def test_debug_cost_router_rpc_http_and_ctl(capsys):
    import urllib.error
    import urllib.request

    from tikv_tpu.server.server import Client, Server
    from tikv_tpu.server.service import KvService
    from tikv_tpu.server.status_server import StatusServer
    from tikv_tpu.storage.storage import Storage

    eng = _engine(400)
    ep = _router_ep(eng)
    dag = _sum_dag()
    ep.handle_request(_req(400, dag))
    svc = KvService(Storage(), ep)
    srv = Server(svc)
    srv.start()
    c = Client(*srv.addr)
    try:
        snap = c.call("debug_cost_router", {})
        assert snap["router"]["enabled"] is True
        assert snap["router"]["decisions_by_reason"]["static_fallback"] >= 1
        sys.path.insert(0, REPO)
        try:
            import ctl
        finally:
            sys.path.pop(0)
        addr = f"{srv.addr[0]}:{srv.addr[1]}"
        assert ctl.main(["--addr", addr, "cost-router"]) == 0
        out = capsys.readouterr().out
        assert "decisions_by_reason" in out
    finally:
        c.close()
        srv.stop()

    ss = StatusServer(cost_router=lambda: ep.cost_router_snapshot())
    ss.start()
    try:
        host, port = ss.addr
        js = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/debug/cost_router").read())
        assert js["router"]["decisions_by_reason"]["static_fallback"] >= 1
    finally:
        ss.stop()

    ss = StatusServer()  # not wired -> 404, not a crash
    ss.start()
    try:
        host, port = ss.addr
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"http://{host}:{port}/debug/cost_router")
        assert exc.value.code == 404
    finally:
        ss.stop()


def test_observatory_text_surfaces_decline_causes():
    import urllib.request

    from tikv_tpu.copr import encoding
    from tikv_tpu.server.status_server import StatusServer

    encoding.count_decline("device_plan", "router_test_cause")
    ss = StatusServer()
    ss.start()
    try:
        host, port = ss.addr
        body = urllib.request.urlopen(
            f"http://{host}:{port}/debug/observatory").read().decode()
        assert "device-plan declines" in body
        assert "cause=router_test_cause" in body
    finally:
        ss.stop()


def test_cost_router_snapshot_includes_tuner():
    eng = _engine(64)
    ep = _router_ep(eng)
    assert "tuner" not in ep.cost_router_snapshot()
    ep.geometry_tuner = GeometryTuner(observatory=_FakeObs())
    snap = ep.cost_router_snapshot()
    assert snap["tuner"]["enabled"] is True


# ---------------------------------------------------------------------------
# scheduler batch routing: xregion vs direct through the same router
# ---------------------------------------------------------------------------

def test_batch_router_weighs_xregion_against_best_direct():
    r = CostRouter(config=RouterConfig(seed=9, epsilon=0.0,
                                       cold_probe_rate=0.0, min_count=3))
    # xregion measured slower than the best direct path -> route direct
    table = {"xregion": {"count": 10, "cost_ms": 12.0},
             "direct": {"count": 10, "cost_ms": 3.0}}
    d = r.route("sigB", ["xregion", "direct"], costs=table)
    assert (d.path, d.reason) == ("direct", "measured")
    # and the reverse keeps the batch grouping
    table = {"xregion": {"count": 10, "cost_ms": 2.0},
             "direct": {"count": 10, "cost_ms": 9.0}}
    d = r.route("sigB", ["xregion", "direct"], costs=table)
    assert (d.path, d.reason) == ("xregion", "measured")
