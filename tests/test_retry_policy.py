"""The shared backoff-retry policy (util/retry.py): error-class routing,
exponential backoff with jitter, bounded attempts, retry-after hints, and
the raft-client reconnect adoption."""

import logging
import random
import time

import pytest

from tikv_tpu.raft.region import EpochError, NotLeaderError, Region, RegionEpoch
from tikv_tpu.storage.txn.scheduler import SchedTooBusy
from tikv_tpu.util import retry
from tikv_tpu.util.metrics import REGISTRY
from tikv_tpu.util.retry import (
    DeadlineExceeded,
    RetryPolicy,
    Retrier,
    ServerBusyError,
    classify,
    deadline_from_context,
    wait_until,
)


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_error_class_routing():
    assert classify(NotLeaderError(1, 2)) == "not_leader"
    assert classify(EpochError(Region(1, b"", b"", RegionEpoch(), []))) == "epoch"
    assert classify(SchedTooBusy("q full")) == "busy"
    assert classify(ServerBusyError()) == "busy"
    assert classify(TimeoutError("t")) == "timeout"
    assert classify(DeadlineExceeded("d")) == "deadline"
    assert classify(AssertionError("a")) == "suspect"
    assert classify(KeyError("k")) == "suspect"
    assert classify(ValueError("v")) == "permanent"


def test_retry_class_attribute_overrides_routing():
    e = KeyError("out of range")
    e.retry_class = "permanent"
    assert classify(e) == "permanent"
    r = Retrier(site="t")
    assert r.should_retry(e) is None


# ---------------------------------------------------------------------------
# backoff curve
# ---------------------------------------------------------------------------

def test_backoff_exponential_and_capped():
    p = RetryPolicy(base_s=0.02, max_s=1.0, multiplier=2.0, jitter=0.0)
    vals = [p.backoff(i) for i in range(1, 10)]
    assert vals[0] == pytest.approx(0.02)
    assert vals[1] == pytest.approx(0.04)
    assert all(b >= a for a, b in zip(vals, vals[1:]))
    assert vals[-1] == 1.0  # hard ceiling with jitter off

    # jitter applies AFTER the ceiling: at saturation callers spread over
    # [max_s*(1-j), max_s*(1+j)] instead of collapsing to exactly max_s —
    # N clients backing off a dead peer must not re-sync into lockstep
    pj = RetryPolicy(base_s=0.02, max_s=1.0, jitter=0.2)
    rng = random.Random(7)
    saturated = [pj.backoff(i, rng) for i in range(20, 32)]
    assert all(0.8 <= b <= 1.2 for b in saturated), saturated
    assert len({round(b, 6) for b in saturated}) > 1, "jitter collapsed"


def test_busy_retry_after_hint_dominates_backoff():
    r = Retrier(RetryPolicy(base_s=0.001, max_s=0.002), site="t")
    assert r.should_retry(ServerBusyError(retry_after_s=0.25)) >= 0.25
    # without a hint the computed curve applies (ceiling + post-clamp jitter)
    assert r.should_retry(ServerBusyError()) <= 0.002 * 1.2


def test_sched_too_busy_carries_retry_after():
    e = SchedTooBusy("q", retry_after_s=0.125)
    r = Retrier(site="t")
    assert r.should_retry(e) >= 0.125


def test_busy_hint_dominates_backoff_under_concurrent_callers():
    """ISSUE 15 regression: every ``busy``-class shed — scheduler
    busy_reject, tenant-quota shed, txn SchedTooBusy — carries a NON-ZERO
    ``retry_after_s``, and with many callers retrying concurrently the
    hint dominates each caller's early backoff curve (the server's drain
    estimate, not the client's tiny base_s, paces the herd)."""
    import threading

    hint = 0.2
    policy = RetryPolicy(base_s=0.001, max_s=2.0, jitter=0.2)
    sleeps_by_caller: dict[int, list[float]] = {}
    mu = threading.Lock()

    def caller(idx: int):
        r = Retrier(policy, site="busy_herd")
        mine = []
        # first 6 attempts: the curve (0.001..0.032 * jitter) sits far
        # below the hint — every sleep must be >= the hint anyway
        for _ in range(6):
            d = r.should_retry(ServerBusyError("full", retry_after_s=hint))
            assert d is not None
            mine.append(d)
        with mu:
            sleeps_by_caller[idx] = mine

    threads = [threading.Thread(target=caller, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert len(sleeps_by_caller) == 8
    for mine in sleeps_by_caller.values():
        assert all(d >= hint for d in mine), mine
    # the hint floor: even a zero-configured busy knob yields > 0 on the
    # wire (scheduler floors at 1ms; SchedTooBusy floors its drain hint)
    from tikv_tpu.storage.txn.scheduler import Scheduler
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.txn import commands as cmds
    from tikv_tpu.storage.txn_types import Key, Mutation

    sched = Scheduler(LocalEngine(BTreeEngine()), pool_size=64,
                      pending_write_threshold=1)
    try:
        with sched._mu:
            sched._inflight = 1  # at threshold: next submit is too busy
        with pytest.raises(SchedTooBusy) as ei:
            sched.submit(cmds.Prewrite(
                [Mutation.put(Key.from_raw(b"k"), b"v")], b"k", 10))
        assert ei.value.retry_after_s >= 0.001
    finally:
        with sched._mu:
            sched._inflight = 0
        sched.stop()


# ---------------------------------------------------------------------------
# data_not_ready: the watermark-aware class (ISSUE 7 bugfix satellite)
# ---------------------------------------------------------------------------

def _dnr(read_ts, resolved):
    from tikv_tpu.raft.raftkv import RaftKv

    return RaftKv.DataNotReadyError(1, read_ts, resolved)


def test_data_not_ready_routes_retryable_not_permanent():
    """The PR-7 bugfix: DataNotReadyError used to classify ``permanent``
    and was never retried — now it is its own retryable class."""
    assert classify(_dnr(100, 50)) == "data_not_ready"
    r = Retrier(RetryPolicy(base_s=0.001, max_s=0.002, jitter=0.0), site="t")
    assert r.should_retry(_dnr(100, 50)) is not None


def test_data_not_ready_hint_derived_from_watermark_lag():
    from tikv_tpu.util.retry import TSO_PHYSICAL_SHIFT, data_not_ready_hint

    # logical test-clock lag: ~1ms per unit, capped
    assert data_not_ready_hint(_dnr(120, 100)) == pytest.approx(0.02)
    assert data_not_ready_hint(_dnr(10_000, 0)) == pytest.approx(0.1)
    # physical TSO lag (ms << 18): converts exactly, capped at 1s
    e = _dnr(2_000 << TSO_PHYSICAL_SHIFT, 1_750 << TSO_PHYSICAL_SHIFT)
    assert data_not_ready_hint(e) == pytest.approx(0.25)
    e = _dnr(60_000 << TSO_PHYSICAL_SHIFT, 0)
    assert data_not_ready_hint(e) == pytest.approx(1.0)
    # the retrier's sleep honors the derived hint over a tiny curve
    r = Retrier(RetryPolicy(base_s=0.0001, max_s=0.0002, jitter=0.0), site="t")
    assert r.should_retry(_dnr(120, 100)) >= 0.02


def test_data_not_ready_call_loop_waits_then_succeeds():
    calls = [0]

    def fn():
        calls[0] += 1
        if calls[0] < 3:
            raise _dnr(200, 100)  # watermark 100 behind
        return "served"

    slept = []
    assert retry.call(fn, site="t", sleep=slept.append) == "served"
    assert calls[0] == 3
    assert all(s >= 0.1 for s in slept), "backoff must wait for the watermark"


# ---------------------------------------------------------------------------
# call(): the loop
# ---------------------------------------------------------------------------

def test_call_retries_transient_then_succeeds():
    calls = [0]

    def fn():
        calls[0] += 1
        if calls[0] < 4:
            raise NotLeaderError(1, None)
        return "served"

    slept = []
    assert retry.call(fn, site="t", sleep=slept.append) == "served"
    assert calls[0] == 4 and len(slept) == 3


def test_call_raises_permanent_immediately():
    calls = [0]

    def fn():
        calls[0] += 1
        raise ValueError("real bug")

    with pytest.raises(ValueError):
        retry.call(fn, site="t", sleep=lambda s: None)
    assert calls[0] == 1


def test_call_deadline_bounds_the_loop():
    clock = [0.0]

    def fn():
        clock[0] += 0.5
        raise TimeoutError("still nothing")

    with pytest.raises(TimeoutError):
        retry.call(fn, site="t", timeout=2.0, sleep=lambda s: None,
                   clock=lambda: clock[0])
    assert clock[0] <= 3.0  # stopped near the deadline, not unbounded


def test_suspect_errors_bounded_and_logged():
    policy = RetryPolicy(base_s=0.0, jitter=0.0,
                         class_attempts={"suspect": 3})
    calls = [0]

    def fn():
        calls[0] += 1
        raise AssertionError("no leader yet... or a bug")

    # capture with a handler ON the retry logger, not caplog: once any test
    # emits through util/logger.py the "tikv_tpu" root gets propagate=False,
    # so records never reach caplog's root handler in a full-suite run
    records: list[logging.LogRecord] = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    log = logging.getLogger("tikv_tpu.retry")
    handler = _Capture(level=logging.WARNING)
    old_level = log.level
    log.addHandler(handler)
    log.setLevel(logging.WARNING)
    try:
        with pytest.raises(AssertionError):
            retry.call(fn, policy=policy, site="bounded", sleep=lambda s: None)
    finally:
        log.removeHandler(handler)
        log.setLevel(old_level)
    assert calls[0] == 4  # 3 absorbed failures + the final raise
    assert any("suspect" in rec.getMessage() for rec in records)


def test_retry_metrics_by_site_and_class():
    c = REGISTRY.counter("tikv_client_retry_total")
    before = c.get(site="metrics_site", error_class="not_leader")

    def fn():
        raise NotLeaderError(3, None)

    r = Retrier(RetryPolicy(base_s=0.0, jitter=0.0, max_attempts=2), site="metrics_site")
    assert r.should_retry(NotLeaderError(3, None)) is not None
    assert c.get(site="metrics_site", error_class="not_leader") == before + 1


# ---------------------------------------------------------------------------
# wait_until + deadlines
# ---------------------------------------------------------------------------

def test_wait_until_polls_to_success_and_times_out():
    state = {"n": 0}

    def pred():
        state["n"] += 1
        return state["n"] >= 3

    assert wait_until(pred, timeout=5.0, interval=0.0, sleep=lambda s: None)
    with pytest.raises(TimeoutError, match="nope"):
        wait_until(lambda: False, timeout=0.05, interval=0.01, desc="nope")


def test_deadline_from_context_spellings():
    assert deadline_from_context(None) is None
    assert deadline_from_context({}) is None
    assert deadline_from_context({"deadline": 123.5}) == 123.5
    d = deadline_from_context({"timeout_ms": 500}, clock=lambda: 10.0)
    assert d == pytest.approx(10.5)
    # explicit deadline wins over timeout_ms
    assert deadline_from_context({"deadline": 1.0, "timeout_ms": 500}) == 1.0


# ---------------------------------------------------------------------------
# raft-client reconnect adoption
# ---------------------------------------------------------------------------

def test_raft_client_reconnect_backoff_grows():
    """Consecutive connect failures push down_until out on the shared
    exponential policy (no more constant 0.5s hammering), and a real
    connect resets the streak."""
    import socket as socketlib
    import threading

    from tikv_tpu.server.raft_client import RaftClient

    client = RaftClient(resolver=lambda sid: None)  # unresolvable store
    try:
        conn = client._conn_for(9)
        gaps = []
        for _ in range(4):
            conn.down_until = 0.0  # force the next probe
            with conn.send_mu:
                assert not conn._connect_locked()
            gaps.append(conn.down_until - time.monotonic())
        assert conn.connect_failures == 4
        assert gaps[0] > 0
        # exponential: the 4th gap is well beyond the 1st even under jitter
        assert gaps[3] > gaps[0] * 2
    finally:
        client.close()

    # a successful connect resets the failure streak
    srv = socketlib.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    accepted = threading.Thread(target=lambda: srv.accept(), daemon=True)
    accepted.start()
    client = RaftClient(resolver=lambda sid: srv.getsockname())
    try:
        conn = client._conn_for(1)
        conn.connect_failures = 5
        with conn.send_mu:
            assert conn._connect_locked()
        assert conn.connect_failures == 0
    finally:
        client.close()
        srv.close()
