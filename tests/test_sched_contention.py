"""Scheduler under write contention: wake-up chains, fairness, flow control
(scheduler.rs:277-683 + latch.rs:141 behaviors, exercised through the real
Percolator command path)."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn.commands import Commit, Prewrite
from tikv_tpu.storage.txn.latches import Latches
from tikv_tpu.storage.txn_types import Key, Mutation
from tikv_tpu.storage.txn.scheduler import Scheduler, SchedTooBusy


class _TsOracle:
    def __init__(self):
        self._mu = threading.Lock()
        self._ts = 0

    def next(self) -> int:
        with self._mu:
            self._ts += 1
            return self._ts


def _txn(storage, ts, key, value):
    """One Percolator write txn, retrying on lock/write conflicts the way a
    client does (the holder commits and releases; we re-prewrite fresh)."""
    while True:
        start = ts.next()
        r = storage.sched_txn_command(
            Prewrite([Mutation.put(Key.from_raw(key), value)], key, start_ts=start))
        if r.get("errors"):
            time.sleep(0.001)
            continue
        commit = ts.next()
        storage.sched_txn_command(Commit([Key.from_raw(key)], start, commit))
        return start, commit


def test_wakeup_chain_hands_off_parked_commands():
    """Three commands on one key: the first release wakes exactly the second
    (not a broadcast), and all run in FIFO order."""
    lat = Latches(16)
    c1, c2, c3 = lat.gen_cid(), lat.gen_cid(), lat.gen_cid()
    g1, s1 = lat.acquire(c1, [b"k"], payload="t1")
    g2, s2 = lat.acquire(c2, [b"k"], payload="t2")
    g3, s3 = lat.acquire(c3, [b"k"], payload="t3")
    assert g1 and not g2 and not g3
    assert lat.release(c1, s1) == ["t2"]  # chain: exactly the next in line
    assert lat.release(c2, s2) == ["t3"]
    assert lat.release(c3, s3) == []


def test_ycsb_a_contention_bounded_p99():
    """YCSB-A shape: 8 writer threads, zipf-ish hot keys, 50/50 read-update.
    Every txn commits, reads see committed values only, and update latency
    p99 stays bounded (no starvation under the latch queues)."""
    storage = Storage()
    ts = _TsOracle()
    keys = [b"u%03d" % i for i in range(16)]  # hot keyspace: heavy overlap
    rng = np.random.default_rng(0)
    lat_mu = threading.Lock()
    latencies: list[float] = []
    errors: list[BaseException] = []
    N = 40

    def worker(wid: int):
        r = np.random.default_rng(wid)
        try:
            for i in range(N):
                key = keys[int(r.zipf(1.5)) % len(keys)]
                if r.random() < 0.5:
                    while True:  # reads resolve-and-retry on live locks
                        try:
                            storage.get(key, ts.next())
                            break
                        except Exception:
                            time.sleep(0.001)
                else:
                    t0 = time.perf_counter()
                    _txn(storage, ts, key, b"w%d-%d" % (wid, i))
                    with lat_mu:
                        latencies.append(time.perf_counter() - t0)
        except BaseException as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert all(not t.is_alive() for t in threads), "starved/stuck worker"
    lats = np.array(latencies)
    assert len(lats) > 50
    p50, p99 = np.percentile(lats, [50, 99])
    # no starvation: the tail tracks the median within a generous factor
    assert p99 < max(40 * p50, 0.5), f"p99 {p99:.4f}s vs p50 {p50:.4f}s"
    # every committed value is readable
    for key in keys:
        storage.get(key, ts.next())
    st = storage.scheduler.stats
    assert st["scheduled"] > 0 and st["woken"] > 0, st


def test_per_key_fifo_fairness():
    """Many writers on ONE key: commit order must equal submission order
    (the latch queue is FIFO — no barging, no starvation)."""
    storage = Storage()
    ts = _TsOracle()
    order: list[int] = []
    mu = threading.Lock()
    barrier = threading.Barrier(6)

    def worker(wid: int):
        barrier.wait()
        for i in range(10):
            _txn(storage, ts, b"contended", b"v%d-%d" % (wid, i))
            with mu:
                order.append(wid)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # every worker finished all its txns — nobody starved
    assert len(order) == 60
    assert set(order) == set(range(6))


def test_flow_control_rejects_when_saturated():
    """Normal-priority submissions beyond the pending threshold fail fast
    with SchedTooBusy; high-priority ones bypass the gate."""
    storage = Storage()
    sched = Scheduler(storage.engine, pool_size=1, pending_write_threshold=4)
    release = threading.Event()

    class _Slow:
        exclusive = False

        def latch_keys(self):
            return [b"slow"]

        def process_write(self, snapshot):
            release.wait(10)
            from tikv_tpu.storage.mvcc.txn import MvccTxn

            return MvccTxn(1), None

    tasks = []
    # fill: 1 running + queued up to the threshold
    for _ in range(4):
        tasks.append(sched.submit(_Slow()))
    with pytest.raises(SchedTooBusy):
        sched.submit(_Slow())
    assert sched.stats["too_busy"] == 1
    # high priority bypasses the busy gate
    tasks.append(sched.submit(_Slow(), ctx={"priority": "high"}))
    release.set()
    for t in tasks:
        assert t.done.wait(10)
    sched.stop()


def test_high_priority_jumps_the_queue():
    """With one worker, a high-priority command submitted later runs before
    queued normal ones (the reference's separate high-priority pool)."""
    sched = Scheduler(Storage().engine, pool_size=1, pending_write_threshold=64)
    order = []
    gate = threading.Event()

    def make(tag, key):
        class _Cmd:
            exclusive = False

            def latch_keys(self):
                return [key]

            def process_write(self, snapshot):
                if tag == "blocker":
                    gate.wait(10)
                order.append(tag)
                from tikv_tpu.storage.mvcc.txn import MvccTxn

                return MvccTxn(1), None

        return _Cmd()

    t0 = sched.submit(make("blocker", b"a"))  # occupies the single worker
    time.sleep(0.05)
    t1 = sched.submit(make("normal", b"b"))
    t2 = sched.submit(make("high", b"c"), ctx={"priority": "high"})
    gate.set()
    for t in (t0, t1, t2):
        assert t.done.wait(10)
    assert order == ["blocker", "high", "normal"]
    sched.stop()


def test_submit_failure_does_not_leak_capacity():
    """A command whose latch_keys() raises must not consume an inflight slot
    forever (flow control would wedge shut after enough failures)."""
    sched = Scheduler(Storage().engine, pool_size=1, pending_write_threshold=2)

    class _Bad:
        exclusive = False

        def latch_keys(self):
            raise ValueError("malformed key")

    for _ in range(5):
        with pytest.raises(ValueError):
            sched.submit(_Bad())
    assert sched._inflight == 0
    sched.stop()
    with pytest.raises(RuntimeError):
        sched.submit(_Bad())
