"""Differential tests for the zone-tiled clustered warm path (jax_zone.py).

Every case runs a DAG through the device warm-cache path with small tiles (so
full / empty / partial tiles all occur) and asserts the encoded response is
byte-identical to the CPU pipeline — the same oracle contract as
test_jax_eval.py, plus assertions that the zone path (not the generic scan)
actually served the query where expected.
"""

import numpy as np
import pytest

from tikv_tpu.copr import jax_zone
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.cache import ColumnBlockCache
from tikv_tpu.copr.dag import (
    Aggregation,
    BatchExecutorsRunner,
    DagRequest,
    Limit,
    Selection,
    TableScan,
    TopN,
)
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.executors import FixtureScanSource
from tikv_tpu.copr.jax_eval import JaxDagEvaluator
from tikv_tpu.copr.rpn import call, col, const_bytes, const_decimal, const_int
from tikv_tpu.copr.table import encode_row, record_key

from copr_fixtures import TABLE_ID


@pytest.fixture(autouse=True)
def small_tiles(monkeypatch):
    """Small tiles so a few thousand rows produce many tiles with mixed
    full/empty/partial classifications."""
    monkeypatch.setattr(jax_zone, "TILE_ROWS", 64)


def mixed_table_kvs(n, seed=0, with_nulls=False):
    """id, v int (sortable range col), d decimal(2), tag varchar (dict-coded
    group key), w int.  Optional NULLs in v and tag.

    Returns (cols, kvs, cache): kvs feed the CPU oracle; the pre-filled
    ColumnBlockCache is the decoded image with dict-coded varchars sharing
    ONE dictionary object across blocks (the stable-dictionary contract the
    zone path keys on — built directly, the same way bench.build_cache does,
    because the row decoder only dictionary-encodes fixed-layout rows)."""
    rng = np.random.default_rng(seed)
    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.decimal_type(2)),
        ColumnInfo(4, FieldType.varchar()),
        ColumnInfo(5, FieldType.int64()),
    ]
    v = rng.integers(0, 10_000, n)
    d = rng.integers(0, 5_000, n)
    tags = [b"alpha", b"beta", b"gamma"]
    t = rng.integers(0, 3, n)
    w = rng.integers(-50, 50, n)
    null_v = rng.random(n) < 0.05 if with_nulls else np.zeros(n, dtype=bool)
    null_t = rng.random(n) < 0.05 if with_nulls else np.zeros(n, dtype=bool)
    non_handle = cols[1:]
    kvs = []
    for i in range(n):
        row = [
            None if null_v[i] else int(v[i]),
            int(d[i]),
            None if null_t[i] else tags[t[i]],
            int(w[i]),
        ]
        kvs.append((record_key(TABLE_ID, i), encode_row(non_handle, row)))

    from tikv_tpu.copr.datatypes import Column, EvalType

    dictionary = np.empty(3, dtype=object)
    dictionary[:] = sorted(tags)
    code_of = {tag: j for j, tag in enumerate(sorted(tags))}
    codes = np.array([code_of[tags[ti]] for ti in t], dtype=np.int64)
    handles = np.arange(n, dtype=np.int64)
    cache = ColumnBlockCache()
    block = 2048  # long group runs so boundary/pad tiles stay a small fraction
    for s in range(0, n, block):
        e = min(s + block, n)
        m = e - s
        z = np.zeros(m, dtype=bool)
        cache.add(
            [
                Column(EvalType.INT, handles[s:e], z.copy()),
                Column(EvalType.INT, np.where(null_v[s:e], 0, v[s:e]), null_v[s:e].copy()),
                Column(EvalType.DECIMAL, d[s:e].copy(), z.copy(), 2),
                Column(EvalType.BYTES, codes[s:e].copy(), null_t[s:e].copy(), 0, dictionary),
                Column(EvalType.INT, w[s:e].copy(), z.copy()),
            ],
            m,
        )
    cache.filled = True
    return cols, kvs, cache


def run_warm(executors, fixture, output_offsets=None):
    cols, kvs, cache = fixture
    dag = DagRequest(executors=executors, output_offsets=output_offsets)
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
    ev = JaxDagEvaluator(dag, block_rows=2048)
    warm = ev.run(None, cache=cache)
    return cpu, warm, ev


def zone_served(ev) -> bool:
    zone = getattr(ev, "_zone", None)
    return bool(zone) and zone.served > 0


FIX = mixed_table_kvs(6000)
NFIX = mixed_table_kvs(6000, seed=1, with_nulls=True)
COLS, KVS, CACHE = FIX
NCOLS, NKVS, NCACHE = NFIX


def test_zone_grouped_range_predicate():
    """Grouped agg with a recognized range conjunct: the bench Q1 shape."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection([call("le", col(1), const_int(7000))]),
            Aggregation(
                group_by=[col(3)],
                agg_funcs=[
                    AggDescriptor("sum", col(1)),
                    AggDescriptor("avg", col(2)),
                    AggDescriptor("count", None),
                ],
            ),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_ungrouped_multi_conjunct():
    """Q6 shape: several conjuncts, expression aggregate, no grouping."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection(
                [
                    call("ge", col(1), const_int(2000)),
                    call("lt", col(1), const_int(3000)),
                    call("ge", col(2), const_decimal(500, 2)),
                ]
            ),
            Aggregation(group_by=[], agg_funcs=[AggDescriptor("sum", call("multiply", col(2), col(4)))]),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_min_max_and_negative_values():
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection([call("gt", col(1), const_int(1000))]),
            Aggregation(
                group_by=[col(3)],
                agg_funcs=[
                    AggDescriptor("min", col(4)),
                    AggDescriptor("max", col(4)),
                    AggDescriptor("sum", col(4)),
                ],
            ),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_nulls_in_group_key_and_values():
    """NULLs force tiles partial; NULL group keys form their own group."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, NCOLS),
            Selection([call("le", col(1), const_int(8000))]),
            Aggregation(
                group_by=[col(3)],
                agg_funcs=[
                    AggDescriptor("sum", col(1)),
                    AggDescriptor("count", col(1)),
                    AggDescriptor("avg", col(1)),
                    AggDescriptor("count", None),
                ],
            ),
        ],
        NFIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_unrecognized_conjunct_still_exact():
    """A non col-vs-const conjunct classifies everything partial; with the
    partial fraction at 100% the zone path declines and the generic warm
    path serves — response must still match."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection([call("lt", col(1), call("plus", col(4), const_int(5000)))]),
            Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("count", None)]),
        ],
        FIX,
    )
    assert warm.encode() == cpu.encode()


def test_zone_all_tiles_empty():
    """A predicate no row satisfies: zero groups, empty response."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection([call("gt", col(1), const_int(10_000_000))]),
            Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(1))]),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_eq_and_flipped_conjuncts():
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            # const-on-the-left flavors exercise the flipped recognition
            Selection([call("ge", const_int(9000), col(1)), call("ne", col(2), const_decimal(600000, 2))]),
            Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(4))]),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_post_agg_topn_limit():
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection([call("le", col(1), const_int(9500))]),
            Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(1))]),
            TopN([(col(0), True)], 2),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_no_selection():
    """No conjuncts at all: every tile is full (minus pad tiles)."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(1)), AggDescriptor("count", None)]),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_var_pop_served():
    """var_pop rides the zone path: int sums + f64 sum-of-squares per tile
    (the same carry layout as the CPU AggState) — covering bare int and
    NEGATIVE-valued columns, a DECIMAL column, and an EXPRESSION argument."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, COLS),
            Selection([call("le", col(1), const_int(7000))]),
            Aggregation(group_by=[col(3)], agg_funcs=[
                AggDescriptor("var_pop", col(1)),
                AggDescriptor("var_pop", col(4)),
                AggDescriptor("var_pop", col(2)),  # decimal(2)
                AggDescriptor("var_pop", call("multiply", col(1), col(4))),
                AggDescriptor("count", None),
            ]),
        ],
        FIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_var_pop_with_nulls():
    """NULL-bearing argument column: null tiles are forced partial and the
    partial path's live-mask gates the sum-of-squares."""
    cpu, warm, ev = run_warm(
        [
            TableScan(TABLE_ID, NCOLS),
            Selection([call("le", col(1), const_int(8000))]),
            Aggregation(group_by=[col(3)], agg_funcs=[
                AggDescriptor("var_pop", col(1)),
                AggDescriptor("count", col(1)),
            ]),
        ],
        NFIX,
    )
    assert zone_served(ev)
    assert warm.encode() == cpu.encode()


def test_zone_repeat_and_second_evaluator_share_layout():
    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, COLS),
            Selection([call("le", col(1), const_int(7000))]),
            Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(1))]),
        ]
    )
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(KVS)).handle_request()
    ev = JaxDagEvaluator(dag, block_rows=2048)
    w1 = ev.run(None, cache=CACHE)
    w2 = ev.run(None, cache=CACHE)
    assert w1.encode() == w2.encode() == cpu.encode()
    ev2 = JaxDagEvaluator(dag, block_rows=512)
    assert ev2.run(None, cache=CACHE).encode() == cpu.encode()


@pytest.mark.parametrize("seed", [11, 22, 33, 44, 55, 66])
def test_zone_differential_fuzz(seed):
    """Randomized plans over randomized tables: every response must match
    the CPU pipeline byte-for-byte whichever path (zone / generic / fused)
    serves it.  Seeded — failures reproduce exactly."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(3000, 9000))
    from tikv_tpu.copr.datatypes import Column, EvalType

    cols_info = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.decimal_type(2)),
        ColumnInfo(4, FieldType.varchar()),
        ColumnInfo(5, FieldType.varchar()),
        ColumnInfo(6, FieldType.int64()),
    ]
    v = rng.integers(-5000, 5000, n)
    d = rng.integers(0, 100000, n)
    tags_a = [b"aa", b"bb", b"cc"]
    tags_b = [b"xx", b"yy"]
    ta = rng.integers(0, 3, n)
    tb = rng.integers(0, 2, n)
    w = rng.integers(0, 1 << 30, n)
    null_v = rng.random(n) < float(rng.choice([0.0, 0.05, 0.3]))
    kvs = [
        (record_key(TABLE_ID, i), encode_row(cols_info[1:], [
            None if null_v[i] else int(v[i]), int(d[i]),
            tags_a[ta[i]], tags_b[tb[i]], int(w[i]),
        ]))
        for i in range(n)
    ]
    da = np.empty(3, dtype=object); da[:] = tags_a
    db = np.empty(2, dtype=object); db[:] = tags_b
    cache = ColumnBlockCache()
    B = int(rng.choice([1024, 2048, 4096]))
    handles = np.arange(n, dtype=np.int64)
    for s in range(0, n, B):
        e = min(s + B, n); m = e - s
        z = lambda: np.zeros(m, dtype=bool)
        cache.add([
            Column(EvalType.INT, handles[s:e], z()),
            Column(EvalType.INT, np.where(null_v[s:e], 0, v[s:e]), null_v[s:e].copy()),
            Column(EvalType.DECIMAL, d[s:e].copy(), z(), 2),
            Column(EvalType.BYTES, ta[s:e].astype(np.int64), z(), 0, da),
            Column(EvalType.BYTES, tb[s:e].astype(np.int64), z(), 0, db),
            Column(EvalType.INT, w[s:e].copy(), z()),
        ], m)
    cache.filled = True

    conj_pool = [
        lambda: call("le", col(1), const_int(int(rng.integers(-4000, 6000)))),
        lambda: call("gt", col(1), const_int(int(rng.integers(-6000, 4000)))),
        lambda: call("ge", col(2), const_decimal(int(rng.integers(0, 90000)), 2)),
        lambda: call("ne", col(1), const_int(int(rng.integers(-5000, 5000)))),
        lambda: call("lt", col(1), call("plus", col(5), const_int(100))),  # unrecognized
    ]
    agg_pool = [
        lambda: AggDescriptor("sum", col(1)),
        lambda: AggDescriptor("count", None),
        lambda: AggDescriptor("avg", col(2)),
        lambda: AggDescriptor("min", col(1)),
        lambda: AggDescriptor("max", col(2)),
        lambda: AggDescriptor("count", col(1)),
        lambda: AggDescriptor("sum", call("multiply", col(2), col(1))),
        lambda: AggDescriptor("var_pop", col(1)),
        # outside the zone op set: exercises the generic warm paths' byte
        # parity under the same randomized tables
        lambda: AggDescriptor("first", col(1)),
        lambda: AggDescriptor("bit_xor", col(5)),
        lambda: AggDescriptor("bit_and", col(5)),
        lambda: AggDescriptor("bit_or", col(5)),
    ]
    for _case in range(6):
        n_conj = int(rng.integers(0, 3))
        conds = [conj_pool[int(rng.integers(0, len(conj_pool)))]() for _ in range(n_conj)]
        group = [[], [col(3)], [col(3), col(4)]][int(rng.integers(0, 3))]
        aggs = [agg_pool[int(rng.integers(0, len(agg_pool)))]()
                for _ in range(int(rng.integers(1, 4)))]
        execs = [TableScan(TABLE_ID, cols_info)]
        if conds:
            execs.append(Selection(conds))
        execs.append(Aggregation(group_by=group, agg_funcs=aggs))
        dag = DagRequest(executors=execs)
        cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
        ev = JaxDagEvaluator(dag, block_rows=B)
        warm = ev.run(None, cache=cache)
        assert warm.encode() == cpu.encode(), (
            f"seed={seed} case={_case} conds={n_conj} group={len(group)} "
            f"aggs={[a.op for a in aggs]}"
        )

    # raw TopN with a varchar payload over the same cache (device top-K merge)
    for _t in range(2):
        desc = bool(rng.integers(0, 2))
        execs = [
            TableScan(TABLE_ID, cols_info),
            Selection([call("gt", col(1), const_int(int(rng.integers(-4000, 2000))))]),
            TopN([(col(1), desc), (col(0), not desc)], int(rng.integers(1, 60))),
        ]
        dag = DagRequest(executors=execs)
        cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
        dev = JaxDagEvaluator(dag, block_rows=B).run(None, cache=cache)
        assert dev.encode() == cpu.encode(), f"seed={seed} topn case={_t}"


def test_zone_failure_falls_through_to_generic(monkeypatch):
    """A zone-path exception (backend/compiler failure on a new accelerator)
    must fall through to the generic warm path and be remembered — never
    surface to the caller."""
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("le", col(1), const_int(7000))]),
        Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(1))]),
    ])
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(KVS)).handle_request()
    ev = JaxDagEvaluator(dag, block_rows=2048)
    zone = ev._zone_evaluator()
    calls = {"n": 0}

    def boom(cache):
        calls["n"] += 1
        raise RuntimeError("simulated backend failure")

    monkeypatch.setattr(zone, "_try_run_inner", boom)
    assert ev.run(None, cache=CACHE).encode() == cpu.encode()
    assert CACHE in zone._declined  # remembered: no retry storm
    assert zone.failed >= 1 and "simulated" in zone.last_error
    assert ev.run(None, cache=CACHE).encode() == cpu.encode()
    assert calls["n"] >= 1


def test_full_tile_program_shared_across_selection_constants():
    """Distinct selection CONSTANTS must reuse one compiled full-tile
    program: the full-tile fn never evaluates selection row-wise (the
    classification arrives as w_full), so keying its cache on the full plan
    signature churned the per-layout cache and recompiled identical XLA
    (advisor round 5)."""
    fix = mixed_table_kvs(6000, seed=7)
    _cols, _kvs, cache = fix
    consts = [3000, 4000, 5000, 6000]
    for c in consts:
        cpu, warm, ev = run_warm(
            [
                TableScan(TABLE_ID, fix[0]),
                Selection([call("le", col(1), const_int(c))]),
                Aggregation(group_by=[col(3)], agg_funcs=[AggDescriptor("sum", col(1))]),
            ],
            fix,
        )
        assert zone_served(ev)
        assert warm.encode() == cpu.encode()
    layout_fns = cache.blocks[0].device
    for sig, entry in layout_fns.items():
        if sig[0] == "zone_layout":
            fns = entry.__dict__.get("_zone_fns", {})
            full_keys = [k for k in fns if k[0] == "full"]
            assert len(full_keys) == 1, full_keys  # shared across constants
            partial_keys = [k for k in fns if k[0] == "partial"]
            assert len(partial_keys) >= 2  # partial programs DO depend on constants
            break
    else:
        raise AssertionError("no zone layout pinned")
