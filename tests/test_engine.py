"""Engine conformance suite — any KvEngine implementation must pass.

Plays the role of the reference's components/engine_traits_tests crate: the
same assertions run against every registered engine (BTreeEngine now, the
native C++ engine once wired in).
"""

import pytest

from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE, WriteBatch

ENGINES = {"btree": BTreeEngine}

try:
    from tikv_tpu.native.engine import NativeEngine, native_available

    if native_available():
        ENGINES["native"] = NativeEngine
except ImportError:
    pass


@pytest.fixture(params=sorted(ENGINES))
def engine(request):
    return ENGINES[request.param]()


def test_point_ops(engine):
    assert engine.get(b"k") is None
    engine.put_cf(CF_DEFAULT, b"k", b"v")
    assert engine.get(b"k") == b"v"
    engine.put_cf(CF_DEFAULT, b"k", b"v2")
    assert engine.get(b"k") == b"v2"
    engine.delete_cf(CF_DEFAULT, b"k")
    assert engine.get(b"k") is None


def test_cf_isolation(engine):
    engine.put_cf(CF_DEFAULT, b"k", b"d")
    engine.put_cf(CF_LOCK, b"k", b"l")
    engine.put_cf(CF_WRITE, b"k", b"w")
    assert engine.get_cf(CF_DEFAULT, b"k") == b"d"
    assert engine.get_cf(CF_LOCK, b"k") == b"l"
    assert engine.get_cf(CF_WRITE, b"k") == b"w"


def test_write_batch_atomic_order(engine):
    wb = WriteBatch()
    wb.put(b"a", b"1")
    wb.put(b"a", b"2")
    wb.delete(b"b")
    wb.put(b"b", b"3")
    engine.write(wb)
    assert engine.get(b"a") == b"2"
    assert engine.get(b"b") == b"3"


def test_delete_range(engine):
    for i in range(10):
        engine.put_cf(CF_DEFAULT, bytes([i]), b"v")
    wb = WriteBatch()
    wb.delete_range_cf(CF_DEFAULT, bytes([3]), bytes([7]))
    engine.write(wb)
    remaining = [k for k, _ in engine.scan_cf(CF_DEFAULT, b"", None)]
    assert remaining == [bytes([i]) for i in [0, 1, 2, 7, 8, 9]]


def test_scan_ranges(engine):
    keys = [b"a", b"b", b"c", b"d", b"e"]
    for k in keys:
        engine.put_cf(CF_DEFAULT, k, k.upper())
    assert [k for k, _ in engine.scan_cf(CF_DEFAULT, b"b", b"d")] == [b"b", b"c"]
    assert [k for k, _ in engine.scan_cf(CF_DEFAULT, b"", None)] == keys
    assert [k for k, _ in engine.scan_cf(CF_DEFAULT, b"b", b"e", reverse=True)] == [b"d", b"c", b"b"]
    assert [k for k, _ in engine.scan_cf(CF_DEFAULT, b"", None, limit=2)] == [b"a", b"b"]


def test_snapshot_isolation(engine):
    engine.put_cf(CF_DEFAULT, b"k", b"v1")
    snap = engine.snapshot()
    engine.put_cf(CF_DEFAULT, b"k", b"v2")
    engine.put_cf(CF_DEFAULT, b"new", b"x")
    assert snap.get_cf(CF_DEFAULT, b"k") == b"v1"
    assert snap.get_cf(CF_DEFAULT, b"new") is None
    assert engine.get(b"k") == b"v2"
    snap2 = engine.snapshot()
    assert snap2.get_cf(CF_DEFAULT, b"k") == b"v2"
    # old snapshot unaffected by later writes
    engine.delete_cf(CF_DEFAULT, b"k")
    assert snap.get_cf(CF_DEFAULT, b"k") == b"v1"
    assert snap2.get_cf(CF_DEFAULT, b"k") == b"v2"


def test_cursor_semantics(engine):
    for k in [b"b", b"d", b"f"]:
        engine.put_cf(CF_DEFAULT, k, b"v")
    cur = engine.snapshot().cursor_cf(CF_DEFAULT)
    assert cur.seek(b"a") and cur.key() == b"b"
    assert cur.seek(b"b") and cur.key() == b"b"
    assert cur.seek(b"c") and cur.key() == b"d"
    assert not cur.seek(b"g")
    assert cur.seek_for_prev(b"g") and cur.key() == b"f"
    assert cur.seek_for_prev(b"d") and cur.key() == b"d"
    assert cur.seek_for_prev(b"c") and cur.key() == b"b"
    assert not cur.seek_for_prev(b"a")
    assert cur.seek_to_first() and cur.key() == b"b"
    assert cur.next() and cur.key() == b"d"
    assert cur.prev() and cur.key() == b"b"
    assert not cur.prev()
    assert cur.seek_to_last() and cur.key() == b"f"
    assert not cur.next()


def test_cursor_bounds(engine):
    for k in [b"a", b"b", b"c", b"d"]:
        engine.put_cf(CF_DEFAULT, k, b"v")
    cur = engine.snapshot().cursor_cf(CF_DEFAULT, lower=b"b", upper=b"d")
    assert cur.seek_to_first() and cur.key() == b"b"
    assert cur.seek_to_last() and cur.key() == b"c"
    assert cur.seek(b"a") and cur.key() == b"b"
    assert not cur.seek(b"d")


def test_bulk_load():
    engine = BTreeEngine()
    engine.put_cf(CF_DEFAULT, b"m", b"old")
    items = [(bytes([i]), bytes([i])) for i in range(5)]
    engine.bulk_load(CF_DEFAULT, items)
    keys = [k for k, _ in engine.scan_cf(CF_DEFAULT, b"", None)]
    assert keys == [bytes([i]) for i in range(5)] + [b"m"]


def test_native_engine_full_stack():
    """The native engine drops in under MVCC + txn + coprocessor unchanged."""
    pytest.importorskip("tikv_tpu.native.engine")
    from tikv_tpu.native.engine import NativeEngine, native_available

    if not native_available():
        pytest.skip("native engine unavailable")
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID, product_kvs
    from tikv_tpu.copr.dag import BatchExecutorsRunner, DagRequest, TableScan
    from tikv_tpu.copr.executors import MvccScanSource
    from tikv_tpu.copr.mvcc_batch import MvccBatchScanSource
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    store = Storage(engine=LocalEngine(NativeEngine()))
    for i, (rk, val) in enumerate(product_kvs()):
        ts = 10 + 2 * i
        r = store.sched_txn_command(Prewrite([Mutation.put(Key.from_raw(rk), val)], rk, ts))
        assert "errors" not in r
        store.sched_txn_command(Commit([Key.from_raw(rk)], ts, ts + 1))
    assert len(store.scan(b"", None, None, 100)) == 6
    snap = store.engine.snapshot(None)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    r1 = BatchExecutorsRunner(dag, MvccScanSource(snap, 100, [record_range(TABLE_ID)])).handle_request()
    assert len(r1.iter_rows()) == 6
    r2 = BatchExecutorsRunner(
        DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS)]),
        MvccBatchScanSource(snap, 100, [record_range(TABLE_ID)]),
    ).handle_request()
    assert r2.encode() == r1.encode()


def test_native_engine_snapshot_sequence_semantics():
    pytest.importorskip("tikv_tpu.native.engine")
    from tikv_tpu.native.engine import NativeEngine, native_available

    if not native_available():
        pytest.skip("native engine unavailable")
    eng = NativeEngine()
    eng.put_cf(CF_DEFAULT, b"k", b"v1")
    s1 = eng.snapshot()
    eng.put_cf(CF_DEFAULT, b"k", b"v2")
    s2 = eng.snapshot()
    eng.delete_cf(CF_DEFAULT, b"k")
    assert s1.get_cf(CF_DEFAULT, b"k") == b"v1"
    assert s2.get_cf(CF_DEFAULT, b"k") == b"v2"
    assert eng.get(b"k") is None
    s1.release()
    s2.release()
    # after releasing snapshots, later writes compact old versions away
    eng.put_cf(CF_DEFAULT, b"k", b"v3")
    assert eng.get(b"k") == b"v3"


def test_native_bulk_load_sorted_and_random():
    """Hinted O(1) appends for ascending streams; random order falls back to
    the O(log n) path with identical content."""
    import random

    from tikv_tpu.native.engine import NativeEngine

    items = [(b"bk%06d" % i, b"v%d" % i) for i in range(5000)]
    ne = NativeEngine()
    ne.bulk_load("default", items)
    rnd = items[:]
    random.Random(3).shuffle(rnd)
    ne2 = NativeEngine()
    ne2.bulk_load("default", rnd)
    s1, s2 = ne.snapshot(), ne2.snapshot()
    assert list(s1.scan_cf("default", b"bk", b"bl")) == list(s2.scan_cf("default", b"bk", b"bl"))
    assert s1.get_cf("default", b"bk004999") == b"v4999"


def test_native_delete_range_after_hinted_inserts():
    from tikv_tpu.native.engine import NativeEngine
    from tikv_tpu.storage.engine import WriteBatch

    ne = NativeEngine()
    ne.bulk_load("default", [(b"k%02d" % i, b"v") for i in range(20)])
    wb = WriteBatch()
    wb.delete_range_cf("default", b"k05", b"k15")
    ne.write(wb)
    snap = ne.snapshot()
    got = [k for k, _ in snap.scan_cf("default", b"k", b"l")]
    assert got == [b"k%02d" % i for i in list(range(5)) + list(range(15, 20))]
