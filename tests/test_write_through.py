"""Write-through region-cache deltas (ISSUE 4 tentpole).

Acceptance contract: with write-through enabled, a warm read after N
committed writes performs ZERO ``scan_delta`` CF_WRITE scans
(counter-asserted via ``stats.deltas``) and responses stay byte-identical to
the scan_delta and cold CPU paths.  A failpoint disabling apply-side
emission (including a mid-batch toggle) must leave responses byte-identical
through the scan_delta fallback.

Unit tests drive :func:`notify_region_write` with exactly the op tuples the
raft apply path emits; the ``raft`` tests run the whole pipeline — txn
scheduler (group commit) → raft propose/apply → ``_exec_data_cmd`` emission
→ warm coprocessor serve — over a real in-process cluster.
"""

from __future__ import annotations

import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID
from fixtures import put_committed

from tikv_tpu.copr.dag import Aggregation, DagRequest, Limit, Selection, TableScan
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.region_cache import notify_region_write, notify_region_write_lost
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_DEFAULT, CF_LOCK, CF_WRITE, WriteBatch
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Lock, LockType, Write, WriteType
from tikv_tpu.util import failpoint

NON_HANDLE = [c for c in PRODUCT_COLUMNS if not c.is_pk_handle]
N_ROWS = 64
REGION = 7


def _engine(n=N_ROWS, v2=False):
    eng = BTreeEngine()
    enc = encode_row_v2 if v2 else encode_row
    for i in range(n):
        name = [b"apple", b"banana", b"cherry"][i % 3]
        put_committed(eng, record_key(TABLE_ID, i),
                      enc(NON_HANDLE, [name, i * 7 % 23, 100 + i]), 90, 100)
    return eng


def _scan_dag():
    return DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), Limit(1 << 20)])


def _sel_dag():
    return DagRequest(executors=[
        TableScan(TABLE_ID, PRODUCT_COLUMNS),
        Selection([call("gt", col(2), const_int(5))]),
    ])


def _agg_dag():
    aggs = [AggDescriptor("sum", col(2)), AggDescriptor("count", None)]
    return DagRequest(executors=[
        TableScan(TABLE_ID, PRODUCT_COLUMNS), Aggregation([col(1)], aggs),
    ])


def _req(dag, ts, apply_index, region_id=REGION):
    return CoprRequest(
        103, dag, [record_range(TABLE_ID)], ts,
        context={"region_id": region_id, "region_epoch": (1, 1),
                 "apply_index": apply_index},
    )


def _pair(eng, **kw):
    warm = Endpoint(LocalEngine(eng), enable_device=True, **kw)
    cold = Endpoint(LocalEngine(eng), enable_device=True, enable_region_cache=False)
    return warm, cold


def commit_ops(eng, raw_key, value, start_ts, commit_ts, force_default=False):
    """Apply a committed write to ``eng`` and return the exact op tuples the
    raft apply path would emit for it (value None = committed DELETE)."""
    k = Key.from_raw(raw_key)
    ops = []
    if value is None:
        w = Write(WriteType.DELETE, start_ts)
    elif len(value) <= 255 and not force_default:
        w = Write(WriteType.PUT, start_ts, short_value=value)
    else:
        w = Write(WriteType.PUT, start_ts)
        ops.append(("put", CF_DEFAULT, k.append_ts(start_ts).encoded, value))
    ops.append(("put", CF_WRITE, k.append_ts(commit_ts).encoded, w.to_bytes()))
    ops.append(("delete", CF_LOCK, k.encoded, None))
    wb = WriteBatch()
    for op, cf, key, val in ops:
        if op == "put":
            wb.put_cf(cf, key, val)
        else:
            wb.delete_cf(cf, key)
    eng.write(wb)
    return ops


def lock_ops(eng, raw_key, start_ts, value=b"x"):
    """A prewrite's lock put (data rides the lock's short value)."""
    k = Key.from_raw(raw_key)
    lock = Lock(LockType.PUT, raw_key, start_ts, ttl=30000, short_value=value)
    eng.put_cf(CF_LOCK, k.encoded, lock.to_bytes())
    return [("put", CF_LOCK, k.encoded, lock.to_bytes())]


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
@pytest.mark.parametrize("mk_dag", [_scan_dag, _sel_dag, _agg_dag],
                         ids=["scan", "selection", "aggregation"])
def test_wt_delta_zero_scan_byte_identical(v2, mk_dag):
    """N committed writes between reads: the warm read folds the buffered
    write-through delta in — outcome 'wt_delta', stats.deltas stays 0 (not
    one CF_WRITE scan) — and bytes match the cold decode exactly."""
    eng = _engine(v2=v2)
    warm, cold = _pair(eng)
    r0 = warm.handle_request(_req(mk_dag(), 200, 3))
    assert r0.metrics["region_cache"] == "miss"

    enc = encode_row_v2 if v2 else encode_row
    ops = []
    ops += commit_ops(eng, record_key(TABLE_ID, 5),
                      enc(NON_HANDLE, [b"durian", 999, 5]), 210, 220)
    ops += commit_ops(eng, record_key(TABLE_ID, 11),
                      enc(NON_HANDLE, [b"apple", 1000, 6]), 210, 220)
    notify_region_write(REGION, ops, 4)

    r1 = warm.handle_request(_req(mk_dag(), 300, 4))
    assert r1.metrics["region_cache"] == "wt_delta"
    assert r1.metrics["region_cache_delta_rows"] == 2
    assert warm.region_cache.stats.deltas == 0, "scan_delta must not run"
    assert warm.region_cache.stats.wt_deltas == 1
    assert r1.data == cold.handle_request(_req(mk_dag(), 300, 4)).data
    # the folded image keeps serving plain hits
    r2 = warm.handle_request(_req(mk_dag(), 300, 4))
    assert r2.metrics["region_cache"] == "hit"
    assert r2.data == r1.data


def test_wt_delta_insert_and_delete_structural():
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    ops = []
    ops += commit_ops(eng, record_key(TABLE_ID, 500),
                      encode_row(NON_HANDLE, [b"elderberry", 7, 1]), 210, 220)
    ops += commit_ops(eng, record_key(TABLE_ID, 0), None, 210, 220)
    notify_region_write(REGION, ops, 4)
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "wt_delta"
    assert warm.region_cache.stats.deltas == 0
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data


def test_wt_delta_large_value_resolves_via_getter():
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    row = encode_row(NON_HANDLE, [b"fig", 77, 88])
    ops = commit_ops(eng, record_key(TABLE_ID, 9), row, 210, 220,
                     force_default=True)
    notify_region_write(REGION, ops, 4,
                        get_default=lambda k: eng.get_cf(CF_DEFAULT, k))
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "wt_delta"
    assert warm.region_cache.stats.deltas == 0
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data


def test_wt_large_value_without_getter_degrades_to_scan_delta():
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    ops = commit_ops(eng, record_key(TABLE_ID, 9),
                     encode_row(NON_HANDLE, [b"fig", 77, 88]), 210, 220,
                     force_default=True)
    notify_region_write(REGION, ops, 4)  # no get_default -> unparseable
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "delta"  # scan_delta fallback
    assert warm.region_cache.stats.wt_lost == 1
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data


def test_wt_lock_blocks_reader_then_commit_serves():
    """A prewrite's lock flows through write-through: the warm read re-scans
    CF_LOCK and raises exactly like the scanners; the commit clears it and
    the next read folds the value in."""
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    notify_region_write(REGION, lock_ops(eng, record_key(TABLE_ID, 4), 250), 4)
    with pytest.raises(Exception, match="locked"):
        warm.handle_request(_req(_scan_dag(), 300, 4))
    with pytest.raises(Exception, match="locked"):
        cold.handle_request(_req(_scan_dag(), 300, 4))
    ops = commit_ops(eng, record_key(TABLE_ID, 4),
                     encode_row(NON_HANDLE, [b"grape", 1, 2]), 250, 260)
    notify_region_write(REGION, ops, 5)
    r = warm.handle_request(_req(_scan_dag(), 300, 5))
    assert r.metrics["region_cache"] == "wt_delta"
    assert warm.region_cache.stats.deltas == 0
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 5)).data


def test_wt_lost_marker_forces_scan_delta_then_recovers():
    """notify_region_write_lost (the emission-off path) drops the pending
    chain: the next read repairs via scan_delta; once repaired, fresh
    notifies resume the write-through path."""
    eng = _engine()
    warm, cold = _pair(eng)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    ops = commit_ops(eng, record_key(TABLE_ID, 5),
                     encode_row(NON_HANDLE, [b"durian", 9, 9]), 210, 220)
    notify_region_write(REGION, ops, 4)
    # a write of unknown content lands (emission disabled for it)
    put_committed(eng, record_key(TABLE_ID, 6),
                  encode_row(NON_HANDLE, [b"kiwi", 8, 8]), 230, 240)
    notify_region_write_lost(REGION, 5)
    r = warm.handle_request(_req(_scan_dag(), 300, 5))
    assert r.metrics["region_cache"] == "delta"  # repair via scan_delta
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 5)).data
    # emission resumes: pendings restart cleanly after the repair
    ops = commit_ops(eng, record_key(TABLE_ID, 7),
                     encode_row(NON_HANDLE, [b"lime", 3, 3]), 250, 260)
    notify_region_write(REGION, ops, 6)
    r2 = warm.handle_request(_req(_scan_dag(), 400, 6))
    assert r2.metrics["region_cache"] == "wt_delta"
    assert r2.data == cold.handle_request(_req(_scan_dag(), 400, 6)).data


def test_wt_image_built_mid_stream_never_splices_a_gap():
    """A notify that predates the image's build snapshot must not seed a
    pending chain (the image would replay a delta it already contains or
    miss one it never saw) — the read repairs through scan_delta."""
    eng = _engine()
    warm, cold = _pair(eng)
    # a write is notified BEFORE any image exists (watermark advances)
    ops = commit_ops(eng, record_key(TABLE_ID, 5),
                     encode_row(NON_HANDLE, [b"durian", 9, 9]), 110, 120)
    notify_region_write(REGION, ops, 4)
    # image builds from an OLDER snapshot identity (apply_index 3)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    # next notify: watermark (4) is ahead of the image (3) -> no pending
    ops = commit_ops(eng, record_key(TABLE_ID, 6),
                     encode_row(NON_HANDLE, [b"kiwi", 8, 8]), 210, 220)
    notify_region_write(REGION, ops, 5)
    r = warm.handle_request(_req(_scan_dag(), 300, 5))
    assert r.metrics["region_cache"] == "delta"
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 5)).data


def test_wt_disabled_cache_keeps_scan_delta_path():
    from tikv_tpu.copr.region_cache import RegionColumnCache

    eng = _engine()
    rc = RegionColumnCache(write_through=False)
    warm = Endpoint(LocalEngine(eng), enable_device=True, region_cache=rc)
    cold = Endpoint(LocalEngine(eng), enable_device=True, enable_region_cache=False)
    warm.handle_request(_req(_scan_dag(), 200, 3))
    ops = commit_ops(eng, record_key(TABLE_ID, 5),
                     encode_row(NON_HANDLE, [b"durian", 9, 9]), 210, 220)
    notify_region_write(REGION, ops, 4)
    r = warm.handle_request(_req(_scan_dag(), 300, 4))
    assert r.metrics["region_cache"] == "delta"
    assert rc.stats.wt_deltas == 0
    assert r.data == cold.handle_request(_req(_scan_dag(), 300, 4)).data


# ---------------------------------------------------------------------------
# End-to-end over raft: txn scheduler (group commit) -> apply -> emission
# ---------------------------------------------------------------------------


def _raft_harness(n_rows=48):
    """One-store cluster with a seeded record table, a warm endpoint and a
    CPU oracle over the SAME raft engine."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    c = Cluster(1)
    c.run()
    kv = c.raftkv(1)
    wb = WriteBatch()
    for i in range(n_rows):
        k = Key.from_raw(record_key(TABLE_ID, i))
        w = Write(WriteType.PUT, 90,
                  short_value=encode_row(NON_HANDLE, [b"apple", i % 23, 100 + i]))
        wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
    kv.write({"region_id": FIRST_REGION_ID}, wb)
    warm = Endpoint(kv, enable_device=True)
    cold = Endpoint(kv, enable_device=False)
    return c, kv, warm, cold, FIRST_REGION_ID


def _raft_req(dag, ts, region_id):
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts,
                       context={"region_id": region_id})


def _commit_rows_via_scheduler(kv, region_id, rows, ts0, group=True):
    """Prewrite+commit ``rows`` as single-key txns through the real txn
    scheduler over raft — grouped into coalesced proposals by default."""
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn.scheduler import Scheduler
    from tikv_tpu.storage.txn_types import Mutation

    sched = Scheduler(kv, pool_size=1, group_commit_max=32 if group else 1)
    ctx = {"region_id": region_id}
    try:
        tasks = []
        for i, (handle, row) in enumerate(rows):
            rk = record_key(TABLE_ID, handle)
            tasks.append(sched.submit(Prewrite(
                [Mutation.put(Key.from_raw(rk), row)], rk, start_ts=ts0 + i), ctx))
        for t in tasks:
            assert t.done.wait(30) and t.exc is None, t.exc
            assert not (t.result or {}).get("errors"), t.result
        tasks = []
        for i, (handle, _row) in enumerate(rows):
            rk = record_key(TABLE_ID, handle)
            tasks.append(sched.submit(Commit(
                [Key.from_raw(rk)], ts0 + i, ts0 + 1000 + i), ctx))
        for t in tasks:
            assert t.done.wait(30) and t.exc is None, t.exc
    finally:
        sched.stop()
    return ts0 + 1000 + len(rows)  # a ts above every commit


def test_raft_write_through_end_to_end():
    """The full pipeline: group-committed txn writes through raft, apply-side
    emission, warm serve with zero scan_delta — byte-identical to the CPU
    pipeline over the same engine."""
    c, kv, warm, cold, rid = _raft_harness()
    r0 = warm.handle_request(_raft_req(_scan_dag(), 200, rid))
    assert r0.metrics["region_cache"] == "miss"
    assert r0.data == cold.handle_request(_raft_req(_scan_dag(), 200, rid)).data

    rows = [(i, encode_row(NON_HANDLE, [b"banana", i, i])) for i in (3, 7, 11, 200)]
    hi = _commit_rows_via_scheduler(kv, rid, rows, ts0=300)
    r1 = warm.handle_request(_raft_req(_scan_dag(), hi + 10, rid))
    assert r1.metrics["region_cache"] == "wt_delta"
    assert warm.region_cache.stats.deltas == 0, \
        "a warm read after committed writes must not scan CF_WRITE"
    assert r1.data == cold.handle_request(_raft_req(_scan_dag(), hi + 10, rid)).data
    # repeat read: plain hit, still byte-identical
    r2 = warm.handle_request(_raft_req(_scan_dag(), hi + 10, rid))
    assert r2.metrics["region_cache"] == "hit"
    assert r2.data == r1.data


def test_raft_failpoint_disables_emission_and_recovers_mid_batch():
    """The ``apply_emit_write_delta`` failpoint turns emission off: responses
    stay byte-identical through the scan_delta fallback, including a toggle
    in the middle of a write sequence, and write-through resumes after."""
    c, kv, warm, cold, rid = _raft_harness()
    warm.handle_request(_raft_req(_scan_dag(), 200, rid))
    try:
        # batch 1 emitted, EMISSION OFF for batch 2, batch 3 emitted again
        _commit_rows_via_scheduler(
            kv, rid, [(1, encode_row(NON_HANDLE, [b"kiwi", 1, 1]))], ts0=300)
        failpoint.cfg("apply_emit_write_delta", "return")
        _commit_rows_via_scheduler(
            kv, rid, [(2, encode_row(NON_HANDLE, [b"lime", 2, 2]))], ts0=2000)
        failpoint.remove("apply_emit_write_delta")
        hi = _commit_rows_via_scheduler(
            kv, rid, [(3, encode_row(NON_HANDLE, [b"plum", 3, 3]))], ts0=4000)
    finally:
        failpoint.remove("apply_emit_write_delta")
    r = warm.handle_request(_raft_req(_scan_dag(), hi + 10, rid))
    # the lost batch forces the scan_delta repair — and bytes match exactly
    assert r.metrics["region_cache"] == "delta"
    assert warm.region_cache.stats.wt_lost >= 1
    assert r.data == cold.handle_request(_raft_req(_scan_dag(), hi + 10, rid)).data
    # after the repair, write-through takes over again
    hi2 = _commit_rows_via_scheduler(
        kv, rid, [(4, encode_row(NON_HANDLE, [b"pear", 4, 4]))], ts0=6000)
    r2 = warm.handle_request(_raft_req(_scan_dag(), hi2 + 10, rid))
    assert r2.metrics["region_cache"] == "wt_delta"
    assert r2.data == cold.handle_request(_raft_req(_scan_dag(), hi2 + 10, rid)).data


def test_raft_replica_replays_are_deduped():
    """Three replicas apply every batch — three notifies per index.  The
    watermark dedupes the replays and the warm path still serves exactly."""
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster

    c = Cluster(3)
    c.run()
    kv = c.raftkv(1)
    wb = WriteBatch()
    for i in range(16):
        k = Key.from_raw(record_key(TABLE_ID, i))
        w = Write(WriteType.PUT, 90,
                  short_value=encode_row(NON_HANDLE, [b"apple", i, 100 + i]))
        wb.put_cf(CF_WRITE, k.append_ts(100).encoded, w.to_bytes())
    kv.write({"region_id": FIRST_REGION_ID}, wb)
    warm = Endpoint(kv, enable_device=True)
    cold = Endpoint(kv, enable_device=False)
    warm.handle_request(_raft_req(_scan_dag(), 200, FIRST_REGION_ID))
    hi = _commit_rows_via_scheduler(
        kv, FIRST_REGION_ID,
        [(5, encode_row(NON_HANDLE, [b"mango", 5, 5]))], ts0=300)
    r = warm.handle_request(_raft_req(_scan_dag(), hi + 10, FIRST_REGION_ID))
    assert r.metrics["region_cache"] == "wt_delta"
    assert warm.region_cache.stats.deltas == 0
    assert r.data == cold.handle_request(_raft_req(_scan_dag(), hi + 10, FIRST_REGION_ID)).data
