"""Native raft log engine (native/raftlog.cc): the purpose-built WAL store
for raft entries — segmented appends, conflict truncation, purge + segment
GC, rewrite of live tails, crash recovery (raft_log_engine/src/engine.rs)."""

import os
import threading

import pytest

from tikv_tpu.native.raftlog import NativeRaftLog, raftlog_available

pytestmark = pytest.mark.skipif(not raftlog_available(), reason="g++/native unavailable")


def _open(tmp_path, **kw):
    kw.setdefault("sync", False)  # fdatasync off for speed; durability test opts in
    return NativeRaftLog(str(tmp_path / "rlog"), **kw)


def _entries(lo, hi, tag=b"e"):
    return [tag + b"-%d" % i for i in range(lo, hi)]


class TestBasics:
    def test_append_fetch_roundtrip(self, tmp_path):
        log = _open(tmp_path)
        log.append(7, 1, _entries(1, 11), state=b"hs1")
        assert log.first_index(7) == 1
        assert log.last_index(7) == 10
        got = log.entries(7)
        assert [i for i, _ in got] == list(range(1, 11))
        assert got[3][1] == b"e-4"
        assert log.state(7) == b"hs1"
        assert log.entries(7, 4, 7) == [(4, b"e-4"), (5, b"e-5"), (6, b"e-6")]
        log.close()

    def test_missing_region_is_empty(self, tmp_path):
        log = _open(tmp_path)
        assert log.first_index(99) == 0
        assert log.last_index(99) == 0
        assert log.entries(99) == []
        assert log.state(99) is None
        log.close()

    def test_state_only_append(self, tmp_path):
        log = _open(tmp_path)
        log.put_state(3, b"only-state")
        assert log.state(3) == b"only-state"
        assert log.last_index(3) == 0
        assert 3 in log.regions()
        log.close()

    def test_conflict_truncation(self, tmp_path):
        """A new leader's append at index k replaces the old suffix >= k —
        the raft rule, applied at the storage layer (replay applies it too)."""
        log = _open(tmp_path)
        log.append(1, 1, _entries(1, 10, b"old"))
        log.append(1, 6, _entries(6, 8, b"new"))
        assert log.last_index(1) == 7
        got = dict(log.entries(1))
        assert got[5] == b"old-5"
        assert got[6] == b"new-6"
        assert got[7] == b"new-7"
        log.close()

    def test_multi_region_isolation(self, tmp_path):
        log = _open(tmp_path)
        log.append(1, 1, _entries(1, 5, b"r1"), state=b"s1")
        log.append(2, 100, _entries(100, 105, b"r2"), state=b"s2")
        assert log.first_index(2) == 100
        assert dict(log.entries(1))[4] == b"r1-4"
        assert dict(log.entries(2))[104] == b"r2-104"
        assert sorted(log.regions()) == [1, 2]
        log.clean(1)
        assert log.entries(1) == []
        assert log.state(1) is None
        assert log.regions() == [2]
        log.close()


class TestPurgeAndGc:
    def test_purge_drops_prefix(self, tmp_path):
        log = _open(tmp_path)
        log.append(1, 1, _entries(1, 101))
        log.purge(1, 60)
        assert log.first_index(1) == 61
        assert log.last_index(1) == 100
        assert log.entries(1, 0, 62) == [(61, b"e-61")]
        log.close()

    def test_dead_segments_unlinked(self, tmp_path):
        # tiny segments force rolls; purging everything must delete files
        log = _open(tmp_path, segment_bytes=2048)
        for batch in range(20):
            log.append(1, 1 + batch * 50, _entries(1 + batch * 50, 51 + batch * 50))
        assert log.stats()["segments"] > 3
        log.purge(1, 990)
        # state of region 1 was never written; all old segments are dead
        s = log.stats()
        assert s["segments"] <= 3, s
        files = os.listdir(log.path)
        assert len(files) == s["segments"]
        assert dict(log.entries(1))[1000] == b"e-1000"
        log.close()

    def test_rewrite_relocates_live_tail(self, tmp_path):
        """A laggard region's few live entries in an old segment get copied
        forward so the segment can be unlinked (engine.rs rewrite)."""
        log = _open(tmp_path, segment_bytes=4096, rewrite_max=64)
        log.append(2, 1, _entries(1, 4, b"laggard"), state=b"s2")  # tiny, old
        for batch in range(30):
            log.append(1, 1 + batch * 50, _entries(1 + batch * 50, 51 + batch * 50))
        log.purge(1, 1400)
        s = log.stats()
        assert s["rewrites"] >= 1, s
        assert s["segments"] <= 3, s
        # the laggard's entries and state survived the relocation
        assert dict(log.entries(2)) == {1: b"laggard-1", 2: b"laggard-2", 3: b"laggard-3"}
        assert log.state(2) == b"s2"
        log.close()

    def test_purge_everything_then_append(self, tmp_path):
        # snapshot-install pattern: all entries purged, append resumes at a gap
        log = _open(tmp_path)
        log.append(1, 1, _entries(1, 10))
        log.purge(1, 9)
        assert log.entries(1) == []
        log.append(1, 500, _entries(500, 503))
        assert log.first_index(1) == 500
        assert log.last_index(1) == 502
        log.close()


class TestRecovery:
    def test_reopen_restores_everything(self, tmp_path):
        log = _open(tmp_path, segment_bytes=4096)
        log.append(1, 1, _entries(1, 200), state=b"hs-old")
        log.append(1, 150, _entries(150, 180, b"new"), state=b"hs-new")
        log.append(2, 7, _entries(7, 9, b"r2"))
        log.purge(1, 20)
        log.clean(2)
        log.append(3, 1, _entries(1, 3, b"r3"))
        log.close()

        log2 = _open(tmp_path, segment_bytes=4096)
        assert log2.first_index(1) == 21
        assert log2.last_index(1) == 179
        got = dict(log2.entries(1))
        assert got[149] == b"e-149"
        assert got[150] == b"new-150"
        assert log2.state(1) == b"hs-new"
        assert log2.entries(2) == [] and log2.state(2) is None
        assert dict(log2.entries(3)) == {1: b"r3-1", 2: b"r3-2"}
        assert sorted(log2.regions()) == [1, 3]
        log2.close()

    def test_reopen_after_rewrite(self, tmp_path):
        log = _open(tmp_path, segment_bytes=4096, rewrite_max=64)
        log.append(2, 1, _entries(1, 4, b"laggard"), state=b"s2")
        for batch in range(30):
            log.append(1, 1 + batch * 50, _entries(1 + batch * 50, 51 + batch * 50))
        log.purge(1, 1400)
        assert log.stats()["rewrites"] >= 1
        log.close()
        log2 = _open(tmp_path, segment_bytes=4096)
        assert dict(log2.entries(2))[3] == b"laggard-3"
        assert log2.state(2) == b"s2"
        assert log2.last_index(1) == 1500
        log2.close()

    def test_torn_tail_truncated(self, tmp_path):
        log = _open(tmp_path)
        log.append(1, 1, _entries(1, 6))
        log.close()
        # simulate a crash mid-append: garbage half-record at the tail
        files = sorted(os.listdir(tmp_path / "rlog"))
        with open(tmp_path / "rlog" / files[-1], "ab") as f:
            f.write(b"\x99\x12\x34half-a-record")
        log2 = _open(tmp_path)
        assert log2.last_index(1) == 5
        assert dict(log2.entries(1))[5] == b"e-5"
        # and the tail was physically truncated so new appends are clean
        log2.append(1, 6, _entries(6, 8))
        log2.close()
        log3 = _open(tmp_path)
        assert log3.last_index(1) == 7
        log3.close()

    def test_durable_sync_mode(self, tmp_path):
        log = NativeRaftLog(str(tmp_path / "rlog"), sync=True)
        log.append(1, 1, _entries(1, 4), state=b"hs")
        log.close()
        log2 = NativeRaftLog(str(tmp_path / "rlog"), sync=True)
        assert log2.last_index(1) == 3
        assert log2.state(1) == b"hs"
        log2.close()


class TestConcurrency:
    def test_parallel_appends_group_commit(self, tmp_path):
        """Many threads appending distinct regions with sync=1: every append
        must be indexed, and grouped fsync must not lose or dup anything."""
        log = NativeRaftLog(str(tmp_path / "rlog"), sync=True, segment_bytes=1 << 20)
        n_threads, per = 8, 50
        errs = []

        def run(rid):
            try:
                for i in range(1, per + 1):
                    log.append(rid, i, [b"r%d-%d" % (rid, i)])
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=run, args=(r,)) for r in range(1, n_threads + 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        for r in range(1, n_threads + 1):
            assert log.last_index(r) == per
            assert dict(log.entries(r))[per] == b"r%d-%d" % (r, per)
        log.close()
        log2 = _open(tmp_path)
        for r in range(1, n_threads + 1):
            assert log2.last_index(r) == per
        log2.close()

    def test_concurrent_reads_during_appends(self, tmp_path):
        log = _open(tmp_path)
        stop = threading.Event()
        errs = []

        def reader():
            while not stop.is_set():
                try:
                    es = log.entries(1)
                    for i, b in es:
                        assert b == b"e-%d" % i
                except Exception as e:  # pragma: no cover
                    errs.append(e)
                    return

        t = threading.Thread(target=reader)
        t.start()
        for batch in range(50):
            log.append(1, 1 + batch * 20, _entries(1 + batch * 20, 21 + batch * 20))
            if batch % 10 == 9:
                log.purge(1, batch * 20 - 100)
        stop.set()
        t.join()
        assert not errs
        log.close()


class TestStoreIntegration:
    """The log engine behind the multi-raft store: entries route to the
    segmented log (not CF_RAFT), recovery reads them back, and log GC purges
    instead of range-deleting (store.py handle_ready/recover/compact)."""

    @pytest.fixture
    def rl_cluster(self, tmp_path):
        from tikv_tpu.raft.cluster import Cluster

        c = Cluster(3)
        for sid, store in c.stores.items():
            store.raft_log = NativeRaftLog(str(tmp_path / f"rl-{sid}"), sync=False)
        c.run()
        yield c, tmp_path

    def test_entries_live_in_log_engine_not_cf_raft(self, rl_cluster):
        from tikv_tpu.storage.engine import CF_RAFT
        from tikv_tpu.util import keys

        c, _ = rl_cluster
        c.must_put(b"k1", b"v1")
        c.must_put(b"k2", b"v2")
        for sid, store in c.stores.items():
            assert store.raft_log.last_index(1) >= 2, sid
            # CF_RAFT holds region meta + apply state but NO log entries
            log_prefix = keys.region_raft_prefix(1) + keys.RAFT_LOG_SUFFIX
            snap = store.engine.snapshot()
            logged = list(snap.scan_cf(
                CF_RAFT, log_prefix, log_prefix[:-1] + bytes([log_prefix[-1] + 1])
            ))
            assert logged == [], sid

    def test_recovery_from_log_engine(self, rl_cluster, tmp_path):
        from tikv_tpu.raft.cluster import FIRST_REGION_ID
        from tikv_tpu.raft.store import Store

        c, base = rl_cluster
        c.must_put(b"r1", b"v1")
        c.must_put(b"r2", b"v2")
        victim = 2
        old = c.stores[victim]
        applied_before = old.peers[FIRST_REGION_ID].node.applied
        old.raft_log.close()
        # "crash": fresh Store over the surviving engine + reopened log dir
        new_store = Store(
            victim, c.transport, engine=old.engine,
            raft_log=NativeRaftLog(str(base / f"rl-{victim}"), sync=False),
        )
        assert new_store.recover() == 1
        peer = new_store.peers[FIRST_REGION_ID]
        assert peer.node.applied == applied_before
        assert peer.node.log.last_index() >= applied_before
        c.stores[victim] = new_store
        c.transport.register(new_store)
        c.must_put(b"r3", b"v3")
        c.tick(3)
        assert c.get_on_store(victim, b"r3") == b"v3"

    def test_log_gc_purges_log_engine(self, rl_cluster):
        c, _ = rl_cluster
        for i in range(60):
            c.must_put(b"k%d" % i, b"v")
        for sid, store in c.stores.items():
            before = store.raft_log.first_index(1)
            dropped = store.compact_raft_logs(threshold=20, slack=5)
            assert dropped > 0, sid
            assert store.raft_log.first_index(1) > before, sid
            assert store.raft_log.last_index(1) >= 60, sid
        # the cluster still works after purge
        c.must_put(b"after-gc", b"v")
        assert c.must_get(b"after-gc") == b"v"


class TestMigration:
    def test_cf_raft_store_migrates_into_log_engine(self, tmp_path):
        """A store persisted BEFORE the log engine was enabled (raft state +
        entries in CF_RAFT) must recover with its term/vote/entries intact —
        migrated into the log engine, legacy copies removed — not amnesiac
        (store.py _migrate_region_log)."""
        from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
        from tikv_tpu.raft.store import Store
        from tikv_tpu.storage.engine import CF_RAFT
        from tikv_tpu.util import keys

        c = Cluster(3)  # legacy mode: no raft_log anywhere
        c.run()
        for i in range(12):
            c.must_put(b"mig-%02d" % i, b"v%d" % i)
        victim = 2
        old = c.stores[victim]
        old_peer = old.peers[FIRST_REGION_ID]
        applied_before = old_peer.node.applied
        term_before = old_peer.node.term
        vote_before = old_peer.node.vote
        # legacy CF_RAFT holds the log
        log_prefix = keys.region_raft_prefix(1) + keys.RAFT_LOG_SUFFIX
        snap = old.engine.snapshot()
        legacy = list(snap.scan_cf(CF_RAFT, log_prefix,
                                   log_prefix[:-1] + bytes([log_prefix[-1] + 1])))
        assert legacy, "fixture must have CF_RAFT log entries"

        # "upgrade": restart the store WITH the log engine over the same kv
        rl = NativeRaftLog(str(tmp_path / "mig-rl"), sync=False)
        new_store = Store(victim, c.transport, engine=old.engine, raft_log=rl)
        assert new_store.recover() == 1
        peer = new_store.peers[FIRST_REGION_ID]
        assert peer.node.applied == applied_before
        assert peer.node.term == term_before
        assert peer.node.vote == vote_before  # double-vote safety survives
        assert peer.node.log.last_index() >= applied_before
        # migrated: the log engine holds the entries + state...
        assert rl.last_index(FIRST_REGION_ID) >= applied_before
        assert rl.state(FIRST_REGION_ID) is not None
        # ...and the legacy CF_RAFT copies are gone (no split brain)
        snap = old.engine.snapshot()
        leftover = list(snap.scan_cf(CF_RAFT, log_prefix,
                                     log_prefix[:-1] + bytes([log_prefix[-1] + 1])))
        assert leftover == []
        assert snap.get_cf(CF_RAFT, keys.raft_state_key(FIRST_REGION_ID)) is None
        # the migrated peer keeps participating
        c.stores[victim] = new_store
        c.transport.register(new_store)
        c.must_put(b"post-migration", b"pv")
        c.tick(3)
        assert c.get_on_store(victim, b"post-migration") == b"pv"

    def test_migration_preserves_noncontiguous_runs(self, tmp_path):
        """Legacy stores can hold a GAPPED CF_RAFT log (compaction artifacts);
        migration's run-splitting must keep the live contiguous SUFFIX the
        raft node needs, never feed the log engine an impossible gap."""
        from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
        from tikv_tpu.raft.store import Store
        from tikv_tpu.storage.engine import CF_RAFT, WriteBatch
        from tikv_tpu.util import codec, keys

        c = Cluster(1)
        c.run()
        for i in range(20):
            c.must_put(b"gap-%02d" % i, b"v")
        old = c.stores[1]
        peer = old.peers[FIRST_REGION_ID]
        applied_before = peer.node.applied
        # punch a hole in the middle of the legacy log (indexes 5..8)
        log_prefix = keys.region_raft_prefix(1) + keys.RAFT_LOG_SUFFIX
        wb = WriteBatch()
        wb.delete_range_cf(CF_RAFT, log_prefix + codec.encode_u64(5),
                           log_prefix + codec.encode_u64(9))
        old.engine.write(wb)

        rl = NativeRaftLog(str(tmp_path / "gap-rl"), sync=False)
        new_store = Store(1, c.transport, engine=old.engine, raft_log=rl)
        assert new_store.recover() == 1
        # the contiguous suffix after the gap survived in the log engine
        assert rl.last_index(FIRST_REGION_ID) >= applied_before
        assert rl.first_index(FIRST_REGION_ID) >= 9
        got = dict(rl.entries(FIRST_REGION_ID))
        assert sorted(got) == list(range(rl.first_index(FIRST_REGION_ID),
                                         rl.last_index(FIRST_REGION_ID) + 1))
