"""Zone-map pruned execution: soundness + byte-identity coverage.

The contract under test is the ISSUE 16 acceptance list: per-block
min/max/null zone maps prune provably-empty blocks at trace time on every
device path, Limit/TopN ride zone-order early exits, and EVERY pruned serve
stays byte-identical to the unpruned device path and the CPU oracle —
across dict/RLE/bitpack/plain encodings, scan/selection/agg/topN/limit
plans, and mid-stream write-delta folds (stale-but-sound widening)."""

import numpy as np
import pytest

from copr_fixtures import TABLE_ID
from fixtures import delete_committed, put_committed

from tikv_tpu.copr import encoding as E
from tikv_tpu.copr import zone_maps as Z
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.cache import _Block
from tikv_tpu.copr.dag import (
    Aggregation, DagRequest, Limit, Selection, TableScan, TopN,
)
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.util.metrics import REGISTRY

# id (pk) | category (dict) | band (monotonic) | small (bitpack) | wide (plain)
COLUMNS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.varchar()),
    ColumnInfo(3, FieldType.int64()),
    ColumnInfo(4, FieldType.int64()),
    ColumnInfo(5, FieldType.int64()),
]
NON_HANDLE = COLUMNS[1:]
CATS = [b"alpha", b"beta", b"gamma", b"delta"]


@pytest.fixture(autouse=True)
def _restore_prune_switch():
    yield
    Z.set_enabled(None)


def _row(i, rng):
    return [CATS[i % len(CATS)], i // 100, int(rng.integers(0, 120)),
            int(rng.integers(-(1 << 40), 1 << 40))]


def _engine(n=600, v2=False, seed=0):
    rng = np.random.default_rng(seed)
    eng = BTreeEngine()
    enc = encode_row_v2 if v2 else encode_row
    for i in range(n):
        put_committed(eng, record_key(TABLE_ID, i),
                      enc(NON_HANDLE, _row(i, rng)), 90, 100)
    return eng


def _req(dag, ts, ai, region_id=7):
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts,
                       context={"region_id": region_id,
                                "region_epoch": (1, 1), "apply_index": ai})


def _pair(eng, **kw):
    kw.setdefault("block_rows", 64)  # many blocks → real pruning decisions
    warm = Endpoint(LocalEngine(eng), enable_device=True, **kw)
    cold = Endpoint(LocalEngine(eng), enable_device=False,
                    enable_region_cache=False)
    return warm, cold


def _image(warm):
    [img] = warm.region_cache._images.values()
    return img


def _prune_count(path, outcome):
    return REGISTRY.counter("tikv_coprocessor_zone_prune_total", "").get(
        path=path, outcome=outcome)


# ---------------------------------------------------------------------------
# Direct units: prune soundness vs brute force, fold widening, TopN cutoff
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [11, 23, 37])
def test_prune_blocks_matches_brute_force(seed):
    """A pruned block must hold NO row satisfying every recognized conjunct
    — checked against a numpy brute-force evaluation of the same predicate
    over the decoded block payloads."""
    rng = np.random.default_rng(seed)
    eng = _engine(n=500, seed=seed)
    warm, _ = _pair(eng)
    ops = {"lt": np.less, "le": np.less_equal, "gt": np.greater,
           "ge": np.greater_equal, "eq": np.equal, "ne": np.not_equal}
    for _ in range(12):
        op = list(ops)[int(rng.integers(0, len(ops)))]
        ci, const = [(0, int(rng.integers(0, 500))),
                     (2, int(rng.integers(0, 6))),
                     (3, int(rng.integers(0, 120)))][int(rng.integers(0, 3))]
        dag = DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Selection([call(op, col(ci), const_int(const))])])
        warm.handle_request(_req(dag, 200, 3))
        cache = _image(warm).block_cache
        ev = warm._evaluator_for(dag)
        keep = Z.prune_blocks(cache, ev.sel_rpns)
        if keep is None:
            continue
        for bi, blk in enumerate(cache.blocks):
            if keep[bi]:
                continue
            data = np.asarray(E.decoded_data(blk.cols[ci]))[:blk.n_valid]
            nulls = np.asarray(E.decoded_nulls(blk.cols[ci]))[:blk.n_valid]
            hits = ops[op](data, const) & ~nulls
            assert not hits.any(), (op, ci, const, bi)


def test_fold_update_widens_and_marks_stale():
    z = Z.ColumnZone(10, 20, 0, 0, 8)
    zones = {0: z, 1: Z.ColumnZone(None, None, 8, 8, 8)}
    Z.fold_update(zones, {0: (np.array([5, 30]), np.array([False, False])),
                          1: (np.array([7, 7]), np.array([True, False]))})
    assert (z.lo, z.hi) == (5, 30) and z.stale
    assert z.null_lo == 0 and z.null_hi == 0
    z1 = zones[1]
    assert (z1.lo, z1.hi) == (7, 7)
    assert z1.null_lo == 7 and z1.null_hi == 8  # one non-null write landed
    # an object (decoded-bytes) write stops tracking that column
    Z.fold_update(zones, {0: (np.array([b"x"], dtype=object),
                              np.array([False]))})
    assert 0 not in zones


def _zblock(lo, hi, n, nulls=0):
    b = _Block(cols=[], n_valid=n)
    b.zones = {3: Z.ColumnZone(lo, hi, nulls, nulls, n)}
    return b


def test_topn_cutoff_order_ascending_and_descending():
    blocks = [_zblock(0, 9, 10), _zblock(10, 19, 10), _zblock(20, 29, 10)]
    keep = np.ones(3, dtype=bool)
    # ascending, k=5: block 0 alone guarantees 5 rows <= 9, so every block
    # with lo > 9 provably misses the top-k
    out = Z.topn_cutoff_order(blocks, keep, 3, False, 5)
    assert list(out) == [True, False, False]
    # descending, k=5: block 2 guarantees 5 rows >= 20 → blocks below exit
    out = Z.topn_cutoff_order(blocks, keep, 3, True, 5)
    assert list(out) == [False, False, True]
    # k beyond the bounded rows: no exit is provable
    assert Z.topn_cutoff_order(blocks, keep, 3, False, 31) is None
    # a block with possible nulls can never exit ascending (nulls sort first)
    nully = [_zblock(0, 9, 10), _zblock(20, 29, 10, nulls=3)]
    out = Z.topn_cutoff_order(nully, np.ones(2, dtype=bool), 3, False, 5)
    assert out is None or bool(out[1])
    # untracked order column → no sound bound at all
    blocks[1].zones = {}
    assert Z.topn_cutoff_order(blocks, keep, 3, False, 5) is None


def test_kill_switch_disables_pruning():
    eng = _engine(n=200)
    warm, _ = _pair(eng)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Selection([call("ge", col(0), const_int(199))])])
    warm.handle_request(_req(dag, 200, 3))
    cache = _image(warm).block_cache
    ev = warm._evaluator_for(dag)
    assert Z.prune_blocks(cache, ev.sel_rpns) is not None
    Z.set_enabled(False)
    assert Z.prune_blocks(cache, ev.sel_rpns) is None


# ---------------------------------------------------------------------------
# Zone soundness under seeded write-delta chaos
# ---------------------------------------------------------------------------


def _assert_zones_sound(cache):
    for blk in cache.blocks:
        if not blk.zones:
            continue
        for ci, z in blk.zones.items():
            data = np.asarray(E.decoded_data(blk.cols[ci]))[:blk.n_valid]
            if data.dtype == object:
                continue
            nulls = np.asarray(E.decoded_nulls(blk.cols[ci]))[:blk.n_valid]
            live = data[~nulls]
            nn = int(nulls.sum())
            assert z.null_lo <= nn <= z.null_hi, (ci, z, nn)
            if len(live):
                assert z.lo is not None and z.lo <= live.min(), (ci, z)
                assert z.hi >= live.max(), (ci, z)


@pytest.mark.parametrize("seed", [5, 17])
def test_zones_stay_sound_under_write_delta_chaos(seed):
    """Rounds of random in-place updates, inserts, and deletes fold into a
    warm image; after every fold each block's zones must still bound the
    actual resident values (stale-but-sound), and pruned serving must still
    answer the oracle's bytes."""
    rng = np.random.default_rng(seed)
    n = 400
    eng = _engine(n=n, seed=seed)
    warm, cold = _pair(eng)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Selection([call("ge", col(3), const_int(60))])])
    warm.handle_request(_req(dag, 200, 3))
    ts, ai = 200, 3
    for _round in range(4):
        ts, ai = ts + 100, ai + 1
        for _ in range(int(rng.integers(1, 6))):
            h = int(rng.integers(0, n))
            put_committed(eng, record_key(TABLE_ID, h),
                          encode_row(NON_HANDLE, _row(h, rng)),
                          ts - 50, ts - 40)
        if rng.integers(0, 2):
            put_committed(eng, record_key(TABLE_ID, n + _round),
                          encode_row(NON_HANDLE, _row(n + _round, rng)),
                          ts - 50, ts - 40)
        if rng.integers(0, 2):
            delete_committed(eng, record_key(TABLE_ID, int(rng.integers(0, n))),
                             ts - 50, ts - 40)
        r = warm.handle_request(_req(dag, ts, ai))
        assert r.data == cold.handle_request(_req(dag, ts, ai)).data
        cache = _image(warm).block_cache
        Z.ensure_zones(cache)
        _assert_zones_sound(cache)


# ---------------------------------------------------------------------------
# End-to-end byte identity: pruned vs unpruned vs CPU oracle
# ---------------------------------------------------------------------------


def _plans(rng, n):
    sel = lambda: [call("ge", col(0), const_int(n - n // 10)),
                   call("gt", col(3), const_int(int(rng.integers(0, 120))))]
    return [
        DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                              Selection(sel()), Limit(1 << 20)]),
        DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                              Selection(sel()),
                              Limit(int(rng.integers(1, 30)))]),
        DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Selection([call("eq", col(2), const_int(int(rng.integers(0, 8))))]),
            Aggregation([col(1)], [AggDescriptor("sum", col(3)),
                                   AggDescriptor("count", None)])]),
        DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            Selection(sel()),
            TopN([(col(3), bool(rng.integers(0, 2))), (col(0), False)],
                 int(rng.integers(1, 25)))]),
        DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            TopN([(col(0), bool(rng.integers(0, 2)))],
                 int(rng.integers(1, 40)))]),
    ]


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
@pytest.mark.parametrize("seed", [101, 202])
def test_pruned_serving_byte_identical_fuzz(seed, v2):
    """Selective scan / Limit / agg / TopN plans over a warm image answer
    the SAME bytes with pruning on, with pruning force-disabled, and on the
    CPU oracle — before and after a mid-stream delta fold."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(300, 600))
    eng = _engine(n=n, v2=v2, seed=seed)
    warm, cold = _pair(eng)

    def check(ts, ai):
        for dag in _plans(rng, n):
            oracle = cold.handle_request(_req(dag, ts, ai)).data
            Z.set_enabled(True)
            pruned = warm.handle_request(_req(dag, ts, ai))
            Z.set_enabled(False)
            unpruned = warm.handle_request(_req(dag, ts, ai))
            Z.set_enabled(None)
            assert pruned.data == oracle, (
                seed, v2, ts, [type(e).__name__ for e in dag.executors])
            assert unpruned.data == oracle, (
                seed, v2, ts, [type(e).__name__ for e in dag.executors])

    before = _prune_count("unary", "pruned")
    check(200, 3)
    assert _prune_count("unary", "pruned") > before, \
        "selective plans over a warm image pruned nothing"
    enc = encode_row_v2 if v2 else encode_row
    for _ in range(int(rng.integers(2, 6))):
        h = int(rng.integers(0, n))
        put_committed(eng, record_key(TABLE_ID, h),
                      enc(NON_HANDLE, [
                          CATS[int(rng.integers(0, len(CATS)))],
                          int(rng.integers(0, 1 << int(rng.choice([3, 50])))),
                          int(rng.integers(0, 200)),
                          int(rng.integers(-(1 << 40), 1 << 40))]),
                      210, 220)
    put_committed(eng, record_key(TABLE_ID, n + 50),
                  enc(NON_HANDLE, _row(n + 50, rng)), 210, 220)
    delete_committed(eng, record_key(TABLE_ID, 1), 210, 220)
    check(300, 4)
    check(300, 4)  # pure hits over the folded images


def test_limit_scan_prunes_on_device():
    """A selective Limit-bearing scan serves warm ON DEVICE with blocks
    pruned (counted), byte-identical to the oracle."""
    eng = _engine(n=600)
    warm, cold = _pair(eng)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Selection([call("ge", col(0), const_int(540))]),
        Limit(25)])
    oracle = cold.handle_request(_req(dag, 200, 3)).data
    warm.handle_request(_req(dag, 200, 3))
    before = _prune_count("unary", "pruned")
    r = warm.handle_request(_req(dag, 200, 3))
    assert r.from_device and r.data == oracle
    assert _prune_count("unary", "pruned") > before


def test_topn_zone_order_early_exit():
    """A bare-key TopN over a warm image exits blocks that provably cannot
    reach the top-k (counted as early_exit), byte-identical both ways."""
    eng = _engine(n=600)
    warm, cold = _pair(eng)
    for desc in (False, True):
        dag = DagRequest(executors=[
            TableScan(TABLE_ID, COLUMNS),
            TopN([(col(0), desc)], 10)])
        oracle = cold.handle_request(_req(dag, 200, 3)).data
        warm.handle_request(_req(dag, 200, 3))
        before = _prune_count("unary", "early_exit")
        r = warm.handle_request(_req(dag, 200, 3))
        assert r.from_device and r.data == oracle, desc
        assert _prune_count("unary", "early_exit") > before, desc


def test_device_plan_decline_named_for_limit_topn():
    """A Limit/TopN-bearing plan the device declines is counted under
    tikv_coprocessor_encoded_decline_total{path=device_plan} with the
    eligibility gate's named cause — never a silent CPU fallback."""
    from tikv_tpu.copr import jax_eval

    eng = _engine(n=100)
    warm, cold = _pair(eng)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        TopN([(col(3), False)], 5000)])  # beyond the device TopN bound
    assert jax_eval.decline_cause(dag) == "topn_limit_too_large"
    before = REGISTRY.counter(
        "tikv_coprocessor_encoded_decline_total", "").get(
        path="device_plan", cause="topn_limit_too_large")
    r = warm.handle_request(_req(dag, 200, 3))
    assert not r.from_device
    assert r.data == cold.handle_request(_req(dag, 200, 3)).data
    assert REGISTRY.counter(
        "tikv_coprocessor_encoded_decline_total", "").get(
        path="device_plan", cause="topn_limit_too_large") == before + 1
    # an eligible plan names no cause
    ok = DagRequest(executors=[TableScan(TABLE_ID, COLUMNS),
                               TopN([(col(3), False)], 10)])
    assert jax_eval.decline_cause(ok) is None


def test_observatory_profiles_pruned_blocks():
    """Warm pruned serves report blocks examined/pruned into the per-sig
    profile, and the floor carries the pruned fraction for obs_diff."""
    from tikv_tpu.copr.observatory import OBSERVATORY, floor_diff

    OBSERVATORY.reset()
    eng = _engine(n=600)
    warm, _ = _pair(eng)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Selection([call("ge", col(0), const_int(540))])])
    for _ in range(4):
        warm.handle_request(_req(dag, 200, 3))
    snap = OBSERVATORY.snapshot()
    views = [v for entry in snap["sigs"].values()
             for pk, v in entry["paths"].items()
             if v.get("blocks_pruned", 0) > 0]
    assert views, "no profile recorded pruned blocks"
    assert all(v["blocks_examined"] >= v["blocks_pruned"] for v in views)
    floor = OBSERVATORY.floor(min_count=3)
    frs = [p.get("pruned_fraction") for sig in floor["sigs"].values()
           for p in sig.values() if p.get("pruned_fraction")]
    assert frs and all(0 < f <= 1 for f in frs)
    # pruning regression: same throughput, collapsed pruned fraction → flag
    import copy

    cur = copy.deepcopy(floor)
    for sig in cur["sigs"].values():
        for p in sig.values():
            p.pop("pruned_fraction", None)
    verdict = floor_diff(floor, cur)
    assert any(r.get("kind") == "pruning" for r in verdict["regressions"])
