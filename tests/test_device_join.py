"""Device-resident Join + Projection: end-to-end differential coverage.

The contract under test is the device-join acceptance list
(docs/device_join.md): two-table join plans served off warm compressed
region images must answer BYTE-IDENTICALLY to the CPU oracle across
inner/left-outer × shared-dict/disjoint-dict/plain-int keys × rowv1/rowv2
× encoded/decoded residency, through mid-stream delta folds on the build
side; the rank path must join without decoding non-surviving build rows;
zone maps must prune non-intersecting key blocks; and every shape the
kernels cannot cover must be a per-cause counted decline, never a silent
or wrong-bytes fallback."""

import numpy as np
import pytest

from copr_fixtures import TABLE_ID
from fixtures import delete_committed, put_committed

from tikv_tpu.copr import jax_join
from tikv_tpu.copr import zone_maps
from tikv_tpu.copr.dag import (
    ENC_TYPE_CHUNK, DagRequest, Join, Limit, Projection, Selection,
    SelectResponse, TableScan, TopN,
)
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.encoding import EncodedColumn
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key, record_range
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.util.metrics import REGISTRY

BT = TABLE_ID + 1          # build-side table, its own region (8)
BT_DISJOINT = TABLE_ID + 2  # build table whose dict shares NO values (9)

# id (pk) | category (dict) | small int | wide int
COLUMNS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.varchar()),
    ColumnInfo(3, FieldType.int64()),
    ColumnInfo(4, FieldType.int64()),
]
NON_HANDLE = COLUMNS[1:]
CATS = [b"alpha", b"beta", b"gamma", b"delta", b"eps"]
DISJOINT_CATS = [b"zeta", b"theta", b"iota"]

_CTX = {
    TABLE_ID: {"region_id": 7, "region_epoch": (1, 1)},
    BT: {"region_id": 8, "region_epoch": (1, 1)},
    BT_DISJOINT: {"region_id": 9, "region_epoch": (1, 1)},
}


def _engine(n_probe=240, n_build=90, v2=False, seed=0):
    rng = np.random.default_rng(seed)
    eng = BTreeEngine()
    enc = encode_row_v2 if v2 else encode_row
    for i in range(n_probe):
        put_committed(eng, record_key(TABLE_ID, i),
                      enc(NON_HANDLE, [CATS[i % len(CATS)], i % 7,
                                       int(rng.integers(0, 1 << 20))]),
                      90, 100)
    for i in range(n_build):
        put_committed(eng, record_key(BT, i),
                      enc(NON_HANDLE, [CATS[i % 3], i % 9,
                                       int(rng.integers(0, 1 << 20))]),
                      90, 100)
    for i in range(30):
        put_committed(eng, record_key(BT_DISJOINT, i),
                      enc(NON_HANDLE, [DISJOINT_CATS[i % 3], i % 5,
                                       int(rng.integers(0, 1 << 20))]),
                      90, 100)
    return eng


def _jdag(lk, rk, extra=(), jt="inner", btable=BT, bctx=True,
          below=(), encode_type=0, ai=3):
    ctx = None
    if bctx:
        ctx = dict(_CTX[btable], apply_index=ai)
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        *below,
        Join([TableScan(btable, COLUMNS)], [record_range(btable)], lk, rk,
             join_type=jt, build_context=ctx),
        *extra,
    ], encode_type=encode_type)


def _req(dag, ts=200, ai=3):
    return CoprRequest(103, dag, [record_range(TABLE_ID)], ts,
                       context=dict(_CTX[TABLE_ID], apply_index=ai))


def _pair(eng, **kw):
    warm = Endpoint(LocalEngine(eng), enable_device=True, **kw)
    cold = Endpoint(LocalEngine(eng), enable_device=False,
                    enable_region_cache=False)
    if warm.cost_router is not None:
        # deterministic rung choice for the differential asserts: the
        # static ladder stands (rank → hash → cpu), no explore/cold probes
        warm.cost_router.enabled = False
    return warm, cold


def _count(name, **labels):
    try:
        return REGISTRY.counter(name, "").get(**labels)
    except Exception:  # noqa: BLE001 — label set never minted yet
        return 0


def _join_plans(ai=3):
    """The differential pool: inner/left × shared-dict/disjoint-dict/
    plain-int keys × bare/filtered/projected/topN downstreams."""
    downstreams = [
        (),
        (Selection([call("gt", col(6), const_int(2))]),),
        (Projection([call("plus", col(0), col(4)), col(1), col(7)]),
         Limit(41)),  # noqa: E501 — project across both sides, then cut
        (TopN([(col(7), True), (col(0), False)], 23),),
    ]
    plans = []
    for jt in ("inner", "left"):
        for lk, rk, btable in [(1, 1, BT), (1, 1, BT_DISJOINT), (2, 2, BT)]:
            for extra in downstreams:
                plans.append(_jdag(lk, rk, extra=extra, jt=jt,
                                   btable=btable, ai=ai))
    return plans


@pytest.mark.parametrize("v2", [False, True], ids=["rowv1", "rowv2"])
@pytest.mark.parametrize("encoded", [True, False],
                         ids=["encoded", "decoded"])
def test_join_differential_pool(v2, encoded):
    """Every plan in the join pool answers the CPU oracle's bytes — warm,
    and again after a mid-stream delta fold on the BUILD side (update, new
    dictionary value, insert, delete)."""
    eng = _engine(v2=v2, seed=11 + v2)
    warm, cold = _pair(eng, encode_columns=encoded)
    for dag in _join_plans():
        r = warm.handle_request(_req(dag))
        c = cold.handle_request(_req(dag))
        assert r.data == c.data, f"warm join bytes diverged: {dag.executors}"

    enc = encode_row_v2 if v2 else encode_row
    # build-side mid-stream fold: in-place update, a NEW dict value, an
    # insert and a delete — the warm image folds, the oracle rescans
    put_committed(eng, record_key(BT, 3),
                  enc(NON_HANDLE, [b"omega", 8, 12345]), 210, 220)
    put_committed(eng, record_key(BT, 200),
                  enc(NON_HANDLE, [b"beta", 1, 777]), 210, 220)
    delete_committed(eng, record_key(BT, 7), 210, 220)
    # and one probe-side write so both images fold
    put_committed(eng, record_key(TABLE_ID, 5),
                  enc(NON_HANDLE, [b"omega", 6, 999]), 210, 220)
    for dag in _join_plans(ai=4):
        r = warm.handle_request(_req(dag, ts=300, ai=4))
        c = cold.handle_request(_req(dag, ts=300, ai=4))
        assert r.data == c.data, f"post-fold bytes diverged: {dag.executors}"


def _image(warm, region_id):
    for key, img in warm.region_cache._images.items():
        if key[0] == region_id:
            return img
    raise AssertionError(f"no image for region {region_id}")


def test_rank_join_decodes_only_survivors():
    """The rank path joins dict code lanes device-side and gathers build
    payloads through ``EncodedColumn.take`` — the full-column decode
    caches of the build image's encoded payload columns stay EMPTY."""
    eng = _engine()
    warm, cold = _pair(eng, shadow_sample=0)
    served0 = _count("tikv_coprocessor_join_total", path="rank",
                     outcome="served")
    dag = _jdag(1, 1)
    r = warm.handle_request(_req(dag))
    assert r.data == cold.handle_request(_req(dag)).data
    assert r.from_device
    assert _count("tikv_coprocessor_join_total", path="rank",
                  outcome="served") == served0 + 1
    img = _image(warm, 8)
    enc_cols = [c for blk in img.block_cache.blocks for c in blk.cols
                if isinstance(c, EncodedColumn)]
    assert enc_cols, "build image carries no encoded payload columns"
    assert all(c._data is None for c in enc_cols), \
        "device join decoded a full encoded column"


def test_zone_maps_prune_join_blocks():
    """Blocks whose key ranges cannot intersect the other side prune
    before any key lane decodes, and the bytes still match the oracle."""
    eng = BTreeEngine()
    for i in range(256):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(NON_HANDLE, [CATS[i % 5], i, i * 3]),
                      90, 100)
    for i in range(64):
        put_committed(eng, record_key(BT, i),
                      encode_row(NON_HANDLE, [CATS[i % 3], i + 100, i]),
                      90, 100)
    warm, cold = _pair(eng, block_rows=32, shadow_sample=0)
    pruned0 = _count("tikv_coprocessor_zone_prune_total", path="join",
                     outcome="pruned")
    dag = _jdag(2, 2)  # int keys: probe 0..255, build 100..163
    r = warm.handle_request(_req(dag))
    assert r.data == cold.handle_request(_req(dag)).data
    assert r.from_device
    pruned = _count("tikv_coprocessor_zone_prune_total", path="join",
                    outcome="pruned") - pruned0
    assert pruned > 0, "no join block pruned despite disjoint key ranges"


def test_join_chunk_encoding_byte_identical():
    """TypeChunk join responses ride the same encoder as the oracle —
    chunk framing and column slabs byte-compare."""
    eng = _engine()
    warm, cold = _pair(eng)
    dag = _jdag(1, 1, extra=(Limit(50),), encode_type=ENC_TYPE_CHUNK)
    r = warm.handle_request(_req(dag))
    dag2 = _jdag(1, 1, extra=(Limit(50),), encode_type=ENC_TYPE_CHUNK)
    c = cold.handle_request(_req(dag2))
    assert r.data == c.data
    assert r.from_device


@pytest.mark.parametrize("shape,cause", [
    (dict(jt="left"), "outer_join"),
    (dict(below=(Selection([call("gt", col(2), const_int(1))]),)),
     "probe_selection"),
    (dict(bctx=False), "no_build_context"),
    (dict(lk=1, rk=2), "key_form_mismatch"),
])
def test_join_declines_are_counted(shape, cause):
    """Every rung decline is a named, counted event AND the CPU pipeline
    serves the identical bytes — never silent, never wrong."""
    eng = _engine(n_probe=60, n_build=30)
    warm, cold = _pair(eng)
    kw = dict(lk=1, rk=1)
    kw.update(shape)
    dag = _jdag(kw.pop("lk"), kw.pop("rk"), **kw)
    before = _count("tikv_coprocessor_encoded_decline_total", path="join",
                    cause=cause)
    plan_declines = _count("tikv_coprocessor_encoded_decline_total",
                           path="device_plan", cause="join_executor")
    r = warm.handle_request(_req(dag))
    c = cold.handle_request(_req(dag))
    assert r.data == c.data
    assert not r.from_device
    assert _count("tikv_coprocessor_encoded_decline_total", path="join",
                  cause=cause) == before + 1
    # join plans never fall off the device plan silently either
    assert _count("tikv_coprocessor_encoded_decline_total",
                  path="device_plan", cause="join_executor") \
        == plan_declines + 1


def test_build_selection_runs_on_cpu_oracle():
    """A build chain with Selections is a valid CPU plan (check_supported)
    and a named device decline — filtered build side still joins right."""
    eng = _engine(n_probe=60, n_build=30)
    warm, cold = _pair(eng)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Join([TableScan(BT, COLUMNS),
              Selection([call("le", col(2), const_int(4))])],
             [record_range(BT)], 1, 1, join_type="inner",
             build_context=dict(_CTX[BT])),
    ])
    before = _count("tikv_coprocessor_encoded_decline_total", path="join",
                    cause="build_selection")
    r = warm.handle_request(_req(dag))
    c = cold.handle_request(_req(dag))
    assert r.data == c.data and not r.from_device
    assert _count("tikv_coprocessor_encoded_decline_total", path="join",
                  cause="build_selection") == before + 1


def test_projection_values():
    """The Projection executor's CPU oracle computes the expression list
    over the child schema row by row."""
    eng = BTreeEngine()
    for i in range(10):
        put_committed(eng, record_key(TABLE_ID, i),
                      encode_row(NON_HANDLE, [CATS[i % 5], i, i * 10]),
                      90, 100)
    cold = Endpoint(LocalEngine(eng), enable_device=False,
                    enable_region_cache=False)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, COLUMNS),
        Projection([call("plus", col(0), col(2)), col(1),
                    call("multiply", col(2), const_int(2))]),
    ])
    resp = cold.handle_request(_req(dag))
    rows = SelectResponse.decode(resp.data).iter_rows()
    assert rows == [[i + i, CATS[i % 5], 2 * i] for i in range(10)]


def test_join_observatory_profile_and_selectivity():
    """Served joins profile build/probe/out rows and selectivity per sig
    (``ctl.py observatory sig`` renders them)."""
    from tikv_tpu.copr import observatory as _obs

    eng = _engine(n_probe=60, n_build=30)
    warm, cold = _pair(eng, shadow_sample=0)
    dag = _jdag(1, 1)
    r = warm.handle_request(_req(dag))
    assert r.data == cold.handle_request(_req(dag)).data
    sig, _ = _obs.dag_sig(dag)
    entry = _obs.OBSERVATORY.snapshot(sig)["sigs"][sig]
    profs = [v for k, v in entry["paths"].items()
             if k.split("|")[0] in ("rank", "hash")]
    assert profs, f"no join-path profile recorded: {list(entry['paths'])}"
    v = profs[0]
    # the window aggregates every serve of this sig in-process, so assert
    # presence and internal consistency rather than exact per-call counts
    assert v["join_probe_rows"] >= 60 and v["join_build_rows"] >= 30
    assert v["join_out_rows"] > 0
    assert v["join_selectivity"] == round(
        v["join_out_rows"] / v["join_probe_rows"], 4)
    text = _obs.format_sig(sig, entry)
    assert "join:" in text and "selectivity=" in text
