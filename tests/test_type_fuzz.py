"""Differential fuzzing of the byte-identity-critical type surface
(VERDICT #6): decimal codec/arithmetic vs Python's decimal oracle, datum
round-trips + memcomparable ordering, row-v2 round-trips + truncation,
datetime pack/parse.  Mirrors the reference's fuzz targets
(fuzz/targets/mod.rs: dec_*, codec::row::v2, mysql::time) with hypothesis."""

from __future__ import annotations

import decimal

import pytest
from hypothesis import given, settings, strategies as st

from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.datum import (
    BYTES_FLAG, FLOAT_FLAG, INT_FLAG, NIL_FLAG, UINT_FLAG,
    decode_datum, encode_datum,
)
from tikv_tpu.copr.mydecimal import HALF_EVEN, MAX_DIGITS, MyDecimal, TRUNCATE
from tikv_tpu.copr.mysql_time import (
    format_datetime, pack_datetime, parse_datetime, unpack_datetime,
)
from tikv_tpu.copr import rowv2

SETTINGS = settings(max_examples=300, deadline=None)

# our decimal is exact (Python ints); the oracle must not round at the
# default 28 significant digits
decimal.getcontext().prec = 120

# --- decimal ---------------------------------------------------------------

dec_strings = st.builds(
    lambda neg, ip, fp: ("-" if neg else "") + (ip or "0") + ("." + fp if fp else ""),
    st.booleans(),
    st.text("0123456789", min_size=0, max_size=40),
    st.text("0123456789", min_size=0, max_size=25),
)


@SETTINGS
@given(dec_strings)
def test_decimal_from_str_matches_python_decimal(s):
    d = MyDecimal.from_str(s)
    oracle = decimal.Decimal(s)
    assert decimal.Decimal(d.to_string()) == oracle


@SETTINGS
@given(dec_strings, dec_strings)
def test_decimal_arith_matches_python_decimal(a, b):
    da, db = MyDecimal.from_str(a), MyDecimal.from_str(b)
    oa, ob = decimal.Decimal(a), decimal.Decimal(b)
    assert decimal.Decimal((da + db).to_string()) == oa + ob
    assert decimal.Decimal((da - db).to_string()) == oa - ob
    prod = da * db
    # frac clamps at 30 by TRUNCATION (MySQL scale rule; decimal.rs do_mul)
    want = (oa * ob).quantize(
        decimal.Decimal(1).scaleb(-prod.frac), rounding=decimal.ROUND_DOWN
    )
    limit = decimal.Decimal(10**MAX_DIGITS - 1).scaleb(-prod.frac)
    if abs(want) > limit:
        # 81-digit overflow saturates to the max magnitude (Res::Overflow)
        want = limit if want > 0 else -limit
    assert decimal.Decimal(prod.to_string()) == want


@SETTINGS
@given(dec_strings, st.integers(-5, 30))
def test_decimal_round_matches_oracle(s, frac):
    d = MyDecimal.from_str(s).round(frac)
    q = decimal.Decimal(1).scaleb(-max(frac, 0)) if frac < 28 else None
    if q is not None:
        with decimal.localcontext() as ctx:
            ctx.prec = 90
            want = decimal.Decimal(s).quantize(
                decimal.Decimal(1).scaleb(-frac), rounding=decimal.ROUND_HALF_UP
            )
        assert decimal.Decimal(d.to_string()) == want
    t = MyDecimal.from_str(s).round(frac, TRUNCATE)
    with decimal.localcontext() as ctx:
        ctx.prec = 90
        want = decimal.Decimal(s).quantize(
            decimal.Decimal(1).scaleb(-frac), rounding=decimal.ROUND_DOWN
        )
    assert decimal.Decimal(t.to_string()) == want


@SETTINGS
@given(dec_strings)
def test_decimal_bin_roundtrip(s):
    d = MyDecimal.from_str(s)
    prec = max(d.int_digits() + d.frac, 1)
    blob = d.encode_bin(prec, d.frac)
    back, used = MyDecimal.decode_bin(blob, prec, d.frac)
    assert used == len(blob)
    assert decimal.Decimal(back.to_string()) == decimal.Decimal(d.to_string())


# --- datum codec -----------------------------------------------------------

datum_values = st.one_of(
    st.tuples(st.just(NIL_FLAG), st.none()),
    st.tuples(st.just(INT_FLAG), st.integers(-(2**63), 2**63 - 1)),
    st.tuples(st.just(UINT_FLAG), st.integers(0, 2**64 - 1)),
    st.tuples(st.just(FLOAT_FLAG), st.floats(allow_nan=False, width=64)),
    st.tuples(st.just(BYTES_FLAG), st.binary(max_size=64)),
)


@SETTINGS
@given(datum_values, st.booleans())
def test_datum_roundtrip(fv, for_key):
    flag, value = fv
    out = bytearray()
    encode_datum(out, flag, value, for_key=for_key)
    d, off = decode_datum(bytes(out))
    assert off == len(out)
    if flag == FLOAT_FLAG:
        assert d.value == pytest.approx(value, nan_ok=False)
    else:
        assert d.value == value


@SETTINGS
@given(st.lists(st.integers(-(2**63), 2**63 - 1), min_size=2, max_size=2),
       st.lists(st.binary(max_size=24), min_size=2, max_size=2))
def test_memcomparable_order_matches_value_order(ints, byts):
    """for_key encodings must sort like the values they encode."""
    for flag, pair in ((INT_FLAG, ints), (BYTES_FLAG, byts)):
        enc = []
        for v in pair:
            out = bytearray()
            encode_datum(out, flag, v, for_key=True)
            enc.append(bytes(out))
        a, b = pair
        assert (enc[0] < enc[1]) == (a < b)
        assert (enc[0] == enc[1]) == (a == b)


# --- row v2 ----------------------------------------------------------------

_COLS = [
    ColumnInfo(1, FieldType.int64()),
    ColumnInfo(3, FieldType.varchar()),
    ColumnInfo(7, FieldType.int64()),
]

row_values = st.tuples(
    st.one_of(st.none(), st.integers(-(2**63), 2**63 - 1)),
    st.one_of(st.none(), st.binary(max_size=32)),
    st.one_of(st.none(), st.integers(-(2**63), 2**63 - 1)),
)


@SETTINGS
@given(row_values)
def test_rowv2_roundtrip(vals):
    raw = rowv2.encode_row_v2(_COLS, list(vals))
    sl = rowv2.RowSliceV2(raw)
    for info, want in zip(_COLS, vals):
        cell = sl.get(info.col_id)
        if want is None:
            assert cell is None
        else:
            assert rowv2.decode_cell(info, cell) == want


@SETTINGS
@given(row_values, st.integers(1, 40))
def test_rowv2_truncation_never_yields_garbage(vals, cut):
    """A truncated row must raise, never decode wrong cells silently
    (row_slice.rs corruption error; the round-2 advisor's finding)."""
    raw = rowv2.encode_row_v2(_COLS, list(vals))
    if cut >= len(raw):
        return
    try:
        sl = rowv2.RowSliceV2(raw[:cut])
    except ValueError:
        return  # correct: corruption detected
    # header happened to parse: every cell it returns must still be a
    # prefix-faithful slice, never out of bounds
    for info in _COLS:
        try:
            cell = sl.get(info.col_id)
        except KeyError:
            continue
        if cell is not None:
            assert len(cell) <= len(raw[:cut])


# --- datetime --------------------------------------------------------------


@SETTINGS
@given(st.integers(1000, 9999), st.integers(1, 12), st.integers(1, 28),
       st.integers(0, 23), st.integers(0, 59), st.integers(0, 59),
       st.integers(0, 999999))
def test_datetime_pack_roundtrip(y, mo, d, h, mi, s, us):
    packed = pack_datetime(y, mo, d, h, mi, s, us)
    assert unpack_datetime(packed) == (y, mo, d, h, mi, s, us)
    # format → parse is the identity on the packed value
    assert parse_datetime(format_datetime(packed)) == packed


def test_reference_decimal_vectors():
    """Edge vectors from decimal.rs tests (round/shift/to-string)."""
    cases = [
        ("123.456", 2, HALF_EVEN, "123.46"),
        ("123.454", 2, HALF_EVEN, "123.45"),
        ("-123.455", 2, HALF_EVEN, "-123.46"),  # half away from zero
        ("123.456", 0, HALF_EVEN, "123"),
        ("99.99", 1, HALF_EVEN, "100.0"),
        ("-99.99", 1, HALF_EVEN, "-100.0"),
        ("123.456", -1, HALF_EVEN, "120"),
        ("15", -1, HALF_EVEN, "20"),
        ("0.999", 0, TRUNCATE, "0"),
        ("-0.999", 0, TRUNCATE, "0"),
    ]
    for s, frac, mode, want in cases:
        got = MyDecimal.from_str(s).round(frac, mode).to_string()
        assert got == want, (s, frac, mode, got, want)


def test_reference_zero_date_and_fsp_vectors():
    """time/mod.rs zero-date + fractional-seconds vectors."""
    zero = pack_datetime(0, 0, 0, 0, 0, 0, 0)
    assert format_datetime(zero) == "0000-00-00 00:00:00"
    assert unpack_datetime(zero) == (0, 0, 0, 0, 0, 0, 0)
    p = parse_datetime("2021-03-04 05:06:07.125")
    assert unpack_datetime(p) == (2021, 3, 4, 5, 6, 7, 125000)


def test_zero_date_kernel_regressions():
    """Widening pack_datetime to admit zero dates must not turn NULL kernel
    results into garbage (LAST_DAY of zero-month → NULL; CAST(0) → zero
    date; CAST with zero month/day parts → NULL)."""
    from tikv_tpu.copr.kernels import KERNELS

    _, _, last_day = KERNELS["last_day"]
    import numpy as np

    p = pack_datetime(2021, 0, 15)
    d, nulls = last_day(np, (np.array([p]), np.array([False])))
    assert nulls[0], "LAST_DAY of a zero-month date must be NULL"
    _, _, cast = KERNELS["cast_int_datetime"]
    d, nulls = cast(np, (np.array([0]), np.array([False])))
    assert not nulls[0] and d[0] == 0, "CAST(0 AS DATETIME) is the zero date"
    d, nulls = cast(np, (np.array([20210000]), np.array([False])))
    assert nulls[0], "zero month/day numeric literal must be NULL"
