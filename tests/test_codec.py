"""Codec unit tests mirroring the reference's codec test vectors
(components/codec/src/byte.rs, number.rs tests)."""

import struct

import numpy as np
import pytest

from tikv_tpu.util import codec


# Test vectors from the reference's byte.rs tests (same wire format).
BYTES_VECTORS = [
    (b"", bytes([0, 0, 0, 0, 0, 0, 0, 0, 0xF7])),
    (b"\x00", bytes([0, 0, 0, 0, 0, 0, 0, 0, 0xF8])),
    (b"\x01\x02\x03", bytes([1, 2, 3, 0, 0, 0, 0, 0, 0xFA])),
    (
        b"\x01\x02\x03\x04\x05\x06\x07\x08",
        bytes([1, 2, 3, 4, 5, 6, 7, 8, 0xFF, 0, 0, 0, 0, 0, 0, 0, 0, 0xF7]),
    ),
]


@pytest.mark.parametrize("raw,enc", BYTES_VECTORS)
def test_encode_bytes_vectors(raw, enc):
    assert codec.encode_bytes(raw) == enc
    got, consumed = codec.decode_bytes(enc)
    assert got == raw
    assert consumed == len(enc)


def test_encode_bytes_desc_roundtrip():
    for raw in [b"", b"a", b"hello world", b"\xff" * 20, bytes(range(256))]:
        enc = codec.encode_bytes(raw, desc=True)
        got, consumed = codec.decode_bytes(enc, desc=True)
        assert got == raw and consumed == len(enc)


def test_encode_bytes_ordering():
    keys = [b"", b"\x00", b"\x00\x00", b"a", b"ab", b"b", b"\xff", b"\xff\x00"]
    encs = [codec.encode_bytes(k) for k in keys]
    assert encs == sorted(encs)
    desc = [codec.encode_bytes(k, desc=True) for k in keys]
    assert desc == sorted(desc, reverse=True)


def test_encoded_bytes_len():
    for raw in [b"", b"abc", b"12345678", b"x" * 17]:
        enc = codec.encode_bytes(raw) + b"trailing"
        assert codec.encoded_bytes_len(enc) == len(codec.encode_bytes(raw))


U64_CASES = [0, 1, 2**8, 2**16 - 1, 2**32, 2**63, 2**64 - 1]
I64_CASES = [-(2**63), -(2**31), -1, 0, 1, 2**31, 2**63 - 1]
F64_CASES = [float("-inf"), -1e300, -1.5, -0.0, 0.0, 1e-300, 1.0, 3.14159, 1e300, float("inf")]


def test_u64_roundtrip_and_order():
    encs = [codec.encode_u64(v) for v in U64_CASES]
    assert encs == sorted(encs)
    for v, e in zip(U64_CASES, encs):
        assert codec.decode_u64(e) == v
    descs = [codec.encode_u64_desc(v) for v in U64_CASES]
    assert descs == sorted(descs, reverse=True)
    for v, e in zip(U64_CASES, descs):
        assert codec.decode_u64_desc(e) == v


def test_i64_roundtrip_and_order():
    encs = [codec.encode_i64(v) for v in I64_CASES]
    assert encs == sorted(encs)
    for v, e in zip(I64_CASES, encs):
        assert codec.decode_i64(e) == v


def test_f64_roundtrip_and_order():
    encs = [codec.encode_f64(v) for v in F64_CASES]
    assert encs == sorted(encs)
    for v, e in zip(F64_CASES, encs):
        got = codec.decode_f64(e)
        assert got == v or (got != got and v != v)


def test_varint_roundtrip():
    for v in U64_CASES:
        b = codec.encode_var_u64(v)
        got, off = codec.decode_var_u64(b)
        assert got == v and off == len(b)
    for v in I64_CASES:
        b = codec.encode_var_i64(v)
        got, off = codec.decode_var_i64(b)
        assert got == v and off == len(b)


def test_compact_bytes():
    for raw in [b"", b"abc", b"x" * 300]:
        b = codec.encode_compact_bytes(raw)
        got, off = codec.decode_compact_bytes(b)
        assert got == raw and off == len(b)


def test_batch_codecs_match_scalar():
    rng = np.random.default_rng(0)
    u = rng.integers(0, 2**63, size=100, dtype=np.uint64) * 2 + rng.integers(0, 2, 100).astype(np.uint64)
    enc = codec.encode_u64_batch(u)
    scalar = np.frombuffer(b"".join(codec.encode_u64(int(v)) for v in u), dtype=np.uint8).reshape(-1, 8)
    assert np.array_equal(enc, scalar)
    assert np.array_equal(codec.decode_u64_batch(enc), u)

    i = u.view(np.int64)
    enci = codec.encode_i64_batch(i)
    scalari = np.frombuffer(b"".join(codec.encode_i64(int(v)) for v in i), dtype=np.uint8).reshape(-1, 8)
    assert np.array_equal(enci, scalari)
    assert np.array_equal(codec.decode_i64_batch(enci), i)

    f = rng.standard_normal(100) * 1e10
    encf = np.frombuffer(b"".join(codec.encode_f64(float(v)) for v in f), dtype=np.uint8).reshape(-1, 8)
    assert np.array_equal(codec.decode_f64_batch(encf), f)


def test_decode_errors():
    with pytest.raises(ValueError):
        codec.decode_bytes(b"\x01\x02")
    with pytest.raises(ValueError):
        codec.decode_var_u64(b"\xff" * 11)
    with pytest.raises(ValueError):
        codec.decode_compact_bytes(codec.encode_var_i64(100) + b"xx")
