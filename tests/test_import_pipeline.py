"""SST import pipeline: disk staging, duplicate detection, raft-replicated
ingest with a replica restarting mid-ingest (sst_importer.rs +
src/import/duplicate_detect.rs + fsm/apply.rs exec_ingest_sst behaviors)."""

from __future__ import annotations

import threading

import pytest

from tikv_tpu.pd.client import MockPd
from tikv_tpu.server.cluster import FIRST_REGION_ID, ServerCluster
from tikv_tpu.sidecar.backup import MAGIC, LocalStorage
from tikv_tpu.sidecar.importer import SstImporter
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key
from tikv_tpu.util import codec


def _backup_file(pairs, backup_ts=50) -> bytes:
    out = bytearray(MAGIC)
    out += codec.encode_var_u64(backup_ts)
    for k, v in pairs:
        out += codec.encode_compact_bytes(k)
        out += codec.encode_compact_bytes(v)
    return bytes(out)


def test_staging_is_unbounded_and_disk_backed(tmp_path):
    """100 downloaded files stay staged simultaneously — no eviction."""
    storage = LocalStorage(str(tmp_path / "ext"))
    imp = SstImporter(storage, workdir=str(tmp_path / "stage"))
    for i in range(100):
        storage.write("f%03d.bak" % i, _backup_file([(b"k%03d" % i, b"v")]))
        imp.download("f%03d.bak" % i)
    assert imp.staged_count() == 100
    # ingest consumes the staged copy; the others remain
    eng = LocalEngine(BTreeEngine())
    imp.restore(eng, "f007.bak", restore_ts=100)
    assert imp.staged_count() == 99
    assert eng.snapshot(None).get_cf(CF_WRITE, Key.from_raw(b"k007").append_ts(101).encoded)


def test_duplicate_detection(tmp_path):
    from fixtures import put_committed

    storage = LocalStorage(str(tmp_path / "ext"))
    imp = SstImporter(storage, workdir=str(tmp_path / "stage"))
    eng = BTreeEngine()
    put_committed(eng, b"dup1", b"old", 10, 20)
    put_committed(eng, b"dup2", b"old", 10, 30)
    storage.write("in.bak", _backup_file([(b"dup1", b"new"), (b"dup2", b"new"),
                                          (b"fresh", b"new")]))
    imp.download("in.bak")
    dups = imp.duplicate_detect(eng.snapshot(), "in.bak")
    assert sorted(d["key"] for d in dups) == [b"dup1", b"dup2"]
    assert all(d["type"] == "PUT" for d in dups)
    # min_commit_ts filters out older-than-threshold collisions
    dups = imp.duplicate_detect(eng.snapshot(), "in.bak", min_commit_ts=25)
    assert [d["key"] for d in dups] == [b"dup2"]


def test_raft_ingest_100_files_with_replica_restart(tmp_path):
    """The VERDICT's done-bar: a 3-node cluster ingests 100 files through the
    raft ingest_sst command while one replica restarts mid-ingest; every
    store converges to identical data."""
    storage = LocalStorage(str(tmp_path / "ext"))
    imp = SstImporter(storage, workdir=str(tmp_path / "stage"))
    for i in range(100):
        storage.write(
            "chunk%03d.bak" % i,
            _backup_file([(b"imp%03d-%d" % (i, j), b"val%03d-%d" % (i, j))
                          for j in range(5)]))
        imp.download("chunk%03d.bak" % i)
    c = ServerCluster(3, pd=MockPd())
    c.run()
    try:
        c.must_put(b"seed", b"x")  # elect a leader first
        restarted = threading.Event()

        def ingest_all():
            for i in range(100):
                if i == 40:
                    restarted.set()
                imp.ingest_via_raft(
                    lambda blob: c.ingest_sst(FIRST_REGION_ID, blob),
                    "chunk%03d.bak" % i, restore_ts=1000 + 2 * i)

        t = threading.Thread(target=ingest_all)
        t.start()
        restarted.wait(30)
        c.stop_node(3)   # replica down mid-ingest
        c.restart_node(3)
        t.join(timeout=120)
        assert not t.is_alive(), "ingest stalled"
        assert imp.staged_count() == 0
        # every replica holds every imported key (store 3 caught up from its
        # log / snapshot — the ingest payload rides the raft log)
        import time

        probe = [(0, 0), (39, 4), (40, 0), (70, 2), (99, 4)]
        for i, j in probe:
            wkey = Key.from_raw(b"imp%03d-%d" % (i, j)).append_ts(1000 + 2 * i + 1)
            for sid in (1, 2, 3):
                t0 = time.time()
                v = None
                while time.time() - t0 < 30:
                    v = c.get_on_store(sid, wkey.encoded, cf=CF_WRITE)
                    if v is not None:
                        break
                    time.sleep(0.1)
                assert v is not None, f"store {sid} missing imported key {i},{j}"
    finally:
        c.shutdown()


def test_ingest_rejects_out_of_range_keys(tmp_path):
    """exec_ingest_sst range rule: a payload with keys outside the target
    region is rejected at propose time (out-of-range keys in region A's log
    would be invisible to A's range-bounded snapshots — replica divergence)."""
    from tikv_tpu.sidecar.importer import encode_ingest_entries

    c = ServerCluster(3, pd=MockPd())
    c.run()
    try:
        c.must_put(b"a-seed", b"x")
        new_rid = c.split_region(FIRST_REGION_ID, b"m")
        # FIRST_REGION now covers [, m); a payload with a key >= m must fail
        payload = encode_ingest_entries([("default", b"zzz", b"v")])
        with pytest.raises(Exception, match="outside region"):
            c.ingest_sst(FIRST_REGION_ID, payload, timeout=3.0)
        # and an in-range payload still lands
        c.ingest_sst(FIRST_REGION_ID, encode_ingest_entries([("default", b"abc", b"v")]))
        for sid in (1, 2, 3):
            c.wait_get_on_store(sid, b"abc", b"v")
    finally:
        c.shutdown()
