"""A real distributed deployment: 3 store PROCESSES + a PD service.

The round-trip the reference proves with ServerCluster + real tikv-server
binaries: stores in separate OS processes over durable engine dirs, peer raft
and client KV over TCP, PD over TCP, leader kill -9 + failover + restart
recovery.  Nothing is shared but sockets and disks.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from tikv_tpu.native.engine import native_available
from tikv_tpu.pd.client import MockPd
from tikv_tpu.pd.service import PdService
from tikv_tpu.server.server import Client, Server

FIRST_REGION_ID = 1
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_store(store_id: int, pd_addr, data_dir: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [
            sys.executable, "-m", "tikv_tpu.server.standalone",
            "--store-id", str(store_id),
            "--pd", f"{pd_addr[0]}:{pd_addr[1]}",
            "--dir", data_dir,
            "--expect-stores", "3",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _wait_ready(proc, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(f"store process exited rc={proc.poll()}")
        if line.startswith(b"READY"):
            return line.decode().strip()
    raise AssertionError("store never became READY")


class _ClusterClient:
    """Leader-following client: PD tells it where region 1's leader lives."""

    def __init__(self, pd: MockPd):
        self.pd = pd
        self._clients: dict[int, Client] = {}

    def _leader_client(self) -> Client | None:
        sid = self.pd.leader_of(FIRST_REGION_ID)
        if sid is None:
            return None
        addr = self.pd.get_store_addr(sid)
        if addr is None:
            return None
        c = self._clients.get(sid)
        if c is None:
            try:
                c = Client(addr[0], addr[1])
            except OSError:
                return None
            self._clients[sid] = c
        return c

    def call(self, method: str, req: dict, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            c = self._leader_client()
            if c is None:
                time.sleep(0.2)
                continue
            try:
                # short per-attempt timeout: a server mid-election answers
                # slowly or not at all; retrying against the current PD
                # leader beats waiting out one stuck call
                r = c.call(method, req, timeout=8.0)
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                for sid, cl in list(self._clients.items()):
                    if cl is c:
                        cl.close()
                        del self._clients[sid]
                time.sleep(0.2)
                continue
            if isinstance(r, dict) and ("error" in r or r.get("errors")):
                last = r
                time.sleep(0.2)
                continue
            return r
        raise AssertionError(f"{method} never succeeded: {last!r}")

    def put(self, key: bytes, value: bytes) -> None:
        ts1 = self.pd.get_tso()
        ctx = {"region_id": FIRST_REGION_ID}
        self.call(
            "kv_prewrite",
            {
                "mutations": [{"op": "put", "key": key, "value": value}],
                "primary_lock": key,
                "start_version": ts1,
                "context": ctx,
            },
        )
        self.call(
            "kv_commit",
            {
                "keys": [key],
                "start_version": ts1,
                "commit_version": self.pd.get_tso(),
                "context": ctx,
            },
        )

    def get(self, key: bytes) -> bytes | None:
        r = self.call(
            "kv_get",
            {"key": key, "version": self.pd.get_tso(), "context": {"region_id": FIRST_REGION_ID}},
        )
        return r.get("value")

    def close(self) -> None:
        for c in self._clients.values():
            c.close()


@pytest.mark.skipif(not native_available(), reason="needs the native durable engine")
def test_three_process_cluster_failover_and_recovery(tmp_path):
    pd = MockPd()
    pd_server = Server(PdService(pd))
    pd_server.start()
    procs = {}
    client = None
    try:
        for sid in (1, 2, 3):
            procs[sid] = _spawn_store(sid, pd_server.addr, str(tmp_path / f"store{sid}"))
        for sid in (1, 2, 3):
            _wait_ready(procs[sid])

        client = _ClusterClient(pd)
        client.put(b"alpha", b"1")
        assert client.get(b"alpha") == b"1"

        # kill -9 the leader process: survivors elect, writes keep flowing
        leader_sid = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and leader_sid is None:
            leader_sid = pd.leader_of(FIRST_REGION_ID)
            time.sleep(0.1)
        assert leader_sid is not None
        procs[leader_sid].kill()
        procs[leader_sid].wait()

        client.put(b"beta", b"2")
        assert client.get(b"beta") == b"2"
        assert client.get(b"alpha") == b"1"
        new_leader = pd.leader_of(FIRST_REGION_ID)
        assert new_leader != leader_sid

        # restart the killed store on its engine dir: WAL recovery + raft
        # catch-up over the wire
        procs[leader_sid] = _spawn_store(
            leader_sid, pd_server.addr, str(tmp_path / f"store{leader_sid}")
        )
        _wait_ready(procs[leader_sid])
        client.put(b"gamma", b"3")
        assert client.get(b"gamma") == b"3"
        # the restarted store heartbeats again = it recovered and rejoined
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if leader_sid in pd.alive_stores(within_secs=3.0):
                break
            time.sleep(0.2)
        assert leader_sid in pd.alive_stores(within_secs=3.0)
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()
        pd_server.stop()


def test_dr_auto_sync_transitions_multiprocess(tmp_path):
    """DR auto-sync across OS processes (replication_mode.rs + VERDICT r4
    item 10): two label groups; killing the minority DC drops the cluster to
    async (writes keep flowing), its return passes sync_recover back to
    sync.  State rides store-heartbeat responses over the real wire."""
    pd = MockPd()
    pd.store_down_secs = 2.0
    pd_server = Server(PdService(pd))
    pd_server.start()
    procs, client = {}, None

    def wait_state(want: str, timeout=30.0) -> str:
        deadline = time.monotonic() + timeout
        seen = None
        while time.monotonic() < deadline:
            with pd._mu:
                pd._update_replication_state()
                seen = pd.replication["state"]
            if seen == want:
                return seen
            time.sleep(0.2)
        raise AssertionError(f"replication state stuck at {seen}, wanted {want}")

    try:
        for sid in (1, 2, 3):
            procs[sid] = _spawn_store(sid, pd_server.addr, str(tmp_path / f"d{sid}"))
        for sid in (1, 2, 3):
            _wait_ready(procs[sid])
        client = _ClusterClient(pd)
        client.put(b"pre", b"1")
        pd.enable_dr_auto_sync({1: "east", 2: "east", 3: "west"})
        assert pd.replication["state"] == "sync"
        client.put(b"sync-write", b"2")
        assert client.get(b"sync-write") == b"2"

        # the west DC dies: sync -> async, majority commit keeps serving
        procs[3].kill()
        procs[3].wait()
        wait_state("async")
        client.put(b"async-write", b"3")
        assert client.get(b"async-write") == b"3"

        # west returns: async -> sync_recover -> sync
        procs[3] = _spawn_store(3, pd_server.addr, str(tmp_path / "d3"))
        _wait_ready(procs[3])
        wait_state("sync")
        client.put(b"resync-write", b"4")
        assert client.get(b"resync-write") == b"4"
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()
        pd_server.stop()


def test_hot_region_leader_balance_multiprocess(tmp_path):
    """Hot-region-aware leader balance across OS processes (VERDICT r4 item
    10): three regions all led by store 1; write load makes them hot, and
    PD's load-weighted leader balance moves leadership off the hot store via
    region-heartbeat operators over the real wire."""
    from tikv_tpu.storage.txn_types import Key
    from tikv_tpu.util import keys as keymod

    pd = MockPd()
    pd.replication_factor = 3  # scheduling enabled
    pd.balance_threshold = 10**9  # frozen while the test stacks leaders
    pd_server = Server(PdService(pd))
    pd_server.start()
    procs, clients = {}, {}

    def client_for(sid):
        c = clients.get(sid)
        if c is None:
            addr = pd.get_store_addr(sid)
            c = clients[sid] = Client(addr[0], addr[1])
        return c

    def region_for(raw_key: bytes) -> int:
        enc = keymod.data_key(Key.from_raw(raw_key).encoded)
        best = FIRST_REGION_ID
        for rid, region in pd.regions.items():
            start = keymod.data_key(region.start_key) if region.start_key else b""
            end = keymod.data_key(region.end_key) if region.end_key else None
            if enc >= start and (end is None or enc < end):
                best = rid
        return best

    def call_leader(region_id, method, req, timeout=40.0):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            sid = pd.leaders.get(region_id)
            if sid is None:
                time.sleep(0.2)
                continue
            try:
                r = client_for(sid).call(
                    method, dict(req, context={"region_id": region_id}),
                    timeout=10.0)
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                clients.pop(sid, None)
                time.sleep(0.2)
                continue
            if isinstance(r, dict) and (r.get("error") or r.get("errors")):
                last = r
                time.sleep(0.2)
                continue
            return r
        raise AssertionError(f"{method} on region {region_id}: {last!r}")

    def put(key: bytes, value: bytes):
        rid = region_for(key)
        ts1 = pd.get_tso()
        call_leader(rid, "kv_prewrite", {
            "mutations": [{"op": "put", "key": key, "value": value}],
            "primary_lock": key, "start_version": ts1,
        })
        call_leader(rid, "kv_commit", {
            "keys": [key], "start_version": ts1,
            "commit_version": pd.get_tso(),
        })

    try:
        for sid in (1, 2, 3):
            procs[sid] = _spawn_store(sid, pd_server.addr, str(tmp_path / f"h{sid}"))
        for sid in (1, 2, 3):
            _wait_ready(procs[sid])
        put(b"key-050", b"seed")
        # three regions over the key space
        for split in (b"key-300", b"key-600"):
            call_leader(region_for(split), "kv_split_region", {"split_key": split})
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(pd.regions) < 3:
            time.sleep(0.2)
        assert len(pd.regions) >= 3

        # drag every leader onto store 1 (the adversarial starting point)
        def leaders():
            return {rid: pd.leaders.get(rid) for rid in list(pd.regions)}

        deadline = time.monotonic() + 40
        while time.monotonic() < deadline:
            lds = leaders()
            if None not in lds.values() and all(s == 1 for s in lds.values()):
                break
            for rid, sid in lds.items():
                if sid is not None and sid != 1:
                    region = pd.regions.get(rid)
                    peer = region.peer_on_store(1) if region is not None else None
                    if peer is not None and rid not in pd.operators:
                        pd.add_operator(rid, {
                            "type": "transfer_leader",
                            "peer_id": peer.peer_id, "store_id": 1,
                        })
            time.sleep(0.5)
        assert all(s == 1 for s in leaders().values()), leaders()
        pd.balance_threshold = 2  # release the balancer against the hot pile

        # hammer writes across all regions: store 1 leads every hot region
        stop = time.monotonic() + 45
        i = 0
        moved = False
        while time.monotonic() < stop:
            put(b"key-%03d" % (i % 900), b"v%d" % i)
            i += 1
            lds = leaders()
            if any(s not in (None, 1) for s in lds.values()):
                moved = True
                break
        assert moved, f"leader balance never moved a hot leader: {leaders()}"
    finally:
        for c in clients.values():
            c.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()
        pd_server.stop()
