"""A real distributed deployment: 3 store PROCESSES + a PD service.

The round-trip the reference proves with ServerCluster + real tikv-server
binaries: stores in separate OS processes over durable engine dirs, peer raft
and client KV over TCP, PD over TCP, leader kill -9 + failover + restart
recovery.  Nothing is shared but sockets and disks.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from tikv_tpu.native.engine import native_available
from tikv_tpu.pd.client import MockPd
from tikv_tpu.pd.service import PdService
from tikv_tpu.server.server import Client, Server

FIRST_REGION_ID = 1
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_store(store_id: int, pd_addr, data_dir: str):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [
            sys.executable, "-m", "tikv_tpu.server.standalone",
            "--store-id", str(store_id),
            "--pd", f"{pd_addr[0]}:{pd_addr[1]}",
            "--dir", data_dir,
            "--expect-stores", "3",
        ],
        env=env,
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _wait_ready(proc, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(f"store process exited rc={proc.poll()}")
        if line.startswith(b"READY"):
            return line.decode().strip()
    raise AssertionError("store never became READY")


class _ClusterClient:
    """Leader-following client: PD tells it where region 1's leader lives."""

    def __init__(self, pd: MockPd):
        self.pd = pd
        self._clients: dict[int, Client] = {}

    def _leader_client(self) -> Client | None:
        sid = self.pd.leader_of(FIRST_REGION_ID)
        if sid is None:
            return None
        addr = self.pd.get_store_addr(sid)
        if addr is None:
            return None
        c = self._clients.get(sid)
        if c is None:
            try:
                c = Client(addr[0], addr[1])
            except OSError:
                return None
            self._clients[sid] = c
        return c

    def call(self, method: str, req: dict, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            c = self._leader_client()
            if c is None:
                time.sleep(0.2)
                continue
            try:
                # short per-attempt timeout: a server mid-election answers
                # slowly or not at all; retrying against the current PD
                # leader beats waiting out one stuck call
                r = c.call(method, req, timeout=8.0)
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                for sid, cl in list(self._clients.items()):
                    if cl is c:
                        cl.close()
                        del self._clients[sid]
                time.sleep(0.2)
                continue
            if isinstance(r, dict) and ("error" in r or r.get("errors")):
                last = r
                time.sleep(0.2)
                continue
            return r
        raise AssertionError(f"{method} never succeeded: {last!r}")

    def put(self, key: bytes, value: bytes) -> None:
        ts1 = self.pd.get_tso()
        ctx = {"region_id": FIRST_REGION_ID}
        self.call(
            "kv_prewrite",
            {
                "mutations": [{"op": "put", "key": key, "value": value}],
                "primary_lock": key,
                "start_version": ts1,
                "context": ctx,
            },
        )
        self.call(
            "kv_commit",
            {
                "keys": [key],
                "start_version": ts1,
                "commit_version": self.pd.get_tso(),
                "context": ctx,
            },
        )

    def get(self, key: bytes) -> bytes | None:
        r = self.call(
            "kv_get",
            {"key": key, "version": self.pd.get_tso(), "context": {"region_id": FIRST_REGION_ID}},
        )
        return r.get("value")

    def close(self) -> None:
        for c in self._clients.values():
            c.close()


@pytest.mark.skipif(not native_available(), reason="needs the native durable engine")
def test_three_process_cluster_failover_and_recovery(tmp_path):
    pd = MockPd()
    pd_server = Server(PdService(pd))
    pd_server.start()
    procs = {}
    client = None
    try:
        for sid in (1, 2, 3):
            procs[sid] = _spawn_store(sid, pd_server.addr, str(tmp_path / f"store{sid}"))
        for sid in (1, 2, 3):
            _wait_ready(procs[sid])

        client = _ClusterClient(pd)
        client.put(b"alpha", b"1")
        assert client.get(b"alpha") == b"1"

        # kill -9 the leader process: survivors elect, writes keep flowing
        leader_sid = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and leader_sid is None:
            leader_sid = pd.leader_of(FIRST_REGION_ID)
            time.sleep(0.1)
        assert leader_sid is not None
        procs[leader_sid].kill()
        procs[leader_sid].wait()

        client.put(b"beta", b"2")
        assert client.get(b"beta") == b"2"
        assert client.get(b"alpha") == b"1"
        new_leader = pd.leader_of(FIRST_REGION_ID)
        assert new_leader != leader_sid

        # restart the killed store on its engine dir: WAL recovery + raft
        # catch-up over the wire
        procs[leader_sid] = _spawn_store(
            leader_sid, pd_server.addr, str(tmp_path / f"store{leader_sid}")
        )
        _wait_ready(procs[leader_sid])
        client.put(b"gamma", b"3")
        assert client.get(b"gamma") == b"3"
        # the restarted store heartbeats again = it recovered and rejoined
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if leader_sid in pd.alive_stores(within_secs=3.0):
                break
            time.sleep(0.2)
        assert leader_sid in pd.alive_stores(within_secs=3.0)
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait()
        pd_server.stop()
