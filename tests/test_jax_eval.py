"""Differential tests: JAX device path vs CPU oracle path.

The BASELINE.json contract: for every eligible DAG, the device path's encoded
SelectResponse must equal the CPU pipeline's bytes exactly (int/decimal
pipelines; REAL aggregates are float-rounding-exempt).
"""

import numpy as np
import pytest

from tikv_tpu.copr import jax_eval
from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import (
    Aggregation,
    BatchExecutorsRunner,
    DagRequest,
    Limit,
    Selection,
    TableScan,
    TopN,
)
from tikv_tpu.copr.executors import FixtureScanSource
from tikv_tpu.copr.jax_eval import JaxDagEvaluator, supports
from tikv_tpu.copr.rpn import call, col, const_bytes, const_decimal, const_int

from copr_fixtures import (
    PRODUCT_COLUMNS,
    TABLE_ID,
    numeric_table_kvs,
    product_kvs,
)


def run_both(executors, kvs, block_rows=256, output_offsets=None):
    dag = DagRequest(executors=executors, output_offsets=output_offsets)
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
    ev = JaxDagEvaluator(dag, block_rows=block_rows)
    dev = ev.run(FixtureScanSource(kvs))
    return cpu, dev


NUMERIC_COLS, NUMERIC_KVS, (A, B, C) = numeric_table_kvs(5000)


def test_supports_routing():
    assert supports(DagRequest(executors=[TableScan(TABLE_ID, NUMERIC_COLS)]))
    assert supports(
        DagRequest(
            executors=[
                TableScan(TABLE_ID, NUMERIC_COLS),
                Selection([call("lt", col(1), const_int(10))]),
                Aggregation(group_by=[], agg_funcs=[AggDescriptor("count", None)]),
            ]
        )
    )
    # raw TopN over numeric schemas IS device-routable (running top-K merge)
    assert supports(
        DagRequest(executors=[TableScan(TABLE_ID, NUMERIC_COLS), TopN([(col(1), False)], 5)])
    )
    # …but not with bytes payload columns or oversized K
    assert not supports(
        DagRequest(executors=[TableScan(TABLE_ID, NUMERIC_COLS), TopN([(col(1), False)], 100000)])
    )
    # bytes PAYLOAD columns now ride as dictionary codes (round 5) — but a
    # bytes sort KEY still routes to CPU
    assert supports(
        DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), TopN([(col(0), False)], 5)])
    )
    assert not supports(
        DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), TopN([(col(1), False)], 5)])
    )
    # bytes predicate stays on CPU
    assert not supports(
        DagRequest(
            executors=[
                TableScan(TABLE_ID, PRODUCT_COLUMNS),
                Selection([call("eq", col(1), const_bytes(b"apple"))]),
            ]
        )
    )
    # bytes group-by IS eligible (host dictionary encoding)
    assert supports(
        DagRequest(
            executors=[
                TableScan(TABLE_ID, PRODUCT_COLUMNS),
                Aggregation(group_by=[col(1)], agg_funcs=[AggDescriptor("count", None)]),
            ]
        )
    )


def test_scan_only_identical():
    cpu, dev = run_both([TableScan(TABLE_ID, NUMERIC_COLS)], NUMERIC_KVS)
    assert cpu.encode() == dev.encode()


def test_selection_identical():
    cond = call(
        "and",
        call("lt", col(1), const_int(500)),
        call("gt", col(2), const_int(20)),
    )
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([cond])], NUMERIC_KVS
    )
    assert cpu.encode() == dev.encode()
    assert len(cpu.iter_rows()) > 0


def test_selection_three_predicates_identical():
    # the BASELINE config-2 shape: lt/gt/eq conjunction
    conds = [
        call("lt", col(1), const_int(800)),
        call("gt", col(2), const_int(10)),
        call("ne", col(3), const_decimal(0, 2)),
    ]
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection(conds)], NUMERIC_KVS
    )
    assert cpu.encode() == dev.encode()


def test_selection_with_limit_identical():
    cond = call("lt", col(1), const_int(500))
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([cond]), Limit(37)], NUMERIC_KVS
    )
    assert cpu.encode() == dev.encode()
    assert len(cpu.iter_rows()) == 37


def test_simple_agg_identical():
    # Q6 shape: filtered sum/count/avg over decimal
    aggs = [
        AggDescriptor("count", None),
        AggDescriptor("sum", col(3)),
        AggDescriptor("avg", col(3)),
        AggDescriptor("min", col(1)),
        AggDescriptor("max", col(3)),
    ]
    cond = call("lt", col(1), const_int(500))
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([cond]), Aggregation([], aggs)],
        NUMERIC_KVS,
    )
    assert cpu.encode() == dev.encode()


def test_simple_agg_empty_result_identical():
    aggs = [AggDescriptor("count", None), AggDescriptor("sum", col(3)), AggDescriptor("min", col(1))]
    cond = call("lt", col(1), const_int(-1))  # nothing passes
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([cond]), Aggregation([], aggs)],
        NUMERIC_KVS,
    )
    assert cpu.encode() == dev.encode()


def test_decimal_arith_agg_identical():
    # sum(c * c) — decimal multiply, frac adds
    aggs = [AggDescriptor("sum", call("multiply", col(3), col(3)))]
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Aggregation([], aggs)], NUMERIC_KVS
    )
    assert cpu.encode() == dev.encode()


def test_hash_agg_int_key_identical():
    aggs = [AggDescriptor("count", None), AggDescriptor("sum", col(3))]
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Aggregation([col(2)], aggs)], NUMERIC_KVS
    )
    assert cpu.encode() == dev.encode()


def test_hash_agg_group_capacity_growth():
    # group key with 1000 distinct values over small capacity start
    aggs = [AggDescriptor("count", None)]
    dag_execs = [TableScan(TABLE_ID, NUMERIC_COLS), Aggregation([col(1)], aggs)]
    dag = DagRequest(executors=dag_execs)
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(NUMERIC_KVS)).handle_request()
    ev = JaxDagEvaluator(dag, block_rows=128)
    jax_eval._GROUP_CAPACITY_START = 16  # force growth path
    try:
        ev._capacity = 16
        dev = ev.run(FixtureScanSource(NUMERIC_KVS))
    finally:
        jax_eval._GROUP_CAPACITY_START = 1024
    assert cpu.encode() == dev.encode()


def test_hash_agg_bytes_key_identical():
    # Q1 shape: group by varchar, sum decimals
    kvs = product_kvs()
    aggs = [AggDescriptor("count", None), AggDescriptor("sum", col(2)), AggDescriptor("avg", col(3))]
    cpu, dev = run_both(
        [TableScan(TABLE_ID, PRODUCT_COLUMNS), Aggregation([col(1)], aggs)], kvs, block_rows=4
    )
    assert cpu.encode() == dev.encode()


def test_hash_agg_topn_identical():
    aggs = [AggDescriptor("sum", col(3))]
    cpu, dev = run_both(
        [
            TableScan(TABLE_ID, NUMERIC_COLS),
            Aggregation([col(2)], aggs),
            TopN([(col(0), True)], 10),
        ],
        NUMERIC_KVS,
    )
    assert cpu.encode() == dev.encode()
    assert len(cpu.iter_rows()) == 10


def test_output_offsets_identical():
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS)], NUMERIC_KVS, output_offsets=[3, 0]
    )
    assert cpu.encode() == dev.encode()


def test_real_agg_close():
    cols, kvs, _ = numeric_table_kvs(500)
    # cast-free real column doesn't exist in numeric fixture; divide produces real
    aggs = [AggDescriptor("sum", call("divide_real", col(2), const_int(7)))]
    dag = DagRequest(executors=[TableScan(TABLE_ID, cols), Aggregation([], aggs)])
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
    dev = JaxDagEvaluator(dag, block_rows=64).run(FixtureScanSource(kvs))
    (c,) = cpu.iter_rows()
    (d,) = dev.iter_rows()
    assert c[0] == pytest.approx(d[0], rel=1e-12)


def test_selection_then_group_by_identical():
    """Groups existing only in filtered-out rows must not be emitted."""
    aggs = [AggDescriptor("count", None), AggDescriptor("sum", col(3))]
    cond = call("lt", col(1), const_int(50))  # most groups of col(2) survive partially
    cpu, dev = run_both(
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([cond]), Aggregation([col(2)], aggs)],
        NUMERIC_KVS,
    )
    assert cpu.encode() == dev.encode()
    assert 0 < len(cpu.iter_rows()) < 100


def test_supports_does_not_leak_valueerror():
    assert not supports(
        DagRequest(
            executors=[
                TableScan(TABLE_ID, NUMERIC_COLS),
                Selection([call("no_such_fn", col(1))]),
            ]
        )
    )
    assert not supports(
        DagRequest(executors=[TableScan(TABLE_ID, NUMERIC_COLS), Selection([call("lt", col(1))])])
    )


def test_warm_cache_paths_identical():
    """All three warm-cache modes (simple, stable-dict coded, general gids)
    must match the CPU path byte-for-byte, and repeated cached runs agree."""
    from tikv_tpu.copr.cache import ColumnBlockCache

    cases = [
        # simple agg (no groups)
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([call("lt", col(1), const_int(500))]),
         Aggregation([], [AggDescriptor("count", None), AggDescriptor("sum", col(3))])],
        # general gids path (int group key is not dict-encoded)
        [TableScan(TABLE_ID, NUMERIC_COLS), Selection([call("lt", col(1), const_int(500))]),
         Aggregation([col(2)], [AggDescriptor("count", None), AggDescriptor("sum", col(3))])],
    ]
    for execs in cases:
        dag = DagRequest(executors=execs)
        cpu = BatchExecutorsRunner(dag, FixtureScanSource(NUMERIC_KVS)).handle_request()
        ev = JaxDagEvaluator(dag, block_rows=256)
        cache = ColumnBlockCache()
        first = ev.run(FixtureScanSource(NUMERIC_KVS), cache=cache)  # fills
        assert cache.filled
        warm1 = ev.run(None, cache=cache)
        warm2 = ev.run(None, cache=cache)
        assert first.encode() == cpu.encode()
        assert warm1.encode() == cpu.encode()
        assert warm2.encode() == cpu.encode()


def test_warm_cache_stable_dict_group():
    """Q1 shape through the on-device group-id (stable dictionary) path."""
    from tikv_tpu.copr.cache import ColumnBlockCache

    kvs = product_kvs([(i, [b"apple", b"banana", b"cherry"][i % 3], i % 7, i * 3) for i in range(1, 900)])
    aggs = [AggDescriptor("count", None), AggDescriptor("sum", col(2)), AggDescriptor("avg", col(3))]
    execs = [
        TableScan(TABLE_ID, PRODUCT_COLUMNS),
        Selection([call("gt", col(2), const_int(1))]),
        Aggregation([col(1)], aggs),
    ]
    dag = DagRequest(executors=execs)
    cpu = BatchExecutorsRunner(dag, FixtureScanSource(kvs)).handle_request()
    ev = JaxDagEvaluator(dag, block_rows=128)
    cache = ColumnBlockCache()
    ev.run(FixtureScanSource(kvs), cache=cache)
    warm = ev.run(None, cache=cache)
    assert warm.encode() == cpu.encode()
    # a second evaluator over the same cache also agrees (shared HBM arrays)
    ev2 = JaxDagEvaluator(dag, block_rows=128)
    assert ev2.run(None, cache=cache).encode() == cpu.encode()


def test_group_keys_with_trailing_nul_stay_distinct():
    """numpy 'S' arrays equate b'a' and b'a\\x00' — group keys must not."""
    from tikv_tpu.copr.groupby import GroupDict

    data = np.array([b"a", b"a\x00", b"a", b"b"], dtype=object)
    nulls = np.zeros(4, dtype=bool)
    gd = GroupDict()
    gids = gd.assign([(data, nulls)])
    assert len(gd) == 3
    assert gids[0] == gids[2] and gids[0] != gids[1]
    assert gd.rows[gids[1]][0] == b"a\x00"


def test_batch_respects_other_evaluators_null_masks():
    """A nullable column referenced only by a non-base evaluator must keep
    its null mask in the fused batch program."""
    from tikv_tpu.copr.cache import ColumnBlockCache
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType, NOT_NULL_FLAG
    from tikv_tpu.copr.jax_eval import run_batch_cached
    from tikv_tpu.copr.table import encode_row, record_key

    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),  # nullable
        ColumnInfo(3, FieldType.int64()),  # nullable
    ]
    kvs = [
        (record_key(7, i), encode_row(cols[1:], [None if i % 3 == 0 else i, i]))
        for i in range(300)
    ]
    # base evaluator references only column 2 (never null); column 1 (which
    # HAS nulls) is referenced only by the second evaluator — its null mask
    # must still ship in the fused program
    dag_a = DagRequest(executors=[TableScan(7, cols), Aggregation([], [AggDescriptor("sum", col(2))])])
    dag_b = DagRequest(executors=[TableScan(7, cols), Aggregation([], [AggDescriptor("count", col(1)), AggDescriptor("sum", col(1))])])
    ev_a = JaxDagEvaluator(dag_a, block_rows=64)
    ev_b = JaxDagEvaluator(dag_b, block_rows=64)
    cache = ColumnBlockCache()
    ev_a.run(FixtureScanSource(kvs), cache=cache)
    ra, rb = run_batch_cached([ev_a, ev_b], cache)
    cpu_a = BatchExecutorsRunner(dag_a, FixtureScanSource(kvs)).handle_request()
    cpu_b = BatchExecutorsRunner(dag_b, FixtureScanSource(kvs)).handle_request()
    assert ra.encode() == cpu_a.encode()
    assert rb.encode() == cpu_b.encode()


def test_limb_matmul_seg_sum_exact():
    """Int64 segment sums via f32 limb matmuls must be bit-exact for the
    full int64 range, including negatives and wraparound-prone magnitudes."""
    import jax.numpy as jnp
    import numpy as np

    from tikv_tpu.copr.jax_eval import _limb_matmul_seg_sum, _seg_sum

    rng = np.random.default_rng(7)
    n, cap = 1024, 1024
    gids = rng.integers(0, 777, size=n)
    vals = np.concatenate(
        [
            rng.integers(-(2**62), 2**62, size=n - 6),
            np.array([2**63 - 1, -(2**63), -1, 0, 10**18, -(10**18)]),
        ]
    ).astype(np.int64)
    expect = np.zeros(cap, dtype=np.int64)
    np.add.at(expect, gids, vals)
    got = np.asarray(_limb_matmul_seg_sum(jnp.asarray(vals), jnp.asarray(gids), cap))
    np.testing.assert_array_equal(got, expect)
    # the dispatcher routes 64 < C <= 4096 int sums through the matmul path
    got2 = np.asarray(_seg_sum(jnp.asarray(vals), jnp.asarray(gids), cap))
    np.testing.assert_array_equal(got2, expect)
    # larger blocks shrink the limb width but stay exact
    n2 = 8192
    gids2 = rng.integers(0, 100, size=n2)
    vals2 = rng.integers(-(2**62), 2**62, size=n2).astype(np.int64)
    expect2 = np.zeros(128, dtype=np.int64)
    np.add.at(expect2, gids2, vals2)
    got3 = np.asarray(_limb_matmul_seg_sum(jnp.asarray(vals2), jnp.asarray(gids2), 128))
    np.testing.assert_array_equal(got3, expect2)


def test_raw_topn_identical():
    """Device running top-K merge vs CPU BatchTopNExecutor — byte identity
    across asc/desc, multi-key, selection, ties, and K > matching rows."""
    for order_by, sel, k in [
        ([(col(1), False)], None, 10),  # asc int
        ([(col(1), True)], None, 10),  # desc int
        ([(col(3), False)], None, 25),  # asc decimal
        ([(col(2), False), (col(1), True)], None, 50),  # multi-key w/ ties
        ([(col(1), False)], call("lt", col(2), const_int(30)), 20),  # + filter
        ([(col(1), False)], call("lt", col(1), const_int(3)), 500),  # K > rows
        ([(call("mod", col(1), const_int(7)), False)], None, 40),  # expr key
    ]:
        execs = [TableScan(TABLE_ID, NUMERIC_COLS)]
        if sel is not None:
            execs.append(Selection([sel]))
        execs.append(TopN(order_by, k))
        cpu, dev = run_both(execs, NUMERIC_KVS, block_rows=256)
        assert cpu.encode() == dev.encode(), (order_by, sel, k)
        if sel is None:
            assert len(cpu.iter_rows()) == min(k, 5000)


def test_raw_topn_with_nulls_identical():
    """NULLs first ascending / last descending, matching the CPU comparator,
    with ties among NULLs resolved in stream order."""
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType, FieldTypeTp
    from tikv_tpu.copr.table import encode_row, record_key

    cols = [
        ColumnInfo(col_id=1, ftype=FieldType.int64(), is_pk_handle=True),
        ColumnInfo(col_id=2, ftype=FieldType(FieldTypeTp.LONGLONG)),
        ColumnInfo(col_id=3, ftype=FieldType(FieldTypeTp.DOUBLE)),
    ]
    rng = np.random.default_rng(11)
    kvs = []
    for h in range(300):
        iv = None if h % 7 == 0 else int(rng.integers(-50, 50))
        fv = None if h % 11 == 0 else float(rng.normal())
        kvs.append((record_key(TABLE_ID, h + 1), encode_row(cols[1:], [iv, fv])))
    for order_by in [
        [(col(1), False)],
        [(col(1), True)],
        [(col(2), False)],  # real key with nulls
        [(col(2), True)],
        [(col(1), False), (col(2), True)],
    ]:
        cpu, dev = run_both(
            [TableScan(TABLE_ID, cols), TopN(order_by, 37)], kvs, block_rows=64
        )
        assert cpu.encode() == dev.encode(), order_by


def test_raw_topn_extreme_values_identical():
    """±inf / huge int64 keys survive the monotone sort-key encoding."""
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType, FieldTypeTp
    from tikv_tpu.copr.table import encode_row, record_key

    cols = [
        ColumnInfo(col_id=1, ftype=FieldType.int64(), is_pk_handle=True),
        ColumnInfo(col_id=2, ftype=FieldType(FieldTypeTp.LONGLONG)),
        ColumnInfo(col_id=3, ftype=FieldType(FieldTypeTp.DOUBLE)),
    ]
    vals = [
        (2**63 - 1, float("inf")),
        (-(2**63), float("-inf")),
        (0, 0.0),
        (1, 1.5),
        (-1, -1.5),
        (2**62, 1e308),
        (-(2**62), -1e308),
    ]
    kvs = [
        (record_key(TABLE_ID, h + 1), encode_row(cols[1:], [iv, fv]))
        for h, (iv, fv) in enumerate(vals)
    ]
    for order_by in [[(col(1), False)], [(col(1), True)], [(col(2), False)], [(col(2), True)]]:
        cpu, dev = run_both([TableScan(TABLE_ID, cols), TopN(order_by, 5)], kvs, block_rows=4)
        assert cpu.encode() == dev.encode(), order_by


def test_endpoint_topn_stays_on_device_with_zero_fallbacks():
    """Eligible TopN/agg plans driven through Endpoint.handle_request must run
    on the device path — a silent permanent fallback (device_fallbacks > 0 or
    from_device=False) would still produce correct bytes, so only this
    assertion catches a broken device route (endpoint.rs:392 analog)."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.engine import WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    eng = BTreeEngine()
    wb = WriteBatch()
    for rk, val in NUMERIC_KVS[:500]:
        wb.put_cf("write", Key.from_raw(rk).append_ts(11).encoded,
                  Write(WriteType.PUT, 10, short_value=val).to_bytes())
    eng.write(wb)
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    ep_cpu = Endpoint(LocalEngine(eng), enable_device=False)
    plans = [
        [TableScan(TABLE_ID, NUMERIC_COLS), TopN([(col(1), True)], 7)],
        [TableScan(TABLE_ID, NUMERIC_COLS),
         Selection([call("lt", col(2), const_int(40))]),
         TopN([(col(2), False), (col(1), True)], 5)],
        [TableScan(TABLE_ID, NUMERIC_COLS),
         Aggregation([col(2)], [AggDescriptor("sum", col(1)), AggDescriptor("count", None)])],
    ]
    for execs in plans:
        req = lambda: CoprRequest(103, DagRequest(executors=execs), [record_range(TABLE_ID)], 100, context={})
        r_dev = ep.handle_request(req())
        r_cpu = ep_cpu.handle_request(req())
        assert r_dev.from_device, f"plan {execs} fell off the device path: {ep.last_device_error}"
        assert r_dev.data == r_cpu.data
    assert ep.device_fallbacks == 0, ep.last_device_error


def test_endpoint_falls_back_to_cpu_on_device_failure(monkeypatch):
    """A device-path runtime failure (tunnel, compiler, OOM) must re-run on
    the CPU oracle, not surface an accelerator error to the client."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.engine import WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType

    eng = BTreeEngine()
    wb = WriteBatch()
    for rk, val in NUMERIC_KVS[:50]:
        wb.put_cf("write", Key.from_raw(rk).append_ts(11).encoded,
                  Write(WriteType.PUT, 10, short_value=val).to_bytes())
    eng.write(wb)
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    dag = DagRequest(executors=[TableScan(TABLE_ID, NUMERIC_COLS), TopN([(col(1), False)], 5)])
    req = lambda: CoprRequest(103, DagRequest(executors=dag.executors), [record_range(TABLE_ID)], 100, context={})
    monkeypatch.setattr(
        JaxDagEvaluator, "run", lambda self, src, cache=None: (_ for _ in ()).throw(RuntimeError("tunnel down"))
    )
    r = ep.handle_request(req())
    assert not r.from_device
    assert len(r.data) > 0


def test_device_failure_does_not_poison_block_cache(monkeypatch):
    """A transient failure during cache fill must invalidate the partial
    cache — retrying used to double-append blocks and serve wrong data."""
    from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
    from tikv_tpu.copr.table import record_range
    from tikv_tpu.storage.btree_engine import BTreeEngine
    from tikv_tpu.storage.kv import LocalEngine
    from tikv_tpu.storage.engine import WriteBatch
    from tikv_tpu.storage.txn_types import Key, Write, WriteType
    from tikv_tpu.copr.aggr import AggDescriptor

    eng = BTreeEngine()
    wb = WriteBatch()
    for rk, val in NUMERIC_KVS[:500]:
        wb.put_cf("write", Key.from_raw(rk).append_ts(11).encoded,
                  Write(WriteType.PUT, 10, short_value=val).to_bytes())
    eng.write(wb)
    ep = Endpoint(LocalEngine(eng), enable_device=True)
    dag = DagRequest(executors=[
        TableScan(TABLE_ID, NUMERIC_COLS),
        Aggregation([], [AggDescriptor("count", None), AggDescriptor("sum", col(1))]),
    ])
    ctx = {"region_id": 1, "cache_version": 7}
    req = lambda: CoprRequest(103, DagRequest(executors=dag.executors), [record_range(TABLE_ID)], 100, context=ctx)
    # fail mid-fill: the evaluator dies after the cache got partial blocks
    orig_run = JaxDagEvaluator.run

    def failing_run(self, src, cache=None):
        if cache is not None:
            cache.add([None], 1)  # simulate partial fill before the fault
        raise RuntimeError("transient device fault")

    monkeypatch.setattr(JaxDagEvaluator, "run", failing_run)
    r1 = ep.handle_request(req())
    assert not r1.from_device
    assert ep.device_fallbacks == 1 and "transient" in ep.last_device_error
    monkeypatch.setattr(JaxDagEvaluator, "run", orig_run)
    r2 = ep.handle_request(req())  # refills the cache from scratch
    r3 = ep.handle_request(req())  # served from the (clean) cache
    cpu = Endpoint(LocalEngine(eng), enable_device=False).handle_request(req())
    assert r2.data == r3.data == cpu.data == r1.data


def test_float_sums_beyond_onehot_window():
    """REAL sums with hundreds of groups ride the blocked mask-reduce (not
    scatter) and match the CPU oracle within float rounding."""
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.table import encode_row, record_key

    rng = np.random.default_rng(3)
    n, n_groups = 4000, 500
    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.double()),
    ]
    g = rng.integers(0, n_groups, n)
    x = rng.normal(size=n) * 100
    kvs = [
        (record_key(TABLE_ID, i), encode_row(cols[1:], [int(g[i]), float(x[i])]))
        for i in range(n)
    ]
    aggs = [AggDescriptor("sum", col(2)), AggDescriptor("count", None)]
    cpu, dev = run_both(
        [TableScan(TABLE_ID, cols), Aggregation([col(1)], aggs)], kvs, block_rows=512
    )
    crows = sorted(cpu.iter_rows(), key=lambda r: r[-1])
    drows = sorted(dev.iter_rows(), key=lambda r: r[-1])
    assert len(crows) == n_groups == len(drows)
    for c, d in zip(crows, drows):
        assert c[-1] == d[-1] and c[1] == d[1]  # key + count exact
        assert c[0] == pytest.approx(d[0], rel=1e-9)


# ---------------------------------------------------------------------------
# Round-5 eligibility widening: first/bit_* aggregates, dict-coded varchar
# TopN payloads, index-scan leaves (VERDICT r4 item 6)
# ---------------------------------------------------------------------------


def test_first_and_bit_aggs_device():
    """first/bit_and/bit_or/bit_xor ride the device path and match CPU."""
    execs = [
        TableScan(TABLE_ID, NUMERIC_COLS),
        Selection([call("lt", col(1), const_int(800))]),
        Aggregation(
            group_by=[col(2)],
            agg_funcs=[
                AggDescriptor("first", col(1)),
                AggDescriptor("bit_and", col(1)),
                AggDescriptor("bit_or", col(1)),
                AggDescriptor("bit_xor", col(1)),
                AggDescriptor("count", None),
            ],
        ),
    ]
    assert supports(DagRequest(executors=execs))
    cpu, dev = run_both(execs, NUMERIC_KVS)
    assert dev.encode() == cpu.encode()


def test_first_bit_aggs_ungrouped_device():
    execs = [
        TableScan(TABLE_ID, NUMERIC_COLS),
        Aggregation(
            group_by=[],
            agg_funcs=[
                AggDescriptor("first", col(1)),
                AggDescriptor("bit_xor", col(2)),
                AggDescriptor("bit_and", col(2)),
            ],
        ),
    ]
    assert supports(DagRequest(executors=execs))
    cpu, dev = run_both(execs, NUMERIC_KVS)
    assert dev.encode() == cpu.encode()


def test_first_agg_with_nulls_device():
    """first skips NULLs (CPU semantics); all-NULL groups output NULL."""
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.table import encode_row, record_key

    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.int64()),
    ]
    rng = np.random.default_rng(3)
    kvs = []
    for i in range(4000):
        v = None if rng.random() < 0.3 else int(rng.integers(0, 50))
        g = int(rng.integers(0, 5))
        kvs.append((record_key(TABLE_ID, i), encode_row(cols[1:], [v, g])))
    execs = [
        TableScan(TABLE_ID, cols),
        Aggregation(group_by=[col(2)], agg_funcs=[AggDescriptor("first", col(1))]),
    ]
    cpu, dev = run_both(execs, kvs)
    assert dev.encode() == cpu.encode()


def test_topn_varchar_payload_device():
    """Dict-coded varchar payload columns ship as codes through the device
    top-K merge and decode back byte-identically."""
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.table import encode_row, record_key

    cols = [
        ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.varchar()),   # payload, never a sort key
        ColumnInfo(4, FieldType.int64()),
    ]
    tags = [b"aaaa", b"bbbb", b"cccc", b"dddd", b"eeee"]  # fixed-length rows
    rng = np.random.default_rng(5)
    kvs = []
    for i in range(5000):
        kvs.append((record_key(TABLE_ID, i), encode_row(cols[1:], [
            int(rng.integers(0, 10_000)), tags[int(rng.integers(0, 5))],
            int(rng.integers(-100, 100)),
        ])))
    execs = [
        TableScan(TABLE_ID, cols),
        Selection([call("lt", col(1), const_int(9000))]),
        TopN([(col(1), True), (col(3), False)], 40),
    ]
    assert supports(DagRequest(executors=execs))
    cpu, dev = run_both(execs, kvs)
    assert dev.encode() == cpu.encode()


def _index_fixture(n=6000, seed=9):
    """Two-column index (a, b) with handle; entries sorted in index order."""
    from tikv_tpu.copr import datum as datum_mod
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
    from tikv_tpu.copr.table import index_key
    from tikv_tpu.util import codec

    rng = np.random.default_rng(seed)
    a = rng.integers(0, 8, n)
    b = rng.integers(0, 10_000, n)
    cols = [
        ColumnInfo(1, FieldType.int64()),
        ColumnInfo(2, FieldType.int64()),
        ColumnInfo(3, FieldType.int64(), is_pk_handle=True),
    ]
    kvs = []
    for i in range(n):
        k = index_key(TABLE_ID, 7, [
            (datum_mod.INT_FLAG, int(a[i])), (datum_mod.INT_FLAG, int(b[i])),
        ]) + codec.encode_i64(i)  # unique suffix keeps keys distinct
        kvs.append((k, codec.encode_u64(i)))
    kvs.sort(key=lambda kv: kv[0])
    return cols, kvs


def test_index_scan_leaf_device():
    from tikv_tpu.copr.dag import IndexScan

    cols, kvs = _index_fixture()
    execs = [
        IndexScan(TABLE_ID, 7, cols),
        Selection([call("lt", col(1), const_int(9000))]),
        Aggregation(
            group_by=[col(0)],
            agg_funcs=[AggDescriptor("sum", col(1)), AggDescriptor("count", None)],
        ),
    ]
    assert supports(DagRequest(executors=execs))
    cpu, dev = run_both(execs, kvs, block_rows=512)
    assert dev.encode() == cpu.encode()


def test_index_scan_streamed_prefix_device():
    """Stream agg grouped on the index-column prefix: scan order sorts by it,
    so the device hash output equals the CPU stream executor's."""
    from tikv_tpu.copr.dag import IndexScan

    cols, kvs = _index_fixture()
    execs = [
        IndexScan(TABLE_ID, 7, cols),
        Aggregation(
            group_by=[col(0)],
            agg_funcs=[AggDescriptor("sum", col(1)), AggDescriptor("max", col(1))],
            streamed=True,
        ),
    ]
    assert supports(DagRequest(executors=execs))
    cpu, dev = run_both(execs, kvs, block_rows=512)
    assert dev.encode() == cpu.encode()


def test_index_scan_bytes_column_stays_cpu():
    from tikv_tpu.copr.dag import IndexScan
    from tikv_tpu.copr.datatypes import ColumnInfo, FieldType

    cols = [
        ColumnInfo(1, FieldType.varchar()),
        ColumnInfo(2, FieldType.int64(), is_pk_handle=True),
    ]
    dag = DagRequest(executors=[
        IndexScan(TABLE_ID, 7, cols),
        Aggregation(group_by=[], agg_funcs=[AggDescriptor("count", None)]),
    ])
    assert not supports(dag)
