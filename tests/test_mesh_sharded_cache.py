"""Mesh-sharded warm serving (ISSUE 3): the region column cache spread over
a simulated 8-device CPU mesh must serve cross-region batches as ONE
shard_map program, byte-identical to the single-device scheduler path and
the per-request CPU pipeline — through uneven region→device assignment,
fewer regions than devices, block-spread huge regions, and mid-batch
eviction of a sharded image."""

import numpy as np
import pytest

import jax

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import Aggregation, DagRequest, Selection, TableScan
from tikv_tpu.copr.datatypes import ColumnInfo, FieldType
from tikv_tpu.copr.endpoint import CoprRequest, Endpoint
from tikv_tpu.copr.region_cache import RegionColumnCache, notify_region_epoch_change
from tikv_tpu.copr.rpn import call, col, const_int
from tikv_tpu.copr.table import encode_row, record_key
from tikv_tpu.parallel.mesh import make_mesh
from tikv_tpu.storage.btree_engine import BTreeEngine
from tikv_tpu.storage.engine import CF_WRITE
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.txn_types import Key, Write, WriteType
from tikv_tpu.util.metrics import REGISTRY

TABLE_ID = 88

COLS = [
    ColumnInfo(1, FieldType.int64(), is_pk_handle=True),
    ColumnInfo(2, FieldType.int64()),
    ColumnInfo(3, FieldType.varchar()),
    ColumnInfo(4, FieldType.decimal_type(2)),
]

ROWS_PER = 500


def _engine(n: int, seed: int = 3) -> BTreeEngine:
    rng = np.random.default_rng(seed)
    a = rng.integers(0, 100, n)
    price = rng.integers(100, 100000, n)
    names = (b"x", b"y", b"z")
    eng = BTreeEngine()
    items = []
    for i in range(n):
        rk = record_key(TABLE_ID, i)
        val = encode_row(COLS[1:], [int(a[i]), names[i % 3], int(price[i])])
        items.append((Key.from_raw(rk).append_ts(20).encoded,
                      Write(WriteType.PUT, 10, short_value=val).to_bytes()))
    eng.bulk_load(CF_WRITE, items)
    return eng


def _sum_dag(cut: int) -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Selection([call("lt", col(1), const_int(cut))]),
        Aggregation([], [AggDescriptor("sum", col(3)),
                         AggDescriptor("count", None),
                         AggDescriptor("max", col(1))]),
    ])


def _group_dag() -> DagRequest:
    return DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Aggregation([col(2)], [AggDescriptor("sum", col(1)),
                               AggDescriptor("count", None)]),
    ])


def _req(region: int, dag: DagRequest, rows_per: int = ROWS_PER,
         apply_index: int = 7) -> CoprRequest:
    lo = record_key(TABLE_ID, region * rows_per)
    hi = record_key(TABLE_ID, (region + 1) * rows_per)
    return CoprRequest(103, dag, [(lo, hi)], 100,
                       context={"region_id": region + 1,
                                "region_epoch": (1, 1),
                                "apply_index": apply_index})


N_REGIONS = 5  # deliberately fewer than the 8 conftest devices AND not a divisor


@pytest.fixture(scope="module")
def endpoints():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    eng = _engine(ROWS_PER * max(N_REGIONS, 10))
    mesh = make_mesh(groups=2)
    sharded = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256,
                       mesh=mesh)
    single = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256)
    cpu = Endpoint(LocalEngine(eng), enable_device=False)
    return sharded, single, cpu


def _sweep(dags, n_regions=N_REGIONS):
    return [_req(r, d()) for d in dags for r in range(n_regions)]


def test_sharded_batch_byte_identical_uneven_assignment(endpoints):
    """5 regions over 8 devices (uneven, region count < device count): the
    batch runs the SHARDED program and responses are byte-identical to both
    the single-device scheduler path and the per-request CPU pipeline."""
    sharded, single, cpu = endpoints
    dags = [lambda: _sum_dag(60), lambda: _sum_dag(90), _group_dag]
    sharded.handle_batch(_sweep(dags))  # warm: fill + compile
    single.handle_batch(_sweep(dags))
    before = REGISTRY.counter(
        "tikv_coprocessor_sched_batches_total", "").get(kind="xregion_sharded")
    got = sharded.handle_batch(_sweep(dags))
    after = REGISTRY.counter(
        "tikv_coprocessor_sched_batches_total", "").get(kind="xregion_sharded")
    assert after >= before + 3, "one sharded batch per plan signature"
    ref = single.handle_batch(_sweep(dags))
    assert all(g.from_device for g in got)
    for q, g, s in zip(_sweep(dags), got, ref):
        want = cpu.handle_request(
            CoprRequest(103, q.dag, q.ranges, q.start_ts, dict(q.context)))
        assert g.data == s.data == want.data
    # placement metadata: images actually spread over more than one device
    used = [b for b in sharded.region_cache.placement().values() if b > 0]
    assert len(used) >= min(N_REGIONS, 2)


def test_sharded_batch_more_regions_than_devices(endpoints):
    """10 regions on 8 devices: some devices own two slabs-worth of regions;
    results still match the oracle byte-for-byte."""
    sharded, _single, cpu = endpoints
    reqs = [_req(r, _sum_dag(75)) for r in range(10)]
    sharded.handle_batch([_req(r, _sum_dag(75)) for r in range(10)])  # warm
    got = sharded.handle_batch(reqs)
    for q, g in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(103, q.dag, q.ranges, q.start_ts, dict(q.context)))
        assert g.data == want.data
    assert all(g.from_device for g in got)


def test_mid_batch_eviction_of_sharded_image(endpoints):
    """An invalidation between batches (raft epoch change on a sharded
    image) must not poison serving: the invalidated region rebuilds (cold
    fill) while the others keep their shards; bytes stay identical."""
    sharded, _single, cpu = endpoints
    dags = [lambda: _sum_dag(60)]
    sharded.handle_batch(_sweep(dags))  # ensure warm
    notify_region_epoch_change(3, reason="split")  # region_id 3 == region 2
    got = sharded.handle_batch(_sweep(dags))
    for q, g in zip(_sweep(dags), got):
        want = cpu.handle_request(
            CoprRequest(103, q.dag, q.ranges, q.start_ts, dict(q.context)))
        assert g.data == want.data
    # and the dropped image's bytes left the placement ledger (no leak)
    total_placed = sum(sharded.region_cache.placement().values())
    assert total_placed <= sharded.region_cache.total_bytes() + 1


def test_unary_warm_request_rides_mesh(endpoints):
    """A warm unary aggregation request serves through the sharded launcher
    (mesh_cache_hit) — the PR-2 cache→mesh bypass is gone."""
    sharded, _single, cpu = endpoints
    q = _req(1, _sum_dag(60))
    sharded.handle_request(_req(1, _sum_dag(60)))  # warm
    before = REGISTRY.counter("tikv_coprocessor_mesh_cache_hit_total", "").get()
    r = sharded.handle_request(q)
    after = REGISTRY.counter("tikv_coprocessor_mesh_cache_hit_total", "").get()
    assert r.from_device and r.from_cache
    assert after == before + 1
    assert r.data == cpu.handle_request(_req(1, _sum_dag(60))).data


def test_huge_region_block_spread():
    """A single region bigger than the per-device budget block-spreads over
    the mesh; the sharded program merges per-device partials with the
    collective rules and the answer matches the CPU pipeline."""
    eng = _engine(4000, seed=9)
    mesh = make_mesh(groups=2)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256,
                  mesh=mesh)
    # force "huge": a tiny per-device budget makes any image block-spread
    ep.region_cache = RegionColumnCache(block_rows=256, mesh=mesh,
                                        per_device_budget=1)
    cpu = Endpoint(LocalEngine(eng), enable_device=False)
    q = lambda: _req(0, _sum_dag(2000), rows_per=4000)
    ep.handle_request(q())  # fill (miss)
    img = next(iter(ep.region_cache._images.values()))
    owners = img.block_cache.owner_devices
    assert owners is not None and len(set(owners)) > 1, \
        "huge region must spread its blocks over several devices"
    r = ep.handle_request(q())
    assert r.from_device and r.from_cache
    assert r.data == cpu.handle_request(q()).data


def test_rebalance_after_eviction():
    """Evicting/invalidating images rebalances placement: the device-load
    spread shrinks and the ledger matches resident bytes."""
    eng = _engine(ROWS_PER * 6, seed=4)
    mesh = make_mesh(groups=1)
    ep = Endpoint(LocalEngine(eng), enable_device=True, block_rows=256,
                  mesh=mesh)
    for r in range(6):
        ep.handle_request(_req(r, _sum_dag(60)))
    rc = ep.region_cache
    assert sum(rc.placement().values()) == rc.total_bytes()
    for rid in (1, 2):
        rc.invalidate_region(rid)
    assert sum(rc.placement().values()) == rc.total_bytes()
    loads = list(rc.placement().values())
    resident = [i.nbytes for i in rc._images.values()]
    if resident:
        # no device holds more than the max image above the mean — the
        # rebalance moved what it could
        spread = max(loads) - min(loads)
        assert spread <= max(resident), (loads, resident)


def test_sharded_responses_match_with_first_agg_fallback(endpoints):
    """A batch whose plan has no mesh merge rule (`first`) falls back off
    the sharded program but still answers correctly."""
    sharded, _single, cpu = endpoints
    first_dag = lambda: DagRequest(executors=[
        TableScan(TABLE_ID, COLS),
        Aggregation([], [AggDescriptor("first", col(1)),
                         AggDescriptor("count", None)]),
    ])
    reqs = [_req(r, first_dag()) for r in range(N_REGIONS)]
    sharded.handle_batch([_req(r, first_dag()) for r in range(N_REGIONS)])
    got = sharded.handle_batch(reqs)
    for q, g in zip(reqs, got):
        want = cpu.handle_request(
            CoprRequest(103, q.dag, q.ranges, q.start_ts, dict(q.context)))
        assert g.data == want.data
