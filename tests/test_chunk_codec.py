"""chunk_codec round-trip property tests (ISSUE 14 satellite).

The codec predates any direct coverage: every FieldTypeTp (including
FLOAT's 4-byte cells and the 40-byte decimal struct), null bitmaps at
rows % 8 ∈ {0..7}, empty and all-null columns, var-len offset
monotonicity — for BOTH builders (the append-oriented ChunkColumn and the
vectorized ``encode_np_column`` the serving plane uses), which must emit
identical bytes.
"""

from __future__ import annotations

import random
import struct

import numpy as np
import pytest

from tikv_tpu.copr import chunk_codec as cc
from tikv_tpu.copr.chunk_codec import (
    DECIMAL_STRUCT_SIZE,
    ChunkColumn,
    column_values,
    decode_chunk,
    decode_column,
    encode_chunk,
    encode_np_column,
)
from tikv_tpu.copr.datatypes import EvalType, FieldType, FieldTypeTp, UNSIGNED_FLAG


def _rand_value(rng: random.Random, ft: FieldType):
    et = ft.eval_type
    if et == EvalType.INT:
        if ft.is_unsigned:
            return rng.randrange(0, 2**64)
        return rng.randrange(-2**63, 2**63)
    if et == EvalType.REAL:
        v = rng.uniform(-1e9, 1e9)
        return struct.unpack("<f", struct.pack("<f", v))[0] if cc.fixed_len(ft) == 4 else v
    if et == EvalType.DECIMAL:
        return (rng.randrange(-10**17, 10**17), ft.decimal)
    if et == EvalType.DATETIME:
        return rng.randrange(0, 2**62)
    if et == EvalType.DURATION:
        return rng.randrange(-10**12, 10**12)
    if et == EvalType.ENUM:
        return rng.randrange(1, len(ft.elems) + 1)
    # BYTES / JSON / SET payloads ride raw
    return bytes(rng.randrange(256) for _ in range(rng.randrange(0, 24)))


_ALL_TPS = [
    FieldType(FieldTypeTp.TINY),
    FieldType(FieldTypeTp.SHORT),
    FieldType(FieldTypeTp.INT24),
    FieldType(FieldTypeTp.LONG),
    FieldType(FieldTypeTp.LONGLONG),
    FieldType(FieldTypeTp.LONGLONG, UNSIGNED_FLAG),
    FieldType(FieldTypeTp.FLOAT),
    FieldType(FieldTypeTp.DOUBLE),
    FieldType(FieldTypeTp.NEW_DECIMAL, decimal=2),
    FieldType(FieldTypeTp.NEW_DECIMAL, decimal=0),
    FieldType(FieldTypeTp.NEW_DECIMAL, decimal=11),
    FieldType(FieldTypeTp.DATE),
    FieldType(FieldTypeTp.DATETIME),
    FieldType(FieldTypeTp.TIMESTAMP),
    FieldType(FieldTypeTp.DURATION),
    FieldType(FieldTypeTp.BLOB),
    FieldType(FieldTypeTp.VAR_STRING),
    FieldType(FieldTypeTp.STRING),
    FieldType(FieldTypeTp.JSON),
    FieldType.enum_type([b"a", b"bb", b"ccc"]),
    FieldType(FieldTypeTp.SET, elems=(b"x", b"y")),
]


@pytest.mark.parametrize("ft", _ALL_TPS, ids=lambda ft: f"{ft.tp.name}{'u' if ft.is_unsigned else ''}d{ft.decimal}")
@pytest.mark.parametrize("n", [0, 1, 5, 7, 8, 9, 15, 16, 17, 100])
def test_roundtrip_every_field_type(ft, n):
    """Append n values (null density ~1/3), encode, decode, compare —
    covering every rows%8 bitmap remainder, empty, and var-len offsets."""
    rng = random.Random(n * 1000 + int(ft.tp))
    col = ChunkColumn(ft)
    want = []
    for _ in range(n):
        if rng.random() < 0.33:
            col.append_null()
            want.append(None)
        else:
            v = _rand_value(rng, ft)
            col.append(v)
            want.append(v)
    blob = col.encode()
    out, pos = decode_column(blob, 0, ft)
    assert pos == len(blob)
    got = column_values(out)
    for w, g in zip(want, got):
        if w is None:
            assert g is None
        elif ft.eval_type == EvalType.REAL:
            assert g == pytest.approx(w)
        elif ft.eval_type == EvalType.ENUM:
            assert g == w  # chunk enum decodes the u64 index
        elif ft.eval_type in (EvalType.BYTES, EvalType.JSON) or ft.tp == FieldTypeTp.SET:
            assert bytes(g) == bytes(w)
        else:
            assert g == w
    # var-len offsets are monotone and end at the data length
    if not col.fixed:
        assert out.offsets[0] == 0
        assert all(a <= b for a, b in zip(out.offsets, out.offsets[1:]))
        assert out.offsets[-1] == len(out.data)


def test_all_null_column_roundtrip():
    ft = FieldType(FieldTypeTp.LONGLONG)
    col = ChunkColumn(ft)
    for _ in range(11):
        col.append_null()
    out, _ = decode_column(col.encode(), 0, ft)
    assert column_values(out) == [None] * 11
    assert out.null_cnt == 11


def test_no_null_column_omits_bitmap():
    ft = FieldType(FieldTypeTp.LONGLONG)
    col = ChunkColumn(ft)
    for i in range(9):
        col.append(i)
    blob = col.encode()
    # header + 9 * 8 cell bytes, NO bitmap when null_cnt == 0
    assert len(blob) == 8 + 9 * 8
    out, _ = decode_column(blob, 0, ft)
    assert column_values(out) == list(range(9))


@pytest.mark.parametrize("ft", [
    FieldType(FieldTypeTp.LONGLONG),
    FieldType(FieldTypeTp.DOUBLE),
    FieldType(FieldTypeTp.DURATION),
    FieldType(FieldTypeTp.DATETIME),
    FieldType(FieldTypeTp.NEW_DECIMAL, decimal=2),
    FieldType(FieldTypeTp.NEW_DECIMAL, decimal=13),
    FieldType(FieldTypeTp.VAR_STRING),
    FieldType(FieldTypeTp.JSON),
], ids=lambda ft: f"{ft.tp.name}d{ft.decimal}")
@pytest.mark.parametrize("n", [0, 1, 7, 8, 9, 64, 257])
def test_vectorized_encode_byte_identical(ft, n):
    """encode_np_column (the serving-plane encoder) emits the EXACT bytes
    the append builder does for the same logical values."""
    rng = np.random.default_rng(n + int(ft.tp))
    et = ft.eval_type
    nulls = rng.random(n) < 0.3
    if et == EvalType.INT:
        data = rng.integers(-2**62, 2**62, n)
    elif et == EvalType.REAL:
        data = rng.standard_normal(n)
    elif et == EvalType.DECIMAL:
        data = rng.integers(-10**17, 10**17, n)
    elif et in (EvalType.DATETIME,):
        data = rng.integers(0, 2**62, n)
    elif et == EvalType.DURATION:
        data = rng.integers(-10**12, 10**12, n)
    else:
        data = np.empty(n, object)
        for i in range(n):
            data[i] = bytes(rng.integers(0, 255, rng.integers(0, 20)).astype(np.uint8))
    col = ChunkColumn(ft)
    for i in range(n):
        if nulls[i]:
            col.append_null()
        elif et == EvalType.DECIMAL:
            col.append((int(data[i]), ft.decimal))
        elif et == EvalType.REAL:
            col.append(float(data[i]))
        elif et in (EvalType.BYTES, EvalType.JSON):
            col.append(data[i])
        else:
            col.append(int(data[i]))
    assert encode_np_column(ft, data, nulls) == col.encode()


def test_vectorized_encode_dictionary_column():
    """Dictionary-coded BYTES columns encode through the dictionary — the
    same bytes a decoded (materialized) column produces."""
    ft = FieldType(FieldTypeTp.VAR_STRING)
    d = np.array([b"apple", b"banana", b"cherry"], dtype=object)
    codes = np.array([0, 2, 1, 1, 0], dtype=np.int64)
    nulls = np.array([False, False, True, False, False])
    want = encode_np_column(ft, d[codes], nulls)
    assert encode_np_column(ft, codes, nulls, dictionary=d) == want


def test_decimal_cells_vectorized_identity_and_roundtrip():
    rng = np.random.default_rng(7)
    for frac in range(0, cc.MAX_VEC_DECIMAL_FRAC + 1):
        vals = np.concatenate([
            rng.integers(-10**18, 10**18, 100),
            np.array([0, 1, -1, 9, 10**17, -(2**63), 2**63 - 1], np.int64),
        ]).astype(np.int64)
        cells = cc.encode_decimal_cells(vals, frac)
        for i, v in enumerate(vals):
            assert cells[i].tobytes() == cc.encode_decimal_cell(int(v), frac)
        assert np.array_equal(cc.decode_decimal_cells(cells, frac), vals)
    with pytest.raises(ValueError):
        cc.encode_decimal_cells(np.zeros(1, np.int64), cc.MAX_VEC_DECIMAL_FRAC + 1)


def test_column_numpy_matches_column_values():
    rng = np.random.default_rng(3)
    n = 41
    for ft, data in [
        (FieldType(FieldTypeTp.LONGLONG), rng.integers(-2**62, 2**62, n)),
        (FieldType(FieldTypeTp.DOUBLE), rng.standard_normal(n)),
        (FieldType(FieldTypeTp.NEW_DECIMAL, decimal=4), rng.integers(-10**15, 10**15, n)),
        (FieldType(FieldTypeTp.DATETIME), rng.integers(0, 2**62, n)),
    ]:
        nulls = rng.random(n) < 0.25
        col, _ = decode_column(encode_np_column(ft, data, nulls), 0, ft)
        vec, vn = cc.column_numpy(col)
        assert np.array_equal(vn, nulls)
        scalar = column_values(col)
        for i in range(n):
            if nulls[i]:
                assert scalar[i] is None
            elif ft.eval_type == EvalType.DECIMAL:
                assert scalar[i] == (int(vec[i]), ft.decimal)
            else:
                assert scalar[i] == pytest.approx(vec[i])


def test_multi_column_chunk_roundtrip_and_truncation_guards():
    fts = [FieldType(FieldTypeTp.LONGLONG), FieldType(FieldTypeTp.VAR_STRING)]
    cols = []
    for ft in fts:
        c = ChunkColumn(ft)
        for i in range(5):
            c.append(i if ft.eval_type == EvalType.INT else b"v%d" % i)
        cols.append(c)
    blob = encode_chunk(cols)
    back = decode_chunk(blob, fts)
    assert [column_values(c) for c in back] == [column_values(c) for c in cols]
    with pytest.raises(ValueError):
        decode_chunk(blob + b"\x00", fts)  # trailing bytes
    with pytest.raises(ValueError):
        decode_chunk(blob[:-1], fts)  # truncated cell data
