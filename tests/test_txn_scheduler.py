"""Group-commit write path (ISSUE 4 tentpole) + latch wake-up chains.

Contracts under test:

* queued compatible prewrite/commit commands coalesce into ONE engine write
  (the raft proposal the group amortizes), with results and persisted state
  byte-identical to per-command execution
* per-command errors inside a group fail only their own task
* releasing a group-executed batch wakes every parked conflicting command —
  FIFO per latch slot, no lost wake-ups, including overlapping multi-slot
  commands
* ``tikv_scheduler_too_busy_total`` / ``tikv_scheduler_group_size`` are real
  REGISTRY metrics (satellite: SchedTooBusy used to bump only a stats dict)
"""

from __future__ import annotations

import threading
import time

import pytest

from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.mvcc.txn import TxnLockNotFoundError
from tikv_tpu.storage.storage import Storage
from tikv_tpu.storage.txn.commands import Commit, Prewrite
from tikv_tpu.storage.txn.latches import Latches
from tikv_tpu.storage.txn.scheduler import Scheduler, SchedTooBusy
from tikv_tpu.storage.txn_types import Key, Mutation
from tikv_tpu.util.metrics import REGISTRY


class CountingEngine(LocalEngine):
    """LocalEngine that counts write() calls — each one stands in for a raft
    propose→apply→ack round trip."""

    def __init__(self):
        super().__init__()
        self.write_calls = 0

    def write(self, ctx, batch):
        self.write_calls += 1
        return super().write(ctx, batch)


class _Blocker:
    """Non-groupable command that parks the (single) worker until released,
    letting the test queue a deterministic backlog behind it.  ``started``
    fires once the worker is actually inside process_write — tests MUST
    wait on it before queueing (a sleep-based guess is flaky on a loaded
    box and splits the group)."""

    exclusive = False
    groupable = False

    def __init__(self, key=b"__blocker__"):
        self.key = key
        self.started = threading.Event()
        self.release = threading.Event()

    def latch_keys(self):
        return [self.key]

    def process_write(self, snapshot):
        from tikv_tpu.storage.mvcc.txn import MvccTxn

        self.started.set()
        self.release.wait(10)
        return MvccTxn(1), None


def _prewrite(i, ts, key=None):
    key = key if key is not None else b"k%03d" % i
    return Prewrite([Mutation.put(Key.from_raw(key), b"v%d" % ts)], key, start_ts=ts)


def _slot_distinct_keys(sched, n, prefix=b"k"):
    """Keys whose ENCODED forms hash to n distinct latch slots — commands
    latch ``Key.encoded``, and a slot collision would PARK the later command
    (correct, but it splits the group and breaks exact engine-write-count
    assertions; key hashing is seed-dependent)."""
    keys, used = [], set()
    i = 0
    while len(keys) < n:
        k = prefix + b"%04d" % i
        i += 1
        s = sched.latches.slot_ids([Key.from_raw(k).encoded])[0]
        if s not in used:
            used.add(s)
            keys.append(k)
    return keys


def _commit(i, start, commit, key=None):
    key = key if key is not None else b"k%03d" % i
    return Commit([Key.from_raw(key)], start, commit)


def test_group_commit_one_engine_write_for_queued_prewrites():
    eng = CountingEngine()
    sched = Scheduler(eng, pool_size=1, group_commit_max=32)
    keys = _slot_distinct_keys(sched, 8)
    blocker = _Blocker()
    tb = sched.submit(blocker)
    assert blocker.started.wait(10)  # worker parked on the blocker
    tasks = [sched.submit(_prewrite(i, ts=10, key=k)) for i, k in enumerate(keys)]
    before = eng.write_calls
    blocker.release.set()
    for t in tasks:
        assert t.done.wait(10)
        assert t.exc is None, t.exc
    assert tb.done.wait(10)
    # 8 prewrites, one grouped engine write
    assert eng.write_calls - before == 1, eng.write_calls - before
    # all 8 locks are really in the engine: commits succeed
    blocker2 = _Blocker(b"__blocker2__")
    tb2 = sched.submit(blocker2)
    assert blocker2.started.wait(10)
    commits = [sched.submit(_commit(i, 10, 20, key=k)) for i, k in enumerate(keys)]
    before = eng.write_calls
    blocker2.release.set()
    for t in commits:
        assert t.done.wait(10)
        assert t.exc is None, t.exc
    assert tb2.done.wait(10)
    assert eng.write_calls - before == 1
    sched.stop()
    # committed values readable through the normal MVCC read path
    storage = Storage(engine=eng)
    for k in keys:
        assert storage.get(k, 30) == b"v10"


def test_group_commit_results_identical_to_per_command():
    """Same workload through a grouping and a non-grouping scheduler must
    leave byte-identical engine state."""

    def run(group_max):
        eng = CountingEngine()
        sched = Scheduler(eng, pool_size=1, group_commit_max=group_max)
        blocker = _Blocker()
        sched.submit(blocker)
        assert blocker.started.wait(10)
        tasks = [sched.submit(_prewrite(i, ts=5)) for i in range(6)]
        tasks += [sched.submit(_prewrite(i, ts=5, key=b"x%d" % i)) for i in range(3)]
        blocker.release.set()
        for t in tasks:
            assert t.done.wait(10) and t.exc is None
        c = [sched.submit(_commit(i, 5, 9)) for i in range(6)]
        for t in c:
            assert t.done.wait(10) and t.exc is None
        sched.stop()
        snap = eng.snapshot(None)
        state = []
        for cf in ("default", "lock", "write"):
            state.extend((cf, k, v) for k, v in snap.scan_cf(cf, b"", b"\xff" * 20))
        return state, eng.write_calls

    grouped, grouped_writes = run(32)
    solo, solo_writes = run(1)
    assert grouped == solo
    assert grouped_writes < solo_writes


def test_group_member_error_does_not_poison_the_group():
    """A commit with no lock (TxnLockNotFoundError) grouped with healthy
    commands fails alone; the rest land."""
    eng = CountingEngine()
    sched = Scheduler(eng, pool_size=1, group_commit_max=32)
    storage = Storage(engine=eng)
    # prewrite k0..k3 the normal way
    for i in range(4):
        t = sched.submit(_prewrite(i, ts=7))
        assert t.done.wait(10) and t.exc is None
    blocker = _Blocker()
    sched.submit(blocker)
    assert blocker.started.wait(10)
    good = [sched.submit(_commit(i, 7, 11)) for i in range(4)]
    bad = sched.submit(Commit([Key.from_raw(b"never-prewritten")], 7, 11))
    blocker.release.set()
    for t in good:
        assert t.done.wait(10)
        assert t.exc is None, t.exc
    assert bad.done.wait(10)
    assert isinstance(bad.exc, TxnLockNotFoundError)
    sched.stop()
    for i in range(4):
        assert storage.get(b"k%03d" % i, 20) == b"v7"


def test_group_release_wakes_parked_commands_no_lost_wakeups():
    """Commands parked behind group members must all wake when the group's
    batch releases — and land their writes (a lost wake-up would hang the
    done.wait below)."""
    eng = CountingEngine()
    sched = Scheduler(eng, pool_size=2, group_commit_max=32)
    blocker = _Blocker()
    sched.submit(blocker)
    assert blocker.started.wait(10)
    first = [sched.submit(_prewrite(i, ts=3)) for i in range(6)]
    # conflicting second wave: same keys -> parked in the latch queues
    second = [sched.submit(Commit([Key.from_raw(b"k%03d" % i)], 3, 4))
              for i in range(6)]
    blocker.release.set()
    for t in first + second:
        assert t.done.wait(10), "lost wake-up: task never ran"
        assert t.exc is None, t.exc
    sched.stop()
    storage = Storage(engine=eng)
    for i in range(6):
        assert storage.get(b"k%03d" % i, 9) == b"v3"


def test_latch_fifo_across_overlapping_multislot_commands():
    """Chained multi-slot commands A(k1,k2), B(k2,k3), C(k3,k4): releases
    must wake exactly the next-in-line once it holds EVERY slot — FIFO per
    slot, no premature or duplicate wake-ups."""
    lat = Latches(64)
    ca, cb, cc = lat.gen_cid(), lat.gen_cid(), lat.gen_cid()
    ga, sa = lat.acquire(ca, [b"k1", b"k2"], payload="A")
    gb, sb = lat.acquire(cb, [b"k2", b"k3"], payload="B")
    gc_, sc = lat.acquire(cc, [b"k3", b"k4"], payload="C")
    assert ga and not gb
    # C holds k4 and the k3 front (B queued behind nothing on k3? no: B
    # enqueued on k3 first) — C is behind B on k3, so C is parked too
    assert not gc_
    assert lat.release(ca, sa) == ["B"]  # exactly B, exactly once
    assert lat.release(cb, sb) == ["C"]
    assert lat.release(cc, sc) == []


def test_latch_fifo_interleaved_under_group_execution():
    """Heavy interleaving through the real scheduler: per-key commit order
    must equal submission order even when group commit batches writers."""
    eng = CountingEngine()
    sched = Scheduler(eng, pool_size=3, group_commit_max=8)
    storage = Storage(engine=eng)
    N = 12
    blocker = _Blocker()
    sched.submit(blocker)
    assert blocker.started.wait(10)
    tasks = []
    for ts in range(1, N + 1):
        # every command touches the shared hot key + a private key
        key = b"hot"
        m = [Mutation.put(Key.from_raw(key), b"w%03d" % ts),
             Mutation.put(Key.from_raw(b"p%03d" % ts), b"x")]
        tasks.append(sched.submit(Prewrite(m, key, start_ts=ts)))
    blocker.release.set()
    done = [t.done.wait(10) for t in tasks]
    assert all(done)
    # first prewrite wins the hot key; the rest see its lock (FIFO means
    # exactly the submission-order head succeeded)
    oks = [t for t in tasks if not t.result.get("errors")]
    assert tasks[0] in oks
    for t in tasks[1:]:
        errs = t.result.get("errors") or []
        assert errs, "later prewrite must have collided with the first lock"
    sched.stop()
    assert storage.scan_lock(None, None, 1 << 60)


def test_too_busy_and_group_size_are_registry_metrics():
    busy_before = REGISTRY.counter("tikv_scheduler_too_busy_total", "").get()
    g_before = REGISTRY.histogram("tikv_scheduler_group_size", "").count()
    eng = CountingEngine()
    sched = Scheduler(eng, pool_size=1, pending_write_threshold=2,
                      group_commit_max=32)
    blocker = _Blocker()
    sched.submit(blocker)
    assert blocker.started.wait(10)
    t1 = sched.submit(_prewrite(0, ts=2))
    with pytest.raises(SchedTooBusy):
        sched.submit(_prewrite(1, ts=2))
    assert REGISTRY.counter(
        "tikv_scheduler_too_busy_total", "").get() == busy_before + 1
    blocker.release.set()
    assert t1.done.wait(10)
    sched.stop()
    assert REGISTRY.histogram("tikv_scheduler_group_size", "").count() > g_before


def test_group_commit_disabled_is_per_command():
    eng = CountingEngine()
    sched = Scheduler(eng, pool_size=1, group_commit_max=1)
    keys = _slot_distinct_keys(sched, 5)
    blocker = _Blocker()
    sched.submit(blocker)
    assert blocker.started.wait(10)
    tasks = [sched.submit(_prewrite(i, ts=4, key=k)) for i, k in enumerate(keys)]
    before = eng.write_calls
    blocker.release.set()
    for t in tasks:
        assert t.done.wait(10) and t.exc is None
    assert eng.write_calls - before == 5
    sched.stop()
