"""Coprocessor executor-pipeline tests.

Mirrors the reference's tests/integrations/coprocessor/test_select.rs coverage
(select, selection, aggregation, topN, limit) over both the fixture leaf and a
real MVCC snapshot leaf.
"""

import numpy as np
import pytest

from tikv_tpu.copr.aggr import AggDescriptor
from tikv_tpu.copr.dag import (
    Aggregation,
    BatchExecutorsRunner,
    DagRequest,
    Limit,
    Selection,
    TableScan,
    TopN,
    check_supported,
)
from tikv_tpu.copr.executors import FixtureScanSource, MvccScanSource
from tikv_tpu.copr.datatypes import EvalType
from tikv_tpu.copr.rpn import call, col, const_decimal, const_int
from tikv_tpu.copr.table import record_range

from copr_fixtures import PRODUCT_COLUMNS, PRODUCT_ROWS, TABLE_ID, product_engine, product_kvs


def run_dag(executors, source=None, output_offsets=None):
    dag = DagRequest(executors=executors, output_offsets=output_offsets)
    if source is None:
        source = FixtureScanSource(product_kvs())
    return BatchExecutorsRunner(dag, source).handle_request()


def test_full_table_scan():
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    rows = resp.iter_rows()
    assert len(rows) == len(PRODUCT_ROWS)
    assert rows[0] == [1, b"apple", 10, (150, 2)]
    assert rows[3][1] is None
    assert rows[5][3] is None


def test_table_scan_output_offsets():
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS)], output_offsets=[2, 0])
    rows = resp.iter_rows()
    assert rows[0] == [10, 1]


def test_mvcc_leaf_matches_fixture():
    eng = product_engine()
    start, end = record_range(TABLE_ID)
    src = MvccScanSource(eng.snapshot(), ts=200, ranges=[(start, end)])
    resp_mvcc = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS)], source=src)
    resp_fix = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS)])
    assert resp_mvcc.encode() == resp_fix.encode()


def test_mvcc_leaf_respects_ts():
    eng = product_engine(commit_ts=100)
    start, end = record_range(TABLE_ID)
    src = MvccScanSource(eng.snapshot(), ts=50, ranges=[(start, end)])
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS)], source=src)
    assert resp.iter_rows() == []


def test_selection():
    # count > 9 AND count < 25
    cond = call("and", call("gt", col(2), const_int(9)), call("lt", col(2), const_int(25)))
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS), Selection([cond])])
    ids = [r[0] for r in resp.iter_rows()]
    assert ids == [1, 2, 5]


def test_selection_decimal_predicate():
    # price < 2.00 (scaled 200); NULL price row must not pass
    cond = call("lt", col(3), const_decimal(200, 2))
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS), Selection([cond])])
    ids = [r[0] for r in resp.iter_rows()]
    assert ids == [1, 2, 5]


def test_simple_aggregation():
    resp = run_dag(
        [
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation(
                group_by=[],
                agg_funcs=[
                    AggDescriptor("count", None),
                    AggDescriptor("sum", col(2)),
                    AggDescriptor("avg", col(3)),
                    AggDescriptor("min", col(2)),
                    AggDescriptor("max", col(3)),
                ],
            ),
        ]
    )
    rows = resp.iter_rows()
    assert len(rows) == 1
    count, sum_cnt, avg_n, avg_sum, min_cnt, max_price = rows[0]
    assert count == 6
    assert sum_cnt == 10 + 20 + 30 + 5 + 15 + 8
    assert avg_n == 5  # one NULL price
    assert avg_sum == (150 + 75 + 1250 + 200 + 150, 2)
    assert min_cnt == 5
    assert max_price == (1250, 2)


def test_hash_aggregation_group_by_name():
    resp = run_dag(
        [
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation(
                group_by=[col(1)],
                agg_funcs=[AggDescriptor("count", None), AggDescriptor("sum", col(2))],
            ),
        ]
    )
    rows = {tuple(r[2:][0:1])[0]: (r[0], r[1]) for r in resp.iter_rows()}
    assert rows[b"apple"] == (2, 25)
    assert rows[b"banana"] == (2, 28)
    assert rows[b"cherry"] == (1, 30)
    assert rows[None] == (1, 5)


def test_stream_aggregation_same_result():
    # stream agg contracts sorted-by-group-key input (stream_aggr_executor.rs
    # trusts the plan); the scan is ordered by handle, so group on col(0)
    mk = lambda streamed: run_dag(
        [
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Aggregation(group_by=[col(0)], agg_funcs=[AggDescriptor("count", None)], streamed=streamed),
        ]
    )
    assert mk(True).encode() == mk(False).encode()


def test_topn():
    resp = run_dag(
        [TableScan(TABLE_ID, PRODUCT_COLUMNS), TopN(order_by=[(col(2), True)], limit=3)]
    )
    ids = [r[0] for r in resp.iter_rows()]
    assert ids == [3, 2, 5]  # count desc: 30, 20, 15


def test_topn_nulls_first_asc():
    resp = run_dag(
        [TableScan(TABLE_ID, PRODUCT_COLUMNS), TopN(order_by=[(col(3), False)], limit=2)]
    )
    rows = resp.iter_rows()
    assert rows[0][3] is None  # NULL price first ascending
    assert rows[1][3] == (75, 2)


def test_topn_desc_nulls_last():
    resp = run_dag(
        [TableScan(TABLE_ID, PRODUCT_COLUMNS), TopN(order_by=[(col(3), True)], limit=6)]
    )
    rows = resp.iter_rows()
    assert rows[0][3] == (1250, 2)
    assert rows[-1][3] is None


def test_limit():
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS), Limit(2)])
    assert len(resp.iter_rows()) == 2
    resp = run_dag([TableScan(TABLE_ID, PRODUCT_COLUMNS), Limit(100)])
    assert len(resp.iter_rows()) == 6


def test_selection_then_agg_then_topn():
    cond = call("ge", col(2), const_int(8))
    resp = run_dag(
        [
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Selection([cond]),
            Aggregation(group_by=[col(1)], agg_funcs=[AggDescriptor("sum", col(2))]),
            TopN(order_by=[(col(0), True)], limit=2),
        ]
    )
    rows = resp.iter_rows()
    assert rows == [[30, b"cherry"], [28, b"banana"]]


def test_check_supported_rejects_bad_plans():
    with pytest.raises(ValueError):
        check_supported(DagRequest(executors=[]))
    with pytest.raises(ValueError):
        check_supported(DagRequest(executors=[Limit(1)]))
    with pytest.raises(ValueError):
        check_supported(
            DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS), TableScan(1, [])])
        )


def test_batch_growth_over_large_fixture():
    # >1024 rows to exercise batch growth and chunk flushing
    rows = [(i, b"x", i % 7, i) for i in range(1, 3001)]
    resp = run_dag(
        [TableScan(TABLE_ID, PRODUCT_COLUMNS), Selection([call("ne", col(2), const_int(3))])],
        source=FixtureScanSource(product_kvs(rows)),
    )
    got = [r[0] for r in resp.iter_rows()]
    expect = [i for i in range(1, 3001) if i % 7 != 3]
    assert got == expect
    assert len(resp.chunks) > 1


def test_decimal_divide_real_unscales():
    """divide_real over DECIMAL(2) must divide the numeric value, not the scaled int."""
    from tikv_tpu.copr.rpn import compile_expr, eval_expr_on_chunk
    from tikv_tpu.copr.datatypes import Chunk, Column, EvalType

    price = Column.from_values(EvalType.DECIMAL, [150, 250], frac=2)  # 1.50, 2.50
    qty = Column.from_values(EvalType.INT, [3, 5])
    chunk = Chunk.full([price, qty])
    schema = [(EvalType.DECIMAL, 2), (EvalType.INT, 0)]
    rpn = compile_expr(call("divide_real", col(0), col(1)), schema)
    data, nulls = eval_expr_on_chunk(rpn, chunk)
    assert data[0] == pytest.approx(0.5)
    assert data[1] == pytest.approx(0.5)
    # decimal / decimal
    rpn2 = compile_expr(call("divide_real", col(0), col(0)), schema)
    data2, _ = eval_expr_on_chunk(rpn2, chunk)
    assert data2[0] == pytest.approx(1.0)


def test_int_divide_truncates_toward_zero():
    from tikv_tpu.copr.rpn import compile_expr, eval_expr_on_chunk
    from tikv_tpu.copr.datatypes import Chunk, Column, EvalType

    a = Column.from_values(EvalType.INT, [7, -7, 7, -7, 1])
    b = Column.from_values(EvalType.INT, [2, 2, -2, -2, 0])
    chunk = Chunk.full([a, b])
    schema = [(EvalType.INT, 0), (EvalType.INT, 0)]
    rpn = compile_expr(call("int_divide", col(0), col(1)), schema)
    data, nulls = eval_expr_on_chunk(rpn, chunk)
    assert list(data[:4]) == [3, -3, -3, 3]
    assert bool(nulls[4])  # x DIV 0 = NULL


def test_string_kernels():
    from tikv_tpu.copr.datatypes import Chunk, Column, EvalType
    from tikv_tpu.copr.rpn import compile_expr, const_bytes, eval_expr_on_chunk

    names = Column.from_values(EvalType.BYTES, [b"  Apple ", b"banana", None, b""])
    chunk = Chunk.full([names])
    schema = [(EvalType.BYTES, 0)]

    def run(expr):
        return eval_expr_on_chunk(compile_expr(expr, schema), chunk)

    d, n = run(call("length", col(0)))
    assert list(d[:2]) == [8, 6] and bool(n[2])
    d, n = run(call("upper", call("trim", col(0))))
    assert d[0] == b"APPLE" and d[1] == b"BANANA"
    d, n = run(call("substr3", col(0), const_int(3), const_int(4)))
    assert d[0] == b"Appl"
    d, n = run(call("concat", col(0), const_bytes(b"!"), col(0)))
    assert d[1] == b"banana!banana"
    d, n = run(call("replace", col(0), const_bytes(b"a"), const_bytes(b"_")))
    assert d[1] == b"b_n_n_"
    d, n = run(call("left", col(0), const_int(3)))
    assert d[1] == b"ban"
    d, n = run(call("locate", const_bytes(b"nan"), col(0)))
    assert d[1] == 3
    d, n = run(call("reverse", col(0)))
    assert d[1] == b"ananab"
    d, n = run(call("hex", col(0)))
    assert d[3] == b""


def test_like_kernel():
    from tikv_tpu.copr.datatypes import Chunk, Column, EvalType
    from tikv_tpu.copr.rpn import compile_expr, const_bytes, eval_expr_on_chunk

    names = Column.from_values(EvalType.BYTES, [b"apple", b"banana", b"grape", b"a%b"])
    chunk = Chunk.full([names])
    schema = [(EvalType.BYTES, 0)]

    def like(pat):
        d, _ = eval_expr_on_chunk(
            compile_expr(call("like", col(0), const_bytes(pat)), schema), chunk
        )
        return list(d)

    assert like(b"%an%") == [0, 1, 0, 0]
    assert like(b"a%") == [1, 0, 0, 1]
    assert like(b"_rape") == [0, 0, 1, 0]
    assert like(b"a\\%b") == [0, 0, 0, 1]  # escaped % is literal


def test_in_case_coalesce_casts():
    from tikv_tpu.copr.datatypes import Chunk, Column, EvalType
    from tikv_tpu.copr.rpn import compile_expr, eval_expr_on_chunk

    a = Column.from_values(EvalType.INT, [1, 5, None, 9])
    r = Column.from_values(EvalType.REAL, [1.4, 2.5, -2.5, 0.0])
    chunk = Chunk.full([a, r])
    schema = [(EvalType.INT, 0), (EvalType.REAL, 0)]

    def run(expr):
        return eval_expr_on_chunk(compile_expr(expr, schema), chunk)

    d, n = run(call("in", col(0), const_int(1), const_int(9)))
    assert list(d[[0, 1, 3]]) == [1, 0, 1] and bool(n[2])
    d, n = run(
        call("case_when", call("gt", col(0), const_int(4)), const_int(100), const_int(-1))
    )
    assert list(d[[0, 1, 3]]) == [-1, 100, 100]
    d, n = run(call("coalesce", col(0), const_int(42)))
    assert d[2] == 42 and not n[2]
    d, n = run(call("cast_real_int", col(1)))
    assert list(d) == [1, 3, -3, 0]  # MySQL half-away-from-zero
    d, n = run(call("cast_int_real", col(0)))
    assert d[0] == 1.0 and d.dtype.kind == "f"


def test_device_rejects_new_bytes_kernels():
    """String kernels stay CPU-only; supports() must still say no."""
    from tikv_tpu.copr.dag import DagRequest, Selection, TableScan
    from tikv_tpu.copr.jax_eval import supports
    from tikv_tpu.copr.rpn import const_bytes

    dag = DagRequest(
        executors=[
            TableScan(TABLE_ID, PRODUCT_COLUMNS),
            Selection([call("like", col(1), const_bytes(b"a%"))]),
        ]
    )
    assert not supports(dag)


# ----------------------------------------------------- stream aggregation

def _feed_chunks(chunks, schema):
    from tikv_tpu.copr.executors import BatchExecuteResult, BatchExecutor

    class Feed(BatchExecutor):
        def __init__(self):
            self.i = 0

        def schema(self):
            return schema

        def next_batch(self, n):
            from tikv_tpu.copr.datatypes import Chunk

            if self.i >= len(chunks):
                return BatchExecuteResult(Chunk.full([]), True)
            c = chunks[self.i]
            self.i += 1
            return BatchExecuteResult(c, self.i >= len(chunks))

    return Feed()


def _drain_rows(ex):
    rows = []
    drained = False
    while not drained:
        r = ex.next_batch(1024)
        drained = r.is_drained
        ch = r.chunk
        vals = [c.to_values() for c in ch.columns]
        rows.extend(zip(*vals) if vals else [])
    return rows


def _mk_chunk(keys, vals):
    from tikv_tpu.copr.datatypes import Chunk, Column

    return Chunk.full([
        Column.from_values(EvalType.BYTES, keys),
        Column.from_values(EvalType.INT, vals),
    ])


def test_stream_agg_group_spans_chunks():
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor

    schema = [(EvalType.BYTES, 0), (EvalType.INT, 0)]
    chunks = [
        _mk_chunk([b"a", b"a", b"b"], [1, 2, 3]),
        _mk_chunk([b"b", b"b", b"c"], [4, 5, 6]),
        _mk_chunk([b"c"], [7]),
    ]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks(chunks, schema), [col(0)],
        [AggDescriptor("sum", col(1)), AggDescriptor("count", None)],
    )
    assert _drain_rows(agg) == [(3, 2, b"a"), (12, 3, b"b"), (13, 2, b"c")]


def test_stream_agg_bounded_state():
    """Between batches at most ONE group's state is resident."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor

    schema = [(EvalType.BYTES, 0), (EvalType.INT, 0)]
    chunks = [
        _mk_chunk([b"g%04d" % i for i in range(k, k + 100)], list(range(100)))
        for k in range(0, 1000, 100)
    ]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks(chunks, schema), [col(0)], [AggDescriptor("sum", col(1))]
    )
    emitted = 0
    drained = False
    while not drained:
        r = agg.next_batch(1024)
        drained = r.is_drained
        emitted += r.chunk.num_rows
        # the carry is at most one group wide
        assert len(agg.states[0].count) <= 1
    assert emitted == 1000


def test_stream_agg_matches_hash_path():
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.executors import (
        BatchHashAggregationExecutor,
        BatchStreamAggregationExecutor,
    )

    rng = np.random.default_rng(7)
    keys = sorted(b"k%03d" % rng.integers(0, 40) for _ in range(500))
    vals = [int(v) for v in rng.integers(-100, 100, size=500)]
    schema = [(EvalType.BYTES, 0), (EvalType.INT, 0)]
    chunks = [_mk_chunk(keys[i : i + 64], vals[i : i + 64]) for i in range(0, 500, 64)]

    def run(cls):
        ex = cls(
            _feed_chunks(chunks, schema), [col(0)],
            [AggDescriptor("sum", col(1)), AggDescriptor("count", None),
             AggDescriptor("min", col(1)), AggDescriptor("max", col(1))],
        )
        return sorted(_drain_rows(ex), key=lambda r: r[-1])

    assert run(BatchStreamAggregationExecutor) == run(BatchHashAggregationExecutor)


def test_stream_agg_nulls_group_together():
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor

    schema = [(EvalType.BYTES, 0), (EvalType.INT, 0)]
    chunks = [
        _mk_chunk([None, None], [1, 2]),
        _mk_chunk([None, b"z"], [3, 10]),
    ]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks(chunks, schema), [col(0)], [AggDescriptor("sum", col(1))]
    )
    assert _drain_rows(agg) == [(6, None), (10, b"z")]


def test_stream_agg_empty_input():
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor

    schema = [(EvalType.BYTES, 0), (EvalType.INT, 0)]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks([], schema), [col(0)], [AggDescriptor("count", None)]
    )
    assert _drain_rows(agg) == []


def test_stream_agg_null_expr_key_spans_chunks():
    """NULL group keys canonicalize to None: the garbage data a kernel leaves
    under a null mask must not split the NULL group at a chunk boundary."""
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.datatypes import Chunk, Column
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor

    def mk(a_vals, b_vals):
        return Chunk.full([
            Column.from_values(EvalType.INT, a_vals),
            Column.from_values(EvalType.INT, b_vals),
        ])

    schema = [(EvalType.INT, 0), (EvalType.INT, 0)]
    # group key = a + b; a is NULL with different b values across the boundary
    chunks = [mk([None, None], [7, 8]), mk([None, 5], [9, 5])]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks(chunks, schema),
        [call("plus", col(0), col(1))],
        [AggDescriptor("count", None)],
    )
    rows = _drain_rows(agg)
    assert rows == [(3, None), (1, 10)]


def test_stream_agg_json_minmax_carry():
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.datatypes import Chunk, Column
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor
    from tikv_tpu.copr.json_value import json_decode, json_encode

    def mk(keys, docs):
        return Chunk.full([
            Column.from_values(EvalType.BYTES, keys),
            Column.from_values(EvalType.JSON, [json_encode(d) for d in docs]),
        ])

    schema = [(EvalType.BYTES, 0), (EvalType.JSON, 0)]
    # group g1 emitted in batch 1; g2 spans the boundary — its JSON min must
    # compare against its OWN carried best, not g1's stale cache slot
    chunks = [mk([b"g1", b"g2"], [100, 50]), mk([b"g2", b"g2"], [30, 70])]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks(chunks, schema), [col(0)], [AggDescriptor("min", col(1))]
    )
    rows = _drain_rows(agg)
    assert [(json_decode(v), k) for v, k in rows] == [(100, b"g1"), (30, b"g2")]


def test_stream_agg_enum_key_keeps_dictionary():
    from tikv_tpu.copr.aggr import AggDescriptor
    from tikv_tpu.copr.datatypes import Chunk, Column, enum_column, enum_names
    from tikv_tpu.copr.executors import BatchStreamAggregationExecutor

    elems = (b"red", b"green")
    schema = [(EvalType.ENUM, 0), (EvalType.INT, 0)]
    chunks = [
        Chunk.full([enum_column([1, 1], elems), Column.from_values(EvalType.INT, [1, 2])]),
        Chunk.full([enum_column([2], elems), Column.from_values(EvalType.INT, [5])]),
    ]
    agg = BatchStreamAggregationExecutor(
        _feed_chunks(chunks, schema), [col(0)], [AggDescriptor("sum", col(1))]
    )
    out = []
    drained = False
    key_cols = []
    while not drained:
        r = agg.next_batch(1024)
        drained = r.is_drained
        if r.chunk.num_rows:
            key_cols.append(r.chunk.columns[-1])
    names = [enum_names(kc).to_values() for kc in key_cols]
    # the second chunk arrives with is_drained, so both groups emit together
    assert names == [[b"red", b"green"]]
