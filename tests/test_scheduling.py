"""Consistency-check observer + PD heartbeat-response scheduling + load split.

Reference surfaces: raftstore/src/coprocessor/consistency_check.rs (region
hash verified across replicas), pd_client lib.rs:180 (operators piggybacked
on region heartbeat responses), store/worker/split_controller.rs (load-based
auto split).
"""

import time

import numpy as np
import pytest

from tikv_tpu.pd.client import MockPd
from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
from tikv_tpu.server.cluster import ServerCluster
from tikv_tpu.storage.engine import CF_DEFAULT, WriteBatch
from tikv_tpu.util import keys as keymod


# -- consistency check -------------------------------------------------------

def _run_check(c: Cluster, region_id: int) -> None:
    leader = c.wait_leader(region_id)
    import threading

    done = threading.Event()
    leader.schedule_consistency_check(lambda r: done.set())
    for _ in range(200):
        c.process()
        c.tick()
        if done.is_set():
            break
    # let the follow-up verify_hash entry commit + apply everywhere
    c.tick(5)


def test_consistency_check_all_replicas_agree():
    c = Cluster(3)
    c.run()
    for i in range(20):
        c.must_put(b"ck-%02d" % i, b"v%d" % i)
    _run_check(c, FIRST_REGION_ID)
    hashes = set()
    for sid in (1, 2, 3):
        rec = c.stores[sid].consistency_hashes.get(FIRST_REGION_ID)
        assert rec is not None, f"store {sid} never hashed"
        hashes.add(rec)
        assert not c.stores[sid].inconsistent_regions
    assert len(hashes) == 1, f"replica hashes diverge on healthy data: {hashes}"


def test_consistency_check_detects_injected_divergence():
    """A replica whose engine silently diverged (bit rot, lost write) is
    caught by the hash comparison at an identical apply point."""
    c = Cluster(3)
    c.run()
    for i in range(10):
        c.must_put(b"dk-%02d" % i, b"v%d" % i)
    # corrupt store 3's applied data BEHIND raft's back
    c.stores[3].engine.put_cf(CF_DEFAULT, keymod.data_key(b"dk-05"), b"CORRUPT")
    _run_check(c, FIRST_REGION_ID)
    assert FIRST_REGION_ID in c.stores[3].inconsistent_regions, (
        "diverged replica not detected"
    )
    bad = c.stores[3].inconsistent_regions[FIRST_REGION_ID]
    assert bad["local_hash"] != bad["leader_hash"]
    # healthy replicas stay clean
    assert not c.stores[1].inconsistent_regions
    assert not c.stores[2].inconsistent_regions


# -- PD scheduling ------------------------------------------------------------

def test_pd_repairs_under_replicated_region():
    """replication_factor=3 with a 2-replica region: PD orders add_peer on
    the spare store through the heartbeat response; the cluster heals
    without manual ops."""
    pd = MockPd()
    pd.replication_factor = 3
    c = ServerCluster(3, pd=pd)
    c.start()
    c.bootstrap(store_ids=[1, 2])
    c.nodes[1].store.peers[FIRST_REGION_ID].node.campaign()
    c.wait_leader(FIRST_REGION_ID)
    try:
        c.must_put(b"rk", b"rv")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if FIRST_REGION_ID in c.nodes[3].store.peers:
                break
            time.sleep(0.1)
        assert FIRST_REGION_ID in c.nodes[3].store.peers, "PD never repaired"
        c.wait_get_on_store(3, b"rk", b"rv")
    finally:
        c.shutdown()


def test_pd_removes_excess_replica():
    pd = MockPd()
    pd.replication_factor = 2
    c = ServerCluster(3, pd=pd)
    c.start()
    c.bootstrap()
    c.nodes[1].store.peers[FIRST_REGION_ID].node.campaign()
    c.wait_leader(FIRST_REGION_ID)
    try:
        c.must_put(b"xk", b"xv")
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            leader = c.leader_peer(FIRST_REGION_ID)
            if leader is not None and len(leader.region.peers) == 2:
                break
            time.sleep(0.1)
        leader = c.leader_peer(FIRST_REGION_ID)
        assert len(leader.region.peers) == 2, "PD never removed the excess replica"
        c.must_put(b"xk2", b"xv2")
        assert c.must_get(b"xk2") == b"xv2"
    finally:
        c.shutdown()


def test_manual_transfer_leader_operator():
    """pd-ctl style injected operator: transfer_leader rides the next
    heartbeat response and the old leader sends MsgTimeoutNow."""
    pd = MockPd()
    c = ServerCluster(3, pd=pd)
    c.run()
    try:
        c.must_put(b"tk", b"tv")
        leader = c.wait_leader(FIRST_REGION_ID)
        old_sid = leader.store.store_id
        target_sid = next(s for s in (1, 2, 3) if s != old_sid)
        target_peer = leader.region.peer_on_store(target_sid)
        pd.add_operator(
            FIRST_REGION_ID,
            {"type": "transfer_leader", "peer_id": target_peer.peer_id, "store_id": target_sid},
        )
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            cur = c.leader_peer(FIRST_REGION_ID)
            if cur is not None and cur.store.store_id == target_sid:
                break
            time.sleep(0.1)
        cur = c.leader_peer(FIRST_REGION_ID)
        assert cur.store.store_id == target_sid, "leadership never transferred"
        assert c.must_get(b"tk") == b"tv"
    finally:
        c.shutdown()


# -- load-based auto split ----------------------------------------------------

def test_load_based_auto_split():
    """Sustained write load above the QPS threshold splits the hot region at
    its middle key (AutoSplitController)."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.raftkv import RaftKv
    from tikv_tpu.raft.store import ChannelTransport
    from tikv_tpu.server.node import Node

    pd = MockPd()
    transport = ChannelTransport()
    node = Node(pd, transport, split_qps_threshold=10.0)
    transport.register(node.store)
    node.try_bootstrap_cluster([node.store_id])
    node.create_region_peers()
    peer = node.store.peers[FIRST_REGION_ID]
    peer.node.campaign()
    node.pump()
    node.start(heartbeat_interval=0.2)
    try:
        kv = RaftKv(node.store)
        deadline = time.monotonic() + 20
        i = 0
        while time.monotonic() < deadline and len(node.store.peers) < 2:
            wb = WriteBatch()
            wb.put_cf("write", b"ls-%06d" % i, b"v")
            try:
                kv.write({"region_id": FIRST_REGION_ID}, wb)
            except Exception:
                break  # region split mid-write (epoch changed): done
            i += 1
        assert len(node.store.peers) >= 2, "hot region never split"
        regions = sorted(p.region.id for p in node.store.peers.values())
        assert len(pd.regions) >= 2
    finally:
        node.stop()


def test_pd_replaces_voter_on_dead_store():
    """A voter on a permanently-down store is REPLACED (remove then re-add)
    even though the count still equals the replication factor — the
    reference's max-store-down-time behavior."""
    pd = MockPd()
    pd.replication_factor = 3
    pd.store_down_secs = 1.0
    c = ServerCluster(4, pd=pd)
    c.start()
    c.bootstrap(store_ids=[1, 2, 3])
    c.nodes[1].store.peers[FIRST_REGION_ID].node.campaign()
    c.wait_leader(FIRST_REGION_ID)
    try:
        c.must_put(b"dk", b"dv")
        c.stop_node(3)  # store 3 stops heartbeating; store 4 is the spare
        deadline = time.monotonic() + 25
        while time.monotonic() < deadline:
            leader = c.leader_peer(FIRST_REGION_ID)
            if leader is not None:
                stores = {p.store_id for p in leader.region.peers}
                if 3 not in stores and 4 in stores:
                    break
            time.sleep(0.1)
        leader = c.leader_peer(FIRST_REGION_ID)
        stores = {p.store_id for p in leader.region.peers}
        assert 3 not in stores and 4 in stores, f"never replaced: {stores}"
        c.must_put(b"dk2", b"dv2")
        c.wait_get_on_store(4, b"dk2", b"dv2")
    finally:
        c.shutdown()


def test_pd_balance_region_converges():
    """balance-region scheduler: replicas migrate off the crowded store via
    two-phase add-then-remove operators until the spread falls under the
    threshold (pd-server balance-region; operator surface lib.rs:180-217)."""
    pd = MockPd()
    pd.replication_factor = 1
    pd.balance_region_threshold = 2
    pd.balance_threshold = 10**9  # isolate: no leader-balance interference
    c = ServerCluster(2, pd=pd)
    c.start()
    c.bootstrap(store_ids=[1])
    c.nodes[1].store.peers[FIRST_REGION_ID].node.campaign()
    c.wait_leader(FIRST_REGION_ID)
    try:
        # 10 single-replica regions, all on store 1; store 2 hosts none
        import string

        split_keys = [k.encode() for k in string.ascii_lowercase[:9]]
        rid = FIRST_REGION_ID
        for k in split_keys:
            c.must_put(k, b"v")
        for k in split_keys:
            c.split_region(c.region_for_key(k), k)

        def counts():
            per = {1: 0, 2: 0}
            for node in c.nodes.values():
                per[node.store.store_id] = len(node.store.peers)
            return per

        # balancing may already be migrating replicas while we split — only
        # the end state matters: 10 single-replica regions (in-flight moves
        # transiently show an extra peer), spread within the threshold
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline:
            per = counts()
            if (per[1] + per[2] == 10 and per[2] >= 4
                    and abs(per[1] - per[2]) <= pd.balance_region_threshold):
                break
            time.sleep(0.2)
        per = counts()
        assert per[2] >= 4 and abs(per[1] - per[2]) <= pd.balance_region_threshold, (
            f"never converged: {per}"
        )
        assert per[1] + per[2] == 10, per  # moves, not copies
        # the data followed the replicas
        for k in split_keys:
            assert c.must_get(k) == b"v"
    finally:
        c.shutdown()


def test_leader_balance_weighs_region_load():
    """Hot-region-aware leader balance (pd-server hot scheduler role): equal
    leader COUNTS still rebalance when one store leads all the load; zero
    load everywhere keeps the old pure-count behavior."""
    from tikv_tpu.raft.region import Peer as RegionPeer, Region, RegionEpoch

    pd = MockPd()
    pd.replication_factor = 2
    pd.balance_threshold = 2
    pd.balance_region_threshold = 10**9  # isolate leader balance

    def mk_region(rid):
        return Region(rid, b"%d-a" % rid, b"%d-z" % rid, RegionEpoch(),
                      [RegionPeer(rid * 10 + 1, 1), RegionPeer(rid * 10 + 2, 2)])

    regions = {rid: mk_region(rid) for rid in (1, 2, 3, 4)}
    pd.store_heartbeat(1, {})
    pd.store_heartbeat(2, {})
    # equal counts: stores 1 and 2 lead two regions each — no load, balanced
    # (interleaved registration so the count delta never crosses the
    # threshold transiently)
    for rid, lsid in ((1, 1), (3, 2), (2, 1), (4, 2)):
        pd.region_heartbeat(regions[rid], lsid)
    for rid, lsid in ((1, 1), (3, 2), (2, 1), (4, 2)):
        op = pd.region_heartbeat(regions[rid], lsid)
        assert op is None, (rid, op)
    # store 1's regions run hot; store 2's stay idle — several beats build
    # the EWMA past the threshold (2 weight units = 200 load at unit=100)
    for _ in range(6):
        pd.region_heartbeat(regions[1], 1, load=400)
        pd.region_heartbeat(regions[2], 1, load=400)
        pd.region_heartbeat(regions[3], 2, load=0)
        pd.region_heartbeat(regions[4], 2, load=0)
    op = pd.region_heartbeat(regions[1], 1, load=400)
    assert op is not None and op["type"] == "transfer_leader", op
    assert op["store_id"] == 2
