"""Buffer-exposure sanitizer (analysis/bufsan) — seeded strikes + regression.

The strike tests are deliberate bugs: mutate a buffer INSIDE its exposure
window (between ``wire.dumps_parts`` and the frame writer's send completion,
or between a device pin and its drop) and assert bufsan reports the
mutation with BOTH stacks — exactly the nemesis-style seeding the lock-order
sanitizer gets in test_sanitizer.py.  The race test is the regression half:
write-through folds hammering a region image while a client streams chunk
responses off it over a real socket must stay byte-identical to the CPU
oracle with ZERO violations, because the fixed tree copies-on-export
(chunk slabs are immutable bytes) and defers pin patches to scatter_update.
"""

from __future__ import annotations

import os
import socket
import threading

import numpy as np
import pytest

from copr_fixtures import PRODUCT_COLUMNS, TABLE_ID
from test_write_through import (
    NON_HANDLE,
    REGION,
    _engine,
    _req,
    _scan_dag,
    commit_ops,
)

from tikv_tpu.analysis import bufsan, sanitizer
from tikv_tpu.copr.cache import ColumnBlockCache
from tikv_tpu.copr.dag import ENC_TYPE_CHUNK, DagRequest, Limit, TableScan
from tikv_tpu.copr.dag_wire import dag_to_wire
from tikv_tpu.copr.endpoint import Endpoint
from tikv_tpu.copr.region_cache import notify_region_write
from tikv_tpu.copr.rowv2 import encode_row_v2
from tikv_tpu.copr.table import record_key, record_range
from tikv_tpu.server import wire
from tikv_tpu.server.server import Client, Server, write_frame_parts
from tikv_tpu.server.service import KvService
from tikv_tpu.storage.kv import LocalEngine
from tikv_tpu.storage.storage import Storage
from tikv_tpu.util.metrics import REGISTRY


@pytest.fixture(autouse=True)
def _isolate():
    """Seeded violations must not leak into the session-wide sanitize gate
    (conftest) or other tests — same snapshot/restore contract as
    test_sanitizer.py, extended with the bufsan ledger."""
    s_saved = sanitizer.snapshot_state()
    b_saved = bufsan.snapshot_state()
    sanitizer.clear_reports()
    bufsan.clear()
    yield
    bufsan.restore_state(b_saved)
    sanitizer.restore_state(s_saved)


# ---------------------------------------------------------------------------
# seeded strikes — both exposure kinds, both report stacks
# ---------------------------------------------------------------------------


def test_strike_wire_part_mutated_before_send():
    """Mutate the backing array between dumps_parts and write_frame_parts:
    the release verify at send completion must report, naming both the
    export site and the release site."""
    arr = np.arange(512, dtype=np.int64)
    with sanitizer.force():
        parts = wire.dumps_parts({"data": memoryview(arr).cast("B")})
        assert bufsan.ledger_size() == 1
        assert bufsan.exposed_kinds() == {"wire_part": 1}
        arr[:5] = 999  # the strike: in-place write inside the window
        a, b = socket.socketpair()
        try:
            write_frame_parts(a, parts)
        finally:
            a.close()
            b.close()
        reps = bufsan.reports()
    assert len(reps) == 1
    text = reps[0].format()
    assert "wire.dumps_parts" in text
    assert "server.write_frame_parts" in text
    # both stacks present: the exposure stack and the release stack
    assert len(reps[0].stacks) == 2
    assert all(frames for _label, frames in reps[0].stacks)
    assert bufsan.ledger_size() == 0, "release still drops the entry"


def test_strike_device_pin_bypass_write():
    """A host write that bypasses scatter_update while the array is pinned:
    caught by the release verify at drop_device."""
    with sanitizer.force():
        cache = ColumnBlockCache()
        cache.add([], 0)
        blk = cache.blocks[0]
        host = np.arange(256, dtype=np.int64)
        cache.device_arrays(blk, ("striketest", 0), lambda b: (host,))
        assert bufsan.exposed_kinds() == {"device_pin": 1}
        host[:3] = 7  # the strike: not routed through scatter_update
        cache.drop_device()
        reps = bufsan.reports()
    assert len(reps) == 1
    text = reps[0].format()
    assert "cache.device_arrays" in text
    assert "cache.drop_device" in text
    assert len(reps[0].stacks) == 2


def test_strike_mutation_choke_point_reports_immediately():
    """note_mutation (the _apply_updates choke point) must report an
    overlapping live exposure BEFORE the write, with the mutation stack."""
    arr = np.zeros(4096, dtype=np.uint8)
    with sanitizer.force():
        bufsan.export("wire_part", memoryview(arr), site="test.export")
        bufsan.note_mutation([arr[100:200]], site="test.fold")
        reps = bufsan.reports()
    assert len(reps) == 1
    text = reps[0].format()
    assert "mutation" in text and "test.fold" in text and "test.export" in text


def test_note_mutation_excludes_device_pins():
    """The coordinated host-mutate-then-scatter path would otherwise be a
    permanent false positive (docs/static_analysis.md FP policy)."""
    arr = np.zeros(4096, dtype=np.uint8)
    with sanitizer.force():
        bufsan.export("device_pin", arr, site="t.pin")
        bufsan.note_mutation([arr], site="t.fold")
        assert not bufsan.reports()
        bufsan.clear()


def test_scatter_update_reregisters_pins_no_false_positive():
    """The real coordinated path: pin, mutate host, scatter_update patches
    and re-registers — the later drop must verify clean."""
    with sanitizer.force():
        cache = ColumnBlockCache()
        cache.add([], 0)
        blk = cache.blocks[0]
        host = np.arange(64, dtype=np.int64)
        # unknown-kind sig: scatter_update drops (releases) it, and the
        # release verify runs against the pre-mutation sample... so the
        # coordinated order is mutate-AFTER-release here, like _apply_updates
        cache.device_arrays(blk, ("striketest", 1), lambda b: (host,))
        cache.scatter_update({})  # drops + releases the unknown-kind pin
        host[:3] = -1  # host write lands after the pin released: legal
        cache.drop_device()
        assert not bufsan.reports()


# ---------------------------------------------------------------------------
# ledger mechanics
# ---------------------------------------------------------------------------


def test_release_unregistered_is_quiet():
    with sanitizer.force():
        assert bufsan.release(b"never exported") == 0
        assert not bufsan.reports()


def test_ledger_bound_evicts_with_verify():
    """Past _MAX_LEDGER the oldest entry is evicted — but still verified,
    so a leaked-and-mutated exposure cannot age out silently."""
    with sanitizer.force():
        first = np.arange(64, dtype=np.uint8)
        bufsan.export("wire_part", first, site="t.first")
        first[:4] = 9  # mutate while exposed; never explicitly released
        for _ in range(bufsan._MAX_LEDGER):
            bufsan.export("wire_part", np.zeros(8, dtype=np.uint8), site="t.fill")
        assert bufsan.ledger_size() == bufsan._MAX_LEDGER
        reps = bufsan.reports()
    assert len(reps) == 1
    assert "t.first" in reps[0].format()


def test_metric_counts_export_release_violation():
    c = REGISTRY.counter("tikv_bufsan_total")
    base = {e: c.get(event=e) for e in ("export", "release", "violation")}
    arr = np.arange(128, dtype=np.uint8)
    with sanitizer.force():
        bufsan.export("wire_part", arr, site="t.m")
        arr[:2] = 1
        bufsan.release(arr, site="t.m")
    assert c.get(event="export") == base["export"] + 1
    assert c.get(event="release") == base["release"] + 1
    assert c.get(event="violation") == base["violation"] + 1


@pytest.mark.skipif(os.environ.get("TIKV_TPU_SANITIZE") == "1",
                    reason="sanitize smoke run: bufsan is globally armed")
def test_disabled_is_noop():
    arr = np.arange(64, dtype=np.uint8)
    bufsan.export("wire_part", arr, site="t.off")
    assert bufsan.ledger_size() == 0
    assert bufsan.release(arr) == 0


# ---------------------------------------------------------------------------
# the regression race: wt folds vs sendmsg gather writes (ISSUE 20 sat. 2)
# ---------------------------------------------------------------------------


def test_wt_fold_races_chunk_serving_byte_identical():
    """4 client threads pull chunk responses off the warm image over a real
    socket while writer threads fold write-through deltas into the same
    region — the fold's in-place column writes racing the ``sendmsg``
    gather writes on the serve side.  The racing commits are IDEMPOTENT
    (same row, same value, climbing commit ts), so every read at a high ts
    sees the same visible bytes: each warm-served chunk must byte-match the
    cold CPU oracle, real folds must have happened, and bufsan (armed for
    the whole test) must stay silent because chunk slabs are copies and pin
    patches defer to scatter_update."""
    BIG_TS = 1 << 40
    eng = LocalEngine(_engine(v2=True))
    warm = Endpoint(eng, enable_device=True)
    cold = Endpoint(eng, enable_device=True, enable_region_cache=False)
    dag = DagRequest(executors=[TableScan(TABLE_ID, PRODUCT_COLUMNS),
                                Limit(1 << 20)],
                     encode_type=ENC_TYPE_CHUNK)
    racer_val = encode_row_v2(NON_HANDLE, [b"racer", 9, 9])
    with sanitizer.force():
        # warm the image, land the first racer write, fold it once so the
        # oracle below already includes the (stable) racer value
        warm.handle_request(_req(dag, BIG_TS, 3))
        notify_region_write(
            REGION, commit_ops(eng.kv, record_key(TABLE_ID, 5),
                               racer_val, 210, 215), 4)
        r = warm.handle_request(_req(dag, BIG_TS, 4))
        assert r.metrics["region_cache"] == "wt_delta"
        oracle_bytes = bytes(cold.handle_request(_req(dag, BIG_TS, 4)).data)
        assert oracle_bytes, "oracle must have chunk payload"

        srv = Server(KvService(Storage(engine=eng), warm))
        srv.start()
        stop = threading.Event()
        errors: list = []
        fold_mu = threading.Lock()
        latest = [4]

        def folder():
            ts = 230
            while not stop.is_set():
                with fold_mu:
                    idx = latest[0] + 1
                    ops = commit_ops(eng.kv, record_key(TABLE_ID, 5),
                                     racer_val, ts, ts + 5)
                    notify_region_write(REGION, ops, idx)
                    latest[0] = idx
                ts += 10

        def client(n_iters=12):
            try:
                c = Client(*srv.addr)
                for _ in range(n_iters):
                    resp = c.call("coprocessor", {
                        "dag": dag_to_wire(dag),
                        "ranges": [list(record_range(TABLE_ID))],
                        "start_ts": BIG_TS,
                        "context": {"region_id": REGION,
                                    "region_epoch": (1, 1),
                                    "apply_index": latest[0]},
                    })
                    assert "error" not in resp, resp.get("error")
                    got = b"".join(bytes(p) for p in resp["data_parts"])
                    if got != oracle_bytes:
                        errors.append("chunk bytes diverged from oracle")
                        return
                c.close()
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        folders = [threading.Thread(target=folder, daemon=True)
                   for _ in range(2)]
        clients = [threading.Thread(target=client) for _ in range(4)]
        try:
            for t in folders + clients:
                t.start()
            for t in clients:
                t.join(timeout=60)
        finally:
            stop.set()
            for t in folders:
                t.join(timeout=10)
            srv.stop()
        assert not errors, errors
        # the race must be real: deltas actually folded into the warm image
        assert warm.region_cache.stats.wt_deltas >= 1
        assert not bufsan.reports(), [r.format() for r in bufsan.reports()]
