"""Memory accounting (tikv_util/src/memory.rs MemoryQuota + MemoryTrace,
server.rs:129-131 high-water) and CDC sink flow control (cdc/src/channel.rs):
quotas bound buffered bytes, congestion tears subscriptions down instead of
ballooning the store, and incremental scans pause against a full sink."""

import threading
import time

import pytest

from tikv_tpu.util.memory import MemoryQuota, StoreMemoryTrace


class TestMemoryQuota:
    def test_alloc_free(self):
        q = MemoryQuota(100)
        assert q.alloc(60)
        assert not q.alloc(50)
        assert q.alloc(40)
        q.free(60)
        assert q.in_use() == 40
        assert q.alloc(50)

    def test_alloc_force_exceeds(self):
        q = MemoryQuota(10)
        q.alloc_force(50)
        assert q.in_use() == 50
        assert not q.alloc(1)

    def test_alloc_wait_unblocks_on_free(self):
        q = MemoryQuota(100)
        assert q.alloc(100)
        got = []

        def blocked():
            got.append(q.alloc_wait(40, timeout=10.0))

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.1)
        assert not got  # parked
        q.free(50)
        t.join(timeout=5)
        assert got == [True]

    def test_alloc_wait_cancel(self):
        q = MemoryQuota(10)
        assert q.alloc(10)
        stop = threading.Event()
        got = []
        t = threading.Thread(
            target=lambda: got.append(q.alloc_wait(5, timeout=30, cancelled=stop.is_set)))
        t.start()
        time.sleep(0.1)
        stop.set()
        t.join(timeout=5)
        assert got == [False]


class TestMemoryTrace:
    def test_tree_sums(self):
        root = StoreMemoryTrace("store")
        eng = root.child("engine")
        eng.add(100)
        cdc = root.child("cdc")
        cdc.add(30)
        deep = eng.child("block-cache")
        deep.add(7)
        assert root.sum() == 137
        snap = root.snapshot()
        assert snap["total"] == 137
        names = {c["name"] for c in snap["children"]}
        assert names == {"engine", "cdc"}
        eng.sub(100)
        assert root.sum() == 37

    def test_provider_nodes(self):
        root = StoreMemoryTrace("store")
        backing = {"n": 500}
        root.child("engine", provider=lambda: backing["n"])
        assert root.sum() == 500
        backing["n"] = 10
        assert root.sum() == 10

    def test_high_water_fires_once_per_excursion(self):
        root = StoreMemoryTrace("store")
        fired = []
        root.set_high_water(100, lambda total: fired.append(total))
        node = root.child("x")
        node.add(50)
        assert fired == []
        node.add(60)
        assert len(fired) == 1 and fired[0] >= 100
        node.add(10)  # still high: no re-fire until it falls below
        assert len(fired) == 1
        node.sub(100)
        node.add(5)  # below mark: re-arms
        assert len(fired) == 1
        node.add(200)
        assert len(fired) == 2


def _committed_event_store():
    """A tiny store + txn helpers whose MVCC commits the CDC observer sees."""
    from tikv_tpu.pd.client import MockPd
    from tikv_tpu.raft.cluster import FIRST_REGION_ID, Cluster
    from tikv_tpu.storage.storage import Storage
    from tikv_tpu.storage.txn.commands import Commit, Prewrite
    from tikv_tpu.storage.txn_types import Key, Mutation

    pd = MockPd()
    c = Cluster(1, pd=pd)
    c.run()
    leader = c.wait_leader(FIRST_REGION_ID)
    storage = Storage(engine=c.raftkv(leader.store.store_id))
    ctx = {"region_id": FIRST_REGION_ID}

    def put(key: bytes, value: bytes) -> None:
        ts = pd.get_tso()
        storage.sched_txn_command(
            Prewrite([Mutation.put(Key.from_raw(key), value)], key, ts), ctx)
        storage.sched_txn_command(Commit([Key.from_raw(key)], ts, pd.get_tso()), ctx)

    return c, put, pd


class TestCdcFlowControl:
    def test_congested_sink_tears_down_subscription(self):
        from tikv_tpu.sidecar.cdc import CdcService

        c, put, pd = _committed_event_store()
        store = c.stores[1]
        svc = CdcService(store, memory_quota_bytes=2_000)
        r = svc.register(1, checkpoint_ts=0)
        assert "sub_id" in r, r
        sub = r["sub_id"]
        # commit far more than the quota can buffer without any client drain
        for i in range(50):
            put(b"ck-%03d" % i, b"v" * 200)
        r = svc.events(sub, after_seq=0)
        assert "congested" in (r.get("error") or {}), r
        # torn down: quota released, a fresh registration works
        assert svc.quota.in_use() == 0
        r2 = svc.register(1, checkpoint_ts=svc.store.peers[1].node.applied)
        assert "sub_id" in r2, r2

    def test_drain_releases_quota(self):
        from tikv_tpu.sidecar.cdc import CdcService

        c, put, pd = _committed_event_store()
        store = c.stores[1]
        svc = CdcService(store, memory_quota_bytes=1 << 20)
        sub = svc.register(1, checkpoint_ts=0)["sub_id"]
        for i in range(10):
            put(b"dk-%02d" % i, b"v" * 100)
        assert svc.quota.in_use() > 0
        r = svc.events(sub, after_seq=0, limit=1024)
        assert r["events"]
        # ack everything: the next pull frees the buffered reservation
        svc.events(sub, after_seq=r["last_seq"], limit=1)
        assert svc.quota.in_use() == 0

    def test_incremental_scan_pauses_until_drained(self):
        """A scan bigger than the quota must PAUSE (not drop, not balloon)
        and finish once the consumer drains (channel.rs scan pacing)."""
        from tikv_tpu.sidecar.cdc import CdcService

        c, put, pd = _committed_event_store()
        for i in range(30):
            put(b"sk-%02d" % i, b"v" * 300)
        store = c.stores[1]
        svc = CdcService(store, memory_quota_bytes=3_000)  # ~7 events fit
        done = {}

        def run_register():
            done.update(svc.register(1, checkpoint_ts=pd.get_tso()))

        t = threading.Thread(target=run_register)
        t.start()
        time.sleep(0.3)
        assert t.is_alive(), "scan should be paused against the full sink"
        # drain as a consumer would until the scan completes
        sub_hint = max(svc._subs)  # the registering subscription
        last = 0
        deadline = time.monotonic() + 20
        while t.is_alive() and time.monotonic() < deadline:
            r = svc.events(sub_hint, after_seq=last, limit=64)
            if r.get("events"):
                last = r["last_seq"]
            time.sleep(0.02)
        t.join(timeout=5)
        assert not t.is_alive()
        assert done.get("scanned") == 30, done
