"""Distributed deadlock detection (src/server/lock_manager/deadlock.rs:343-391):
wait-for edges from every store forward to the detector leader — the store
holding region 1's leadership — so a lock cycle SPANNING stores breaks by
DeadlockError, not by waiter timeout."""

import threading
import time

import pytest

from tikv_tpu.server.cluster import FIRST_REGION_ID, ServerCluster
from tikv_tpu.server.server import Client


@pytest.fixture
def cluster3f():
    c = ServerCluster(3, full_service=True)
    c.run()
    yield c
    c.shutdown()


def _lock_client(cluster, region_id):
    leader = cluster.wait_leader(region_id)
    sid = leader.store.store_id
    return Client(*cluster.resolve(sid)), sid, leader


def _plock(client, region_id, key, start_ts, wait_ms=0, timeout=30.0):
    return client.call(
        "kv_pessimistic_lock",
        {
            "keys": [key],
            "primary_lock": key,
            "start_version": start_ts,
            "for_update_ts": start_ts,
            "wait_timeout_ms": wait_ms,
            "context": {"region_id": region_id},
        },
        timeout=timeout,
    )


def test_cross_store_cycle_broken_by_error_not_timeout(cluster3f):
    c = cluster3f
    # two regions with leaders on DIFFERENT stores
    right_id = c.split_region(FIRST_REGION_ID, b"m")
    left_leader = c.wait_leader(FIRST_REGION_ID)
    detector_sid = left_leader.store.store_id
    other = next(s for s in (1, 2, 3) if s != detector_sid)
    c.transfer_leader(right_id, other, timeout=30.0)

    cl_left, sid_left, _ = _lock_client(c, FIRST_REGION_ID)
    cl_right, sid_right, _ = _lock_client(c, right_id)
    assert sid_left != sid_right, "cycle must span two stores"

    # txn 10 locks "a" (left region), txn 20 locks "z" (right region)
    r = _plock(cl_left, FIRST_REGION_ID, b"a", 10)
    assert not r.get("error"), r
    r = _plock(cl_right, right_id, b"z", 20)
    assert not r.get("error"), r

    # txn 10 now waits for "z" at the right store (edge 10 -> 20 forwarded)
    waiter_result = {}

    def waiter():
        waiter_result["r"] = _plock(cl_right, right_id, b"z", 10, wait_ms=20_000)

    t = threading.Thread(target=waiter)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        info = cl_right.call("get_lock_wait_info", {})
        if info.get("entries"):
            break
        time.sleep(0.1)
    else:
        raise AssertionError("txn 10 never started waiting")

    # txn 20 asks for "a" at the LEFT store: edge 20 -> 10 closes the cycle.
    # wait_ms is generous — detection, not timeout, must break the cycle.
    t0 = time.monotonic()
    r = _plock(cl_left, FIRST_REGION_ID, b"a", 20, wait_ms=20_000)
    dt = time.monotonic() - t0
    err = r.get("error") or {}
    assert "deadlock" in err, f"expected deadlock error, got {r}"
    assert dt < 5.0, f"cycle broken by timeout ({dt:.1f}s), not detection"
    dl = err["deadlock"]
    assert {dl["waiting_txn"], dl["blocked_on_txn"]} == {10, 20}

    # unwind: roll back txn 20, which wakes txn 10's waiter
    cl_right.call("kv_pessimistic_rollback",
                  {"keys": [b"z"], "start_version": 20, "for_update_ts": 20,
                   "context": {"region_id": right_id}})
    t.join(timeout=25)
    assert not t.is_alive(), "txn 10's waiter never finished"
    cl_left.close()
    cl_right.close()


def test_local_cycle_still_detected_on_leader_store(cluster3f):
    """Same-store cycles keep working through the forwarding handle."""
    c = cluster3f
    cl, sid, _ = _lock_client(c, FIRST_REGION_ID)
    assert not _plock(cl, FIRST_REGION_ID, b"k1", 100).get("error")
    assert not _plock(cl, FIRST_REGION_ID, b"k2", 200).get("error")
    waiter_result = {}
    t = threading.Thread(target=lambda: waiter_result.update(
        r=_plock(cl, FIRST_REGION_ID, b"k2", 100, wait_ms=15_000)))
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if cl.call("get_lock_wait_info", {}).get("entries"):
            break
        time.sleep(0.1)
    r = _plock(cl, FIRST_REGION_ID, b"k1", 200, wait_ms=15_000)
    assert "deadlock" in (r.get("error") or {}), r
    cl.call("kv_pessimistic_rollback",
            {"keys": [b"k2"], "start_version": 200, "for_update_ts": 200,
             "context": {"region_id": FIRST_REGION_ID}})
    t.join(timeout=20)
    assert not t.is_alive()
    cl.close()
